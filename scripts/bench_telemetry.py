#!/usr/bin/env python
"""Telemetry-enabled LM training run → the committed TELEMETRY.json artifact.

Runs a short GPT training loop (synthetic data) with the full telemetry
stack on — step-phase spans, MFU/goodput accounting, the compile fence,
the flight recorder — and merges the resulting RunReport into
TELEMETRY.json with round timestamps (the BENCH_LM.json artifact pattern:
bounded history, sections survive re-runs). Queued in
scripts/tpu_pipeline.sh so every tunnel window banks an on-chip goodput/
MFU/phase-breakdown row next to the throughput benches.

Same resilience contract as bench.py / bench_cost_table.py: this parent
NEVER imports jax, the child runs under the watchdog behind a probe-first
budget, and the artifact is always written (a report row or a structured
error). CPU-sim runs work any round (tiny config; logic check) — pass
DTF_TEL_TINY=1 or just run without a chip and let the probe route it.

MFU REGRESSION FENCE (ROADMAP item 3 — hold the line once won): a tpu
row whose ``mfu`` falls more than ``--mfu-tol`` (rel., default 10%)
below the newest committed TELEMETRY.json row of the SAME config fails
CLOSED — exit 1, the regressed row is NOT merged, the committed artifact
keeps the golden. An intentional change rides
``--allow-mfu-regression="<why>"`` (the comms-budget --write-golden
idiom): the new row merges with the justification recorded and becomes
the next baseline. CPU-sim rows are never fenced — sim MFU is a logic
check, not a measurement.
"""

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from _dtf_artifact import load_runs, merge_runs, same_config as _same

ARTIFACT = os.path.join(ROOT, "TELEMETRY.json")
SENTINEL = "TELEMETRY_REPORT "
CHILD_TIMEOUT_S = 900
TOTAL_BUDGET_S = float(os.environ.get("DTF_TEL_BUDGET_S", "1200"))
MFU_TOL_DEFAULT = float(os.environ.get("DTF_TEL_MFU_TOL", "0.10"))

#: the identity of a telemetry row for fence purposes — rows measured
#: under different shapes/models/backends are never comparable.
CONFIG_KEYS = ("backend", "model", "tiny", "batch", "seq")


def same_config(a, b) -> bool:
    return _same(a, b, CONFIG_KEYS)


def fence_baseline(prev_runs, report):
    """Newest committed row comparable to ``report`` that carries a
    measured mfu (error rows and mfu-less rows can't be baselines)."""
    for row in reversed(prev_runs or []):
        if ("error" not in row and row.get("mfu") is not None
                and same_config(row, report)):
            return row
    return None


def check_mfu_fence(prev_runs, report, *, tol_frac=MFU_TOL_DEFAULT):
    """``(ok, detail)`` — ok=False means a tpu row regressed beyond
    tolerance vs its committed baseline (the fail-closed case). CPU rows
    and first-of-config rows pass with an explanatory detail."""
    backend = report.get("backend")
    if backend in (None, "cpu"):
        return True, {"fenced": False, "reason": "cpu-sim row (logic "
                                                 "check, never fenced)"}
    if "error" in report or report.get("mfu") is None:
        return True, {"fenced": False, "reason": "no measured mfu in row"}
    base = fence_baseline(prev_runs, report)
    if base is None:
        return True, {"fenced": False,
                      "reason": "no committed baseline for this config"}
    floor = base["mfu"] * (1.0 - tol_frac)
    detail = {"fenced": True, "baseline_mfu": base["mfu"],
              "baseline_ts": base.get("ts"), "mfu": report["mfu"],
              "floor": round(floor, 8), "tol_frac": tol_frac}
    return report["mfu"] >= floor, detail


def child():
    import jax
    import optax

    from dtf_tpu.core import train as tr
    from dtf_tpu.core.mesh import make_mesh
    from dtf_tpu.data.synthetic import SyntheticData
    from dtf_tpu.hooks import LoggingHook, StopAtStepHook
    from dtf_tpu.loop import Trainer
    from dtf_tpu.metrics import MetricWriter
    from dtf_tpu.models import gpt
    from dtf_tpu.telemetry import (Telemetry, analytic_lm_flops_per_step,
                                   param_count)

    tiny = os.environ.get("DTF_TEL_TINY") == "1"
    # batch must divide over the data axis (8-way on the CPU sim)
    b = int(os.environ.get("DTF_TEL_BATCH", "8"))
    s = int(os.environ.get("DTF_TEL_SEQ", "64" if tiny else "512"))
    n_steps = int(os.environ.get("DTF_TEL_STEPS", "12"))
    cfg = gpt.GPTConfig.tiny() if tiny else gpt.GPTConfig.gpt2_small()

    mesh = make_mesh()
    # global-batch FLOPs vs the whole mesh's peak (n_devices divisor)
    tel = Telemetry(min_stall_s=300.0, n_devices=mesh.devices.size)
    model, init_fn = gpt.make_init(cfg, mesh, seq_len=s)
    tx = optax.adamw(1e-4)
    state, shardings = tr.create_train_state(
        init_fn, tx, jax.random.PRNGKey(0), mesh, param_rules=gpt.tp_rules)
    step = tr.make_train_step(gpt.make_loss(model), tx, mesh, shardings,
                              telemetry=tel)
    tokens = b * s
    tel.set_throughput_model(
        tokens_per_step=tokens,
        model_flops_per_step=analytic_lm_flops_per_step(
            n_params=param_count(state.params), layers=cfg.layers,
            width=cfg.d_model, seq_len=s, tokens_per_step=tokens))

    data = SyntheticData("gpt", b, seed=0, seq_len=s,
                         vocab_size=cfg.vocab_size)
    trainer = Trainer(
        step, mesh,
        hooks=[LoggingHook(MetricWriter(None, also_log=False), 4,
                           tokens_per_step=tokens, telemetry=tel),
               StopAtStepHook(n_steps)],
        telemetry=tel)
    trainer.fit(state, iter(data))
    report = tel.finish({
        "backend": jax.default_backend(),
        "n_devices": mesh.devices.size,
        "model": "gpt", "tiny": tiny, "batch": b, "seq": s})
    print(SENTINEL + json.dumps(report))


def _parse_args(argv):
    """--mfu-tol=X and --allow-mfu-regression="why" (no argparse: the
    --child re-invocation must pass through untouched)."""
    tol, justification = MFU_TOL_DEFAULT, None
    for a in argv:
        if a.startswith("--mfu-tol="):
            tol = float(a.split("=", 1)[1])
        elif a.startswith("--allow-mfu-regression="):
            justification = a.split("=", 1)[1]
        elif a == "--allow-mfu-regression":
            justification = "(no reason given)"
    return tol, justification


def main(argv=()):
    from _dtf_watchdog import Budget, child_argv, probe_backend, \
        run_watchdogged

    tol, justification = _parse_args(argv)
    budget = Budget(TOTAL_BUDGET_S)
    meta = {"ts": round(time.time(), 1),
            "round": os.environ.get("DTF_ROUND", "")}
    backend, errs = probe_backend(
        timeout_s=min(90, max(10.0, budget.remaining(10))),
        retries=2, backoff_s=10, env=dict(os.environ))
    if backend is None:
        merge_runs(ARTIFACT, {
            "telemetry": "run_report_error",
            "error": ("backend unavailable (probe failed): "
                      + "; ".join(errs))[:2000]}, meta)
        print(json.dumps({"error": "probe failed"}))
        return 0

    def parse(line):
        if line.startswith(SENTINEL):
            try:
                return json.loads(line[len(SENTINEL):])
            except ValueError:
                return None
        return None

    report, errors = run_watchdogged(
        child_argv(os.path.abspath(__file__)), parse,
        timeout_s=min(CHILD_TIMEOUT_S, max(60.0, budget.remaining(30))),
        retries=1, backoff_s=0, env=dict(os.environ))
    if report is None:
        report = {"telemetry": "run_report_error",
                  "error": (f"probe OK (backend={backend}) but telemetry "
                            "run failed: " + "; ".join(errors))[:2000]}

    # ---- MFU regression fence (vs the COMMITTED artifact, pre-merge) ----
    ok, fence = check_mfu_fence(load_runs(ARTIFACT), report, tol_frac=tol)
    if not ok and justification is None:
        # fail CLOSED: the regressed row does NOT replace the committed
        # baseline — rerun with --allow-mfu-regression="why" if intended
        print(json.dumps({"ok": False, "backend": backend,
                          "mfu": report.get("mfu"), "mfu_fence": fence,
                          "error": "mfu regression vs committed "
                                   "TELEMETRY.json row (row not merged; "
                                   "justify with --allow-mfu-regression)"}))
        return 1
    if not ok:
        report = {**report, "mfu_justification": justification}
        fence = {**fence, "justified": justification}
    merge_runs(ARTIFACT, report, meta)
    print(json.dumps({"ok": "error" not in report,
                      "backend": backend,
                      "mfu": report.get("mfu"),
                      "mfu_fence": fence,
                      "goodput": report.get("goodput_buckets",
                                            {}).get("goodput")}))
    return 0


if __name__ == "__main__":
    if "--child" in sys.argv:
        child()
    else:
        sys.exit(main(sys.argv[1:]))
