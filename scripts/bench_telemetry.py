#!/usr/bin/env python
"""Telemetry-enabled LM training run → the committed TELEMETRY.json artifact.

Runs a short GPT training loop (synthetic data) with the full telemetry
stack on — step-phase spans, MFU/goodput accounting, the compile fence,
the flight recorder — and merges the resulting RunReport into
TELEMETRY.json with round timestamps (the BENCH_LM.json artifact pattern:
bounded history, sections survive re-runs). Queued in
scripts/tpu_pipeline.sh so every tunnel window banks an on-chip goodput/
MFU/phase-breakdown row next to the throughput benches.

Same resilience contract as bench.py / bench_cost_table.py: this parent
NEVER imports jax, the child runs under the watchdog behind a probe-first
budget, and the artifact is always written (a report row or a structured
error). CPU-sim runs work any round (tiny config; logic check) — pass
DTF_TEL_TINY=1 or just run without a chip and let the probe route it.
"""

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

ARTIFACT = os.path.join(ROOT, "TELEMETRY.json")
SENTINEL = "TELEMETRY_REPORT "
CHILD_TIMEOUT_S = 900
TOTAL_BUDGET_S = float(os.environ.get("DTF_TEL_BUDGET_S", "1200"))


def child():
    import jax
    import optax

    from dtf_tpu.core import train as tr
    from dtf_tpu.core.mesh import make_mesh
    from dtf_tpu.data.synthetic import SyntheticData
    from dtf_tpu.hooks import LoggingHook, StopAtStepHook
    from dtf_tpu.loop import Trainer
    from dtf_tpu.metrics import MetricWriter
    from dtf_tpu.models import gpt
    from dtf_tpu.telemetry import (Telemetry, analytic_lm_flops_per_step,
                                   param_count)

    tiny = os.environ.get("DTF_TEL_TINY") == "1"
    # batch must divide over the data axis (8-way on the CPU sim)
    b = int(os.environ.get("DTF_TEL_BATCH", "8"))
    s = int(os.environ.get("DTF_TEL_SEQ", "64" if tiny else "512"))
    n_steps = int(os.environ.get("DTF_TEL_STEPS", "12"))
    cfg = gpt.GPTConfig.tiny() if tiny else gpt.GPTConfig.gpt2_small()

    mesh = make_mesh()
    # global-batch FLOPs vs the whole mesh's peak (n_devices divisor)
    tel = Telemetry(min_stall_s=300.0, n_devices=mesh.devices.size)
    model, init_fn = gpt.make_init(cfg, mesh, seq_len=s)
    tx = optax.adamw(1e-4)
    state, shardings = tr.create_train_state(
        init_fn, tx, jax.random.PRNGKey(0), mesh, param_rules=gpt.tp_rules)
    step = tr.make_train_step(gpt.make_loss(model), tx, mesh, shardings,
                              telemetry=tel)
    tokens = b * s
    tel.set_throughput_model(
        tokens_per_step=tokens,
        model_flops_per_step=analytic_lm_flops_per_step(
            n_params=param_count(state.params), layers=cfg.layers,
            width=cfg.d_model, seq_len=s, tokens_per_step=tokens))

    data = SyntheticData("gpt", b, seed=0, seq_len=s,
                         vocab_size=cfg.vocab_size)
    trainer = Trainer(
        step, mesh,
        hooks=[LoggingHook(MetricWriter(None, also_log=False), 4,
                           tokens_per_step=tokens, telemetry=tel),
               StopAtStepHook(n_steps)],
        telemetry=tel)
    trainer.fit(state, iter(data))
    report = tel.finish({
        "backend": jax.default_backend(),
        "n_devices": mesh.devices.size,
        "model": "gpt", "tiny": tiny, "batch": b, "seq": s})
    print(SENTINEL + json.dumps(report))


def _merge(path, entry, meta, keep_runs=20):
    """telemetry.run.merge_artifact, replicated: importing anything under
    dtf_tpu pulls _jax_compat → jax, which this parent must never do."""
    data = {"runs": []}
    try:
        with open(path) as f:
            prev = json.load(f)
        if isinstance(prev, dict) and isinstance(prev.get("runs"), list):
            data = prev
    except (OSError, ValueError):
        pass
    data["runs"] = (data["runs"] + [{**entry, **meta}])[-keep_runs:]
    with open(path, "w") as f:
        json.dump(data, f, indent=1)


def main():
    from _dtf_watchdog import Budget, child_argv, probe_backend, \
        run_watchdogged

    budget = Budget(TOTAL_BUDGET_S)
    meta = {"ts": round(time.time(), 1),
            "round": os.environ.get("DTF_ROUND", "")}
    backend, errs = probe_backend(
        timeout_s=min(90, max(10.0, budget.remaining(10))),
        retries=2, backoff_s=10, env=dict(os.environ))
    if backend is None:
        _merge(ARTIFACT, {
            "telemetry": "run_report_error",
            "error": ("backend unavailable (probe failed): "
                      + "; ".join(errs))[:2000]}, meta)
        print(json.dumps({"error": "probe failed"}))
        return 0

    def parse(line):
        if line.startswith(SENTINEL):
            try:
                return json.loads(line[len(SENTINEL):])
            except ValueError:
                return None
        return None

    report, errors = run_watchdogged(
        child_argv(os.path.abspath(__file__)), parse,
        timeout_s=min(CHILD_TIMEOUT_S, max(60.0, budget.remaining(30))),
        retries=1, backoff_s=0, env=dict(os.environ))
    if report is None:
        report = {"telemetry": "run_report_error",
                  "error": (f"probe OK (backend={backend}) but telemetry "
                            "run failed: " + "; ".join(errors))[:2000]}
    _merge(ARTIFACT, report, meta)
    print(json.dumps({"ok": "error" not in report,
                      "backend": backend,
                      "mfu": report.get("mfu"),
                      "goodput": report.get("goodput_buckets",
                                            {}).get("goodput")}))
    return 0


if __name__ == "__main__":
    if "--child" in sys.argv:
        child()
    else:
        sys.exit(main())
