#!/usr/bin/env python
"""GPT causal-LM pretraining — the long-context flagship workload.

    python scripts/train_gpt.py --seq_len=2048 --mesh_seq=4 --grad_accum=2
    python scripts/train_gpt.py --size=tiny --moe_every=2 --mesh_expert=4

Every parallelism axis is flag-driven: dp over `data` (+ ZeRO-1), TP over
`model` (Megatron rules), ring attention over `seq` for long context,
Switch-MoE expert parallelism over `expert`; `--remat` trades FLOPs for HBM
on long sequences. Flash attention (fused Pallas kernel) is the single-chip
default on TPU.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from absl import app, flags, logging as absl_logging

from dtf_tpu.cli import flags as dflags

dflags.define_cluster_flags()
dflags.define_mesh_flags()
dflags.define_train_flags(batch_size=32, learning_rate=3e-4, train_steps=200,
                          lr_schedule="cosine")
flags.DEFINE_integer("seq_len", 512, "sequence length")
flags.DEFINE_string("size", "small", "small (gpt2-124M) | medium "
                    "(gpt2-355M) | tiny")
flags.DEFINE_boolean("zero1", True, "shard optimizer state over data axis")
flags.DEFINE_integer("moe_every", 0, "every k-th block uses Switch-MoE "
                     "(0 = dense)")
flags.DEFINE_integer("moe_top_k", 1, "experts per token: 1 = Switch, "
                     "2 = GShard top-2 (normalized gates)")
flags.DEFINE_boolean("remat", False, "jax.checkpoint each block")
flags.DEFINE_integer("kv_heads", 0, "grouped-query attention: shared K/V "
                     "heads (0 = plain MHA; must divide heads)")
flags.DEFINE_integer("attn_window", 0, "sliding-window attention: each "
                     "query sees the last N keys (0 = full causal). With "
                     "mesh_seq>1 this routes to halo attention (one "
                     "neighbor-tail ppermute); zigzag rejects windows")
flags.DEFINE_integer("attn_global_every", 0, "with attn_window: every "
                     "k-th layer uses full causal attention (alternating "
                     "local/global; 0 = all layers windowed)")
flags.DEFINE_string("attn_impl", "auto", "auto | dense | flash | ring | "
                    "zigzag (load-balanced causal ring; needs mesh_seq>1)")
flags.DEFINE_boolean("tp_overlap", False, "latency-hiding collective "
                     "matmul for the Megatron TP projections: decompose "
                     "the blocking all-gather/reduce-scatter around each "
                     "sharded einsum into a ppermute ring overlapped with "
                     "per-chunk matmuls (needs --mesh_model>1; "
                     "docs/OVERLAP.md)")
flags.DEFINE_enum("matmul_precision", "", ["", "auto", "bf16", "int8",
                                           "fp8"],
                  "low-precision compute for the Megatron TP projections: "
                  "'' = bf16 (no tuner), auto = the banked kernel-tune "
                  "winner per projection site, int8/fp8 = explicit pin "
                  "(wins over a measured winner with one WARN). Forward "
                  "only — gradients and master weights stay full "
                  "precision; with --tp_overlap the ring payload is what "
                  "quantizes (docs/TUNING.md)")
flags.DEFINE_integer("pipe_microbatches", 0, "pipeline microbatches when "
                     "mesh_pipe>1 (0 = 4x stages, the bubble-amortizing "
                     "default)")
flags.DEFINE_integer("pipe_interleave", 1, "model chunks per pipe device "
                     "(Megatron interleaved schedule when >1)")
flags.DEFINE_enum("pipe_schedule", "gpipe", ["gpipe", "1f1b", "zb"],
                  "pipeline schedule: gpipe (autodiff through the scan; "
                  "O(M) activation stash, shrink it with --remat), 1f1b "
                  "(fused forward/backward rounds; O(stages) stash, remat "
                  "built in — for depth-sharded models that exceed HBM "
                  "under gpipe), or zb (zero-bubble: 1f1b with the "
                  "backward split into B/W, weight-grads deferred into "
                  "the drain bubble — same numbers, less idle on the "
                  "MPMD executor; docs/PIPELINE.md)")
flags.DEFINE_integer("loss_chunk_vocab", 0, "compute the LM loss fused "
                     "with the lm_head in vocab chunks of this width "
                     "(0 = full logits). Removes the O(batch*seq*vocab) "
                     "logits memory — the single-chip batch ceiling. "
                     "Not with --mesh_model (TP shards the vocab dim) or "
                     "--mesh_pipe")
flags.DEFINE_integer("loss_chunk_tokens", 0, "fused LM loss chunking "
                     "TOKENS instead of vocab columns: O(chunk*vocab) "
                     "live logits, one full-vocab matmul per block — "
                     "the faster chunking axis on chip (PERF.md 0b). "
                     "Mutually exclusive with --loss_chunk_vocab; same "
                     "--mesh_model/--mesh_pipe restrictions")
flags.DEFINE_boolean("loss_pallas", False, "Pallas fused head+CE kernel: "
                     "logits never leave VMEM (dtf_tpu/ops/fused_ce.py). "
                     "Mutually exclusive with the chunked-loss flags; "
                     "same --mesh_model/--mesh_pipe restrictions")
flags.DEFINE_integer("eval_every", 0, "held-out eval (val.bin or held-out "
                     "synthetic) every N steps; 0 = final eval only. On the "
                     "pipelined path the eval step runs un-pipelined "
                     "against the same stacked params.")
flags.DEFINE_string("publish_dir", "", "weight hot-swap publishing "
                    "(ISSUE 14): every --publish_every steps, emit a "
                    "params-only snapshot as the next monotone VERSION "
                    "into this dir (atomic manifest + content digest); "
                    "serve_gpt --publish_dir/--swap_poll_ticks rolls "
                    "new versions across a live fleet with zero "
                    "downtime (docs/RESILIENCE.md §9)")
flags.DEFINE_integer("publish_every", 100, "with --publish_dir: publish "
                     "a version every N steps (plus once at end of run)")
flags.DEFINE_string("event_log_dir", "", "fleet EVENT PLANE (ISSUE 20): "
                    "chief-side lifecycle events (checkpoint saves, "
                    "degraded restores, published versions, stream "
                    "reweights/faults) append to CRC-framed shards under "
                    "this dir; `python -m dtf_tpu.telemetry timeline` "
                    "merges them with the serve/fault trails into one "
                    "run story (docs/OBSERVABILITY.md §9)")
flags.DEFINE_string("stream_spec", "", "streaming data tier (ISSUE 15, "
                    "docs/DATA.md): a JSON mixture spec (inline or a "
                    ".json path) of weighted token sources — "
                    "'{\"sources\": [{\"name\": ..., \"path\": ..., "
                    "\"weight\": ...}, ...]}'. The spec is recorded in "
                    "the model-config manifest and its per-source "
                    "cursors ride every checkpoint as a 'stream' item, "
                    "so a killed run resumes the EXACT batch sequence "
                    "and a resumed run cannot silently change its "
                    "mixture. Empty: the plain --data_dir/synthetic "
                    "path")
flags.DEFINE_integer("distill_draft", 0, "acceptance-driven draft "
                     "refresh (ISSUE 19): train an N-layer EARLY-EXIT "
                     "draft of the served checkpoint named by "
                     "--distill_from, initialized from its first N "
                     "blocks (gpt.draft_truncate) — the served model "
                     "itself is never touched. Point --stream_spec at a "
                     "'servelog' source (serve_gpt --log_sink_dir's "
                     "shards) to distill on live traffic, and "
                     "--publish_dir at the dir a fleet polls via "
                     "serve_gpt --draft_publish_dir for draft-only "
                     "rolling swaps (docs/SERVING.md). 0 = off")
flags.DEFINE_string("distill_from", "", "with --distill_draft: logdir of "
                    "the SERVED checkpoint whose manifest fixes the "
                    "architecture and whose params seed the draft "
                    "(--size and the architecture flags are ignored — "
                    "a draft that drifts from the verifier's widths "
                    "could not swap in)")
FLAGS = flags.FLAGS


def main(argv):
    del argv
    import jax
    import optax
    from jax.sharding import PartitionSpec as P

    from dtf_tpu.checkpoint import Checkpointer
    from dtf_tpu.cli.launch import (emit_run_report, lm_eval_hook,
                                    profiler_hooks, setup,
                                    telemetry_from_flags)
    from dtf_tpu.core import train as tr
    from dtf_tpu.core.comms import batch_shardings_for, shard_batch
    from dtf_tpu.data.synthetic import SyntheticData
    from dtf_tpu.hooks import (CheckpointHook, LoggingHook,
                               PreemptionHook, StopAtStepHook)
    from dtf_tpu.loop import Trainer
    from dtf_tpu.metrics import MetricWriter
    from dtf_tpu.models import gpt

    mesh, info = setup(FLAGS)
    sp = mesh.shape.get("seq", 1) > 1
    tel = telemetry_from_flags(FLAGS, info)

    try:
        base = gpt.GPTConfig.by_name(FLAGS.size)
    except KeyError as e:
        raise app.UsageError(f"--size: {e.args[0]}")
    import dataclasses

    if FLAGS.tp_overlap and mesh.shape.get("model", 1) <= 1:
        absl_logging.warning(
            "--tp_overlap has no effect without --mesh_model>1 (no TP "
            "collectives to hide); proceeding on the plain path")
    if FLAGS.tp_overlap and mesh.shape.get("pipe", 1) > 1:
        raise app.UsageError(
            "--tp_overlap is not supported with --mesh_pipe: pipeline "
            "stages run mesh-less (gpt_pipe) or with their own manual TP "
            "(gpt_pipe_tp), so the flag would be silently dropped")
    cfg = dataclasses.replace(base, moe_every=FLAGS.moe_every,
                              remat=FLAGS.remat, attn_impl=FLAGS.attn_impl,
                              kv_heads=FLAGS.kv_heads or None,
                              attn_window=FLAGS.attn_window,
                              attn_global_every=FLAGS.attn_global_every,
                              tp_overlap=FLAGS.tp_overlap,
                              matmul_precision=FLAGS.matmul_precision,
                              moe=dataclasses.replace(
                                  base.moe, top_k=FLAGS.moe_top_k))
    # acceptance-driven draft refresh (ISSUE 19): the architecture comes
    # from the SERVED manifest truncated to --distill_draft layers — a
    # draft that drifted from the verifier's widths could not swap in —
    # and the params seed from its first blocks (the base checkpoint is
    # read-only here; only the student trains)
    bman = distill_params = None
    if FLAGS.distill_draft:
        if not FLAGS.distill_from:
            raise app.UsageError(
                "--distill_draft needs --distill_from=<served logdir> "
                "(the checkpoint whose first layers seed the draft)")
        if mesh.shape.get("pipe", 1) > 1:
            raise app.UsageError(
                "--distill_draft does not compose with --mesh_pipe: the "
                "draft is at most served-depth minus one layer — "
                "depth-sharding it buys nothing")
        from dtf_tpu.checkpoint import load_model_config as _load_mc

        bdir = os.path.join(FLAGS.distill_from, "ckpt")
        bman = _load_mc(bdir)
        if bman is None:
            raise app.UsageError(
                f"--distill_from={FLAGS.distill_from} has no "
                "model_config.json manifest; the served architecture "
                "cannot be guessed")
        try:
            bbase = gpt.GPTConfig.by_name(bman.get("size", "small"))
        except KeyError as e:
            raise app.UsageError(
                f"--distill_from manifest size: {e.args[0]}")
        bcfg = dataclasses.replace(
            bbase, kv_heads=bman.get("kv_heads") or None,
            attn_window=int(bman.get("attn_window", 0) or 0),
            attn_global_every=int(bman.get("attn_global_every", 0) or 0))
        bck = Checkpointer(bdir)
        if bck.latest_step() is None:
            raise app.UsageError(f"no checkpoint under {bdir}")
        bparams = bck.restore_params()
        bck.close()
        try:
            cfg, distill_params = gpt.draft_truncate(
                bcfg, bparams, FLAGS.distill_draft)
        except ValueError as e:
            raise app.UsageError(str(e))
        cfg = dataclasses.replace(cfg, remat=FLAGS.remat,
                                  attn_impl=FLAGS.attn_impl)
        absl_logging.info(
            "distilling a %d-layer draft of %s (size %s, served step %d)",
            FLAGS.distill_draft, FLAGS.distill_from,
            bman.get("size", "?"), bck.last_restored_step)
    sched = dflags.make_lr_schedule(FLAGS)   # LoggingHook surfaces the LR
    tx = dflags.make_optimizer(
        FLAGS, lambda s: optax.adamw(s, weight_decay=(
            FLAGS.weight_decay if FLAGS.weight_decay >= 0 else 0.1)),
        recipe_uses_wd=True)
    if sum(map(bool, (FLAGS.loss_chunk_vocab, FLAGS.loss_chunk_tokens,
                      FLAGS.loss_pallas))) > 1:
        raise app.UsageError(
            "--loss_chunk_vocab, --loss_chunk_tokens and --loss_pallas "
            "are mutually exclusive — pick one fused-loss strategy")
    pipelined = mesh.shape.get("pipe", 1) > 1
    grads_fn = None   # set by --pipe_schedule=1f1b/zb (fused fwd/bwd path)
    if pipelined:
        from dtf_tpu.models import gpt_pipe

        if (FLAGS.loss_chunk_vocab or FLAGS.loss_chunk_tokens
                or FLAGS.loss_pallas):
            raise app.UsageError(
                "--loss_chunk_vocab/--loss_chunk_tokens/--loss_pallas are "
                "not supported with --mesh_pipe (the pipelined loss owns "
                "its head application); use them on the non-pipelined path")
        tp_in_pipe = mesh.shape.get("model", 1) > 1
        if sp and tp_in_pipe:
            raise app.UsageError(
                "--mesh_pipe>1 with BOTH --mesh_seq>1 and --mesh_model>1 "
                "is not supported; PP x SP runs ring/halo attention inside "
                "the stages, PP x TP runs Megatron splits — pick one")
        if sp and FLAGS.attn_impl == "zigzag":
            raise app.UsageError(
                "--attn_impl=zigzag cannot combine with --mesh_pipe>1; "
                "PP x SP uses the plain ring (auto)")
        # microbatch rule: n_micro | batch and (batch/n_micro) % data == 0;
        # the interleaved schedule additionally needs n_micro % pipe == 0.
        # Default: the largest feasible count <= 4x stages (amortizes the
        # (S-1)/(M+S-1) bubble without starving the data shards).
        per_data = FLAGS.batch_size // mesh.shape.get("data", 1)
        n_micro = FLAGS.pipe_microbatches
        if not n_micro:
            pipe_n = mesh.shape["pipe"]
            cands = [n for n in range(1, 4 * pipe_n + 1)
                     if per_data % n == 0
                     and (FLAGS.pipe_interleave == 1 or n % pipe_n == 0)]
            if not cands:
                raise app.UsageError(
                    f"no feasible pipeline microbatch count for batch "
                    f"{FLAGS.batch_size} / data={mesh.shape.get('data', 1)} "
                    f"/ pipe={pipe_n} / interleave={FLAGS.pipe_interleave}; "
                    "adjust --batch_size or set --pipe_microbatches")
            n_micro = max(cands)
            absl_logging.info("pipeline: using %d microbatches", n_micro)
        n_stages = mesh.shape["pipe"]
        if FLAGS.pipe_schedule in ("1f1b", "zb"):
            if FLAGS.pipe_interleave != 1 or tp_in_pipe:
                raise app.UsageError(
                    f"--pipe_schedule={FLAGS.pipe_schedule} supports "
                    "neither --pipe_interleave>1 nor --mesh_model>1; it "
                    "composes with data and seq sharding")
            if FLAGS.grad_accum != 1:
                raise app.UsageError(
                    "--grad_accum>1 is redundant with "
                    f"--pipe_schedule={FLAGS.pipe_schedule} (microbatch "
                    "accumulation is the schedule); raise "
                    "--pipe_microbatches instead")
        if tp_in_pipe:
            from dtf_tpu.models import gpt_pipe_tp

            if FLAGS.pipe_interleave != 1:
                raise app.UsageError(
                    "--pipe_interleave>1 is not supported with TP-in-pipe "
                    "(--mesh_model>1); use one or the other")
            init_fn = gpt_pipe_tp.make_pipe_tp_init(
                cfg, mesh, seq_len=FLAGS.seq_len)
            loss_fn = gpt_pipe_tp.make_pipe_tp_loss(
                cfg, mesh, n_microbatches=n_micro)
            param_rules = gpt_pipe_tp.pipe_tp_rules()
            eval_fn = gpt_pipe_tp.make_pipe_tp_eval(cfg, n_stages)
        else:
            init_fn = gpt_pipe.make_pipe_init(
                cfg, mesh, seq_len=FLAGS.seq_len,
                interleave_v=FLAGS.pipe_interleave)
            if FLAGS.pipe_schedule in ("1f1b", "zb"):
                maker = {"1f1b": gpt_pipe.make_pipe_grads_1f1b,
                         "zb": gpt_pipe.make_pipe_grads_zb}[
                             FLAGS.pipe_schedule]
                grads_fn = maker(cfg, mesh, n_microbatches=n_micro)
                loss_fn = None
            else:
                loss_fn = gpt_pipe.make_pipe_loss(
                    cfg, mesh, n_microbatches=n_micro,
                    interleave_v=FLAGS.pipe_interleave)
            param_rules = gpt_pipe.pipe_rules()
            eval_fn = gpt_pipe.make_pipe_eval(
                cfg, n_stages, interleave_v=FLAGS.pipe_interleave,
                seq_shards=mesh.shape.get("seq", 1))
        model = None
    else:
        # the model needs the mesh for ring attention (seq axis) AND for the
        # shard_map'd flash kernel (model axis) — pass it unconditionally.
        if ((FLAGS.loss_chunk_vocab or FLAGS.loss_chunk_tokens
             or FLAGS.loss_pallas) and mesh.shape.get("model", 1) > 1):
            raise app.UsageError(
                "--loss_chunk_vocab/--loss_chunk_tokens/--loss_pallas "
                "cannot combine with --mesh_model: TP shards the lm_head "
                "over the vocab dim, which fused application would fight "
                "(all-gathering W per chunk)")
        model, init_fn = gpt.make_init(cfg, mesh, seq_len=FLAGS.seq_len)
        # auto loss path: monolithic logits when they fit HBM (fastest),
        # the banked kernel-tune winner — token-chunked fused CE by
        # default — when they don't; explicit flags win but warn when
        # they force a measured-slower path (PERF.md 0c, docs/TUNING.md)
        lpath = dflags.resolve_lm_loss(
            FLAGS, batch=FLAGS.batch_size, seq_len=FLAGS.seq_len,
            vocab_size=cfg.vocab_size, mesh_shape=dict(mesh.shape))
        lchunk, tchunk = lpath.chunk_vocab, lpath.chunk_tokens
        lpallas = FLAGS.loss_pallas or lpath.pallas
        loss_fn = gpt.make_loss(model, loss_chunk=lchunk,
                                loss_chunk_tokens=tchunk,
                                loss_pallas=lpallas)
        param_rules = gpt.tp_rules
        eval_fn = gpt.make_eval(model, loss_chunk=lchunk,
                                loss_chunk_tokens=tchunk,
                                loss_pallas=lpallas)
    state, shardings = tr.create_train_state(
        init_fn, tx, jax.random.PRNGKey(FLAGS.seed), mesh,
        param_rules=param_rules, zero1=FLAGS.zero1)
    if distill_params is not None:
        # seed the student: the state was BUILT at the draft architecture,
        # so this is a values-only device_put onto the already-computed
        # shardings — fresh optimizer moments are exactly right for a
        # newly-initialized student
        state = state.replace(params=jax.device_put(
            distill_params, shardings.params))

    from dtf_tpu.data import formats

    # the stream spec's authority chain: a manifest written by the run
    # this logdir is resuming WINS over the flag (a resumed run cannot
    # silently change its mixture) — read it before we overwrite it below
    from dtf_tpu.checkpoint import load_model_config
    from dtf_tpu.data import stream as dstream

    prev_manifest = load_model_config(os.path.join(FLAGS.logdir, "ckpt"))
    stream = None
    try:
        stream_spec = dstream.resolve_stream_spec(FLAGS.stream_spec,
                                                  prev_manifest)
        if stream_spec is not None:
            from dtf_tpu.fault.inject import maybe_stream_fault

            stream = dstream.build_stream(
                stream_spec, global_batch=FLAGS.batch_size,
                seq_len=FLAGS.seq_len, vocab_size=cfg.vocab_size,
                seed=FLAGS.seed, host_index=info.process_id,
                host_count=info.num_processes,
                producer_depth=FLAGS.prefetch_depth,
                fault_plan=maybe_stream_fault())
    except (ValueError, OSError) as e:
        # spec-shape AND spec-content errors (missing/unreadable corpus,
        # bad reweight, indivisible batch) get the flag-error treatment
        raise app.UsageError(f"--stream_spec: {e}")
    if stream is not None:
        data = stream
    else:
        data = formats.detect_token_data(
            FLAGS.data_dir, FLAGS.batch_size, FLAGS.seq_len, mode="clm",
            vocab_size=cfg.vocab_size, seed=FLAGS.seed,
            host_index=info.process_id, host_count=info.num_processes)
        if data is None:
            if FLAGS.data_dir:
                absl_logging.warning(
                    "no token .bin in %s; using synthetic data",
                    FLAGS.data_dir)
            data = SyntheticData("gpt", FLAGS.batch_size, seed=FLAGS.seed,
                                 seq_len=FLAGS.seq_len,
                                 vocab_size=cfg.vocab_size,
                                 host_index=info.process_id,
                                 host_count=info.num_processes)
    kwargs = {}
    spec = None
    if sp:
        spec = P("data", "seq")
        probe = (stream.template_batch() if stream is not None
                 else data.batch(0))
        kwargs["batch_shardings"] = batch_shardings_for(probe, mesh, spec)
    if grads_fn is not None:
        if FLAGS.grad_shard:
            absl_logging.warning(
                "--grad_shard has no effect with --pipe_schedule="
                f"{FLAGS.pipe_schedule} "
                "(microbatching lives inside the fused schedule)")
        step = tr.make_train_step_from_grads(grads_fn, tx, mesh, shardings,
                                             telemetry=tel, **kwargs)
    else:
        # --grad_shard viability: the sharded accumulator needs a
        # pure-GSPMD loss — the shard_map kernels (ring/zigzag/halo/flash
        # attention, Pallas CE, collective-matmul overlap, pipeline
        # stages) pin their own batch-over-data layouts the
        # per-shard-group vmap cannot nest (docs/ZERO.md).
        eff_attn = gpt.effective_attn_impl(FLAGS.attn_impl, sp)
        blockers = []
        if eff_attn != "dense":
            # covers the seq-sharded ring/zigzag/halo family and flash;
            # explicit dense composes even windowed + seq-sharded (the
            # model's dense path is pure GSPMD).
            blockers.append(f"attention impl {eff_attn!r} runs in "
                            "shard_map (use --attn_impl=dense)")
        if FLAGS.loss_pallas or (not pipelined and lpath.pallas):
            blockers.append("--loss_pallas fused CE runs in shard_map")
        if FLAGS.tp_overlap and mesh.shape.get("model", 1) > 1:
            blockers.append("--tp_overlap collective matmuls run in "
                            "shard_map")
        if pipelined:
            blockers.append("pipelined stages run in shard_map")
        if FLAGS.moe_every:
            blockers.append("MoE aux losses ride mutable collections, "
                            "which shard-stacked loss calls cannot thread")
        grad_shard = dflags.resolve_grad_shard(FLAGS, mesh,
                                               blockers=blockers)
        step = tr.make_train_step(loss_fn, tx, mesh, shardings,
                                  grad_accum=FLAGS.grad_accum,
                                  grad_shard=grad_shard, telemetry=tel,
                                  **kwargs)

    tokens_per_step = model_flops = None
    if tel is not None:
        # analytic MFU model (the bench_lm mfu_analytic convention): no
        # extra trace — an AOT cost_analysis() here would re-lower the
        # step and unpin the compile fence (telemetry/accounting.py)
        from dtf_tpu.telemetry import (analytic_lm_flops_per_step,
                                       param_count)

        tokens_per_step = FLAGS.batch_size * FLAGS.seq_len
        model_flops = analytic_lm_flops_per_step(
            n_params=param_count(state.params), layers=cfg.layers,
            width=cfg.d_model, seq_len=FLAGS.seq_len,
            tokens_per_step=tokens_per_step)
        tel.set_throughput_model(tokens_per_step=tokens_per_step,
                                 model_flops_per_step=model_flops)

    writer = MetricWriter(FLAGS.logdir if info.is_chief else None)
    ckpt = Checkpointer(os.path.join(FLAGS.logdir, "ckpt"),
                        save_interval_steps=FLAGS.checkpoint_every)
    # architecture manifest next to the Orbax dir: generate_gpt.py /
    # serve_gpt.py auto-load it instead of trusting hand-matched --size
    # flags (a mismatch used to garble decode silently)
    from dtf_tpu.checkpoint import save_model_config

    manifest_cfg = {
        "model": "gpt", "size": FLAGS.size,
        "kv_heads": FLAGS.kv_heads, "attn_window": FLAGS.attn_window,
        "attn_global_every": FLAGS.attn_global_every,
        "moe_every": FLAGS.moe_every, "vocab_size": cfg.vocab_size,
        "d_model": cfg.d_model, "layers": cfg.layers, "heads": cfg.heads,
        "d_ff": cfg.d_ff, "kv_cache_dtype": ""}
    if FLAGS.distill_draft:
        # a DRAFT manifest: size names the base widths, "layers" (already
        # cfg.layers == the truncation) + "draft_layers" mark the depth —
        # serve_gpt --draft_ckpt resolves the truncated stack from it
        manifest_cfg.update({
            "size": bman.get("size", FLAGS.size),
            "kv_heads": cfg.kv_heads or 0,
            "attn_window": cfg.attn_window,
            "attn_global_every": cfg.attn_global_every,
            "moe_every": 0,
            "draft_layers": FLAGS.distill_draft,
            "distilled_from": FLAGS.distill_from})
    if stream_spec is not None:
        # the mixture identity rides the manifest: the resolve above
        # guarantees a relaunch into this logdir keeps (or is refused a
        # change of) exactly this spec
        manifest_cfg[dstream.MANIFEST_KEY] = stream_spec
    save_model_config(ckpt.directory, manifest_cfg)
    # the fleet event plane (ISSUE 20): chief-only — EventLog is a
    # single-writer log, and under the fake-hosts harness N workers over
    # one dir would interleave two generations of shards
    events = None
    if FLAGS.event_log_dir and getattr(info, "participates_in_save", True):
        from dtf_tpu.telemetry.events import EventLog

        events = EventLog(FLAGS.event_log_dir)
        ckpt.attach_event_log(events)
        if stream is not None:
            stream.attach_event_log(events)
    publisher = None
    # only the checkpoint-owning process publishes (the PreemptionHook
    # ckpt=None idiom): under the fake-hosts harness every worker is its
    # own process_index-0 program, and N publishers racing one manifest
    # would commit digests over half-written dirs
    if FLAGS.publish_dir and getattr(info, "participates_in_save", True):
        from dtf_tpu.publish import ParamPublisher

        publisher = ParamPublisher(FLAGS.publish_dir)
        if events is not None:
            publisher.event_log = events
        # the architecture manifest rides next to the publish manifest so
        # a fleet serving ONLY the publish dir still resolves the config
        save_model_config(FLAGS.publish_dir, manifest_cfg)
    place_batch = lambda b: shard_batch(  # noqa: E731
        gpt.zigzag_batch(b, mesh.shape["seq"])
        if (sp and FLAGS.attn_impl == "zigzag") else b,
        mesh, spec=spec)
    # every path evaluates — the pipelined ones via the un-pipelined
    # sequential eval over the same stacked params (VERDICT r3 #7)
    eval_hook = lm_eval_hook(
        FLAGS, info, mesh, shardings, eval_fn, writer,
        place_batch, kind="gpt", mode="clm", vocab_size=cfg.vocab_size,
        batch_shardings=kwargs.get("batch_shardings"), telemetry=tel)
    from dtf_tpu.fault import inject
    from dtf_tpu.hooks import PublishHook

    hooks = [LoggingHook(writer, FLAGS.log_every, lr_schedule=sched,
                         tokens_per_step=tokens_per_step,
                         model_flops_per_step=model_flops,
                         telemetry=tel),
             *([dstream.StreamCheckpointHook(ckpt, stream)]
               if stream is not None else []),
             CheckpointHook(ckpt, FLAGS.checkpoint_every),
             *([PublishHook(publisher, FLAGS.publish_every)]
               if publisher is not None else []),
             PreemptionHook(ckpt),
             *([eval_hook] if eval_hook else []),
             StopAtStepHook(FLAGS.train_steps),
             *profiler_hooks(FLAGS, telemetry=tel,
                             flops_per_step=model_flops)]
    fault = inject.maybe_hook(host_index=info.process_id,
                              checkpointer=ckpt, publisher=publisher)
    if fault is not None:
        hooks.insert(0, fault)   # injected faults land before save hooks
    trainer = Trainer(
        step, mesh, hooks=hooks,
        checkpointer=ckpt,
        place_batch=place_batch,
        telemetry=tel,
        prefetch=FLAGS.prefetch_depth)
    state = trainer.fit(state, iter(data))
    extra = {
        "launcher": "train_gpt", "size": FLAGS.size,
        "batch_size": FLAGS.batch_size, "seq_len": FLAGS.seq_len,
        "mesh": dict(mesh.shape)}
    if stream is not None:
        # per-source throughput / realized fractions / queue depth in the
        # RunReport (backpressure itself is the data_wait phase span)
        extra["stream"] = stream.stats()
    emit_run_report(tel, info, extra=extra)
    writer.close()
    ckpt.close()
    if events is not None:
        events.emit("train_end", step=int(state.step))
        events.close()
    print(f"done: step={int(state.step)}")


if __name__ == "__main__":
    app.run(main)
