#!/bin/sh
# On-chip artifact pipeline (PERF.md §3c) — run the moment a chip is
# reachable. Every step is probe-first + budget-capped, so a tunnel that
# dies mid-pipeline costs minutes per step and leaves structured errors.
# Order = current value density (re-ranked after the round-5 03:18 window
# banked the full attention sweep): smoke stays first as the cheap
# correctness gate, then everything whose rows are missing or stale —
# the LM benches now measure the sweep-picked 512x1024 flash default
# (expected to lift GPT past the 58.0% MFU banked on 512x512), the
# still-unmeasured rows ride next (the --tp_overlap collective-matmul
# A/B pair — needs a multi-chip pool, a 1-chip tunnel banks a structured
# mesh error — and the standalone bwd-block sweep both round-5 windows
# died before reaching), decode + cost-table re-run with the
# host-readback fence fix, bench.py retries the headline the 04:38
# tunnel death swallowed. The full attention sweeps, banked at the old
# default, re-run last to re-measure at the new one if the window
# survives that long.
set -x
cd "$(dirname "$0")/.." || exit 1
python scripts/tpu_smoke.py
# autotune FIRST (after the correctness gate): banks block-shape +
# loss-path winners into KERNEL_TUNE.json so every bench below — and
# the PR 8 MFU fences — measures at tuned defaults (docs/TUNING.md)
python scripts/bench_tune.py
# precision A/B (ISSUE 17): bf16/int8/fp8 tp_dense cells + rel_err →
# BENCH_QUANT.json; rows bank into KERNEL_TUNE_SWEEP.json
# precision_rows and flip the matmul_precision policy entries to
# measured on re-seed (bench_tune's precision sweep skips
# already-banked cells, so running both is cheap)
python scripts/bench_quant.py
python scripts/bench_lm.py
python scripts/bench_lm.py --sweep-gpt
python scripts/bench_lm.py --sweep-bert
python scripts/bench_lm.py --sweep-tp-overlap
python scripts/bench_lm.py --sweep-grad-shard
# zero-bubble A/B (ISSUE 18): 1F1B vs ZB at m4/m8 on a data x pipe mesh
# -> BENCH_LM_PIPE.json (multi-chip; 1-chip tunnel banks a mesh error)
python scripts/bench_lm.py --sweep-pipe
python scripts/bench_attention.py tpu --sweep-blocks-bwd
python scripts/bench_decode.py
python scripts/bench_decode.py --sweep-serve
python scripts/bench_telemetry.py
python scripts/bench_profile.py
# control-plane ticks/sec (ISSUE 20): chip-independent, banked per round
# with a fail-closed regression fence -> CONTROL_PLANE.json
python scripts/bench_serve_cp.py
python scripts/bench_cost_table.py
python bench.py
python scripts/bench_lm.py --phases-gpt
python scripts/bench_attention.py tpu
python scripts/bench_attention.py tpu --sweep-blocks
