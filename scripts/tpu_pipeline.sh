#!/bin/sh
# On-chip artifact pipeline (PERF.md §3c) — run the moment a chip is
# reachable. Every step is probe-first + budget-capped, so a tunnel that
# dies mid-pipeline costs minutes per step and leaves structured errors.
set -x
cd "$(dirname "$0")/.." || exit 1
python scripts/tpu_smoke.py
python scripts/bench_attention.py tpu
python scripts/bench_attention.py tpu --sweep-blocks
python scripts/bench_lm.py
python scripts/bench_lm.py --sweep-gpt
python scripts/bench_lm.py --phases-gpt
python scripts/bench_lm.py --sweep-bert
python scripts/bench_decode.py
python scripts/bench_cost_table.py
python bench.py
