#!/usr/bin/env python
"""Compiled-on-TPU smoke test for the first-party Pallas kernels.

VERDICT r1 weak-spot #4: both kernels (`dtf_tpu/ops/flash_attention.py`,
`dtf_tpu/ops/embed_gather.py`) were only ever exercised with
``interpret=True`` on CPU. This script runs them with ``interpret=False``
through the real Mosaic compiler on the attached TPU chip, asserts numerics
against the dense references, and writes a JSON artifact
(``TPU_SMOKE.json`` at the repo root) recording per-check max errors.

Resilient to the flaky axon backend the same way bench.py is: the parent
process never imports jax; the measurement runs in a watchdogged subprocess
with retries, and the artifact always gets written (ok=false + error on
unrecoverable failure).
"""

import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
ARTIFACT = (os.environ.get("DTF_SMOKE_ARTIFACT")
            or os.path.join(ROOT, "TPU_SMOKE.json"))
SENTINEL = "TPU_SMOKE_RESULT "
# Probe-first budget (VERDICT r3 weak #1): fast-fail on a dead backend in
# ~3.5 min instead of burning 3 x 600 s of child timeouts.
TOTAL_BUDGET_S = float(os.environ.get("DTF_SMOKE_BUDGET_S", "900"))
PROBE_TIMEOUT_S = 90
CHILD_TIMEOUT_S = 600


def child():
    sys.path.insert(0, ROOT)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dtf_tpu.ops import attention as att
    from dtf_tpu.ops import embed_gather as eg
    from dtf_tpu.ops import flash_attention as fa

    backend = jax.default_backend()
    results = {"backend": backend, "device": str(jax.devices()[0]),
               "interpret": False, "checks": {}}

    def record(name, got, want, tol):
        err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                    - want.astype(jnp.float32))))
        ok = bool(err <= tol)
        results["checks"][name] = {"max_abs_err": err, "tol": tol, "ok": ok}
        return ok

    ok = True
    key = jax.random.PRNGKey(0)
    kq, kk, kv, kt, ki, kd = jax.random.split(key, 6)

    # --- flash attention fwd+bwd, aligned and unaligned T, causal+full ---
    for t, tag in ((256, "t256"), (200, "t200_unaligned")):
        b, h, d = 2, 4, 128
        q = jax.random.normal(kq, (b, h, t, d), jnp.float32)
        k = jax.random.normal(kk, (b, h, t, d), jnp.float32)
        v = jax.random.normal(kv, (b, h, t, d), jnp.float32)
        for causal in (True, False):
            name = f"flash_fwd_{tag}_{'causal' if causal else 'full'}"

            def loss_flash(q, k, v):
                o = fa.flash_attention(q, k, v, causal=causal,
                                       interpret=False)
                return jnp.sum(o * (1 + jnp.cos(o))), o

            def loss_dense(q, k, v):
                o = att.dense_attention(q, k, v, causal=causal)
                return jnp.sum(o * (1 + jnp.cos(o))), o

            (_, o_f), g_f = jax.jit(jax.value_and_grad(
                loss_flash, argnums=(0, 1, 2), has_aux=True))(q, k, v)
            # Dense reference at HIGHEST precision = true-f32 ground truth.
            # (Setting HIGHEST globally breaks Mosaic's dot lowering, so the
            # kernel runs at production precision — its bf16 MXU rounding,
            # ~1e-2 absolute at these magnitudes, is what the tolerances
            # budget for; an algorithmic bug shows up orders above that.)
            with jax.default_matmul_precision("highest"):
                (_, o_d), g_d = jax.jit(jax.value_and_grad(
                    loss_dense, argnums=(0, 1, 2), has_aux=True))(q, k, v)
            ok &= record(name, o_f, o_d, tol=2e-2)
            for gi, gn in zip(range(3), ("dq", "dk", "dv")):
                ok &= record(f"flash_bwd_{tag}_"
                             f"{'causal' if causal else 'full'}_{gn}",
                             g_f[gi], g_d[gi], tol=5e-2)

    # --- bf16 fwd sanity (the production dtype) ---
    qb = jax.random.normal(kq, (2, 4, 256, 128), jnp.bfloat16)
    kb = jax.random.normal(kk, (2, 4, 256, 128), jnp.bfloat16)
    vb = jax.random.normal(kv, (2, 4, 256, 128), jnp.bfloat16)
    o_fb = jax.jit(lambda q, k, v: fa.flash_attention(
        q, k, v, causal=True, interpret=False))(qb, kb, vb)
    o_db = att.dense_attention(qb.astype(jnp.float32),
                               kb.astype(jnp.float32),
                               vb.astype(jnp.float32), causal=True)
    ok &= record("flash_fwd_bf16_causal", o_fb, o_db, tol=5e-2)

    # --- masked flash (BERT padding path) fwd+bwd vs dense+bias ---
    t_m, b_m, h_m, d_m = 256, 2, 4, 128
    qm = jax.random.normal(kq, (b_m, h_m, t_m, d_m), jnp.float32)
    km = jax.random.normal(kk, (b_m, h_m, t_m, d_m), jnp.float32)
    vm = jax.random.normal(kv, (b_m, h_m, t_m, d_m), jnp.float32)
    mask = np.ones((b_m, t_m), bool)
    mask[0, 150:] = False            # padded tail crossing block boundaries
    mask = jnp.asarray(mask)
    bias = jnp.where(mask[:, None, None, :], 0.0, -jnp.inf)

    def loss_flash_m(q, k, v):
        o = fa.flash_attention(q, k, v, kv_mask=mask, interpret=False)
        return jnp.sum(o * (1 + jnp.cos(o))), o

    def loss_dense_m(q, k, v):
        o = att.dense_attention(q, k, v, bias=bias)
        return jnp.sum(o * (1 + jnp.cos(o))), o

    (_, o_fm), g_fm = jax.jit(jax.value_and_grad(
        loss_flash_m, argnums=(0, 1, 2), has_aux=True))(qm, km, vm)
    with jax.default_matmul_precision("highest"):
        (_, o_dm), g_dm = jax.jit(jax.value_and_grad(
            loss_dense_m, argnums=(0, 1, 2), has_aux=True))(qm, km, vm)
    ok &= record("flash_fwd_kv_mask", o_fm, o_dm, tol=2e-2)
    for gi, gn in zip(range(3), ("dq", "dk", "dv")):
        ok &= record(f"flash_bwd_kv_mask_{gn}", g_fm[gi], g_dm[gi], tol=5e-2)

    # --- sliding-window flash (grid-level block skip) vs dense+window ---
    qw = jax.random.normal(kq, (2, 4, 256, 128), jnp.float32)
    kw = jax.random.normal(kk, (2, 4, 256, 128), jnp.float32)
    vw = jax.random.normal(kv, (2, 4, 256, 128), jnp.float32)

    def loss_flash_w(q, k, v):
        o = fa.flash_attention(q, k, v, causal=True, window=96,
                               block_q=64, block_k=64,  # noqa: tiny pin —
                               # smoke exercises the window GRID SKIP,
                               # which needs several blocks inside T=256
                               interpret=False)
        return jnp.sum(o * (1 + jnp.cos(o))), o

    def loss_dense_w(q, k, v):
        o = att.dense_attention(q, k, v, causal=True, window=96)
        return jnp.sum(o * (1 + jnp.cos(o))), o

    (_, o_fw), g_fw = jax.jit(jax.value_and_grad(
        loss_flash_w, argnums=(0, 1, 2), has_aux=True))(qw, kw, vw)
    with jax.default_matmul_precision("highest"):
        (_, o_dw), g_dw = jax.jit(jax.value_and_grad(
            loss_dense_w, argnums=(0, 1, 2), has_aux=True))(qw, kw, vw)
    ok &= record("flash_fwd_window", o_fw, o_dw, tol=2e-2)
    for gi, gn in zip(range(3), ("dq", "dk", "dv")):
        ok &= record(f"flash_bwd_window_{gn}", g_fw[gi], g_dw[gi], tol=5e-2)

    # --- embed gather fwd + scatter-add bwd ---
    table = jax.random.normal(kt, (1000, 64), jnp.float32)
    ids = jax.random.randint(ki, (4, 37), 0, 1000)

    def loss_gather(tb):
        out = eg.gather_rows(tb, ids, interpret=False)
        return jnp.sum(out * jnp.sin(out)), out

    def loss_take(tb):
        out = jnp.take(tb, ids.reshape(-1), axis=0).reshape(
            ids.shape + (tb.shape[1],))
        return jnp.sum(out * jnp.sin(out)), out

    (_, og), gg = jax.jit(jax.value_and_grad(loss_gather,
                                             has_aux=True))(table)
    (_, ot), gt = jax.jit(jax.value_and_grad(loss_take, has_aux=True))(table)
    ok &= record("embed_gather_fwd", og, ot, tol=1e-6)
    ok &= record("embed_gather_bwd_scatter_add", gg, gt, tol=1e-5)

    # --- chunked prefill == one-shot prefill, compiled (round 5) ---
    # the serving memory knob (generate(prefill_chunk=...)): windowed GQA
    # config so the rolling-cache wrap path is the thing compiled+proven
    from dtf_tpu.models import gpt as gpt_lib

    cfgp = gpt_lib.GPTConfig.tiny(dtype=jnp.float32, kv_heads=2,
                                  decode_len=32, attn_window=8,
                                  attn_global_every=2)
    modelp = gpt_lib.GPT(cfgp)
    varsp = modelp.init(jax.random.PRNGKey(3), jnp.zeros((1, 1), jnp.int32))
    promptp = jax.random.randint(kd, (2, 12), 0, cfgp.vocab_size)
    one = jax.jit(lambda p, pr: gpt_lib.generate(modelp, p, pr, 6))(
        varsp["params"], promptp)
    chk = jax.jit(lambda p, pr: gpt_lib.generate(
        modelp, p, pr, 6, prefill_chunk=5))(varsp["params"], promptp)
    ok &= record("chunked_prefill_decode", chk.astype(jnp.float32),
                 one.astype(jnp.float32), tol=0.0)

    # --- pallas fused head+CE fwd+bwd vs full-logits path (round 5) ---
    from dtf_tpu.ops.fused_ce import pallas_lm_cross_entropy
    from dtf_tpu.ops.losses import softmax_cross_entropy

    kx, kw2, kl = jax.random.split(kd, 3)
    xc = jax.random.normal(kx, (4, 256, 128), jnp.bfloat16)
    wc = jax.random.normal(kw2, (128, 1000), jnp.float32) * 0.05
    labc = jax.random.randint(kl, (4, 256), 0, 1000)
    labc = labc.at[0, :10].set(-100)   # ignored band

    def loss_fused(x, w):
        return pallas_lm_cross_entropy(x, w, labc, ignore_index=-100,
                                       block_n=256, block_v=256,  # noqa:
                                       # tiny pin — multi-tile grid at the
                                       # smoke's V=1000 needs small blocks
                                       interpret=False)[0]

    def loss_full(x, w):
        return softmax_cross_entropy(x.astype(jnp.float32) @ w, labc,
                                     ignore_index=-100)[0]

    lf_, gf_ = jax.jit(jax.value_and_grad(loss_fused, argnums=(0, 1)))(
        xc, wc)
    with jax.default_matmul_precision("highest"):
        ld_, gd_ = jax.jit(jax.value_and_grad(loss_full, argnums=(0, 1)))(
            xc, wc)
    ok &= record("fused_ce_fwd", jnp.asarray(lf_), jnp.asarray(ld_),
                 tol=2e-2)
    ok &= record("fused_ce_bwd_dx", gf_[0].astype(jnp.float32),
                 gd_[0].astype(jnp.float32), tol=5e-2)
    ok &= record("fused_ce_bwd_dw", gf_[1], gd_[1], tol=5e-2)

    results["ok"] = bool(ok) and backend == "tpu"
    if backend != "tpu":
        results["note"] = (f"ran on backend={backend}; not a TPU-compiled "
                           "proof")
    print(SENTINEL + json.dumps(results))


def main():
    from _dtf_watchdog import Budget, child_argv, probe_backend, \
        run_watchdogged

    budget = Budget(TOTAL_BUDGET_S)
    backend, probe_errors = probe_backend(
        timeout_s=min(PROBE_TIMEOUT_S, max(10.0, budget.remaining(10))),
        retries=2, backoff_s=10, env=dict(os.environ))
    if backend is None:
        result = {"ok": False,
                  "error": ("backend unavailable (probe failed): "
                            + "; ".join(probe_errors))[:3000]}
    else:
        result, errors = run_watchdogged(
            child_argv(os.path.abspath(__file__)),
            lambda line: (json.loads(line[len(SENTINEL):])
                          if line.startswith(SENTINEL) else None),
            timeout_s=min(CHILD_TIMEOUT_S, max(60.0, budget.remaining(30))),
            retries=1, backoff_s=0, env=dict(os.environ))
        if result is None:
            result = {"ok": False,
                      "error": (f"probe OK (backend={backend}) but smoke "
                                "child failed: " + "; ".join(errors))[:3000]}
    if not result.get("ok"):
        # a failed ATTEMPT must not destroy a previous GREEN proof — the
        # committed artifact is the kernel-compiles-on-chip evidence, and
        # the dress-rehearsal of the pipeline against a dead tunnel showed
        # this exact overwrite. Keep the green result; record the outage.
        try:
            with open(ARTIFACT) as f:
                prior = json.load(f)
        except (OSError, json.JSONDecodeError):
            prior = {}
        if prior.get("ok"):
            prior["last_attempt_error"] = result.get("error", "")[:3000]
            result = prior
    with open(ARTIFACT, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return 0 if result.get("ok") and "last_attempt_error" not in result \
        else 1


if __name__ == "__main__":
    if "--child" in sys.argv:
        child()
    else:
        sys.exit(main())
