#!/usr/bin/env python
"""PP activation-memory measurement (VERDICT r4 weak #6 / next #7).

The GPipe schedule is one differentiated ``lax.scan``: autodiff stashes
each scan step's residuals, so WITHOUT remat the backward keeps
O(n_microbatches) per-stage activations live — the classic GPipe stash.
``cfg.remat`` wraps every block in ``jax.checkpoint`` inside the stage, so
only the per-microbatch block INPUTS stay stashed and the rest
rematerializes in the backward.

This script puts numbers on that trade with XLA's own allocator report
(``compiled.memory_analysis().temp_size_in_bytes`` — peak temp allocation
of the compiled fwd+bwd program), across remat on/off and two microbatch
counts, plus the fused-1F1B schedule (``pipeline_1f1b_grads``: forwards
and backwards interleaved in one scan, O(stages) stash, stage recompute
built in) and its ZERO-BUBBLE variant (``pipeline_zb_grads``, ISSUE 18:
backward split into B/W, W deferred into the drain bubble — one extra
depth-S cotangent ring on top of 1F1B's stash) against the same model.
Alongside the measured temps, ``schedule_bubble_model`` prices the IDLE
fraction of both fused schedules at m4/m8 (pure step-count dependency
sim, no compile): the artifact shows what the extra ZB stash buys.
Pure compile-time analysis on the CPU sim: no TPU, no probe, no
timing — runnable any round regardless of the tunnel. Artifact:
``PIPE_MEM.json`` (+ one JSON line per row on stdout); regeneration
MERGES by (schedule, remat, n_microbatches) key, preserving rows a
given run doesn't re-measure.

Cross-check (ISSUE 9 satellite): a GLOBAL-BATCH sweep per schedule
family — temp measured at batch B/2 and B, extrapolated to 2B with the
memory pass's affine model (``dtf_tpu.analysis.memory.affine_temp_model``
— the exact primitive ``python -m dtf_tpu.analysis fit`` inverts max
batch with), and ASSERTED against XLA's measured 2B number within
``PREDICT_TOL``: each 2B row carries a ``predicted_temp_bytes`` column
next to its measured one.  (Batch, not microbatch count, is the swept
axis on purpose: at fixed global batch a higher ``n_microbatches``
SHRINKS each microbatch, so temp is deliberately non-affine there —
that trade is what the main rows above measure.)
"""

import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
ARTIFACT = os.path.join(ROOT, "PIPE_MEM.json")

#: measured-vs-predicted relative tolerance for the affine temp model —
#: XLA's allocator is piecewise (fusion decisions shift with shapes),
#: but stash + working set grow linearly in batch rows; beyond this the
#: fit planner's batch inversion can't be trusted.  Measured slack on
#: this stack: 0.6% (gpipe), 2.7% (gpipe+remat).
PREDICT_TOL = 0.25


def main():
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from dtf_tpu.core import train as tr
    from dtf_tpu.core.comms import shard_batch
    from dtf_tpu.core.mesh import MeshConfig, make_mesh
    from dtf_tpu.data.synthetic import SyntheticData
    from dtf_tpu.models import gpt, gpt_pipe

    # explicit 4-device subset: the 8-device sim would otherwise demand
    # every axis product == 8
    mesh = make_mesh(MeshConfig(data=2, pipe=2), devices=jax.devices()[:4])
    seq = int(os.environ.get("DTF_PIPEMEM_SEQ", "256"))
    batch = int(os.environ.get("DTF_PIPEMEM_BATCH", "16"))
    base = gpt.GPTConfig(vocab_size=512, d_model=256, layers=8, heads=8,
                         d_ff=1024, dtype=jnp.float32)
    data = SyntheticData("gpt", batch, seed=0, seq_len=seq,
                         vocab_size=base.vocab_size).batch(0)

    rows = []
    for remat in (False, True):
        cfg = dataclasses.replace(base, remat=remat)
        for n_micro in (4, 8):
            init_fn = gpt_pipe.make_pipe_init(cfg, mesh, seq_len=seq)
            loss_fn = gpt_pipe.make_pipe_loss(cfg, mesh,
                                              n_microbatches=n_micro)
            tx = optax.sgd(1e-3)
            state, shardings = tr.create_train_state(
                init_fn, tx, jax.random.PRNGKey(0), mesh,
                param_rules=gpt_pipe.pipe_rules())
            sharded = shard_batch(data, mesh)

            def fwdbwd(st, bt):
                (loss, _), grads = jax.value_and_grad(
                    lambda p: loss_fn(p, st.extra, bt,
                                      jax.random.PRNGKey(0)),
                    has_aux=True)(st.params)
                return loss, grads

            mem = (jax.jit(fwdbwd)  # aot-ok: bench measurement lowering
                   .lower(state, sharded).compile()
                   .memory_analysis())
            row = {"schedule": "gpipe", "remat": remat,
                   "n_microbatches": n_micro,
                   "temp_bytes": int(mem.temp_size_in_bytes),
                   "arg_bytes": int(mem.argument_size_in_bytes),
                   "out_bytes": int(mem.output_size_in_bytes)}
            rows.append(row)
            print(json.dumps(row), flush=True)

            if remat:
                continue   # the fused schedules' remat IS the schedule
            for sched, maker in (
                    ("1f1b", gpt_pipe.make_pipe_grads_1f1b),
                    ("zb", gpt_pipe.make_pipe_grads_zb)):
                grads_fused = maker(cfg, mesh, n_microbatches=n_micro)

                def fwdbwd_fused(st, bt):
                    loss, _, grads = grads_fused(st.params, st.extra, bt,
                                                 jax.random.PRNGKey(0))
                    return loss, grads

                # measurement lowering of a bench-local wrapper program
                mem = (jax.jit(fwdbwd_fused)  # aot-ok: bench measurement
                       .lower(state, sharded).compile()
                       .memory_analysis())
                row = {"schedule": sched, "remat": False,
                       "n_microbatches": n_micro,
                       "temp_bytes": int(mem.temp_size_in_bytes),
                       "arg_bytes": int(mem.argument_size_in_bytes),
                       "out_bytes": int(mem.output_size_in_bytes)}
                rows.append(row)
                print(json.dumps(row), flush=True)

    # --- batch sweep: the memory pass's affine temp model vs XLA -------
    # temp(batch) measured at B/2 and B, extrapolated to 2B, asserted
    # against the real 2B compile — per schedule family at n_micro=4.
    from dtf_tpu.analysis import memory as memory_pass

    def temp_at(remat, schedule, batch_rows):
        cfg = dataclasses.replace(base, remat=remat)
        init_fn = gpt_pipe.make_pipe_init(cfg, mesh, seq_len=seq)
        tx = optax.sgd(1e-3)
        state, _ = tr.create_train_state(
            init_fn, tx, jax.random.PRNGKey(0), mesh,
            param_rules=gpt_pipe.pipe_rules())
        data = SyntheticData("gpt", batch_rows, seed=0, seq_len=seq,
                             vocab_size=base.vocab_size).batch(0)
        sharded = shard_batch(data, mesh)
        if schedule in ("1f1b", "zb"):
            maker = (gpt_pipe.make_pipe_grads_1f1b if schedule == "1f1b"
                     else gpt_pipe.make_pipe_grads_zb)
            grads_fn = maker(cfg, mesh, n_microbatches=4)

            def fwdbwd(st, bt):
                loss, _, grads = grads_fn(st.params, st.extra, bt,
                                          jax.random.PRNGKey(0))
                return loss, grads
        else:
            loss_fn = gpt_pipe.make_pipe_loss(cfg, mesh, n_microbatches=4)

            def fwdbwd(st, bt):
                (loss, _), grads = jax.value_and_grad(
                    lambda p: loss_fn(p, st.extra, bt,
                                      jax.random.PRNGKey(0)),
                    has_aux=True)(st.params)
                return loss, grads

        mem = (jax.jit(fwdbwd)  # aot-ok: bench measurement lowering
               .lower(state, sharded).compile()
               .memory_analysis())
        return int(mem.temp_size_in_bytes)

    predict_ok = True
    sweep = []
    for sched, remat in (("gpipe", False), ("gpipe", True),
                         ("1f1b", False), ("zb", False)):
        temps = {b: temp_at(remat, sched, b)
                 for b in (batch // 2, batch, 2 * batch)}
        model = memory_pass.affine_temp_model(
            {b: temps[b] for b in (batch // 2, batch)})
        pred = memory_pass.predict_temp(model, 2 * batch)
        meas = temps[2 * batch]
        err = abs(pred - meas) / max(meas, 1)
        row = {"schedule": sched, "remat": remat, "n_microbatches": 4,
               "batch_sweep": {str(b): t for b, t in temps.items()},
               "temp_bytes": meas, "batch": 2 * batch,
               "predicted_temp_bytes": pred,
               "predict_rel_err": round(err, 4)}
        sweep.append(row)
        print(json.dumps(row), flush=True)
        predict_ok = predict_ok and err <= PREDICT_TOL

    # --- step-count bubble model: what the extra ZB stash buys ---------
    # pure dependency-graph sim (parallel/pipeline.schedule_bubble_model)
    # at the measured mesh's S=2 and the ISSUE 18 reference point S=4 —
    # ZB's modeled idle fraction must sit strictly below 1F1B's.
    from dtf_tpu.parallel.pipeline import schedule_bubble_model

    bubble_rows = []
    zb_beats_1f1b = True
    for n_stages in (2, 4):
        for n_micro in (4, 8):
            pair = {}
            for sched in ("1f1b", "zb"):
                m = schedule_bubble_model(n_stages, n_micro, sched)
                pair[sched] = m
                bubble_rows.append(m)
                print(json.dumps(m), flush=True)
            zb_beats_1f1b = zb_beats_1f1b and (
                pair["zb"]["idle_frac"] < pair["1f1b"]["idle_frac"])

    base_row = next(r for r in rows if r["schedule"] == "gpipe"
                    and not r["remat"] and r["n_microbatches"] == 8)
    remat_row = next(r for r in rows if r["schedule"] == "gpipe"
                     and r["remat"] and r["n_microbatches"] == 8)
    f1b_row = next(r for r in rows if r["schedule"] == "1f1b"
                   and r["n_microbatches"] == 8)
    zb_row = next(r for r in rows if r["schedule"] == "zb"
                  and r["n_microbatches"] == 8)
    summary = {
        "config": {"d_model": base.d_model, "layers": base.layers,
                   "d_ff": base.d_ff, "seq": seq, "batch": batch,
                   "mesh": "data2 x pipe2", "backend":
                   jax.default_backend()},
        "rows": rows,
        "remat_temp_reduction_at_m8": round(
            base_row["temp_bytes"] / max(remat_row["temp_bytes"], 1), 2),
        "1f1b_temp_reduction_at_m8": round(
            base_row["temp_bytes"] / max(f1b_row["temp_bytes"], 1), 2),
        "1f1b_vs_gpipe_remat_at_m8": round(
            remat_row["temp_bytes"] / max(f1b_row["temp_bytes"], 1), 2),
        "zb_temp_overhead_vs_1f1b_at_m8": round(
            zb_row["temp_bytes"] / max(f1b_row["temp_bytes"], 1), 2),
        "batch_sweep": sweep,
        "predict_tol": PREDICT_TOL,
        "predicted_within_tol": predict_ok,
        "bubble_model": bubble_rows,
        "zb_idle_below_1f1b": zb_beats_1f1b,
    }
    # merge-preserving regeneration: rows from an older artifact that this
    # run did NOT re-measure (other seq/batch env settings, future
    # schedules) survive; re-measured keys are replaced in place.
    if os.path.exists(ARTIFACT):
        try:
            with open(ARTIFACT) as f:
                old = json.load(f)
        except (OSError, ValueError):
            old = {}
        key = lambda r: (r.get("schedule"), r.get("remat"),
                         r.get("n_microbatches"))
        fresh = {key(r) for r in rows}
        summary["rows"] = rows + [r for r in old.get("rows", ())
                                  if key(r) not in fresh]
    with open(ARTIFACT, "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps({"remat_temp_reduction_at_m8":
                      summary["remat_temp_reduction_at_m8"],
                      "1f1b_temp_reduction_at_m8":
                      summary["1f1b_temp_reduction_at_m8"],
                      "zb_temp_overhead_vs_1f1b_at_m8":
                      summary["zb_temp_overhead_vs_1f1b_at_m8"],
                      "zb_idle_below_1f1b": zb_beats_1f1b,
                      "predicted_within_tol": predict_ok}))
    # ISSUE 18's schedule contract: deferring W into the drain bubble
    # must shrink modeled idle at every (S, M) this artifact prices.
    assert zb_beats_1f1b, (
        "zero-bubble modeled idle_frac not strictly below 1F1B's — see "
        "PIPE_MEM.json bubble_model rows")
    # the cross-check satellite's contract: affine extrapolation must
    # track XLA's allocator — fail loudly (after writing the artifact,
    # so the rows are inspectable) when it doesn't.
    assert predict_ok, (
        f"predicted_temp_bytes off by more than {PREDICT_TOL:.0%} on at "
        f"least one batch-sweep row (batch={2 * batch}, n_micro=4) — see "
        f"PIPE_MEM.json batch_sweep[].predict_rel_err")


if __name__ == "__main__":
    main()
