#!/usr/bin/env python
"""MNIST softmax training — the reference repo's `distributed.py`, TPU-native.

Launch-compatible with the reference (SURVEY.md §1 L6): the same command
shape works, e.g.

    python scripts/distributed.py \
        --ps_hosts=localhost:2222 --worker_hosts=localhost:2223,localhost:2224 \
        --job_name=worker --task_index=0 --issync=1 --backend=tpu

On the TPU backend the ps/worker roles collapse (ps processes exit 0; workers
become JAX processes over one device mesh); a single-process launch with no
cluster flags trains on all local devices. `--backend=cpu` runs the same
program on a simulated mesh for development.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from absl import app, flags, logging as absl_logging

from dtf_tpu.cli import flags as dflags

dflags.define_cluster_flags()
dflags.define_mesh_flags()
dflags.define_train_flags(batch_size=64, learning_rate=0.01, train_steps=200)
flags.DEFINE_string("model", "softmax", "softmax | mlp")
FLAGS = flags.FLAGS


def main(argv):
    del argv
    absl_logging.use_python_logging()
    import logging

    logging.getLogger("dtf_tpu").setLevel(logging.INFO)
    import json

    import jax
    import optax

    from dtf_tpu.checkpoint import Checkpointer
    from dtf_tpu.cli.launch import (emit_run_report, host_batches,
                                    profiler_hooks, setup,
                                    telemetry_from_flags)
    from dtf_tpu.core import train as tr
    from dtf_tpu.data import mnist as mnist_data
    from dtf_tpu.data.synthetic import SyntheticData
    from dtf_tpu.fault import inject
    from dtf_tpu.hooks import (CheckpointHook, LoggingHook,
                               PreemptionHook, StopAtStepHook)
    from dtf_tpu.loop import Trainer
    from dtf_tpu.metrics import MetricWriter
    from dtf_tpu.models import mnist as mnist_model

    mesh, info = setup(FLAGS)
    tel = telemetry_from_flags(FLAGS, info)

    model = mnist_model.make_model(FLAGS.model)
    # GradientDescentOptimizer equivalent; the reference used plain SGD.
    sched = dflags.make_lr_schedule(FLAGS)   # LoggingHook surfaces the LR
    tx = dflags.make_optimizer(FLAGS, optax.sgd)
    state, shardings = tr.create_train_state(
        mnist_model.make_init(model), tx, jax.random.PRNGKey(FLAGS.seed),
        mesh)
    step = tr.make_train_step(mnist_model.make_loss(model), tx, mesh,
                              shardings, grad_accum=FLAGS.grad_accum,
                              telemetry=tel)

    def make_loader(*, host_index, host_count):
        if FLAGS.data_dir and mnist_data.available(FLAGS.data_dir):
            from dtf_tpu.data import native as native_io

            img = os.path.join(FLAGS.data_dir,
                               mnist_data.FILES["train_images"])
            lab = os.path.join(FLAGS.data_dir,
                               mnist_data.FILES["train_labels"])
            if native_io.native_available() and os.path.exists(img) \
                    and os.path.exists(lab):
                # C++ prefetching loader (queue-runner successor)
                return native_io.NativeIdxData(
                    img, lab, FLAGS.batch_size, seed=FLAGS.seed,
                    host_index=host_index, host_count=host_count)
            return mnist_data.MnistData(
                FLAGS.data_dir, FLAGS.batch_size, seed=FLAGS.seed,
                host_index=host_index, host_count=host_count)
        if FLAGS.data_dir:
            absl_logging.warning("MNIST files not found in %s; using "
                                 "synthetic data", FLAGS.data_dir)
        return SyntheticData(
            "mnist", FLAGS.batch_size, seed=FLAGS.seed,
            host_index=host_index, host_count=host_count)

    # single / real-multi / fake-hosts dispatch (docs/RESILIENCE.md):
    # fake mode feeds per-host disjoint shards through the HostView
    # assembly so the multi-host data contract runs on the CPU sim too.
    batches, place_batch = host_batches(info, mesh, make_loader)

    writer = MetricWriter(FLAGS.logdir if info.is_chief else None)
    # fake hosts: only the chief owns the shared checkpoint dir (every
    # worker holds the full state); real multi-host saves are collective.
    ckpt = Checkpointer(os.path.join(FLAGS.logdir, "ckpt"),
                        save_interval_steps=FLAGS.checkpoint_every)
    save_ckpt = ckpt if info.participates_in_save else None

    def on_preempt(step_):
        # the SIGTERM chain's last link: flight dump happened in the
        # telemetry handler, the checkpoint is durable — now tell the
        # controller where the run stood (one host fact file).
        marker = os.path.join(FLAGS.logdir, "telemetry",
                              f"p{info.process_id}" if
                              info.num_processes > 1 else "",
                              "preempt.json")
        os.makedirs(os.path.dirname(marker), exist_ok=True)
        with open(marker, "w") as f:
            json.dump({"step": int(step_), "host": info.process_id}, f)

    hooks = [LoggingHook(writer, FLAGS.log_every, lr_schedule=sched,
                         telemetry=tel)]
    fault = inject.maybe_hook(host_index=info.process_id,
                              checkpointer=save_ckpt)
    if fault is not None:
        hooks.insert(0, fault)   # injected faults land before save hooks
    hooks += [CheckpointHook(save_ckpt, FLAGS.checkpoint_every)
              ] if save_ckpt is not None else []
    hooks += [PreemptionHook(save_ckpt, on_preempt=on_preempt),
              StopAtStepHook(FLAGS.train_steps),
              *profiler_hooks(FLAGS, telemetry=tel)]
    trainer = Trainer(step, mesh, hooks=hooks, checkpointer=ckpt,
                      place_batch=place_batch, telemetry=tel,
                      prefetch=FLAGS.prefetch_depth)
    state = trainer.fit(state, batches)
    emit_run_report(tel, info, extra={"workload": "mnist",
                                      "fake_hosts": info.fake_hosts})

    # final eval (the reference's script printed test accuracy at the end):
    # real data → the FULL t10k test split, averaged over batches; synthetic
    # → a held-out step index.
    import itertools

    from dtf_tpu.core.comms import shard_batch

    # fake hosts hold the whole mesh, so they read the full split locally
    # (local_host_ids); real processes read their 1/N shard.
    eval_host, eval_hosts = info.local_host_ids()
    if not (FLAGS.data_dir and mnist_data.available(FLAGS.data_dir)):
        held_out = SyntheticData("mnist", FLAGS.batch_size, seed=FLAGS.seed,
                                 host_index=eval_host,
                                 host_count=eval_hosts)
        eval_batches = [held_out.batch(10_000_019)]
    else:
        test = mnist_data.MnistData(
            FLAGS.data_dir, FLAGS.batch_size, split="test", seed=FLAGS.seed,
            host_index=eval_host, host_count=eval_hosts)
        # uniform across hosts: every process must drive the jitted eval
        # step the same number of times or the mesh deadlocks.
        eval_batches = itertools.islice(iter(test),
                                        test.batches_per_epoch_uniform())
    eval_step = tr.make_eval_step(mnist_model.make_eval(model), mesh,
                                  shardings)
    totals, n_eval = {}, 0
    for eval_batch in eval_batches:
        m = eval_step(state, shard_batch(eval_batch, mesh))
        for k, v in m.items():
            totals[k] = totals.get(k, 0.0) + float(v)
        n_eval += 1
    if n_eval:
        eval_metrics = {k: v / n_eval for k, v in totals.items()}
        writer.write_scalars(int(state.step), eval_metrics)
        summary = f"eval_accuracy={eval_metrics['eval_accuracy']:.4f}"
    else:
        absl_logging.warning(
            "test split smaller than one uniform per-host batch "
            "(batch_size too large for the host count); skipping final eval")
        summary = "eval_accuracy=n/a"
    writer.close()
    ckpt.close()
    print(f"done: step={int(state.step)} {summary}")


if __name__ == "__main__":
    app.run(main)
