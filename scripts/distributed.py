#!/usr/bin/env python
"""MNIST softmax training — the reference repo's `distributed.py`, TPU-native.

Launch-compatible with the reference (SURVEY.md §1 L6): the same command
shape works, e.g.

    python scripts/distributed.py \
        --ps_hosts=localhost:2222 --worker_hosts=localhost:2223,localhost:2224 \
        --job_name=worker --task_index=0 --issync=1 --backend=tpu

On the TPU backend the ps/worker roles collapse (ps processes exit 0; workers
become JAX processes over one device mesh); a single-process launch with no
cluster flags trains on all local devices. `--backend=cpu` runs the same
program on a simulated mesh for development.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from absl import app, flags, logging as absl_logging

from dtf_tpu.cli import flags as dflags

dflags.define_cluster_flags()
dflags.define_mesh_flags()
dflags.define_train_flags(batch_size=64, learning_rate=0.01, train_steps=200)
flags.DEFINE_string("model", "softmax", "softmax | mlp")
FLAGS = flags.FLAGS


def main(argv):
    del argv
    absl_logging.use_python_logging()
    import logging

    logging.getLogger("dtf_tpu").setLevel(logging.INFO)
    import jax
    import optax

    from dtf_tpu.checkpoint import Checkpointer
    from dtf_tpu.cli.launch import profiler_hooks, setup
    from dtf_tpu.core import train as tr
    from dtf_tpu.data import mnist as mnist_data
    from dtf_tpu.data.synthetic import SyntheticData
    from dtf_tpu.hooks import (CheckpointHook, LoggingHook,
                               PreemptionHook, StopAtStepHook)
    from dtf_tpu.loop import Trainer
    from dtf_tpu.metrics import MetricWriter
    from dtf_tpu.models import mnist as mnist_model

    mesh, info = setup(FLAGS)

    model = mnist_model.make_model(FLAGS.model)
    # GradientDescentOptimizer equivalent; the reference used plain SGD.
    sched = dflags.make_lr_schedule(FLAGS)   # LoggingHook surfaces the LR
    tx = dflags.make_optimizer(FLAGS, optax.sgd)
    state, shardings = tr.create_train_state(
        mnist_model.make_init(model), tx, jax.random.PRNGKey(FLAGS.seed),
        mesh)
    step = tr.make_train_step(mnist_model.make_loss(model), tx, mesh,
                              shardings, grad_accum=FLAGS.grad_accum)

    if FLAGS.data_dir and mnist_data.available(FLAGS.data_dir):
        from dtf_tpu.data import native as native_io

        img = os.path.join(FLAGS.data_dir, mnist_data.FILES["train_images"])
        lab = os.path.join(FLAGS.data_dir, mnist_data.FILES["train_labels"])
        if native_io.native_available() and os.path.exists(img) \
                and os.path.exists(lab):
            # C++ prefetching loader (queue-runner successor)
            data = native_io.NativeIdxData(
                img, lab, FLAGS.batch_size, seed=FLAGS.seed,
                host_index=info.process_id, host_count=info.num_processes)
        else:
            data = mnist_data.MnistData(
                FLAGS.data_dir, FLAGS.batch_size, seed=FLAGS.seed,
                host_index=info.process_id, host_count=info.num_processes)
    else:
        if FLAGS.data_dir:
            absl_logging.warning("MNIST files not found in %s; using "
                                 "synthetic data", FLAGS.data_dir)
        data = SyntheticData(
            "mnist", FLAGS.batch_size, seed=FLAGS.seed,
            host_index=info.process_id, host_count=info.num_processes)

    writer = MetricWriter(FLAGS.logdir if info.is_chief else None)
    ckpt = Checkpointer(os.path.join(FLAGS.logdir, "ckpt"),
                        save_interval_steps=FLAGS.checkpoint_every)
    trainer = Trainer(
        step, mesh,
        hooks=[LoggingHook(writer, FLAGS.log_every, lr_schedule=sched),
               CheckpointHook(ckpt, FLAGS.checkpoint_every),
               PreemptionHook(ckpt),
               StopAtStepHook(FLAGS.train_steps),
               *profiler_hooks(FLAGS)],
        checkpointer=ckpt)
    state = trainer.fit(state, iter(data))

    # final eval (the reference's script printed test accuracy at the end):
    # real data → the FULL t10k test split, averaged over batches; synthetic
    # → a held-out step index.
    import itertools

    from dtf_tpu.core.comms import shard_batch

    if isinstance(data, SyntheticData):
        eval_batches = [data.batch(10_000_019)]
    else:
        test = mnist_data.MnistData(
            FLAGS.data_dir, FLAGS.batch_size, split="test", seed=FLAGS.seed,
            host_index=info.process_id, host_count=info.num_processes)
        # uniform across hosts: every process must drive the jitted eval
        # step the same number of times or the mesh deadlocks.
        eval_batches = itertools.islice(iter(test),
                                        test.batches_per_epoch_uniform())
    eval_step = tr.make_eval_step(mnist_model.make_eval(model), mesh,
                                  shardings)
    totals, n_eval = {}, 0
    for eval_batch in eval_batches:
        m = eval_step(state, shard_batch(eval_batch, mesh))
        for k, v in m.items():
            totals[k] = totals.get(k, 0.0) + float(v)
        n_eval += 1
    if n_eval:
        eval_metrics = {k: v / n_eval for k, v in totals.items()}
        writer.write_scalars(int(state.step), eval_metrics)
        summary = f"eval_accuracy={eval_metrics['eval_accuracy']:.4f}"
    else:
        absl_logging.warning(
            "test split smaller than one uniform per-host batch "
            "(batch_size too large for the host count); skipping final eval")
        summary = "eval_accuracy=n/a"
    writer.close()
    ckpt.close()
    print(f"done: step={int(state.step)} {summary}")


if __name__ == "__main__":
    app.run(main)
