#!/usr/bin/env python
"""MNIST softmax training — the reference repo's `distributed.py`, TPU-native.

Launch-compatible with the reference (SURVEY.md §1 L6): the same command
shape works, e.g.

    python scripts/distributed.py \
        --ps_hosts=localhost:2222 --worker_hosts=localhost:2223,localhost:2224 \
        --job_name=worker --task_index=0 --issync=1 --backend=tpu

On the TPU backend the ps/worker roles collapse (ps processes exit 0; workers
become JAX processes over one device mesh); a single-process launch with no
cluster flags trains on all local devices. `--backend=cpu` runs the same
program on a simulated mesh for development.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from absl import app, flags, logging as absl_logging

from dtf_tpu.cli import flags as dflags

dflags.define_cluster_flags()
dflags.define_mesh_flags()
dflags.define_train_flags(batch_size=64, learning_rate=0.01, train_steps=200)
flags.DEFINE_string("model", "softmax", "softmax | mlp")
FLAGS = flags.FLAGS


def main(argv):
    del argv
    absl_logging.use_python_logging()
    import logging

    logging.getLogger("dtf_tpu").setLevel(logging.INFO)
    import jax
    import optax

    from dtf_tpu.checkpoint import Checkpointer
    from dtf_tpu.cli.launch import setup
    from dtf_tpu.core import train as tr
    from dtf_tpu.data import mnist as mnist_data
    from dtf_tpu.data.synthetic import SyntheticData
    from dtf_tpu.hooks import CheckpointHook, LoggingHook, StopAtStepHook
    from dtf_tpu.loop import Trainer
    from dtf_tpu.metrics import MetricWriter
    from dtf_tpu.models import mnist as mnist_model

    mesh, info = setup(FLAGS)

    model = mnist_model.make_model(FLAGS.model)
    # GradientDescentOptimizer equivalent; the reference used plain SGD.
    tx = optax.sgd(FLAGS.learning_rate)
    state, shardings = tr.create_train_state(
        mnist_model.make_init(model), tx, jax.random.PRNGKey(FLAGS.seed),
        mesh)
    step = tr.make_train_step(mnist_model.make_loss(model), tx, mesh,
                              shardings, grad_accum=FLAGS.grad_accum)

    if FLAGS.data_dir and mnist_data.available(FLAGS.data_dir):
        from dtf_tpu.data import native as native_io

        img = os.path.join(FLAGS.data_dir, mnist_data.FILES["train_images"])
        lab = os.path.join(FLAGS.data_dir, mnist_data.FILES["train_labels"])
        if native_io.native_available() and os.path.exists(img) \
                and os.path.exists(lab):
            # C++ prefetching loader (queue-runner successor)
            data = native_io.NativeIdxData(
                img, lab, FLAGS.batch_size, seed=FLAGS.seed,
                host_index=info.process_id, host_count=info.num_processes)
        else:
            data = mnist_data.MnistData(
                FLAGS.data_dir, FLAGS.batch_size, seed=FLAGS.seed,
                host_index=info.process_id, host_count=info.num_processes)
    else:
        if FLAGS.data_dir:
            absl_logging.warning("MNIST files not found in %s; using "
                                 "synthetic data", FLAGS.data_dir)
        data = SyntheticData(
            "mnist", FLAGS.batch_size, seed=FLAGS.seed,
            host_index=info.process_id, host_count=info.num_processes)

    writer = MetricWriter(FLAGS.logdir if info.is_chief else None)
    ckpt = Checkpointer(os.path.join(FLAGS.logdir, "ckpt"),
                        save_interval_steps=FLAGS.checkpoint_every)
    trainer = Trainer(
        step, mesh,
        hooks=[LoggingHook(writer, FLAGS.log_every),
               CheckpointHook(ckpt, FLAGS.checkpoint_every),
               StopAtStepHook(FLAGS.train_steps)],
        checkpointer=ckpt)
    state = trainer.fit(state, iter(data))

    # final eval (the reference's script printed test accuracy at the end):
    # real data → the t10k test split; synthetic → a held-out step index.
    if isinstance(data, SyntheticData):
        eval_batch = data.batch(10_000_019)
    else:
        eval_batch = next(iter(mnist_data.MnistData(
            FLAGS.data_dir, FLAGS.batch_size, split="test", seed=FLAGS.seed,
            host_index=info.process_id, host_count=info.num_processes)))
    eval_step = tr.make_eval_step(mnist_model.make_eval(model), mesh,
                                  shardings)
    from dtf_tpu.core.comms import shard_batch

    eval_metrics = eval_step(state, shard_batch(eval_batch, mesh))
    writer.write_scalars(int(state.step),
                         {k: float(v) for k, v in eval_metrics.items()})
    writer.close()
    ckpt.close()
    print(f"done: step={int(state.step)} "
          f"eval_accuracy={float(eval_metrics['eval_accuracy']):.4f}")


if __name__ == "__main__":
    app.run(main)
