#!/usr/bin/env python
"""MFU diagnosis for the ResNet-50 bench (VERDICT r2 next-round #2).

Runs a matrix of experiments on the real chip, each in a watchdogged
subprocess (axon resilience contract, same as bench.py):

- batch sweep: step time at batch 128/256/512/1024;
- XLA's own FLOP count for the compiled step (``compiled.cost_analysis()``)
  so the analytic 3x4.09 GFLOP/img constant in bench.py is cross-checked
  against the compiler instead of trusted;
- dispatch-mode A/B: per-step Python dispatch vs K steps folded into one
  device-side ``lax.scan`` — isolates host->TPU dispatch latency (the chip
  sits behind a tunnel here) from device compute time.

Writes ``PERF_SWEEP.json`` at the repo root; PERF.md interprets it.
"""

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
ARTIFACT = os.path.join(ROOT, "PERF_SWEEP.json")
SENTINEL = "PERF_ROW "
CHILD_TIMEOUT_S = 900
RESNET50_TRAIN_FLOPS_PER_IMG = 3 * 4.09e9
V5E_PEAK_BF16_FLOPS = 197e12


def child():
    sys.path.insert(0, ROOT)
    import jax
    import numpy as np
    import optax

    from dtf_tpu.core import train as tr
    from dtf_tpu.core.comms import shard_batch
    from dtf_tpu.core.mesh import make_mesh
    from dtf_tpu.models import resnet

    batch = int(os.environ["DTF_PERF_BATCH"])
    # dispatch | scan | profile
    mode = os.environ.get("DTF_PERF_MODE", "dispatch")
    n_steps = int(os.environ.get("DTF_PERF_STEPS", "20"))
    bf16_input = os.environ.get("DTF_PERF_BF16_IN") == "1"

    mesh = make_mesh()
    model = resnet.resnet50()
    tx = optax.sgd(0.1, momentum=0.9)
    state, shardings = tr.create_train_state(
        resnet.make_init(model, (224, 224, 3)), tx, jax.random.PRNGKey(0),
        mesh)
    step = tr.make_train_step(resnet.make_loss(model), tx, mesh, shardings,
                              log_grad_norm=False)

    rng = np.random.default_rng(0)
    img = rng.random((batch, 224, 224, 3), np.float32)
    if bf16_input:
        # host-side bf16 (ml_dtypes): the transfer and the model input are
        # half the bytes; no device round-trip before shard_batch.
        import ml_dtypes
        img = img.astype(ml_dtypes.bfloat16)
    data = shard_batch(
        {"image": img,
         "label": rng.integers(0, 1000, (batch,)).astype(np.int32)}, mesh)

    row = {"batch": batch, "mode": mode, "n_steps": n_steps,
           "bf16_input": bf16_input, "backend": jax.default_backend()}

    # XLA's own cost model for one compiled step (only once, on the 128 run).
    if os.environ.get("DTF_PERF_COST") == "1":
        try:
            # aot-ok: one-shot XLA cost model of the swept step
            traced = step.lower(state, data)
            cost = traced.compile().cost_analysis()  # aot-ok: cost leg
            if isinstance(cost, (list, tuple)):
                cost = cost[0]
            row["xla_flops_per_step"] = float(cost.get("flops", 0.0))
            row["xla_bytes_accessed"] = float(cost.get("bytes accessed", 0.0))
        except Exception as e:  # cost_analysis is best-effort per backend
            row["cost_error"] = repr(e)[:300]

    if mode == "scan":
        # Fold K steps into one jit call: an inner non-donating jitted step
        # scanned on-device. Removes per-step host dispatch entirely — the
        # delta vs "dispatch" mode IS the tunnel/dispatch overhead.
        raw = tr.make_train_step(resnet.make_loss(model), tx, mesh, shardings,
                                 log_grad_norm=False, donate=False)

        @jax.jit
        def k_steps(state, data):
            def body(s, _):
                s2, m = raw(s, data)
                return s2, m["loss"]
            return jax.lax.scan(body, state, None, length=n_steps)

        # fence with a VALUE READBACK: on the axon plugin block_until_ready
        # returns early (the r2 sweep measured an impossible 0.2 ms/step
        # this way); float() forces the transfer and cannot lie.
        state2, losses = k_steps(state, data)
        float(losses[-1])
        t0 = time.perf_counter()
        state2, losses = k_steps(state, data)
        float(losses[-1])
        dt = time.perf_counter() - t0
    elif mode == "profile":
        import glob
        import gzip
        prof_dir = os.path.join(ROOT, "profile_r03")
        for _ in range(3):
            state, metrics = step(state, data)
        float(metrics["loss"])
        with jax.profiler.trace(prof_dir):
            t0 = time.perf_counter()
            for _ in range(n_steps):
                state, metrics = step(state, data)
            float(metrics["loss"])
            dt = time.perf_counter() - t0
        # parse the XPlane with the tensorboard profile plugin → top ops
        try:
            from tensorboard_plugin_profile.convert import raw_to_tool_data
            xplanes = glob.glob(os.path.join(
                prof_dir, "plugins/profile/*/*.xplane.pb"))
            data_str, _ = raw_to_tool_data.xspace_to_tool_data(
                [xplanes[-1]], "framework_op_stats", {"tqx": "out:csv;"})
            if isinstance(data_str, bytes):
                data_str = data_str.decode()
            if data_str.startswith("\x1f\x8b".encode().decode("latin1")):
                data_str = gzip.decompress(
                    data_str.encode("latin1")).decode()
            row["op_stats_csv_head"] = "\n".join(
                data_str.splitlines()[:25])
        except Exception as e:
            row["profile_parse_error"] = repr(e)[:500]
    else:
        for _ in range(3):
            state, metrics = step(state, data)
        float(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(n_steps):
            state, metrics = step(state, data)
        float(metrics["loss"])
        dt = time.perf_counter() - t0

    img_s = batch * n_steps / dt
    row["sec_per_step"] = round(dt / n_steps, 5)
    row["img_per_sec"] = round(img_s, 1)
    row["mfu_analytic"] = round(
        img_s * RESNET50_TRAIN_FLOPS_PER_IMG / V5E_PEAK_BF16_FLOPS, 4)
    if "xla_flops_per_step" in row:
        row["mfu_xla"] = round(
            row["xla_flops_per_step"] * n_steps / dt / V5E_PEAK_BF16_FLOPS, 4)
    print(SENTINEL + json.dumps(row))


def main():
    from _dtf_watchdog import Budget, child_argv, probe_backend, \
        run_budgeted_jobs

    budget = Budget(float(os.environ.get("DTF_PERF_BUDGET_S", "5400")))
    # fast-fail a dead tunnel (~3.5 min) before a multi-child sweep
    backend, probe_errors = probe_backend(env=dict(os.environ))
    if backend is None:
        print(json.dumps({"probe": ("backend unavailable: "
                                    + "; ".join(probe_errors))[:2000]}))
        return 1

    default_grid = []
    for batch in (128, 256, 512, 1024):
        default_grid.append(
            {"DTF_PERF_BATCH": str(batch), "DTF_PERF_MODE": "dispatch",
             "DTF_PERF_COST": "1" if batch == 128 else "0"})
    default_grid.append({"DTF_PERF_BATCH": "256", "DTF_PERF_MODE": "scan"})
    default_grid.append({"DTF_PERF_BATCH": "1024", "DTF_PERF_MODE": "scan"})
    grids = {
        "default": default_grid,
        # round-2 findings: throughput FALLS with batch → probe smaller
        # batches, bf16 host input, the fixed scan fence, and a profile.
        "followup": [
            {"DTF_PERF_BATCH": "64", "DTF_PERF_MODE": "dispatch"},
            {"DTF_PERF_BATCH": "96", "DTF_PERF_MODE": "dispatch"},
            {"DTF_PERF_BATCH": "128", "DTF_PERF_MODE": "dispatch",
             "DTF_PERF_BF16_IN": "1"},
            {"DTF_PERF_BATCH": "128", "DTF_PERF_MODE": "scan"},
            {"DTF_PERF_BATCH": "128", "DTF_PERF_MODE": "profile",
             "DTF_PERF_STEPS": "5"},
        ],
        # bf16 host input dropped after r3 measurement attempts: the
        # ml_dtypes-bf16 host->device transfer path is pathologically slow
        # on axon (child hit the 900 s watchdog), and the roofline shows
        # input bytes are ~0.2% of step traffic — not a lever worth chasing.
        "followup2": [
            {"DTF_PERF_BATCH": "128", "DTF_PERF_MODE": "profile",
             "DTF_PERF_STEPS": "5"},
            # scan length 5 (not 20): the 20-step scan-of-train-step graph
            # took >8 min to compile on axon and hit the watchdog.
            {"DTF_PERF_BATCH": "128", "DTF_PERF_MODE": "scan",
             "DTF_PERF_STEPS": "5"},
        ],
    }
    grid = grids[sys.argv[1] if len(sys.argv) > 1 else "default"]

    tag = sys.argv[1] if len(sys.argv) > 1 else "default"
    artifact = (ARTIFACT if tag == "default"
                else ARTIFACT.replace(".json", f"_{tag}.json"))
    def on_result(row, job, rows, errors):
        # write incrementally so partial progress survives a later hang
        with open(artifact, "w") as f:
            json.dump({"rows": rows, "errors": errors}, f, indent=1)
        print(json.dumps(row if row is not None else errors[-1]))

    rows, errors = run_budgeted_jobs(
        grid, child_argv(os.path.abspath(__file__)),
        lambda line: (json.loads(line[len(SENTINEL):])
                      if line.startswith(SENTINEL) else None),
        budget=budget, cap_s=CHILD_TIMEOUT_S, env_base=dict(os.environ),
        on_result=on_result)
    return 0 if rows else 1


if __name__ == "__main__":
    if "--child" in sys.argv:
        child()
    else:
        sys.exit(main())
