#!/usr/bin/env python
"""GPT inference: load a train_gpt.py checkpoint, decode with the KV cache.

    python scripts/generate_gpt.py --logdir=/tmp/dtf_tpu_logs --size=tiny \
        --prompt=12,7,99 --n_new=16 --temperature=0.8 --top_p=0.9

The serving half of the flagship loop: restores params from the Orbax
checkpoint the training launcher wrote, builds the decode-mode model
(``decode_len`` sized to prompt+new), and runs :func:`dtf_tpu.models.gpt.
generate` — greedy or temperature/top-k/nucleus sampling, optionally
sharded over a (data, model) mesh (KV cache lands P('data','model')).
Prints one token-id row per batch element.
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from absl import app, flags

from dtf_tpu.cli import flags as dflags

dflags.define_cluster_flags()
dflags.define_mesh_flags()
flags.DEFINE_string("logdir", "/tmp/dtf_tpu_logs", "training logdir whose "
                    "ckpt/ subdir holds the checkpoint to serve")
flags.DEFINE_string("size", "small", "small (gpt2-124M) | medium "
                    "(gpt2-355M) | tiny — auto-loaded from the checkpoint "
                    "manifest when present (a contradicting flag errors)")
flags.DEFINE_integer("kv_heads", 0, "grouped-query attention heads "
                     "(0 = plain MHA); manifest wins")
flags.DEFINE_integer("attn_window", 0, "sliding-window size (0 = full "
                     "causal); manifest wins")
flags.DEFINE_integer("attn_global_every", 0, "global-attention layer "
                     "cadence; manifest wins")
flags.DEFINE_string("prompt", "", "comma-separated token ids; empty = a "
                    "fixed demo prompt")
flags.DEFINE_integer("batch", 1, "decode batch size (prompt is broadcast)")
flags.DEFINE_integer("n_new", 32, "tokens to generate")
flags.DEFINE_float("temperature", 0.0, "0 = greedy, else sampling")
flags.DEFINE_integer("num_beams", 0, "beam-search width (0/1 = off); "
                     "deterministic, excludes the sampling flags")
flags.DEFINE_float("length_penalty", 0.0, "beam rescoring alpha: "
                   "score / len**alpha (0 = pure sum-logprob)")
flags.DEFINE_integer("top_k", 0, "top-k filter (0 = off)")
flags.DEFINE_float("top_p", 1.0, "nucleus filter (1.0 = off)")
flags.DEFINE_integer("seed", 0, "sampling PRNG seed")
flags.DEFINE_integer("eos_id", -1, "stop token: once a sequence emits it, "
                     "later positions are --pad_id (-1 = no stop token)")
flags.DEFINE_integer("pad_id", 0, "pad token written after --eos_id")
flags.DEFINE_string("kv_cache_dtype", "", "'' = cache at compute dtype; "
                    "'int8' = symmetric per-slot quantization — half the "
                    "cache bytes, multiplicative with --kv_heads and "
                    "--attn_window")
flags.DEFINE_integer("prefill_chunk", 0, "prefill the prompt in chunks of "
                     "this many tokens (bounded-memory long prompts; "
                     "0 = one-shot prefill)")
FLAGS = flags.FLAGS


def main(argv):
    del argv
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dtf_tpu.checkpoint import Checkpointer
    from dtf_tpu.core.mesh import MeshConfig, make_mesh
    from dtf_tpu.core.sharding import shard_tree
    from dtf_tpu.models import gpt

    if FLAGS.num_beams > 1 and (FLAGS.temperature > 0.0 or FLAGS.top_k
                                or FLAGS.top_p < 1.0):
        raise app.UsageError(
            "--num_beams is a deterministic search; it excludes "
            "--temperature/--top_k/--top_p")
    if FLAGS.temperature == 0.0 and (FLAGS.top_k or FLAGS.top_p < 1.0):
        raise app.UsageError(
            "--top_k/--top_p have no effect at --temperature=0 (greedy); "
            "set a positive temperature to sample")
    if FLAGS.backend == "cpu":
        jax.config.update("jax_platforms", "cpu")
    # Serving is a single-process, chief-only job: no cluster bootstrap.
    # Sharded decode is opt-in (explicit positive mesh axes) and runs on a
    # device SUBSET sized to the mesh — a serving batch is often tiny, and
    # training's all-devices mesh would demand batch % n_devices == 0.
    sharded = FLAGS.mesh_model > 1 or FLAGS.mesh_data > 1
    mesh = None
    if sharded:
        dp = max(FLAGS.mesh_data, 1)
        tp = max(FLAGS.mesh_model, 1)
        if dp * tp > len(jax.devices()):
            raise app.UsageError(
                f"mesh {dp}x{tp} exceeds {len(jax.devices())} devices")
        mesh = make_mesh(MeshConfig(data=dp, model=tp),
                         devices=jax.devices()[:dp * tp])

    from dtf_tpu.checkpoint import load_model_config

    # the config manifest train_gpt.py writes next to the Orbax dir is
    # authoritative for the architecture fields; hand-matched flags only
    # survive when they agree (a mismatch used to garble decode silently)
    ckpt_dir = os.path.join(FLAGS.logdir, "ckpt")
    try:
        decode_cfg = dflags.resolve_decode_config(
            FLAGS, load_model_config(ckpt_dir))
    except ValueError as e:
        raise app.UsageError(str(e))
    try:
        base = gpt.GPTConfig.by_name(decode_cfg["size"])
    except KeyError as e:
        raise app.UsageError(f"--size: {e.args[0]}")
    prompt_ids = ([int(t) for t in FLAGS.prompt.split(",") if t.strip()]
                  or [1, 2, 3, 4])
    if max(prompt_ids) >= base.vocab_size or min(prompt_ids) < 0:
        raise app.UsageError(
            f"prompt ids must be in [0, {base.vocab_size})")
    total = len(prompt_ids) + FLAGS.n_new
    if decode_cfg["kv_cache_dtype"] not in ("", "int8"):
        raise app.UsageError(
            f"--kv_cache_dtype={decode_cfg['kv_cache_dtype']!r}: "
            "'' or 'int8'")
    cfg = dataclasses.replace(base,
                              kv_heads=decode_cfg["kv_heads"] or None,
                              attn_window=decode_cfg["attn_window"],
                              attn_global_every=decode_cfg[
                                  "attn_global_every"],
                              kv_cache_dtype=decode_cfg["kv_cache_dtype"],
                              decode_len=total)
    model = gpt.GPT(cfg)

    ckpt = Checkpointer(ckpt_dir)
    step = ckpt.latest_step()
    if step is None:
        raise app.UsageError(f"no checkpoint under {FLAGS.logdir}/ckpt")
    # params-only restore: new checkpoints carry a dedicated params item
    # (no ~3x opt_state read); legacy ones fall back to the full-tree read
    params = ckpt.restore_params(step)
    print(f"restored checkpoint step {step} from {FLAGS.logdir}/ckpt",
          file=sys.stderr)

    if sharded:
        params = shard_tree(params, mesh, gpt.tp_rules)

    prompt = jnp.broadcast_to(jnp.asarray(prompt_ids, jnp.int32)[None, :],
                              (FLAGS.batch, len(prompt_ids)))
    if FLAGS.num_beams > 1:
        if mesh is not None:
            raise app.UsageError("--num_beams does not compose with a "
                                 "sharded decode mesh; shard the batch "
                                 "outside instead")
        out = gpt.generate_beam(
            model, params, prompt, FLAGS.n_new, num_beams=FLAGS.num_beams,
            eos_id=FLAGS.eos_id if FLAGS.eos_id >= 0 else None,
            pad_id=FLAGS.pad_id, length_penalty=FLAGS.length_penalty,
            prefill_chunk=FLAGS.prefill_chunk)
    else:
        out = gpt.generate(model, params, prompt, FLAGS.n_new,
                           rng=jax.random.PRNGKey(FLAGS.seed),
                           temperature=FLAGS.temperature,
                           top_k=FLAGS.top_k, top_p=FLAGS.top_p,
                           eos_id=FLAGS.eos_id if FLAGS.eos_id >= 0 else None,
                           pad_id=FLAGS.pad_id,
                           prefill_chunk=FLAGS.prefill_chunk, mesh=mesh)
    for row in np.asarray(out):
        print(",".join(str(int(t)) for t in row))


if __name__ == "__main__":
    app.run(main)
