#!/usr/bin/env python
"""Low-precision matmul A/B: bf16 vs int8 vs fp8 at the tp_dense sites
(ISSUE 17; docs/TUNING.md "Precision winners").

Each child times ONE (shape, precision) cell with the scan-amortized
loop proven in bench_attention (many iterations inside one jitted
``lax.scan``, null-jit tunnel round trip subtracted — a single dispatch
over the axon tunnel costs ~75 ms and would swamp a 768x3072 matmul)
and reports the quality bound next to the speed: ``rel_err`` is the
Frobenius relative error vs the f32 reference on the SAME operands.
Selection happens later, in ``tune.search.select_precision_winner``:
fastest ``matmul_s`` among rows inside the rel-err ceiling, bf16 exempt.

On a TPU backend the rows bank into KERNEL_TUNE_SWEEP.json
``precision_rows`` (replace-by-identity, crash-safe after every row) and
the committed KERNEL_TUNE.json golden is re-seeded from them — same
contract as bench_tune's flash rows: the golden stays re-derivable from
committed artifacts. On the CPU sim the sweep is a tiny wiring check
(interpret-grade timings are not MXU-predictive) and rows land ONLY in
BENCH_QUANT.json, never the committed sweep artifact.

Resilience contract (bench.py idiom): the parent never imports jax,
prints ONE JSON line last, exits 0 even against a dead tunnel.
"""

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
ARTIFACT = os.path.join(ROOT, "BENCH_QUANT.json")
SENTINEL = "QUANT_ROW "
CHILD_TIMEOUT_S = 600
TOTAL_BUDGET_S = float(os.environ.get("DTF_QUANT_BUDGET_S", "3600"))
PROBE_TIMEOUT_S = 90

#: the tp_dense sites worth a winner: the GPT-2-small flagship's four
#: projections (qkv/attn-proj column 768x768, mlp_in column 768x3072,
#: attn_out row 768x768, mlp_out row 3072x768) and the gpt2_draft twin
#: at d384/ff1536 — the shapes the serving draft actually runs.
QUANT_SITES = (
    {"parallel": "column", "d_in": 768, "d_out": 768},
    {"parallel": "column", "d_in": 768, "d_out": 3072},
    {"parallel": "row", "d_in": 768, "d_out": 768},
    {"parallel": "row", "d_in": 3072, "d_out": 768},
    {"parallel": "column", "d_in": 384, "d_out": 384},
    {"parallel": "column", "d_in": 384, "d_out": 1536},
    {"parallel": "row", "d_in": 384, "d_out": 384},
    {"parallel": "row", "d_in": 1536, "d_out": 384},
)
PRECISIONS = ("bf16", "int8", "fp8")
#: CPU-sim wiring-check cell (one site, bf16+int8; fp8 exercises the
#: same code path as int8 and interpret timing is meaningless anyway).
CPU_SITES = ({"parallel": "column", "d_in": 16, "d_out": 32},)
CPU_PRECISIONS = ("bf16", "int8")


def _job(site, precision, *, b=8, t=1024):
    return {"DTF_QUANT_PARALLEL": site["parallel"],
            "DTF_QUANT_D_IN": str(site["d_in"]),
            "DTF_QUANT_D_OUT": str(site["d_out"]),
            "DTF_QUANT_B": str(b), "DTF_QUANT_T": str(t),
            "DTF_QUANT_PRECISION": precision}


def child():
    import statistics

    import jax
    import jax.numpy as jnp
    from jax import lax

    from dtf_tpu.ops import quant

    parallel = os.environ["DTF_QUANT_PARALLEL"]
    d_in = int(os.environ["DTF_QUANT_D_IN"])
    d_out = int(os.environ["DTF_QUANT_D_OUT"])
    b = int(os.environ.get("DTF_QUANT_B", "8"))
    t = int(os.environ.get("DTF_QUANT_T", "1024"))
    precision = os.environ.get("DTF_QUANT_PRECISION", "int8")
    reps = int(os.environ.get("DTF_QUANT_REPS", "50"))
    if precision == "fp8" and not quant.fp8_supported():
        # a structured failure, not a silent bf16 row mislabeled fp8
        raise RuntimeError("fp8: no float8_e4m3fn dtype on this jax")

    kx, kw = jax.random.split(jax.random.PRNGKey(0))
    x = jax.random.normal(kx, (b, t, d_in), jnp.bfloat16)
    w = (jax.random.normal(kw, (d_in, d_out), jnp.bfloat16)
         / jnp.bfloat16(d_in ** 0.5))

    if precision == "bf16":
        mm = lambda a: jnp.einsum("btd,df->btf", a, w)  # noqa: E731
    else:
        mm = lambda a: quant.quantized_matmul(  # noqa: E731
            a, w, precision=precision)

    # quality bound on the same operands the timing loop runs (f32 ref)
    ref = jnp.einsum("btd,df->btf", x.astype(jnp.float32),
                     w.astype(jnp.float32))
    err = float(quant.rel_err(jax.jit(mm)(x), ref))

    def med_timed(fn, *args, n=3):
        float(fn(*args))  # compile + warm
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            float(fn(*args))
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts)

    null_s = med_timed(jax.jit(lambda v: v * 2.0), jnp.float32(1.0), n=5)

    # scan-amortized: the carry folds the output back into the next
    # iteration's activations at 1e-30 (rounds away in bf16, but XLA
    # cannot hoist the loop-invariant matmul out of the scan body).
    @jax.jit
    def loop(x0):
        def body(c, _):
            y = mm(c)
            return c + jnp.bfloat16(1e-30) * y.astype(
                jnp.float32).sum().astype(jnp.bfloat16), None

        out, _ = lax.scan(body, x0, None, length=reps)
        return out.astype(jnp.float32).sum()

    total = med_timed(loop, x)
    matmul_s = max(total - null_s, reps * 1e-7) / reps
    flops = 2.0 * b * t * d_in * d_out
    print(SENTINEL + json.dumps({
        "parallel": parallel, "d_in": d_in, "d_out": d_out, "b": b, "t": t,
        "dtype": "bfloat16", "precision": precision,
        "backend": jax.default_backend(), "n_devices": 1,
        "matmul_s": round(matmul_s, 9),
        "matmul_tflops": round(flops / matmul_s / 1e12, 3),
        "rel_err": round(err, 6)}))


def persist_precision_row(row):
    """One measured row into KERNEL_TUNE_SWEEP.json ``precision_rows``
    (replace-by-identity) — bench_tune's _persist_sweep_row contract:
    the committed golden stays re-derivable from committed artifacts."""
    from dtf_tpu.tune import search

    path = os.path.join(ROOT, search.SWEEP_ARTIFACT)
    data = {}
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        data = {}
    rows = data.get("precision_rows", [])

    def ident(r):
        return (r.get("parallel"), r.get("d_in"), r.get("d_out"),
                r.get("b"), r.get("t"), r.get("dtype"), r.get("precision"),
                r.get("backend"), r.get("n_devices"))

    rows = [r for r in rows if ident(r) != ident(row)] + [row]
    data["precision_rows"] = rows
    with open(path, "w") as f:
        json.dump(data, f, indent=1)


def reseed_golden():
    """Re-derive matmul_precision winners from the banked rows and merge
    them into BOTH caches (local + committed golden)."""
    from dtf_tpu.tune import cache, search

    entries = search.seed_precision_entries(ROOT)
    if entries:
        cache.merge_entries(cache.local_path(), entries,
                            generated_by="bench_quant.py")
        cache.merge_entries(cache.golden_path(), entries,
                            generated_by="bench_quant.py")
    return {e.canonical_key(): e.winner for e in entries}


def _write_merged(rows, errors):
    data = {}
    try:
        with open(ARTIFACT) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        data = {}
    data["rows"] = rows
    data["errors"] = errors
    with open(ARTIFACT, "w") as f:
        json.dump(data, f, indent=1)


def main():
    from _dtf_watchdog import Budget, child_argv, probe_backend, \
        run_budgeted_jobs

    summary = {"rows": 0, "errors": 0, "winners": {}}
    budget = Budget(TOTAL_BUDGET_S)
    backend, probe_errors = probe_backend(
        timeout_s=min(PROBE_TIMEOUT_S, max(10.0, budget.remaining(10))),
        retries=2, backoff_s=10, env=dict(os.environ))
    summary["backend"] = backend
    if backend is None:
        summary["probe"] = ("backend unavailable: "
                            + "; ".join(probe_errors))[:2000]
        print(json.dumps(summary))
        return 0

    on_tpu = backend == "tpu" and os.environ.get("DTF_QUANT_SMOKE") != "1"
    if on_tpu:
        jobs = [_job(s, p) for s in QUANT_SITES for p in PRECISIONS]
    else:
        jobs = [_job(s, p, b=1, t=8)
                for s in CPU_SITES for p in CPU_PRECISIONS]

    def on_result(row, job, rows, errors):
        _write_merged(rows, errors)
        summary["rows"] = len(rows)
        summary["errors"] = len(errors)
        if row is not None and on_tpu:
            persist_precision_row(row)
            summary["winners"] = reseed_golden()

    run_budgeted_jobs(
        jobs, child_argv(os.path.abspath(__file__)),
        lambda line: (json.loads(line[len(SENTINEL):])
                      if line.startswith(SENTINEL) else None),
        budget=budget, cap_s=CHILD_TIMEOUT_S, env_base=dict(os.environ),
        on_result=on_result)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    if "--child" in sys.argv:
        child()
    else:
        sys.exit(main())
