#!/usr/bin/env python
"""Host-side data-pipeline benchmark: native C++ loader vs pure Python.

The reference's input path is TF's C++ FIFOQueue/queue-runner machinery
(SURVEY.md §2b N7); this framework's replacement is ``native/dtfio.cpp``
(mmap + splitmix64 shuffle + double-buffered prefetch thread) bound via
ctypes, with a numpy fallback. This bench puts numbers on that choice —
entirely tunnel-independent (no jax import): it measures images/sec for
the IDX epoch path and MB/s for TFRecord span indexing (native
CRC32C-verified single pass vs the pure-python framing walk).

The IDX rows compare each design AS SHIPPED, which is not identical
per-epoch work: ``MnistData`` converts u8→f32 ONCE at construction
(4× resident memory, conversion untimed here) so its timed epoch is a
f32 gather; ``NativeIdxData`` normalizes per batch inside the timed
loop at ¼ the memory. The ``python_per_batch_normalize`` row is the
equal-work control (u8 gather + astype(f32)*scale per batch).

Artifact: ``BENCH_IO.json``. Tiny mode (DTF_IO_TINY=1) is CI-pinned in
tests/test_scripts.py so the wiring cannot rot between benchmark runs.
"""

import json
import os
import struct
import sys
import tempfile
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
ARTIFACT = os.path.join(ROOT, "BENCH_IO.json")

TINY = os.environ.get("DTF_IO_TINY") == "1"
N_IMAGES = 2_000 if TINY else 60_000          # MNIST-train-sized
BATCH = 256
N_RECORDS = 200 if TINY else 2_000            # TFRecord corpus
RECORD_BYTES = 1_024 if TINY else 10_240      # ~20 MB full-size (writing
# is pure-python masked-CRC-bound, so a bigger corpus measures the writer
# not the indexers; 20 MB is plenty for a stable MB/s)
EPOCHS = 1 if TINY else 3


def _timed_epochs(next_batch, n_batches):
    t0 = time.perf_counter()
    for _ in range(EPOCHS * n_batches):
        b = next_batch()
        assert b["image"].dtype == np.float32
    return time.perf_counter() - t0


def bench_idx(d):
    from dtf_tpu.data.mnist import MnistData, write_idx
    from dtf_tpu.data.native import NativeIdxData, native_available

    r = np.random.RandomState(0)
    images = r.randint(0, 256, (N_IMAGES, 28, 28)).astype(np.uint8)
    labels = r.randint(0, 10, (N_IMAGES,)).astype(np.uint8)
    ip = os.path.join(d, "train-images-idx3-ubyte")
    lp = os.path.join(d, "train-labels-idx1-ubyte")
    write_idx(ip, images)
    write_idx(lp, labels)
    n_batches = N_IMAGES // BATCH
    out = {"n_images": N_IMAGES, "batch": BATCH, "epochs": EPOCHS}

    py = MnistData(d, BATCH, split="train", seed=1)
    it = iter(py)
    # warm one epoch (page cache + any lazy init), then measure
    for _ in range(n_batches):
        next(it)
    t = _timed_epochs(lambda: next(it), n_batches)
    out["python_images_per_sec"] = round(EPOCHS * n_batches * BATCH / t, 1)
    out["python_converts_once_at_init"] = True  # see module docstring

    # equal-work python control: u8 rows gathered and normalized PER
    # BATCH, like the native loader (and at the same 1x resident memory)
    flat = images.reshape(N_IMAGES, -1)
    rs = np.random.RandomState(1)
    scale = np.float32(1.0 / 255.0)

    def per_batch():
        idx = rs.randint(0, N_IMAGES, BATCH)
        return {"image": flat[idx].astype(np.float32) * scale,
                "label": labels[idx].astype(np.int32)}

    for _ in range(n_batches):
        per_batch()
    t = _timed_epochs(per_batch, n_batches)
    out["python_per_batch_normalize_images_per_sec"] = round(
        EPOCHS * n_batches * BATCH / t, 1)

    if native_available():
        nat = NativeIdxData(ip, lp, BATCH, seed=1)
        for _ in range(n_batches):
            nat.next_batch()
        t = _timed_epochs(nat.next_batch, n_batches)
        out["native_images_per_sec"] = round(
            EPOCHS * n_batches * BATCH / t, 1)
        out["native_speedup_vs_shipped"] = round(
            out["native_images_per_sec"] / out["python_images_per_sec"], 2)
        out["native_speedup_vs_equal_work"] = round(
            out["native_images_per_sec"]
            / out["python_per_batch_normalize_images_per_sec"], 2)
        nat.close()
    else:
        out["native_images_per_sec"] = None
        out["native_error"] = "no C++ toolchain"
    return out


def bench_tfrecord(d):
    from dtf_tpu.data import tfrecord as tfr
    from dtf_tpu.data.native import native_available

    payload = os.urandom(RECORD_BYTES)
    path = os.path.join(d, "bench.tfrecord")
    tfr.write_tfrecords(path, (payload for _ in range(N_RECORDS)))
    size_mb = os.path.getsize(path) / 1e6
    out = {"n_records": N_RECORDS, "file_mb": round(size_mb, 1)}

    t0 = time.perf_counter()
    off, lens = tfr._python_spans(path)
    t_py = time.perf_counter() - t0
    assert len(off) == N_RECORDS
    out["python_index_mb_per_sec"] = round(size_mb / t_py, 1)

    # apples-to-apples with the native pass (which CRC-verifies every
    # payload): the python walk above checks only the 12-byte length CRCs
    with open(path, "rb") as f:
        raw = f.read()
    t0 = time.perf_counter()
    for o, n in zip(off[:50], lens[:50]):   # 50 records ≈ 0.5 MB: plenty
        o, n = int(o), int(n)
        (pcrc,) = struct.unpack_from("<I", raw, o + n)
        assert pcrc == tfr.masked_crc32c(raw[o:o + n])
    t_crc = (time.perf_counter() - t0) * (N_RECORDS / 50)
    out["python_index_verified_mb_per_sec"] = round(
        size_mb / (t_py + t_crc), 2)

    if native_available():
        t0 = time.perf_counter()
        off, _len = tfr.tfrecord_spans(path)  # native, payload-CRC-verified
        t_nat = time.perf_counter() - t0
        assert len(off) == N_RECORDS
        out["native_index_mb_per_sec"] = round(size_mb / t_nat, 1)
        out["native_verifies_payload_crc"] = True
        # the fair comparison: both sides verifying every payload CRC
        out["native_speedup_verified"] = round(
            out["native_index_mb_per_sec"]
            / out["python_index_verified_mb_per_sec"], 1)
    else:
        out["native_index_mb_per_sec"] = None
        out["native_error"] = "no C++ toolchain"
    return out


STREAM_BATCH = 32
STREAM_SEQ = 128 if not TINY else 32
STREAM_STEPS = 40 if TINY else 400


def bench_stream(d):
    """Mixture-stream assembly throughput (ISSUE 15, docs/DATA.md): two
    token corpora mixed 70/30, inline vs the bounded background producer
    — the number that says whether the data tier can outrun the step."""
    from dtf_tpu.data.stream import MixtureStream, TokenBinSource

    r = np.random.RandomState(0)
    for name in ("a", "b"):
        r.randint(0, 50_000, 200_000).astype(np.uint16).tofile(
            os.path.join(d, f"{name}.bin"))
    out = {"batch": STREAM_BATCH, "seq_len": STREAM_SEQ,
           "steps": STREAM_STEPS, "weights": {"a": 0.7, "b": 0.3}}

    def sources():
        return [TokenBinSource(os.path.join(d, f"{n}.bin"), STREAM_SEQ,
                               vocab_size=50_000, seed=1, salt=i, name=n)
                for i, n in enumerate(("a", "b"))]

    for label, depth in (("inline", 0), ("producer_depth2", 2)):
        stream = MixtureStream(sources(), {"a": 0.7, "b": 0.3},
                               STREAM_BATCH, seed=1, producer_depth=depth)
        it = iter(stream)
        next(it)                                 # warm (thread spin-up)
        t0 = time.perf_counter()
        for _ in range(STREAM_STEPS):
            b = next(it)
            assert b["input_ids"].dtype == np.int32
        dt = time.perf_counter() - t0
        stream.close()
        out[f"{label}_batches_per_sec"] = round(STREAM_STEPS / dt, 1)
        out[f"{label}_tokens_per_sec"] = round(
            STREAM_STEPS * STREAM_BATCH * STREAM_SEQ / dt, 1)
    stats = stream.stats()
    out["realized_frac_a"] = stats["per_source"]["a"]["realized_frac"]
    return out


def main():
    row = {"tiny": TINY, "host_cpus": os.cpu_count()}
    with tempfile.TemporaryDirectory() as d:
        row["idx_epoch"] = bench_idx(d)
    with tempfile.TemporaryDirectory() as d:
        row["tfrecord_index"] = bench_tfrecord(d)
    with tempfile.TemporaryDirectory() as d:
        row["mixture_stream"] = bench_stream(d)
    if not TINY:
        with open(ARTIFACT, "w") as f:
            json.dump(row, f, indent=1)
    print(json.dumps(row))
    return 0


if __name__ == "__main__":
    sys.exit(main())
