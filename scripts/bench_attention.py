#!/usr/bin/env python
"""Long-context attention benchmark: ring vs dense, causal-skip on vs off.

VERDICT r1 weak-spot #5 asked for measured evidence that the long-context
path does not waste FLOPs. CPU-sim mode times, at several sequence lengths:

- dense causal attention (the O(T^2) single-device baseline),
- ring attention over an 8-way ``seq`` mesh WITHOUT causal block skipping,
- ring attention WITH skipping (the default) — incoming blocks entirely
  above the diagonal never run their matmuls.

On real hardware the 8 ring shards run concurrently; under the CPU
8-virtual-device sim they share host cores, so *total* compute is what the
wall clock sees — which is exactly the quantity block-skipping halves.
CPU-sim mode re-execs itself under a clean 8-device virtual-CPU env
(pattern shared with tests/conftest.py).

TPU mode (``bench_attention.py tpu``, VERDICT r2 #3): flash vs dense on the
REAL chip — fwd and fwd+bwd at seq 1k..32k in bf16, interpret=False,
watchdogged like bench.py (the parent never imports jax). Timing is
scan-amortized (see ``tpu_child``): many iterations inside one jitted
``lax.scan`` with a measured null-jit tunnel round trip subtracted, because
a single dispatch over the axon tunnel costs ~75 ms and swamps kernel time.
Dense rows are skipped past seq 8k where the f32 score matrix exceeds v5e
HBM — flash-only rows there ARE the long-context claim. A single chip can't
ring, but flash-vs-dense is the measurable long-context evidence today.

Artifact: ``ATTN_BENCH.json`` with a ``cpu_sim`` section (ring rows) and a
``tpu`` section (flash rows); each mode preserves the other's section.
"""

import json
import os
import statistics
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
ARTIFACT = os.path.join(ROOT, "ATTN_BENCH.json")
SENTINEL = "ATTN_TPU_RESULT "
TPU_CHILD_TIMEOUT_S = 900
# Hard total budget for a tpu run (probe + all children): the round-3
# failure mode was the tunnel dying MID-collection, after the probe would
# have passed — every child then burned full retries (VERDICT r3 weak #1).
TPU_TOTAL_BUDGET_S = float(os.environ.get("DTF_ATTN_BUDGET_S", "5400"))


def _read_artifact() -> dict:
    """Guarded read; migrates the legacy (r2) top-level-cpu-rows layout."""
    data = {}
    if os.path.exists(ARTIFACT):
        try:
            with open(ARTIFACT) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            data = {}
        if "rows" in data and "cpu_sim" not in data:
            data = {"cpu_sim": data}
    return data


def _merge_artifact(section: str, payload: dict):
    data = _read_artifact()
    data[section] = payload
    with open(ARTIFACT, "w") as f:
        json.dump(data, f, indent=1)


# --------------------------------------------------------------- CPU sim

def cpu_main():
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from dtf_tpu.core.mesh import MeshConfig, make_mesh
    from dtf_tpu.ops import attention as att

    def timed(fn, *args, reps=5):
        out = fn(*args)
        jax.block_until_ready(out)
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts)

    mesh = make_mesh(MeshConfig(data=1, seq=8))
    b, h, d = 1, 8, 64
    results = {"device_count": jax.device_count(),
               "backend": jax.default_backend(), "rows": []}

    for t in (4096, 8192, 16384):
        q = jax.random.normal(jax.random.PRNGKey(0), (b, h, t, d),
                              jnp.float32)
        k = jax.random.normal(jax.random.PRNGKey(1), (b, h, t, d),
                              jnp.float32)
        v = jax.random.normal(jax.random.PRNGKey(2), (b, h, t, d),
                              jnp.float32)

        dense = jax.jit(functools.partial(att.dense_attention, causal=True))

        def ring(skip):
            spec = P(None, None, "seq", None)
            fn = functools.partial(att.ring_attention, causal=True,
                                   skip_masked_blocks=skip)
            sm = jax.shard_map(
                lambda q, k, v: fn(q, k, v),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
            return jax.jit(sm)

        def zigzag():
            spec = P(None, None, "seq", None)
            sm = jax.shard_map(
                lambda q, k, v: att.zigzag_ring_attention(q, k, v),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
            return jax.jit(sm)

        def halo(window):
            sm = jax.shard_map(
                lambda q, k, v: att.halo_attention(q, k, v, window=window),
                mesh=mesh, in_specs=(spec_, spec_, spec_), out_specs=spec_)
            return jax.jit(sm)

        spec_ = P(None, None, "seq", None)
        t_dense = timed(dense, q, k, v)
        t_ring_noskip = timed(ring(False), q, k, v)
        t_ring_skip = timed(ring(True), q, k, v)
        # zigzag: same total FLOPs as skip on the CPU sim (shared cores);
        # its extra win — no straggler shard — only shows on real parallel
        # chips, so treat this row as a correctness/overhead check.
        t_zigzag = timed(zigzag(), q, k, v)
        # halo = sliding window under the same seq sharding: total compute
        # O(T·window), so the CPU-sim wall clock (which sees total compute)
        # should fall well below every full-attention variant. Window is
        # capped at the shard length (the halo fetch is one neighbor tail).
        w = min(1024, t // 8)
        t_halo = timed(halo(w), q, k, v)
        row = {"seq": t, "dense_s": round(t_dense, 4),
               "ring_noskip_s": round(t_ring_noskip, 4),
               "ring_skip_s": round(t_ring_skip, 4),
               "zigzag_s": round(t_zigzag, 4),
               "halo_window": w,
               "halo_s": round(t_halo, 4),
               "skip_speedup": round(t_ring_noskip / t_ring_skip, 3),
               "halo_vs_ring_skip": round(t_ring_skip / t_halo, 3)}
        results["rows"].append(row)
        print(row)

    _merge_artifact("cpu_sim", results)


# --------------------------------------------------------------- real TPU

def tpu_child():
    """ONE sequence length per child (DTF_ATTN_SEQ); ~5 axon compiles each.

    Timing method (round-3 fix): a single call over the axon tunnel costs a
    ~75 ms round trip, which swamped kernel time — the first committed rows
    were FLAT from seq 1k to 4k (16x the FLOPs, same wall time). So each
    measurement folds ``reps`` iterations into ONE jitted ``lax.scan`` whose
    carry feeds the output back into the next iteration's query (scaled by
    1e-30 — numerically a no-op in bf16, but XLA cannot hoist the
    loop-invariant compute out of the scan). Per-iter time is
    (scan_time - null_jit_time) / reps, with the tunnel round trip measured
    by a trivial jitted readback and reps scaled so kernel FLOPs dominate.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from dtf_tpu.ops import attention as att
    from dtf_tpu.ops import flash_attention as fa

    # batch/heads/head_dim default to the long-context bench shape;
    # bench_tune.py's children override them to sweep the TRAIN shapes
    # (e.g. GPT-2-small's b8 h12 d64 s1024) through this same
    # scan-amortized machinery.
    b = int(os.environ.get("DTF_ATTN_B", "2"))
    h = int(os.environ.get("DTF_ATTN_H", "8"))
    d = int(os.environ.get("DTF_ATTN_D", "128"))
    t = int(os.environ["DTF_ATTN_SEQ"])
    # block-shape override for the MXU-roof sweep (VERDICT r3 #4): the
    # 512x512 default is a diagnosis-driven guess; the sweep measures it
    # against rectangular and larger shapes on the real chip.
    blk_q = int(os.environ.get("DTF_ATTN_BQ", "0"))
    blk_k = int(os.environ.get("DTF_ATTN_BK", "0"))
    blk_h = int(os.environ.get("DTF_ATTN_BH", "0"))  # head fold (fwd only)
    blk_qb = int(os.environ.get("DTF_ATTN_BQB", "0"))  # bwd-only blocks
    blk_kb = int(os.environ.get("DTF_ATTN_BKB", "0"))
    # CPU CI pin: interpret-mode run of this exact child (tiny seq) so a
    # wiring typo can't surface for the first time on the chip
    interp = os.environ.get("DTF_ATTN_INTERPRET") == "1"
    # Carry feedback scale: o*EPS is >30 orders below 1-ulp of any O(1)
    # carry entry, so the add rounds away and the values are unchanged in
    # practice — but XLA cannot prove that, so the scan body stays live.
    EPS = 1e-30

    def med_timed(fn, *args, n=3):
        float(fn(*args))  # compile + warm
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            float(fn(*args))
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts)

    # tunnel round-trip baseline: same dispatch+readback path, ~zero compute
    null_s = med_timed(jax.jit(lambda x: x * 2.0), jnp.float32(1.0), n=5)

    def scan_timed(step, q0, reps):
        @jax.jit
        def loop(q):
            out, _ = lax.scan(lambda c, _: (step(c), None), q, None,
                              length=reps)
            return out.astype(jnp.float32).sum()
        total = med_timed(loop, q0)
        # floor at 1us/iter: null_s jitters a few ms, and a noisy run where
        # the scan median lands below it must not produce 0.0 (the speedup /
        # TFLOP divisions downstream would crash the child after all the
        # measurement time was already spent).
        return max(total - null_s, reps * 1e-6) / reps

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, h, t, d), jnp.bfloat16)
               for kk in ks)

    def fwd_step(impl):
        return lambda c: c + impl(c, k, v) * EPS

    def fwdbwd_step(impl):
        def loss(q, k, v):
            return impl(q, k, v).astype(jnp.float32).sum()

        def step(c):
            dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(c, k, v)
            return c + (dq + dk + dv) * EPS
        return step

    blk_kw = {}
    if blk_q:
        blk_kw["block_q"] = blk_q
    if blk_k:
        blk_kw["block_k"] = blk_k
    if blk_h:
        blk_kw["block_h"] = blk_h
    if blk_qb:
        blk_kw["block_q_bwd"] = blk_qb
    if blk_kb:
        blk_kw["block_k_bwd"] = blk_kb
    flash = lambda q, k, v: fa.flash_attention(  # noqa: E731
        q, k, v, causal=True, interpret=interp, **blk_kw)
    dense = lambda q, k, v: att.dense_attention(  # noqa: E731
        q, k, v, causal=True)

    # reps: enough kernel FLOPs that the subtracted tunnel overhead is noise
    fwd_flops = 4 * b * h * t * t * d  # causal halves it; keep conservative
    def reps_for(flops):
        if interp:
            return 2  # CI wiring check, not a measurement
        return max(8, min(512, int(4e12 / flops)))
    r_fwd, r_bwd = reps_for(fwd_flops), reps_for(3.5 * fwd_flops)

    # dense materializes f32 scores [b,h,t,t]; past ~6 GB it cannot fit v5e
    # HBM alongside operands — record that as the finding, don't crash.
    dense_ok = b * h * t * t * 4 < 6e9

    # report the blocks that actually run: unset args resolve through
    # the kernel-tune cache now (a row must not claim the module default
    # while the kernel ran a banked winner)
    from dtf_tpu.tune import resolver as tune_resolver

    plan = tune_resolver.flash_plan(
        seq=t, heads=h, head_dim=d, dtype="bfloat16", causal=True,
        window=0, n_devices=jax.device_count(),
        backend=jax.default_backend())
    # mirrors flash_attention's plan gate EXACTLY: the banked bwd pair
    # applies only on the fully-auto path; any explicit block (fwd or
    # bwd) keeps unset bwd fields on the inherit-the-fwd contract, and
    # a misreported pair here would be persisted and seeded as a
    # "measured" winner for blocks that never ran
    auto_bwd = not (blk_q or blk_k or blk_qb or blk_kb)
    eff_bqb = blk_qb or (plan.block_q_bwd if auto_bwd else 0)
    eff_bkb = blk_kb or (plan.block_k_bwd if auto_bwd else 0)
    row = {"seq": t, "backend": jax.default_backend(), "b": b, "h": h,
           "d": d, "dtype": "bfloat16", "null_jit_s": round(null_s, 5),
           "reps_fwd": r_fwd, "reps_fwdbwd": r_bwd,
           "block_q": min(blk_q or plan.block_q, t),
           "block_k": min(blk_k or plan.block_k, t),
           "block_h": blk_h or plan.block_h,
           "block_q_bwd": eff_bqb, "block_k_bwd": eff_bkb}
    row["flash_fwd_s"] = round(scan_timed(fwd_step(flash), q, r_fwd), 6)
    row["flash_fwdbwd_s"] = round(scan_timed(fwdbwd_step(flash), q, r_bwd), 6)
    if t >= 4096:
        # sliding-window locality on chip: O(T·window) via grid-level block
        # skip — the long-context claim the halo/window stack makes.
        wn = 1024
        flash_w = lambda q, k, v: fa.flash_attention(  # noqa: E731
            q, k, v, causal=True, window=wn, interpret=interp, **blk_kw)
        r_w = reps_for(4 * b * h * t * wn * d)
        row["window"] = wn
        row["flash_window_fwd_s"] = round(
            scan_timed(fwd_step(flash_w), q, r_w), 6)
        row["window_speedup"] = round(
            row["flash_fwd_s"] / row["flash_window_fwd_s"], 3)
    if dense_ok:
        row["dense_fwd_s"] = round(scan_timed(fwd_step(dense), q, r_fwd), 6)
        row["dense_fwdbwd_s"] = round(
            scan_timed(fwdbwd_step(dense), q, r_bwd), 6)
        row["fwd_speedup"] = round(row["dense_fwd_s"] / row["flash_fwd_s"], 3)
        row["fwdbwd_speedup"] = round(
            row["dense_fwdbwd_s"] / row["flash_fwdbwd_s"], 3)
    else:
        row["dense_skipped"] = "f32 scores [b,h,t,t] exceed v5e HBM"
    # achieved TFLOP/s on the causal-true FLOP count (half the full matrix)
    row["flash_fwd_tflops"] = round(
        0.5 * fwd_flops / row["flash_fwd_s"] / 1e12, 2)
    print(SENTINEL + json.dumps(row))


def tpu_main():
    from _dtf_watchdog import Budget, probe_backend, run_budgeted_jobs

    budget = Budget(TPU_TOTAL_BUDGET_S)
    # fast-fail on a dead tunnel before committing to 6 x 900 s of children
    backend, probe_errors = probe_backend(env=dict(os.environ))
    if backend is None:
        # append the outage to the tpu section WITHOUT wiping rows already
        # measured (the pre-outage evidence PERF.md §3c preserves)
        err = {"probe": ("backend unavailable: "
                         + "; ".join(probe_errors))[:2000]}
        tpu = _read_artifact().get("tpu", {})
        tpu.setdefault("errors", []).append(err)
        _merge_artifact("tpu", tpu)
        print(json.dumps(err))
        return 1

    argv = [sys.executable, os.path.abspath(__file__), "tpu", "--child"]
    parse = lambda line: (json.loads(line[len(SENTINEL):])  # noqa: E731
                          if line.startswith(SENTINEL) else None)

    if "--sweep-blocks-bwd" in sys.argv:
        # the still-unmeasured BACKWARD block rows alone (ISSUE 2): the
        # full --sweep-blocks queue runs last in the pipeline and both
        # round-5 windows died before reaching its bwd tail, so this
        # standalone pass banks the five bwd rows early. Fwd stays pinned
        # at its sweep winner (512x1024); (512,1024) repeats as the
        # same-window control row.
        jobs = [{"DTF_ATTN_SEQ": "8192",
                 "DTF_ATTN_BQB": str(bqb), "DTF_ATTN_BKB": str(bkb)}
                for bqb, bkb in ((512, 512), (1024, 512), (512, 1024),
                                 (1024, 1024), (256, 1024))]

        def on_result(row, job, rows, errs):
            tpu = _read_artifact().get("tpu", {})
            tpu["bwd_block_sweep"] = {"rows": rows, "errors": errs}
            _merge_artifact("tpu", tpu)
            print(json.dumps(row if row is not None else errs[-1]))

        rows, errs = run_budgeted_jobs(
            jobs, argv, parse, budget=budget, cap_s=TPU_CHILD_TIMEOUT_S,
            env_base=dict(os.environ), on_result=on_result)
        return 0 if rows else 1

    if "--sweep-blocks" in sys.argv:
        # MXU-roof block-shape search (VERDICT r3 #4) at the headline seq:
        # square vs rectangular vs larger blocks, one child each.
        jobs = [{"DTF_ATTN_SEQ": "8192", "DTF_ATTN_BQ": str(bq),
                 "DTF_ATTN_BK": str(bk), "DTF_ATTN_BH": str(bh)}
                for bq, bk, bh in (
                    (256, 256, 1), (512, 512, 1), (512, 1024, 1),
                    (1024, 512, 1), (1024, 1024, 1), (512, 2048, 1),
                    # head folding (fwd): amortize per-grid-step overhead
                    (512, 512, 2), (512, 512, 4), (1024, 1024, 2))]
        # bwd-only block rows (round 5): fwd pinned at its sweep winner
        # (512x1024 — now the default), vary ONLY the backward blocks.
        # The bwd ran ~92 TF/s vs fwd's ~170 in the round-5 window; its
        # grids stream the opposite extents, so the optimum may differ.
        # (512, 1024) duplicates the inherited fwd default on purpose: a
        # same-window control row, so bwd deltas are read against a
        # baseline measured in THIS window, not one from a different
        # tunnel session.
        jobs += [{"DTF_ATTN_SEQ": "8192",
                  "DTF_ATTN_BQB": str(bqb), "DTF_ATTN_BKB": str(bkb)}
                 for bqb, bkb in ((512, 512), (1024, 512), (512, 1024),
                                  (1024, 1024), (256, 1024))]

        def on_result(row, job, rows, errs):
            tpu = _read_artifact().get("tpu", {})
            tpu["block_sweep"] = {"rows": rows, "errors": errs}
            _merge_artifact("tpu", tpu)
            print(json.dumps(row if row is not None else errs[-1]))

        rows, errs = run_budgeted_jobs(
            jobs, argv, parse, budget=budget, cap_s=TPU_CHILD_TIMEOUT_S,
            env_base=dict(os.environ), on_result=on_result)
        return 0 if rows else 1

    jobs = [{"DTF_ATTN_SEQ": str(t)}
            for t in (1024, 2048, 4096, 8192, 16384, 32768)]

    def on_result(row, job, rows, errs):
        # incremental write: partial progress survives a later hang; the
        # update preserves sibling keys (block_sweep) in the tpu section
        tpu = _read_artifact().get("tpu", {})
        tpu.update(backend="tpu", rows=rows, errors=errs)
        _merge_artifact("tpu", tpu)
        print(json.dumps(row if row is not None else errs[-1]))

    rows, errs = run_budgeted_jobs(
        jobs, argv, parse, budget=budget, cap_s=TPU_CHILD_TIMEOUT_S,
        env_base=dict(os.environ), on_result=on_result)
    return 0 if rows else 1


if __name__ == "__main__":
    if "tpu" in sys.argv:
        if "--child" in sys.argv:
            tpu_child()
        else:
            sys.exit(tpu_main())
    else:
        from _dtf_env import cpu_sim_env, is_cpu_sim

        if (not is_cpu_sim(os.environ, 8)
                and os.environ.get("_DTF_ATTN_BENCH_REEXEC") != "1"):
            env = cpu_sim_env(8, os.environ)
            env["_DTF_ATTN_BENCH_REEXEC"] = "1"
            os.execve(sys.executable, [sys.executable] + sys.argv, env)
        cpu_main()
