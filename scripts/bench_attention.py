#!/usr/bin/env python
"""Long-context attention benchmark: ring vs dense, causal-skip on vs off.

VERDICT r1 weak-spot #5 asked for measured evidence that the long-context
path does not waste FLOPs. This times, at several sequence lengths:

- dense causal attention (the O(T^2) single-device baseline),
- ring attention over an 8-way ``seq`` mesh WITHOUT causal block skipping,
- ring attention WITH skipping (the default) — incoming blocks entirely
  above the diagonal never run their matmuls.

On real hardware the 8 ring shards run concurrently; under the CPU
8-virtual-device sim they share host cores, so *total* compute is what the
wall clock sees — which is exactly the quantity block-skipping halves. The
artifact `ATTN_BENCH.json` records medians per (impl, seq).

Runs itself under a clean 8-device virtual-CPU env (re-exec pattern shared
with tests/conftest.py).
"""

import json
import os
import statistics
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from _dtf_env import cpu_sim_env, is_cpu_sim  # noqa: E402

if (not is_cpu_sim(os.environ, 8)
        and os.environ.get("_DTF_ATTN_BENCH_REEXEC") != "1"):
    env = cpu_sim_env(8, os.environ)
    env["_DTF_ATTN_BENCH_REEXEC"] = "1"
    os.execve(sys.executable, [sys.executable] + sys.argv, env)

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dtf_tpu.core.mesh import MeshConfig, make_mesh
from dtf_tpu.ops import attention as att


def timed(fn, *args, reps=5):
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts)


def main():
    mesh = make_mesh(MeshConfig(data=1, seq=8))
    b, h, d = 1, 8, 64
    results = {"device_count": jax.device_count(),
               "backend": jax.default_backend(), "rows": []}

    for t in (4096, 8192, 16384):
        q = jax.random.normal(jax.random.PRNGKey(0), (b, h, t, d),
                              jnp.float32)
        k = jax.random.normal(jax.random.PRNGKey(1), (b, h, t, d),
                              jnp.float32)
        v = jax.random.normal(jax.random.PRNGKey(2), (b, h, t, d),
                              jnp.float32)

        dense = jax.jit(functools.partial(att.dense_attention, causal=True))

        def ring(skip):
            spec = P(None, None, "seq", None)
            fn = functools.partial(att.ring_attention, causal=True,
                                   skip_masked_blocks=skip)
            sm = jax.shard_map(
                lambda q, k, v: fn(q, k, v),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
            return jax.jit(sm)

        def zigzag():
            spec = P(None, None, "seq", None)
            sm = jax.shard_map(
                lambda q, k, v: att.zigzag_ring_attention(q, k, v),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
            return jax.jit(sm)

        t_dense = timed(dense, q, k, v)
        t_ring_noskip = timed(ring(False), q, k, v)
        t_ring_skip = timed(ring(True), q, k, v)
        # zigzag: same total FLOPs as skip on the CPU sim (shared cores);
        # its extra win — no straggler shard — only shows on real parallel
        # chips, so treat this row as a correctness/overhead check.
        t_zigzag = timed(zigzag(), q, k, v)
        row = {"seq": t, "dense_s": round(t_dense, 4),
               "ring_noskip_s": round(t_ring_noskip, 4),
               "ring_skip_s": round(t_ring_skip, 4),
               "zigzag_s": round(t_zigzag, 4),
               "skip_speedup": round(t_ring_noskip / t_ring_skip, 3)}
        results["rows"].append(row)
        print(row)

    with open(os.path.join(ROOT, "ATTN_BENCH.json"), "w") as f:
        json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
