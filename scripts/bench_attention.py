#!/usr/bin/env python
"""Long-context attention benchmark: ring vs dense, causal-skip on vs off.

VERDICT r1 weak-spot #5 asked for measured evidence that the long-context
path does not waste FLOPs. CPU-sim mode times, at several sequence lengths:

- dense causal attention (the O(T^2) single-device baseline),
- ring attention over an 8-way ``seq`` mesh WITHOUT causal block skipping,
- ring attention WITH skipping (the default) — incoming blocks entirely
  above the diagonal never run their matmuls.

On real hardware the 8 ring shards run concurrently; under the CPU
8-virtual-device sim they share host cores, so *total* compute is what the
wall clock sees — which is exactly the quantity block-skipping halves.
CPU-sim mode re-execs itself under a clean 8-device virtual-CPU env
(pattern shared with tests/conftest.py).

TPU mode (``bench_attention.py tpu``, VERDICT r2 #3): flash vs dense on the
REAL chip — fwd and fwd+bwd at seq 1k/2k/4k/8k in bf16, interpret=False,
watchdogged like bench.py (the parent never imports jax), value-readback
fenced (block_until_ready is unreliable on the axon plugin). A single chip
can't ring, but flash-vs-dense is the measurable long-context claim today.

Artifact: ``ATTN_BENCH.json`` with a ``cpu_sim`` section (ring rows) and a
``tpu`` section (flash rows); each mode preserves the other's section.
"""

import json
import os
import statistics
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
ARTIFACT = os.path.join(ROOT, "ATTN_BENCH.json")
SENTINEL = "ATTN_TPU_RESULT "
TPU_CHILD_TIMEOUT_S = 900


def _merge_artifact(section: str, payload: dict):
    data = {}
    if os.path.exists(ARTIFACT):
        try:
            with open(ARTIFACT) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            data = {}
        # legacy layout (r2): top-level cpu rows — move under cpu_sim
        if "rows" in data and "cpu_sim" not in data:
            data = {"cpu_sim": data}
    data[section] = payload
    with open(ARTIFACT, "w") as f:
        json.dump(data, f, indent=1)


# --------------------------------------------------------------- CPU sim

def cpu_main():
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from dtf_tpu.core.mesh import MeshConfig, make_mesh
    from dtf_tpu.ops import attention as att

    def timed(fn, *args, reps=5):
        out = fn(*args)
        jax.block_until_ready(out)
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts)

    mesh = make_mesh(MeshConfig(data=1, seq=8))
    b, h, d = 1, 8, 64
    results = {"device_count": jax.device_count(),
               "backend": jax.default_backend(), "rows": []}

    for t in (4096, 8192, 16384):
        q = jax.random.normal(jax.random.PRNGKey(0), (b, h, t, d),
                              jnp.float32)
        k = jax.random.normal(jax.random.PRNGKey(1), (b, h, t, d),
                              jnp.float32)
        v = jax.random.normal(jax.random.PRNGKey(2), (b, h, t, d),
                              jnp.float32)

        dense = jax.jit(functools.partial(att.dense_attention, causal=True))

        def ring(skip):
            spec = P(None, None, "seq", None)
            fn = functools.partial(att.ring_attention, causal=True,
                                   skip_masked_blocks=skip)
            sm = jax.shard_map(
                lambda q, k, v: fn(q, k, v),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
            return jax.jit(sm)

        def zigzag():
            spec = P(None, None, "seq", None)
            sm = jax.shard_map(
                lambda q, k, v: att.zigzag_ring_attention(q, k, v),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
            return jax.jit(sm)

        t_dense = timed(dense, q, k, v)
        t_ring_noskip = timed(ring(False), q, k, v)
        t_ring_skip = timed(ring(True), q, k, v)
        # zigzag: same total FLOPs as skip on the CPU sim (shared cores);
        # its extra win — no straggler shard — only shows on real parallel
        # chips, so treat this row as a correctness/overhead check.
        t_zigzag = timed(zigzag(), q, k, v)
        row = {"seq": t, "dense_s": round(t_dense, 4),
               "ring_noskip_s": round(t_ring_noskip, 4),
               "ring_skip_s": round(t_ring_skip, 4),
               "zigzag_s": round(t_zigzag, 4),
               "skip_speedup": round(t_ring_noskip / t_ring_skip, 3)}
        results["rows"].append(row)
        print(row)

    _merge_artifact("cpu_sim", results)


# --------------------------------------------------------------- real TPU

def tpu_child():
    """ONE sequence length per child (DTF_ATTN_SEQ): the full 4-seq matrix
    is ~16 slow axon compiles and blew the 900 s watchdog three times in a
    row; per-seq children keep each attempt at 4 compiles."""
    import jax
    import jax.numpy as jnp

    from dtf_tpu.ops import attention as att
    from dtf_tpu.ops import flash_attention as fa

    b, h, d = 2, 8, 128
    t = int(os.environ["DTF_ATTN_SEQ"])

    def fence_timed(fn, *args, reps=5):
        # scalar-readback fence: float() cannot return before the compute.
        float(fn(*args))
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(fn(*args))
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts)

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, h, t, d), jnp.bfloat16)
               for kk in ks)

    def fwd(impl):
        def f(q, k, v):
            o = impl(q, k, v)
            return o.astype(jnp.float32).sum()
        return jax.jit(f)

    def fwdbwd(impl):
        def loss(q, k, v):
            return impl(q, k, v).astype(jnp.float32).sum()

        def f(q, k, v):
            dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            return (dq.astype(jnp.float32).sum()
                    + dk.astype(jnp.float32).sum()
                    + dv.astype(jnp.float32).sum())
        return jax.jit(f)

    flash = lambda q, k, v: fa.flash_attention(  # noqa: E731
        q, k, v, causal=True, interpret=False)
    dense = lambda q, k, v: att.dense_attention(  # noqa: E731
        q, k, v, causal=True)

    row = {"seq": t, "backend": jax.default_backend(), "b": b, "h": h,
           "d": d, "dtype": "bfloat16"}
    row["flash_fwd_s"] = round(fence_timed(fwd(flash), q, k, v), 5)
    row["dense_fwd_s"] = round(fence_timed(fwd(dense), q, k, v), 5)
    row["flash_fwdbwd_s"] = round(fence_timed(fwdbwd(flash), q, k, v), 5)
    row["dense_fwdbwd_s"] = round(fence_timed(fwdbwd(dense), q, k, v), 5)
    row["fwd_speedup"] = round(row["dense_fwd_s"] / row["flash_fwd_s"], 3)
    row["fwdbwd_speedup"] = round(
        row["dense_fwdbwd_s"] / row["flash_fwdbwd_s"], 3)
    print(SENTINEL + json.dumps(row))


def tpu_main():
    from _dtf_watchdog import run_watchdogged

    rows, errs_all = [], []
    for t in (1024, 2048, 4096, 8192):
        env = dict(os.environ)
        env["DTF_ATTN_SEQ"] = str(t)
        row, errors = run_watchdogged(
            [sys.executable, os.path.abspath(__file__), "tpu", "--child"],
            lambda line: (json.loads(line[len(SENTINEL):])
                          if line.startswith(SENTINEL) else None),
            timeout_s=TPU_CHILD_TIMEOUT_S, retries=2, backoff_s=15, env=env)
        if row is None:
            errs_all.append({"seq": t, "errors": errors})
        else:
            rows.append(row)
        # incremental write: partial progress survives a later hang
        result = {"backend": "tpu", "rows": rows, "errors": errs_all}
        _merge_artifact("tpu", result)
        print(json.dumps(row if row is not None else errs_all[-1]))
    return 0 if rows else 1


if __name__ == "__main__":
    if "tpu" in sys.argv:
        if "--child" in sys.argv:
            tpu_child()
        else:
            sys.exit(tpu_main())
    else:
        from _dtf_env import cpu_sim_env, is_cpu_sim

        if (not is_cpu_sim(os.environ, 8)
                and os.environ.get("_DTF_ATTN_BENCH_REEXEC") != "1"):
            env = cpu_sim_env(8, os.environ)
            env["_DTF_ATTN_BENCH_REEXEC"] = "1"
            os.execve(sys.executable, [sys.executable] + sys.argv, env)
        cpu_main()
