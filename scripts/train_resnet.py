#!/usr/bin/env python
"""ResNet training: CIFAR-10 ResNet-20 (BASELINE config 2, the MWMS+NCCL row)
or ImageNet ResNet-50 (config 3, the north-star metric).

    python scripts/train_resnet.py --config=cifar    # ResNet-20
    python scripts/train_resnet.py --config=imagenet # ResNet-50

Same cluster flags as the reference scripts; the MultiWorkerMirroredStrategy
collective path is the same compiled mean-gradient all-reduce (SURVEY.md §3.5
maps MWMS 1:1 onto the psum path).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from absl import app, flags, logging as absl_logging

from dtf_tpu.cli import flags as dflags

dflags.define_cluster_flags()
dflags.define_mesh_flags()
dflags.define_train_flags(batch_size=256, learning_rate=0.1, train_steps=500,
                          lr_schedule="cosine")
flags.DEFINE_string("config", "cifar", "cifar (ResNet-20) | imagenet "
                    "(ResNet-50)")
flags.DEFINE_integer("eval_every", 0, "run a small eval sweep every N steps "
                     "(0 = final eval only)")
FLAGS = flags.FLAGS


def main(argv):
    del argv
    import jax
    import optax

    from dtf_tpu.checkpoint import Checkpointer
    from dtf_tpu.cli.launch import (emit_run_report, profiler_hooks, setup,
                                    telemetry_from_flags)
    from dtf_tpu.core import train as tr
    from dtf_tpu.data.synthetic import SyntheticData
    from dtf_tpu.core.comms import shard_batch
    from dtf_tpu.hooks import (CheckpointHook, EvalHook, LoggingHook,
                               PreemptionHook, StopAtStepHook)
    from dtf_tpu.loop import Trainer
    from dtf_tpu.metrics import MetricWriter
    from dtf_tpu.models import resnet

    mesh, info = setup(FLAGS)
    tel = telemetry_from_flags(FLAGS, info)

    if FLAGS.config == "cifar":
        model, shape, kind = resnet.resnet20(), (32, 32, 3), "cifar"
    else:
        model, shape, kind = resnet.resnet50(), (224, 224, 3), "imagenet"

    sched = dflags.make_lr_schedule(FLAGS)   # LoggingHook surfaces the LR
    # Recipe regularization is the classic 1e-4 L2 on kernels. When
    # --optimizer picks a decoupled-decay family the loss-side L2 is
    # dropped and the 1e-4 moves into --weight_decay (with a warning)
    # unless the user set one — resolve BEFORE make_optimizer so the
    # promoted default is actually consumed (cli/flags.resolve_loss_l2).
    loss_l2 = dflags.resolve_loss_l2(FLAGS, recipe_l2=1e-4)
    tx = dflags.make_optimizer(
        FLAGS, lambda s: optax.sgd(s, momentum=0.9, nesterov=True),
        recipe_uses_wd=True)   # consumed as loss-side L2 below
    state, shardings = tr.create_train_state(
        resnet.make_init(model, shape), tx, jax.random.PRNGKey(FLAGS.seed),
        mesh)
    step = tr.make_train_step(
        resnet.make_loss(model, weight_decay=loss_l2), tx, mesh,
        shardings, grad_accum=FLAGS.grad_accum, telemetry=tel)

    examples_per_step = model_flops = None
    if tel is not None:
        # throughput model: examples/step always; FLOPs only for the
        # ResNet-50 config, where the bench.py per-image constant applies
        from dtf_tpu.telemetry import RESNET50_TRAIN_FLOPS_PER_IMG

        examples_per_step = FLAGS.batch_size
        if kind == "imagenet":
            model_flops = RESNET50_TRAIN_FLOPS_PER_IMG * FLAGS.batch_size
        tel.set_throughput_model(tokens_per_step=examples_per_step,
                                 model_flops_per_step=model_flops,
                                 throughput_name="examples_per_sec")

    from dtf_tpu.data import formats

    data = formats.detect_image_data(
        FLAGS.data_dir, FLAGS.batch_size, seed=FLAGS.seed,
        host_index=info.process_id, host_count=info.num_processes)
    if data is None:
        if FLAGS.data_dir:
            absl_logging.warning("no images.npy/labels.npy or CIFAR .bin "
                                 "batches in %s; using synthetic data",
                                 FLAGS.data_dir)
        data = SyntheticData(kind, FLAGS.batch_size, seed=FLAGS.seed,
                             host_index=info.process_id,
                             host_count=info.num_processes)

    writer = MetricWriter(FLAGS.logdir if info.is_chief else None)
    ckpt = Checkpointer(os.path.join(FLAGS.logdir, "ckpt"),
                        save_interval_steps=FLAGS.checkpoint_every)
    eval_step = tr.make_eval_step(resnet.make_eval(model), mesh, shardings)
    using_real_data = not isinstance(data, SyntheticData)
    if using_real_data:
        # score on the matching held-out split; if the data_dir has none,
        # drop eval rather than report numbers from unrelated tensors.
        eval_data = formats.detect_image_eval_data(
            FLAGS.data_dir, FLAGS.batch_size, seed=FLAGS.seed,
            host_index=info.process_id, host_count=info.num_processes)
        if eval_data is None:
            absl_logging.warning(
                "no eval split (test_images.npy / test_batch.bin) in %s; "
                "skipping periodic eval", FLAGS.data_dir)
            batches_fn = None
        else:
            import itertools

            n_eval_batches = eval_data.batches_per_epoch_uniform()
            batches_fn = lambda: itertools.islice(  # noqa: E731
                iter(eval_data), n_eval_batches)
    else:
        eval_data = SyntheticData(kind, FLAGS.batch_size, seed=FLAGS.seed + 1,
                                  host_index=info.process_id,
                                  host_count=info.num_processes)
        batches_fn = lambda: (eval_data.batch(10_000_000 + i)  # noqa: E731
                              for i in range(4))
    eval_hook = None
    if batches_fn is not None:
        eval_hook = EvalHook(
            eval_step, batches_fn,
            writer, FLAGS.eval_every or FLAGS.train_steps,
            place_batch=lambda b: shard_batch(b, mesh))
    trainer = Trainer(
        step, mesh,
        hooks=[LoggingHook(writer, FLAGS.log_every, lr_schedule=sched,
                           tokens_per_step=examples_per_step,
                           model_flops_per_step=model_flops,
                           throughput_name="examples_per_sec",
                           telemetry=tel),
               CheckpointHook(ckpt, FLAGS.checkpoint_every),
               PreemptionHook(ckpt),
               *([eval_hook] if eval_hook else []),
               StopAtStepHook(FLAGS.train_steps),
               *profiler_hooks(FLAGS, telemetry=tel,
                               flops_per_step=model_flops)],
        checkpointer=ckpt,
        telemetry=tel,
        prefetch=FLAGS.prefetch_depth)
    state = trainer.fit(state, iter(data))
    emit_run_report(tel, info, extra={
        "launcher": "train_resnet", "config": FLAGS.config,
        "batch_size": FLAGS.batch_size, "mesh": dict(mesh.shape)})
    writer.close()
    ckpt.close()
    print(f"done: step={int(state.step)}")


if __name__ == "__main__":
    app.run(main)
