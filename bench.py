#!/usr/bin/env python
"""Headline benchmark: ResNet-50/ImageNet training throughput on one chip.

BASELINE.json's metric is "ImageNet ResNet-50 images/sec/chip" with a
north-star of step-time parity vs 8×A100 MultiWorkerMirroredStrategy+NCCL.
The reference publishes no measured numbers (BASELINE.json "published": {}),
so vs_baseline is computed against the A100 per-chip anchor implied by the
north star: 8×A100 MWMS ResNet-50 ≈ 2500 images/sec/GPU in mixed precision
(MLPerf-era TF numbers), i.e. parity ⇔ vs_baseline ≈ 1.0 on a per-chip basis.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import sys
import time

A100_PER_CHIP_IMG_S = 2500.0


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dtf_tpu.core import train as tr
    from dtf_tpu.core.comms import shard_batch
    from dtf_tpu.core.mesh import make_mesh
    from dtf_tpu.models import resnet

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    mesh = make_mesh()
    n_chips = mesh.devices.size

    model = resnet.resnet50()
    tx = optax.sgd(0.1, momentum=0.9)
    state, shardings = tr.create_train_state(
        resnet.make_init(model, (224, 224, 3)), tx, jax.random.PRNGKey(0),
        mesh)
    step = tr.make_train_step(resnet.make_loss(model), tx, mesh, shardings,
                              log_grad_norm=False)

    rng = np.random.default_rng(0)
    data = shard_batch(
        {"image": rng.random((batch, 224, 224, 3), np.float32),
         "label": rng.integers(0, 1000, (batch,)).astype(np.int32)}, mesh)

    # warmup (compile + 2 steps); fence via a value readback — on the
    # experimental axon plugin block_until_ready alone proved unreliable.
    for _ in range(3):
        state, metrics = step(state, data)
    float(metrics["loss"])

    n_steps = 20
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, data)
    float(metrics["loss"])  # the step chain is sequential: this syncs all
    dt = time.perf_counter() - t0

    img_s = batch * n_steps / dt
    img_s_chip = img_s / n_chips
    print(json.dumps({
        "metric": "resnet50_imagenet_train_images_per_sec_per_chip",
        "value": round(img_s_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s_chip / A100_PER_CHIP_IMG_S, 4),
    }))


if __name__ == "__main__":
    main()
