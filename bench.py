#!/usr/bin/env python
"""Headline benchmark: ResNet-50/ImageNet training throughput on one chip.

BASELINE.json's metric is "ImageNet ResNet-50 images/sec/chip" with a
north-star of ">=60% MFU, step-time parity vs 8xA100 MWMS+NCCL". The
reference publishes no measured numbers (BASELINE.json "published": {}), so
vs_baseline is computed against the A100 per-chip anchor implied by the
north star: 8xA100 MWMS ResNet-50 ~ 2500 images/sec/GPU in mixed precision
(MLPerf-era TF numbers), i.e. parity <=> vs_baseline ~ 1.0 per chip. MFU is
computed from first principles (see _MFU notes below) so the >=60% north
star is directly measurable.

Resilience contract (VERDICT r1 #2): the experimental `axon` PJRT backend
can hang during setup, so the measurement runs in a watchdogged subprocess
with retries; this parent NEVER imports jax. Whatever happens, stdout's
LAST line is exactly one JSON object:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": N}
or, on unrecoverable failure,
  {"metric": ..., "value": 0, "unit": ..., "vs_baseline": 0, "error": "..."}
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Anchor for vs_baseline — named source (VERDICT r2/r3 asked for one):
# NVIDIA's NGC "ResNet-50 v1.5 for TensorFlow" performance table reports
# ~2.4-2.6k images/sec per A100-80GB GPU in mixed precision (AMP+XLA,
# batch 256), i.e. ~20-21k img/s for the 8-GPU DGX A100 training row; the
# MXNet MLPerf-derived variant of the same model lands slightly higher.
# NVIDIA's MLPerf Training v1.x closed-division ResNet entries (DGX A100
# systems) imply the same per-GPU band once end-to-end epochs/minutes are
# converted to throughput. 2500 img/s/GPU is the midpoint of that band —
# the "8xA100 MWMS+NCCL step-time parity" target BASELINE.json names.
A100_PER_CHIP_IMG_S = 2500.0

# ResNet-50 v1.5 forward pass at 224x224 is ~4.09e9 MAC-derived FLOPs/image
# (2 FLOPs per MAC, the convention MLPerf/"How to Scale Your Model" use).
# Training = fwd + bwd ~ 3x forward.
RESNET50_TRAIN_FLOPS_PER_IMG = 3 * 4.09e9
# TPU v5e (v5 lite) peak bf16 matmul throughput per chip.
V5E_PEAK_BF16_FLOPS = 197e12

METRIC = "resnet50_imagenet_train_images_per_sec_per_chip"

# VERDICT r3 weak #1: the old 3 x 900 s retry pipeline could burn ~46 min
# against a dead backend — past the driver's own timeout, so the guaranteed
# last-line JSON never printed (BENCH_r03: rc=124, parsed=null). The harness
# now spends its time against a hard TOTAL budget: a cheap probe first
# (fast-fail ~3.5 min worst case), then ONE measurement attempt sized to
# what remains. A number or a structured error lands inside ~10 minutes no
# matter what the tunnel does.
TOTAL_BUDGET_S = float(os.environ.get("DTF_BENCH_BUDGET_S", "600"))
PROBE_TIMEOUT_S = 90
CHILD_TIMEOUT_S = 900        # cap; actual timeout = min(cap, budget left)


def child():
    """The actual measurement (runs in the watchdogged subprocess)."""
    import jax
    import numpy as np
    import optax

    from dtf_tpu.core import train as tr
    from dtf_tpu.core.comms import shard_batch
    from dtf_tpu.core.mesh import make_mesh
    from dtf_tpu.models import resnet

    t_child0 = time.perf_counter()
    batch = int(os.environ.get("DTF_BENCH_BATCH", "128"))
    mesh = make_mesh()
    n_chips = mesh.devices.size

    model = resnet.resnet50()
    tx = optax.sgd(0.1, momentum=0.9)
    state, shardings = tr.create_train_state(
        resnet.make_init(model, (224, 224, 3)), tx, jax.random.PRNGKey(0),
        mesh)
    step = tr.make_train_step(resnet.make_loss(model), tx, mesh, shardings,
                              log_grad_norm=False)

    rng = np.random.default_rng(0)
    data = shard_batch(
        {"image": rng.random((batch, 224, 224, 3), np.float32),
         "label": rng.integers(0, 1000, (batch,)).astype(np.int32)}, mesh)

    # warmup (compile + 2 steps); fence via a value readback — on the
    # experimental axon plugin block_until_ready alone proved unreliable.
    t_warm0 = time.perf_counter()
    for _ in range(3):
        state, metrics = step(state, data)
    float(metrics["loss"])
    warmup_s = time.perf_counter() - t_warm0

    n_steps = 20
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, data)
    float(metrics["loss"])  # the step chain is sequential: this syncs all
    dt = time.perf_counter() - t0

    img_s = batch * n_steps / dt
    img_s_chip = img_s / n_chips
    mfu = img_s_chip * RESNET50_TRAIN_FLOPS_PER_IMG / V5E_PEAK_BF16_FLOPS
    # goodput accounting (docs/OBSERVABILITY.md): productive = the timed
    # measurement loop; warmup (compile + settle) and state/data setup are
    # the overhead buckets of this process's wall clock so far.
    total_s = time.perf_counter() - t_child0
    out = {
        "metric": METRIC,
        "value": round(img_s_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s_chip / A100_PER_CHIP_IMG_S, 4),
        "mfu": round(mfu, 4),
        "backend": jax.default_backend(),
        "n_chips": n_chips,
        "goodput": round(dt / max(total_s, 1e-9), 4),
        "goodput_buckets": {
            "productive_s": round(dt, 3),
            "compile_warmup_s": round(warmup_s, 3),
            "setup_s": round(max(total_s - dt - warmup_s, 0.0), 3),
            "total_s": round(total_s, 3),
        },
    }
    # Roofline context (PERF.md §1): XLA's own FLOP/byte counts show this
    # model runs AT the v5e HBM-bandwidth roofline — mfu_xla and the
    # bandwidth utilisation say how close to the achievable ceiling we are.
    try:
        # aot-ok: roofline cost analysis of the bench step
        cost = step.lower(state, data).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        nbytes = float(cost.get("bytes accessed", 0.0))
        if flops:
            out["mfu_xla"] = round(
                flops * n_steps / dt / V5E_PEAK_BF16_FLOPS, 4)
        if nbytes:
            out["hbm_roofline_util"] = round(
                (nbytes * n_steps / dt) / 819e9, 4)
    except Exception:
        pass  # cost analysis is best-effort; headline fields stand alone
    print(json.dumps(out))


def _parse(line):
    # the result is the last stdout line that parses as our JSON
    try:
        result = json.loads(line)
    except (json.JSONDecodeError, ValueError):
        return None
    if isinstance(result, dict) and result.get("metric") == METRIC:
        return result
    return None


def main():
    from _dtf_watchdog import Budget, child_argv, probe_backend, \
        run_watchdogged

    if len(sys.argv) > 1 and sys.argv[1] != "--child":
        os.environ["DTF_BENCH_BATCH"] = sys.argv[1]
    budget = Budget(TOTAL_BUDGET_S)
    backend, probe_errors = probe_backend(
        timeout_s=min(PROBE_TIMEOUT_S, max(10.0, budget.remaining(10))),
        retries=2, backoff_s=10, env=dict(os.environ))
    if backend is None:
        result = {"metric": METRIC, "value": 0, "unit": "images/sec/chip",
                  "vs_baseline": 0,
                  "error": ("backend unavailable (probe failed): "
                            + "; ".join(probe_errors))[:2000]}
        _finalize(result)
        print(json.dumps(result))
        return 0
    # probe warmed the plugin; ONE measurement attempt in the time left
    result, errors = run_watchdogged(
        child_argv(os.path.abspath(__file__)), _parse,
        timeout_s=min(CHILD_TIMEOUT_S, max(60.0, budget.remaining(30))),
        retries=1, backoff_s=0, env=dict(os.environ))
    if result is None:
        result = {"metric": METRIC, "value": 0, "unit": "images/sec/chip",
                  "vs_baseline": 0,
                  "error": (f"probe OK (backend={backend}) but measurement "
                            "failed: " + "; ".join(errors))[:2000]}
    _finalize(result)
    print(json.dumps(result))
    return 0  # structured error on stdout IS the contract; rc 0 so it lands


def _finalize(result: dict) -> None:
    """Attach companion numbers — inline on a live run, or under an
    explicit ``banked_from_committed_artifacts`` key on a failed one.
    A failed headline must not present stale artifact numbers as THIS
    run's measurements, but the scoreboard line should still point at
    the committed on-chip evidence (measured in an earlier tunnel
    window; provenance in PERF.md §0b)."""
    if "error" not in result:
        _attach_companion_metrics(result)
        return
    banked: dict = {}
    _attach_companion_metrics(banked)
    if banked:
        result["banked_from_committed_artifacts"] = banked


def _attach_companion_metrics(result: dict) -> None:
    """Surface the transformer-side numbers in the one driver-recorded line.

    The headline metric is the BASELINE's ResNet-50 throughput, but the
    ≥60%-MFU north star is only physically reachable on matmul-dominated
    LM workloads (PERF.md §1) — so when scripts/bench_lm.py /
    bench_attention.py artifacts exist, their key numbers ride along.
    Best-effort: a missing/partial artifact attaches nothing.
    """
    root = os.path.dirname(os.path.abspath(__file__))

    def rows_of(name, *keys):
        """Best-effort artifact rows; ANY malformation yields [] — this
        helper must never be able to break the one-JSON-line contract."""
        try:
            with open(os.path.join(root, name)) as f:
                data = json.load(f)
            for key in keys:
                data = data.get(key, {}) if isinstance(data, dict) else {}
            return [r for r in data if isinstance(r, dict)] \
                if isinstance(data, list) else []
        except Exception:
            return []

    for row in rows_of("BENCH_LM.json", "rows"):
        if row.get("backend") != "tpu":
            continue  # CPU-sim tiny rows must not pose as TPU numbers
        name = row.get("model")
        if name in ("gpt", "bert") and "tokens_per_sec" in row:
            result[f"{name}_tokens_per_sec"] = row["tokens_per_sec"]
            if "mfu_analytic" in row:
                result[f"{name}_mfu"] = row["mfu_analytic"]
        elif name == "widedeep" and "examples_per_sec" in row:
            result["widedeep_examples_per_sec"] = row["examples_per_sec"]
    for row in rows_of("ATTN_BENCH.json", "tpu", "rows"):
        if row.get("seq") == 8192 and "fwd_speedup" in row:
            result["flash_vs_dense_fwd_8k"] = row["fwd_speedup"]
    tel_rows = [row for row in rows_of("TELEMETRY.json", "runs")
                if row.get("backend") == "tpu" and "error" not in row]
    if tel_rows:
        # newest-last history: BOTH companions come from the single last
        # on-chip run — mixing one run's mfu with another's goodput would
        # pose as one measurement; CPU-sim tiny reports excluded above
        row = tel_rows[-1]
        if row.get("mfu") is not None:
            result["train_telemetry_mfu"] = row["mfu"]
        g = row.get("goodput_buckets", {}).get("goodput")
        if g is not None:
            result["train_telemetry_goodput"] = g
    for row in rows_of("BENCH_LM.json", "decode", "rows"):
        if (row.get("backend") == "tpu"
                and row.get("decode_tokens_per_sec")
                # dispatch-latency junk guard (the axon block_until_ready
                # defect, PERF.md §0b): a real per-token step is >10 µs
                and row.get("ms_per_step", 0) > 0.01):
            tag = ("gqa" if row.get("kv_heads", 0) < row.get("heads", 0)
                   else "mha")
            if row.get("window"):
                tag += "_rolling"
            result[f"decode_{tag}_tokens_per_sec"] = \
                row["decode_tokens_per_sec"]


if __name__ == "__main__":
    if "--child" in sys.argv:
        child()
    else:
        sys.exit(main())
