"""Drive the analyzer passes over registry configs (shared by CLI and tests)."""

from __future__ import annotations

import os
from typing import Sequence

from dtf_tpu.analysis import collective as collective_pass
from dtf_tpu.analysis import configs as cfgs
from dtf_tpu.analysis import hlo as hlo_pass
from dtf_tpu.analysis import host as host_pass
from dtf_tpu.analysis import jaxpr as jaxpr_pass
from dtf_tpu.analysis import memory as memory_pass
from dtf_tpu.analysis import specs as specs_pass
from dtf_tpu.analysis.findings import Finding

GOLDEN_BASENAME = "STATIC_ANALYSIS.json"

#: every pass the runner knows, in execution order.  "host" is
#: config-independent (AST lint over the jax-free control plane); "hlo"
#: and "memory" share one AOT compile per config (compile_program).
ALL_PASSES = ("host", "specs", "jaxpr", "collective", "hlo", "memory")


def golden_path() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, GOLDEN_BASENAME)


def run_specs(config: cfgs.AnalysisConfig) -> list[Finding]:
    """Rulebook + ZeRO-1 lints at real scale (eval_shape only)."""
    mesh = config.mesh()
    view = config.spec_view(mesh)
    findings = specs_pass.lint_rules(
        view.params, view.rules, dict(mesh.shape), config=config.name,
        allow_dead=config.allow_dead, replicated_ok=config.replicated_ok)
    for opt_name, make_tx in cfgs.OPTIMIZER_FAMILIES.items():
        findings += specs_pass.lint_opt_specs(
            make_tx(), view.params, view.rules, mesh, config=config.name,
            opt_name=opt_name, zero1=view.zero1)
        findings += specs_pass.lint_opt_specs(
            make_tx(), view.params, view.rules, mesh, config=config.name,
            opt_name=opt_name, zero1=False)
    return findings


def run_jaxpr(config: cfgs.AnalysisConfig, view=None) -> list[Finding]:
    """Trace-level lints on the tiny train step (no compile)."""
    view = view or config.step_view(config.mesh())
    closed = jaxpr_pass.trace_step(view.step, view.state, view.batch)
    return jaxpr_pass.lint_jaxpr(closed, config=config.name)


def run_collective(config: cfgs.AnalysisConfig, view=None) -> list[Finding]:
    """Collective soundness over the step's shard_map bodies (no compile).

    Per-config only — the config-independent mirrored-ring fence is
    :func:`dtf_tpu.analysis.collective.ring_soundness`, run once per
    :func:`analyze` invocation rather than once per config.
    """
    view = view or config.step_view(config.mesh())
    closed = jaxpr_pass.trace_step(view.step, view.state, view.batch)
    return collective_pass.lint_collectives(closed, config=config.name)


def compile_program(config: cfgs.AnalysisConfig, view=None):
    """AOT-compile a config's program once for every compiled-side pass.

    Returns ``(view, lowered, compiled)`` — the hlo pass reads the
    optimized text, the memory pass additionally needs the lowering's
    ``args_info`` (donation flags) and the executable's committed input
    shardings.
    """
    view = view or config.step_view(config.mesh())
    # the analyzer compiles registered step views for fencing — this
    # aot-ok: IS the consumer the executor registers abstracts for
    lowered = view.step.lower(view.state, view.batch)
    return view, lowered, lowered.compile()  # aot-ok: compile leg


def compile_budget(config: cfgs.AnalysisConfig, view=None) -> dict:
    """AOT-compile the tiny train step and extract its comms budget."""
    _, _, compiled = compile_program(config, view)
    return hlo_pass.comms_budget(compiled)


def run_hlo(config: cfgs.AnalysisConfig, golden: dict,
            view=None, budget: dict | None = None) -> list[Finding]:
    if budget is None:
        budget = compile_budget(config, view)
    want = golden.get("budgets", {}).get(config.name)
    if want is None:
        return [Finding(config.name, "hlo", "missing-golden", "error",
                        f"no golden comms budget for {config.name!r}; "
                        f"run `python -m dtf_tpu.analysis --write-golden`")]
    return hlo_pass.check_budget(budget, want, config=config.name)


def run_memory(config: cfgs.AnalysisConfig, golden: dict,
               view=None, lowered=None, compiled=None,
               budget: dict | None = None) -> list[Finding]:
    """The memory pass for one config: breakdown fence vs golden +
    resident-state accounting cross-check + donation soundness/gate.
    Shares ``compile_program``'s artifacts with the hlo pass when the
    caller provides them."""
    if compiled is None:
        view, lowered, compiled = compile_program(config, view)
    want = golden.get("budgets", {}).get(config.name)
    return memory_pass.lint_program(config, view, lowered, compiled,
                                    want, budget)


def analyze(names: Sequence[str] | None = None,
            passes: Sequence[str] = ALL_PASSES,
            golden: dict | None = None,
            budgets_out: dict | None = None) -> list[Finding]:
    """Run the requested passes over the requested configs.

    ``budgets_out``: pass a dict to receive each analyzed config's compiled
    comms budget (the CLI reports the per-config collective-bytes delta vs
    golden from it, so a PR's comms cost is visible in the JSON line).
    """
    selected = (cfgs.REGISTRY if not names
                else tuple(cfgs.BY_NAME[n] for n in names))
    if {"hlo", "memory"} & set(passes) and golden is None:
        path = golden_path()
        golden = (hlo_pass.load_golden(path) if os.path.exists(path)
                  else {"budgets": {}})
    findings: list[Finding] = []
    if "host" in passes:
        # config-independent: race/lock/signal/atomic-write/clock lints
        # over the jax-free control plane (serve/fault/telemetry/stream/
        # publish) — pure AST, no trace or compile.
        findings += host_pass.lint_host()
    if "collective" in passes:
        # config-independent: the mirrored-ring fence over every
        # registered custom_vjp ring pair (ops/collective_matmul).
        findings += collective_pass.ring_soundness()
    for config in selected:
        if "specs" in passes:
            findings += run_specs(config)
        # the step view (mesh + full train-step construction) is the
        # expensive part — build it once and share across all trace/
        # compile passes; jaxpr + collective also share the one trace,
        # hlo + memory the one AOT compile
        view = (config.step_view(config.mesh())
                if {"jaxpr", "collective", "hlo", "memory"} & set(passes)
                else None)
        if {"jaxpr", "collective"} & set(passes):
            closed = jaxpr_pass.trace_step(view.step, view.state,
                                           view.batch)
            if "jaxpr" in passes:
                findings += jaxpr_pass.lint_jaxpr(closed,
                                                  config=config.name)
            if "collective" in passes:
                findings += collective_pass.lint_collectives(
                    closed, config=config.name)
        if {"hlo", "memory"} & set(passes):
            view, lowered, compiled = compile_program(config, view)
            budget = hlo_pass.comms_budget(compiled)
            if budgets_out is not None:
                budgets_out[config.name] = budget
            if "hlo" in passes:
                findings += run_hlo(config, golden, view, budget=budget)
            if "memory" in passes:
                findings += run_memory(config, golden, view, lowered,
                                       compiled, budget=budget)
    return findings
