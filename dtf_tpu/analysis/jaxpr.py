"""Trace-level lints over the train step's jaxpr (no compile, no device).

``jax.make_jaxpr`` on the (jitted) step with abstract inputs costs one
trace — seconds even for the flagship — and exposes failure classes the
type system doesn't:

- ``float64-leak``      — a wide dtype in the step (a stray numpy f64
  scalar upcasting a whole tree; only bites when x64 is enabled, which is
  exactly when nobody is looking at dtypes).
- ``host-callback``     — ``pure_callback``/``io_callback``/``debug``
  callbacks inside the compiled step: a device→host sync per step, the
  kind of "why is MFU 12%?" regression that static analysis catches for
  free.
- ``collective-outside-shard-map`` — ``psum``/``all_gather``/axis-index
  primitives bound outside any ``shard_map`` scope (e.g. under a stray
  ``vmap(axis_name=...)``): they compile, but against whatever axis
  happens to be in scope — never what the mesh intended.

The walker recurses through every higher-order primitive (pjit, scan,
while, cond, custom_vjp, remat) — including ``shard_map`` bodies, where
the f64/callback lints still apply — and tracks whether the current
sub-jaxpr is inside a ``shard_map``, which only suppresses the
axis-collective lint (collectives there are the whole point).
"""

from __future__ import annotations

from typing import Any, Callable

import jax

from dtf_tpu.analysis.findings import Finding

#: primitives legal only inside shard_map (axis-env consumers).
AXIS_PRIMS = frozenset({
    "psum", "pmean", "pmax", "pmin", "ppermute", "pbroadcast", "pgather",
    "all_gather", "all_to_all", "psum_scatter", "reduce_scatter",
    "axis_index",
})

#: primitive-name fragments that mean "host round-trip inside the step".
CALLBACK_FRAGMENTS = ("callback", "outside_call", "infeed", "outfeed")

#: dtypes that should never appear in a TPU train step.
WIDE_DTYPES = ("float64", "complex128")

#: primitives whose sub-jaxprs run under a bound mesh-axis scope: the walk
#: DOES descend (f64/callback lints apply inside), but marks the subtree
#: as inside shard_map so the axis-collective lint stays quiet there.
_SHARD_MAP_PRIMS = frozenset({"shard_map"})


def _sub_jaxprs(eqn):
    """Yield every closed/open jaxpr hiding in an eqn's params."""
    for val in eqn.params.values():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            if isinstance(v, jax.core.ClosedJaxpr):
                yield v.jaxpr
            elif isinstance(v, jax.core.Jaxpr):
                yield v


def _walk(jaxpr, visit: Callable, *, inside_shard_map: bool) -> None:
    for eqn in jaxpr.eqns:
        visit(eqn, inside_shard_map)
        name = eqn.primitive.name
        inner = inside_shard_map or name in _SHARD_MAP_PRIMS
        for sub in _sub_jaxprs(eqn):
            _walk(sub, visit, inside_shard_map=inner)


def lint_jaxpr(closed_jaxpr, *, config: str) -> list[Finding]:
    """All trace-level lints over one closed jaxpr."""
    findings: list[Finding] = []
    seen: set[tuple[str, str]] = set()   # (check, key) de-dup

    def add(check: str, key: str, detail: str):
        if (check, key) in seen:
            return
        seen.add((check, key))
        findings.append(Finding(config, "jaxpr", check, "error", detail))

    def visit(eqn, inside_shard_map: bool):
        name = eqn.primitive.name
        if any(frag in name for frag in CALLBACK_FRAGMENTS):
            add("host-callback", name,
                f"host callback primitive {name!r} inside the step "
                f"(device->host sync every step)")
        if name in AXIS_PRIMS and not inside_shard_map:
            axes = eqn.params.get("axes",
                                  eqn.params.get("axis_name", "?"))
            add("collective-outside-shard-map", f"{name}:{axes}",
                f"{name} over {axes!r} bound outside any shard_map")
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            dtype = str(getattr(aval, "dtype", ""))
            if dtype in WIDE_DTYPES:
                add("float64-leak", f"{name}:{dtype}",
                    f"{name} produces {dtype} "
                    f"{getattr(aval, 'shape', ())} inside the step")

    _walk(closed_jaxpr.jaxpr, visit, inside_shard_map=False)
    return findings


def trace_step(step_fn: Callable, *abstract_args: Any):
    """``make_jaxpr`` helper: trace the (possibly jitted) step on
    ShapeDtypeStructs only — no device buffers, no compile."""
    return jax.make_jaxpr(step_fn)(*abstract_args)
