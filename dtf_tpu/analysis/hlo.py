"""Comms budget from AOT-compiled HLO — the regression fence for
XLA-inserted collectives.

The train step is lowered and compiled on the 8-device CPU sim
(``step.lower(abstract_state, abstract_batch).compile()``); the optimized
HLO text then names every collective GSPMD inserted — the all-reduce of
the gradient mean, the reduce-scatter/all-gather pair of ZeRO-1, TP's
activation all-reduces, the pipeline's collective-permutes.  That mix IS
the framework's communication contract: an accidental resharding (a spec
change that makes XLA all-gather a weight every step) shows up here as a
count/byte diff against the committed golden (``STATIC_ANALYSIS.json``)
long before a chip ever runs it.

Parsing is textual on purpose: opcode spellings (``all-reduce``,
``all-gather``, ``reduce-scatter``, ``collective-permute``,
``all-to-all``, plus their async ``-start`` forms) are stable across XLA
versions, and byte sizes fall out of the result shapes.
"""

from __future__ import annotations

import json
import re
from typing import Any, Mapping

from dtf_tpu.analysis.findings import Finding

COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

#: bits per element — BITS, not bytes, so the packed sub-byte dtypes
#: (s4/u4) and the fp8 family count instead of silently contributing 0 B
#: to the fence (an int8-KV or fp8 collective that the byte fence cannot
#: see is a fence with a hole in it).
_DTYPE_BITS = {
    "pred": 8, "s2": 2, "u2": 2, "s4": 4, "u4": 4, "s8": 8, "u8": 8,
    "s16": 16, "u16": 16, "f16": 16, "bf16": 16,
    "s32": 32, "u32": 32, "f32": 32, "s64": 64, "u64": 64, "f64": 64,
    "c64": 64, "c128": 128,
    "f8e4m3": 8, "f8e4m3fn": 8, "f8e4m3b11fnuz": 8, "f8e4m3fnuz": 8,
    "f8e5m2": 8, "f8e5m2fnuz": 8, "f8e3m4": 8, "f8e8m0fnu": 8,
    "f4e2m1fn": 4,
}

#: HLO types that genuinely carry no payload in a collective result.
_TOKEN_DTYPES = frozenset({"token", "opaque"})

#: `lhs = <type> <opcode>(...)`; async `-start` counted, `-done` skipped
#: (same transfer), fused/computation names can't match: the opcode slot
#: sits right after the result type.
_COLLECTIVE_RE = re.compile(
    r"=\s+(?P<type>\([^=]*?\)|\S+)\s+"
    r"(?P<op>" + "|".join(re.escape(o) for o in COLLECTIVE_OPS) + r")"
    r"(?P<async>-start)?\(")

#: dtype tokens are alphanumeric runs (f8e4m3fn, s4, bf16 — not just
#: letters+digits: the fp8 family interleaves them).
_SHAPE_RE = re.compile(r"(?P<dtype>[a-z][a-z0-9]*)\[(?P<dims>[0-9,]*)\]")


def _shape_bytes(type_str: str) -> tuple[int, set[str]]:
    """(total bytes, unknown dtypes) of every array shape in an HLO
    result type string.

    An unrecognized non-token dtype is NOT silently skipped: it would
    count 0 bytes and quietly hole the byte fence, so it is surfaced to
    the caller and becomes an ``unknown-dtype`` finding in
    :func:`check_budget`.
    """
    total = 0
    unknown: set[str] = set()
    for m in _SHAPE_RE.finditer(type_str):
        dtype = m.group("dtype")
        bits = _DTYPE_BITS.get(dtype)
        if bits is None:
            if dtype not in _TOKEN_DTYPES:
                unknown.add(dtype)
            continue
        n = 1
        for d in m.group("dims").split(","):
            if d:
                n *= int(d)
        total += (n * bits + 7) // 8
    return total, unknown


def collective_stats(hlo_text: str) -> dict:
    """Per-opcode ``{count, bytes}`` plus totals, from optimized HLO text.

    ``bytes`` is the per-device result payload of each collective (the
    resharding volume a step moves over the interconnect, up to reduction
    fan-in), summed over call sites. Collective results whose dtype the
    byte table does not know are listed under ``unknown_dtypes`` (present
    only when non-empty) — :func:`check_budget` turns that into a
    fail-closed finding rather than counting them as 0 bytes.
    """
    stats = {op: {"count": 0, "bytes": 0} for op in COLLECTIVE_OPS}
    unknown: set[str] = set()
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        op = m.group("op")
        nbytes, unk = _shape_bytes(m.group("type"))
        stats[op]["count"] += 1
        stats[op]["bytes"] += nbytes
        unknown |= unk
    stats["total"] = {
        "count": sum(stats[op]["count"] for op in COLLECTIVE_OPS),
        "bytes": sum(stats[op]["bytes"] for op in COLLECTIVE_OPS),
    }
    if unknown:
        stats["unknown_dtypes"] = sorted(unknown)
    return stats


def comms_budget(compiled) -> dict:
    """Budget dict for one compiled step (``lowered.compile()`` result).

    Besides the per-opcode collective stats, records the program's full
    HBM breakdown from ``memory_analysis()`` — argument/output/peak-temp/
    alias/generated-code bytes (``analysis/memory.MEMORY_FIELDS``), where
    grad-accum accumulators, activation stashes, collective staging
    buffers AND the resident state itself live.  The memory pass
    (:func:`dtf_tpu.analysis.memory.check_memory`) fences every field
    against the golden, so an accumulator-HBM regression (e.g. a
    ``--grad_shard`` config silently falling back to the replicated f32
    accumulator) or a state leaf going replicated fails tier-1 just like
    an extra all-gather does.
    """
    from dtf_tpu.analysis import memory as memory_pass

    text = compiled.as_text()
    budget = collective_stats(text)
    mem = memory_pass.memory_breakdown(compiled)
    if mem is not None:
        budget["memory"] = mem
    # source attribution per collective call site (analysis/provenance.py)
    # — recorded in the golden but never fenced on its own: it names the
    # offending line when the opcode fence above trips, and feeds --diff.
    from dtf_tpu.analysis import provenance

    budget["provenance"] = provenance.collective_provenance(text)
    return budget


def check_budget(budget: Mapping[str, Any], golden: Mapping[str, Any],
                 *, config: str) -> list[Finding]:
    """Exact count fence + byte fence against the committed golden.

    Counts must match exactly — one extra all-gather is precisely the
    regression this pass exists to catch.  Bytes must match exactly too
    (shapes are deterministic for a pinned jax/XLA); regenerate the golden
    via ``python -m dtf_tpu.analysis --write-golden`` when a change is
    intentional, and justify the diff in the PR.

    The budget's ``memory`` breakdown is fenced by the memory pass
    (:func:`dtf_tpu.analysis.memory.check_memory`), not here — this
    fence owns the collectives only.
    """
    from dtf_tpu.analysis import provenance

    findings = []
    got_prov = budget.get("provenance")
    want_prov = golden.get("provenance")
    if budget.get("unknown_dtypes"):
        # fail CLOSED: a collective whose dtype the byte table can't size
        # was counted as 0 B — the byte fence has a hole until the table
        # learns the dtype (_DTYPE_BITS).
        findings.append(Finding(
            config, "hlo", "unknown-dtype", "error",
            f"collective result dtype(s) {budget['unknown_dtypes']} not in "
            f"the byte table — counted as 0 B; teach _DTYPE_BITS the "
            f"dtype so the byte fence covers it"))
    for op in COLLECTIVE_OPS + ("total",):
        got = budget.get(op, {"count": 0, "bytes": 0})
        want = golden.get(op, {"count": 0, "bytes": 0})
        # total-row drift repeats the per-op rows; per-line attribution
        # only makes sense per opcode
        where = ("" if op == "total" else
                 provenance.attribute_drift(op, got_prov, want_prov))
        if got["count"] != want["count"]:
            findings.append(Finding(
                config, "hlo", "collective-count-drift", "error",
                f"{op}: {got['count']} in compiled step vs {want['count']} "
                f"in golden (regenerate with --write-golden if intended)"
                f"{where}"))
        elif got["bytes"] != want["bytes"]:
            findings.append(Finding(
                config, "hlo", "collective-bytes-drift", "error",
                f"{op}: {got['bytes']:,} B vs {want['bytes']:,} B golden "
                f"(count unchanged — shapes/dtypes moved){where}"))
    return findings


def load_golden(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def save_golden(path: str, budgets: Mapping[str, Any], *, meta: dict) -> None:
    doc = {"_meta": meta, "budgets": dict(sorted(budgets.items()))}
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
