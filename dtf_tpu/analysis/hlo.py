"""Comms budget from AOT-compiled HLO — the regression fence for
XLA-inserted collectives.

The train step is lowered and compiled on the 8-device CPU sim
(``step.lower(abstract_state, abstract_batch).compile()``); the optimized
HLO text then names every collective GSPMD inserted — the all-reduce of
the gradient mean, the reduce-scatter/all-gather pair of ZeRO-1, TP's
activation all-reduces, the pipeline's collective-permutes.  That mix IS
the framework's communication contract: an accidental resharding (a spec
change that makes XLA all-gather a weight every step) shows up here as a
count/byte diff against the committed golden (``STATIC_ANALYSIS.json``)
long before a chip ever runs it.

Parsing is textual on purpose: opcode spellings (``all-reduce``,
``all-gather``, ``reduce-scatter``, ``collective-permute``,
``all-to-all``, plus their async ``-start`` forms) are stable across XLA
versions, and byte sizes fall out of the result shapes.
"""

from __future__ import annotations

import json
import re
from typing import Any, Mapping

from dtf_tpu.analysis.findings import Finding

COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

#: `lhs = <type> <opcode>(...)`; async `-start` counted, `-done` skipped
#: (same transfer), fused/computation names can't match: the opcode slot
#: sits right after the result type.
_COLLECTIVE_RE = re.compile(
    r"=\s+(?P<type>\([^=]*?\)|\S+)\s+"
    r"(?P<op>" + "|".join(re.escape(o) for o in COLLECTIVE_OPS) + r")"
    r"(?P<async>-start)?\(")

_SHAPE_RE = re.compile(r"(?P<dtype>[a-z]+\d*)\[(?P<dims>[0-9,]*)\]")


def _shape_bytes(type_str: str) -> int:
    """Total bytes of every array shape in an HLO result type string."""
    total = 0
    for m in _SHAPE_RE.finditer(type_str):
        nbytes = _DTYPE_BYTES.get(m.group("dtype"))
        if nbytes is None:
            continue   # token[] / opaque[] etc. carry no payload
        n = 1
        for d in m.group("dims").split(","):
            if d:
                n *= int(d)
        total += n * nbytes
    return total


def collective_stats(hlo_text: str) -> dict:
    """Per-opcode ``{count, bytes}`` plus totals, from optimized HLO text.

    ``bytes`` is the per-device result payload of each collective (the
    resharding volume a step moves over the interconnect, up to reduction
    fan-in), summed over call sites.
    """
    stats = {op: {"count": 0, "bytes": 0} for op in COLLECTIVE_OPS}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        op = m.group("op")
        stats[op]["count"] += 1
        stats[op]["bytes"] += _shape_bytes(m.group("type"))
    stats["total"] = {
        "count": sum(stats[op]["count"] for op in COLLECTIVE_OPS),
        "bytes": sum(stats[op]["bytes"] for op in COLLECTIVE_OPS),
    }
    return stats


def comms_budget(compiled) -> dict:
    """Budget dict for one compiled step (``lowered.compile()`` result).

    Besides the per-opcode collective stats, records the step's peak temp
    allocation (``memory_analysis().temp_size_in_bytes`` — where grad-accum
    accumulators, activation stashes and collective staging buffers live),
    so an accumulator-HBM regression (e.g. a ``--grad_shard`` config
    silently falling back to the replicated f32 accumulator) fails the
    fence in tier-1 just like an extra all-gather does.
    """
    budget = collective_stats(compiled.as_text())
    try:
        mem = compiled.memory_analysis()
        budget["memory"] = {"temp_bytes": int(mem.temp_size_in_bytes)}
    except Exception:  # noqa: BLE001 — backends without an allocator report
        pass
    return budget


def check_budget(budget: Mapping[str, Any], golden: Mapping[str, Any],
                 *, config: str) -> list[Finding]:
    """Exact count fence + byte fence against the committed golden.

    Counts must match exactly — one extra all-gather is precisely the
    regression this pass exists to catch.  Bytes must match exactly too
    (shapes are deterministic for a pinned jax/XLA); regenerate the golden
    via ``python -m dtf_tpu.analysis --write-golden`` when a change is
    intentional, and justify the diff in the PR.
    """
    findings = []
    for op in COLLECTIVE_OPS + ("total",):
        got = budget.get(op, {"count": 0, "bytes": 0})
        want = golden.get(op, {"count": 0, "bytes": 0})
        if got["count"] != want["count"]:
            findings.append(Finding(
                config, "hlo", "collective-count-drift", "error",
                f"{op}: {got['count']} in compiled step vs {want['count']} "
                f"in golden (regenerate with --write-golden if intended)"))
        elif got["bytes"] != want["bytes"]:
            findings.append(Finding(
                config, "hlo", "collective-bytes-drift", "error",
                f"{op}: {got['bytes']:,} B vs {want['bytes']:,} B golden "
                f"(count unchanged — shapes/dtypes moved)"))
    want_mem = golden.get("memory")
    got_mem = budget.get("memory")
    if want_mem is not None and got_mem is None:
        # fail CLOSED: a backend that stops reporting memory_analysis()
        # must not silently disable the accumulator-HBM fence (and a
        # subsequent --write-golden would silently drop the 'memory'
        # entries) — surface it as a finding instead.
        findings.append(Finding(
            config, "hlo", "temp-bytes-unavailable", "error",
            "golden pins a peak-temp budget but memory_analysis() "
            "reported nothing on this backend — the accumulator-HBM "
            "fence did not run"))
    elif want_mem is not None and (
            got_mem["temp_bytes"] != want_mem["temp_bytes"]):
        findings.append(Finding(
            config, "hlo", "temp-bytes-drift", "error",
            f"peak temp allocation {got_mem['temp_bytes']:,} B vs "
            f"{want_mem['temp_bytes']:,} B golden (accumulators / stashes "
            f"/ staging buffers moved; regenerate with --write-golden if "
            f"intended)"))
    return findings


def load_golden(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def save_golden(path: str, budgets: Mapping[str, Any], *, meta: dict) -> None:
    doc = {"_meta": meta, "budgets": dict(sorted(budgets.items()))}
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
