"""Host-plane soundness pass — race/lock/signal/atomic-write/clock lints.

The analyzer fences every device-side dimension (sharding rules,
collective soundness, HBM, comms budgets); this pass fences the jax-free
CONTROL PLANE the resilience/serving PRs built — serve health/router,
fault controller, flight recorder, publish watcher, stream producer —
which is thread-heavy, signal-handling, and determinism-critical. Every
defect in it so far was caught by hand review (the FlightRecorder SIGTERM
self-deadlock, publish tmp+rename atomicity); these lints make those
review findings fail-closed.

Fenced scope: ``dtf_tpu/serve/``, ``dtf_tpu/fault/``,
``dtf_tpu/telemetry/``, ``dtf_tpu/data/stream/``, ``dtf_tpu/publish.py``.
AST-only over :mod:`dtf_tpu.analysis.hostmodel`'s class/thread/lock model
(no imports executed, no compiles — the pass is tier-1 cheap).

Finding classes (all ``severity=error``; file:line provenance like the
collective pass):

- ``unguarded-shared-state`` — an attribute written WITHOUT the owning
  lock held, in a class that runs a ``threading.Thread`` target, where
  the attribute is touched from both the thread side (the target's
  in-class call closure) and the non-thread side. Guard every access
  with the class lock, or pin a deliberate lock-free publish-once site
  with ``# lock-ok: <why>`` (atomic reference assignment under the GIL
  is the one sanctioned lock-free pattern).
- ``signal-handler-deadlock`` — a plain ``threading.Lock`` acquirable
  from a registered signal handler's call graph (cross-class through
  typed attributes: ``self.flight = FlightRecorder(...)`` then
  ``self.flight.dump()``). A signal lands between bytecodes on the main
  thread; if the main thread holds the lock, the handler self-deadlocks
  and the process goes SIGTERM-immune (the PR 5 FlightRecorder class).
  Must be an ``RLock``; no pin — this one is fail-closed.
- ``non-atomic-publish`` — a raw write-mode ``open()`` or bare
  ``os.rename``/``os.replace``/``shutil.move`` outside the one choke
  point :mod:`dtf_tpu._hostio` (``atomic_replace``/``append_line`` — the
  ``ring_perm`` idiom: one constructor, lint everything else). A reader
  racing a raw write sees a torn file. Deliberate raw IO (fault
  injection's damage verbs) pins with ``# io-ok: <why>``.
- ``clock-escape`` — a direct ``time.*()``/``random.*``/``os.urandom``/
  global-state ``np.random`` call in modules whose contracts are
  injectable clocks and counter-based rng. A raw call breaks
  injectable-clock tests and bitwise replay. The sanctioned spellings:
  a ``time.X`` as a keyword-parameter DEFAULT (``clock=time.monotonic``
  — the injection point itself), seeded ``np.random.default_rng(seed)``
  / ``np.random.SeedSequence([...])``, and ``# clock-ok: <why>`` pins
  for genuinely wall-clock sites.

docs/ANALYSIS.md §"Host-plane pass" documents the registry and pins.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Sequence

from dtf_tpu.analysis import hostmodel
from dtf_tpu.analysis.findings import Finding

PASS = "host"

#: the one sanctioned write choke point (module basename is exempt).
CHOKE_POINT = "dtf_tpu._hostio"

PIN_CLOCK = "# clock-ok:"
PIN_LOCK = "# lock-ok:"
PIN_IO = "# io-ok:"

#: wall-clock/rng spellings fenced when CALLED directly.
_TIME_FNS = {"time", "monotonic", "perf_counter", "sleep", "time_ns",
             "monotonic_ns", "perf_counter_ns", "process_time",
             "process_time_ns"}

#: np.random constructors that are counter-/seed-based WHEN given args.
_NP_SEEDED_CTORS = {"default_rng", "SeedSequence", "Generator", "PCG64",
                    "Philox", "SFC64", "MT19937"}

#: fenced package paths under the dtf_tpu package root, plus publish.py.
_FENCED_DIRS = (("serve",), ("fault",), ("telemetry",), ("data", "stream"))
_FENCED_FILES = ("publish.py",)


def package_root() -> str:
    """The ``dtf_tpu`` package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fenced_files(root: Optional[str] = None) -> List[str]:
    """Every file the host pass fences, in deterministic order."""
    root = root or package_root()
    files: List[str] = []
    for parts in _FENCED_DIRS:
        d = os.path.join(root, *parts)
        for r, ds, fs in os.walk(d):
            ds[:] = [x for x in ds if x != "__pycache__"]
            for f in sorted(fs):
                if f.endswith(".py"):
                    files.append(os.path.join(r, f))
    for name in _FENCED_FILES:
        p = os.path.join(root, name)
        if os.path.exists(p):
            files.append(p)
    return files


def _rel(path: str) -> str:
    """Display path: repo-relative when under the repo, else as given."""
    repo = os.path.dirname(package_root())
    rel = os.path.relpath(os.path.abspath(path), repo)
    return path if rel.startswith("..") else rel


def _finding(check: str, path: str, lineno: int, msg: str) -> Finding:
    return Finding("", PASS, check, "error", f"{_rel(path)}:{lineno}: {msg}")


# --------------------------------------------------------- lock discipline

def _lint_shared_state(mod: hostmodel.ModuleModel) -> List[Finding]:
    pins = mod.pin_lines(PIN_LOCK)
    out: List[Finding] = []
    for cls in mod.classes:
        if not cls.thread_targets:
            continue
        thread_funcs = cls.reachable(cls.thread_targets)
        attrs = sorted({a.attr for a in cls.accesses})
        for attr in attrs:
            if attr in cls.locks or attr in cls.threadsafe:
                continue
            acc = [a for a in cls.accesses
                   if a.attr == attr
                   and a.func.split(".")[0] != "__init__"
                   and a.lineno not in pins]
            t_side = [a for a in acc if a.func in thread_funcs]
            m_side = [a for a in acc if a.func not in thread_funcs]
            if not t_side or not m_side:
                continue        # single-side ownership needs no lock
            unguarded_writes = [a for a in acc if a.write and not a.guarded]
            if not unguarded_writes:
                continue
            w = min(unguarded_writes, key=lambda a: a.lineno)
            target = ", ".join(sorted(cls.thread_targets))
            out.append(_finding(
                "unguarded-shared-state", mod.path, w.lineno,
                f"{cls.name}.{attr} is written without the owning lock "
                f"(e.g. in {w.func}) but is shared between the "
                f"{target!r} thread side and other methods — guard every "
                f"access with the class lock, or pin a deliberate "
                f"publish-once site with '{PIN_LOCK} <why>'"))
    return out


def _lint_signal_locks(mod: hostmodel.ModuleModel,
                       by_name: dict) -> List[Finding]:
    out: List[Finding] = []
    for cls in mod.classes:
        for handler in sorted(cls.signal_handlers):
            # walk the handler's call closure, following typed-attribute
            # calls into other modeled classes (visited set bounds it)
            todo = [(cls, handler)]
            visited = set()
            while todo:
                owner, entry = todo.pop()
                if (owner.name, entry) in visited:
                    continue
                visited.add((owner.name, entry))
                for f in owner.reachable({entry}):
                    for lock, lineno in owner.acquires.get(f, []):
                        if owner.locks.get(lock) != "Lock":
                            continue
                        out.append(_finding(
                            "signal-handler-deadlock", owner.path, lineno,
                            f"signal handler {cls.name}.{handler} can "
                            f"acquire plain Lock {owner.name}.{lock} — a "
                            f"signal landing while this thread holds it "
                            f"self-deadlocks the handler (the process "
                            f"goes SIGTERM-immune); use "
                            f"threading.RLock() (the FlightRecorder "
                            f"postmortem class)"))
                    for attr, meth in owner.cross_calls.get(f, ()):
                        other = by_name.get(owner.attr_types.get(attr, ""))
                        if other is not None:
                            todo.append((other, meth))
    return out


# ------------------------------------------------------- atomic-write lint

def _is_name(node: ast.AST, *names: str) -> bool:
    return isinstance(node, ast.Name) and node.id in names


def _open_mode(node: ast.Call) -> Optional[str]:
    mode = node.args[1] if len(node.args) > 1 else next(
        (kw.value for kw in node.keywords if kw.arg == "mode"), None)
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return "r" if mode is None else None    # dynamic mode: not fenced


def _lint_atomic_writes(mod: hostmodel.ModuleModel) -> List[Finding]:
    if os.path.basename(mod.path) == "_hostio.py":
        return []
    pins = mod.pin_lines(PIN_IO)
    out: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or node.lineno in pins:
            continue
        fn = node.func
        if _is_name(fn, "open"):
            mode = _open_mode(node)
            if mode is not None and any(c in mode for c in "wax+"):
                out.append(_finding(
                    "non-atomic-publish", mod.path, node.lineno,
                    f"raw open(..., {mode!r}) in the host control plane "
                    f"— a reader racing this write sees a torn file; "
                    f"route it through {CHOKE_POINT}.atomic_replace "
                    f"(whole files) / append_line (jsonl), or pin "
                    f"deliberate raw IO with '{PIN_IO} <why>'"))
        elif (isinstance(fn, ast.Attribute)
              and ((fn.attr in ("rename", "replace")
                    and _is_name(fn.value, "os"))
                   or (fn.attr == "move"
                       and _is_name(fn.value, "shutil")))):
            base = "os" if _is_name(fn.value, "os") else "shutil"
            out.append(_finding(
                "non-atomic-publish", mod.path, node.lineno,
                f"bare {base}.{fn.attr} in the host control plane — the "
                f"tmp+replace commit sequence lives in ONE place "
                f"({CHOKE_POINT}.atomic_replace); a second hand-rolled "
                f"copy is where the next torn-manifest bug comes from "
                f"(pin deliberate raw IO with '{PIN_IO} <why>')"))
    return out


# -------------------------------------------------------------- clock lint

def _np_random_attr(fn: ast.Attribute) -> Optional[str]:
    """``np.random.X`` / ``numpy.random.X`` -> ``X``."""
    base = fn.value
    if (isinstance(base, ast.Attribute) and base.attr == "random"
            and _is_name(base.value, "np", "numpy")):
        return fn.attr
    return None


def _clock_spelling(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        if _is_name(fn.value, "time") and fn.attr in _TIME_FNS:
            return f"time.{fn.attr}()"
        if _is_name(fn.value, "random"):
            return f"random.{fn.attr}()"
        if _is_name(fn.value, "os") and fn.attr == "urandom":
            return "os.urandom()"
        if (fn.attr in ("now", "utcnow", "today")
                and (_is_name(fn.value, "datetime", "date")
                     or (isinstance(fn.value, ast.Attribute)
                         and fn.value.attr in ("datetime", "date")))):
            return f"datetime.{fn.attr}()"
        np_attr = _np_random_attr(fn)
        if np_attr is not None:
            if np_attr in _NP_SEEDED_CTORS:
                if not node.args and not node.keywords:
                    return f"unseeded np.random.{np_attr}()"
                return None       # seeded constructor: counter-based, ok
            return f"np.random.{np_attr}() (global-state rng)"
    return None


def _lint_clock(mod: hostmodel.ModuleModel) -> List[Finding]:
    pins = mod.pin_lines(PIN_CLOCK)
    out: List[Finding] = []
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.ImportFrom)
                and node.module in ("time", "random")
                and node.lineno not in pins):
            out.append(_finding(
                "clock-escape", mod.path, node.lineno,
                f"'from {node.module} import ...' in a clock-disciplined "
                f"module — bare names dodge the time.*/random.* fence; "
                f"import the module and thread calls through an "
                f"injectable parameter (the clock=time.monotonic "
                f"default idiom)"))
            continue
        if not isinstance(node, ast.Call) or node.lineno in pins:
            continue
        spelling = _clock_spelling(node)
        if spelling is not None:
            out.append(_finding(
                "clock-escape", mod.path, node.lineno,
                f"raw {spelling} in a clock-disciplined module — a "
                f"direct wall-clock/rng call breaks injectable-clock "
                f"tests and bitwise replay; thread it through the named "
                f"clock/rng parameter (clock=time.monotonic / seeded "
                f"np.random.default_rng), or pin a genuinely wall-clock "
                f"site with '{PIN_CLOCK} <why>'"))
    return out


# --------------------------------------------------------------- the pass

def lint_modules(mods: Sequence[hostmodel.ModuleModel]) -> List[Finding]:
    by_name = {}
    for m in mods:
        for c in m.classes:
            by_name.setdefault(c.name, c)
    out: List[Finding] = []
    for m in mods:
        out += _lint_shared_state(m)
        out += _lint_signal_locks(m, by_name)
        out += _lint_atomic_writes(m)
        out += _lint_clock(m)
    return out


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    """Lint explicit files (the seeded-defect tests' entry point)."""
    mods = []
    findings: List[Finding] = []
    for p in paths:
        try:
            mods.append(hostmodel.build_module(p))
        except SyntaxError as e:
            findings.append(_finding("syntax-error", p, e.lineno or 0,
                                     f"unparseable: {e.msg}"))
    return findings + lint_modules(mods)


def lint_host(root: Optional[str] = None) -> List[Finding]:
    """The whole fenced tree — what the runner and ``lint.sh --full`` run."""
    return lint_paths(fenced_files(root))


__all__ = ["PASS", "fenced_files", "lint_host", "lint_modules",
           "lint_paths"]
