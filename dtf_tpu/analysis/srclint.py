"""Minimal source linter — the ``scripts/lint.sh`` fallback when pyflakes
is not installed (the container policy is no new deps; see ISSUE/PR notes).

Pyflakes-grade checks that matter for this codebase, AST-only (no
imports executed):

- syntax errors (files that won't even parse),
- unused imports (module scope; ``# noqa`` and ``__init__.py`` re-exports
  honored),
- duplicate top-level definitions (a copy-pasted ``def test_x`` silently
  shadowing the first is a real way to lose a test),
- ``import *`` (kills static analysis),
- ``except:`` bare handlers (swallow KeyboardInterrupt in launch loops),
- direct ``jax.lax.all_gather``/``psum_scatter`` calls in ``models/`` —
  model code must route TP collectives through ``dtf_tpu.core.comms``
  (one choke point: the comms-budget fence and the ``--tp_overlap``
  collective-matmul dispatch both live behind it),
- raw ``jax.lax.ppermute`` perm lists outside ``core/comms.py`` /
  ``ops/collective_matmul.py`` — a perm at a ppermute call site must be
  a name bound from the named builders ``ring_perm``/``shift_perm``
  (``core/comms.py``), the construction the collective soundness pass
  (``analysis/collective.py``) introspects; a hand-typed pair list with
  one transposed entry compiles clean and trains silently wrong,
- blocking device readbacks (``int(...)``/``float(...)``/``.item()``) in
  the iteration loop of ``dtf_tpu/loop.py``'s ``Trainer.fit`` — the hot
  path is SYNC-FREE (PR 3: a per-step readback serializes dispatch
  against compute and defeats the prefetch double-buffer); designated
  backpressure points carry a ``# blocking-ok: <why>`` marker. This
  protects the invariant statically; tests/test_telemetry.py proves it
  dynamically with the counter-instrumented fit,
- integer block-shape literals at flash-attention / Pallas fused-CE
  call sites outside ``dtf_tpu/ops/`` + ``dtf_tpu/tune/`` (and test
  files, whose parity pins are the point) — launchers and models must
  leave block args at 0 so the kernel-tune resolver supplies the banked
  per-shape winner (KERNEL_TUNE.json; docs/TUNING.md). A hard-coded
  literal silently freezes a shape the autotuner has since beaten —
  the PR 7 ring-perm fence idiom applied to block shapes,
- module-level ``jax`` / ``tensorflow`` imports in ``dtf_tpu/telemetry/``
  — the telemetry package (the XPlane parser and report CLI especially)
  must import without ANY backend present: reports are generated on
  machines with no chip from traces captured on one, and a jax import in
  a live axon env can hang outright (the loop.py lazy-import idiom,
  enforced). Backend-touching helpers import lazily inside functions; a
  deliberate exception carries ``# noqa``.

Usage: ``python -m dtf_tpu.analysis.srclint PATH [PATH ...]`` — prints one
finding per line, exits 1 if any.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Iterator


def _py_files(paths: list[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def _noqa_lines(src: str) -> set[int]:
    return {i for i, line in enumerate(src.splitlines(), 1)
            if "# noqa" in line}


class _Names(ast.NodeVisitor):
    """Collect every identifier USED (loads + attribute roots)."""

    def __init__(self):
        self.used: set[str] = set()

    def visit_Name(self, node):
        self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)


def lint_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]

    problems: list[str] = []
    noqa = _noqa_lines(src)
    is_init = os.path.basename(path) == "__init__.py"

    names = _Names()
    names.visit(tree)
    # names referenced in module __all__ strings count as used
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            names.used.add(node.value)

    # ---- unused imports (module top level only — conservative) ----
    for node in tree.body:
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                if (not is_init and node.lineno not in noqa
                        and bound not in names.used):
                    problems.append(
                        f"{path}:{node.lineno}: unused import {bound!r}")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    problems.append(
                        f"{path}:{node.lineno}: import * from "
                        f"{node.module!r}")
                    continue
                bound = alias.asname or alias.name
                if (not is_init and node.lineno not in noqa
                        and bound not in names.used):
                    problems.append(
                        f"{path}:{node.lineno}: unused import {bound!r}")

    # ---- duplicate top-level defs ----
    seen: dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if node.name in seen and node.lineno not in noqa:
                problems.append(
                    f"{path}:{node.lineno}: {node.name!r} redefines the "
                    f"one at line {seen[node.name]}")
            seen[node.name] = node.lineno

    # ---- bare except ----
    for node in ast.walk(tree):
        if (isinstance(node, ast.ExceptHandler) and node.type is None
                and node.lineno not in noqa):
            problems.append(f"{path}:{node.lineno}: bare 'except:'")

    # ---- direct lax collectives in models/ (must route through comms) ----
    # absolute path + segment test (a relative `srclint gpt.py` run from
    # inside models/ must still be fenced; `submodels/` must not be),
    # anchored on the package root: only segments AFTER the last
    # `dtf_tpu` count, so a checkout living under some ancestor named
    # "models" (/home/ml/models/repo/...) doesn't fence the whole tree.
    # Without a `dtf_tpu` anchor (fixtures, scratch files) only the
    # immediate parent directory counts.
    dirs = os.path.abspath(path).replace(os.sep, "/").split("/")[:-1]
    anchored = "dtf_tpu" in dirs
    if anchored:
        dirs = dirs[len(dirs) - dirs[::-1].index("dtf_tpu"):]
        in_models = "models" in dirs
    else:
        in_models = bool(dirs) and dirs[-1] == "models"
    if in_models:
        fenced = ("all_gather", "psum_scatter")
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in fenced
                    and node.lineno not in noqa):
                continue
            base = node.func.value    # jax.lax.X or lax.X
            is_lax = (isinstance(base, ast.Name) and base.id == "lax") or (
                isinstance(base, ast.Attribute) and base.attr == "lax")
            if is_lax:
                problems.append(
                    f"{path}:{node.lineno}: direct jax.lax."
                    f"{node.func.attr} in models/ — route through "
                    f"dtf_tpu.core.comms (the comms-budget fence and "
                    f"--tp_overlap dispatch choke point)")

    # ---- block-shape literals at tuned-kernel call sites ----
    # anchored files: dirs is already trimmed past the last `dtf_tpu`
    # segment (the models/ fence above), so `in` checks are in-package.
    # Unanchored files (scripts/, tests/, scratch): only the IMMEDIATE
    # parent counts — a checkout under /home/ci/tests/... must not
    # exempt every file, nor an ancestor named ops/ bless one (the same
    # anchoring discipline as the models/ fence).
    base = os.path.basename(path)
    in_tests = (("tests" in dirs) if anchored
                else (bool(dirs) and dirs[-1] == "tests")) \
        or base.startswith("test_")
    blessed_block_module = bool(dirs) and dirs[-1] in ("ops", "tune")
    if not (blessed_block_module or in_tests):
        problems += _block_literals(tree, path, noqa)
        problems += _precision_literals(tree, path, noqa)

    # ---- raw ppermute perm lists (must come from the named builders) ----
    blessed_perm_module = (
        ("dtf_tpu" in dirs or (bool(dirs) and dirs[-1] in ("core", "ops")))
        and ((base == "comms.py" and (not dirs or dirs[-1] == "core"))
             or (base == "collective_matmul.py"
                 and (not dirs or dirs[-1] == "ops"))))
    if not blessed_perm_module:
        problems += _raw_ppermute_perms(tree, path, noqa)

    # ---- blocking readbacks in the trainer hot path (loop.py fit) ----
    if os.path.basename(path) == "loop.py" and (
            "dtf_tpu" in dirs or not dirs or dirs[-1] == "dtf_tpu"):
        problems += _hotpath_readbacks(tree, path, noqa, src)

    # ---- raw AOT lower/compile outside the executor (ISSUE 18) ----
    # core/executor.py is the one sanctioned home of the
    # jit→lower→compile idiom; tune/ sweeps compile candidate programs
    # by design, and tests exercise raw AOT surfaces directly.
    blessed_aot_module = (
        (base == "executor.py" and (not dirs or dirs[-1] == "core"))
        or (("tune" in dirs) if anchored
            else (bool(dirs) and dirs[-1] == "tune")))
    if not (blessed_aot_module or in_tests):
        problems += _raw_aot_compiles(tree, path, noqa, src)

    # ---- backend imports fenced out of telemetry/tune/fault/stream ----
    # telemetry: reports parse traces on chipless machines. tune: the
    # bench_tune parent imports the package BEFORE probing the backend
    # (dead-tunnel rc-0 contract) — a module-level jax import in either
    # can hang a live-axon process before any code runs. fault: the run
    # controller supervises possibly-WEDGED backends from a clean chief
    # process — importing the thing it must outlive would be fatal.
    for pkg, why in (("telemetry", "reports parse traces on chipless "
                      "machines; an axon-env jax import can hang"),
                     ("tune", "bench_tune's parent imports it BEFORE "
                      "probing the backend — a module-level backend "
                      "import hangs the dead-tunnel rc-0 path"),
                     ("fault", "the run controller supervises a possibly-"
                      "wedged backend from a clean process and must "
                      "never import what it has to outlive"),
                     ("stream", "the mixture stream is pure host IO "
                      "whose producer thread and bench row must run — "
                      "and be testable — with no backend present")):
        in_pkg = (pkg in dirs if anchored
                  else bool(dirs) and dirs[-1] == pkg)
        if in_pkg:
            problems += _backend_imports(tree, path, noqa, pkg, why)

    # logsink.py is the ONE jax-free module inside serve/ (ISSUE 19):
    # backend-free processes (distill tooling, the poison-import test)
    # load it by file location because serve/__init__ pulls jax — a
    # module-level backend import here would defeat that load path.
    if base == "logsink.py" and (("serve" in dirs) if anchored
                                 else bool(dirs) and dirs[-1] == "serve"):
        problems += _backend_imports(
            tree, path, noqa, "serve/logsink",
            "the serve-log sink is host-side file IO loaded by file "
            "location in backend-free processes; serve/__init__ owns "
            "the jax imports")

    return problems


#: module roots whose import pulls a backend (or its proto stack) into
#: the process — fenced at telemetry module level, lazy-only inside.
_BACKEND_ROOTS = ("jax", "jaxlib", "tensorflow")


def _backend_imports(tree, path: str, noqa: set,
                     pkg: str = "telemetry",
                     why: str = "reports parse traces on chipless "
                     "machines; an axon-env jax import can hang") -> list:
    """Import-time backend imports in a fenced package (``telemetry/``,
    ``tune/``) — these must stay importable in a process with no
    jax/tensorflow at all, and a module-level jax import in a live axon
    env can hang before any code runs (CLAUDE.md). Lazy imports inside
    functions are the sanctioned spelling; anything that executes at
    module import time is fenced, including imports wrapped in try/if
    or sitting in a class body (they still run on import)."""
    def module_time_nodes(body):
        # every statement that executes when the module is imported:
        # descend into try/if/with/class bodies, NOT into functions
        # (a def's body runs at call time — that's the lazy spelling)
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield node
            for attr in ("body", "orelse", "finalbody"):
                yield from module_time_nodes(getattr(node, attr, []) or [])
            for h in getattr(node, "handlers", []) or []:
                yield from module_time_nodes(h.body)

    problems = []
    for node in module_time_nodes(tree.body):
        roots = []
        if isinstance(node, ast.Import):
            roots = [a.name.split(".")[0] for a in node.names]
        elif isinstance(node, ast.ImportFrom) and node.level == 0 \
                and node.module:
            roots = [node.module.split(".")[0]]
        for root in roots:
            if root in _BACKEND_ROOTS and node.lineno not in noqa:
                problems.append(
                    f"{path}:{node.lineno}: module-level '{root}' import "
                    f"in dtf_tpu/{pkg}/ — the {pkg} package must "
                    f"import without a backend ({why}); import it "
                    f"lazily inside the function that needs it")
    return problems


#: tuned-kernel entry points and the block kwargs the tuner owns: an
#: int literal for one of these outside ops//tune/ (and tests) bypasses
#: the kernel-tune resolver (dtf_tpu/tune; docs/TUNING.md).
_TUNED_KERNEL_CALLS = {
    "flash_attention": ("block_q", "block_k", "block_h",
                        "block_q_bwd", "block_k_bwd"),
    "flash_attention_sharded": ("block_h",),
    "pallas_lm_cross_entropy": ("block_n", "block_v"),
    "pallas_lm_cross_entropy_sharded": ("block_n", "block_v"),
}


def _block_literals(tree, path: str, noqa: set) -> list:
    """Nonzero int literals for tuner-owned block kwargs at flash /
    fused-CE call sites — launchers and models must leave them at 0 (the
    resolver sentinel) or thread a resolved variable, so the banked
    per-shape winners actually apply. 0 is the sentinel itself and
    stays legal; a deliberate pin carries ``# noqa`` with its why."""
    problems = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and node.lineno not in noqa):
            continue
        fn = node.func
        fn_name = (fn.id if isinstance(fn, ast.Name)
                   else fn.attr if isinstance(fn, ast.Attribute) else None)
        fenced = _TUNED_KERNEL_CALLS.get(fn_name or "")
        if not fenced:
            continue
        for kw in node.keywords:
            if (kw.arg in fenced and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, int)
                    and not isinstance(kw.value.value, bool)
                    and kw.value.value != 0
                    and kw.value.lineno not in noqa):
                problems.append(
                    f"{path}:{kw.value.lineno}: block-shape literal "
                    f"{kw.arg}={kw.value.value} at a {fn_name} call — "
                    f"leave it 0 so the kernel-tune resolver supplies "
                    f"the banked winner (dtf_tpu/tune, KERNEL_TUNE.json; "
                    f"docs/TUNING.md), or mark a deliberate pin with "
                    f"'# noqa: <why>'")
    return problems


#: the tp_dense/ring entry points whose ``precision`` kwarg the tuner
#: owns (ISSUE 17), and the literal values that stay legal anywhere:
#: "" (bf16 status quo) and "auto" (resolver decides). A hard-coded
#: "int8"/"fp8" outside ops//tune/ (and tests) bypasses the measured
#: quality bound exactly the way a block-shape literal bypasses the
#: banked block winner — same fence, string edition.
_PRECISION_CALLS = ("tp_dense", "TpDense", "quantized_matmul",
                    "ag_matmul_quant_sharded", "matmul_rs_quant_sharded")
_PRECISION_FREE_LITERALS = ("", "auto")


def _precision_literals(tree, path: str, noqa: set) -> list:
    """String precision literals other than ''/'auto' at tp_dense / ring
    call sites — launchers and models must pass '' (bf16), 'auto' (the
    kernel-tune winner), or thread a resolved variable (e.g.
    ``precision=cfg.matmul_precision``, which is an Attribute, not a
    Constant, and passes untouched). A deliberate pin carries
    ``# noqa`` with its why."""
    problems = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and node.lineno not in noqa):
            continue
        fn = node.func
        fn_name = (fn.id if isinstance(fn, ast.Name)
                   else fn.attr if isinstance(fn, ast.Attribute) else None)
        if fn_name not in _PRECISION_CALLS:
            continue
        for kw in node.keywords:
            if (kw.arg == "precision"
                    and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)
                    and kw.value.value not in _PRECISION_FREE_LITERALS
                    and kw.value.lineno not in noqa):
                problems.append(
                    f"{path}:{kw.value.lineno}: precision literal "
                    f"{kw.value.value!r} at a {fn_name} call — pass '' "
                    f"(bf16), 'auto' (the kernel-tune winner under its "
                    f"rel-err ceiling), or a resolved variable "
                    f"(dtf_tpu/tune, KERNEL_TUNE.json; docs/TUNING.md), "
                    f"or mark a deliberate pin with '# noqa: <why>'")
    return problems


#: the sanctioned perm constructors (core/comms.py) — the introspection
#: surface of the collective soundness pass.
_PERM_BUILDERS = ("ring_perm", "shift_perm")


def _raw_ppermute_perms(tree, path: str, noqa: set) -> list:
    """``jax.lax.ppermute`` calls whose ``perm`` is not a name bound from
    ``ring_perm``/``shift_perm`` — outside the two ring modules, rings
    must come from the named helpers the soundness pass can introspect
    (the PR 2 fence idiom, applied to perm construction).

    A name counts as blessed only when EVERY assignment to it in the file
    is a builder call — a second function hand-typing a pair list into a
    name some other scope blessed (``perm`` is the idiomatic name
    everywhere) must not ride the first function's blessing.
    """
    def _is_builder(value) -> bool:
        if not isinstance(value, ast.Call):
            return False
        fn = value.func
        fn_name = (fn.id if isinstance(fn, ast.Name)
                   else fn.attr if isinstance(fn, ast.Attribute) else None)
        return fn_name in _PERM_BUILDERS

    #: in-place mutators that de-bless a builder-built list.
    _MUTATORS = ("append", "extend", "insert", "remove", "pop", "sort",
                 "reverse", "clear")

    blessed: set[str] = set()
    tainted: set[str] = set()
    for node in ast.walk(tree):
        targets = ()
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = (node.target,), node.value
        elif isinstance(node, ast.AugAssign):
            # perm += [...] hand-edits a blessed list — taint it
            targets, value = (node.target,), None
        for tgt in targets:
            names = ([tgt] if isinstance(tgt, ast.Name)
                     else [e for e in ast.walk(tgt)
                           if isinstance(e, ast.Name)])
            for nm in names:
                (blessed if _is_builder(value) else tainted).add(nm.id)
        # perm.append((0, 2)) mutates in place — taint too
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
                and isinstance(node.func.value, ast.Name)):
            tainted.add(node.func.value.id)
    blessed -= tainted

    problems = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and node.lineno not in noqa):
            continue
        # every spelling: jax.lax.ppermute / lax.ppermute / a bare
        # `ppermute` from `from jax.lax import ppermute` — leaving one
        # spelling unfenced leaves the hole open
        if isinstance(node.func, ast.Attribute):
            if node.func.attr != "ppermute":
                continue
        elif not (isinstance(node.func, ast.Name)
                  and node.func.id == "ppermute"):
            continue
        perm = None
        if len(node.args) >= 3:
            perm = node.args[2]
        else:
            perm = next((kw.value for kw in node.keywords
                         if kw.arg == "perm"), None)
        if (isinstance(perm, ast.Name) and perm.id in blessed):
            continue
        if isinstance(perm, ast.Call):
            fn = perm.func
            fn_name = (fn.id if isinstance(fn, ast.Name)
                       else fn.attr if isinstance(fn, ast.Attribute)
                       else None)
            if fn_name in _PERM_BUILDERS:
                continue
        problems.append(
            f"{path}:{node.lineno}: raw perm at jax.lax.ppermute call — "
            f"build it with core.comms.ring_perm/shift_perm (the named "
            f"helpers the collective soundness pass introspects); a "
            f"hand-typed pair list dodges the ring fence")
    return problems


def _raw_aot_compiles(tree, path: str, noqa: set, src: str) -> list:
    """``.lower(args)`` / ``.compile(`` attribute calls outside
    ``core/executor.py`` (+ tune/ + tests) — the AOT idiom must route
    through :func:`dtf_tpu.core.executor.program`, the one place that
    owns the recompile fence, sharding pins, the donation gate and the
    analysis step-view registration (ISSUE 18). A deliberate raw site
    carries ``# aot-ok: <why>`` (covers its line and the next, so the
    idiomatic two-line ``.lower(...)\\n.compile()`` needs one pin).

    Skipped on purpose: no-argument ``.lower()`` (``str.lower`` — the
    bare-operand Program.lower() spelling is executor-internal) and
    ``re.compile(``."""
    ok: set[int] = set()
    for i, line in enumerate(src.splitlines(), 1):
        if "# aot-ok" in line:
            ok.update((i, i + 1))
    problems = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("lower", "compile")
                and node.lineno not in noqa
                and node.lineno not in ok):
            continue
        if (node.func.attr == "lower" and not node.args
                and not node.keywords):
            continue                      # str.lower()
        fn_base = node.func.value
        if (node.func.attr == "compile"
                and isinstance(fn_base, ast.Name) and fn_base.id == "re"):
            continue                      # re.compile()
        problems.append(
            f"{path}:{node.lineno}: raw .{node.func.attr}( AOT idiom — "
            f"route through dtf_tpu.core.executor.program (the fence / "
            f"pins / donation / step-view choke point; docs/ANALYSIS.md), "
            f"or mark a deliberate site with '# aot-ok: <why>'")
    return problems


def _hotpath_readbacks(tree, path: str, noqa: set, src: str) -> list:
    """``int()``/``float()``/``.item()`` inside the iteration loop of
    ``Trainer.fit`` — each is a blocking device readback serializing host
    dispatch against device compute (the PR 3 sync-free invariant). The
    one-time resume sync sits BEFORE the loop and is legal; an intentional
    backpressure point inside it must carry ``# blocking-ok: <why>``."""
    allowed = {i for i, line in enumerate(src.splitlines(), 1)
               if "# blocking-ok" in line}

    def loops_of_fit():
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and fn.name == "fit":
                    for node in ast.walk(fn):
                        if isinstance(node, (ast.For, ast.While)):
                            yield node

    problems = []
    seen: set[int] = set()
    for loop in loops_of_fit():
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call) or node.lineno in seen:
                continue
            name = None
            if isinstance(node.func, ast.Name) and \
                    node.func.id in ("int", "float"):
                name = f"{node.func.id}(...)"
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item":
                name = ".item()"
            if name is None or node.lineno in noqa \
                    or node.lineno in allowed:
                continue
            seen.add(node.lineno)
            problems.append(
                f"{path}:{node.lineno}: {name} in Trainer.fit's hot loop "
                f"— a blocking device readback breaks the sync-free loop "
                f"(PR 3); move it to a hook or mark a designated "
                f"backpressure point with '# blocking-ok: <why>'")
    return problems


def main(argv: list[str]) -> int:
    paths = argv or ["dtf_tpu"]
    problems = []
    n = 0
    for f in _py_files(paths):
        n += 1
        problems += lint_file(f)
    for p in problems:
        print(p)
    print(f"srclint: {n} files, {len(problems)} problems")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
