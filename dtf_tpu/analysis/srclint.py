"""Minimal source linter — the ``scripts/lint.sh`` fallback when pyflakes
is not installed (the container policy is no new deps; see ISSUE/PR notes).

Pyflakes-grade checks that matter for this codebase, AST-only (no
imports executed):

- syntax errors (files that won't even parse),
- unused imports (module scope; ``# noqa`` and ``__init__.py`` re-exports
  honored),
- duplicate top-level definitions (a copy-pasted ``def test_x`` silently
  shadowing the first is a real way to lose a test),
- ``import *`` (kills static analysis),
- ``except:`` bare handlers (swallow KeyboardInterrupt in launch loops).

Usage: ``python -m dtf_tpu.analysis.srclint PATH [PATH ...]`` — prints one
finding per line, exits 1 if any.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Iterator


def _py_files(paths: list[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def _noqa_lines(src: str) -> set[int]:
    return {i for i, line in enumerate(src.splitlines(), 1)
            if "# noqa" in line}


class _Names(ast.NodeVisitor):
    """Collect every identifier USED (loads + attribute roots)."""

    def __init__(self):
        self.used: set[str] = set()

    def visit_Name(self, node):
        self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)


def lint_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]

    problems: list[str] = []
    noqa = _noqa_lines(src)
    is_init = os.path.basename(path) == "__init__.py"

    names = _Names()
    names.visit(tree)
    # names referenced in module __all__ strings count as used
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            names.used.add(node.value)

    # ---- unused imports (module top level only — conservative) ----
    for node in tree.body:
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                if (not is_init and node.lineno not in noqa
                        and bound not in names.used):
                    problems.append(
                        f"{path}:{node.lineno}: unused import {bound!r}")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    problems.append(
                        f"{path}:{node.lineno}: import * from "
                        f"{node.module!r}")
                    continue
                bound = alias.asname or alias.name
                if (not is_init and node.lineno not in noqa
                        and bound not in names.used):
                    problems.append(
                        f"{path}:{node.lineno}: unused import {bound!r}")

    # ---- duplicate top-level defs ----
    seen: dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if node.name in seen and node.lineno not in noqa:
                problems.append(
                    f"{path}:{node.lineno}: {node.name!r} redefines the "
                    f"one at line {seen[node.name]}")
            seen[node.name] = node.lineno

    # ---- bare except ----
    for node in ast.walk(tree):
        if (isinstance(node, ast.ExceptHandler) and node.type is None
                and node.lineno not in noqa):
            problems.append(f"{path}:{node.lineno}: bare 'except:'")

    return problems


def main(argv: list[str]) -> int:
    paths = argv or ["dtf_tpu"]
    problems = []
    n = 0
    for f in _py_files(paths):
        n += 1
        problems += lint_file(f)
    for p in problems:
        print(p)
    print(f"srclint: {n} files, {len(problems)} problems")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
