"""The analyzable-config registry: every shipping parallelism configuration,
described once, for all three analyzer passes.

Each entry carries two views:

- **spec view** (real scale): the production model's abstract param tree +
  its rulebook, built with ``jax.eval_shape`` — free, so the rule lints run
  against the REAL dims (a 768-hidden BERT, the 50304-vocab GPT head), where
  divisibility actually matters.
- **step view** (tiny scale): the same train-step construction the launcher
  performs, on the ``tiny`` model config — compiled AOT on the 8-device CPU
  sim for the comms-budget fence and traced for the jaxpr lints.  Tiny
  shapes keep compile cost test-tier friendly; the collective STRUCTURE
  (which collectives, on which paths) is what the fence pins, and that is a
  property of the sharding code, not the layer count.

``replicated_ok`` / ``allow_dead`` encode each config's *intentional*
deviations (pipeline embed/head replicated by design; the MoE expert rule
dead on dense GPT) so the analyzer can hold everything else to zero
findings.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import jax
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from dtf_tpu.core import train as tr
from dtf_tpu.core.comms import batch_shardings_for
from dtf_tpu.core.mesh import MeshConfig, make_mesh
from dtf_tpu.data.synthetic import SyntheticData

PyTree = Any


@dataclasses.dataclass(frozen=True)
class SpecView:
    params: PyTree                    # abstract (ShapeDtypeStruct) tree
    rules: Sequence[tuple]            # the production rulebook
    zero1: bool = True


@dataclasses.dataclass(frozen=True)
class StepView:
    step: Callable                    # jitted train step (AOT-lowerable)
    state: PyTree                     # abstract TrainState
    batch: PyTree                     # abstract batch
    #: the in_shardings the builder passed to jit, as ``(state_shardings,
    #: batch_shardings)`` — the DECLARED layout the memory pass prices the
    #: resident-state model at and cross-checks against the executable's
    #: committed shardings (``state-accounting-drift``).  None = each
    #: abstract leaf carries its own ``.sharding`` (the serve views).
    arg_shardings: Any = None

    @classmethod
    def of(cls, program, state, batch) -> "StepView":
        """The view of an executor :class:`~dtf_tpu.core.executor.Program`:
        the builder already registered its declared input layouts on the
        Program (``arg_shardings``), so config builders stop re-spelling
        the tuple they just passed to jit — one declaration, consumed by
        both the compile and the memory fence."""
        return cls(program, state, batch,
                   arg_shardings=getattr(program, "arg_shardings", None))


@dataclasses.dataclass(frozen=True)
class AnalysisConfig:
    name: str
    mesh_config: MeshConfig
    spec_view: Callable[[Mesh], SpecView]
    step_view: Callable[[Mesh], StepView]
    #: rule patterns allowed to match nothing in THIS config (e.g. the MoE
    #: expert rule on a dense GPT — the rulebook is shared).
    allow_dead: tuple[str, ...] = ()
    #: leaf-path regexes intentionally replicated despite their size.
    replicated_ok: tuple[str, ...] = ()
    #: the optimizer family this config's LAUNCHER trains with — the fit
    #: planner prices optimizer moments for it (``fit --opt`` overrides).
    opt_name: str = "adamw"
    #: serve configs: a zero-arg callable returning the REAL-scale model
    #: config for HBM fit planning (``python -m dtf_tpu.analysis fit``) —
    #: per-slot KV and page-pool bytes are priced from it via eval_shape.
    fit_serve_cfg: Callable[[], Any] | None = None
    #: speculative serve configs: the REAL-scale DRAFT model config — the
    #: fit planner then also prices draft params + per-slot draft KV and
    #: answers "max slots with spec on" (the draft is resident state the
    #: slot budget must leave room for).
    fit_draft_cfg: Callable[[], Any] | None = None

    def mesh(self, devices=None) -> Mesh:
        return make_mesh(self.mesh_config, devices=devices)


def _abstract_batch(kind: str, batch: int, **kw) -> PyTree:
    example = SyntheticData(kind, batch, **kw).batch(0)
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
        example)


def _rng():
    return jax.random.PRNGKey(0)


# --------------------------------------------------------------------------
# Per-workload builders.  Each step_view mirrors its launcher's train-step
# construction (scripts/*.py) — same loss, optimizer family, rules, ZeRO-1
# and batch placement — at tiny scale.
# --------------------------------------------------------------------------

def _mnist_spec(mesh):
    from dtf_tpu.models import mnist

    model = mnist.make_model("softmax")
    params = jax.eval_shape(mnist.make_init(model), _rng())["params"]
    return SpecView(params, rules=())


def _mnist_step(mesh):
    from dtf_tpu.models import mnist

    model = mnist.make_model("softmax")
    tx = optax.sgd(0.01)
    state, shardings = tr.abstract_train_state(
        mnist.make_init(model), tx, _rng(), mesh)
    step = tr.make_train_step(mnist.make_loss(model), tx, mesh, shardings)
    return StepView.of(step, state, _abstract_batch("mnist", 32))


def _resnet_spec(variant):
    def build(mesh):
        from dtf_tpu.models import resnet

        model = (resnet.resnet20() if variant == "cifar"
                 else resnet.resnet50())
        shape = (32, 32, 3) if variant == "cifar" else (224, 224, 3)
        params = jax.eval_shape(
            resnet.make_init(model, shape), _rng())["params"]
        return SpecView(params, rules=())

    return build


def _resnet_step(variant, batch):
    def build(mesh):
        from dtf_tpu.models import resnet

        model = (resnet.resnet20() if variant == "cifar"
                 else resnet.resnet50())
        shape = (32, 32, 3) if variant == "cifar" else (224, 224, 3)
        tx = optax.sgd(0.1, momentum=0.9, nesterov=True)
        state, shardings = tr.abstract_train_state(
            resnet.make_init(model, shape), tx, _rng(), mesh)
        step = tr.make_train_step(
            resnet.make_loss(model, weight_decay=1e-4), tx, mesh, shardings)
        return StepView.of(step, state, _abstract_batch(variant, batch))

    return build


def _bert_spec(mesh):
    from dtf_tpu.models import bert

    cfg = bert.BertConfig.base()
    _, init_fn = bert.make_init(cfg, mesh, seq_len=128)
    params = jax.eval_shape(init_fn, _rng())["params"]
    return SpecView(params, rules=bert.tp_rules)


def _bert_step(mesh):
    from dtf_tpu.models import bert

    cfg = bert.BertConfig.tiny()
    model, init_fn = bert.make_init(cfg, mesh, seq_len=32)
    tx = optax.adamw(1e-4, weight_decay=0.01)
    state, shardings = tr.abstract_train_state(
        init_fn, tx, _rng(), mesh, param_rules=bert.tp_rules)
    batch = _abstract_batch("bert", 16, seq_len=32, vocab_size=128)
    batch_sh = batch_shardings_for(batch, mesh, P("data", "seq"))
    step = tr.make_train_step(
        bert.make_loss(model), tx, mesh, shardings, grad_accum=2,
        batch_shardings=batch_sh)
    return StepView.of(step, state, batch)


def _bert_accum_step(grad_shard):
    """BASELINE config 4's machinery (grad-accum + ZeRO-1) on a dp4 x sp2
    mesh — the ``--grad_shard`` A/B pair: ``grad_shard=False`` is the
    replicated-accumulator control at the SAME mesh, so the golden shows
    the all-reduce → reduce-scatter swap and the accumulator temp-bytes
    shrink side by side (docs/ZERO.md). The model is built MESH-LESS
    (dense attention over GSPMD-sharded tokens): ``--grad_shard`` requires
    a pure-GSPMD loss — the shard_map'd kernels (ring/flash) pin their own
    batch-over-data layout, which the per-shard-group vmap cannot nest."""

    def build(mesh):
        from dtf_tpu.models import bert

        cfg = bert.BertConfig.tiny()
        model, init_fn = bert.make_init(cfg, None, seq_len=32)
        tx = optax.adamw(1e-4, weight_decay=0.01)
        state, shardings = tr.abstract_train_state(
            init_fn, tx, _rng(), mesh, param_rules=bert.tp_rules)
        batch = _abstract_batch("bert", 16, seq_len=32, vocab_size=128)
        batch_sh = batch_shardings_for(batch, mesh, P("data", "seq"))
        step = tr.make_train_step(
            bert.make_loss(model), tx, mesh, shardings, grad_accum=2,
            grad_shard=grad_shard, batch_shardings=batch_sh)
        return StepView.of(step, state, batch)

    return build


def _widedeep_spec(mesh):
    from dtf_tpu.models import widedeep

    model = widedeep.WideDeep()
    params = jax.eval_shape(widedeep.make_init(model), _rng())["params"]
    return SpecView(params, rules=widedeep.rules)


def _widedeep_step(mesh):
    from dtf_tpu.models import widedeep

    model = widedeep.WideDeep()
    tx = optax.adam(1e-3)
    state, shardings = tr.abstract_train_state(
        widedeep.make_init(model), tx, _rng(), mesh,
        param_rules=widedeep.rules)
    step = tr.make_train_step(widedeep.make_loss(model), tx, mesh,
                              shardings)
    return StepView.of(step, state, _abstract_batch("widedeep", 64))


def _gpt_cfg(tiny: bool, **kw):
    from dtf_tpu.models import gpt

    return (gpt.GPTConfig.tiny(**kw) if tiny
            else dataclasses.replace(gpt.GPTConfig.gpt2_small(), **kw))


def _gpt_real_cfg(**kw):
    """Zero-arg REAL-scale model-config builder for the serve entries'
    ``fit_serve_cfg`` hook — the HBM fit planner prices per-slot KV and
    page-pool bytes from it (eval_shape only, never compiled)."""
    def build():
        return _gpt_cfg(False, **kw)

    return build


def _gpt_spec(**cfg_kw):
    def build(mesh):
        from dtf_tpu.models import gpt

        _, init_fn = gpt.make_init(_gpt_cfg(False, **cfg_kw), mesh,
                                   seq_len=128)
        params = jax.eval_shape(init_fn, _rng())["params"]
        return SpecView(params, rules=gpt.tp_rules)

    return build


def _gpt_step(**cfg_kw):
    def build(mesh):
        from dtf_tpu.models import gpt

        cfg = _gpt_cfg(True, **cfg_kw)
        model, init_fn = gpt.make_init(cfg, mesh, seq_len=32)
        tx = optax.adamw(3e-4, weight_decay=0.1)
        state, shardings = tr.abstract_train_state(
            init_fn, tx, _rng(), mesh, param_rules=gpt.tp_rules)
        batch = _abstract_batch("gpt", 8, seq_len=32, vocab_size=128)
        sp = mesh.shape.get("seq", 1) > 1
        kw = {}
        if sp:
            kw["batch_shardings"] = batch_shardings_for(
                batch, mesh, P("data", "seq"))
        step = tr.make_train_step(gpt.make_loss(model), tx, mesh,
                                  shardings, **kw)
        return StepView.of(step, state, batch)

    return build


def _gpt_serve_step(mesh):
    """The serving engine's ``decode_all`` program (dtf_tpu/serve) as a
    step view: state = the TP-sharded params, batch = the slot-batched
    engine state (KV cache P('data','model') — slots over data shards,
    heads over TP shards). The fence pins the decode graph's collectives
    exactly as ``DecodeEngine`` AOT-compiles them, so a resharding slipped
    into the serving hot path (e.g. a cache spec change making GSPMD
    all-gather every slot's K/V per token) fails tier-1 before a chip
    ever serves it."""
    from dtf_tpu.models import gpt
    from dtf_tpu.serve.engine import decode_step_view

    step, abs_params, abs_state = decode_step_view(
        gpt.GPTConfig.tiny(), n_slots=8, max_len=64, mesh=mesh)
    return StepView(step, abs_params, abs_state)


def _gpt_eval_step(mesh):
    """The launcher's EVAL program (``lm_eval_hook`` →
    ``tr.make_eval_step`` over ``gpt.make_eval``) — an AOT program that
    runs every ``--eval_every`` window on the same mesh as training but
    was never fenced: a spec regression visible only in the eval graph
    (e.g. GSPMD all-gathering the head table for the full-logits CE)
    would surface as a mysterious eval-time stall, not a tier-1 failure."""
    from dtf_tpu.models import gpt

    cfg = _gpt_cfg(True)
    model, init_fn = gpt.make_init(cfg, mesh, seq_len=32)
    tx = optax.adamw(3e-4, weight_decay=0.1)
    state, shardings = tr.abstract_train_state(
        init_fn, tx, _rng(), mesh, param_rules=gpt.tp_rules)
    batch = _abstract_batch("gpt", 8, seq_len=32, vocab_size=128)
    batch_sh = batch_shardings_for(batch, mesh, P("data", "seq"))
    step = tr.make_eval_step(gpt.make_eval(model), mesh, shardings,
                             batch_shardings=batch_sh)
    return StepView.of(step, state, batch)


def _gpt_prefill_step(mesh):
    """The serving engine's PREFILL program (``serve.engine``
    ``prefill_into_slot``) at the ``gpt_serve`` mesh — fences the
    admission path's collectives, including the known sharded-prefill
    dynamic-slice resharding PR 4 documented as un-fenced (docs/SERVING.md):
    growth there now fails tier-1 instead of quietly eating TTFT."""
    from dtf_tpu.models import gpt
    from dtf_tpu.serve.engine import prefill_step_view

    step, abs_params, ops = prefill_step_view(
        gpt.GPTConfig.tiny(), n_slots=8, max_len=64, prefill_chunk=8,
        mesh=mesh)
    return StepView(step, abs_params, ops)


def _gpt_pages_step(mesh):
    """The PR 6 page programs (``page_load`` ∘ ``page_save`` — one
    admission tick of the prefix page cache) as one fenced step: the
    batched pool gather/scatter must stay a fixed set of collectives
    however the pool is laid out."""
    from dtf_tpu.models import gpt
    from dtf_tpu.serve.engine import page_step_view

    step, bundle, ops = page_step_view(
        gpt.GPTConfig.tiny(), n_slots=8, max_len=64, kv_page_size=16,
        n_pages=4, mesh=mesh)
    return StepView(step, bundle, ops)


def _gpt_serve_int8_step(mesh):
    """``gpt_serve`` with ``kv_cache_dtype="int8"`` — the quantized-KV
    decode graph (int8 K/V + f32 per-position scales in the cache,
    dequant-on-read inside the step). Fenced separately so the dequant
    multiplies can never grow a collective the bf16 fence wouldn't see
    (the cache leaves carry the SAME shardings; only dtypes and the scale
    leaves differ — docs/ANALYSIS.md)."""
    from dtf_tpu.models import gpt
    from dtf_tpu.serve.engine import decode_step_view

    step, abs_params, abs_state = decode_step_view(
        gpt.GPTConfig.tiny(kv_cache_dtype="int8"), n_slots=8, max_len=64,
        mesh=mesh)
    return StepView(step, abs_params, abs_state)


def _gpt_serve_spec_step(mesh):
    """The SPECULATIVE serving tick (ISSUE 13): ``draft_all`` ∘ ``verify``
    as one step — the two graphs a ``spec_k > 0`` engine compiles beyond
    prefill. Fences the draft model's unrolled k-step proposal loop and
    the (k+1)-wide verify pass (its TP all-reduces, per-row span scatter
    and rollback) so a layout change that turns speculation's one-dispatch
    win into per-token collective traffic fails tier-1; the memory fields
    price the k-token verify temp + the draft's resident cache."""
    from dtf_tpu.models import gpt
    from dtf_tpu.serve.engine import spec_step_view

    step, bundle, ops = spec_step_view(
        gpt.GPTConfig.tiny(),
        dataclasses.replace(gpt.GPTConfig.tiny(), layers=1), n_slots=8,
        max_len=64, spec_k=4, mesh=mesh)
    return StepView(step, bundle, ops)


def _gpt_serve_disagg_step(mesh):
    """The DISAGGREGATED fleet's prefill-replica admission tick
    (``prefill_into_slot`` ∘ ``page_save``): the handoff-producing
    composition — the page pool as prefill→decode KV transport. Fenced so
    the transport's collective structure (chunk TP projections + the pool
    scatter over data shards) cannot silently grow into whole-leaf
    traffic per admission."""
    from dtf_tpu.models import gpt
    from dtf_tpu.serve.engine import disagg_step_view

    step, bundle, ops = disagg_step_view(
        gpt.GPTConfig.tiny(), n_slots=8, max_len=64, prefill_chunk=8,
        kv_page_size=16, n_pages=4, mesh=mesh)
    return StepView(step, bundle, ops)


def _gpt_draft_real_cfg():
    """Zero-arg REAL-scale draft-config builder (``fit_draft_cfg``)."""
    from dtf_tpu.models import gpt

    return gpt.GPTConfig.gpt2_draft()


def _gpt_pipe_spec(mesh):
    from dtf_tpu.models import gpt, gpt_pipe

    init_fn = gpt_pipe.make_pipe_init(gpt.GPTConfig.gpt2_small(), mesh,
                                      seq_len=128)
    params = jax.eval_shape(init_fn, _rng())["params"]
    return SpecView(params, rules=gpt_pipe.pipe_rules())


def _gpt_pipe_step(schedule):
    def build(mesh):
        from dtf_tpu.models import gpt, gpt_pipe

        cfg = gpt.GPTConfig.tiny()
        init_fn = gpt_pipe.make_pipe_init(cfg, mesh, seq_len=32)
        tx = optax.adamw(3e-4, weight_decay=0.1)
        state, shardings = tr.abstract_train_state(
            init_fn, tx, _rng(), mesh, param_rules=gpt_pipe.pipe_rules())
        batch = _abstract_batch("gpt", 16, seq_len=32, vocab_size=128)
        if schedule in ("1f1b", "zb"):
            maker = {"1f1b": gpt_pipe.make_pipe_grads_1f1b,
                     "zb": gpt_pipe.make_pipe_grads_zb}[schedule]
            grads_fn = maker(cfg, mesh, n_microbatches=4)
            step = tr.make_train_step_from_grads(grads_fn, tx, mesh,
                                                 shardings)
        else:
            loss_fn = gpt_pipe.make_pipe_loss(cfg, mesh, n_microbatches=4)
            step = tr.make_train_step(loss_fn, tx, mesh, shardings)
        return StepView.of(step, state, batch)

    return build


def _gpt_pipe_tp_spec(mesh):
    from dtf_tpu.models import gpt, gpt_pipe_tp

    init_fn = gpt_pipe_tp.make_pipe_tp_init(gpt.GPTConfig.gpt2_small(),
                                            mesh, seq_len=128)
    params = jax.eval_shape(init_fn, _rng())["params"]
    return SpecView(params, rules=gpt_pipe_tp.pipe_tp_rules())


def _gpt_pipe_tp_step(mesh):
    from dtf_tpu.models import gpt, gpt_pipe_tp

    cfg = gpt.GPTConfig.tiny()
    init_fn = gpt_pipe_tp.make_pipe_tp_init(cfg, mesh, seq_len=32)
    tx = optax.adamw(3e-4, weight_decay=0.1)
    state, shardings = tr.abstract_train_state(
        init_fn, tx, _rng(), mesh,
        param_rules=gpt_pipe_tp.pipe_tp_rules())
    loss_fn = gpt_pipe_tp.make_pipe_tp_loss(cfg, mesh, n_microbatches=4)
    step = tr.make_train_step(loss_fn, tx, mesh, shardings)
    return StepView.of(
        step, state,
        _abstract_batch("gpt", 8, seq_len=32, vocab_size=128))


#: the registry: five BASELINE workloads + the GPT flagship + pipelined
#: variants + the MoE expert-parallel path (all-to-all coverage) + the
#: whole AOT-program inventory beyond train steps — serving decode
#: (bf16/int8), serving prefill, the page-cache tick, and the eval step
#: (ISSUE 7: the fence covers the fleet, not one program shape).
REGISTRY: tuple[AnalysisConfig, ...] = (
    AnalysisConfig("mnist", MeshConfig(data=8), _mnist_spec, _mnist_step,
                   opt_name="sgd"),
    AnalysisConfig("resnet_cifar", MeshConfig(data=8),
                   _resnet_spec("cifar"), _resnet_step("cifar", 16),
                   opt_name="momentum"),
    AnalysisConfig("resnet_imagenet", MeshConfig(data=8),
                   _resnet_spec("imagenet"), _resnet_step("imagenet", 8),
                   opt_name="momentum"),
    AnalysisConfig("bert", MeshConfig(data=2, seq=2, model=2),
                   _bert_spec, _bert_step),
    AnalysisConfig("bert_accum", MeshConfig(data=4, seq=2),
                   _bert_spec, _bert_accum_step(False)),
    AnalysisConfig("bert_grad_shard", MeshConfig(data=4, seq=2),
                   _bert_spec, _bert_accum_step(True)),
    AnalysisConfig("widedeep", MeshConfig(data=4, model=2),
                   _widedeep_spec, _widedeep_step, opt_name="adam"),
    AnalysisConfig("gpt", MeshConfig(data=2, seq=2, model=2),
                   _gpt_spec(), _gpt_step(),
                   # the shared GPT rulebook carries the MoE expert rule;
                   # dense flagship has no expert params.
                   allow_dead=(r"w_(in|out)$",)),
    AnalysisConfig("gpt_overlap", MeshConfig(data=2, seq=2, model=2),
                   _gpt_spec(tp_overlap=True), _gpt_step(tp_overlap=True),
                   # --tp_overlap: the fence pins the intended collective
                   # swap — TP-layer all-gather/reduce-scatter traffic
                   # becomes collective-permute rings (docs/OVERLAP.md).
                   allow_dead=(r"w_(in|out)$",)),
    AnalysisConfig("gpt_overlap_q8",
                   MeshConfig(data=2, seq=2, model=2),
                   _gpt_spec(tp_overlap=True, matmul_precision="int8"),
                   _gpt_step(tp_overlap=True, matmul_precision="int8"),
                   # quantized-operand rings (ISSUE 17): same ppermute
                   # collectives as gpt_overlap, but each FORWARD ring
                   # hop carries the int8 payload + f32 scale sideband
                   # instead of the full-width tensor — the fence pins
                   # the byte shrink exactly (backward rings stay
                   # full-precision: master weights). docs/TUNING.md.
                   allow_dead=(r"w_(in|out)$",)),
    AnalysisConfig("gpt_moe", MeshConfig(data=4, expert=2),
                   _gpt_spec(moe_every=2), _gpt_step(moe_every=2)),
    AnalysisConfig("gpt_serve", MeshConfig(data=4, model=2),
                   _gpt_spec(), _gpt_serve_step,
                   # decode-mode config: the step is the serving engine's
                   # decode_all, not a train step (dtf_tpu/serve).
                   allow_dead=(r"w_(in|out)$",),
                   fit_serve_cfg=_gpt_real_cfg()),
    AnalysisConfig("gpt_serve_int8", MeshConfig(data=4, model=2),
                   _gpt_spec(), _gpt_serve_int8_step,
                   # the quantized-KV serving decode graph (same mesh,
                   # same spec view — params don't quantize).
                   allow_dead=(r"w_(in|out)$",),
                   fit_serve_cfg=_gpt_real_cfg(kv_cache_dtype="int8")),
    AnalysisConfig("gpt_eval", MeshConfig(data=2, seq=2, model=2),
                   _gpt_spec(), _gpt_eval_step,
                   # the launcher's eval program at the training mesh —
                   # whole-inventory fence: every AOT program rides the
                   # golden, not just train steps (ISSUE 7).
                   allow_dead=(r"w_(in|out)$",)),
    AnalysisConfig("gpt_prefill", MeshConfig(data=4, model=2),
                   _gpt_spec(), _gpt_prefill_step,
                   # the serving ADMISSION path (prefill_into_slot) at
                   # the gpt_serve mesh — the engine's other AOT program.
                   allow_dead=(r"w_(in|out)$",),
                   fit_serve_cfg=_gpt_real_cfg()),
    AnalysisConfig("gpt_pages", MeshConfig(data=4, model=2),
                   _gpt_spec(), _gpt_pages_step,
                   # the prefix-page-cache load/save programs (PR 6) —
                   # one admission tick, fenced like any other program.
                   allow_dead=(r"w_(in|out)$",),
                   fit_serve_cfg=_gpt_real_cfg()),
    AnalysisConfig("gpt_serve_spec", MeshConfig(data=4, model=2),
                   _gpt_spec(), _gpt_serve_spec_step,
                   # the speculative tick (draft_all ∘ verify, ISSUE 13)
                   # at the gpt_serve mesh; fit prices "max slots with
                   # spec on" from the real draft config.
                   allow_dead=(r"w_(in|out)$",),
                   fit_serve_cfg=_gpt_real_cfg(),
                   fit_draft_cfg=_gpt_draft_real_cfg),
    AnalysisConfig("gpt_serve_disagg", MeshConfig(data=4, model=2),
                   _gpt_spec(), _gpt_serve_disagg_step,
                   # the disaggregated prefill-replica admission tick
                   # (prefill ∘ page_save — the KV-transport composition).
                   allow_dead=(r"w_(in|out)$",),
                   fit_serve_cfg=_gpt_real_cfg()),
    AnalysisConfig("gpt_pipe", MeshConfig(data=4, pipe=2),
                   _gpt_pipe_spec, _gpt_pipe_step("gpipe"),
                   # embed/head ride ZeRO-1 over data, not the pipe axis
                   # (gpt_pipe.pipe_rules docstring).
                   replicated_ok=(r"^embed/", r"^head/")),
    AnalysisConfig("gpt_pipe_1f1b", MeshConfig(data=4, pipe=2),
                   _gpt_pipe_spec, _gpt_pipe_step("1f1b"),
                   replicated_ok=(r"^embed/", r"^head/")),
    AnalysisConfig("gpt_pipe_zb", MeshConfig(data=4, pipe=2),
                   _gpt_pipe_spec, _gpt_pipe_step("zb"),
                   # same layout contract as gpt_pipe_1f1b: ZB only
                   # re-orders the backward (B now, W deferred into the
                   # bubble) — embed/head stay ZeRO-1 over data.
                   replicated_ok=(r"^embed/", r"^head/")),
    AnalysisConfig("gpt_pipe_tp", MeshConfig(data=2, pipe=2, model=2),
                   _gpt_pipe_tp_spec, _gpt_pipe_tp_step,
                   replicated_ok=(r"^embed/", r"^head/")),
)

BY_NAME = {c.name: c for c in REGISTRY}

#: every optimizer family ``cli/flags.py make_optimizer`` can emit — the
#: ZeRO-1 spec lint runs the whole set against every config's params.
OPTIMIZER_FAMILIES: dict[str, Callable[[], optax.GradientTransformation]] = {
    "sgd": lambda: optax.sgd(0.01),
    "momentum": lambda: optax.sgd(0.01, momentum=0.9, nesterov=True),
    "adam": lambda: optax.adam(1e-3),
    "adamw": lambda: optax.adamw(1e-3, weight_decay=1e-4),
    "lamb": lambda: optax.lamb(1e-3, weight_decay=1e-4),
    "adafactor": lambda: optax.adafactor(1e-3),
}
