"""Static sharding & collectives analyzer.

Validates the framework's parallelism configuration WITHOUT touching a
device, so a wrong regex rule, a mesh-indivisible dimension, or an
XLA-inserted resharding all-gather fails in tier-1 in seconds instead of
burning a TPU window (the tunnel gives minutes of chip time per round —
PERF.md §0c).

Three passes, one CLI (``python -m dtf_tpu.analysis``):

- :mod:`dtf_tpu.analysis.specs` — rulebook linting: dead/shadowed regex
  rules, duplicate mesh axes in one spec, rank overflow, mesh-indivisible
  dims, large leaves silently falling to REPLICATED, and the same checks on
  every optimizer family's ZeRO-1 state specs.
- :mod:`dtf_tpu.analysis.hlo` — AOT-compile the real pjit train step on the
  8-device CPU sim, parse the optimized HLO, and fence the collective mix
  (counts + bytes) against the committed ``STATIC_ANALYSIS.json`` golden.
- :mod:`dtf_tpu.analysis.jaxpr` — trace-level lints: float64 leaks, host
  callbacks inside the step, axis collectives outside ``shard_map``.
- :mod:`dtf_tpu.analysis.host` — host-plane soundness over the jax-free
  control plane (serve/fault/telemetry/data-stream/publish): lock
  discipline, signal-handler deadlock, atomic-write choke point, clock
  discipline (pure AST on :mod:`dtf_tpu.analysis.hostmodel`, no imports).

The config registry (:mod:`dtf_tpu.analysis.configs`) covers the five
BASELINE workloads plus the GPT flagship and the ``gpt_pipe*`` variants.
"""

from dtf_tpu.analysis.findings import Finding, severity_counts  # noqa: F401
