"""Collective soundness — a dataflow pass over ``shard_map`` bodies.

The jaxpr lints (``analysis/jaxpr.py``) stop at the shard_map boundary:
collectives inside are "the whole point" and stay unexamined. But the
hand-written collectives the framework now leans on — the ppermute rings
of ``ops/collective_matmul.py``, ring/zigzag/halo attention, the pipeline
schedules, ``core/comms.grad_reduce_scatter`` — are exactly where a
transposed ``perm`` entry or a forgotten ``psum`` over a contracted axis
compiles cleanly and trains silently wrong. This pass walks every
shard_map body in the traced step and verifies:

- ``ppermute-not-permutation`` — a ``perm`` with an out-of-range index, a
  duplicated destination (nondeterministic overwrite) or a duplicated
  source. Partial shifts (halo exchange, pipeline edges — unique pairs,
  edges falling off) are legal; duplicates never are.
- ``unknown-collective-axis`` — a collective bound over an axis name the
  enclosing shard_map's mesh does not carry (it would resolve against
  whatever axis happens to be in scope, never what the rulebook meant).
- ``unreduced-partial-escape`` — a shard_map output derived from math
  that contracted a SHARDED dimension (a per-shard partial sum) escaping
  while its out_spec claims the value complete over the contracted axis
  (the axis appears nowhere in the output's ``out_names`` — with
  ``check_vma=False`` nothing else ever checks that claim). ``psum`` /
  ``pmean`` / ``psum_scatter`` discharge the obligation; riding a
  ``ppermute`` over the axis also exempts (a partial on a ring is being
  hand-reduced — the ring schedule itself is covered by
  :func:`ring_soundness` plus the bitwise parity tests, which a static
  pass cannot replace), and so does escaping SHARDED over the axis
  (per-shard partials handed to an outer reducer, e.g. autodiff
  residuals re-entering the mirrored backward shard_map). A partial
  that claims replication with no collective over its axis is the
  train-silently-wrong class this check exists for.

Separately, :func:`ring_soundness` holds every registered custom_vjp ring
pair (``ops/collective_matmul.ring_inventory``) to the mirrored-ring
invariant: both sides bind only true ring permutations, and the backward
rides the forward's ring or its exact inverse — anything else breaks the
overlap-under-grad contract PR 2's collective matmul depends on.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from dtf_tpu.analysis.findings import Finding
from dtf_tpu.analysis.jaxpr import _sub_jaxprs

#: collectives that discharge a partial-sum obligation over their axes.
_REDUCING = frozenset({"psum", "pmean", "psum_scatter", "reduce_scatter"})

#: collectives whose axis names must exist in the enclosing mesh.
_AXIS_COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "ppermute", "pbroadcast", "pgather",
    "all_gather", "all_to_all", "psum_scatter", "reduce_scatter",
})

#: primitives through which per-dim sharding tracking survives untouched.
_DIM_PRESERVING = frozenset({
    "convert_element_type", "copy", "integer_pow", "exp", "log", "tanh",
    "sqrt", "rsqrt", "neg", "sign", "abs", "floor", "ceil", "round",
    "is_finite", "logistic", "erf", "sin", "cos", "stop_gradient",
    "slice", "rev", "reduce_precision", "clamp",
})

#: binary/n-ary elementwise primitives (same-shape merge of records).
_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "max", "min", "pow", "rem", "atan2",
    "and", "or", "xor", "eq", "ne", "lt", "le", "gt", "ge", "select_n",
    "nextafter", "add_any",
})

#: reduction primitives (params['axes'] = reduced positional dims).
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_prod", "reduce_max", "reduce_min", "reduce_and",
    "reduce_or", "argmax", "argmin",
})


class _Rec(NamedTuple):
    """Abstract value: per-dim mesh axes (how this dim is sharded), the
    axes over which the value is an unreduced partial sum, and the axes
    whose ppermutes the value's ancestry has ridden."""

    dims: tuple          # tuple[frozenset[str], ...] aligned to rank
    partial: frozenset   # axes needing a reduction before escape
    ringed: frozenset    # axes whose ring the value has ridden

    @staticmethod
    def empty(rank: int = 0) -> "_Rec":
        return _Rec((frozenset(),) * rank, frozenset(), frozenset())


def _rank(var) -> int:
    return len(getattr(getattr(var, "aval", None), "shape", ()))


def _axes_of(params: dict) -> tuple[str, ...]:
    """Normalize a collective eqn's axis names to a flat tuple of strs."""
    raw = params.get("axes", params.get("axis_name", ()))
    if raw is None:
        return ()
    if isinstance(raw, str):
        return (raw,)
    out = []
    for a in (raw if isinstance(raw, (tuple, list)) else (raw,)):
        if isinstance(a, (tuple, list)):
            out.extend(str(x) for x in a)
        else:
            out.append(str(a))
    return tuple(out)


def _merge_dims(recs: list[_Rec], rank: int) -> tuple:
    dims = [frozenset()] * rank
    for r in recs:
        if len(r.dims) == rank:
            dims = [d | rd for d, rd in zip(dims, r.dims)]
    return tuple(dims)


def _union_rec(recs: list[_Rec], rank: int) -> _Rec:
    return _Rec(_merge_dims(recs, rank),
                frozenset().union(*[r.partial for r in recs])
                if recs else frozenset(),
                frozenset().union(*[r.ringed for r in recs])
                if recs else frozenset())


def _check_perm(perm, n: int | None) -> str | None:
    """None if ``perm`` is sound, else a one-line defect description.

    Duplicated destinations (nondeterministic overwrite), duplicated
    sources, and out-of-range indices are defects; a PARTIAL shift with
    unique pairs (halo exchange — edges fall off, receivers of nothing
    get zeros) is legal.
    """
    pairs = [tuple(int(x) for x in p) for p in perm]
    srcs = [s for s, _ in pairs]
    dsts = [d for _, d in pairs]
    if n is not None:
        bad = [p for p in pairs
               if not (0 <= p[0] < n and 0 <= p[1] < n)]
        if bad:
            return f"index out of range for axis size {n}: {bad}"
    if len(set(dsts)) != len(dsts):
        dup = sorted({d for d in dsts if dsts.count(d) > 1})
        return (f"duplicated destination(s) {dup} — nondeterministic "
                f"overwrite (two sends land on one device)")
    if len(set(srcs)) != len(srcs):
        dup = sorted({s for s in srcs if srcs.count(s) > 1})
        return (f"duplicated source(s) {dup} — a device sends twice while "
                f"another's data is dropped")
    return None


def _full_ring_defect(perm, n: int) -> str | None:
    """Ring-op contract: the perm must be a TRUE permutation of 0..n-1."""
    basic = _check_perm(perm, n)
    if basic is not None:
        return basic
    pairs = [tuple(int(x) for x in p) for p in perm]
    if (len(pairs) != n or {s for s, _ in pairs} != set(range(n))
            or {d for _, d in pairs} != set(range(n))):
        return (f"not a permutation of the full axis (size {n}): sources "
                f"{sorted({s for s, _ in pairs})}, destinations "
                f"{sorted({d for _, d in pairs})} — dropped sources read "
                f"garbage (zeros) on the ring")
    return None


# ---------------------------------------------------------------------------
# The dataflow interpreter over one shard_map body.
# ---------------------------------------------------------------------------

class _Interp:
    def __init__(self, axis_sizes: dict, report):
        self.axis_sizes = axis_sizes
        self.report = report     # report(check, key, detail)

    # -- record store ------------------------------------------------------
    def _read(self, env: dict, atom) -> _Rec:
        if not hasattr(atom, "aval") or isinstance(atom, jax.core.Literal):
            return _Rec.empty(_rank(atom))
        return env.get(id(atom), _Rec.empty(_rank(atom)))

    def run(self, jaxpr, in_recs: list[_Rec]) -> list[_Rec]:
        """Interpret ``jaxpr`` (an open Jaxpr); returns outvar records."""
        env: dict[int, _Rec] = {}
        for var in jaxpr.constvars:
            env[id(var)] = _Rec.empty(_rank(var))
        for var, rec in zip(jaxpr.invars, in_recs):
            env[id(var)] = rec
        for eqn in jaxpr.eqns:
            self._eqn(env, eqn)
        return [self._read(env, v) for v in jaxpr.outvars]

    # -- one equation ------------------------------------------------------
    def _eqn(self, env: dict, eqn) -> None:
        name = eqn.primitive.name
        ins = [self._read(env, v) for v in eqn.invars]
        out_rank = _rank(eqn.outvars[0]) if eqn.outvars else 0

        if name in _AXIS_COLLECTIVES:
            self._collective(env, eqn, ins)
            return
        if name == "dot_general":
            env[id(eqn.outvars[0])] = self._dot_general(eqn, ins)
            return
        if name in _REDUCE_PRIMS:
            axes = set(eqn.params.get("axes", ()))
            r = ins[0]
            partial = set(r.partial)
            if name in ("reduce_sum", "reduce_prod"):
                # summing a sharded dim locally creates a partial
                for d in axes:
                    if d < len(r.dims):
                        partial |= r.dims[d]
            dims = tuple(dv for d, dv in enumerate(r.dims)
                         if d not in axes)
            for ov in eqn.outvars:
                env[id(ov)] = _Rec(dims, frozenset(partial), r.ringed)
            return
        if name == "transpose":
            perm = eqn.params["permutation"]
            r = ins[0]
            dims = (tuple(r.dims[p] for p in perm)
                    if len(r.dims) == len(perm) else
                    (frozenset(),) * out_rank)
            env[id(eqn.outvars[0])] = _Rec(dims, r.partial, r.ringed)
            return
        if name == "broadcast_in_dim":
            r = ins[0]
            shape = eqn.params["shape"]
            bcast = eqn.params["broadcast_dimensions"]
            dims = [frozenset()] * len(shape)
            for i, d in enumerate(bcast):
                if i < len(r.dims):
                    dims[d] = r.dims[i]
            env[id(eqn.outvars[0])] = _Rec(tuple(dims), r.partial, r.ringed)
            return
        if name == "squeeze":
            r = ins[0]
            drop = set(eqn.params["dimensions"])
            dims = tuple(dv for d, dv in enumerate(r.dims) if d not in drop)
            env[id(eqn.outvars[0])] = _Rec(dims, r.partial, r.ringed)
            return
        if name == "concatenate":
            rec = _union_rec(ins, out_rank)
            env[id(eqn.outvars[0])] = rec
            return
        if name in _DIM_PRESERVING:
            r = ins[0] if ins else _Rec.empty(out_rank)
            rec = _Rec(r.dims if len(r.dims) == out_rank
                       else (frozenset(),) * out_rank,
                       frozenset().union(*[i.partial for i in ins])
                       if ins else frozenset(),
                       frozenset().union(*[i.ringed for i in ins])
                       if ins else frozenset())
            for ov in eqn.outvars:
                env[id(ov)] = rec
            return
        if name in _ELEMENTWISE or name in ("dynamic_update_slice",
                                            "dynamic_slice"):
            arr = [r for r, v in zip(ins, eqn.invars)
                   if _rank(v) == out_rank] or ins
            rec = _union_rec(arr, out_rank)
            for ov in eqn.outvars:
                env[id(ov)] = rec
            return
        if name == "scan":
            self._scan(env, eqn, ins)
            return
        if name == "while":
            self._while(env, eqn, ins)
            return
        if name == "cond":
            self._cond(env, eqn, ins)
            return
        if name in ("pjit", "closed_call", "core_call", "remat",
                    "checkpoint", "custom_jvp_call", "custom_vjp_call",
                    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr"):
            sub = self._one_sub(eqn)
            if sub is not None and len(sub.invars) == len(ins):
                outs = self.run(sub, ins)
                for ov, rec in zip(eqn.outvars, outs):
                    env[id(ov)] = rec
                return
        # opaque fallback (pallas_call, gather/scatter, rng, unknown):
        # dims tracking is lost, partial/ringed propagate conservatively.
        self._opaque(env, eqn, ins)

    def _opaque(self, env: dict, eqn, ins: list[_Rec]) -> None:
        partial = (frozenset().union(*[r.partial for r in ins])
                   if ins else frozenset())
        ringed = (frozenset().union(*[r.ringed for r in ins])
                  if ins else frozenset())
        for ov in eqn.outvars:
            env[id(ov)] = _Rec((frozenset(),) * _rank(ov), partial, ringed)
        # sub-jaxprs of unhandled higher-order prims may still bind
        # collectives: a ppermute there must credit `ringed`, a psum must
        # discharge — approximate both by scanning for collective names.
        for sub in _sub_jaxprs(eqn):
            red, rung = _collectives_in(sub)
            if rung or red:
                for ov in eqn.outvars:
                    r = env[id(ov)]
                    env[id(ov)] = _Rec(r.dims, r.partial - red,
                                       r.ringed | rung)

    # -- collectives -------------------------------------------------------
    def _collective(self, env: dict, eqn, ins: list[_Rec]) -> None:
        name = eqn.primitive.name
        axes = _axes_of(eqn.params)
        unknown = [a for a in axes if a not in self.axis_sizes]
        if unknown:
            self.report(
                "unknown-collective-axis", f"{name}:{unknown}",
                f"{name} bound over axis {unknown} but the enclosing "
                f"shard_map mesh carries only "
                f"{sorted(self.axis_sizes)} — it would resolve against "
                f"whatever axis is in scope, never what the rulebook "
                f"meant")
        if name == "ppermute":
            sizes = [self.axis_sizes.get(a) for a in axes]
            n = None
            if all(s is not None for s in sizes):
                n = int(np.prod(sizes)) if sizes else None
            defect = _check_perm(eqn.params.get("perm", ()), n)
            if defect:
                self.report("ppermute-not-permutation",
                            f"{axes}:{eqn.params.get('perm')}",
                            f"ppermute over {axes}: {defect}")
        for iv, ov in zip(eqn.invars, eqn.outvars):
            r = self._read(env, iv)
            partial, ringed = r.partial, r.ringed
            if name in _REDUCING:
                partial = partial - set(axes)
            if name == "ppermute":
                ringed = ringed | set(axes)
            dims = r.dims
            if name in ("psum_scatter", "reduce_scatter"):
                d = eqn.params.get("scatter_dimension", 0)
                if d < len(dims):
                    dims = tuple(dv | set(axes) if i == d else dv
                                 for i, dv in enumerate(dims))
            elif name == "all_gather":
                dims = tuple(dv - set(axes) for dv in dims)
            elif name == "all_to_all":
                # all_to_all retargets the sharded dim (split_axis →
                # concat_axis); modelling that reliably across jax
                # spellings isn't worth it — drop dim tracking, which
                # can only lose findings (quiet), never invent one.
                dims = (frozenset(),) * _rank(ov)
            if len(dims) != _rank(ov):
                dims = (frozenset(),) * _rank(ov)
            env[id(ov)] = _Rec(dims, partial, ringed)
        # n-ary collectives with a single output (psum of a tree zips;
        # leftover outvars — be safe)
        for ov in eqn.outvars[len(eqn.invars):]:
            env[id(ov)] = _union_rec(ins, _rank(ov))

    def _dot_general(self, eqn, ins: list[_Rec]) -> _Rec:
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs, rhs = ins[0], ins[1]
        contracted = frozenset()
        for d in lc:
            if d < len(lhs.dims):
                contracted |= lhs.dims[d]
        for d in rc:
            if d < len(rhs.dims):
                contracted |= rhs.dims[d]
        l_free = [d for d in range(len(lhs.dims))
                  if d not in lc and d not in lb]
        r_free = [d for d in range(len(rhs.dims))
                  if d not in rc and d not in rb]
        dims = ([lhs.dims[b] | (rhs.dims[b2] if b2 < len(rhs.dims)
                                else frozenset())
                 for b, b2 in zip(lb, rb)]
                + [lhs.dims[d] for d in l_free]
                + [rhs.dims[d] for d in r_free])
        return _Rec(tuple(dims),
                    lhs.partial | rhs.partial | contracted,
                    lhs.ringed | rhs.ringed)

    # -- higher-order ------------------------------------------------------
    def _one_sub(self, eqn):
        subs = list(_sub_jaxprs(eqn))
        return subs[0] if len(subs) == 1 else None

    def _scan(self, env: dict, eqn, ins: list[_Rec]) -> None:
        sub = self._one_sub(eqn)
        nc = eqn.params.get("num_consts", 0)
        nk = eqn.params.get("num_carry", 0)
        if sub is None or len(sub.invars) != len(ins):
            self._opaque(env, eqn, ins)
            return
        # xs operands are sliced along the leading axis inside the body
        body_in = list(ins[:nc + nk])
        for r in ins[nc + nk:]:
            body_in.append(_Rec(r.dims[1:], r.partial, r.ringed))
        # two rounds: a partial/ring arising mid-scan rides the carry back
        outs = self.run(sub, body_in)
        carry = [_union_rec([a, b], len(a.dims))
                 for a, b in zip(body_in[nc:nc + nk], outs[:nk])]
        outs = self.run(sub, body_in[:nc] + carry + body_in[nc + nk:])
        for ov, rec in zip(eqn.outvars[:nk], outs[:nk]):
            env[id(ov)] = rec
        for ov, rec in zip(eqn.outvars[nk:], outs[nk:]):
            env[id(ov)] = _Rec((frozenset(),) + rec.dims, rec.partial,
                               rec.ringed)

    def _while(self, env: dict, eqn, ins: list[_Rec]) -> None:
        body = eqn.params.get("body_jaxpr")
        body = getattr(body, "jaxpr", body)
        nb = eqn.params.get("body_nconsts", 0)
        nc = eqn.params.get("cond_nconsts", 0)
        carry = ins[nc + nb:]
        if body is None or len(body.invars) != nb + len(carry):
            self._opaque(env, eqn, ins)
            return
        consts = ins[nc:nc + nb]
        outs = self.run(body, consts + carry)
        carry2 = [_union_rec([a, b], len(a.dims))
                  for a, b in zip(carry, outs)]
        outs = self.run(body, consts + carry2)
        for ov, rec in zip(eqn.outvars, outs):
            env[id(ov)] = rec

    def _cond(self, env: dict, eqn, ins: list[_Rec]) -> None:
        branches = eqn.params.get("branches", ())
        ops = ins[1:]
        per_branch = []
        for br in branches:
            sub = getattr(br, "jaxpr", br)
            if len(sub.invars) != len(ops):
                self._opaque(env, eqn, ins)
                return
            per_branch.append(self.run(sub, ops))
        for i, ov in enumerate(eqn.outvars):
            recs = [b[i] for b in per_branch]
            env[id(ov)] = _union_rec(recs, _rank(ov))


def _collectives_in(jaxpr) -> tuple[frozenset, frozenset]:
    """(axes reduced over, axes ppermuted over) anywhere in a jaxpr."""
    red, rung = set(), set()
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _REDUCING:
            red.update(_axes_of(eqn.params))
        elif name == "ppermute":
            rung.update(_axes_of(eqn.params))
        for sub in _sub_jaxprs(eqn):
            r2, g2 = _collectives_in(sub)
            red.update(r2)
            rung.update(g2)
    return frozenset(red), frozenset(rung)


# ---------------------------------------------------------------------------
# Entry points.
# ---------------------------------------------------------------------------

def _iter_shard_maps(jaxpr):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "shard_map":
            yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from _iter_shard_maps(sub)


def lint_collectives(closed_jaxpr, *, config: str) -> list[Finding]:
    """All shard_map-body soundness checks over one traced step."""
    findings: list[Finding] = []
    seen: set[tuple[str, str]] = set()

    def report(check: str, key: str, detail: str):
        if (check, key) in seen:
            return
        seen.add((check, key))
        findings.append(Finding(config, "collective", check, "error",
                                detail))

    for eqn in _iter_shard_maps(closed_jaxpr.jaxpr):
        mesh = eqn.params.get("mesh")
        axis_sizes = dict(getattr(mesh, "shape", {}) or {})
        body = eqn.params.get("jaxpr")
        body = getattr(body, "jaxpr", body)
        if body is None or not axis_sizes:
            continue
        in_names = eqn.params.get("in_names")
        in_recs = []
        for i, var in enumerate(body.invars):
            rank = _rank(var)
            dims = [frozenset()] * rank
            if in_names is not None and i < len(in_names):
                for d, names in dict(in_names[i]).items():
                    if d < rank:
                        dims[d] = frozenset(
                            str(n) for n in (names if isinstance(
                                names, (tuple, list)) else (names,)))
            in_recs.append(_Rec(tuple(dims), frozenset(), frozenset()))
        out_names = eqn.params.get("out_names")
        interp = _Interp(axis_sizes, report)
        outs = interp.run(body, in_recs)
        for i, rec in enumerate(outs):
            out_axes: set = set()
            if out_names is not None and i < len(out_names):
                for names in dict(out_names[i]).values():
                    out_axes.update(
                        str(n) for n in (names if isinstance(
                            names, (tuple, list)) else (names,)))
            offending = rec.partial - rec.ringed - out_axes
            if offending:
                report(
                    "unreduced-partial-escape", f"out{i}:{sorted(offending)}",
                    f"shard_map output #{i} contracted dimension(s) "
                    f"sharded over {sorted(offending)} but escapes "
                    f"claiming replication over that axis, with no "
                    f"psum/psum_scatter (and no ring) on the way out — "
                    f"each shard returns its local partial sum")
    return findings


# ---------------------------------------------------------------------------
# Mirrored-ring soundness over the registered custom_vjp ring pairs.
# ---------------------------------------------------------------------------

def _perms_in(jaxpr) -> set:
    perms = set()
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "ppermute":
            perms.add(tuple(sorted(tuple(int(x) for x in p)
                                   for p in eqn.params["perm"])))
        for sub in _sub_jaxprs(eqn):
            perms.update(_perms_in(sub))
    return perms


def _inverse(perm: tuple) -> tuple:
    return tuple(sorted((d, s) for s, d in perm))


def _trace_ringed(fn, axis: str, n: int, args) -> set:
    """Trace ``fn(axis, *args)`` under a size-``n`` shard_map (abstract,
    replicated per-shard args — trace only, never executed) and return
    the set of ppermute perms it binds."""
    mesh = Mesh(np.array(jax.devices()[:n]), (axis,))
    wrapped = jax.shard_map(functools.partial(fn, axis), mesh=mesh,
                            in_specs=P(), out_specs=P(), check_vma=False)
    closed = jax.make_jaxpr(lambda *a: wrapped(*a))(*args)
    return _perms_in(closed.jaxpr)


def ring_soundness(ops=None, *, axis_sizes=(2, 4),
                   config: str = "collective_matmul") -> list[Finding]:
    """The mirrored-ring fence over ``ring_inventory()`` (or an explicit
    op list, for tests): every perm either side binds must be a TRUE
    permutation of the full axis, and the backward's rings must each be
    the forward ring or its exact inverse. A backward that binds no ring
    while the forward does has fallen back to blocking collectives — the
    overlap the custom_vjp exists to preserve is silently gone."""
    if ops is None:
        from dtf_tpu.ops import collective_matmul as cm

        ops = cm.ring_inventory()
    findings: list[Finding] = []
    axis = "ring"
    usable = [n for n in axis_sizes if n <= len(jax.devices())]
    for op in ops:
        for n in usable:
            fwd = _trace_ringed(op.fwd, axis, n, op.fwd_args(n))
            bwd = _trace_ringed(op.bwd, axis, n, op.bwd_args(n))
            for side, perms in (("forward", fwd), ("backward", bwd)):
                for p in perms:
                    defect = _full_ring_defect(p, n)
                    if defect:
                        findings.append(Finding(
                            config, "collective", "ppermute-not-permutation",
                            "error",
                            f"{op.name} {side} ring at axis size {n}: "
                            f"{defect}"))
            legal = fwd | {_inverse(p) for p in fwd}
            rogue = [p for p in bwd if p not in legal]
            if rogue:
                findings.append(Finding(
                    config, "collective", "ring-not-mirrored", "error",
                    f"{op.name} backward at axis size {n} binds ring(s) "
                    f"{sorted(rogue)} that are neither the forward ring "
                    f"nor its inverse {sorted(legal)} — the mirrored-ring "
                    f"invariant (overlap surviving grad) is broken"))
            if fwd and not bwd:
                findings.append(Finding(
                    config, "collective", "ring-not-mirrored", "error",
                    f"{op.name} backward at axis size {n} binds NO ring "
                    f"while the forward does — grad fell back to blocking "
                    f"collectives; the custom_vjp mirror is gone"))
    return findings
