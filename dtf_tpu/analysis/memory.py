"""Static HBM accounting — the "memory" pass and the fit planner.

The comms fence (analysis/hlo.py) proves every AOT program's collective
mix sound, but until this pass only ONE memory number was pinned
(``temp_size_in_bytes``).  The dominant failure mode left for chip time
was the silent one: a config that OOMs a 16 GiB v5e, or a donated train
state whose aliasing XLA quietly dropped (the PR 1 BN-stats-freeze
class).  This pass closes both holes on the CPU sim:

- **breakdown fence** (:func:`check_memory`): the full per-program HBM
  breakdown from AOT ``memory_analysis()`` — argument/output/temp/
  generated-code/alias bytes, recorded per budget in
  ``STATIC_ANALYSIS.json`` and fenced fail-closed per FIELD with the
  same ``--diff``/``--write-golden`` idiom as the comms budgets.
- **resident-state model** (:func:`resident_bytes` /
  :func:`state_accounting`): an analytic per-device pricing of every
  program argument — params + optimizer moments + KV/page pools —
  built from the registry's DECLARED shardings (the same introspection
  hooks the launchers use: ``train.abstract_train_state``,
  ``sharding.zero1_opt_specs``, ``serve.pages.pool_abstract``) and
  cross-checked against the compiled executable's argument bytes and
  per-leaf committed shardings.  A leaf that silently changed dtype or
  replication (a dropped ``in_shardings`` entry, a spec change XLA
  answers with replication) is a ``state-accounting-drift`` finding
  naming the leaf, not an 8x-bigger argument buffer discovered on chip.
- **donation soundness** (:func:`donation_soundness`): for every
  program lowered with donated arguments, each donated-and-kept leaf
  must be aliased to an output in the executable
  (``input_output_alias`` in the optimized HLO header) — a donation
  XLA dropped is a ``dropped-donation`` finding.  This turns the BN
  freeze from a bisected runtime mystery into a CPU-sim lint;
  :func:`donation_gate` additionally asserts (rather than assumes) the
  ``_jax_compat.BACKFILLED`` gate in ``core/train.py``: registry
  programs must donate NOTHING on backfilled jax.
- **fit planner** (:func:`fit`): inverts the resident model under a
  per-chip HBM budget — max KV slots and page-pool size for serve
  configs (bf16 AND int8 KV, real-scale ``eval_shape`` pricing, no
  compile), max global batch for train configs (analytic resident +
  a measured affine temp-vs-batch model from two tiny AOT compiles).
  ``python -m dtf_tpu.analysis fit --config=gpt_serve --hbm-gb=16``.

Everything here runs on the 8-device CPU sim; nothing needs a chip.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Mapping, Optional, Sequence

import numpy as np

from dtf_tpu.analysis.findings import Finding

PyTree = Any

#: memory_analysis() fields recorded in every budget and fenced per field.
MEMORY_FIELDS = (
    ("temp_bytes", "temp_size_in_bytes"),
    ("arg_bytes", "argument_size_in_bytes"),
    ("out_bytes", "output_size_in_bytes"),
    ("alias_bytes", "alias_size_in_bytes"),
    ("gen_code_bytes", "generated_code_size_in_bytes"),
)

#: aggregate state-accounting tolerance: XLA pads/alignments and scalar
#: bookkeeping the analytic model doesn't price.  Anything beyond this is
#: a leaf-level dtype/replication change, which is exactly the finding.
ACCOUNTING_REL_TOL = 0.02
ACCOUNTING_ABS_TOL = 4096


def fmt_bytes(n: int) -> str:
    """453K / 1.2M style — the per-field drift findings' spelling."""
    n = int(n)
    for unit, div in (("G", 1 << 30), ("M", 1 << 20), ("K", 1 << 10)):
        if abs(n) >= div:
            v = n / div
            return f"{v:.1f}{unit}" if abs(v) < 10 else f"{v:.0f}{unit}"
    return str(n)


# ---------------------------------------------------------------------------
# Per-device pricing arithmetic (deliberately NOT jax's shard_shape — the
# model must be an independent accounting the compiled side can contradict).
# ---------------------------------------------------------------------------

def _spec_device_bytes(shape: Sequence[int], dtype, spec,
                       mesh_shape: Mapping[str, int]) -> int:
    """THE pricing arithmetic: per-device bytes of one array under a
    PartitionSpec — each sharded dim ceil-divided by the product of its
    mesh axes (XLA pads ragged shards up; axes missing from the mesh
    count as size 1), unsharded dims at full extent.  Shared by the
    fence-side :func:`leaf_device_bytes` and the fit planner's
    :func:`_price_spec_tree` so the two cannot drift apart."""
    dims = [int(d) for d in shape]
    for i, entry in enumerate(spec):
        if entry is None or i >= len(dims):
            continue
        names = (entry,) if isinstance(entry, str) else tuple(entry)
        k = 1
        for n in names:
            k *= int(mesh_shape.get(n, 1))
        dims[i] = -(-dims[i] // k)
    n = 1
    for d in dims:
        n *= d
    return n * np.dtype(dtype).itemsize


def leaf_device_bytes(shape: Sequence[int], dtype, sharding=None) -> int:
    """Per-device bytes of one array leaf under a NamedSharding
    (replicated leaves — ``sharding=None`` — cost their full extent on
    every device)."""
    if sharding is None or getattr(sharding, "spec", None) is None:
        return _spec_device_bytes(shape, dtype, (), {})
    return _spec_device_bytes(shape, dtype, sharding.spec,
                              dict(sharding.mesh.shape))


def tree_device_bytes(tree: PyTree, shardings: PyTree = None) -> int:
    """Summed per-device bytes of a ShapeDtypeStruct tree.

    ``shardings``: an optional matching tree of NamedShardings (or ONE
    NamedSharding broadcast over every leaf — jit's prefix-spec
    convention); without it each leaf's own ``.sharding`` is used, and a
    leaf with neither is priced replicated (its full extent).
    """
    import jax

    leaves = jax.tree.leaves(tree)
    shs = _broadcast_shardings(shardings, len(leaves), tree)
    total = 0
    for leaf, sh in zip(leaves, shs):
        if sh is None:
            sh = getattr(leaf, "sharding", None)
        total += leaf_device_bytes(leaf.shape, leaf.dtype, sh)
    return total


def _broadcast_shardings(shardings, n_leaves: int, tree) -> list:
    """Resolve a shardings argument to one entry per leaf of ``tree``."""
    import jax

    if shardings is None:
        return [None] * n_leaves
    if not isinstance(shardings, (list, tuple, dict)) and not hasattr(
            shardings, "tree_flatten"):
        # a bare sharding object: jit broadcasts it over the subtree
        if hasattr(shardings, "spec"):
            return [shardings] * n_leaves
    flat = jax.tree.leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec") or x is None)
    if len(flat) != n_leaves:
        raise ValueError(
            f"shardings tree has {len(flat)} leaves for a {n_leaves}-leaf "
            f"value tree")
    return flat


def _flat_declared(view) -> tuple[list, list, list]:
    """``(paths, leaves, shardings)`` for the program args
    ``(state, batch)``.

    Declared shardings come from ``view.arg_shardings`` (the in_shardings
    the builder passed to jit) when present, else from each abstract
    leaf's own ``.sharding`` (the serve views embed them), else None —
    the caller prices such leaves at the executable's committed sharding
    (no independent claim to check).
    """
    import jax

    flat = jax.tree_util.tree_flatten_with_path((view.state, view.batch))[0]
    paths = ["/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                      for k in path) for path, _ in flat]
    leaves = [leaf for _, leaf in flat]
    decl: list = [getattr(leaf, "sharding", None) for leaf in leaves]
    arg_sh = getattr(view, "arg_shardings", None)
    if arg_sh is not None:
        n_state = len(jax.tree.leaves(view.state))
        state_sh = _broadcast_shardings(arg_sh[0], n_state, view.state)
        batch_sh = _broadcast_shardings(arg_sh[1], len(leaves) - n_state,
                                        view.batch)
        decl = state_sh + batch_sh
    return paths, leaves, decl


# ---------------------------------------------------------------------------
# (a) the breakdown fence
# ---------------------------------------------------------------------------

def memory_breakdown(compiled) -> Optional[dict]:
    """The fenced ``memory_analysis()`` fields of one compiled program,
    or None on a backend without an allocator report (the golden check
    then fails closed — see :func:`check_memory`)."""
    try:
        mem = compiled.memory_analysis()
        return {name: int(getattr(mem, attr)) for name, attr in MEMORY_FIELDS}
    except Exception:  # noqa: BLE001 — backends without an allocator report
        return None


def hbm_peak_bytes(mem: Mapping[str, int]) -> int:
    """The planner's peak-resident estimate for one program: arguments +
    outputs + peak temps + generated code, minus donated (aliased) output
    bytes that reuse argument buffers."""
    return (mem.get("arg_bytes", 0) + mem.get("out_bytes", 0)
            + mem.get("temp_bytes", 0) + mem.get("gen_code_bytes", 0)
            - mem.get("alias_bytes", 0))


def memory_delta(got: Mapping[str, int] | None,
                 want: Mapping[str, int] | None) -> list[str]:
    """Per-field human-readable delta lines (``--diff``); [] when clean."""
    got, want = got or {}, want or {}
    lines = []
    for field in sorted(set(got) | set(want)):
        g, w = got.get(field), want.get(field)
        if g != w:
            lines.append(
                f"memory {field} {fmt_bytes(w) if w is not None else '?'}"
                f"→{fmt_bytes(g) if g is not None else '?'} "
                f"[{w}→{g}]")
    return lines


def check_memory(got: Mapping[str, int] | None,
                 want: Mapping[str, int] | None, *,
                 config: str) -> list[Finding]:
    """Exact per-field fence against the golden's memory breakdown.

    Fails CLOSED: a golden that pins memory numbers while the backend
    reports none means the fence did not run — that is a finding, not a
    skip (otherwise a later ``--write-golden`` would silently drop the
    memory entries and nobody would notice the fence died).
    """
    if want is None:
        return []
    if got is None:
        return [Finding(
            config, "memory", "memory-unavailable", "error",
            "golden pins a memory breakdown but memory_analysis() "
            "reported nothing on this backend — the HBM fence did not "
            "run")]
    findings = []
    for field in sorted(set(want) | set(got)):
        g, w = got.get(field), want.get(field)
        if g != w:
            findings.append(Finding(
                config, "memory", "memory-bytes-drift", "error",
                f"{field} {fmt_bytes(w or 0)}→{fmt_bytes(g or 0)} "
                f"({(g or 0) - (w or 0):+,} B vs golden; accumulators / "
                f"stashes / argument layouts moved — regenerate with "
                f"--write-golden if intended)"))
    return findings


# ---------------------------------------------------------------------------
# (b) resident-state model + cross-check
# ---------------------------------------------------------------------------

def resident_bytes(view) -> dict:
    """Analytic per-device pricing of one program's arguments.

    ``{"state_bytes", "batch_bytes", "total_bytes"}`` — the declared
    cost of everything resident across calls (state: params, moments,
    KV pools) plus the per-call batch, each leaf priced at its DECLARED
    sharding via :func:`leaf_device_bytes`.
    """
    import jax

    _, leaves, decl = _flat_declared(view)
    n_state = len(jax.tree.leaves(view.state))
    state = sum(leaf_device_bytes(lf.shape, lf.dtype, sh)
                for lf, sh in zip(leaves[:n_state], decl[:n_state]))
    batch = sum(leaf_device_bytes(lf.shape, lf.dtype, sh)
                for lf, sh in zip(leaves[n_state:], decl[n_state:]))
    return {"state_bytes": state, "batch_bytes": batch,
            "total_bytes": state + batch}


def _committed_flat(compiled) -> Optional[list]:
    """Flat per-arg committed shardings from the executable (None entries
    = the leaf was pruned out of the compiled program), or None when the
    surface is unavailable on this jax."""
    import jax

    try:
        args_sh = compiled.input_shardings[0]
    except Exception:  # noqa: BLE001 — older stages without the property
        return None
    return jax.tree.leaves(
        args_sh, is_leaf=lambda x: x is None or hasattr(x, "spec"))


def state_accounting(config_name: str, view, compiled, *,
                     rel_tol: float = ACCOUNTING_REL_TOL,
                     abs_tol: int = ACCOUNTING_ABS_TOL) -> list[Finding]:
    """Cross-check the analytic model against the compiled executable.

    Two layers:

    - per-leaf: every KEPT argument's committed sharding must price to
      the same per-device bytes as its declared sharding — a leaf the
      partitioner answered with replication (or whose declared dtype no
      longer matches what the builder constructs) is named directly.
    - aggregate: the summed model (kept leaves only — jit prunes unused
      args, e.g. the eval program drops ``opt_state``) must match
      ``memory_analysis().argument_size_in_bytes`` within tolerance.
    """
    findings: list[Finding] = []
    mem = memory_breakdown(compiled)
    committed = _committed_flat(compiled)
    paths, leaves, decl = _flat_declared(view)
    if committed is not None and len(committed) != len(leaves):
        return [Finding(
            config_name, "memory", "state-accounting-drift", "error",
            f"executable reports {len(committed)} argument leaves, the "
            f"declared state+batch has {len(leaves)} — the program and "
            f"the introspected state desynchronized")]

    model_kept = 0
    for i, leaf in enumerate(leaves):
        comm = committed[i] if committed is not None else None
        if committed is not None and comm is None:
            continue  # pruned: costs nothing in the executable
        d_sh = decl[i] if decl[i] is not None else comm
        d_bytes = leaf_device_bytes(leaf.shape, leaf.dtype, d_sh)
        model_kept += d_bytes
        if comm is not None and decl[i] is not None:
            c_bytes = leaf_device_bytes(leaf.shape, leaf.dtype, comm)
            if c_bytes != d_bytes:
                findings.append(Finding(
                    config_name, "memory", "state-accounting-drift",
                    "error",
                    f"{paths[i]}: declared {d_bytes:,} B/device "
                    f"(spec {getattr(d_sh, 'spec', None)}) but the "
                    f"executable committed {c_bytes:,} B/device "
                    f"(spec {getattr(comm, 'spec', None)}) — the leaf "
                    f"silently changed replication"))
    if mem is not None:
        got = mem["arg_bytes"]
        tol = max(abs_tol, int(rel_tol * max(model_kept, got)))
        if abs(got - model_kept) > tol:
            findings.append(Finding(
                config_name, "memory", "state-accounting-drift", "error",
                f"analytic resident model prices the kept arguments at "
                f"{model_kept:,} B/device but the executable allocates "
                f"{got:,} B/device (|Δ| > {tol:,} B) — a leaf silently "
                f"changed dtype or replication"))
    return findings


# ---------------------------------------------------------------------------
# (c) donation soundness
# ---------------------------------------------------------------------------

#: the module header's alias map: ``input_output_alias={ {0}: (2, {},
#: may-alias), ... }`` — each entry names the PARAMETER NUMBER an output
#: tuple index aliases.
_ALIAS_PARAM_RE = re.compile(r"\(\s*(\d+)\s*,\s*\{")


def aliased_param_numbers(hlo_text: str) -> set[int]:
    """Parameter numbers aliased to outputs in an optimized module."""
    head = hlo_text.split("\n", 1)[0]
    start = head.find("input_output_alias={")
    if start < 0:
        return set()
    # the attribute's map nests one {} per entry — cut at the matching
    # top-level close brace before scanning for `(N, {` param numbers.
    depth = 0
    end = len(head)
    for i in range(start + len("input_output_alias="), len(head)):
        if head[i] == "{":
            depth += 1
        elif head[i] == "}":
            depth -= 1
            if depth == 0:
                end = i
                break
    return {int(m) for m in _ALIAS_PARAM_RE.findall(head[start:end])}


def donated_flags(lowered) -> list[bool]:
    """Flat per-argument donation flags from ``lowered.args_info``."""
    import jax

    try:
        info = lowered.args_info
    except Exception:  # noqa: BLE001 — stages without args_info
        return []
    return [bool(getattr(a, "donated", False))
            for a in jax.tree.leaves(info)]


def donation_soundness(config_name: str, lowered, compiled,
                       *, arg_paths: Sequence[str] | None = None
                       ) -> list[Finding]:
    """Every donated-and-kept argument must be aliased to an output.

    A donated buffer XLA could not alias is deleted at dispatch while
    its contents go nowhere — exactly the class behind the warm-cache
    BN-stats freeze (donated executable deserialized without its
    aliasing).  Donated leaves jit PRUNED from the program are skipped:
    they never reach the runtime.
    """
    donated = donated_flags(lowered)
    if not any(donated):
        return []
    committed = _committed_flat(compiled)
    aliased = aliased_param_numbers(compiled.as_text())
    findings = []
    param = 0
    for i, d in enumerate(donated):
        kept = committed is None or committed[i] is not None
        if not kept:
            continue
        if d and param not in aliased:
            where = (arg_paths[i] if arg_paths and i < len(arg_paths)
                     else f"arg[{i}]")
            findings.append(Finding(
                config_name, "memory", "dropped-donation", "error",
                f"{where}: donated to the compiled program but aliased "
                f"to NO output (input_output_alias) — its buffer dies at "
                f"dispatch and the update silently vanishes (the "
                f"BN-stats-freeze class); drop the donation or alias the "
                f"leaf through"))
        param += 1
    return findings


def donation_gate(config_name: str, lowered) -> list[Finding]:
    """Assert the ``_jax_compat.BACKFILLED`` donation gate.

    On backfilled (pre-0.5) jax a donated executable deserialized from
    the persistent compile cache drops its aliasing (core/train.py
    version-gates donation off there).  A registry program that donates
    anyway means the gate was bypassed — the exact setup of the PR 1 BN
    freeze, caught here statically instead of by a warm-cache bisect.
    """
    from dtf_tpu import _jax_compat as _compat

    if not _compat.BACKFILLED:
        return []
    n = sum(donated_flags(lowered))
    if not n:
        return []
    return [Finding(
        config_name, "memory", "donation-on-backfilled-jax", "error",
        f"{n} argument leaf/leaves donated on BACKFILLED jax — the "
        f"core/train.py donation gate was bypassed; donated executables "
        f"deserialized from the persistent cache drop aliased outputs "
        f"here (tests/conftest.py note)")]


def lint_program(config, view, lowered, compiled,
                 golden_budget: Mapping[str, Any] | None,
                 budget: Mapping[str, Any] | None = None) -> list[Finding]:
    """The whole memory pass for one registry program."""
    got_mem = (budget or {}).get("memory") if budget is not None \
        else memory_breakdown(compiled)
    want_mem = (golden_budget or {}).get("memory")
    paths, _, _ = _flat_declared(view)
    findings = check_memory(got_mem, want_mem, config=config.name)
    findings += state_accounting(config.name, view, compiled)
    findings += donation_soundness(config.name, lowered, compiled,
                                   arg_paths=paths)
    findings += donation_gate(config.name, lowered)
    return findings


# ---------------------------------------------------------------------------
# temp-vs-scale affine model (shared by the fit planner and
# scripts/bench_pipe_mem.py's predicted_temp_bytes cross-check)
# ---------------------------------------------------------------------------

def affine_temp_model(points: Mapping[int, int]) -> tuple[float, float]:
    """Least-squares ``temp(n) = intercept + slope * n`` over measured
    ``{n: temp_bytes}`` points (two suffice: scan stashes grow linearly
    in the scanned count — microbatches, batch rows)."""
    if len(points) < 2:
        raise ValueError("need at least two (n, temp_bytes) points")
    xs = np.array(sorted(points), dtype=np.float64)
    ys = np.array([points[int(x)] for x in xs], dtype=np.float64)
    slope, intercept = np.polyfit(xs, ys, 1)
    return float(intercept), float(slope)


def predict_temp(model: tuple[float, float], n: int) -> int:
    intercept, slope = model
    return int(round(intercept + slope * n))


# ---------------------------------------------------------------------------
# (d) the fit planner
# ---------------------------------------------------------------------------

def _price_spec_tree(tree: PyTree, specs: PyTree, mesh) -> int:
    """Per-device bytes of an abstract tree under a PartitionSpec tree
    (axes missing from ``mesh`` count as size 1) — the same arithmetic
    as the fence side, via :func:`_spec_device_bytes`."""
    import jax
    from jax.sharding import PartitionSpec as P

    mesh_shape = dict(mesh.shape)
    total = 0

    def one(spec, leaf):
        nonlocal total
        total += _spec_device_bytes(leaf.shape, leaf.dtype, spec,
                                    mesh_shape)
        return spec

    jax.tree.map(one, specs, tree, is_leaf=lambda x: isinstance(x, P))
    return total


#: the low-precision storage widths ``fit --precision`` prices, straight
#: from the hlo.py bit-width table (s8 / f8e4m3fn are both 8 bits).
_PRECISION_BITS = {"int8": 8, "fp8": 8}


def _quant_params_bytes(tree: PyTree, specs: PyTree, mesh,
                        precision: str) -> int:
    """Per-device bytes of a param tree with every matrix leaf (ndim>=2)
    stored at ``precision`` width plus its per-channel f32 scale sideband
    (one scale per output channel — the ops/quant.py layout: quantize
    over the contraction axis 0, scale shape (1,) + shape[1:]). Vector
    leaves (biases, layernorm gains) stay at their own dtype — they are
    noise next to the matrices and the quant tier never touches them."""
    import jax
    from jax.sharding import PartitionSpec as P

    bits = _PRECISION_BITS[precision]
    mesh_shape = dict(mesh.shape)
    total = 0

    def one(spec, leaf):
        nonlocal total
        if len(leaf.shape) >= 2:
            total += _spec_device_bytes(leaf.shape, np.dtype(np.int8),
                                        spec, mesh_shape) * bits // 8
            total += _spec_device_bytes((1,) + tuple(leaf.shape[1:]),
                                        np.dtype(np.float32), spec,
                                        mesh_shape)
        else:
            total += _spec_device_bytes(leaf.shape, leaf.dtype, spec,
                                        mesh_shape)
        return spec

    jax.tree.map(one, specs, tree, is_leaf=lambda x: isinstance(x, P))
    return total


def _fit_serve(config, hbm_bytes: int, *, max_len: int, kv_page_size: int,
               slots: Optional[int],
               precision: Optional[str] = None) -> dict:
    """Real-scale serve planning: params + per-slot KV + page pool,
    priced via ``eval_shape`` only (no compile).  Reports bf16 AND int8
    KV side by side — the two serving memory levers the engine ships."""
    from dtf_tpu.core import sharding as shd
    from dtf_tpu.serve import pages as pages_lib
    from dtf_tpu.serve.engine import engine_state_struct

    mesh = config.mesh()
    data_size = int(mesh.shape.get("data", 1))
    spec_view = config.spec_view(mesh)
    param_specs = shd.tree_specs(spec_view.params, spec_view.rules)
    params_dev = _price_spec_tree(spec_view.params, param_specs, mesh)

    base_cfg = config.fit_serve_cfg()
    out: dict = {
        "params_bytes_per_device": params_dev,
        "max_len": max_len, "kv_page_size": kv_page_size, "kv": {},
    }
    avail = hbm_bytes - params_dev
    avail_q = None
    if precision is not None:
        # --precision: weights held at 8-bit (matrix leaves + per-channel
        # scale sideband, the ops/quant.py layout) — the HBM the
        # quantized tier frees buys extra slots on the same chip.
        qparams_dev = _quant_params_bytes(spec_view.params, param_specs,
                                          mesh, precision)
        out["precision"] = precision
        out["params_bytes_per_device_at_precision"] = qparams_dev
        avail_q = hbm_bytes - qparams_dev

    # speculative decoding (fit_draft_cfg): the draft model is RESIDENT
    # state too — its params (priced under the same TP rules) and one
    # draft KV slot per target slot. "max slots with spec on" is then
    # answerable before any chip time: the slot budget shrinks by the
    # draft's per-slot cache and the draft params come off the top.
    draft_cfg = (config.fit_draft_cfg()
                 if config.fit_draft_cfg is not None else None)
    draft_params_dev = 0
    if draft_cfg is not None:
        import jax

        from dtf_tpu.models import gpt as gpt_lib

        dmodel = gpt_lib.GPT(draft_cfg, mesh)
        dparams = jax.eval_shape(lambda: dmodel.init(
            jax.random.PRNGKey(0),
            jax.numpy.zeros((1, 1), jax.numpy.int32)))["params"]
        dspecs = shd.tree_specs(dparams, gpt_lib.tp_rules)
        draft_params_dev = _price_spec_tree(dparams, dspecs, mesh)
        out["draft_params_bytes_per_device"] = draft_params_dev

    for kv_name in ("bf16", "int8"):
        kv_dtype = "" if kv_name == "bf16" else "int8"
        cfg = dataclasses.replace(base_cfg, kv_cache_dtype=kv_dtype)
        # price data_size slots (one per data shard) so the per-device
        # number is exactly one GLOBAL slot's cost — pricing a single
        # slot would overstate by the data-axis factor (ceil(1/N) = 1).
        struct = engine_state_struct(cfg, n_slots=data_size,
                                     max_len=max_len, mesh=mesh)
        per_slot = tree_device_bytes(struct) / data_size
        pool = pages_lib.pool_abstract(struct["cache"], 1, kv_page_size,
                                       mesh)
        per_page = tree_device_bytes(pool)
        max_slots = int(avail // per_slot) if avail > 0 else 0
        max_slots -= max_slots % data_size  # even slot sharding
        row = {
            "kv_bytes_per_slot_per_device": int(round(per_slot)),
            "page_bytes_per_device": per_page,
            "max_slots": max_slots,
        }
        if avail_q is not None:
            q_slots = int(avail_q // per_slot) if avail_q > 0 else 0
            q_slots -= q_slots % data_size
            row["max_slots_at_precision"] = q_slots
        if slots is not None:
            left = avail - slots * per_slot
            row["slots"] = slots
            row["max_pages_at_slots"] = max(0, int(left // per_page))
        if draft_cfg is not None:
            dstruct = engine_state_struct(
                dataclasses.replace(draft_cfg, kv_cache_dtype=kv_dtype),
                n_slots=data_size, max_len=max_len, mesh=mesh)
            per_slot_draft = tree_device_bytes(dstruct) / data_size
            savail = avail - draft_params_dev
            max_spec = (int(savail // (per_slot + per_slot_draft))
                        if savail > 0 else 0)
            max_spec -= max_spec % data_size
            row["draft_kv_bytes_per_slot_per_device"] = int(
                round(per_slot_draft))
            row["max_slots_with_spec"] = max_spec
            if avail_q is not None:
                # the quantized-DRAFT deployment (serve_gpt
                # --draft_precision): target weights stay bf16, the
                # draft's matrices go 8-bit.
                qdraft_dev = _quant_params_bytes(dparams, dspecs, mesh,
                                                 precision)
                sq = avail - qdraft_dev
                mq = (int(sq // (per_slot + per_slot_draft))
                      if sq > 0 else 0)
                mq -= mq % data_size
                row["max_slots_with_spec_at_draft_precision"] = mq
        out["kv"][kv_name] = row
    return out


def _scale_batch(batch: PyTree, b: int) -> PyTree:
    import jax

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct((b,) + tuple(x.shape[1:]), x.dtype),
        batch)


def _fit_train(config, hbm_bytes: int, *, opt: Optional[str],
               grad_accum: int, grad_shard: bool,
               act_scale: Optional[float], mesh=None,
               precision: Optional[str] = None) -> dict:
    """Train planning: analytic resident state + a measured affine
    temp-vs-batch model (two AOT compiles of the registry's own tiny
    program).  The batch inversion answers at PROGRAM scale — the same
    program the fence pins; ``act_scale`` (≈ (L·T·d)_real/(L·T·d)_tiny
    for the LM configs) extrapolates the activation slope to the
    real-scale model and prices the resident side from the real-scale
    spec view instead.  ``mesh`` overrides the config's own mesh — the
    elastic shrink pricing (``fit --hosts --lost``) reuses this whole
    path on the survivor mesh, no new compile machinery."""
    import jax
    from dtf_tpu.analysis import configs as cfgs
    from dtf_tpu.core import sharding as shd

    mesh = config.mesh() if mesh is None else mesh
    data_size = int(mesh.shape.get("data", 1))
    opt_name = opt or config.opt_name
    tx = cfgs.OPTIMIZER_FAMILIES[opt_name]()

    def resident_of(params, rules) -> dict:
        param_specs = shd.tree_specs(params, rules)
        p = _price_spec_tree(params, param_specs, mesh)
        opt_state = jax.eval_shape(tx.init, params)
        opt_specs = shd.zero1_opt_specs(tx, params, param_specs, mesh)
        o = _price_spec_tree(opt_state, opt_specs, mesh)
        acc = 0
        if grad_accum > 1:
            acc_specs = (shd.zero1_param_shard_specs(params, param_specs,
                                                     mesh)
                         if grad_shard else param_specs)
            f32 = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, np.float32), params)
            acc = _price_spec_tree(f32, acc_specs, mesh)
        return {"params_bytes": p, "opt_state_bytes": o,
                "accumulator_bytes": acc, "total_bytes": p + o + acc}

    view = config.step_view(mesh)
    b0 = jax.tree.leaves(view.batch)[0].shape[0]
    temps = {}
    for b in (b0, 2 * b0):
        # the memory pass probes the SAME registered program at a
        # scaled batch — a throwaway measurement lowering, not a
        # aot-ok: new program birth
        compiled = view.step.lower(view.state,
                                   _scale_batch(view.batch, b)).compile()
        temps[b] = int(compiled.memory_analysis().temp_size_in_bytes)
    intercept, slope = affine_temp_model(temps)
    _, leaves, decl = _flat_declared(view)
    n_batch = len(jax.tree.leaves(view.batch))
    batch_row = sum(
        leaf_device_bytes(lf.shape, lf.dtype, sh)
        for lf, sh in zip(leaves[-n_batch:], decl[-n_batch:])) / b0

    scale = 1.0 if act_scale is None else float(act_scale)
    if act_scale is None:
        # program scale: price the view's own declared state — the same
        # program the fence pins, no cross-scale claims.
        resident = {"total_bytes": resident_bytes(view)["state_bytes"]}
        label = "program"
    else:
        spec_view = config.spec_view(mesh)
        resident = resident_of(spec_view.params, spec_view.rules)
        label = "extrapolated"
    avail = hbm_bytes - resident["total_bytes"] - intercept * scale
    per_row = slope * scale + batch_row * scale
    max_batch = int(avail // per_row) if per_row > 0 and avail > 0 else 0
    grain = data_size * max(grad_accum, 1)
    max_batch -= max_batch % grain
    # fit verdict at the program's OWN global batch — the elastic shrink
    # question ("does the survivor mesh still carry the same global
    # batch?") is this number on the shrunk mesh vs the budget.
    need_at_b0 = int(resident["total_bytes"] + intercept * scale
                     + per_row * b0)
    out = {
        "scale": label, "opt": opt_name,
        "grad_accum": grad_accum, "grad_shard": grad_shard,
        "mesh": dict(mesh.shape),
        "resident_bytes_per_device": resident,
        "temp_model": {"intercept_bytes": int(intercept),
                       "bytes_per_batch_row": int(round(per_row)),
                       "measured": {str(k): v for k, v in temps.items()}},
        "act_scale": scale,
        "global_batch": b0,
        "hbm_needed_bytes_at_batch": need_at_b0,
        "fits_at_batch": bool(need_at_b0 <= hbm_bytes),
        "max_global_batch": max(0, max_batch),
    }
    if precision is not None:
        # --precision on a train config: the RESIDENT side is unchanged
        # by design (bf16/f32 master weights, full-precision grads — the
        # quant tier quantizes compute and ring bytes, not state), so
        # only the activation-temp slope shrinks: scaled by 8 bits over
        # the program's own activation width. A documented ESTIMATE —
        # the 8-bit activations live inside fusions XLA shapes as it
        # pleases — bounded below by the measured bf16 row it sits next
        # to (docs/ANALYSIS.md §fit).
        import jax as _jax

        act_bits = 8 * _jax.tree.leaves(view.state.params)[0].dtype.itemsize
        q_ratio = _PRECISION_BITS[precision] / act_bits
        q_per_row = slope * scale * q_ratio + batch_row * scale
        q_max = (int(avail // q_per_row)
                 if q_per_row > 0 and avail > 0 else 0)
        q_max -= q_max % grain
        out["precision"] = precision
        out["temp_model"]["bytes_per_batch_row_at_precision"] = int(
            round(q_per_row))
        out["max_global_batch_at_precision"] = max(0, q_max)
    return out


def fit(name: str, *, hbm_gb: float, max_len: int = 1024,
        kv_page_size: int = 64, slots: Optional[int] = None,
        opt: Optional[str] = None, grad_accum: int = 1,
        grad_shard: bool = False,
        act_scale: Optional[float] = None,
        hosts: Optional[int] = None, lost: int = 0,
        precision: Optional[str] = None,
        log_sink: bool = False) -> dict:
    """The fit planner: what fits a ``hbm_gb``-HBM chip under config
    ``name``'s mesh and sharding rules.  Serve configs answer max KV
    slots (bf16 AND int8) + page-pool size from a pure ``eval_shape``
    pricing at REAL model scale; train configs answer max global batch
    from analytic resident state + a measured temp model.

    ``hosts``/``lost`` (train configs): price the elastic shrink BEFORE
    the controller pays a relaunch — the config's mesh is split across
    ``hosts`` hosts, ``lost`` of them die, and the survivor mesh (data
    axis scaled down, everything else intact — ``fault/elastic.py``) is
    priced side by side with the full mesh at the SAME global batch.
    ``survivor.fits_at_batch`` is the controller's go/no-go: resident
    state grows (ZeRO-1 shards are 1/data') and temp grows (bigger
    per-device batch), so a shrink that no longer fits should relaunch
    at a smaller batch or fail loudly, not OOM on the chip.
    """
    from dtf_tpu.analysis import configs as cfgs

    config = cfgs.BY_NAME[name]
    if precision is not None and precision not in _PRECISION_BITS:
        raise ValueError(
            f"precision={precision!r} must be one of "
            f"{sorted(_PRECISION_BITS)} (bf16 is the default pricing)")
    hbm_bytes = int(hbm_gb * (1 << 30))
    out = {"mode": "fit", "config": name, "hbm_gb": hbm_gb,
           "mesh": dict(config.mesh().shape)}
    if hosts is not None:
        if config.fit_serve_cfg is not None:
            raise ValueError(
                "--hosts/--lost prices train meshes; a serve fleet "
                "shrinks by replica count, not mesh surgery")
        import jax

        from dtf_tpu.core.mesh import MeshConfig, make_mesh
        from dtf_tpu.fault.elastic import survivor_mesh_shape

        surv_shape = survivor_mesh_shape(out["mesh"], hosts, lost)
        n_surv = int(np.prod(list(surv_shape.values())))
        if n_surv > len(jax.devices()):
            raise ValueError(
                f"survivor mesh needs {n_surv} devices; the sim has "
                f"{len(jax.devices())}")
        surv_mesh = make_mesh(MeshConfig(**surv_shape),
                              devices=jax.devices()[:n_surv])
        kw = dict(opt=opt, grad_accum=grad_accum, grad_shard=grad_shard,
                  act_scale=act_scale, precision=precision)
        out.update({
            "kind": "train_shrink", "hosts": hosts, "lost": lost,
            "survivor_mesh": surv_shape,
            "full": _fit_train(config, hbm_bytes, **kw),
            "survivor": _fit_train(config, hbm_bytes, mesh=surv_mesh,
                                   **kw),
        })
        out["survivor_fits_same_batch"] = out["survivor"]["fits_at_batch"]
        return out
    if log_sink and config.fit_serve_cfg is None:
        raise ValueError(
            "--log-sink prices the SERVE request log sink (serve_gpt "
            "--log_sink_dir); pick a serve config")
    if config.fit_serve_cfg is not None:
        out["kind"] = "serve"
        out.update(_fit_serve(config, hbm_bytes, max_len=max_len,
                              kv_page_size=kv_page_size, slots=slots,
                              precision=precision))
        if log_sink:
            # the ISSUE 19 sink is scheduler-side file IO over token ids
            # the host already holds (the _retire record) — no device
            # transfer, no resident tensor, no extra program. An explicit
            # zero beats an absent row: capacity planning can CITE it.
            out["log_sink"] = {"hbm_delta_bytes": 0,
                               "host_side_only": True}
    else:
        out["kind"] = "train"
        out.update(_fit_train(config, hbm_bytes, opt=opt,
                              grad_accum=grad_accum, grad_shard=grad_shard,
                              act_scale=act_scale, precision=precision))
    return out
