"""``python -m dtf_tpu.analysis`` — run every static pass, print ONE JSON line.

bench.py's resilience idiom: stdout's LAST line is always exactly one JSON
object, whatever the backend situation.  The analyzer never needs a chip —
but it does need the 8-device CPU sim, so if the calling environment is not
already pinned there (e.g. PALLAS_AXON_POOL_IPS routes to the real TPU,
where an import can hang on a dead tunnel) it re-execs itself into a
scrubbed child exactly like ``__graft_entry__.dryrun_multichip``.

    python -m dtf_tpu.analysis                       # all configs, all passes
    python -m dtf_tpu.analysis --configs=bert,gpt    # subset
    python -m dtf_tpu.analysis --passes=specs,jaxpr,collective   # no compile
    python -m dtf_tpu.analysis --write-golden        # regenerate the fence
    python -m dtf_tpu.analysis --diff                # per-line provenance +
                                                     # memory-field delta vs
                                                     # golden (PR review aid)
    python -m dtf_tpu.analysis fit --config=gpt_serve --hbm-gb=16
                                                     # HBM fit planner: max
                                                     # KV slots (bf16+int8)
                                                     # / max global batch

Exit status: 0 = no error findings, 1 = findings, 2 = analyzer crashed.
The non-zero-on-error contract is what makes ``scripts/lint.sh --full``
usable as a pre-commit gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

N_DEVICES = 8


def _reexec_if_needed(argv: list[str]) -> None:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.path.insert(0, root)
    from _dtf_env import cpu_sim_env, is_cpu_sim

    if is_cpu_sim(os.environ, N_DEVICES):
        return
    if os.environ.get("_DTF_TPU_ANALYSIS_REEXEC") == "1":
        return
    import subprocess

    env = cpu_sim_env(N_DEVICES, os.environ)
    env["_DTF_TPU_ANALYSIS_REEXEC"] = "1"
    env.setdefault("PYTHONPATH", root)
    proc = subprocess.run(
        [sys.executable, "-m", "dtf_tpu.analysis"] + argv,
        env=env, cwd=root, timeout=1800)
    sys.exit(proc.returncode)


def _fit_main(argv: list[str]) -> int:
    """``python -m dtf_tpu.analysis fit`` — the HBM fit planner."""
    parser = argparse.ArgumentParser(
        prog="python -m dtf_tpu.analysis fit",
        description="Invert the static memory model: what fits a chip.")
    parser.add_argument("--config", required=True,
                        help="registry config name (serve configs answer "
                             "max KV slots bf16+int8; train configs max "
                             "global batch)")
    parser.add_argument("--hbm-gb", type=float, required=True,
                        help="per-chip HBM budget in GiB (v5e: 16)")
    parser.add_argument("--max-len", type=int, default=1024,
                        help="serve: per-slot cache length (prompt + "
                             "generated tokens)")
    parser.add_argument("--kv-page-size", type=int, default=64,
                        help="serve: prefix-cache page size in tokens")
    parser.add_argument("--slots", type=int, default=None,
                        help="serve: fix the slot count and report the "
                             "page-pool size the remaining HBM buys")
    parser.add_argument("--opt", default=None,
                        help="train: optimizer family to price moments "
                             "for (default: the config's launcher family)")
    parser.add_argument("--grad-accum", type=int, default=1,
                        help="train: price a grad_accum f32 accumulator")
    parser.add_argument("--grad-shard", action="store_true",
                        help="train: accumulator ZeRO-1-sharded over data")
    parser.add_argument("--act-scale", type=float, default=None,
                        help="train: activation-slope multiplier "
                             "(≈ (L·T·d)_real/(L·T·d)_program) — switches "
                             "the resident side to the real-scale spec "
                             "view")
    parser.add_argument("--hosts", type=int, default=None,
                        help="train: the mesh is split across N hosts — "
                             "price the elastic shrink (with --lost) "
                             "before the controller relaunches "
                             "(docs/RESILIENCE.md)")
    parser.add_argument("--lost", type=int, default=0,
                        help="train: hosts lost; the survivor mesh "
                             "(data axis scaled down) is priced at the "
                             "SAME global batch next to the full mesh")
    parser.add_argument("--precision", default=None,
                        choices=("int8", "fp8"),
                        help="price the low-precision tier next to bf16: "
                             "serve configs report max_slots with 8-bit "
                             "weights (+ per-channel scale sideband), "
                             "train configs the activation-temp shrink "
                             "(docs/ANALYSIS.md, docs/TUNING.md)")
    parser.add_argument("--log-sink", action="store_true",
                        help="serve: price the request log sink (ISSUE "
                             "19) next to the fleet — it is host-side "
                             "file IO with zero device readbacks, so the "
                             "answer is an explicit HBM no-op (the row "
                             "exists so capacity planning can SAY so "
                             "instead of leaving it to folklore)")
    args = parser.parse_args(argv)

    from dtf_tpu.analysis import configs as cfgs
    from dtf_tpu.analysis import memory as memory_pass

    if args.config not in cfgs.BY_NAME:
        print(json.dumps({"ok": False,
                          "error": f"unknown config {args.config!r}; have "
                                   f"{sorted(cfgs.BY_NAME)}"}))
        return 2
    try:
        out = memory_pass.fit(
            args.config, hbm_gb=args.hbm_gb, max_len=args.max_len,
            kv_page_size=args.kv_page_size, slots=args.slots, opt=args.opt,
            grad_accum=args.grad_accum, grad_shard=args.grad_shard,
            act_scale=args.act_scale, hosts=args.hosts, lost=args.lost,
            precision=args.precision, log_sink=args.log_sink)
    except Exception as e:  # noqa: BLE001 — last line must still be JSON
        print(json.dumps({"ok": False,
                          "error": f"{type(e).__name__}: {e}"[:500]}))
        return 2
    print(json.dumps({"ok": True, **out}))
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        _reexec_if_needed(argv)
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001 — the JSON-last-line contract
        # (child timeout, missing _dtf_env, ...) must hold even when the
        # bootstrap itself dies — exactly the TPU-pointed environments the
        # re-exec exists to protect.
        print(json.dumps({"ok": False,
                          "error": f"bootstrap: {type(e).__name__}: "
                                   f"{e}"[:500]}))
        return 2

    if argv and argv[0] == "fit":
        return _fit_main(argv[1:])

    parser = argparse.ArgumentParser(prog="python -m dtf_tpu.analysis")
    parser.add_argument("--configs", default="",
                        help="comma-separated registry names (default all)")
    parser.add_argument("--passes",
                        default="host,specs,jaxpr,collective,hlo,memory",
                        help="comma-separated passes to run")
    parser.add_argument("--write-golden", action="store_true",
                        help="regenerate STATIC_ANALYSIS.json comms + "
                             "memory budgets")
    parser.add_argument("--golden", default="",
                        help="override golden path")
    parser.add_argument("--diff", action="store_true",
                        help="print the per-source-line collective "
                             "provenance delta vs the golden (PR review "
                             "aid; compiles, no findings verdict)")
    args = parser.parse_args(argv)

    from dtf_tpu.analysis import configs as cfgs
    from dtf_tpu.analysis import hlo as hlo_pass
    from dtf_tpu.analysis import runner
    from dtf_tpu.analysis.findings import severity_counts

    names = [n for n in args.configs.split(",") if n]
    for n in names:
        if n not in cfgs.BY_NAME:
            print(json.dumps({"ok": False,
                              "error": f"unknown config {n!r}; have "
                                       f"{sorted(cfgs.BY_NAME)}"}))
            return 2
    passes = [p for p in args.passes.split(",") if p]
    bad = [p for p in passes if p not in runner.ALL_PASSES]
    if bad:
        # a typo'd pass must not silently disable the fence (exit 0, ran
        # nothing) — same contract as unknown --configs
        print(json.dumps({"ok": False,
                          "error": f"unknown passes {bad}; valid: "
                                   f"{','.join(runner.ALL_PASSES)}"}))
        return 2
    golden_file = args.golden or runner.golden_path()

    try:
        if args.write_golden:
            budgets = {
                c.name: runner.compile_budget(c)
                for c in (cfgs.REGISTRY if not names
                          else [cfgs.BY_NAME[n] for n in names])}
            import jax

            existing = (hlo_pass.load_golden(golden_file).get("budgets", {})
                        if os.path.exists(golden_file) else {})
            existing.update(budgets)
            hlo_pass.save_golden(
                golden_file, existing,
                meta={"jax": jax.__version__, "devices": N_DEVICES,
                      "regen": "python -m dtf_tpu.analysis --write-golden",
                      "note": "comms budget of each config's tiny AOT-"
                              "compiled train step on the 8-device CPU sim"})
            print(json.dumps({"ok": True, "wrote": golden_file,
                              "configs": sorted(budgets)}))
            return 0

        golden = (hlo_pass.load_golden(golden_file)
                  if os.path.exists(golden_file) else {"budgets": {}})

        if args.diff:
            # review aid, not a verdict: compile each config, print the
            # per-line provenance delta AND the per-field memory delta vs
            # golden as plain lines, keep the one-JSON-last-line contract
            # with a summary object.
            from dtf_tpu.analysis import memory as memory_pass
            from dtf_tpu.analysis import provenance

            diff_counts = {}
            for c in (cfgs.REGISTRY if not names
                      else [cfgs.BY_NAME[n] for n in names]):
                budget = runner.compile_budget(c)
                want = golden.get("budgets", {}).get(c.name, {})
                lines = provenance.provenance_delta(
                    budget.get("provenance"), want.get("provenance"))
                lines += memory_pass.memory_delta(
                    budget.get("memory"), want.get("memory"))
                diff_counts[c.name] = len(lines)
                for line in lines:
                    print(f"{c.name}: {line}")
            print(json.dumps({"ok": True, "mode": "diff",
                              "changed_lines": diff_counts}))
            return 0

        budgets: dict = {}
        findings = runner.analyze(names or None, passes, golden=golden,
                                  budgets_out=budgets)
    except Exception as e:  # noqa: BLE001 — last line must still be JSON
        print(json.dumps({"ok": False,
                          "error": f"{type(e).__name__}: {e}"[:500]}))
        return 2

    counts = severity_counts(findings)
    out = {
        "ok": counts["error"] == 0,
        "configs": names or sorted(cfgs.BY_NAME),
        "passes": passes,
        "findings": counts["error"] + counts["warning"],
        "severities": counts,
        "details": [f.to_json() for f in findings
                    if f.severity != "info"][:50],
    }
    if budgets:
        # per-config collective-bytes delta vs the committed golden, so a
        # PR's comms cost shows up in its analysis line (0 everywhere on a
        # clean fence; a drift here pairs with a hlo finding above).
        gb = golden.get("budgets", {})
        out["comms_delta_bytes"] = {
            name: b["total"]["bytes"]
            - gb.get(name, {}).get("total", {}).get("bytes", 0)
            for name, b in sorted(budgets.items())}
        # per-config peak temp allocation (AOT memory_analysis) — the HBM
        # where grad accumulators and activation stashes live; the
        # bert_accum vs bert_grad_shard rows show the --grad_shard
        # accumulator shrink at a glance (docs/ZERO.md).
        out["temp_bytes"] = {
            name: b.get("memory", {}).get("temp_bytes", 0)
            for name, b in sorted(budgets.items())}
        # per-config peak-resident estimate (args + outputs + temps +
        # code − donated aliases) — the number the fit planner budgets
        # against a chip's HBM.
        from dtf_tpu.analysis import memory as memory_pass

        out["hbm_peak_bytes"] = {
            name: memory_pass.hbm_peak_bytes(b.get("memory", {}))
            for name, b in sorted(budgets.items())}
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
