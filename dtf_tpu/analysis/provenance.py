"""Source-attributed comms provenance — who introduced each collective.

XLA op metadata (``source_file``/``source_line``) survives lowering into
the optimized HLO, so every collective the comms-budget fence counts can
be attributed to the Python line that introduced it. That turns a
``collective-count-drift`` finding from "all-reduce 126→127" into
"all-reduce +1 at dtf_tpu/core/train.py:396", and gives PR review a
per-line delta view (``python -m dtf_tpu.analysis --diff``).

Provenance is recorded in the golden next to each budget but is NOT
itself fenced: line numbers shift on every unrelated edit to a traced
file, and a fence over them would page on comment changes. It exists to
*attribute* count/byte drift the opcode fence already caught, and to
print review diffs — staleness only ever makes an annotation slightly
off, never a finding wrong. (``--write-golden`` refreshes it wholesale;
expect provenance churn in the JSON diff whenever traced sources moved.)

Paths are normalized repo-relative (anchored on the last ``dtf_tpu`` /
``tests`` / ``scripts`` path segment; anything outside the repo — jax,
flax internals — keeps its basename) so goldens compare across machines.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

#: repo path anchors: everything from the LAST occurrence of one of these
#: segments on is the stable cross-machine identity of a source file.
_ANCHORS = ("dtf_tpu", "tests", "scripts")

_META_RE = re.compile(
    r'source_file="(?P<file>[^"]+)"\s+source_line=(?P<line>\d+)')

#: instruction name on the LHS of an HLO line: `%all-reduce.2 = ...` —
#: the SAME name the profiler stamps into XPlane op events as ``hlo_op``,
#: which is what makes device time joinable to source lines.
_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%(?P<name>[\w.\-]+)\s*=")


def _rel(path: str) -> str:
    parts = path.replace("\\", "/").split("/")
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] in _ANCHORS:
            return "/".join(parts[i:])
    return parts[-1]


def instruction_sites(hlo_text: str, *, ops=None) -> dict:
    """``{instruction_name: {"op": opcode, "loc": "file:line"}}`` for every
    collective instruction in optimized HLO text.

    The shared source-anchoring helper: the comms-budget golden records
    per-``file:line`` aggregates (:func:`collective_provenance`), while the
    XPlane device-profile parser (:mod:`dtf_tpu.telemetry.profile`) needs
    the PER-INSTRUCTION map — a profiled ``all-reduce.2`` event joins to
    its Python call site through the instruction name, so device seconds
    can be attributed to the line that issued the collective. ``ops``
    restricts the opcode set (default: the fence's COLLECTIVE_OPS).
    Instructions without source metadata map to ``"<unattributed>"``.
    """
    from dtf_tpu.analysis import hlo as hlo_pass

    sites: dict[str, dict] = {}
    for line in hlo_text.splitlines():
        m = hlo_pass._COLLECTIVE_RE.search(line)
        if not m:
            continue
        op = m.group("op")
        if ops is not None and op not in ops:
            continue
        nm = _INSTR_RE.match(line)
        if nm is None:
            continue
        meta = _META_RE.search(line)
        loc = (f"{_rel(meta.group('file'))}:{meta.group('line')}"
               if meta else "<unattributed>")
        nbytes, _ = hlo_pass._shape_bytes(m.group("type"))
        sites[nm.group("name")] = {"op": op, "loc": loc, "bytes": nbytes}
    return sites


def collective_provenance(hlo_text: str) -> dict:
    """``{op: {"file:line": {count, bytes}}}`` from optimized HLO text.

    Reuses the hlo pass's opcode matcher line-by-line (HLO prints one op
    per line) and pairs each collective with the ``metadata={...}`` on
    its own line; collectives with no source metadata (rare: fusion
    roots synthesized by passes) land under ``"<unattributed>"``.
    """
    from dtf_tpu.analysis import hlo as hlo_pass

    prov: dict[str, dict[str, dict]] = {}
    for line in hlo_text.splitlines():
        m = hlo_pass._COLLECTIVE_RE.search(line)
        if not m:
            continue
        op = m.group("op")
        nbytes, _ = hlo_pass._shape_bytes(m.group("type"))
        meta = _META_RE.search(line)
        loc = (f"{_rel(meta.group('file'))}:{meta.group('line')}"
               if meta else "<unattributed>")
        slot = prov.setdefault(op, {}).setdefault(
            loc, {"count": 0, "bytes": 0})
        slot["count"] += 1
        slot["bytes"] += nbytes
    return prov


def profile_site_map(hlo_texts) -> dict:
    """Flatten ``instruction_sites`` over several programs' HLO texts into
    one ``{hlo_op_name: {"op", "loc", "bytes"}}`` join table for the
    device-profile parser. ``hlo_texts``: iterable of optimized HLO
    strings (or a single string). Later programs win name collisions —
    instruction names are unique within a module, and profiled runs window
    one program at a time, so collisions only matter across programs that
    never share a trace."""
    if isinstance(hlo_texts, str):
        hlo_texts = (hlo_texts,)
    out: dict[str, dict] = {}
    for text in hlo_texts:
        out.update(instruction_sites(text))
    return out


def provenance_delta(got: Mapping[str, Any] | None,
                     want: Mapping[str, Any] | None) -> list[str]:
    """Human-readable per-line delta, most-moved first; [] when clean."""
    got, want = got or {}, want or {}
    rows = []
    for op in sorted(set(got) | set(want)):
        g_op, w_op = got.get(op, {}), want.get(op, {})
        for loc in sorted(set(g_op) | set(w_op)):
            g = g_op.get(loc, {"count": 0, "bytes": 0})
            w = w_op.get(loc, {"count": 0, "bytes": 0})
            dc, db = g["count"] - w["count"], g["bytes"] - w["bytes"]
            if dc or db:
                rows.append((abs(dc), abs(db),
                             f"{op} {dc:+d} ({db:+,} B) at {loc} "
                             f"[{w['count']}→{g['count']}]"))
    rows.sort(reverse=True)
    return [r[2] for r in rows]


def attribute_drift(op: str, got_prov: Mapping[str, Any] | None,
                    want_prov: Mapping[str, Any] | None,
                    *, limit: int = 3) -> str:
    """Short suffix for a drift finding: the top moved lines of ``op``.

    Empty string when EITHER side carries no provenance at all (a
    pre-provenance golden, a metadata-stripped backend): diffing real
    call sites against an empty record would list every existing line as
    "drift" and misdirect the reader — better no attribution than wrong
    attribution. An op merely absent on one side (0 → N call sites) is
    attributed normally.
    """
    if got_prov is None or want_prov is None:
        return ""
    got = got_prov.get(op)
    want = want_prov.get(op)
    if got is None and want is None:
        return ""
    lines = provenance_delta({op: got or {}}, {op: want or {}})
    if not lines:
        return ""
    shown = "; ".join(lines[:limit])
    more = f" (+{len(lines) - limit} more lines)" if len(lines) > limit \
        else ""
    return f" — {shown}{more}"
