"""The one currency every analyzer pass trades in."""

from __future__ import annotations

import dataclasses
from typing import Iterable

#: finding severities, in increasing order of "this ships broken".
SEVERITIES = ("info", "warning", "error")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer hit.

    ``severity='error'`` findings fail the CLI (and the fenced tests);
    ``'info'`` records context (e.g. small leaves intentionally falling to
    REPLICATED) without affecting the verdict.
    """

    config: str      # registry config name ("" = config-independent)
    pass_name: str   # "specs" | "jaxpr" | "collective" | "hlo" |
                     # "memory" | "host" | "lint"
    check: str       # kebab-case check id, e.g. "shadowed-rule"
    severity: str    # one of SEVERITIES
    detail: str      # human-readable, one line

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"bad severity {self.severity!r}")

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def errors(findings: Iterable[Finding]) -> list[Finding]:
    return [f for f in findings if f.severity == "error"]


def severity_counts(findings: Iterable[Finding]) -> dict[str, int]:
    counts = {s: 0 for s in SEVERITIES}
    for f in findings:
        counts[f.severity] += 1
    return counts
