"""Shared AST model of the host control plane — classes, threads, locks,
signal handlers, attribute traffic.

The host soundness pass (:mod:`dtf_tpu.analysis.host`) asks three
questions about the jax-free packages: which attributes are touched from a
``threading.Thread`` target vs. the rest of the class, which locks a
registered signal handler can reach, and which file/clock calls bypass the
sanctioned choke points. This module builds the one per-class model those
lints share, AST-only (no imports executed — the srclint discipline), so
each lint is a cheap walk over prebuilt facts.

Model granularity and deliberate limits (documented, not accidental):

- **Per-class.** Threads, locks and attribute traffic are modeled within
  one class; a thread that calls into ANOTHER class's methods is covered
  by that class's own discipline (e.g. the stall watchdog thread calls
  ``FlightRecorder.write_heartbeat``, whose guarded sections are
  FlightRecorder's own model). The only cross-class edge the model keeps
  is attribute TYPE (``self.flight = FlightRecorder(...)`` or a
  constructor-parameter annotation), because the signal-handler lint must
  follow ``self.flight.dump()`` into the class that owns the lock.
- **Lexical guards.** An access counts as guarded when it sits inside a
  ``with self.<lock>:`` block of the same function — the codebase's one
  locking idiom. ``.acquire()``/``.release()`` pairs are recorded as
  acquires (the signal lint needs them) but do not bless a region.
- **Nested defs are call-time scopes.** A ``def run()`` inside a method
  is the thread-target idiom; its body is walked with the guard state
  RESET (the definition site's ``with`` does not hold when the thread
  later runs it).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

#: constructors that make an attribute a lock (tracked by kind — the
#: signal lint's whole point is Lock vs RLock).
_LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock"}

#: constructors whose objects are internally synchronized — attributes
#: bound to these are exempt from the shared-state lint.
_THREADSAFE_CTORS = {
    "Event", "Condition", "Semaphore", "BoundedSemaphore", "Barrier",
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
}

#: method calls that mutate their receiver in place — ``self.x.append(...)``
#: is a WRITE to ``x`` for the shared-state lint.
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "pop", "popleft",
    "popitem", "clear", "sort", "reverse", "update", "add", "discard",
    "setdefault", "put", "put_nowait",
}


@dataclasses.dataclass(frozen=True)
class Access:
    """One ``self.<attr>`` touch inside a class function."""

    attr: str
    lineno: int
    write: bool
    guarded: bool    # lexically inside `with self.<lock>:` of this func
    func: str        # "method" or "method.<locals>.nested"


@dataclasses.dataclass
class ClassModel:
    """Everything the host lints need to know about one class."""

    name: str
    path: str
    lineno: int
    funcs: Set[str] = dataclasses.field(default_factory=set)
    locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    threadsafe: Set[str] = dataclasses.field(default_factory=set)
    accesses: List[Access] = dataclasses.field(default_factory=list)
    #: func -> in-class callees (methods and own nested defs, resolved)
    calls: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)
    #: func -> {(attr, method)} for ``self.<attr>.<method>()`` calls
    cross_calls: Dict[str, Set[Tuple[str, str]]] = dataclasses.field(
        default_factory=dict)
    #: func -> [(lock_attr, lineno)] — `with self.lock:` or `.acquire()`
    acquires: Dict[str, List[Tuple[str, int]]] = dataclasses.field(
        default_factory=dict)
    thread_targets: Set[str] = dataclasses.field(default_factory=set)
    signal_handlers: Set[str] = dataclasses.field(default_factory=set)
    #: attr -> class name, from ``self.x = ClassName(...)`` or an
    #: annotated ctor parameter assigned through (``self.x = param``)
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)

    def reachable(self, entries: Set[str]) -> Set[str]:
        """In-class call-graph closure of ``entries``."""
        seen: Set[str] = set()
        todo = [e for e in entries if e in self.funcs]
        while todo:
            f = todo.pop()
            if f in seen:
                continue
            seen.add(f)
            todo += [c for c in self.calls.get(f, ()) if c not in seen]
        return seen


@dataclasses.dataclass
class ModuleModel:
    path: str
    tree: ast.AST
    src: str
    classes: List[ClassModel]

    def pin_lines(self, marker: str) -> Set[int]:
        """Line numbers pinned by ``marker`` (e.g. ``# clock-ok:``): the
        marker's own line plus the one after it, so a standalone comment
        line pins the statement below (long lines have nowhere inline)."""
        out: Set[int] = set()
        for i, line in enumerate(self.src.splitlines(), 1):
            if marker in line:
                out.update((i, i + 1))
        return out


def _call_name(node: ast.AST) -> Optional[str]:
    """Terminal name of a call target: ``threading.RLock`` -> "RLock"."""
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.<attr>`` -> attr name, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _store_base_attr(target: ast.AST) -> Optional[str]:
    """The self-attribute a store target ultimately mutates:
    ``self.x = ...``, ``self.x[k] = ...``, ``self.x[k][j] += ...`` and
    ``self.x.y = ...`` all write ``x`` (container/object mutation is
    mutation of the shared attribute)."""
    while True:
        if isinstance(target, ast.Subscript):
            target = target.value
        elif isinstance(target, ast.Attribute) and not (
                isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            target = target.value
        else:
            break
    return _self_attr(target)


class _FuncWalker(ast.NodeVisitor):
    """Walk ONE class function (and its nested defs) collecting facts."""

    def __init__(self, model: ClassModel, func: str,
                 nested_names: Set[str]):
        self.model = model
        self.func = func
        self.top = func.split(".")[0]
        self.nested_names = nested_names
        self.guard_depth = 0
        model.calls.setdefault(func, set())
        model.cross_calls.setdefault(func, set())
        model.acquires.setdefault(func, [])

    # ------------------------------------------------------------- helpers

    def _access(self, attr: str, lineno: int, write: bool) -> None:
        self.model.accesses.append(Access(
            attr=attr, lineno=lineno, write=write,
            guarded=self.guard_depth > 0, func=self.func))

    def _resolve_local(self, name: str) -> Optional[str]:
        if name in self.nested_names:
            return f"{self.top}.<locals>.{name}"
        return None

    # -------------------------------------------------------------- scopes

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # a nested def runs at CALL time: new scope, guard state reset
        sub = f"{self.top}.<locals>.{node.name}"
        self.model.funcs.add(sub)
        walker = _FuncWalker(self.model, sub, self.nested_names)
        for stmt in node.body:
            walker.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # lambda bodies also run at call time; walk unguarded, same func
        saved = self.guard_depth
        self.guard_depth = 0
        self.visit(node.body)
        self.guard_depth = saved

    def visit_With(self, node: ast.With) -> None:
        held = 0
        for item in node.items:
            expr = item.context_expr
            attr = _self_attr(expr)
            if attr is not None and attr in self.model.locks:
                self.model.acquires[self.func].append((attr, expr.lineno))
                held += 1
            else:
                self.visit(expr)
        self.guard_depth += held
        for stmt in node.body:
            self.visit(stmt)
        self.guard_depth -= held

    visit_AsyncWith = visit_With

    # ------------------------------------------------------------- stores

    def _visit_store_targets(self, targets) -> None:
        for tgt in targets:
            if isinstance(tgt, (ast.Tuple, ast.List)):
                self._visit_store_targets(tgt.elts)
                continue
            base = _store_base_attr(tgt)
            if base is not None:
                self._access(base, tgt.lineno, write=True)
                # subscript indexes still read values (incl. self attrs)
                while isinstance(tgt, ast.Subscript):
                    self.visit(tgt.slice)
                    tgt = tgt.value
            else:
                self.visit(tgt)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._visit_store_targets(node.targets)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._visit_store_targets([node.target])
        if node.value is not None:
            self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._visit_store_targets([node.target])
        self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        self._visit_store_targets(node.targets)

    # -------------------------------------------------------------- loads

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None:
            self._access(attr, node.lineno,
                         write=isinstance(node.ctx, (ast.Store, ast.Del)))
            return
        self.generic_visit(node)

    # -------------------------------------------------------------- calls

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        # threading.Thread(target=...) — the thread-side entry point
        if _call_name(fn) == "Thread":
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                if isinstance(kw.value, ast.Name):
                    local = self._resolve_local(kw.value.id)
                    if local:
                        self.model.thread_targets.add(local)
                    elif kw.value.id in self.model.funcs:
                        self.model.thread_targets.add(kw.value.id)
                target_attr = _self_attr(kw.value)
                if target_attr is not None:
                    self.model.thread_targets.add(target_attr)
        # signal.signal(SIG, self.handler)
        if (_call_name(fn) == "signal" and isinstance(fn, ast.Attribute)
                and len(node.args) >= 2):
            handler = _self_attr(node.args[1])
            if handler is not None:
                self.model.signal_handlers.add(handler)
        # self.m(...) / nested(...) / self.attr.m(...)
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                # an in-class call edge, not a data-attribute access —
                # visit args only, so `self.produce(...)` doesn't read
                # a phantom "produce" attribute
                self.model.calls[self.func].add(fn.attr)
                for arg in node.args:
                    self.visit(arg)
                for kw in node.keywords:
                    self.visit(kw.value)
                return
            else:
                recv_attr = _self_attr(recv)
                if recv_attr is not None:
                    if recv_attr in self.model.locks:
                        if fn.attr == "acquire":
                            self.model.acquires[self.func].append(
                                (recv_attr, node.lineno))
                    elif fn.attr in _MUTATORS:
                        self._access(recv_attr, node.lineno, write=True)
                        self.model.cross_calls[self.func].add(
                            (recv_attr, fn.attr))
                    else:
                        self._access(recv_attr, node.lineno, write=False)
                        self.model.cross_calls[self.func].add(
                            (recv_attr, fn.attr))
                    for arg in node.args:
                        self.visit(arg)
                    for kw in node.keywords:
                        self.visit(kw.value)
                    return
        elif isinstance(fn, ast.Name):
            local = self._resolve_local(fn.id)
            if local:
                self.model.calls[self.func].add(local)
        self.generic_visit(node)


def _annotation_name(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.split(".")[-1].split("[")[0] or None
    return None


def _collect_attr_bindings(model: ClassModel, fn: ast.FunctionDef) -> None:
    """Pass 1 facts from one method: lock/threadsafe/typed attributes."""
    params: Dict[str, str] = {}
    if fn.name == "__init__":
        for arg in fn.args.args + fn.args.kwonlyargs:
            ann = _annotation_name(arg.annotation)
            if ann:
                params[arg.arg] = ann
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            attr = _self_attr(tgt)
            if attr is None:
                continue
            ctor = _call_name(node.value) if isinstance(node.value,
                                                        ast.Call) else None
            if ctor in _LOCK_CTORS:
                model.locks[attr] = _LOCK_CTORS[ctor]
            elif ctor in _THREADSAFE_CTORS:
                model.threadsafe.add(attr)
            elif ctor and ctor[:1].isupper():
                model.attr_types[attr] = ctor
            elif (isinstance(node.value, ast.Name)
                  and node.value.id in params):
                model.attr_types[attr] = params[node.value.id]


def build_class(path: str, node: ast.ClassDef) -> ClassModel:
    model = ClassModel(name=node.name, path=path, lineno=node.lineno)
    methods = [n for n in node.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    model.funcs = {m.name for m in methods}
    for m in methods:
        _collect_attr_bindings(model, m)
    for m in methods:
        nested = {n.name for n in ast.walk(m)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and n is not m}
        walker = _FuncWalker(model, m.name, nested)
        for stmt in m.body:
            walker.visit(stmt)
    return model


def build_module(path: str, src: Optional[str] = None) -> ModuleModel:
    """Parse one file into its per-class models (never raises on bad
    source — the caller reports a syntax problem as its own finding)."""
    if src is None:
        with open(path, encoding="utf-8") as f:
            src = f.read()
    tree = ast.parse(src, filename=path)
    classes = [build_class(path, n) for n in tree.body
               if isinstance(n, ast.ClassDef)]
    return ModuleModel(path=path, tree=tree, src=src, classes=classes)


__all__ = ["Access", "ClassModel", "ModuleModel", "build_class",
           "build_module"]
