"""Rulebook linting: every way a ``(regex → PartitionSpec)`` placement
rulebook can be silently wrong, checked against the abstract param tree.

The failure modes, each mapped to a check id:

- ``dead-rule``       — a rule whose regex matches no leaf path at all
  (typo'd pattern: the leaf it meant to place falls to REPLICATED).
- ``shadowed-rule``   — a rule that matches leaves but never wins one
  (an earlier rule takes every path first; first-match-wins,
  :func:`dtf_tpu.core.sharding.spec_for`).
- ``duplicate-axis``  — the same mesh axis named twice in one
  PartitionSpec (invalid sharding; GSPMD rejects it only at compile time,
  and only if the rule actually fires on the device path).
- ``unknown-axis``    — a spec naming an axis the mesh doesn't have.
- ``rank-overflow``   — spec longer than the leaf's rank.
- ``indivisible-dim`` — a sharded dim not divisible by the product of its
  mesh axes (gives ragged shards: silent padding or a compile error,
  depending on path).
- ``replicated-large-leaf`` — a leaf ≥ ``large_numel`` elements matched by
  NO rule, silently replicated on every device (the classic "regex missed
  the embedding table" failure). Small unmatched leaves (LN scales,
  biases) are the intended default and reported as one ``info`` summary.

``lint_opt_specs`` applies the same per-leaf spec checks to the ZeRO-1
optimizer-state spec tree (:func:`dtf_tpu.core.sharding.zero1_opt_specs`)
for an optimizer family, catching a ``_zero1_leaf_spec`` regression for
any state layout (adam's param-shaped mu/nu, adafactor's rank-reduced
factored moments, sgd's empty state).
"""

from __future__ import annotations

import re
from typing import Any, Mapping, Sequence

import jax
import optax
from jax.sharding import PartitionSpec as P

from dtf_tpu.analysis.findings import Finding
from dtf_tpu.core import sharding as shd

PyTree = Any

#: leaves at or above this many elements must not silently replicate.
LARGE_NUMEL = 1 << 20


def _numel(shape: Sequence[int]) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _spec_entries(spec: P) -> list[tuple[str, ...]]:
    """Normalize each spec entry to a tuple of axis names."""
    out = []
    for s in spec:
        if s is None:
            out.append(())
        elif isinstance(s, str):
            out.append((s,))
        else:
            out.append(tuple(s))
    return out


def check_spec(path: str, spec: P, shape: Sequence[int],
               mesh_shape: Mapping[str, int], *, config: str,
               where: str = "param") -> list[Finding]:
    """Validate one resolved (leaf, spec) pair against the mesh shape."""
    findings = []
    entries = _spec_entries(spec)

    def err(check, detail):
        findings.append(Finding(config, "specs", check, "error",
                                f"{where} {path}: {detail}"))

    seen: set[str] = set()
    for axes in entries:
        for a in axes:
            if a in seen:
                err("duplicate-axis",
                    f"mesh axis {a!r} used twice in spec {spec}")
            seen.add(a)
            if a not in mesh_shape:
                err("unknown-axis",
                    f"spec {spec} names axis {a!r}, mesh has "
                    f"{sorted(mesh_shape)}")
    if len(entries) > len(shape):
        err("rank-overflow",
            f"spec {spec} has {len(entries)} entries for rank-"
            f"{len(shape)} leaf {tuple(shape)}")
        return findings
    for dim, axes in zip(shape, entries):
        size = 1
        for a in axes:
            size *= mesh_shape.get(a, 1)
        if size > 1 and dim % size:
            err("indivisible-dim",
                f"dim {dim} of {tuple(shape)} not divisible by "
                f"{'*'.join(axes)}={size} (spec {spec})")
    return findings


def lint_rules(params: PyTree, rules: Sequence[shd.Rule],
               mesh_shape: Mapping[str, int], *, config: str,
               allow_dead: Sequence[str] = (),
               replicated_ok: Sequence[str] = (),
               large_numel: int = LARGE_NUMEL) -> list[Finding]:
    """Lint a param rulebook against an abstract param tree.

    ``params`` may be real arrays or ``jax.eval_shape`` output — only
    ``.shape`` is read.  ``mesh_shape`` is ``mesh.shape`` (a Mapping), so
    callers can lint against a hypothetical mesh without building one.

    ``allow_dead``: rule patterns (exact strings) that may legitimately
    match nothing in this config (shared rulebooks, e.g. the MoE expert
    rule on a dense GPT) — downgraded to ``info``.
    ``replicated_ok``: leaf-path regexes whose replication is the design
    (pipeline embed/head).  With an EMPTY rulebook the large-leaf check is
    skipped entirely: pure-DP configs replicate params by construction and
    shard optimizer state via ZeRO-1 instead.
    """
    leaves, raw_hits, wins = shd.rule_matches(params, rules)
    findings: list[Finding] = []

    for i, (pattern, spec) in enumerate(rules):
        if raw_hits[i] == 0:
            sev = "info" if pattern in allow_dead else "error"
            findings.append(Finding(
                config, "specs", "dead-rule", sev,
                f"rule {i} {pattern!r} -> {spec} matches no leaf path"
                + (" (declared optional for this config)"
                   if sev == "info" else "")))
        elif wins[i] == 0:
            findings.append(Finding(
                config, "specs", "shadowed-rule", "error",
                f"rule {i} {pattern!r} -> {spec} matches "
                f"{raw_hits[i]} leaves but every one is taken by an "
                f"earlier rule (first-match-wins)"))

    small_replicated = 0
    for leaf in leaves:
        if leaf.rule_index is None:
            intended = (not rules or any(
                re.search(p, leaf.path) for p in replicated_ok))
            if not intended and _numel(leaf.shape) >= large_numel:
                findings.append(Finding(
                    config, "specs", "replicated-large-leaf", "error",
                    f"param {leaf.path} {leaf.shape} "
                    f"({_numel(leaf.shape):,} elems) matched no rule and "
                    f"silently falls to REPLICATED"))
            else:
                small_replicated += 1
        else:
            findings.extend(check_spec(leaf.path, leaf.spec, leaf.shape,
                                       mesh_shape, config=config))
    if small_replicated:
        findings.append(Finding(
            config, "specs", "replicated-small-leaves", "info",
            f"{small_replicated} leaves fall to REPLICATED "
            f"(intended: empty rulebook, declared-ok path, or "
            f"< {large_numel:,} elems)"))
    return findings


def lint_opt_specs(tx: optax.GradientTransformation, params: PyTree,
                   rules: Sequence[shd.Rule], mesh, *, config: str,
                   opt_name: str = "opt", zero1: bool = True
                   ) -> list[Finding]:
    """Validate the optimizer-state spec tree the train state would use.

    ``mesh`` needs only a ``.shape`` mapping (a real Mesh or a stand-in).
    The spec tree is recomputed exactly the way ``state_specs`` does it,
    then every (state leaf, spec) pair goes through :func:`check_spec`.
    """
    abstract_params = jax.eval_shape(lambda p: p, params)
    param_specs = shd.tree_specs(abstract_params, rules)
    if zero1:
        opt_specs = shd.zero1_opt_specs(tx, abstract_params, param_specs,
                                        mesh)
    else:
        opt_specs = shd.opt_specs_like_params(tx, abstract_params,
                                              param_specs)
    abstract_state = jax.eval_shape(tx.init, abstract_params)

    findings: list[Finding] = []
    state_leaves = jax.tree_util.tree_leaves_with_path(abstract_state)
    spec_leaves = jax.tree.leaves(opt_specs,
                                  is_leaf=lambda x: isinstance(x, P))
    if len(state_leaves) != len(spec_leaves):
        findings.append(Finding(
            config, "specs", "opt-spec-tree-mismatch", "error",
            f"{opt_name}: {len(spec_leaves)} specs for "
            f"{len(state_leaves)} state leaves"))
        return findings
    where = f"{opt_name} state" + ("" if zero1 else " (no zero1)")
    for (path, leaf), spec in zip(state_leaves, spec_leaves):
        findings.extend(check_spec(
            shd.path_str(path), spec, leaf.shape, dict(mesh.shape),
            config=config, where=where))
    return findings
