"""Pipeline parallelism — GPipe microbatch schedule as a single SPMD program.

The reference has no pipeline parallelism (SURVEY.md §2c marks PP "out of
reference scope"), but a complete TPU framework needs it for models whose
layers don't fit one chip even under TP. This is the TPU-idiomatic design:
instead of per-stage processes passing activations over a transport (the
PS/worker shape), the per-stage parameters are *stacked* along a leading
``stage`` dimension sharded over the ``pipe`` mesh axis, and the whole
schedule — bubble included — is one ``lax.scan`` inside ``shard_map``:

- every scan step, each stage applies ``stage_fn`` to its current activation
  and ships the result one hop down the ring (``ppermute`` — a single ICI
  neighbor transfer, exactly the point-to-point the hardware is best at);
- stage 0 feeds microbatch ``t`` in at step ``t``; the last stage writes its
  result for microbatch ``t - (S-1)`` into an output buffer;
- the backward schedule needs no code: autodiff of scan+ppermute *is* the
  reverse pipeline (activations are rematerialized per ``jax.checkpoint``
  policy if the caller wraps ``stage_fn``).

Composes with the other axes: batch dims inside a microbatch stay sharded
over ``data`` (and ``seq``/``model`` inside ``stage_fn``), so dp x pp x tp is
one program. Bubble fraction is the usual (S-1)/(M+S-1); choose
``n_microbatches >= 4*n_stages`` to amortize.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dtf_tpu.core.mesh import AXIS_PIPE

PyTree = Any


def stack_stage_params(params_per_stage: list[PyTree]) -> PyTree:
    """Stack S per-stage param pytrees along a new leading stage dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_per_stage)


def init_stacked(init_fn: Callable[[jax.Array], PyTree], n_stages: int,
                 rng: jax.Array) -> PyTree:
    """Initialize S independent stage params, stacked: vmap(init) over rngs.

    The stacked tree is what gets sharded ``P('pipe', ...)`` — the successor
    of the reference's per-PS variable placement, with stages instead of
    parameter servers as the unit of distribution.
    """
    return jax.vmap(init_fn)(jax.random.split(rng, n_stages))


def pipeline_spmd(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    n_microbatches: int,
    mesh: Mesh,
    *,
    axis_name: str = AXIS_PIPE,
    batch_spec: P = P("data"),
    param_spec_fn: Callable[[Any], P] | None = None,
    param_specs_fn: Callable[[PyTree], PyTree] | None = None,
    check_vma: bool = True,
):
    """Build ``f(stacked_params, x) -> y`` running stages over ``axis_name``.

    ``stage_fn(stage_params, x) -> y`` maps one stage over one microbatch and
    must preserve the activation shape/dtype (the homogeneous-stack case —
    transformer blocks; put embedding/head outside the pipeline).

    ``x``: [B, ...] with B divisible by ``n_microbatches`` x data-shards.
    ``stacked_params``: leading dim = pipe-axis size (see
    :func:`init_stacked`), sharded ``P('pipe', ...)``.

    Returns a function usable under ``jit``; gradients flow through to the
    stacked params and the input.

    ``param_specs_fn``: full params→spec-TREE mapping (path-dependent specs,
    e.g. Megatron TP dims inside stages — see
    :mod:`dtf_tpu.models.gpt_pipe_tp`); overrides the leaf-wise
    ``param_spec_fn``. ``check_vma=False`` disables shard_map's
    varying-manual-axes typing for bodies that mix axes it cannot type
    (per-shard collectives inside the stage).
    """
    n_stages = mesh.shape.get(axis_name, 1)

    def sharded(params, x):
        if x.shape[0] % n_microbatches:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by n_microbatches="
                f"{n_microbatches}")
        n_stacked = jax.tree.leaves(params)[0].shape[0]
        if n_stacked != n_stages:
            raise ValueError(
                f"stage stack has {n_stacked} stages but the '{axis_name}' "
                f"mesh axis has {n_stages} shards; they must match (each "
                "device runs exactly one stage)")
        if n_stages == 1:
            # degenerate pipe axis: plain application, no schedule.
            squeezed = jax.tree.map(lambda p: p[0], params)
            return stage_fn(squeezed, x)

        micro = x.reshape((n_microbatches, x.shape[0] // n_microbatches)
                          + x.shape[1:])

        def body(params, xs):
            # per-shard: params [1, ...] slice of the stage stack; xs
            # [M, mb/data, ...] microbatches (replicated over pipe).
            # pvary: xs arrives replicated over pipe but mixes with
            # pipe-varying values (stage outputs) below — shard_map's
            # varying-manual-axes type system requires the promotion to be
            # explicit. (Skipped when the caller disabled vma typing.)
            if check_vma:
                xs = jax.lax.pcast(xs, (axis_name,), to="varying")
            p = jax.tree.map(lambda t: t[0], params)
            idx = jax.lax.axis_index(axis_name)
            shift = [(i, i + 1) for i in range(n_stages - 1)]

            def step(carry, t):
                act, out = carry
                x_t = jax.lax.dynamic_index_in_dim(
                    xs, jnp.clip(t, 0, n_microbatches - 1), 0, keepdims=False)
                inp = jnp.where(idx == 0, x_t, act)
                y = stage_fn(p, inp)
                # ship to the next stage; stage S-1's y falls off the end
                # (shift is not a ring — no wraparound into stage 0).
                act = jax.lax.ppermute(y, axis_name, shift)
                ot = t - (n_stages - 1)
                ot_c = jnp.clip(ot, 0, n_microbatches - 1)
                write = (idx == n_stages - 1) & (ot >= 0)
                cur = jax.lax.dynamic_index_in_dim(out, ot_c, 0,
                                                   keepdims=False)
                out = jax.lax.dynamic_update_index_in_dim(
                    out, jnp.where(write, y, cur), ot_c, 0)
                return (act, out), None

            act0 = jnp.zeros_like(xs[0])
            out0 = jnp.zeros_like(xs)
            (_, out), _ = jax.lax.scan(
                step, (act0, out0), jnp.arange(n_microbatches + n_stages - 1))
            # outputs live on the last stage only (zeros elsewhere) —
            # replicate over the pipe axis with one psum.
            return jax.lax.psum(out, axis_name)

        if param_specs_fn is not None:
            p_spec = param_specs_fn(params)
        elif param_spec_fn is not None:
            p_spec = jax.tree.map(param_spec_fn, params)
        else:
            p_spec = stage_param_specs(params, axis_name)
        micro_spec = P(None, *batch_spec)
        y = jax.shard_map(
            body, mesh=mesh,
            in_specs=(p_spec, micro_spec), out_specs=micro_spec,
            check_vma=check_vma,
        )(params, micro)
        return y.reshape(x.shape[0:1] + y.shape[2:])

    return sharded


def stage_param_specs(params: PyTree, axis_name: str = AXIS_PIPE) -> PyTree:
    """P('pipe') spec tree for a stacked-stage param tree (for train-state
    sharding rules / create_train_state param_rules bypass)."""
    return jax.tree.map(lambda _: P(axis_name), params)


def interleaved_stage_order(n_devices: int, v_per_device: int) -> list[int]:
    """Stack-row order for the interleaved schedule.

    Device ``i`` must hold logical stages ``{i, n+i, 2n+i, ...}`` (the
    Megatron interleaved assignment), but a P('pipe')-sharded stack gives
    each device CONTIGUOUS rows. So the stack is laid out device-major:
    row ``i*V + v`` holds logical stage ``v*n + i``. Returns that logical
    order; use :func:`reorder_stages` to permute a logically-ordered stack.
    """
    return [v * n_devices + i
            for i in range(n_devices) for v in range(v_per_device)]


def reorder_stages(stacked: PyTree, n_devices: int,
                   v_per_device: int) -> PyTree:
    """Permute a logically-ordered [S, ...] stack into interleaved layout."""
    import numpy as np

    order = np.asarray(interleaved_stage_order(n_devices, v_per_device))
    return jax.tree.map(lambda t: t[order], stacked)


def pipeline_interleaved(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    n_microbatches: int,
    mesh: Mesh,
    v_per_device: int,
    *,
    axis_name: str = AXIS_PIPE,
    batch_spec: P = P("data"),
    check_vma: bool = True,
):
    """Interleaved (circular) pipeline schedule — the Megatron-style
    bubble-reduction over :func:`pipeline_spmd`.

    Each device holds ``V = v_per_device`` model chunks (logical stage
    ``v*n + i`` for device ``i``; total S = n*V finer-grained stages), and
    the activation circles the device ring V times per microbatch. The
    schedule is closed-form: device ``i`` runs (microbatch m, chunk v) at
    tick ``t = i + (m mod n) + n*(v + V*(m//n))`` — a unique (m, v) per
    (i, t), so every device does exactly one chunk per tick in steady state
    and the fill/drain bubble shrinks from (n-1)/M to ~(n-1)/(V*M) of total
    work at the cost of V x more ppermute hops (cheap: neighbor ICI).

    ``stacked_params``: [n*V, ...] in INTERLEAVED row order (see
    :func:`reorder_stages`), sharded P('pipe'). ``n_microbatches`` must be
    a multiple of the pipe-axis size. Gradients flow through scan+ppermute
    like the GPipe path; wrap ``stage_fn`` in ``jax.checkpoint`` to trade
    recompute for activation memory.
    """
    n_stages = mesh.shape.get(axis_name, 1)
    V = v_per_device

    def sharded(params, x):
        if x.shape[0] % n_microbatches:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by n_microbatches="
                f"{n_microbatches}")
        if n_microbatches % max(n_stages, 1):
            raise ValueError(
                f"n_microbatches={n_microbatches} must be a multiple of the "
                f"'{axis_name}' axis size {n_stages} for the interleaved "
                "schedule")
        n_stacked = jax.tree.leaves(params)[0].shape[0]
        if n_stacked != n_stages * V:
            raise ValueError(
                f"stage stack has {n_stacked} rows but needs "
                f"{n_stages} devices x {V} chunks = {n_stages * V}")
        if n_stages == 1:
            out = x
            for v in range(V):
                out = stage_fn(jax.tree.map(lambda t: t[v], params), out)
            return out

        m_count = n_microbatches
        micro = x.reshape((m_count, x.shape[0] // m_count) + x.shape[1:])
        total_ticks = ((n_stages - 1) + ((m_count - 1) % n_stages)
                       + n_stages * ((V - 1) + V * ((m_count - 1)
                                                    // n_stages)) + 1)

        def body(params, xs):
            if check_vma:
                xs = jax.lax.pcast(xs, (axis_name,), to="varying")
            p_local = jax.tree.map(lambda t: t, params)   # [V, ...] shard
            idx = jax.lax.axis_index(axis_name)
            ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]

            def step(carry, t):
                act, out = carry
                # closed-form schedule decode for (this device, tick t)
                u = t - idx
                active = u >= 0
                uc = jnp.maximum(u, 0)
                m_mod = uc % n_stages
                w = uc // n_stages           # = v + V * group
                v = w % V
                g = w // V
                m = g * n_stages + m_mod
                active = active & (m < m_count)
                m_c = jnp.clip(m, 0, m_count - 1)

                x_t = jax.lax.dynamic_index_in_dim(xs, m_c, 0,
                                                   keepdims=False)
                inp = jnp.where((idx == 0) & (v == 0), x_t, act)
                stage_p = jax.tree.map(
                    lambda t_: jax.lax.dynamic_index_in_dim(
                        t_, v, 0, keepdims=False), p_local)
                y = stage_fn(stage_p, inp)

                # final-chunk output on the last device → result buffer
                write = active & (idx == n_stages - 1) & (v == V - 1)
                cur = jax.lax.dynamic_index_in_dim(out, m_c, 0,
                                                   keepdims=False)
                out = jax.lax.dynamic_update_index_in_dim(
                    out, jnp.where(write, y, cur), m_c, 0)
                # everything rides the wraparound ring; the receiver's
                # schedule decode tells it whether the arrival is live
                act = jax.lax.ppermute(y, axis_name, ring)
                return (act, out), None

            act0 = jnp.zeros_like(xs[0])
            out0 = jnp.zeros_like(xs)
            (_, out), _ = jax.lax.scan(step, (act0, out0),
                                       jnp.arange(total_ticks))
            # result lives on the last device only; replicate over pipe.
            # psum would double-count nothing (zeros elsewhere).
            return jax.lax.psum(out, axis_name)

        p_spec = stage_param_specs(params, axis_name)
        micro_spec = P(None, *batch_spec)
        y = jax.shard_map(
            body, mesh=mesh,
            in_specs=(p_spec, micro_spec), out_specs=micro_spec,
            check_vma=check_vma,
        )(params, micro)
        return y.reshape(x.shape[0:1] + y.shape[2:])

    return sharded
