"""Pipeline parallelism — GPipe microbatch schedule as a single SPMD program.

The reference has no pipeline parallelism (SURVEY.md §2c marks PP "out of
reference scope"), but a complete TPU framework needs it for models whose
layers don't fit one chip even under TP. This is the TPU-idiomatic design:
instead of per-stage processes passing activations over a transport (the
PS/worker shape), the per-stage parameters are *stacked* along a leading
``stage`` dimension sharded over the ``pipe`` mesh axis, and the whole
schedule — bubble included — is one ``lax.scan`` inside ``shard_map``:

- every scan step, each stage applies ``stage_fn`` to its current activation
  and ships the result one hop down the ring (``ppermute`` — a single ICI
  neighbor transfer, exactly the point-to-point the hardware is best at);
- stage 0 feeds microbatch ``t`` in at step ``t``; the last stage writes its
  result for microbatch ``t - (S-1)`` into an output buffer;
- the backward schedule needs no code: autodiff of scan+ppermute *is* the
  reverse pipeline (activations are rematerialized per ``jax.checkpoint``
  policy if the caller wraps ``stage_fn``).

Composes with the other axes: batch dims inside a microbatch stay sharded
over ``data`` (and ``seq``/``model`` inside ``stage_fn``), so dp x pp x tp is
one program. Bubble fraction is the usual (S-1)/(M+S-1); choose
``n_microbatches >= 4*n_stages`` to amortize.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dtf_tpu.core.comms import ring_perm, shift_perm
from dtf_tpu.core.mesh import AXIS_PIPE

PyTree = Any


def stack_stage_params(params_per_stage: list[PyTree]) -> PyTree:
    """Stack S per-stage param pytrees along a new leading stage dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_per_stage)


def init_stacked(init_fn: Callable[[jax.Array], PyTree], n_stages: int,
                 rng: jax.Array) -> PyTree:
    """Initialize S independent stage params, stacked: vmap(init) over rngs.

    The stacked tree is what gets sharded ``P('pipe', ...)`` — the successor
    of the reference's per-PS variable placement, with stages instead of
    parameter servers as the unit of distribution.
    """
    return jax.vmap(init_fn)(jax.random.split(rng, n_stages))


def pipeline_spmd(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    n_microbatches: int,
    mesh: Mesh,
    *,
    axis_name: str = AXIS_PIPE,
    batch_spec: P = P("data"),
    param_spec_fn: Callable[[Any], P] | None = None,
    param_specs_fn: Callable[[PyTree], PyTree] | None = None,
    check_vma: bool = True,
):
    """Build ``f(stacked_params, x) -> y`` running stages over ``axis_name``.

    ``stage_fn(stage_params, x) -> y`` maps one stage over one microbatch and
    must preserve the activation shape/dtype (the homogeneous-stack case —
    transformer blocks; put embedding/head outside the pipeline).

    ``x``: [B, ...] with B divisible by ``n_microbatches`` x data-shards.
    ``stacked_params``: leading dim = pipe-axis size (see
    :func:`init_stacked`), sharded ``P('pipe', ...)``.

    Returns a function usable under ``jit``; gradients flow through to the
    stacked params and the input.

    ``param_specs_fn``: full params→spec-TREE mapping (path-dependent specs,
    e.g. Megatron TP dims inside stages — see
    :mod:`dtf_tpu.models.gpt_pipe_tp`); overrides the leaf-wise
    ``param_spec_fn``. ``check_vma=False`` disables shard_map's
    varying-manual-axes typing for bodies that mix axes it cannot type
    (per-shard collectives inside the stage).
    """
    n_stages = mesh.shape.get(axis_name, 1)

    def sharded(params, x):
        if x.shape[0] % n_microbatches:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by n_microbatches="
                f"{n_microbatches}")
        n_stacked = jax.tree.leaves(params)[0].shape[0]
        if n_stacked != n_stages:
            raise ValueError(
                f"stage stack has {n_stacked} stages but the '{axis_name}' "
                f"mesh axis has {n_stages} shards; they must match (each "
                "device runs exactly one stage)")
        if n_stages == 1:
            # degenerate pipe axis: plain application, no schedule.
            squeezed = jax.tree.map(lambda p: p[0], params)
            return stage_fn(squeezed, x)

        micro = x.reshape((n_microbatches, x.shape[0] // n_microbatches)
                          + x.shape[1:])

        def body(params, xs):
            # per-shard: params [1, ...] slice of the stage stack; xs
            # [M, mb/data, ...] microbatches (replicated over pipe).
            # pvary: xs arrives replicated over pipe but mixes with
            # pipe-varying values (stage outputs) below — shard_map's
            # varying-manual-axes type system requires the promotion to be
            # explicit. (Skipped when the caller disabled vma typing.)
            if check_vma:
                xs = jax.lax.pcast(xs, (axis_name,), to="varying")
            p = jax.tree.map(lambda t: t[0], params)
            idx = jax.lax.axis_index(axis_name)
            shift = shift_perm(n_stages)

            def step(carry, t):
                act, out = carry
                x_t = jax.lax.dynamic_index_in_dim(
                    xs, jnp.clip(t, 0, n_microbatches - 1), 0, keepdims=False)
                inp = jnp.where(idx == 0, x_t, act)
                y = stage_fn(p, inp)
                # ship to the next stage; stage S-1's y falls off the end
                # (shift is not a ring — no wraparound into stage 0).
                act = jax.lax.ppermute(y, axis_name, shift)
                ot = t - (n_stages - 1)
                ot_c = jnp.clip(ot, 0, n_microbatches - 1)
                write = (idx == n_stages - 1) & (ot >= 0)
                cur = jax.lax.dynamic_index_in_dim(out, ot_c, 0,
                                                   keepdims=False)
                out = jax.lax.dynamic_update_index_in_dim(
                    out, jnp.where(write, y, cur), ot_c, 0)
                return (act, out), None

            act0 = jnp.zeros_like(xs[0])
            out0 = jnp.zeros_like(xs)
            (_, out), _ = jax.lax.scan(
                step, (act0, out0), jnp.arange(n_microbatches + n_stages - 1))
            # outputs live on the last stage only (zeros elsewhere) —
            # replicate over the pipe axis with one psum.
            return jax.lax.psum(out, axis_name)

        if param_specs_fn is not None:
            p_spec = param_specs_fn(params)
        elif param_spec_fn is not None:
            p_spec = jax.tree.map(param_spec_fn, params)
        else:
            p_spec = stage_param_specs(params, axis_name)
        micro_spec = P(None, *batch_spec)
        y = jax.shard_map(
            body, mesh=mesh,
            in_specs=(p_spec, micro_spec), out_specs=micro_spec,
            check_vma=check_vma,
        )(params, micro)
        return y.reshape(x.shape[0:1] + y.shape[2:])

    return sharded


def stage_param_specs(params: PyTree, axis_name: str = AXIS_PIPE) -> PyTree:
    """P('pipe') spec tree for a stacked-stage param tree (for train-state
    sharding rules / create_train_state param_rules bypass)."""
    return jax.tree.map(lambda _: P(axis_name), params)


def _axes_of(spec: P) -> tuple[str, ...]:
    """Flatten a PartitionSpec into the mesh axis names it mentions."""
    axes: list[str] = []
    for part in spec:
        if part is None:
            continue
        if isinstance(part, str):
            axes.append(part)
        else:
            axes.extend(part)
    return tuple(axes)


def pipeline_1f1b_grads(
    first_fn: Callable[[PyTree, PyTree], jax.Array],
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    last_fn: Callable[[PyTree, jax.Array, PyTree], tuple[jax.Array, jax.Array]],
    n_microbatches: int,
    mesh: Mesh,
    *,
    axis_name: str = AXIS_PIPE,
    batch_spec: P = P("data"),
    check_vma: bool = False,
):
    """1F1B-style fused forward/backward pipeline — O(S) activation stash.

    The GPipe/interleaved schedules above differentiate *through* the scan,
    so autodiff stashes residuals for ALL ``M`` microbatches before the first
    backward runs (the classic GPipe memory profile; ``jax.checkpoint`` on
    ``stage_fn`` shrinks each stash to the stage input but not their count).
    This schedule interleaves forwards and backwards in ONE scan so at most
    ``2S-2`` microbatches are ever in flight per stage — the 1F1B property —
    which means it cannot ride ``jax.grad``: it computes gradients itself
    (per-microbatch ``jax.vjp``, backward recomputes the stage forward from
    the stashed stage *input* — remat is built in) and returns them.

    Round schedule (device ``i`` of ``S``, microbatch ``m`` of ``M``): each
    scan round ``r`` has a forward sub-slot then a backward sub-slot, with a
    neighbor ``ppermute`` after each:

    - ``F(i, m)`` runs at round ``r = i + m`` (activations flow down one hop
      per round, exactly like :func:`pipeline_spmd`);
    - ``B(i, m)`` runs at round ``r = (2S-2-i) + m`` (cotangents flow back up
      one hop per round; the last stage's ``B(S-1, m)`` shares round
      ``S-1+m`` with its own ``F`` — loss + head run inside its backward).

    Consecutive stages are one round apart in both directions, every arrival
    is consumed the round it lands, and a stage's in-flight window
    ``r_B - r_F = 2S-2-2i`` bounds the stash. Total rounds ``M + 2S - 2`` —
    the same fill/drain bubble class as GPipe, at ~``S/M``-th the stash.

    ``first_fn(first_params, mb) -> x`` feeds stage 0 (e.g. embedding);
    ``last_fn(last_params, y, mb) -> (loss_sum, weight)`` consumes the final
    stage output (e.g. LM head + cross-entropy, returning the SUM over the
    microbatch plus its weight). The total loss is ``Σ loss_sum / Σ weight``
    and gradients are of exactly that scalar (weights must not depend on
    params), so results match ``jax.grad`` of the equivalent un-pipelined
    loss. Both run under the schedule: ``first_fn`` only on stage 0's F
    rounds, ``last_fn`` (forward + vjp) only on the last stage's B rounds.

    Returns ``f(first_params, stacked_params, last_params, batch) ->
    (loss_sum, weight, (d_first, d_stages, d_last))`` — gradient SUMS in
    f32; divide by ``weight`` for the gradient of the mean loss.
    ``batch`` is a pytree of ``[B, ...]`` arrays, ``B`` divisible by
    ``n_microbatches`` x the batch shards. Per-round branch predicates
    depend only on the pipe index, so in-branch collectives over other mesh
    axes (e.g. ring attention over ``seq`` inside ``stage_fn``) stay
    uniform within their groups — dp x pp x sp composes.
    """
    n_stages = mesh.shape.get(axis_name, 1)
    S, M = n_stages, n_microbatches
    reduce_axes = _axes_of(batch_spec)
    all_axes = (axis_name,) + reduce_axes

    def z32(p):
        return jax.tree.map(lambda t: jnp.zeros(t.shape, jnp.float32), p)

    def add32(a, d):
        return jax.tree.map(lambda t, u: t + u.astype(jnp.float32), a, d)

    def f(p_first, p_stack, p_last, batch):
        b0 = jax.tree.leaves(batch)[0].shape[0]
        if b0 % M:
            raise ValueError(
                f"batch {b0} not divisible by n_microbatches={M}")
        n_stacked = jax.tree.leaves(p_stack)[0].shape[0]
        if n_stacked != S:
            raise ValueError(
                f"stage stack has {n_stacked} stages but the '{axis_name}' "
                f"mesh axis has {S} shards; they must match")
        micro = jax.tree.map(
            lambda x: x.reshape((M, x.shape[0] // M) + x.shape[1:]), batch)

        if S == 1:
            # degenerate pipe axis: plain per-microbatch value_and_grad,
            # summed — identical math, no schedule.
            def one(pf, ps, pl, mb):
                x = first_fn(pf, mb)
                y = stage_fn(jax.tree.map(lambda t: t[0], ps), x)
                return last_fn(pl, y, mb)

            def body(carry, mb):
                gf, gs, gl, ls, ws = carry
                (l, w), g = jax.value_and_grad(
                    one, argnums=(0, 1, 2), has_aux=True)(
                        p_first, p_stack, p_last, mb)
                return (add32(gf, g[0]), add32(gs, g[1]), add32(gl, g[2]),
                        ls + l, ws + w), None

            (gf, gs, gl, ls, ws), _ = jax.lax.scan(
                body, (z32(p_first), z32(p_stack), z32(p_last),
                       jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
                micro)
            return ls, ws, (gf, gs, gl)

        C = 2 * S - 1          # stash slots; in-flight <= 2S-2 (see above)
        R = M + 2 * S - 2      # total rounds

        def body(p_first, p_stack, p_last, mb):
            p_stage = jax.tree.map(lambda t: t[0], p_stack)
            idx = jax.lax.axis_index(axis_name)
            down = shift_perm(S)
            up = shift_perm(S, shift=-1)
            mb0 = jax.tree.map(lambda t: t[0], mb)
            x_sd = jax.eval_shape(first_fn, p_first, mb0)
            act0 = jnp.zeros(x_sd.shape, x_sd.dtype)
            stash0 = jnp.zeros((C,) + x_sd.shape, x_sd.dtype)

            def pick(m):
                return jax.tree.map(
                    lambda t: jax.lax.dynamic_index_in_dim(
                        t, m, 0, keepdims=False), mb)

            def round_fn(carry, r):
                act, cot, stash, gf, gs, gl, ls, ws = carry
                m_f = r - idx
                f_on = (m_f >= 0) & (m_f < M)
                m_fc = jnp.clip(m_f, 0, M - 1)
                m_b = r - (2 * S - 2 - idx)
                b_on = (m_b >= 0) & (m_b < M)
                m_bc = jnp.clip(m_b, 0, M - 1)

                # Control-flow invariant: ``stage_fn`` may contain
                # collectives over OTHER mesh axes (ring/halo attention over
                # seq, psums over data inside the stage), and collectives
                # must never sit under pipe-varying `lax.cond` — the branch
                # assignment then differs across pipe ranks and the lowered
                # collective schedule corrupts values (observed on the CPU
                # sim). So the stage forward AND its vjp run UNCONDITIONALLY
                # every round — exactly like the GPipe schedule's bubble
                # ticks — with `where`-selected inputs, masked writes, and a
                # zeroed cotangent when inactive (vjp is linear in the
                # cotangent, so inactive grad contributions are exactly 0).
                # first_fn/last_fn stay under cond: they must be
                # collective-free (embedding lookup / head + local loss).

                # ---- forward sub-slot ----
                mb_f = pick(m_fc)
                x_in = jax.lax.cond(
                    idx == 0,
                    lambda: first_fn(p_first, mb_f).astype(act.dtype),
                    lambda: act)
                y = stage_fn(p_stage, x_in)
                cur = jax.lax.dynamic_index_in_dim(stash, m_fc % C, 0,
                                                   keepdims=False)
                stash = jax.lax.dynamic_update_index_in_dim(
                    stash, jnp.where(f_on, x_in, cur), m_fc % C, 0)
                act = jax.lax.ppermute(
                    jnp.where(f_on, y, jnp.zeros_like(y)), axis_name, down)

                # ---- backward sub-slot ----
                mb_b = pick(m_bc)
                x_b = jax.lax.dynamic_index_in_dim(stash, m_bc % C, 0,
                                                   keepdims=False)
                y2, svjp = jax.vjp(stage_fn, p_stage, x_b)

                def last_dy(_):
                    def lf(pl, yy):
                        return last_fn(pl, yy, mb_b)
                    l, lvjp, w = jax.vjp(lf, p_last, y2, has_aux=True)
                    seed = jnp.where(b_on, jnp.ones_like(l),
                                     jnp.zeros_like(l))
                    dpl, dy = lvjp(seed)
                    on = b_on.astype(jnp.float32)
                    return (dy.astype(y2.dtype), add32(gl, dpl),
                            ls + on * l.astype(jnp.float32),
                            ws + on * w.astype(jnp.float32))

                dy, gl, ls, ws = jax.lax.cond(
                    idx == S - 1, last_dy,
                    lambda _: (jnp.where(b_on, cot, jnp.zeros_like(cot)),
                               gl, ls, ws),
                    None)
                dps, dx = svjp(dy)
                gs = add32(gs, dps)

                def first_g(_):
                    _, fvjp = jax.vjp(lambda pf: first_fn(pf, mb_b),
                                      p_first)
                    (dpf,) = fvjp(dx.astype(x_sd.dtype))
                    return add32(gf, dpf)

                gf = jax.lax.cond(idx == 0, first_g, lambda _: gf, None)
                cot = jax.lax.ppermute(dx.astype(act.dtype), axis_name, up)
                return (act, cot, stash, gf, gs, gl, ls, ws), None

            init = (act0, jnp.zeros_like(act0), stash0,
                    z32(p_first), z32(p_stage), z32(p_last),
                    jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
            (_, _, _, gf, gs, gl, ls, ws), _ = jax.lax.scan(
                round_fn, init, jnp.arange(R))

            # grads/loss are partial sums: stage grads live on their own
            # pipe rank but are partial over the batch axes; first/last
            # grads and the loss live on one pipe rank AND are partial over
            # the batch axes.
            if reduce_axes:
                gs = jax.lax.psum(gs, reduce_axes)
            gf = jax.lax.psum(gf, all_axes)
            gl = jax.lax.psum(gl, all_axes)
            ls = jax.lax.psum(ls, all_axes)
            ws = jax.lax.psum(ws, all_axes)
            # re-stack the local stage-grad row so out_specs P(axis_name)
            # maps rows back to the stacked layout.
            gs = jax.tree.map(lambda t: t[None], gs)
            return ls, ws, gf, gs, gl

        micro_spec = P(None, *batch_spec)
        ls, ws, gf, gs, gl = jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(axis_name), P(),
                      jax.tree.map(lambda _: micro_spec, batch)),
            out_specs=(P(), P(), P(), P(axis_name), P()),
            check_vma=check_vma,
        )(p_first, p_stack, p_last, micro)
        return ls, ws, (gf, gs, gl)

    return f


def pipeline_zb_grads(
    first_fn: Callable[[PyTree, PyTree], jax.Array],
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    last_fn: Callable[[PyTree, jax.Array, PyTree], tuple],
    n_microbatches: int,
    mesh: Mesh,
    *,
    axis_name: str = AXIS_PIPE,
    batch_spec: P = P("data"),
    check_vma: bool = False,
):
    """Zero-bubble 1F1B: W/B-split backward, W scheduled into the bubble.

    Same contract, signature and schedule skeleton as
    :func:`pipeline_1f1b_grads`, but each microbatch's backward is split
    (the ZB-H1 move, arxiv 2412.14374):

    - ``B(i, m)`` — activation-grad only (``vjp`` w.r.t. the stage INPUT),
      on the critical path: the cotangent must reach stage ``i-1`` next
      round. Runs where 1F1B ran its fused backward, ``r = 2S-2-i + m``,
      and pushes ``dy`` into a depth-``S`` ring (the stage input is already
      in the 1F1B remat stash — slot ``m % C`` is not overwritten until
      round ``i + m + 2S-1``, after every consumer).
    - ``W(i, m)`` — weight-grad (``vjp`` w.r.t. the stage PARAMS with the
      stashed ``dy``), deferrable: nothing downstream consumes it until the
      end-of-step psum. It runs at ``r = 2S-2 + m`` — device ``i`` thereby
      defers exactly ``i`` W passes into its ``i`` post-drain idle rounds,
      so the last W lands on the last round and total rounds stay
      ``M + 2S-2``. The stash bound (``2S-1`` slots + the ``S``-deep dy
      ring) and the 1F1B <=2S-2-in-flight property are preserved.

    In the lockstep scan both sub-slots still execute every round (masked
    when idle — the collective-uniformity invariant below), so the CPU-sim
    wall clock does not shrink; the win is on the MPMD executor the
    schedule targets, where a device's W fills wall-clock holes between
    dependency-gated F/B ops (see :func:`schedule_bubble_model` for the
    step-count accounting, and ``scripts/bench_pipe_mem.py`` for the
    banked rows). On this remat-style path W re-runs the stage forward
    from the stashed input (same recompute class as 1F1B's fused
    backward, paid once more).

    Gradient accumulation order is pinned to 1F1B's: W contributions are
    popped FIFO (increasing ``m``), and idle-round contributions are exact
    zeros (vjp is linear in the cotangent), so on integer-valued data the
    returned grads are BITWISE equal to :func:`pipeline_1f1b_grads` —
    asserted in tests/test_pipeline.py.
    """
    n_stages = mesh.shape.get(axis_name, 1)
    if n_stages == 1:
        # degenerate pipe axis: no bubble to fill, no schedule — the 1F1B
        # per-microbatch value_and_grad scan is already fused and optimal.
        return pipeline_1f1b_grads(
            first_fn, stage_fn, last_fn, n_microbatches, mesh,
            axis_name=axis_name, batch_spec=batch_spec, check_vma=check_vma)
    S, M = n_stages, n_microbatches
    reduce_axes = _axes_of(batch_spec)
    all_axes = (axis_name,) + reduce_axes

    def z32(p):
        return jax.tree.map(lambda t: jnp.zeros(t.shape, jnp.float32), p)

    def add32(a, d):
        return jax.tree.map(lambda t, u: t + u.astype(jnp.float32), a, d)

    def f(p_first, p_stack, p_last, batch):
        b0 = jax.tree.leaves(batch)[0].shape[0]
        if b0 % M:
            raise ValueError(
                f"batch {b0} not divisible by n_microbatches={M}")
        n_stacked = jax.tree.leaves(p_stack)[0].shape[0]
        if n_stacked != S:
            raise ValueError(
                f"stage stack has {n_stacked} stages but the '{axis_name}' "
                f"mesh axis has {S} shards; they must match")
        micro = jax.tree.map(
            lambda x: x.reshape((M, x.shape[0] // M) + x.shape[1:]), batch)

        C = 2 * S - 1          # stash slots; in-flight <= 2S-2 (1F1B bound)
        R = M + 2 * S - 2      # total rounds — unchanged by the W split

        def body(p_first, p_stack, p_last, mb):
            p_stage = jax.tree.map(lambda t: t[0], p_stack)
            idx = jax.lax.axis_index(axis_name)
            down = shift_perm(S)
            up = shift_perm(S, shift=-1)
            mb0 = jax.tree.map(lambda t: t[0], mb)
            x_sd = jax.eval_shape(first_fn, p_first, mb0)
            act0 = jnp.zeros(x_sd.shape, x_sd.dtype)
            stash0 = jnp.zeros((C,) + x_sd.shape, x_sd.dtype)
            dyq0 = jnp.zeros((S,) + x_sd.shape, x_sd.dtype)

            def pick(m):
                return jax.tree.map(
                    lambda t: jax.lax.dynamic_index_in_dim(
                        t, m, 0, keepdims=False), mb)

            def round_fn(carry, r):
                act, cot, stash, dyq, gf, gs, gl, ls, ws = carry
                m_f = r - idx
                f_on = (m_f >= 0) & (m_f < M)
                m_fc = jnp.clip(m_f, 0, M - 1)
                m_b = r - (2 * S - 2 - idx)
                b_on = (m_b >= 0) & (m_b < M)
                m_bc = jnp.clip(m_b, 0, M - 1)
                m_w = r - (2 * S - 2)
                w_on = (m_w >= 0) & (m_w < M)
                m_wc = jnp.clip(m_w, 0, M - 1)

                # Collective-uniformity invariant: exactly as in
                # pipeline_1f1b_grads, the stage forward, its B (input)
                # vjp and its W (param) vjp all run UNCONDITIONALLY every
                # round — masked inputs / masked stash writes / zeroed
                # cotangents — because stage_fn may contain collectives
                # over other mesh axes and those must never sit under a
                # pipe-varying lax.cond. first_fn/last_fn stay under cond
                # (collective-free by contract).

                # ---- forward sub-slot (identical to 1F1B) ----
                mb_f = pick(m_fc)
                x_in = jax.lax.cond(
                    idx == 0,
                    lambda: first_fn(p_first, mb_f).astype(act.dtype),
                    lambda: act)
                y = stage_fn(p_stage, x_in)
                cur = jax.lax.dynamic_index_in_dim(stash, m_fc % C, 0,
                                                   keepdims=False)
                stash = jax.lax.dynamic_update_index_in_dim(
                    stash, jnp.where(f_on, x_in, cur), m_fc % C, 0)
                act = jax.lax.ppermute(
                    jnp.where(f_on, y, jnp.zeros_like(y)), axis_name, down)

                # ---- B sub-slot: activation grad only ----
                mb_b = pick(m_bc)
                x_b = jax.lax.dynamic_index_in_dim(stash, m_bc % C, 0,
                                                   keepdims=False)
                y2, xvjp = jax.vjp(lambda xx: stage_fn(p_stage, xx), x_b)

                def last_dy(_):
                    def lf(pl, yy):
                        return last_fn(pl, yy, mb_b)
                    l, lvjp, w = jax.vjp(lf, p_last, y2, has_aux=True)
                    seed = jnp.where(b_on, jnp.ones_like(l),
                                     jnp.zeros_like(l))
                    dpl, dy = lvjp(seed)
                    on = b_on.astype(jnp.float32)
                    return (dy.astype(y2.dtype), add32(gl, dpl),
                            ls + on * l.astype(jnp.float32),
                            ws + on * w.astype(jnp.float32))

                dy, gl, ls, ws = jax.lax.cond(
                    idx == S - 1, last_dy,
                    lambda _: (jnp.where(b_on, cot, jnp.zeros_like(cot)),
                               gl, ls, ws),
                    None)
                (dx,) = xvjp(dy)
                # push dy for the deferred W pass; slot m % S is not
                # re-written until B(m+S) at round 2S-2-i+m+S, strictly
                # after W(m) pops it at round 2S-2+m (i <= S-1).
                qcur = jax.lax.dynamic_index_in_dim(dyq, m_bc % S, 0,
                                                    keepdims=False)
                dyq = jax.lax.dynamic_update_index_in_dim(
                    dyq, jnp.where(b_on, dy.astype(act.dtype), qcur),
                    m_bc % S, 0)

                def first_g(_):
                    _, fvjp = jax.vjp(lambda pf: first_fn(pf, mb_b),
                                      p_first)
                    (dpf,) = fvjp(dx.astype(x_sd.dtype))
                    return add32(gf, dpf)

                gf = jax.lax.cond(idx == 0, first_g, lambda _: gf, None)
                cot = jax.lax.ppermute(dx.astype(act.dtype), axis_name, up)

                # ---- W sub-slot: deferred weight grad, FIFO pop ----
                # stash slot m % C still holds the stage input (see
                # docstring); the forward is recomputed from it, exactly
                # the remat 1F1B's fused backward did.
                x_w = jax.lax.dynamic_index_in_dim(stash, m_wc % C, 0,
                                                   keepdims=False)
                dy_w = jax.lax.dynamic_index_in_dim(dyq, m_wc % S, 0,
                                                    keepdims=False)
                _, pvjp = jax.vjp(lambda q: stage_fn(q, x_w), p_stage)
                (dps,) = pvjp(jnp.where(w_on, dy_w, jnp.zeros_like(dy_w)))
                gs = add32(gs, dps)
                return (act, cot, stash, dyq, gf, gs, gl, ls, ws), None

            init = (act0, jnp.zeros_like(act0), stash0, dyq0,
                    z32(p_first), z32(p_stage), z32(p_last),
                    jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
            (_, _, _, _, gf, gs, gl, ls, ws), _ = jax.lax.scan(
                round_fn, init, jnp.arange(R))

            if reduce_axes:
                gs = jax.lax.psum(gs, reduce_axes)
            gf = jax.lax.psum(gf, all_axes)
            gl = jax.lax.psum(gl, all_axes)
            ls = jax.lax.psum(ls, all_axes)
            ws = jax.lax.psum(ws, all_axes)
            gs = jax.tree.map(lambda t: t[None], gs)
            return ls, ws, gf, gs, gl

        micro_spec = P(None, *batch_spec)
        ls, ws, gf, gs, gl = jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(axis_name), P(),
                      jax.tree.map(lambda _: micro_spec, batch)),
            out_specs=(P(), P(), P(), P(axis_name), P()),
            check_vma=check_vma,
        )(p_first, p_stack, p_last, micro)
        return ls, ws, (gf, gs, gl)

    return f


def schedule_bubble_model(n_stages: int, n_microbatches: int,
                          schedule: str = "1f1b", *,
                          t_f: float = 1.0, t_b: float = 1.0,
                          t_w: float = 1.0) -> dict:
    """Step-count bubble model for the fused-1F1B vs zero-bubble schedules.

    Simulates the MPMD executor the schedules target: each device runs its
    op sequence in schedule order, an op starts when the device is free AND
    its cross-device dependency has finished (``F(i,m)`` after ``F(i-1,m)``;
    ``B(i,m)`` after ``B(i+1,m)``, with the last stage's after its own
    ``F``; ``W(i,m)`` after its own ``B(i,m)``). 1F1B's backward is one
    fused op of cost ``t_b + t_w``; ZB splits it and defers W off the
    critical path, which shrinks the fill/drain bubble from
    ``(S-1)(t_f+t_b+t_w)`` toward ``(S-1)(t_f+t_b-t_w)`` (ZB-H1). The
    lockstep ``lax.scan`` realisation cannot show this (every round waits
    for the slowest sub-slot fleet-wide); this model is the schedule's
    honest accounting and is asserted in tests + banked into PIPE_MEM.json.

    Returns ``{"makespan", "busy", "idle_frac", "bubble"}`` — ``busy`` is
    total work per device-timeline (the same for both schedules), so
    ``idle_frac = 1 - busy / (S * makespan)`` is directly comparable.
    """
    if schedule not in ("1f1b", "zb"):
        raise ValueError(f"unknown schedule {schedule!r}")
    S, M = n_stages, n_microbatches
    cost = {"F": t_f, "B": t_b, "W": t_w, "BW": t_b + t_w}

    def device_ops(i):
        evs = []
        for m in range(M):
            evs.append((i + m, 0, "F", m))
            if schedule == "1f1b":
                evs.append((2 * S - 2 - i + m, 1, "BW", m))
            else:
                evs.append((2 * S - 2 - i + m, 1, "B", m))
                evs.append((2 * S - 2 + m, 2, "W", m))
        evs.sort()
        return [(kind, m) for _, _, kind, m in evs]

    bk = "BW" if schedule == "1f1b" else "B"

    def dep(kind, i, m):
        if kind == "F":
            return ("F", i - 1, m) if i else None
        if kind == "W":
            return ("B", i, m)
        return ("F", i, m) if i == S - 1 else (bk, i + 1, m)

    ops = {i: device_ops(i) for i in range(S)}
    ptr = [0] * S
    avail = [0.0] * S
    done: dict[tuple, float] = {}
    while any(ptr[i] < len(ops[i]) for i in range(S)):
        progress = False
        for i in range(S):
            while ptr[i] < len(ops[i]):
                kind, m = ops[i][ptr[i]]
                d = dep(kind, i, m)
                if d is not None and d not in done:
                    break
                t0 = max(avail[i], done.get(d, 0.0))
                done[(kind, i, m)] = t0 + cost[kind]
                avail[i] = t0 + cost[kind]
                ptr[i] += 1
                progress = True
        if not progress:  # pragma: no cover - schedule bug guard
            raise RuntimeError("deadlock in schedule model")
    makespan = max(done.values())
    busy = M * (t_f + t_b + t_w)
    return {
        "schedule": schedule,
        "n_stages": S,
        "n_microbatches": M,
        "makespan": makespan,
        "busy": busy,
        "idle_frac": 1.0 - busy / makespan,
        "bubble": makespan - busy,
    }


def interleaved_stage_order(n_devices: int, v_per_device: int) -> list[int]:
    """Stack-row order for the interleaved schedule.

    Device ``i`` must hold logical stages ``{i, n+i, 2n+i, ...}`` (the
    Megatron interleaved assignment), but a P('pipe')-sharded stack gives
    each device CONTIGUOUS rows. So the stack is laid out device-major:
    row ``i*V + v`` holds logical stage ``v*n + i``. Returns that logical
    order; use :func:`reorder_stages` to permute a logically-ordered stack.
    """
    return [v * n_devices + i
            for i in range(n_devices) for v in range(v_per_device)]


def reorder_stages(stacked: PyTree, n_devices: int,
                   v_per_device: int) -> PyTree:
    """Permute a logically-ordered [S, ...] stack into interleaved layout."""
    import numpy as np

    order = np.asarray(interleaved_stage_order(n_devices, v_per_device))
    return jax.tree.map(lambda t: t[order], stacked)


def pipeline_interleaved(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    n_microbatches: int,
    mesh: Mesh,
    v_per_device: int,
    *,
    axis_name: str = AXIS_PIPE,
    batch_spec: P = P("data"),
    check_vma: bool = True,
):
    """Interleaved (circular) pipeline schedule — the Megatron-style
    bubble-reduction over :func:`pipeline_spmd`.

    Each device holds ``V = v_per_device`` model chunks (logical stage
    ``v*n + i`` for device ``i``; total S = n*V finer-grained stages), and
    the activation circles the device ring V times per microbatch. The
    schedule is closed-form: device ``i`` runs (microbatch m, chunk v) at
    tick ``t = i + (m mod n) + n*(v + V*(m//n))`` — a unique (m, v) per
    (i, t), so every device does exactly one chunk per tick in steady state
    and the fill/drain bubble shrinks from (n-1)/M to ~(n-1)/(V*M) of total
    work at the cost of V x more ppermute hops (cheap: neighbor ICI).

    ``stacked_params``: [n*V, ...] in INTERLEAVED row order (see
    :func:`reorder_stages`), sharded P('pipe'). ``n_microbatches`` must be
    a multiple of the pipe-axis size. Gradients flow through scan+ppermute
    like the GPipe path; wrap ``stage_fn`` in ``jax.checkpoint`` to trade
    recompute for activation memory.
    """
    n_stages = mesh.shape.get(axis_name, 1)
    V = v_per_device

    def sharded(params, x):
        if x.shape[0] % n_microbatches:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by n_microbatches="
                f"{n_microbatches}")
        if n_microbatches % max(n_stages, 1):
            raise ValueError(
                f"n_microbatches={n_microbatches} must be a multiple of the "
                f"'{axis_name}' axis size {n_stages} for the interleaved "
                "schedule")
        n_stacked = jax.tree.leaves(params)[0].shape[0]
        if n_stacked != n_stages * V:
            raise ValueError(
                f"stage stack has {n_stacked} rows but needs "
                f"{n_stages} devices x {V} chunks = {n_stages * V}")
        if n_stages == 1:
            out = x
            for v in range(V):
                out = stage_fn(jax.tree.map(lambda t: t[v], params), out)
            return out

        m_count = n_microbatches
        micro = x.reshape((m_count, x.shape[0] // m_count) + x.shape[1:])
        total_ticks = ((n_stages - 1) + ((m_count - 1) % n_stages)
                       + n_stages * ((V - 1) + V * ((m_count - 1)
                                                    // n_stages)) + 1)

        def body(params, xs):
            if check_vma:
                xs = jax.lax.pcast(xs, (axis_name,), to="varying")
            p_local = jax.tree.map(lambda t: t, params)   # [V, ...] shard
            idx = jax.lax.axis_index(axis_name)
            ring = ring_perm(n_stages)

            def step(carry, t):
                act, out = carry
                # closed-form schedule decode for (this device, tick t)
                u = t - idx
                active = u >= 0
                uc = jnp.maximum(u, 0)
                m_mod = uc % n_stages
                w = uc // n_stages           # = v + V * group
                v = w % V
                g = w // V
                m = g * n_stages + m_mod
                active = active & (m < m_count)
                m_c = jnp.clip(m, 0, m_count - 1)

                x_t = jax.lax.dynamic_index_in_dim(xs, m_c, 0,
                                                   keepdims=False)
                inp = jnp.where((idx == 0) & (v == 0), x_t, act)
                stage_p = jax.tree.map(
                    lambda t_: jax.lax.dynamic_index_in_dim(
                        t_, v, 0, keepdims=False), p_local)
                y = stage_fn(stage_p, inp)

                # final-chunk output on the last device → result buffer
                write = active & (idx == n_stages - 1) & (v == V - 1)
                cur = jax.lax.dynamic_index_in_dim(out, m_c, 0,
                                                   keepdims=False)
                out = jax.lax.dynamic_update_index_in_dim(
                    out, jnp.where(write, y, cur), m_c, 0)
                # everything rides the wraparound ring; the receiver's
                # schedule decode tells it whether the arrival is live
                act = jax.lax.ppermute(y, axis_name, ring)
                return (act, out), None

            act0 = jnp.zeros_like(xs[0])
            out0 = jnp.zeros_like(xs)
            (_, out), _ = jax.lax.scan(step, (act0, out0),
                                       jnp.arange(total_ticks))
            # result lives on the last device only; replicate over pipe.
            # psum would double-count nothing (zeros elsewhere).
            return jax.lax.psum(out, axis_name)

        p_spec = stage_param_specs(params, axis_name)
        micro_spec = P(None, *batch_spec)
        y = jax.shard_map(
            body, mesh=mesh,
            in_specs=(p_spec, micro_spec), out_specs=micro_spec,
            check_vma=check_vma,
        )(params, micro)
        return y.reshape(x.shape[0:1] + y.shape[2:])

    return sharded
