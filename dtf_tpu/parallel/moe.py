"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh axis.

Not in the reference (SURVEY.md §2c marks EP out of its scope) — built
because a complete TPU framework needs the sparse-FFN scaling axis. The
design is the classic TPU MoE (Mesh-TF / GShard / Switch lineage), chosen
because it is *all dense einsums* — exactly what GSPMD partitions well:

- a router scores tokens per expert (f32 softmax);
- top-1 (Switch) dispatch with a fixed capacity C per expert: token→slot
  assignment becomes a one-hot dispatch tensor [G, E, C] (G = tokens);
- ``expert_in = einsum('gec,gd->ecd', dispatch, x)`` — with the E dim
  sharded ``P('expert')``, XLA lowers this to the token all-to-all over ICI;
- each expert runs its FFN on its [C, d] slab (weights stacked [E, ...] and
  expert-sharded — the MoE analogue of PS-sharded variables);
- ``out = einsum('ecd,gec->gd', expert_out, combine)`` routes results back
  (second all-to-all) scaled by the router gate.

Static shapes throughout (capacity drop/pad instead of ragged dispatch):
XLA-friendly, MXU-friendly, and the standard TPU trade — tokens past an
expert's capacity are dropped (their residual path carries them).

Grouped dispatch (GShard §3.2, VERDICT r2 weak #5): a flat dispatch tensor
over all G global tokens is [G, E, C] with C ∝ G/E — O(G²·cap/E) memory and
a G-long cumsum, ~5 GB at BERT-base shapes. Splitting tokens into ``n``
groups of ``s = G/n`` makes it [n, s, E, C_g] with C_g ∝ s/E — total
G·s·cap bytes, i.e. divided by n — and the cumsum (the token→slot race for
capacity) runs *within* each group, which is exactly GShard's semantics.
The group axis rides the ``data`` mesh axis; E rides ``expert``; the two
dispatch einsums still lower to the same pair of all-to-alls.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    num_experts: int = 8
    capacity_factor: float = 1.25
    #: load-balancing auxiliary loss weight (Switch eq. 4).
    aux_loss_weight: float = 1e-2
    #: dispatch groups (GShard G-dim). None → one group per batch row, the
    #: shape that keeps dispatch memory linear in tokens; 1 → flat dispatch
    #: over all tokens (only sane for toy shapes — memory is quadratic).
    num_groups: int | None = None
    #: experts per token: 1 = Switch, 2 = GShard top-2 (normalized gates;
    #: second choices queue behind all first choices for capacity).
    top_k: int = 1

    def __post_init__(self):
        if self.top_k not in (1, 2):
            raise ValueError(f"top_k={self.top_k} must be 1 or 2")


def expert_capacity(tokens_per_group: int, num_experts: int,
                    cfg: MoeConfig) -> int:
    """Slots per expert per group: ``cf · top_k · s / e`` (GShard sets
    C ∝ k — top-2 routes ~2s/e entries per expert, and since second choices
    queue behind firsts, an unscaled capacity would drop essentially every
    second choice, silently degrading to a down-gated top-1)."""
    return max(1, int(cfg.capacity_factor * cfg.top_k
                      * tokens_per_group / num_experts))


def top1_dispatch(router_logits: jax.Array, num_experts: int,
                  capacity: int) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Switch-style top-1 routing → (dispatch [G,E,C], combine [G,E,C], aux).

    ``router_logits`` [G, E] (f32). Tokens beyond an expert's capacity are
    dropped (dispatch row all-zero). ``aux`` is the load-balance loss term:
    E * Σ_e (fraction of tokens to e) * (mean router prob of e).
    """
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    gate = probs.max(axis=-1)                                   # [G]
    choice = probs.argmax(axis=-1)                              # [G]
    onehot = jax.nn.one_hot(choice, num_experts,
                            dtype=jnp.float32)                  # [G,E]
    # position of each token within its chosen expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0             # [G,E]
    in_cap = (pos < capacity) & (onehot > 0)
    pos = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    cap_onehot = jax.nn.one_hot(pos, capacity,
                                dtype=jnp.float32)              # [G,E,C]
    dispatch = cap_onehot * in_cap[..., None]
    combine = dispatch * gate[:, None, None]
    # load-balance aux (Switch Transformer eq. 4)
    frac_tokens = onehot.mean(axis=0)                           # [E]
    frac_probs = probs.mean(axis=0)                             # [E]
    aux = num_experts * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


def top2_dispatch(router_logits: jax.Array, num_experts: int,
                  capacity: int) -> tuple[jax.Array, jax.Array, jax.Array]:
    """GShard top-2 routing → (dispatch [G,E,C], combine [G,E,C], aux).

    Each token goes to its two highest-probability experts with gates
    renormalized over the pair. Capacity policy (GShard §3.3): within an
    expert's queue, ALL first choices precede second choices, so overflow
    drops second choices first. ``aux`` is the same first-choice
    load-balance term as top-1.
    """
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    g1 = probs.max(axis=-1)                                     # [G]
    oh1 = jax.nn.one_hot(probs.argmax(axis=-1), num_experts,
                         dtype=jnp.float32)                     # [G,E]
    probs2 = probs * (1.0 - oh1)
    g2 = probs2.max(axis=-1)
    oh2 = jax.nn.one_hot(probs2.argmax(axis=-1), num_experts,
                         dtype=jnp.float32)
    denom = g1 + g2 + 1e-9
    g1n, g2n = g1 / denom, g2 / denom

    pos1 = jnp.cumsum(oh1, axis=0) * oh1 - 1.0                  # [G,E]
    # second choices queue AFTER every first choice bound for that expert
    pos2 = (jnp.cumsum(oh2, axis=0)
            + oh1.sum(axis=0, keepdims=True)) * oh2 - 1.0
    d_parts = []
    for pos, oh in ((pos1, oh1), (pos2, oh2)):
        in_cap = (pos < capacity) & (oh > 0)
        slot = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
        d_parts.append(jax.nn.one_hot(slot, capacity, dtype=jnp.float32)
                       * in_cap[..., None])
    dispatch = d_parts[0] + d_parts[1]                          # disjoint
    combine = (d_parts[0] * g1n[:, None, None]
               + d_parts[1] * g2n[:, None, None])
    frac_tokens = oh1.mean(axis=0)
    frac_probs = probs.mean(axis=0)
    aux = num_experts * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


class SwitchFFN(nn.Module):
    """Expert-parallel FFN block (drop-in for a dense MLP in a transformer).

    Input [B, T, d] → output [B, T, d]. Expert weights are stacked [E, ...]
    and intended for ``P('expert', ...)`` sharding (see :func:`ep_rules`);
    the dispatch/combine einsums then carry the all-to-alls. The router's
    aux loss is stored in the ``losses`` collection (sow) — pull it with
    ``mutable=['losses']`` and add ``aux_loss_weight`` x its mean to the loss.
    """

    d_model: int
    d_ff: int
    cfg: MoeConfig = MoeConfig()
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        g = b * t
        e = self.cfg.num_experts
        n = b if self.cfg.num_groups is None else self.cfg.num_groups
        if g % n:
            raise ValueError(f"num_groups={n} must divide tokens {g} (={b}x{t})")
        s = g // n  # tokens per group; the capacity race runs within a group
        capacity = expert_capacity(s, e, self.cfg)
        tokens = x.reshape(n, s, d)

        router = nn.Dense(e, dtype=jnp.float32, param_dtype=jnp.float32,
                          name="router")
        route = top1_dispatch if self.cfg.top_k == 1 else top2_dispatch
        dispatch, combine, aux = jax.vmap(
            route, in_axes=(0, None, None))(
                router(tokens), e, capacity)  # [n,s,e,c] x2, aux [n]
        self.sow("losses", "moe_aux", jnp.mean(aux))

        w_in = self.param("w_in", nn.initializers.variance_scaling(
            1.0, "fan_in", "truncated_normal"), (e, d, self.d_ff), jnp.float32)
        w_out = self.param("w_out", nn.initializers.variance_scaling(
            1.0, "fan_in", "truncated_normal"), (e, self.d_ff, d), jnp.float32)

        # all-to-all #1: tokens → their expert's per-group slab. With n on
        # 'data' and e on 'expert' this is the GShard token shuffle over ICI.
        slabs = jnp.einsum("nsec,nsd->necd", dispatch.astype(self.dtype),
                           tokens.astype(self.dtype))
        h = jnp.einsum("necd,edf->necf", slabs, w_in.astype(self.dtype))
        h = nn.gelu(h, approximate=True)
        h = jnp.einsum("necf,efd->necd", h, w_out.astype(self.dtype))
        # all-to-all #2: expert outputs → token order, gated
        out = jnp.einsum("necd,nsec->nsd", h.astype(jnp.float32),
                         combine).astype(x.dtype)
        return out.reshape(b, t, d)


def ep_rules(axis: str = "expert"):
    """Param-placement rules: expert-stacked weights sharded over ``axis``."""
    return [(r"w_(in|out)$", P(axis, None, None))]


def moe_aux_loss(mutables: dict, cfg: MoeConfig) -> jax.Array:
    """Mean of all sown aux terms × weight (0 if the model has no MoE)."""
    losses = mutables.get("losses", {})
    leaves = jax.tree.leaves(losses)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return cfg.aux_loss_weight * sum(jnp.mean(l) for l in leaves) / len(leaves)
