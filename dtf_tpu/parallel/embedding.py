"""Row-sharded embedding tables — successor of PS-sharded embeddings.

Reference capability replaced (SURVEY.md §2c, BASELINE config 5): the
reference round-robins embedding variables across parameter servers via
``replica_device_setter`` (TF ``device_setter.py`` ``_RoundRobinStrategy``)
and every lookup is a remote gather over gRPC. Here tables are row-sharded
over a mesh axis (``NamedSharding(P(axis, None))``) and lookups stay
on-device:

- **GSPMD path** (default): plain ``take`` — the partitioner turns a gather
  on a row-sharded table into local gathers + collectives automatically.
- **Explicit path** (:func:`masked_lookup_sharded`): shard_map with a local
  masked lookup + ``psum`` — each shard serves only ids in its row range and
  contributes zeros elsewhere. One ICI all-reduce of [batch, dim], no table
  replication anywhere; this is the shape a Pallas kernel would optimize.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P


def masked_lookup(table_shard: jax.Array, ids: jax.Array,
                  axis_name: str, gather_fn=None) -> jax.Array:
    """Per-shard body: lookup ids that land in this shard's rows, psum.

    ``table_shard`` [rows/n, dim]; ``ids`` [...] global row indices
    (replicated across the axis). Returns [..., dim] fully-reduced.
    ``gather_fn(table_shard, safe_ids) -> rows`` swaps the row gather
    (default ``jnp.take``; the Pallas kernel path passes
    :func:`dtf_tpu.ops.embed_gather.gather_rows`).
    """
    n_local = table_shard.shape[0]
    start = jax.lax.axis_index(axis_name) * n_local
    local = ids - start
    in_range = (local >= 0) & (local < n_local)
    safe = jnp.clip(local, 0, n_local - 1)
    if gather_fn is None:
        rows = jnp.take(table_shard, safe, axis=0)
    else:
        rows = gather_fn(table_shard, safe)
    rows = jnp.where(in_range[..., None], rows, 0)
    return jax.lax.psum(rows, axis_name)


def masked_lookup_sharded(table: jax.Array, ids: jax.Array, mesh: Mesh,
                          *, axis: str = "model",
                          ids_spec: P = P("data"),
                          use_kernel: bool = False) -> jax.Array:
    """Global-array wrapper over :func:`masked_lookup`.

    ``table`` row-sharded over ``axis``; ``ids`` sharded over ``data``.
    ``use_kernel=True`` swaps the per-shard lookup for the fused Pallas
    gather (:mod:`dtf_tpu.ops.embed_gather` — rows stream HBM→VMEM with the
    ids as the DMA address stream; same masked+psum semantics).
    """
    gather_fn = None
    extra = {}
    if use_kernel:
        from dtf_tpu.ops.embed_gather import gather_rows

        gather_fn = functools.partial(
            gather_rows, interpret=jax.default_backend() != "tpu")
        # pallas out_shapes carry no varying-manual-axes info
        extra = {"check_vma": False}
    fn = functools.partial(masked_lookup, axis_name=axis,
                           gather_fn=gather_fn)
    out_spec = P(*ids_spec, *([None] * 1))
    return jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P(axis, None), ids_spec),
        out_specs=out_spec, **extra)(table, ids)


class RowShardedEmbed(nn.Module):
    """Embedding table intended for ``P(axis, None)`` row sharding.

    The module itself is plain flax (placement comes from the param rules —
    same philosophy as the reference's device_setter wrapping the model); the
    name ``embed_tables`` is what the rule regexes target.
    """

    num_embeddings: int
    features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, ids):
        table = self.param(
            "embedding",
            nn.initializers.variance_scaling(1.0, "fan_in", "normal",
                                             out_axis=0),
            (self.num_embeddings, self.features), jnp.float32)
        return jnp.take(table.astype(self.dtype), ids, axis=0)


#: Placement rule for all RowShardedEmbed tables in a model.
def embedding_rules(axis: str = "model"):
    return [(r"embed_tables.*/embedding", P(axis, None))]
