"""Parallelism strategies (SURVEY.md §2c inventory).

- **DP (sync)** — the default train step: batch over ``data``, mean-gradient
  all-reduce (:mod:`dtf_tpu.core.train`).
- **ZeRO-1** — optimizer-state sharding over ``data``
  (:func:`dtf_tpu.core.sharding.zero1_opt_specs`).
- **TP** — Megatron-style rules over ``model``
  (e.g. :data:`dtf_tpu.models.bert.tp_rules`).
- **SP/CP** — ring attention over ``seq``
  (:mod:`dtf_tpu.ops.attention`).
- **PP** — GPipe microbatch pipeline over ``pipe``: stage-stacked params,
  schedule as one scan+ppermute shard_map (:mod:`dtf_tpu.parallel.pipeline`).
- **EP (MoE)** — Switch-style expert-parallel FFN over ``expert``, token
  dispatch via all-to-all einsums (:mod:`dtf_tpu.parallel.moe`).
- **Embedding sharding** — PS-round-robin successor: row-sharded tables
  (:mod:`dtf_tpu.parallel.embedding`).
- **DP (async)** — not reproduced: hogwild PS updates are an anti-pattern on
  TPU; ``--issync=0`` warns and runs synchronously (behavioral delta).
"""
