"""MNIST IDX reader — successor of tensorflow.examples.tutorials.mnist.input_data.

The reference used the TF1 tutorial loader (``read_data_sets`` +
``next_batch``), which is gone from TF 2.21 (verified in SURVEY.md §1 L3).
This reads the same on-disk format (idx3-ubyte/idx1-ubyte, optionally .gz)
from ``--data_dir`` and reproduces ``next_batch``'s shuffle-each-epoch
semantics. When the files are absent (this container has no network), callers
fall back to :mod:`dtf_tpu.data.synthetic`.

A native (C++) accelerated path for batch assembly lives in
:mod:`dtf_tpu.data.native`; this module is the pure-numpy reference
implementation and the fallback.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Iterator

import numpy as np

from dtf_tpu.data.sharded import ShardedEpochs

FILES = {
    "train_images": "train-images-idx3-ubyte",
    "train_labels": "train-labels-idx1-ubyte",
    "test_images": "t10k-images-idx3-ubyte",
    "test_labels": "t10k-labels-idx1-ubyte",
}


def _open(path: str):
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    return open(path, "rb")


def read_idx(path: str) -> np.ndarray:
    """Parse an IDX file (the classic MNIST container format)."""
    with _open(path) as f:
        magic = struct.unpack(">I", f.read(4))[0]
        dtype_code = (magic >> 8) & 0xFF
        ndim = magic & 0xFF
        if magic >> 16 or dtype_code != 0x08:
            raise ValueError(f"{path}: unsupported IDX magic {magic:#x}")
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
        if data.size != int(np.prod(dims)):
            raise ValueError(f"{path}: truncated IDX payload")
        return data.reshape(dims)


def write_idx(path: str, arr: np.ndarray, *, gz: bool = False) -> None:
    """Write a uint8 array as an IDX file (read_idx's inverse). The one
    shared writer — tests and benchmarks must not re-implement the header
    packing."""
    arr = np.asarray(arr, np.uint8)
    header = struct.pack(f">I{arr.ndim}I", 0x0800 | arr.ndim, *arr.shape)
    opener = gzip.open if gz else open
    with opener(path + (".gz" if gz else ""), "wb") as f:
        f.write(header + arr.tobytes())


def available(data_dir: str) -> bool:
    return all(
        os.path.exists(os.path.join(data_dir, f))
        or os.path.exists(os.path.join(data_dir, f + ".gz"))
        for f in FILES.values())


class MnistData(ShardedEpochs):
    """Shuffled epoch iterator with per-host sharding.

    Matches the reference loader's semantics: images flattened to 784 floats
    in [0,1], labels int32, reshuffled every epoch. Each host sees a disjoint
    1/host_count slice of every epoch (the per-worker feed_dict successor).
    """

    def __init__(self, data_dir: str, batch_size: int, *, split: str = "train",
                 seed: int = 0, host_index: int = 0, host_count: int = 1):
        images = read_idx(os.path.join(data_dir, FILES[f"{split}_images"]))
        labels = read_idx(os.path.join(data_dir, FILES[f"{split}_labels"]))
        self.images = (images.reshape(len(images), -1) / 255.0).astype(
            np.float32)
        self.labels = labels.astype(np.int32)
        super().__init__(len(self.images), batch_size, seed=seed,
                         host_index=host_index, host_count=host_count)

    def __iter__(self) -> Iterator[dict]:
        for idx in self._indices():
            yield {"image": self.images[idx], "label": self.labels[idx]}
