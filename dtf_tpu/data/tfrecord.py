"""TFRecord / ``tf.train.Example`` ingestion — without TensorFlow.

The reference framework is TensorFlow (SURVEY.md §2a: its input path is TF's
native-IO queue-runner machinery), so a migrating user's datasets are
overwhelmingly TFRecord files of serialized ``tf.train.Example`` protos.
This module reads (and writes) that format with zero TF dependency:

- **Record framing** (u64le length + masked CRC32C of length, payload +
  masked CRC32C of payload): indexed by the native C++ library
  (``dtf_tpu/native/dtfio.cpp`` — one mmap'd pass verifying CRCs, then
  payloads are sliced zero-copy out of an ``np.memmap``), with a pure-Python
  fallback walk when no compiler is available (length CRCs verified; the
  O(file) payload CRC pass is native-only).
- **Example wire format**: a hand-rolled protobuf wire codec for exactly the
  ``Example``/``Features``/``Feature`` message shapes (bytes_list /
  float_list / int64_list, packed and unpacked) — the schema is tiny, frozen
  and public, so a 100-line decoder beats dragging in a proto runtime.
- :class:`TFRecordExampleData`: host-sharded, epoch-reshuffled batches under
  the same contract as every other loader (``dtf_tpu/data/sharded.py``).

The encoder (:func:`encode_example` / :func:`write_tfrecords`) exists for
tests and for migrating data *into* the ecosystem-standard format.
"""

from __future__ import annotations

import ctypes
import glob as glob_mod
import struct
from typing import Dict, Iterable, Iterator, List, Optional, Union

import numpy as np

from dtf_tpu.data.sharded import ShardedEpochs

FeatureValue = Union[np.ndarray, List[bytes]]

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) + the TFRecord mask — needed by the writer and the
# pure-Python framing fallback. Table-driven; fine for test-sized files (the
# hot path verifies CRCs in C++).
# ---------------------------------------------------------------------------

_CRC_TABLE: Optional[List[int]] = None


def _crc_table() -> List[int]:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if c & 1 else (c >> 1)
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    # plain-int table loop: native int arithmetic is ~30x faster per byte
    # than numpy-scalar indexing, which matters for write_tfrecords on
    # real migration-sized datasets (the native reader verifies in C++).
    table = _crc_table()
    c = 0xFFFFFFFF
    for b in data:
        c = table[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    c = crc32c(data)
    return ((c >> 15) | (c << 17) & 0xFFFFFFFF) + 0xA282EAD8 & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# protobuf wire primitives
# ---------------------------------------------------------------------------


def _write_varint(out: bytearray, v: int) -> None:
    if v < 0:
        v += 1 << 64  # proto int64: negatives are 10-byte two's complement
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf, pos: int):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint longer than 64 bits")


def _signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _write_tag(out: bytearray, field: int, wire: int) -> None:
    _write_varint(out, (field << 3) | wire)


def _write_len_delim(out: bytearray, field: int, payload: bytes) -> None:
    _write_tag(out, field, 2)
    _write_varint(out, len(payload))
    out += payload


# ---------------------------------------------------------------------------
# tf.train.Example encode
# ---------------------------------------------------------------------------


def _encode_feature(value: FeatureValue) -> bytes:
    """Feature{ bytes_list=1 | float_list=2 | int64_list=3 }."""
    inner = bytearray()
    out = bytearray()
    if isinstance(value, (list, tuple)) and (
            not value or isinstance(value[0], (bytes, bytearray))):
        for b in value:
            _write_len_delim(inner, 1, bytes(b))
        _write_len_delim(out, 1, bytes(inner))
    else:
        arr = np.asarray(value)
        if arr.dtype.kind == "f":
            packed = arr.astype("<f4").tobytes()
            _write_len_delim(inner, 1, packed)  # packed repeated float
            _write_len_delim(out, 2, bytes(inner))
        elif arr.dtype.kind in "iu":
            for v in arr.reshape(-1).tolist():
                _write_varint(inner, int(v))
            payload = bytearray()
            _write_len_delim(payload, 1, bytes(inner))  # packed varints
            _write_len_delim(out, 3, bytes(payload))
        else:
            raise TypeError(f"unsupported feature dtype: {arr.dtype}")
    return bytes(out)


def encode_example(features: Dict[str, FeatureValue]) -> bytes:
    """Serialize one ``tf.train.Example``: {name: float/int array | [bytes]}.

    Float arrays become ``float_list`` (f32), integer arrays ``int64_list``,
    lists of ``bytes`` become ``bytes_list``. Arrays are flattened (the
    Example schema is rank-free; shape is the reader's contract).
    """
    feats = bytearray()
    for name, value in sorted(features.items()):
        entry = bytearray()
        _write_len_delim(entry, 1, name.encode("utf-8"))   # map key
        _write_len_delim(entry, 2, _encode_feature(value))  # map value
        _write_len_delim(feats, 1, bytes(entry))            # Features.feature
    out = bytearray()
    _write_len_delim(out, 1, bytes(feats))                  # Example.features
    return bytes(out)


# ---------------------------------------------------------------------------
# tf.train.Example decode
# ---------------------------------------------------------------------------


def _iter_fields(buf, start: int, end: int):
    pos = start
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 0x7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
            yield field, wire, v
        elif wire == 1:
            yield field, wire, bytes(buf[pos:pos + 8])
            pos += 8
        elif wire == 2:
            n, pos = _read_varint(buf, pos)
            yield field, wire, (pos, pos + n)
            pos += n
        elif wire == 5:
            yield field, wire, bytes(buf[pos:pos + 4])
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
    if pos != end:
        raise ValueError("message overran its length prefix")


def _decode_feature(buf, start: int, end: int) -> FeatureValue:
    for field, wire, val in _iter_fields(buf, start, end):
        if field == 1 and wire == 2:                        # bytes_list
            s, e = val
            return [bytes(buf[a:b])
                    for f, w, (a, b) in _iter_fields(buf, s, e)
                    if f == 1 and w == 2]
        if field == 2 and wire == 2:                        # float_list
            s, e = val
            floats: List[float] = []
            chunks: List[np.ndarray] = []
            for f, w, v in _iter_fields(buf, s, e):
                if f == 1 and w == 2:                       # packed
                    a, b = v
                    chunks.append(np.frombuffer(buf[a:b], "<f4"))
                elif f == 1 and w == 5:                     # unpacked
                    floats.append(struct.unpack("<f", v)[0])
            if chunks:
                return np.concatenate(chunks) if len(chunks) > 1 else chunks[0]
            return np.asarray(floats, np.float32)
        if field == 3 and wire == 2:                        # int64_list
            s, e = val
            ints: List[int] = []
            for f, w, v in _iter_fields(buf, s, e):
                if f == 1 and w == 2:                       # packed varints
                    p, e2 = v
                    while p < e2:
                        x, p = _read_varint(buf, p)
                        ints.append(_signed64(x))
                elif f == 1 and w == 0:                     # unpacked
                    ints.append(_signed64(v))
            return np.asarray(ints, np.int64)
    return np.asarray([], np.float32)  # empty Feature


def parse_example(payload) -> Dict[str, FeatureValue]:
    """Decode one serialized ``tf.train.Example`` into {name: value}.

    ``payload`` is any byte buffer (bytes / memoryview / np.memmap slice).
    float_list → f32 ndarray, int64_list → i64 ndarray, bytes_list →
    list[bytes]. Accepts packed and unpacked numeric encodings.
    """
    buf = memoryview(payload) if not isinstance(payload, memoryview) \
        else payload
    out: Dict[str, FeatureValue] = {}
    for field, wire, val in _iter_fields(buf, 0, len(buf)):
        if field != 1 or wire != 2:
            continue                                        # Example.features
        fs, fe = val
        for f2, w2, v2 in _iter_fields(buf, fs, fe):
            if f2 != 1 or w2 != 2:
                continue                                    # map entry
            es, ee = v2
            name = None
            span = None
            for f3, w3, v3 in _iter_fields(buf, es, ee):
                if f3 == 1 and w3 == 2:
                    a, b = v3
                    name = bytes(buf[a:b]).decode("utf-8")
                elif f3 == 2 and w3 == 2:
                    span = v3
            if name is not None and span is not None:
                out[name] = _decode_feature(buf, span[0], span[1])
    return out


# ---------------------------------------------------------------------------
# record-level IO
# ---------------------------------------------------------------------------


def write_tfrecords(path: str, payloads: Iterable[bytes]) -> int:
    """Write serialized payloads in TFRecord framing. Returns record count."""
    n = 0
    with open(path, "wb") as f:
        for payload in payloads:
            header = struct.pack("<Q", len(payload))
            f.write(header)
            f.write(struct.pack("<I", masked_crc32c(header)))
            f.write(payload)
            f.write(struct.pack("<I", masked_crc32c(payload)))
            n += 1
    return n


def _python_spans(path: str):
    """Fallback framing walk (no compiler): verifies length CRCs only.

    mmap-backed so only the 12-byte headers are ever resident — a
    migration-sized shard must not be slurped into RAM just to index it."""
    import mmap

    off: List[int] = []
    length: List[int] = []
    with open(path, "rb") as f:
        f.seek(0, 2)
        total = f.tell()
        if total == 0:
            return (np.asarray([], np.uint64), np.asarray([], np.uint64))
        raw = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        try:
            pos = 0
            while pos < total:
                if total - pos < 12:
                    raise ValueError(
                        f"{path}: truncated record header at {pos}")
                (n,) = struct.unpack_from("<Q", raw, pos)
                (lcrc,) = struct.unpack_from("<I", raw, pos + 8)
                if lcrc != masked_crc32c(raw[pos:pos + 8]):
                    raise ValueError(f"{path}: length CRC mismatch at {pos}")
                if total - pos - 12 < n + 4:
                    raise ValueError(f"{path}: truncated payload at {pos}")
                off.append(pos + 12)
                length.append(n)
                pos += 12 + n + 4
        finally:
            raw.close()
    return (np.asarray(off, np.uint64), np.asarray(length, np.uint64))


def tfrecord_spans(path: str, *, verify_payload_crc: bool = True):
    """(offsets, lengths) of every record payload in ``path``.

    Uses the native indexer (CRC-verified single pass) when available,
    else the pure-Python walk. Raises ValueError on corrupt framing,
    FileNotFoundError/OSError on unreadable paths (stat'd up front so the
    native path's opaque nullptr can't misreport a typo'd path as
    corruption).
    """
    import os

    os.stat(path)  # raises FileNotFoundError/PermissionError consistently
    from dtf_tpu.data import native as native_mod

    lib = native_mod._load()
    if lib is None:
        return _python_spans(path)
    # always (re)declare the signatures: hasattr() on a CDLL *resolves* the
    # symbol, so it can't serve as a bound-yet check, and the default c_int
    # restype would truncate the 64-bit handle.
    _bind_tfrecord(lib)
    h = lib.dtfio_tfrecord_open(path.encode(), 1 if verify_payload_crc else 0)
    if not h:
        raise ValueError(
            f"{path}: bad TFRecord framing or CRC mismatch (native indexer)")
    try:
        n = lib.dtfio_tfrecord_count(h)
        off = np.zeros(n, np.uint64)
        length = np.zeros(n, np.uint64)
        if n:
            lib.dtfio_tfrecord_spans(
                h, off.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                length.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        return off, length
    finally:
        lib.dtfio_tfrecord_close(h)


def _bind_tfrecord(lib) -> None:
    lib.dtfio_tfrecord_open.restype = ctypes.c_void_p
    lib.dtfio_tfrecord_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.dtfio_tfrecord_count.restype = ctypes.c_longlong
    lib.dtfio_tfrecord_count.argtypes = [ctypes.c_void_p]
    lib.dtfio_tfrecord_spans.restype = None
    lib.dtfio_tfrecord_spans.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64)]
    lib.dtfio_tfrecord_close.restype = None
    lib.dtfio_tfrecord_close.argtypes = [ctypes.c_void_p]


def record_payload_verified(view, offset: int, length: int):
    """One record's payload slice, CRC-verified — or None on a mismatch.

    ``view`` is the file's byte buffer (the per-file ``np.memmap`` view the
    datasets already hold); ``offset``/``length`` come from
    :func:`tfrecord_spans`. This is the streaming tier's corrupt-record
    cursor hook (``dtf_tpu/data/stream``): framing is indexed ONCE without
    payload verification, then each read verifies its own payload CRC so a
    record damaged after indexing (bit rot, a torn shard on a network
    mount) is SKIPPED with a WARN by the caller instead of poisoning the
    run — the checkpoint-restore fallback philosophy applied to data.
    """
    payload = bytes(view[offset:offset + length])
    (stored,) = struct.unpack_from("<I", bytes(
        view[offset + length:offset + length + 4]), 0)
    if stored != masked_crc32c(payload):
        return None
    return payload


def read_tfrecords(path: str) -> Iterator[memoryview]:
    """Yield each record's payload as a zero-copy view into the mmap."""
    off, length = tfrecord_spans(path)
    if off.size == 0:
        return
    data = np.memmap(path, np.uint8, "r")
    view = memoryview(data)
    for o, n in zip(off.tolist(), length.tolist()):
        yield view[o:o + n]


# ---------------------------------------------------------------------------
# dataset
# ---------------------------------------------------------------------------


class TFRecordExampleData(ShardedEpochs):
    """Host-sharded epochs over ``tf.train.Example`` TFRecord shards.

    ``pattern`` globs one or more .tfrecord files (sorted — every host must
    see the same file order for the shared epoch permutation to shard
    disjointly). ``transform(example) -> row`` maps one parsed Example (see
    :func:`parse_example`) to the per-row dict; rows are stacked into the
    batch with ``np.stack`` per key.

    Records are sliced zero-copy from per-file ``np.memmap``; only the
    Example decode and the batch stack run per step. Indexing (with CRC
    verification) happens once, natively, at construction.
    """

    def __init__(self, pattern: str, batch_size: int, transform,
                 *, seed: int = 0, host_index: int = 0, host_count: int = 1):
        files = sorted(glob_mod.glob(pattern))
        if not files:
            raise FileNotFoundError(f"no TFRecord files match {pattern!r}")
        self.files = files
        self.transform = transform
        self._maps = []
        file_ids: List[np.ndarray] = []
        offs: List[np.ndarray] = []
        lens: List[np.ndarray] = []
        for i, f in enumerate(files):
            off, length = tfrecord_spans(f)
            self._maps.append(memoryview(np.memmap(f, np.uint8, "r"))
                              if off.size else None)
            file_ids.append(np.full(off.size, i, np.int32))
            offs.append(off)
            lens.append(length)
        self._file_id = np.concatenate(file_ids)
        self._off = np.concatenate(offs)
        self._len = np.concatenate(lens)
        super().__init__(int(self._off.size), batch_size, seed=seed,
                         host_index=host_index, host_count=host_count)

    def _row(self, i: int) -> dict:
        view = self._maps[int(self._file_id[i])]
        o, n = int(self._off[i]), int(self._len[i])
        return self.transform(parse_example(view[o:o + n]))

    def batch_at(self, indices: np.ndarray) -> dict:
        rows = [self._row(i) for i in indices]
        return {k: np.stack([r[k] for r in rows]) for k in rows[0]}

    def __iter__(self) -> Iterator[dict]:
        for idx in self._indices():
            yield self.batch_at(idx)


def image_example_transform(height: Optional[int] = None,
                            width: Optional[int] = None,
                            channels: Optional[int] = None,
                            *, image_key: str = "image",
                            label_key: str = "label"):
    """Transform for the common image-classification Example layout:
    ``image`` = raw u8 bytes (bytes_list[0]) or a float_list, ``label`` =
    int64_list[0]. u8 images are scaled by 1/255 to [0,1] f32 like every
    other image loader here. Dimensions left as None are read from the
    conventional ``height``/``width``/``depth`` int64 features (depth
    defaults to 3 when absent)."""

    def dim(given, ex, key, default=None):
        if given is not None:
            return given
        if key in ex:
            return int(np.asarray(ex[key]).reshape(-1)[0])
        if default is not None:
            return default
        raise ValueError(
            f"image shape unknown: pass {key}= or store an {key!r} "
            "int64 feature in the Examples")

    def transform(ex: Dict[str, FeatureValue]) -> dict:
        h = dim(height, ex, "height")
        w = dim(width, ex, "width")
        c = dim(channels, ex, "depth", default=3)
        img = ex[image_key]
        if isinstance(img, list):                # raw u8 bytes
            arr = np.frombuffer(img[0], np.uint8).astype(np.float32) / 255.0
        else:
            arr = np.asarray(img, np.float32)
        label = ex[label_key]
        return {"image": arr.reshape(h, w, c),
                "label": np.int32(np.asarray(label).reshape(-1)[0])}

    return transform
