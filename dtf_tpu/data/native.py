"""ctypes bindings for the native (C++) data-loading runtime.

``libdtfio.so`` (see ``dtf_tpu/native/dtfio.cpp``) does mmap'd IDX parsing,
deterministic per-epoch shuffling, u8→f32 normalization, and batch assembly
on a background prefetch thread with a double buffer — the successor of the
reference era's C++ FIFOQueue/queue-runner input machinery (SURVEY.md §2b
N7). Python's only per-batch work is a memcpy into a numpy array.

Builds on demand with g++ (cached next to the source); falls back cleanly if
no compiler is available — callers should use :func:`native_available` and
fall back to :class:`dtf_tpu.data.mnist.MnistData`.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Iterator

import numpy as np

log = logging.getLogger("dtf_tpu")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libdtfio.so")
_lib = None
_lib_lock = threading.Lock()


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, text=True)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        out = getattr(e, "stderr", "")
        log.warning("native dtfio build failed: %s %s", e, out)
        return False


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        src = os.path.join(_NATIVE_DIR, "dtfio.cpp")
        if not os.path.exists(_SO_PATH) or (
                os.path.exists(src)
                and os.path.getmtime(src) > os.path.getmtime(_SO_PATH)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:
            # A present-but-unloadable .so (stale copy, wrong arch) must take
            # the documented clean fallback, not crash the availability probe.
            log.warning("native dtfio load failed: %s", e)
            return None
        lib.dtfio_loader_create.restype = ctypes.c_void_p
        lib.dtfio_loader_create.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_uint64, ctypes.c_size_t, ctypes.c_size_t]
        lib.dtfio_item_size.restype = ctypes.c_size_t
        lib.dtfio_item_size.argtypes = [ctypes.c_void_p]
        lib.dtfio_num_items.restype = ctypes.c_size_t
        lib.dtfio_num_items.argtypes = [ctypes.c_void_p]
        lib.dtfio_loader_next.restype = None
        lib.dtfio_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32)]
        lib.dtfio_loader_destroy.restype = None
        lib.dtfio_loader_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


class NativeIdxData:
    """Prefetching IDX batch loader backed by libdtfio.

    Same contract as :class:`dtf_tpu.data.mnist.MnistData` (host-sharded,
    reshuffled epochs, f32 images in [0,1)), but assembly runs in native code
    one batch ahead of the consumer. The shuffle is splitmix64-based, so
    batch order differs from the numpy loader at equal seeds (both are
    deterministic in themselves).
    """

    def __init__(self, images_path: str, labels_path: str, batch_size: int,
                 *, seed: int = 0, host_index: int = 0, host_count: int = 1):
        lib = _load()
        if lib is None:
            raise RuntimeError("libdtfio.so unavailable (no compiler?)")
        if batch_size % host_count:
            raise ValueError(
                f"global batch {batch_size} not divisible by {host_count} hosts")
        self._lib = lib
        self.local_batch = batch_size // host_count
        self._h = lib.dtfio_loader_create(
            images_path.encode(), labels_path.encode(), self.local_batch,
            seed, host_index, host_count)
        if not self._h:
            raise ValueError(
                f"dtfio could not open {images_path}/{labels_path} "
                "(bad IDX, mismatched item counts, or batch > shard)")
        self.item_size = lib.dtfio_item_size(self._h)
        self.num_items = lib.dtfio_num_items(self._h)
        #: explicit offset cursor (the streaming-tier resume hook): the
        #: native shuffle is deterministic in (seed, host), so "batches
        #: consumed" fully addresses the stream position — :meth:`seek`
        #: replays to it after a restore.
        self.batches_consumed = 0

    def next_batch(self) -> dict:
        if not self._h:
            raise RuntimeError("NativeIdxData used after close()")
        images = np.empty((self.local_batch, self.item_size), np.float32)
        labels = np.empty((self.local_batch,), np.int32)
        self._lib.dtfio_loader_next(
            self._h,
            images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        self.batches_consumed += 1
        return {"image": images, "label": labels}

    def seek(self, n_batches: int) -> None:
        """Advance the cursor to ``n_batches`` consumed (resume-by-replay).

        The native library exposes no random access — its shuffle state
        lives inside the prefetch thread — but the stream IS deterministic,
        so a fresh loader replays ``n`` draws to land exactly where the
        checkpointed one stood. Cost is host-side assembly only (no device
        work); restore-time, not per-step. Rewinding needs a fresh loader.
        """
        if n_batches < self.batches_consumed:
            raise ValueError(
                f"cannot seek backwards ({self.batches_consumed} -> "
                f"{n_batches}); construct a fresh loader")
        while self.batches_consumed < n_batches:
            self.next_batch()

    def __iter__(self) -> Iterator[dict]:
        while True:
            yield self.next_batch()

    def close(self):
        if self._h:
            self._lib.dtfio_loader_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
