"""Device-side input prefetch — the H2D half of the queue-runner story.

The native loader (``dtf_tpu/native/dtfio.cpp``) already assembles batches
on a background host thread; this module overlaps the *host→device
transfer* with the previous step's compute, the standard TPU input-pipeline
double-buffer. ``jax.device_put`` dispatches asynchronously, so placing
batch N+1 while step N runs costs nothing on the host and hides the PCIe
copy behind the MXU time; the training loop then always finds a
device-resident batch waiting.

Reference capability replaced (SURVEY.md §2b N7): TF's ``FIFOQueue`` +
``QueueRunner`` threads kept a staging area full between the input pipeline
and the session step. Here the "queue" is the device's async transfer
stream and ``depth`` bounds how many batches are in flight.
"""

from __future__ import annotations

import collections
from typing import Callable, Iterable, Iterator

Batch = object


def prefetch_to_device(batches: Iterable[Batch],
                       place: Callable[[Batch], Batch],
                       depth: int = 2) -> Iterator[Batch]:
    """Yield ``place(batch)`` with up to ``depth`` placements in flight.

    ``place`` is the host→device mapping (e.g. ``Trainer.place_batch`` —
    typically :func:`dtf_tpu.core.comms.shard_batch`). ``depth=1`` degrades
    to the unpipelined behavior; ``depth=2`` (default) is classic double
    buffering. Order is preserved; every input batch is yielded exactly
    once.
    """
    if depth < 1:
        raise ValueError(f"prefetch depth must be >= 1, got {depth}")
    queue: collections.deque = collections.deque()
    for batch in batches:
        queue.append(place(batch))
        if len(queue) >= depth:
            yield queue.popleft()
    while queue:
        yield queue.popleft()
