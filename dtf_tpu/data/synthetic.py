"""Synthetic datasets for the five BASELINE workload configs.

Successor of the reference's input layer (SURVEY.md §1 L3): the reference fed
MNIST via the long-defunct ``tensorflow.examples.tutorials.mnist`` feed-dict
reader. This environment has no network, so every workload gets a
deterministic synthetic generator with the right shapes/dtypes; real data
(IDX/tfrecord files in ``--data_dir``) plugs in via :mod:`dtf_tpu.data.mnist`
when present. Parity tests (loss decreasing, numerics across mesh sizes) are
data-agnostic by design.

Each generator yields *host-local* numpy batches; multi-host jobs get
disjoint shards via ``shard`` (the per-worker ``next_batch`` successor).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np

Batch = dict


@dataclasses.dataclass(frozen=True)
class SyntheticSpec:
    """Shape/dtype recipe for one workload config."""

    name: str
    num_classes: int


def _rng_for(seed: int, step: int, host: int) -> np.random.Generator:
    return np.random.default_rng(
        np.random.SeedSequence([seed, step, host]))


class SyntheticData:
    """Deterministic, host-sharded synthetic batches.

    ``kind`` ∈ {mnist, cifar, imagenet, bert, gpt, widedeep} — one per
    BASELINE config plus ``gpt`` (causal-LM next-token batches for the
    long-context flagship). Labels are derived from the inputs (not pure
    noise) so that models can actually fit them and "loss decreases" is a
    meaningful test.
    """

    def __init__(self, kind: str, batch_size: int, *, seed: int = 0,
                 host_index: int = 0, host_count: int = 1,
                 seq_len: int = 128, vocab_size: int = 30522,
                 num_sparse: int = 26, hash_buckets: int = 1000):
        if batch_size % host_count:
            raise ValueError(
                f"global batch {batch_size} not divisible by {host_count} hosts")
        self.kind = kind
        self.global_batch = batch_size
        self.local_batch = batch_size // host_count
        self.seed = seed
        self.host = host_index
        self.seq_len = seq_len
        self.vocab = vocab_size
        self.num_sparse = num_sparse
        self.hash_buckets = hash_buckets
        if kind not in ("mnist", "cifar", "imagenet", "bert", "gpt",
                        "widedeep"):
            raise ValueError(f"unknown synthetic dataset kind: {kind!r}")

    def batch(self, step: int) -> Batch:
        r = _rng_for(self.seed, step, self.host)
        n = self.local_batch
        if self.kind == "mnist":
            x = r.random((n, 784), np.float32)
            w = _rng_for(self.seed, 0, 0).standard_normal((784, 10))
            y = (x @ w).argmax(-1).astype(np.int32)
            return {"image": x, "label": y}
        if self.kind == "cifar":
            x = r.random((n, 32, 32, 3), np.float32)
            y = (x.mean((1, 2)) @ _rng_for(self.seed, 0, 0)
                 .standard_normal((3, 10))).argmax(-1).astype(np.int32)
            return {"image": x, "label": y}
        if self.kind == "imagenet":
            x = r.random((n, 224, 224, 3), np.float32)
            y = r.integers(0, 1000, (n,), np.int32)
            return {"image": x, "label": y}
        if self.kind == "bert":
            ids = r.integers(0, self.vocab, (n, self.seq_len), np.int32)
            mask_pos = r.random((n, self.seq_len)) < 0.15
            labels = np.where(mask_pos, ids, -100).astype(np.int32)
            masked = np.where(mask_pos, 103, ids).astype(np.int32)  # [MASK]
            segment = np.zeros((n, self.seq_len), np.int32)
            return {"input_ids": masked, "segment_ids": segment,
                    "attention_mask": np.ones((n, self.seq_len), np.int32),
                    "mlm_labels": labels}
        if self.kind == "gpt":
            # learnable structure: token t+1 = (a*token_t + b) mod V on half
            # the positions, noise on the rest — next-token CE can fall.
            # Built sequentially so the relation holds on the post-replacement
            # (visible) stream even across chained deterministic positions.
            ids = r.integers(0, self.vocab, (n, self.seq_len + 1), np.int32)
            use_det = r.random((n, self.seq_len)) < 0.5
            a, b = 3, 7
            for t in range(self.seq_len):
                det = (a * ids[:, t] + b) % self.vocab
                ids[:, t + 1] = np.where(use_det[:, t], det, ids[:, t + 1])
            labels = ids[:, 1:].astype(np.int32)
            return {"input_ids": ids[:, :-1].astype(np.int32),
                    "labels": labels}
        # widedeep: criteo-like 13 dense + num_sparse categorical features.
        dense = r.standard_normal((n, 13)).astype(np.float32)
        sparse = r.integers(0, self.hash_buckets,
                            (n, self.num_sparse), np.int32)
        logits = dense.sum(-1) + (sparse.sum(-1) % 7 - 3) * 0.3
        y = (logits > 0).astype(np.float32)
        return {"dense": dense, "sparse": sparse, "label": y}

    def __iter__(self) -> Iterator[Batch]:
        step = 0
        while True:
            yield self.batch(step)
            step += 1
