"""On-disk dataset formats for the non-MNIST BASELINE configs.

VERDICT r1 missing-item #2: the reference trained from real files (SURVEY.md
§2a "MNIST input"); this framework read real IDX MNIST but nothing else.
This module adds a file path for every remaining workload, auto-detected
from ``--data_dir`` the same way the MNIST scripts do (train from files when
present, synthetic otherwise):

- **images**: ``images.npy`` + ``labels.npy`` pairs (any [N,H,W,C] uint8 or
  float32 array; memory-mapped) — covers the CIFAR-10 and ImageNet-shaped
  configs without needing a JPEG decoder in an offline container.
- **CIFAR-10 binary**: the canonical ``cifar-10-batches-bin`` layout
  (``data_batch_*.bin``: 1 label byte + 3072 RGB-planar bytes per record).
- **token binary**: a flat uint16/uint32 token stream (``*.bin``, the
  nanoGPT/GPT-2 convention) windowed into causal-LM batches, or dynamically
  masked into BERT MLM batches (the on-the-fly masking recipe).
- **Criteo TSV/CSV**: label + 13 numeric + 26 categorical columns;
  categoricals are hashed into buckets host-side (the PS-era
  ``tf.feature_column.categorical_column_with_hash_bucket`` semantics).

All loaders yield host-local numpy batches, reshuffle each epoch with a
deterministic per-epoch seed, and shard rows disjointly across hosts —
the same contract as :class:`dtf_tpu.data.mnist.MnistData`.
"""

from __future__ import annotations

import glob
import json
import os
import zlib
from typing import Iterator, Optional

import numpy as np

from dtf_tpu.data.sharded import ShardedEpochs

Batch = dict


class NpyImageData(ShardedEpochs):
    """``images.npy`` + ``labels.npy`` image classification data.

    uint8 images are scaled to [0,1) float32; float arrays pass through.
    Files are memory-mapped so ImageNet-sized arrays don't need host RAM.
    """

    def __init__(self, data_dir: str, batch_size: int, *, split: str = "train",
                 seed: int = 0, host_index: int = 0, host_count: int = 1):
        prefix = "" if split == "train" else f"{split}_"
        self.images = np.load(os.path.join(data_dir, f"{prefix}images.npy"),
                              mmap_mode="r")
        self.labels = np.load(os.path.join(data_dir, f"{prefix}labels.npy"),
                              mmap_mode="r")
        if len(self.images) != len(self.labels):
            raise ValueError(
                f"images ({len(self.images)}) / labels ({len(self.labels)}) "
                "row counts differ")
        super().__init__(len(self.images), batch_size, seed=seed,
                         host_index=host_index, host_count=host_count)

    @staticmethod
    def available(data_dir: str, split: str = "train") -> bool:
        prefix = "" if split == "train" else f"{split}_"
        return (os.path.exists(os.path.join(data_dir, f"{prefix}images.npy"))
                and os.path.exists(
                    os.path.join(data_dir, f"{prefix}labels.npy")))

    def __iter__(self) -> Iterator[Batch]:
        for idx in self._indices():
            idx = np.sort(idx)  # sorted fancy-index: sequential mmap reads
            img = np.asarray(self.images[idx])
            if img.dtype == np.uint8:
                img = (img / 255.0).astype(np.float32)
            yield {"image": img.astype(np.float32, copy=False),
                   "label": np.asarray(self.labels[idx]).astype(np.int32)}


class CifarBinData(ShardedEpochs):
    """The canonical CIFAR-10 binary batches (``data_batch_*.bin``).

    Record layout: 1 label byte + 32*32 R plane + G plane + B plane.
    Loaded fully into RAM (180MB max — the real dataset's size).
    """

    RECORD = 1 + 3 * 32 * 32

    def __init__(self, data_dir: str, batch_size: int, *, split: str = "train",
                 seed: int = 0, host_index: int = 0, host_count: int = 1):
        files = (sorted(glob.glob(os.path.join(data_dir, "data_batch_*.bin")))
                 if split == "train"
                 else [os.path.join(data_dir, "test_batch.bin")])
        if not files:
            raise FileNotFoundError(f"no CIFAR .bin batches in {data_dir}")
        raw = np.concatenate([
            np.frombuffer(open(f, "rb").read(), np.uint8) for f in files])
        if raw.size % self.RECORD:
            raise ValueError("truncated CIFAR binary batch")
        rec = raw.reshape(-1, self.RECORD)
        self.labels = rec[:, 0].astype(np.int32)
        # planar RGB → [N, 32, 32, 3]
        self.images = (rec[:, 1:].reshape(-1, 3, 32, 32)
                       .transpose(0, 2, 3, 1) / 255.0).astype(np.float32)
        super().__init__(len(self.labels), batch_size, seed=seed,
                         host_index=host_index, host_count=host_count)

    @staticmethod
    def available(data_dir: str) -> bool:
        return bool(glob.glob(os.path.join(data_dir, "data_batch_*.bin")))

    def __iter__(self) -> Iterator[Batch]:
        for idx in self._indices():
            yield {"image": self.images[idx], "label": self.labels[idx]}


class TokenBinData:
    """Flat binary token stream → LM batches.

    ``path`` is a ``.bin`` file (or a dir containing ``train.bin``) of
    little-endian uint16 tokens (uint32 when ``vocab_size > 65535``), the
    nanoGPT/GPT-2 packing convention. Batches are random seq_len+1 windows,
    deterministic per (seed, step, host) like the synthetic layer.

    ``mode="clm"`` yields {input_ids, labels} (next-token, the GPT script's
    schema); ``mode="mlm"`` applies dynamic masking with the BERT 80/10/10
    recipe (of the 15% selected positions: 80% → [MASK], 10% → random token,
    10% → unchanged) and yields the BERT schema
    {input_ids, segment_ids, attention_mask, mlm_labels}.
    """

    def __init__(self, path: str, batch_size: int, seq_len: int, *,
                 mode: str = "clm", vocab_size: int = 0,
                 mask_token: int = 103, seed: int = 0, split: str = "train",
                 host_index: int = 0, host_count: int = 1):
        if os.path.isdir(path):
            path = os.path.join(path, f"{split}.bin")
        dtype = np.uint32 if vocab_size > 65535 else np.uint16
        self.tokens = np.memmap(path, dtype=dtype, mode="r")
        if len(self.tokens) < seq_len + 1:
            raise ValueError(f"{path}: {len(self.tokens)} tokens < "
                             f"seq_len+1={seq_len + 1}")
        # Sanity-check a sample against silent dtype/vocab mismatches (JAX
        # gathers clip out-of-range ids, so garbage would train "fine").
        sample = np.asarray(self.tokens[:65536])
        if vocab_size and int(sample.max()) >= vocab_size:
            raise ValueError(
                f"{path}: token {int(sample.max())} >= vocab_size "
                f"{vocab_size} — wrong file, vocab, or dtype")
        if dtype == np.uint16 and len(sample) >= 64:
            # a uint32 stream misread as uint16 shows as (low, 0) pairs:
            # odd positions nearly all zero while even positions are not.
            odd_zero = (sample[1::2] == 0).mean()
            even_zero = (sample[0::2] == 0).mean()
            if odd_zero > 0.9 and even_zero < 0.5:
                raise ValueError(
                    f"{path}: looks like uint32 tokens read as uint16 "
                    f"({odd_zero:.0%} of odd positions are 0); pass "
                    "vocab_size > 65535 or repack the file")
        if batch_size % host_count:
            raise ValueError(f"global batch {batch_size} not divisible by "
                             f"{host_count} hosts")
        if mode not in ("clm", "mlm"):
            raise ValueError(f"mode must be clm|mlm, got {mode!r}")
        self.local_batch = batch_size // host_count
        self.seq_len = seq_len
        self.mode = mode
        self.mask_token = mask_token
        #: vocab for the MLM "10% random token" draw; falls back to the
        #: observed sample range when the caller didn't pass vocab_size.
        self.vocab_for_random = vocab_size or int(sample.max()) + 1
        self.seed = seed
        self.host = host_index

    @staticmethod
    def available(path: str, split: str = "train") -> bool:
        """True when ``path`` holds this split: ``<path>/<split>.bin``, or a
        direct ``.bin`` file (train split only)."""
        return (split == "train" and os.path.isfile(path)
                and path.endswith(".bin")) or \
            os.path.exists(os.path.join(path, f"{split}.bin"))

    #: SeedSequence salt separating the per-EXAMPLE stream (:meth:`example`)
    #: from the per-batch stream (:meth:`batch`) — the two must never
    #: collide or a mixture stream and a plain loader at the same seed
    #: would draw correlated windows.
    EXAMPLE_SALT = 0x5EED_0001

    def example(self, index: int) -> Batch:
        """One example addressed by a GLOBAL example index — the mixture
        stream's cursor hook (``dtf_tpu/data/stream``).

        Unlike :meth:`batch` (keyed ``[seed, step, host]``: a host-local
        batch), the draw here is keyed ``[seed, EXAMPLE_SALT, index]`` and
        is host-free, so example ``i`` is the same bytes no matter which
        host materializes it — the property that lets a shrink-resume
        re-partition per-host cursors without changing the realized global
        batch sequence. Rows are unbatched (``[seq_len]`` arrays).
        """
        r = np.random.default_rng(
            np.random.SeedSequence([self.seed, self.EXAMPLE_SALT,
                                    int(index)]))
        s = int(r.integers(0, len(self.tokens) - self.seq_len - 1))
        win = np.asarray(self.tokens[s:s + self.seq_len + 1]).astype(np.int32)
        if self.mode == "clm":
            return {"input_ids": win[:-1], "labels": win[1:]}
        return self._mlm_mask(r, win[:-1])

    def _mlm_mask(self, r: np.random.Generator, ids: np.ndarray) -> Batch:
        """Dynamic masking, the BERT 80/10/10 recipe — ONE implementation
        for the per-batch and per-example streams (of the 15% selected
        positions: 80% → [MASK], 10% → random token, 10% unchanged; all
        still predicted). ``ids`` may be [B, T] or [T]."""
        mask_pos = r.random(ids.shape) < 0.15
        labels = np.where(mask_pos, ids, -100).astype(np.int32)
        u = r.random(ids.shape)
        rand_tok = r.integers(0, self.vocab_for_random, ids.shape)
        masked = np.where(mask_pos & (u < 0.8), self.mask_token,
                          np.where(mask_pos & (u < 0.9), rand_tok, ids))
        return {"input_ids": masked.astype(np.int32),
                "segment_ids": np.zeros_like(ids),
                "attention_mask": np.ones_like(ids),
                "mlm_labels": labels}

    def batch(self, step: int) -> Batch:
        r = np.random.default_rng(
            np.random.SeedSequence([self.seed, step, self.host]))
        starts = r.integers(0, len(self.tokens) - self.seq_len - 1,
                            self.local_batch)
        win = np.stack([
            np.asarray(self.tokens[s:s + self.seq_len + 1]) for s in starts
        ]).astype(np.int32)
        if self.mode == "clm":
            return {"input_ids": win[:, :-1], "labels": win[:, 1:]}
        return self._mlm_mask(r, win[:, :-1])

    def __iter__(self) -> Iterator[Batch]:
        step = 0
        while True:
            yield self.batch(step)
            step += 1


def _hash_bucket(s: str, buckets: int) -> int:
    # stable across processes/runs (unlike Python's salted hash())
    return zlib.crc32(s.encode()) % buckets


class CriteoCsvData(ShardedEpochs):
    """Criteo click-log TSV/CSV → Wide&Deep batches, streaming-parsed.

    Columns: label, 13 numeric (I1..I13), 26 categorical (C1..C26, arbitrary
    strings — the real dataset uses hex ids). Numerics: blank → 0,
    log1p-scaled (the standard Criteo recipe). Categoricals: crc32-hash into
    ``hash_buckets`` (blank → bucket 0). Delimiter auto-detected (tab/comma).

    Scale contract (VERDICT r2 weak #6): the real dataset is ~45M rows /
    11 GB — far beyond host RAM as Python lists. The first construction
    parses the text in ~64 MB chunks (peak memory = one chunk's arrays) and
    appends the parsed columns to a binary cache next to the source
    (``<file>.dtfcache/``); every later construction memory-maps the cache
    and starts instantly. The cache is invalidated by source mtime/size or a
    different ``hash_buckets``/``num_sparse``.
    """

    CHUNK_BYTES = 64 << 20

    def __init__(self, path: str, batch_size: int, *, hash_buckets: int = 1000,
                 num_sparse: int = 26, seed: int = 0, host_index: int = 0,
                 host_count: int = 1):
        if os.path.isdir(path):
            # precedence: train.txt > *.csv > *.tsv (sorted within each tier)
            cands = (glob.glob(os.path.join(path, "train.txt"))
                     + sorted(glob.glob(os.path.join(path, "*.csv")))
                     + sorted(glob.glob(os.path.join(path, "*.tsv"))))
            if not cands:
                raise FileNotFoundError(f"no criteo csv/tsv in {path}")
            path = cands[0]
        cache = self._cache_dir(path, hash_buckets, num_sparse)
        meta_path = os.path.join(cache, "meta.json")
        want_meta = {"version": 2,  # v2: CRLF-stripping parser
                     "mtime": os.path.getmtime(path),
                     "size": os.path.getsize(path),
                     "hash_buckets": hash_buckets, "num_sparse": num_sparse}
        n_rows = None
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            if all(meta.get(k) == v for k, v in want_meta.items()):
                n_rows = meta["n_rows"]
        if n_rows is None:
            n_rows = self._build_cache(path, cache, want_meta, hash_buckets,
                                       num_sparse)
        self.labels = np.memmap(os.path.join(cache, "labels.f32"),
                                np.float32, "r", shape=(n_rows,))
        self.dense = np.memmap(os.path.join(cache, "dense.f32"),
                               np.float32, "r", shape=(n_rows, 13))
        self.sparse = np.memmap(os.path.join(cache, "sparse.i32"),
                                np.int32, "r", shape=(n_rows, num_sparse))
        super().__init__(n_rows, batch_size, seed=seed,
                         host_index=host_index, host_count=host_count)

    @staticmethod
    def _cache_dir(path: str, hash_buckets: int, num_sparse: int) -> str:
        """Writable cache location for ``(path, parse config)``.

        The parse config is part of the directory name, so jobs with
        different ``hash_buckets``/``num_sparse`` build in DISJOINT dirs —
        concurrent mixed-config builders can never tear each other's cache
        (same-config builders produce identical bytes; see _build_cache).

        Default root: next to the source. Datasets often live on read-only
        mounts, so ``DTF_DATA_CACHE`` overrides the root (cache dirs are
        then keyed by a hash of the absolute source path), and an unwritable
        default falls back to a per-user tmp root automatically.
        """
        tag = f"dtfcache-hb{hash_buckets}-ns{num_sparse}"
        root = os.environ.get("DTF_DATA_CACHE")
        if not root:
            d = f"{path}.{tag}"
            try:
                os.makedirs(d, exist_ok=True)
                probe = os.path.join(d, f".w.{os.getpid()}")
                with open(probe, "w"):
                    pass
                os.remove(probe)
                return d
            except OSError:
                import tempfile
                root = os.path.join(tempfile.gettempdir(),
                                    f"dtf_data_cache_{os.getuid()}")
        key = zlib.crc32(os.path.abspath(path).encode())
        d = os.path.join(root, f"{os.path.basename(path)}.{key:08x}.{tag}")
        os.makedirs(d, exist_ok=True)
        return d

    @classmethod
    def _build_cache(cls, path: str, cache: str, meta: dict,
                     hash_buckets: int, num_sparse: int) -> int:
        """Chunked parse → column files. Peak RAM is one chunk, not the file.

        Concurrent builders (every host of a multi-host job constructs the
        loader at startup over a shared mount) each write pid-unique tmp
        files and finish with atomic renames; the parse is deterministic, so
        whichever build lands last leaves identical bytes — no locking
        needed, no torn cache possible.
        """
        os.makedirs(cache, exist_ok=True)
        n_cols = 1 + 13 + num_sparse
        n_rows = 0
        names = ("labels.f32", "dense.f32", "sparse.i32")
        tmp = [os.path.join(cache, f"{n}.tmp.{os.getpid()}") for n in names]
        with open(path, "rb") as src, open(tmp[0], "wb") as f_lab, \
                open(tmp[1], "wb") as f_den, open(tmp[2], "wb") as f_spa:
            carry = b""
            while True:
                block = src.read(cls.CHUNK_BYTES)
                if not block:
                    if carry.strip():
                        n_rows += cls._parse_rows(
                            [carry.decode()], path, n_cols, hash_buckets,
                            f_lab, f_den, f_spa)
                    break
                block = carry + block
                nl = block.rfind(b"\n")
                if nl < 0:
                    carry = block
                    continue
                carry = block[nl + 1:]
                lines = block[:nl].decode().split("\n")
                n_rows += cls._parse_rows(lines, path, n_cols, hash_buckets,
                                          f_lab, f_den, f_spa)
        for t, n in zip(tmp, names):
            os.replace(t, os.path.join(cache, n))
        meta_tmp = os.path.join(cache, f"meta.json.tmp.{os.getpid()}")
        with open(meta_tmp, "w") as f:
            json.dump({**meta, "n_rows": n_rows}, f)
        os.replace(meta_tmp, os.path.join(cache, "meta.json"))
        return n_rows

    @staticmethod
    def _parse_rows(lines, path, n_cols, hash_buckets,
                    f_lab, f_den, f_spa) -> int:
        # rstrip('\r'): binary chunking preserves CRLF terminators that the
        # old text-mode reader swallowed; without this the last categorical
        # column of every row hashes with a trailing \r.
        rows = [ln.rstrip("\r") for ln in lines if ln.rstrip("\r")]
        if not rows:
            return 0
        sep = "\t" if "\t" in rows[0] else ","
        labels = np.empty(len(rows), np.float32)
        dense = np.empty((len(rows), 13), np.float32)
        sparse = np.empty((len(rows), n_cols - 14), np.int32)
        for i, line in enumerate(rows):
            cols = line.split(sep)
            if len(cols) != n_cols:
                raise ValueError(f"{path}: expected {n_cols} columns, "
                                 f"got {len(cols)}")
            labels[i] = float(cols[0])
            dense[i] = [float(c) if c else 0.0 for c in cols[1:14]]
            sparse[i] = [_hash_bucket(c, hash_buckets) if c else 0
                         for c in cols[14:]]
        labels.tofile(f_lab)
        np.log1p(np.maximum(dense, 0.0)).tofile(f_den)
        sparse.tofile(f_spa)
        return len(rows)

    @staticmethod
    def available(path: str) -> bool:
        if os.path.isdir(path):
            return bool(glob.glob(os.path.join(path, "train.txt"))
                        + glob.glob(os.path.join(path, "*.csv"))
                        + glob.glob(os.path.join(path, "*.tsv")))
        return path.endswith((".csv", ".tsv", ".txt")) and os.path.exists(path)

    def __iter__(self) -> Iterator[Batch]:
        for idx in self._indices():
            idx = np.sort(idx)  # sorted fancy-index: sequential mmap reads
            yield {"dense": np.asarray(self.dense[idx]),
                   "sparse": np.asarray(self.sparse[idx]),
                   "label": np.asarray(self.labels[idx])}


def _tfrecord_train_pattern(data_dir: str) -> Optional[str]:
    """TFRecord shard pattern for the train split, or None.

    Prefers ``train*``-prefixed shards; falls back to any ``*.tfrecord*``
    only when no split-prefixed files exist (an unsplit dump), so an
    eval-only directory is never mistaken for training data."""
    pat = os.path.join(data_dir, "train*.tfrecord*")
    if glob.glob(pat):
        return pat
    anyp = os.path.join(data_dir, "*.tfrecord*")
    files = glob.glob(anyp)
    prefixed = any(os.path.basename(f).startswith(("test", "validation",
                                                   "val", "eval"))
                   for f in files)
    return anyp if files and not prefixed else None


def detect_image_data(data_dir: str, batch_size: int, **kw) -> Optional[object]:
    """npy pair > CIFAR binary > TFRecord shards > None, for the resnet
    script. TFRecord Examples use the conventional image/label (+
    height/width/depth) keys — the reference-era dump format."""
    if not data_dir:
        return None
    if NpyImageData.available(data_dir):
        return NpyImageData(data_dir, batch_size, **kw)
    if CifarBinData.available(data_dir):
        return CifarBinData(data_dir, batch_size, **kw)
    pat = _tfrecord_train_pattern(data_dir)
    if pat:
        from dtf_tpu.data.tfrecord import (TFRecordExampleData,
                                           image_example_transform)

        return TFRecordExampleData(pat, batch_size,
                                   transform=image_example_transform(), **kw)
    return None


def detect_image_eval_data(data_dir: str, batch_size: int,
                           **kw) -> Optional[object]:
    """The matching held-out split: ``test_images.npy``/``test_labels.npy``,
    or CIFAR's ``test_batch.bin``. None when no eval files exist — callers
    should then drop eval rather than score on unrelated data."""
    if not data_dir:
        return None
    if NpyImageData.available(data_dir, split="test"):
        return NpyImageData(data_dir, batch_size, split="test", **kw)
    if os.path.exists(os.path.join(data_dir, "test_batch.bin")):
        return CifarBinData(data_dir, batch_size, split="test", **kw)
    for split in ("test", "validation", "val", "eval"):
        pat = os.path.join(data_dir, f"{split}*.tfrecord*")
        if glob.glob(pat):
            from dtf_tpu.data.tfrecord import (TFRecordExampleData,
                                               image_example_transform)

            return TFRecordExampleData(
                pat, batch_size, transform=image_example_transform(), **kw)
    return None


def detect_token_data(data_dir: str, batch_size: int, seq_len: int, *,
                      mode: str, vocab_size: int = 0, split: str = "train",
                      **kw) -> Optional[object]:
    """``<dir>/<split>.bin`` (nanoGPT convention: train.bin / val.bin), or a
    direct ``.bin`` path for the train split. None when the split is
    absent — callers then fall back (synthetic, or skip eval). A PRESENT
    but unusable non-train split (too short for seq_len, empty file) also
    falls back with a warning instead of killing a run whose training data
    is fine; the train split still fails loudly."""
    if not data_dir or not TokenBinData.available(data_dir, split):
        return None
    try:
        return TokenBinData(data_dir, batch_size, seq_len, mode=mode,
                            vocab_size=vocab_size, split=split, **kw)
    except ValueError:
        if split == "train":
            raise
        import logging

        logging.getLogger("dtf_tpu").warning(
            "%s/%s.bin exists but is unusable (too short for seq_len=%d?); "
            "falling back", data_dir, split, seq_len, exc_info=True)
        return None


def detect_criteo_data(data_dir: str, batch_size: int,
                       **kw) -> Optional[object]:
    if data_dir and CriteoCsvData.available(data_dir):
        return CriteoCsvData(data_dir, batch_size, **kw)
    return None
