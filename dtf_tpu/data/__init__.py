"""Input pipeline: per-host sharded batches for the five BASELINE workloads."""
