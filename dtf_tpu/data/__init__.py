"""Input pipeline: per-host sharded batches for the five BASELINE workloads,
plus the streaming data tier (``dtf_tpu/data/stream`` — weighted
multi-dataset mixture with deterministic checkpointed resume; docs/DATA.md)."""
