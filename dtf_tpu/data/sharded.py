"""Shared epoch/shuffle/host-shard iteration contract for array datasets.

One implementation of the reference loader's ``next_batch`` semantics
(reshuffle each epoch, disjoint per-host row shards) used by every
array-backed dataset — :class:`dtf_tpu.data.mnist.MnistData` and the
on-disk formats in :mod:`dtf_tpu.data.formats` — so the sharding rule can
never silently diverge between loaders.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


def epoch_order(n: int, seed: int, epoch: int) -> np.ndarray:
    """Deterministic per-epoch permutation (same on every host)."""
    return np.random.default_rng(
        np.random.SeedSequence([seed, epoch])).permutation(n)


class ShardedEpochs:
    """Base class: epoch reshuffle + ``order[host::count]`` row sharding +
    ``local_batch`` windowing. Subclasses implement ``__iter__`` by drawing
    index batches from :meth:`_indices`."""

    def __init__(self, n_rows: int, batch_size: int, *, seed: int,
                 host_index: int, host_count: int):
        if batch_size % host_count:
            raise ValueError(f"global batch {batch_size} not divisible by "
                             f"{host_count} hosts")
        if n_rows // host_count < batch_size // host_count:
            # _indices() yields nothing when a host shard can't fill one
            # batch, and the epoch while-loop would then busy-spin forever —
            # an empty/undersized dataset must fail loudly instead.
            raise ValueError(
                f"dataset has {n_rows} rows — too few to fill one batch of "
                f"{batch_size} across {host_count} host(s)")
        self.n_rows = n_rows
        self.local_batch = batch_size // host_count
        self.seed = seed
        self.host_index = host_index
        self.host_count = host_count

    def batches_per_epoch_uniform(self) -> int:
        """Per-epoch batch count guaranteed IDENTICAL on every host.

        ``order[host::count]`` gives early hosts one extra row when
        ``n_rows % host_count != 0``; a full-epoch sweep driving a jitted
        collective step must use the same iteration count everywhere or the
        mesh deadlocks. This is the minimum any host can fill.
        """
        return (self.n_rows // self.host_count) // self.local_batch

    def _indices(self) -> Iterator[np.ndarray]:
        epoch = 0
        while True:
            order = epoch_order(self.n_rows, self.seed, epoch)
            shard = order[self.host_index::self.host_count]
            for i in range(0, len(shard) - self.local_batch + 1,
                           self.local_batch):
                yield shard[i:i + self.local_batch]
            epoch += 1


# ---------------------------------------------------------------------------
# Fake-N-hosts input feeding (the elastic-harness data seam).
# ---------------------------------------------------------------------------

def loaders_for_hosts(make_loader, views) -> list:
    """One per-host loader per :class:`dtf_tpu.core.mesh.HostView`.

    ``make_loader(host_index=, host_count=)`` is the loader constructor
    partial every launcher already has (all array loaders and
    ``SyntheticData`` take exactly these two kwargs); each returned loader
    yields that host's disjoint row shard of the global batch.
    """
    return [make_loader(host_index=v.host_index, host_count=v.host_count)
            for v in views]


class FakeHostStream:
    """Zip N per-host loaders into an iterator of per-host batch lists.

    One item = ``[host 0's local batch, ..., host N-1's local batch]`` —
    exactly the shape :func:`dtf_tpu.core.comms.fake_hosts_to_global`
    assembles onto the mesh (pass that as the Trainer's ``place_batch``).
    The single-process fake-cluster worker iterates this instead of one
    global loader, so the per-host sharding contract (disjoint rows,
    equal shares, host-aligned placement) is exercised on every step of a
    CPU-sim run, not just in the real multi-process launch.
    """

    def __init__(self, loaders):
        if not loaders:
            raise ValueError("need at least one per-host loader")
        self.loaders = list(loaders)

    def __iter__(self) -> Iterator[list]:
        its = [iter(ld) for ld in self.loaders]
        while True:
            try:
                yield [next(it) for it in its]
            except StopIteration:
                return
