"""Shared epoch/shuffle/host-shard iteration contract for array datasets.

One implementation of the reference loader's ``next_batch`` semantics
(reshuffle each epoch, disjoint per-host row shards) used by every
array-backed dataset — :class:`dtf_tpu.data.mnist.MnistData` and the
on-disk formats in :mod:`dtf_tpu.data.formats` — so the sharding rule can
never silently diverge between loaders.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


def epoch_order(n: int, seed: int, epoch: int) -> np.ndarray:
    """Deterministic per-epoch permutation (same on every host)."""
    return np.random.default_rng(
        np.random.SeedSequence([seed, epoch])).permutation(n)


class ShardedEpochs:
    """Base class: epoch reshuffle + ``order[host::count]`` row sharding +
    ``local_batch`` windowing. Subclasses implement ``__iter__`` by drawing
    index batches from :meth:`_indices`."""

    def __init__(self, n_rows: int, batch_size: int, *, seed: int,
                 host_index: int, host_count: int):
        if batch_size % host_count:
            raise ValueError(f"global batch {batch_size} not divisible by "
                             f"{host_count} hosts")
        if n_rows // host_count < batch_size // host_count:
            # _indices() yields nothing when a host shard can't fill one
            # batch, and the epoch while-loop would then busy-spin forever —
            # an empty/undersized dataset must fail loudly instead.
            raise ValueError(
                f"dataset has {n_rows} rows — too few to fill one batch of "
                f"{batch_size} across {host_count} host(s)")
        self.n_rows = n_rows
        self.local_batch = batch_size // host_count
        self.seed = seed
        self.host_index = host_index
        self.host_count = host_count

    def batches_per_epoch_uniform(self) -> int:
        """Per-epoch batch count guaranteed IDENTICAL on every host.

        ``order[host::count]`` gives early hosts one extra row when
        ``n_rows % host_count != 0``; a full-epoch sweep driving a jitted
        collective step must use the same iteration count everywhere or the
        mesh deadlocks. This is the minimum any host can fill.
        """
        return (self.n_rows // self.host_count) // self.local_batch

    def _indices(self) -> Iterator[np.ndarray]:
        epoch = 0
        while True:
            order = epoch_order(self.n_rows, self.seed, epoch)
            shard = order[self.host_index::self.host_count]
            for i in range(0, len(shard) - self.local_batch + 1,
                           self.local_batch):
                yield shard[i:i + self.local_batch]
            epoch += 1
