"""Resumable example sources — the per-dataset half of the mixture stream.

A *source* is random-access over an infinite example sequence: ``example(i)``
returns example ``i`` as an unbatched row dict, deterministically, with NO
hidden iteration state. The mixture stream's only per-source state is then a
single integer cursor ("examples consumed so far"), which is what makes the
whole tier checkpointable in a handful of ints and re-partitionable across a
shrunk host set (docs/DATA.md): example ``i`` is the same bytes no matter
which host materializes it or when.

Two shipped sources (both jax-free, numpy-only):

- :class:`TokenBinSource` — a flat token ``.bin`` corpus via the existing
  :class:`dtf_tpu.data.formats.TokenBinData` reader's ``example`` cursor
  hook (random seq_len+1 windows keyed ``[seed, salt, index]``).
- :class:`TFRecordSource` — TFRecord shards with an explicit record-offset
  cursor: example ``i`` maps through the per-epoch permutation to a record,
  whose payload CRC is verified AT READ TIME — a corrupt record is skipped
  with a WARN (the next readable record in epoch order stands in) instead of
  poisoning the run, riding the crc32c machinery the framing already uses.

All sources feeding one mixture must share a schema (same keys, shapes,
dtypes per row) — validated by the stream at construction.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import numpy as np

log = logging.getLogger("dtf_tpu")

Row = Dict[str, np.ndarray]


class TokenBinSource:
    """LM examples over a flat token ``.bin`` corpus (nanoGPT packing).

    ``example(i)`` is :meth:`dtf_tpu.data.formats.TokenBinData.example` —
    one ``[seq_len]`` window drawn by counter-based rng from the global
    example index, host-free. ``salt`` separates the rng streams of two
    sources over the SAME file (two mixture components sampling one corpus
    at different weights must not emit identical windows in lockstep).
    """

    def __init__(self, path: str, seq_len: int, *, mode: str = "clm",
                 vocab_size: int = 0, seed: int = 0, salt: int = 0,
                 name: Optional[str] = None):
        from dtf_tpu.data.formats import TokenBinData

        # local_batch is irrelevant for example() access; host 0/1 keeps
        # the reader's own batch API usable for debugging.
        self._data = TokenBinData(path, 1, seq_len, mode=mode,
                                  vocab_size=vocab_size,
                                  seed=seed + salt, host_index=0,
                                  host_count=1)
        self.name = name or path
        self.seq_len = seq_len

    def example(self, index: int) -> Row:
        return self._data.example(index)


class TFRecordSource:
    """LM examples over TFRecord shards, with a record-offset cursor.

    Records hold a fixed-length int64 ``tokens_key`` feature of
    ``seq_len + 1`` tokens (the packed-window dump format); rows come out
    in the CLM schema ``{input_ids, labels}`` so they mix with
    :class:`TokenBinSource` rows. Example ``i`` maps to record
    ``epoch_order(n, seed, i // n)[i % n]`` — the same deterministic
    per-epoch reshuffle every array loader uses (``data/sharded.py``), as
    an explicit offset mapping instead of iterator state.

    Framing is indexed ONCE without payload verification
    (:func:`dtf_tpu.data.tfrecord.tfrecord_spans`); each read then verifies
    its own payload CRC (:func:`~dtf_tpu.data.tfrecord.record_payload_verified`)
    and a mismatch SKIPS to the next record in epoch order with one WARN
    per damaged record — deterministic under resume (the same file bytes
    skip the same records) and chaos-testable (``corrupt_record`` verb:
    :meth:`poison_next`).
    """

    #: bounded forward scan before giving up: a shard where this many
    #: consecutive records fail CRC is damaged wholesale, not bit-rotted.
    MAX_SKIP_SCAN = 64

    def __init__(self, pattern: str, seq_len: int, *, tokens_key="tokens",
                 seed: int = 0, name: Optional[str] = None):
        import glob as glob_mod

        from dtf_tpu.data.tfrecord import tfrecord_spans

        files = sorted(glob_mod.glob(pattern))
        if not files:
            raise FileNotFoundError(f"no TFRecord files match {pattern!r}")
        self._maps, file_ids, offs, lens = [], [], [], []
        for i, f in enumerate(files):
            off, length = tfrecord_spans(f, verify_payload_crc=False)
            self._maps.append(memoryview(np.memmap(f, np.uint8, "r"))
                              if off.size else None)
            file_ids.append(np.full(off.size, i, np.int32))
            offs.append(off)
            lens.append(length)
        self._file_id = np.concatenate(file_ids)
        self._off = np.concatenate(offs)
        self._len = np.concatenate(lens)
        self.n_records = int(self._off.size)
        if not self.n_records:
            raise ValueError(f"no records in TFRecord files {pattern!r}")
        self.name = name or pattern
        self.seq_len = seq_len
        self.tokens_key = tokens_key
        self.seed = seed
        #: actual CRC-skip events (real bit rot AND the injected verb) —
        #: aggregated into MixtureStream.stats()["corrupt_skips"].
        self.corrupt_skips = 0
        self._warned: set[int] = set()
        self._epoch_perm: tuple = (-1, None)   # (epoch, cached permutation)
        self._poison_next = False

    def poison_next(self) -> None:
        """Arm the ``corrupt_record`` chaos verb: the next record read is
        treated as a CRC mismatch, driving the exact skip-with-WARN branch
        a damaged file takes — without touching the (possibly shared,
        possibly read-only) data files."""
        self._poison_next = True

    def _payload(self, rec: int):
        from dtf_tpu.data.tfrecord import record_payload_verified

        if self._poison_next:
            self._poison_next = False
            return None
        view = self._maps[int(self._file_id[rec])]
        return record_payload_verified(view, int(self._off[rec]),
                                       int(self._len[rec]))

    def _record_for(self, i: int) -> int:
        """Example index → record, through the per-epoch permutation —
        computed ONCE per epoch and cached (per-example recompute would
        be O(n_records) work per row and the producer could never outrun
        the step on a real shard set)."""
        from dtf_tpu.data.sharded import epoch_order

        epoch, pos = divmod(i, self.n_records)
        if self._epoch_perm[0] != epoch:
            self._epoch_perm = (epoch, epoch_order(self.n_records,
                                                   self.seed, epoch))
        return int(self._epoch_perm[1][pos])

    def example(self, index: int) -> Row:
        from dtf_tpu.data.tfrecord import parse_example

        index = int(index)
        for hop in range(self.MAX_SKIP_SCAN):
            rec = self._record_for(index + hop)
            payload = self._payload(rec)
            if payload is not None:
                tokens = np.asarray(
                    parse_example(payload)[self.tokens_key], np.int32)
                if tokens.size < self.seq_len + 1:
                    raise ValueError(
                        f"{self.name}: record {rec} holds {tokens.size} "
                        f"tokens < seq_len+1={self.seq_len + 1}")
                win = tokens[:self.seq_len + 1]
                return {"input_ids": win[:-1], "labels": win[1:]}
            self.corrupt_skips += 1
            if rec not in self._warned:
                self._warned.add(rec)
                log.warning(
                    "%s: record %d failed its payload CRC; skipping it "
                    "(the next record in epoch order stands in) — damaged "
                    "data must not poison the run", self.name, rec)
        raise ValueError(
            f"{self.name}: {self.MAX_SKIP_SCAN} consecutive records failed "
            f"their payload CRCs from example {index} — the shard is "
            "damaged wholesale, not bit-rotted; re-fetch it")
