"""Serve-log records as a resumable stream source — the flywheel's seam.

The serving fleet's :class:`dtf_tpu.serve.logsink.LogSink` records every
terminal ``done`` request into size-rotated jsonl shards; this module owns
the RECORD CODEC both sides share and the :class:`ServeLogSource` that
mounts a sink directory as a mixture-stream source, so "retrain on
yesterday's traffic" is a ``--stream_spec`` edit riding the full PR 15
determinism contract (docs/DATA.md).

On-disk format (write side: ``dtf_tpu/serve/logsink.py``, exclusively
through the ``_hostio`` choke points):

- ``shard-00000.jsonl`` … — one record per line, framed
  ``"<crc32c:08x> <body>"`` where ``body`` is compact key-sorted JSON.
  The CRC covers the body bytes; a reader verifies it per record and a
  mismatch SKIPS the record deterministically with one WARN (the
  TFRecord source's bit-rot discipline, applied to jsonl).
- ``SERVELOG_MANIFEST.json`` — the atomic commit point: the ordered list
  of COMMITTED shards. A shard enters the manifest only once rotated (or
  flushed) — a crash mid-rotation leaves a fully-written shard on disk
  that the next sink over the same directory ADOPTS back into the
  manifest, so committed records are never lost and never re-ordered.

``ServeLogSource`` scans the committed shards ONCE at construction
(verify CRC, apply the spec's filters) into an in-memory index; example
``i`` then maps through the per-epoch permutation exactly like
:class:`~dtf_tpu.data.stream.sources.TFRecordSource` — counter-based,
host-free, random-access — and re-verifies the record CRC at read time
(the ``corrupt_record`` chaos verb's :meth:`poison_next` seam).

jax-free at module level (srclint-fenced with the rest of the package).
"""

from __future__ import annotations

import json
import logging
import os
from typing import List, Optional

import numpy as np

from dtf_tpu.data.sharded import epoch_order
from dtf_tpu.data.tfrecord import crc32c

log = logging.getLogger("dtf_tpu")

#: the sink directory's atomic commit point (written via atomic_replace).
MANIFEST_BASENAME = "SERVELOG_MANIFEST.json"

#: manifest schema version (bump on incompatible layout changes).
MANIFEST_VERSION = 1

#: shard file naming — index-ordered so the manifest's list and a plain
#: directory sort agree on shard order.
SHARD_FMT = "shard-%05d.jsonl"


# ---------------------------------------------------------------------------
# The record codec (both sides of the flywheel import THESE two functions —
# a sink that framed records any other way would silently strand traffic).
# ---------------------------------------------------------------------------

def encode_record(rec: dict) -> str:
    """One serve-log record → one framed jsonl line (no trailing newline).

    Body is compact key-sorted JSON so the same record always encodes to
    the same bytes (the CRC, and therefore the corrupt-skip decisions,
    are deterministic functions of the record's CONTENT)."""
    body = json.dumps(rec, sort_keys=True, separators=(",", ":"))
    return f"{crc32c(body.encode()):08x} {body}"


def decode_record(line: str) -> Optional[dict]:
    """Framed line → record dict, or None when the frame/CRC/JSON is
    damaged (the caller decides whether to skip or count)."""
    crc_hex, sep, body = line.partition(" ")
    if not sep or len(crc_hex) != 8:
        return None
    try:
        crc = int(crc_hex, 16)
    except ValueError:
        return None
    if crc32c(body.encode()) != crc:
        return None
    try:
        rec = json.loads(body)
    except ValueError:
        return None
    return rec if isinstance(rec, dict) else None


def shard_name(index: int) -> str:
    return SHARD_FMT % int(index)


def manifest_path(sink_dir: str) -> str:
    return os.path.join(sink_dir, MANIFEST_BASENAME)


def read_manifest(sink_dir: str) -> Optional[dict]:
    """The committed-shard list, or None when the directory has never
    committed one (a fresh sink dir, or one that crashed before its first
    rotation — adoption handles the orphan shards either way)."""
    try:
        with open(manifest_path(sink_dir)) as f:
            manifest = json.load(f)
    except FileNotFoundError:
        return None
    if int(manifest.get("version", -1)) != MANIFEST_VERSION:
        raise ValueError(
            f"serve-log manifest version {manifest.get('version')!r} != "
            f"{MANIFEST_VERSION} under {sink_dir!r}")
    return manifest


# ---------------------------------------------------------------------------
# The stream source.
# ---------------------------------------------------------------------------

class ServeLogSource:
    """LM examples over a serve-log sink directory (module docstring).

    Rows come out in the shared CLM schema ``{input_ids, labels}`` (int32
    ``[seq_len]``) so served traffic mixes freely with ``tokens``/
    ``tfrecord`` corpora: each record's ``prompt + tokens`` concatenation
    is windowed to ``seq_len + 1`` (the TAIL window when longer — the
    served completion is the training signal) and padded with ``pad_id``
    when shorter.

    Filters (all spec-resolvable, manifest-authoritative on resume):

    - ``status`` — record status to keep (default ``"done"``; the sink
      only writes terminal dones today, but the filter makes the contract
      explicit and future-proof);
    - ``min_version``/``max_version`` — keep records decoded by param
      versions in the closed range (None = unbounded);
    - ``min_tokens`` — drop records with fewer completion tokens.

    Records failing their CRC at SCAN time are dropped deterministically
    with one WARN each (same bytes → same drops → same index on every
    host and every resume); records failing at READ time (bit rot after
    mount, or the ``corrupt_record`` verb via :meth:`poison_next`) skip
    to the next record in epoch order, the TFRecord source's discipline.
    """

    #: bounded forward scan before giving up (a sink where this many
    #: consecutive records rot after mount is damaged wholesale).
    MAX_SKIP_SCAN = 64

    def __init__(self, path: str, seq_len: int, *, seed: int = 0,
                 name: Optional[str] = None, status: str = "done",
                 min_version: Optional[int] = None,
                 max_version: Optional[int] = None, min_tokens: int = 0,
                 pad_id: int = 0):
        manifest = read_manifest(path)
        if manifest is None:
            raise FileNotFoundError(
                f"no {MANIFEST_BASENAME} under {path!r} — not a serve-log "
                "sink directory (or the sink never committed a shard; "
                "flush/close the sink, or point at the right dir)")
        self.name = name or path
        self.seq_len = int(seq_len)
        self.seed = int(seed)
        self.status = status
        self.min_version = min_version
        self.max_version = max_version
        self.min_tokens = int(min_tokens)
        self.pad_id = int(pad_id)
        #: actual CRC-skip events on the READ path (bit rot after mount
        #: and the injected verb alike) — aggregated into
        #: ``MixtureStream.stats()["corrupt_skips"]``.
        self.corrupt_skips = 0
        #: records dropped at SCAN time (CRC damage on disk) — distinct
        #: from read-path skips so stats tell the two stories apart.
        self.scan_drops = 0
        self._filtered = 0
        self._warned: set = set()
        self._epoch_perm: tuple = (-1, None)
        self._poison_next = False
        #: the index: raw framed line per ACCEPTED record, in (shard,
        #: line) order — the addressing every host agrees on.
        self._lines: List[str] = self._scan(path, manifest)
        self.n_records = len(self._lines)
        if not self.n_records:
            raise ValueError(
                f"{self.name}: no records under {path!r} survive the "
                f"filters (status={status!r}, version=[{min_version}, "
                f"{max_version}], min_tokens={min_tokens}) — an empty "
                "source cannot feed a mixture")

    # --------------------------------------------------------------- scan

    def _accept(self, rec: dict) -> bool:
        if rec.get("status", "done") != self.status:
            return False
        v = rec.get("version")
        if self.min_version is not None and (v is None
                                             or int(v) < self.min_version):
            return False
        if self.max_version is not None and (v is None
                                             or int(v) > self.max_version):
            return False
        if len(rec.get("tokens", ())) < self.min_tokens:
            return False
        return True

    def _scan(self, path: str, manifest: dict) -> List[str]:
        lines: List[str] = []
        for sh in manifest["shards"]:
            shard = os.path.join(path, sh["name"])
            with open(shard) as f:
                raw = f.read()
            for lineno, line in enumerate(raw.split("\n")):
                if not line:
                    continue          # the torn/empty tail line
                rec = decode_record(line)
                if rec is None:
                    self.scan_drops += 1
                    key = (sh["name"], lineno)
                    if key not in self._warned:
                        self._warned.add(key)
                        log.warning(
                            "%s: %s line %d failed its record CRC; "
                            "dropped at scan (damaged traffic must not "
                            "poison the run)", self.name, sh["name"],
                            lineno)
                    continue
                if not self._accept(rec):
                    self._filtered += 1
                    continue
                lines.append(line)
        return lines

    # --------------------------------------------------------------- reads

    def poison_next(self) -> None:
        """Arm the ``corrupt_record`` chaos verb: the next record read is
        treated as a CRC mismatch, driving the same skip-with-WARN branch
        post-mount bit rot takes — without touching the shard files."""
        self._poison_next = True

    def _record(self, rec: int) -> Optional[dict]:
        if self._poison_next:
            self._poison_next = False
            return None
        return decode_record(self._lines[rec])

    def _record_for(self, i: int) -> int:
        """Example index → record through the per-epoch permutation
        (cached per epoch — the TFRecord source's idiom)."""
        epoch, pos = divmod(i, self.n_records)
        if self._epoch_perm[0] != epoch:
            self._epoch_perm = (epoch, epoch_order(self.n_records,
                                                   self.seed, epoch))
        return int(self._epoch_perm[1][pos])

    def _window(self, rec: dict) -> np.ndarray:
        full = [int(t) for t in rec.get("prompt", ())] \
            + [int(t) for t in rec.get("tokens", ())]
        want = self.seq_len + 1
        if len(full) >= want:
            win = full[-want:]       # the tail keeps the completion
        else:
            win = full + [self.pad_id] * (want - len(full))
        return np.asarray(win, np.int32)

    def example(self, index: int) -> dict[str, np.ndarray]:
        index = int(index)
        for hop in range(self.MAX_SKIP_SCAN):
            rec_i = self._record_for(index + hop)
            rec = self._record(rec_i)
            if rec is not None:
                win = self._window(rec)
                return {"input_ids": win[:-1], "labels": win[1:]}
            self.corrupt_skips += 1
            if rec_i not in self._warned:
                self._warned.add(rec_i)
                log.warning(
                    "%s: record %d failed its record CRC; skipping it "
                    "(the next record in epoch order stands in) — damaged "
                    "traffic must not poison the run", self.name, rec_i)
        raise ValueError(
            f"{self.name}: {self.MAX_SKIP_SCAN} consecutive records failed "
            f"their CRCs from example {index} — the sink is damaged "
            "wholesale, not bit-rotted; re-capture it")

    def stats(self) -> dict:
        return {"records": self.n_records, "scan_drops": self.scan_drops,
                "filtered": self._filtered,
                "corrupt_skips": self.corrupt_skips}


__all__ = ["MANIFEST_BASENAME", "MANIFEST_VERSION", "ServeLogSource",
           "decode_record", "encode_record", "manifest_path",
           "read_manifest", "shard_name"]
