"""StreamState ⇄ checkpoint plumbing — the resume half of the contract.

One hook wires a :class:`~dtf_tpu.data.stream.mixture.MixtureStream` into
the existing checkpoint lifecycle with zero Trainer changes:

- **save side**: construction registers ``stream.state_at`` as the
  Checkpointer's ``"stream"`` extra-item provider, so EVERY save path —
  periodic :class:`~dtf_tpu.hooks.CheckpointHook`, the PreemptionHook's
  SIGTERM ``save_durable``, the end-of-run force save — stamps the
  StreamState for exactly the step being saved (NOT the producer's
  lookahead position; ``state_at`` exists for precisely that skew).
- **restore side**: ``begin`` runs after the Trainer's restore-if-exists,
  so :attr:`Checkpointer.last_restored_step` names the step actually
  loaded (the guarded fallback walk included). The stream restores the
  matching StreamState; a LEGACY checkpoint without one WARNs and
  fast-forwards by replaying the pure draws (:meth:`MixtureStream.seek`)
  — correct whenever the spec is unchanged (the manifest guard's job),
  minus any live reweights the legacy checkpoint never recorded.

Duck-typed against :class:`dtf_tpu.hooks.Hook` (the FaultHook idiom): no
jax import, so the package fence holds.
"""

from __future__ import annotations

import logging
import time

log = logging.getLogger("dtf_tpu")

#: the Composite member name StreamState rides under.
EXTRA_ITEM = "stream"


class StreamCheckpointHook:
    """Wire a MixtureStream's state into an existing Checkpointer (see
    module docstring). Place anywhere in the hook list — the provider
    fires inside ``Checkpointer.save`` itself, not at hook order."""

    telemetry_bucket = "checkpoint"

    def __init__(self, ckpt, stream, *, wall=time.time):
        self.ckpt = ckpt
        self.stream = stream
        #: injectable wall clock for :attr:`resume_events` stamps (the
        #: host pass's clock-escape discipline; tests pin it).
        self._wall = wall
        #: structured degraded-resume records, mirroring
        #: ``Checkpointer.resume_events`` — the legacy fast-forward WARN
        #: leaves a machine-readable trail for run reports.
        self.resume_events: list = []
        if ckpt is not None:
            ckpt.add_extra_provider(EXTRA_ITEM, stream.state_at)

    def begin(self, state) -> None:
        if self.ckpt is None:
            return
        step = self.ckpt.last_restored_step
        if step is None:
            return                      # fresh run: stream starts at 0
        saved = self.ckpt.restore_extra(EXTRA_ITEM, step=step)
        if saved is None:
            log.warning(
                "checkpoint step %d has no stream state (pre-stream "
                "legacy run); fast-forwarding the mixture by replaying "
                "its draws to step %d — live reweights from the old run, "
                "if any, are lost", step, step)
            self.resume_events.append({
                "event": "legacy-stream-seek", "step": step,
                "t": round(self._wall(), 3)})
            self.stream.seek(step)
            return
        self.stream.restore(saved)
        log.info("stream resumed at step %d (cursors %s)", step,
                 saved["cursors"])

    def before_step(self, step: int) -> None: ...

    def after_step(self, step: int, state, metrics) -> None: ...

    def end(self, state) -> None:
        self.stream.close()
