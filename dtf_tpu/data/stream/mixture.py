"""The multi-source weighted-mixture stream with checkpointed resume.

Design invariants (docs/DATA.md):

- **Counter-based, no hidden state.** Which source supplies each example of
  global batch ``s`` is drawn by rng keyed ``[seed, salt, s]``; which bytes
  a source returns for its ``i``-th example is keyed by ``i`` alone. The
  ENTIRE realized batch sequence is therefore a pure function of
  ``(spec, seed, weight schedule)`` plus one integer cursor per source —
  that tuple IS the checkpointable :meth:`MixtureStream.state`.
- **Global addressing, host slicing.** Draws and cursors describe the
  GLOBAL batch; a host materializes only its row range
  (:meth:`dtf_tpu.core.mesh.HostView.batch_rows`). Cursor state is thus
  host-count-invariant, which makes the dp8→dp4 shrink resume a pure
  re-partition: the survivors build the same global sequence and slice
  different rows of it.
- **Realized fractions converge** to the requested weights (multinomial
  draws per row), and :meth:`reweight` changes the target at a NAMED step,
  recorded in the weight schedule so a resumed run replays the same mix.
- **Backpressure is visible, never fatal.** The optional bounded producer
  thread (``producer_depth``) assembles batches ahead of the consumer;
  when the trainer outruns it the wait lands in the existing ``data_wait``
  span, and :meth:`stats` reports per-source throughput, queue depth and
  realized fractions for the RunReport.

jax-free at module level (srclint-fenced like ``fault/``/``tune/``): batch
assembly is pure host numpy; device placement stays the Trainer's job.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

log = logging.getLogger("dtf_tpu")

#: SeedSequence salt for the per-step source draw (disjoint from the
#: sources' own example streams and the readers' batch streams).
MIX_SALT = 0x5EED_00F2

#: StreamState schema version (bump on incompatible layout changes).
STATE_VERSION = 1

#: snapshots kept for ``state_at`` (must exceed the deepest lookahead:
#: producer queue + trainer prefetch; recompute covers anything older).
_KEEP_SNAPSHOTS = 128


class MixtureStream:
    """Weighted mixture over resumable sources (see module docstring).

    ``sources`` — objects with ``.name`` and ``.example(i) -> row dict``
    (``dtf_tpu/data/stream/sources.py``); all must share a row schema.
    ``weights`` — ``{name: weight}`` (normalized here; all > 0).
    ``global_batch`` — rows per GLOBAL batch; this instance materializes
    the ``host_view`` slice of it (default: the whole batch).
    ``producer_depth`` — 0: assemble inline in the consumer's ``next()``;
    N>0: a bounded background thread keeps up to N batches staged.
    """

    def __init__(self, sources: Sequence, weights: Dict[str, float],
                 global_batch: int, *, seed: int = 0, host_view=None,
                 producer_depth: int = 0, stall_s: float = 1.0,
                 clock=time.perf_counter, sleep=time.sleep):
        if not sources:
            raise ValueError("need at least one source")
        names = [s.name for s in sources]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate source names: {names}")
        if set(weights) != set(names):
            raise ValueError(
                f"weights {sorted(weights)} must name exactly the sources "
                f"{sorted(names)}")
        if global_batch < 1:
            raise ValueError(f"global_batch must be >= 1, got {global_batch}")
        self.sources = list(sources)
        self.names = names
        self.global_batch = int(global_batch)
        self.seed = int(seed)
        #: ``host_view=None`` means "the whole global batch" (single-host
        #: runs) WITHOUT touching dtf_tpu.core.mesh — HostView lives in a
        #: jax-importing module, and this package must work with no
        #: backend at all (the srclint fence's dynamic twin).
        self.host_view = host_view
        self._host_rows = (host_view.batch_rows(global_batch)
                           if host_view is not None
                           else (0, self.global_batch))
        self.producer_depth = int(producer_depth)
        self.stall_s = float(stall_s)
        #: injectable clock/sleep — tests drive the stall verb and the
        #: produce_s accounting without real wall time (analysis host
        #: pass: clock-escape)
        self._clock = clock
        self._sleep = sleep
        #: weight schedule: [[step, {name: weight}], ...] sorted by step;
        #: entry k applies from its step until the next entry's.
        self._schedule: List[list] = [[0, self._normalize(weights)]]
        self._cursors = {n: 0 for n in names}
        self._next_step = 0
        self._snapshots: Dict[int, dict] = {0: dict(self._cursors)}
        self._lock = threading.Lock()
        self._started = False
        self._stop = threading.Event()
        self._fault = None
        self._fault_fired = False
        #: optional fleet EventLog (ISSUE 20): reweights and fault
        #: firings land on the run timeline. EventLog.emit is internally
        #: locked — the producer thread emits safely.
        self._event_log = None
        self._stats = {
            "batches": 0, "examples": {n: 0 for n in names},
            "produce_s": 0.0, "producer_blocked_s": 0.0,
            "consumer_wait_s": 0.0, "queue_depth_max": 0,
            "stalls": 0,
        }
        self._validate_schema()

    # ------------------------------------------------------------- schedule

    @staticmethod
    def _normalize(weights: Dict[str, float]) -> Dict[str, float]:
        if any(w <= 0 for w in weights.values()):
            raise ValueError(f"weights must be > 0, got {weights}")
        total = float(sum(weights.values()))
        return {n: float(w) / total for n, w in weights.items()}

    def _weights_at(self, step: int) -> np.ndarray:
        entry = self._schedule[0][1]
        for start, w in self._schedule:
            if start <= step:
                entry = w
            else:
                break
        return np.asarray([entry[n] for n in self.names], np.float64)

    def reweight(self, at_step: int, weights: Dict[str, float]) -> None:
        """Change the target mixture, effective at global step ``at_step``.

        ``at_step`` must not precede batches already produced — the draws
        for those steps are history a resume must replay, so rewriting
        them would fork the sequence. The new entry is recorded in the
        weight schedule and rides :meth:`state` into the checkpoint.
        """
        with self._lock:
            if at_step < self._next_step:
                raise ValueError(
                    f"reweight at step {at_step} would rewrite history "
                    f"(next step is {self._next_step})")
            if set(weights) != set(self.names):
                raise ValueError(
                    f"reweight {sorted(weights)} must name exactly the "
                    f"sources {sorted(self.names)}")
            norm = self._normalize(weights)
            # build + sort LOCALLY, publish once: _weights_at reads the
            # schedule without this lock (the producer thread's _draw), so
            # it must never observe a half-sorted list
            schedule = ([e for e in self._schedule if e[0] != at_step]
                        + [[at_step, norm]])
            schedule.sort(key=lambda e: e[0])
            self._schedule = schedule
            log.info("mixture reweighted at step %d: %s", at_step,
                     {n: round(w, 4) for n, w in norm.items()})
        if self._event_log is not None:
            self._event_log.emit(
                "stream_reweight", at_step=int(at_step),
                weights={n: round(w, 6) for n, w in norm.items()})

    def attach_event_log(self, event_log) -> None:
        """Mirror stream lifecycle (reweights, chaos-verb firings) onto a
        fleet :class:`dtf_tpu.telemetry.events.EventLog`. The producer
        thread reads the reference, so the publish takes the class lock
        (the EventLog itself is internally locked)."""
        with self._lock:
            self._event_log = event_log

    # ------------------------------------------------------------ the draws

    def _draw(self, step: int) -> np.ndarray:
        """Source id per GLOBAL row of batch ``step`` (pure)."""
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, MIX_SALT, int(step)]))
        return rng.choice(len(self.sources), size=self.global_batch,
                          p=self._weights_at(step))

    def _counts(self, ids: np.ndarray) -> np.ndarray:
        return np.bincount(ids, minlength=len(self.sources))

    def _build(self, step: int, cursors: Dict[str, int],
               ids: Optional[np.ndarray] = None) -> dict:
        """This host's slice of global batch ``step`` at ``cursors``
        (pure in the cursors; does not advance them)."""
        if ids is None:
            ids = self._draw(step)
        # global example index per row: cursor + rank within its source
        idx = np.empty(self.global_batch, np.int64)
        for k, name in enumerate(self.names):
            m = ids == k
            idx[m] = cursors[name] + np.arange(int(m.sum()))
        start, stop = self._host_rows
        rows = [self.sources[int(ids[r])].example(int(idx[r]))
                for r in range(start, stop)]
        return {k: np.stack([r[k] for r in rows]) for k in rows[0]}

    def _validate_schema(self) -> None:
        ref = self.sources[0].example(0)
        for s in self.sources[1:]:
            row = s.example(0)
            if set(row) != set(ref):
                raise ValueError(
                    f"source {s.name!r} schema {sorted(row)} != "
                    f"{self.sources[0].name!r} schema {sorted(ref)}")
            for k in ref:
                if (row[k].shape != ref[k].shape
                        or row[k].dtype != ref[k].dtype):
                    raise ValueError(
                        f"source {s.name!r} field {k!r} "
                        f"{row[k].shape}/{row[k].dtype} != "
                        f"{ref[k].shape}/{ref[k].dtype}")

    def template_batch(self) -> dict:
        """The NEXT batch this host would produce, without advancing any
        cursor — for shape/sharding probes (``batch_shardings_for``)."""
        with self._lock:
            return self._build(self._next_step, dict(self._cursors))

    def produce(self, step: int) -> dict:
        """Build batch ``step`` and advance the cursors past it. Steps
        must be consumed in order (the cursor IS the order)."""
        # the fault DECISION (read-check-set on _fault_fired, stall
        # counter) happens under the lock; the stall itself must not —
        # sleeping while holding the lock would block state_at/stats for
        # the whole injected latency
        fired = None
        stall_for = 0.0
        with self._lock:
            if step != self._next_step:
                raise ValueError(
                    f"produce({step}) out of order; next step is "
                    f"{self._next_step}")
            cursors = dict(self._cursors)
            fault = self._fault
            if (fault is not None and not self._fault_fired
                    and step >= fault.step):
                self._fault_fired = True
                fired = fault
                if fault.kind == "stall_source":
                    self._stats["stalls"] += 1
                    stall_for = self.stall_s
        if fired is not None:
            src = self.sources[fired.source or 0]
            if self._event_log is not None:
                self._event_log.emit("stream_fault", kind=fired.kind,
                                     source=src.name, step=int(step))
            if fired.kind == "stall_source":
                log.warning(
                    "stream fault: stalling source %r for %.1fs at step "
                    "%d (latency-only — batches are unchanged)",
                    src.name, stall_for, step)
                self._sleep(stall_for)
            elif hasattr(src, "poison_next"):
                src.poison_next()
            else:
                log.warning(
                    "stream fault corrupt_record targets source %r, which "
                    "has no record layer; verb ignored", src.name)
        t0 = self._clock()
        ids = self._draw(step)
        batch = self._build(step, cursors, ids)
        counts = self._counts(ids)
        with self._lock:
            for k, name in enumerate(self.names):
                self._cursors[name] += int(counts[k])
                self._stats["examples"][name] += int(counts[k])
            self._next_step = step + 1
            self._snapshots[step + 1] = dict(self._cursors)
            for old in [s for s in self._snapshots
                        if s < step + 1 - _KEEP_SNAPSHOTS]:
                del self._snapshots[old]
            self._stats["batches"] += 1
            self._stats["produce_s"] += self._clock() - t0
        return batch

    # ----------------------------------------------------- state & resume

    def state(self) -> dict:
        """The live StreamState (cursors as of the last PRODUCED batch —
        checkpoints should use :meth:`state_at` with the saved step so a
        prefetched-but-untrained batch is not baked into the resume
        point)."""
        with self._lock:
            return self._state_dict(self._next_step, dict(self._cursors))

    def _state_dict(self, next_step: int, cursors: dict) -> dict:
        return {
            "version": STATE_VERSION,
            "next_step": int(next_step),
            "cursors": {n: int(c) for n, c in cursors.items()},
            "schedule": [[int(s), {n: float(w) for n, w in ws.items()}]
                         for s, ws in self._schedule],
            "seed": self.seed,
            "global_batch": self.global_batch,
        }

    def cursors_at(self, step: int) -> Dict[str, int]:
        """Per-source cursors after batches ``0..step-1`` — from the
        snapshot ring when the producer has been there, recomputed from
        the pure draws otherwise (O(step) rng work, restore-time only)."""
        with self._lock:
            snap = self._snapshots.get(step)
            if snap is not None:
                return dict(snap)
        cursors = {n: 0 for n in self.names}
        for s in range(step):
            counts = self._counts(self._draw(s))
            for k, name in enumerate(self.names):
                cursors[name] += int(counts[k])
        return cursors

    def state_at(self, step: int) -> dict:
        """StreamState as of checkpoint step ``step`` (batches
        ``0..step-1`` consumed). This is the Checkpointer extra-item
        provider: with a background producer running ahead of training,
        the LIVE cursors include staged batches the restore must replay —
        the saved state must describe the trained step, not the
        producer's lookahead."""
        return self._state_dict(step, self.cursors_at(step))

    def restore(self, state: dict) -> None:
        """Resume from a saved StreamState (before iteration starts).

        Validates the identity facts (sources, seed, global batch) so a
        stream built from a DIFFERENT spec cannot silently impersonate the
        checkpointed one, then adopts cursors + weight schedule. Works
        across host counts: the state is global (see module docstring).
        """
        if self._started:
            raise RuntimeError("cannot restore a stream already iterating")
        if int(state.get("version", -1)) != STATE_VERSION:
            raise ValueError(
                f"StreamState version {state.get('version')!r} != "
                f"{STATE_VERSION}")
        if sorted(state["cursors"]) != sorted(self.names):
            raise ValueError(
                f"StreamState sources {sorted(state['cursors'])} != this "
                f"stream's {sorted(self.names)} — the mixture spec changed")
        if int(state["seed"]) != self.seed:
            raise ValueError(
                f"StreamState seed {state['seed']} != {self.seed}")
        if int(state["global_batch"]) != self.global_batch:
            raise ValueError(
                f"StreamState global_batch {state['global_batch']} != "
                f"{self.global_batch} — resuming at a different batch "
                "size forks the sequence")
        schedule = [[int(s), self._normalize(dict(ws))]
                    for s, ws in state["schedule"]]
        schedule.sort(key=lambda e: e[0])
        with self._lock:
            self._cursors = {n: int(c) for n, c in state["cursors"].items()}
            self._next_step = int(state["next_step"])
            self._schedule = schedule
            self._snapshots = {self._next_step: dict(self._cursors)}

    def seek(self, step: int) -> None:
        """Fast-forward to ``next_step == step`` by replaying the pure
        draw counts — the LEGACY-checkpoint resume path (a checkpoint
        without a stream item: the spec still determines everything
        except live reweights, which a legacy checkpoint never had)."""
        if self._started:
            raise RuntimeError("cannot seek a stream already iterating")
        cursors = self.cursors_at(step)
        with self._lock:
            self._cursors = cursors
            self._next_step = int(step)
            self._snapshots = {int(step): dict(cursors)}

    # ---------------------------------------------------------- iteration

    @property
    def next_step(self) -> int:
        with self._lock:
            return self._next_step

    def arm_fault(self, plan, *, stall_s: Optional[float] = None) -> None:
        """Install a :class:`dtf_tpu.fault.inject.StreamFaultPlan`."""
        if plan is not None:
            log.info("stream fault armed: %s", plan)
        with self._lock:
            self._fault = plan
            self._fault_fired = False
            if stall_s is not None:
                self.stall_s = float(stall_s)

    def close(self) -> None:
        self._stop.set()

    def __iter__(self) -> Iterator[dict]:
        if self.producer_depth > 0:
            return self._background_iter()
        return self._inline_iter()

    def _inline_iter(self) -> Iterator[dict]:
        self._started = True
        while not self._stop.is_set():
            yield self.produce(self.next_step)

    def _background_iter(self) -> Iterator[dict]:
        """Bounded producer thread: up to ``producer_depth`` batches
        staged; a full queue blocks the PRODUCER (bounded host memory), an
        empty one blocks the CONSUMER (that wait is the trainer's
        ``data_wait`` span — backpressure made visible, never fatal)."""
        self._started = True
        q: queue.Queue = queue.Queue(maxsize=self.producer_depth)
        stop = self._stop

        def run():
            try:
                while not stop.is_set():
                    batch = self.produce(self.next_step)
                    while not stop.is_set():
                        waited = 0.0
                        try:
                            t0 = self._clock()
                            q.put(batch, timeout=0.2)
                            waited = self._clock() - t0
                            break
                        except queue.Full:
                            waited = 0.2
                        finally:
                            with self._lock:
                                self._stats["producer_blocked_s"] += waited
            except BaseException as e:  # noqa: BLE001 — surfaced below:
                # a producer death must raise in the CONSUMER, not vanish
                # in a daemon thread
                q.put(e)

        thread = threading.Thread(target=run, daemon=True,
                                  name="dtf-stream-producer")
        thread.start()
        try:
            while True:
                t0 = self._clock()
                try:
                    item = q.get(timeout=0.2)
                except queue.Empty:
                    with self._lock:
                        self._stats["consumer_wait_s"] += (
                            self._clock() - t0)
                    if stop.is_set():
                        return      # close() ends the stream like the
                    continue        # inline iterator does, never hangs
                with self._lock:
                    self._stats["consumer_wait_s"] += self._clock() - t0
                    self._stats["queue_depth_max"] = max(
                        self._stats["queue_depth_max"], q.qsize() + 1)
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            while True:      # unblock a producer stuck on a full queue
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            thread.join(timeout=5.0)

    # -------------------------------------------------------------- stats

    def stats(self) -> dict:
        """Per-source throughput / realized-fraction / queue-depth facts
        for the RunReport (host counters only — zero device work)."""
        with self._lock:
            total = sum(self._stats["examples"].values())
            target = self._weights_at(max(self._next_step - 1, 0))
            per_source = {
                n: {
                    "examples": self._stats["examples"][n],
                    "realized_frac": round(
                        self._stats["examples"][n] / total, 6)
                    if total else 0.0,
                    "target_frac": round(float(target[k]), 6),
                    "cursor": self._cursors[n],
                }
                for k, n in enumerate(self.names)
            }
            produce_s = self._stats["produce_s"]
            return {
                "batches": self._stats["batches"],
                "next_step": self._next_step,
                "global_batch": self.global_batch,
                "per_source": per_source,
                "produce_s": round(produce_s, 3),
                "batches_per_sec": round(
                    self._stats["batches"] / produce_s, 2)
                if produce_s else None,
                "producer_depth": self.producer_depth,
                "producer_blocked_s": round(
                    self._stats["producer_blocked_s"], 3),
                "consumer_wait_s": round(
                    self._stats["consumer_wait_s"], 3),
                "queue_depth_max": self._stats["queue_depth_max"],
                "reweights": len(self._schedule) - 1,
                # ACTUAL CRC-skip events from the sources' read paths
                # (real bit rot and the injected verb alike) — counting
                # at the injection site would miss real damage entirely
                "corrupt_skips": sum(getattr(s, "corrupt_skips", 0)
                                     for s in self.sources),
                "stalls": self._stats["stalls"],
            }
