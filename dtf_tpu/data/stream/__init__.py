"""Streaming data tier: multi-dataset weighted mixture with deterministic
checkpointed resume (ISSUE 15; docs/DATA.md).

The trainer-side input subsystem the online-learning loop needed: several
corpora mixed at requested weights by a counter-based sampler, per-source
integer cursors that ride the Orbax checkpoint as a ``stream`` extra item,
a bounded background producer feeding the device-prefetch double buffer,
and chaos verbs (``stall_source`` / ``corrupt_record``) on the shared
``DTF_FAULT_INJECT`` grammar. Kill the run at any step and the resumed
batch sequence is BYTE-identical to the uninterrupted one — including a
dp8→dp4 shrink, because all stream state is host-count-invariant
(global-batch addressing; per-host cursors are a row slice, not state).

Like ``fault/``, ``tune/`` and ``telemetry/``, this package is **jax-free
at module level** (srclint-fenced): batch assembly is pure host numpy and
must import — and be testable — with no backend present; device placement
belongs to the Trainer.
"""

from dtf_tpu.data.stream.mixture import MIX_SALT, STATE_VERSION, MixtureStream
from dtf_tpu.data.stream.persist import EXTRA_ITEM, StreamCheckpointHook
from dtf_tpu.data.stream.servelog import ServeLogSource
from dtf_tpu.data.stream.sources import TFRecordSource, TokenBinSource
from dtf_tpu.data.stream.spec import (MANIFEST_KEY, build_stream,
                                      parse_stream_spec,
                                      resolve_stream_spec)

__all__ = [
    "MIX_SALT", "STATE_VERSION", "MixtureStream", "EXTRA_ITEM",
    "StreamCheckpointHook", "ServeLogSource", "TFRecordSource",
    "TokenBinSource", "MANIFEST_KEY", "build_stream", "parse_stream_spec",
    "resolve_stream_spec",
]
