"""Stream-spec resolution: ``--stream_spec`` JSON → a built MixtureStream.

The spec is the mixture's IDENTITY — which corpora, at which weights, under
which seed — so it rides the model-config manifest
(:func:`dtf_tpu.checkpoint.save_model_config`) next to the checkpoint: a
resumed run that passes a different spec FAILS instead of silently training
the tail of the run on a different mixture, and a resumed run that passes
none inherits the manifest's (the same authority rule the decode config
uses; ``cli/flags.resolve_decode_config``).

Spec shape (JSON object, inline on the flag or a path to a ``.json`` file)::

    {"sources": [{"name": "web",  "path": "/data/web",  "weight": 7},
                 {"name": "code", "kind": "tfrecord",
                  "pattern": "/data/code/*.tfrecord", "weight": 3}],
     "reweight": [[1000, {"web": 5, "code": 5}]]}

``kind`` defaults to ``tokens`` (a ``.bin`` corpus / dir for
:class:`~dtf_tpu.data.stream.sources.TokenBinSource`); ``tfrecord`` maps to
:class:`~dtf_tpu.data.stream.sources.TFRecordSource` (packed-window records,
``tokens_key`` optional); ``servelog`` mounts a serve-log sink directory
(:class:`~dtf_tpu.data.stream.servelog.ServeLogSource` — ``path`` is the
``serve_gpt --log_sink_dir`` directory; optional filter knobs ``status``,
``min_version``/``max_version``, ``min_tokens``, ``pad_id``). Weights are
relative (normalized by the stream). ``reweight`` entries are applied in
order at their named steps.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

log = logging.getLogger("dtf_tpu")

#: the manifest key the training launchers write and serving ignores.
MANIFEST_KEY = "stream_spec"


def parse_stream_spec(text: str) -> dict:
    """Parse + validate a stream spec (inline JSON or a ``.json`` path)."""
    text = text.strip()
    if not text:
        raise ValueError("empty stream spec")
    if not text.startswith("{"):
        try:
            with open(text) as f:
                text = f.read()
        except OSError as e:
            # ValueError so launchers' flag-error conversion catches a
            # mistyped path like any other bad spec
            raise ValueError(f"stream spec path {text!r}: {e}") from e
    spec = json.loads(text)
    if not isinstance(spec, dict) or not isinstance(
            spec.get("sources"), list) or not spec["sources"]:
        raise ValueError(
            "stream spec must be an object with a non-empty 'sources' list")
    names = []
    for src in spec["sources"]:
        if not isinstance(src, dict) or "name" not in src:
            raise ValueError(f"each source needs a 'name': {src!r}")
        kind = src.get("kind", "tokens")
        if kind not in ("tokens", "tfrecord", "servelog"):
            raise ValueError(
                f"source {src['name']!r}: unknown kind {kind!r} "
                "(tokens | tfrecord | servelog)")
        if kind in ("tokens", "servelog") and "path" not in src:
            raise ValueError(f"source {src['name']!r} needs a 'path'")
        if kind == "tfrecord" and "pattern" not in src:
            raise ValueError(f"source {src['name']!r} needs a 'pattern'")
        if float(src.get("weight", 1.0)) <= 0:
            raise ValueError(
                f"source {src['name']!r}: weight must be > 0")
        names.append(src["name"])
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate source names in spec: {names}")
    for entry in spec.get("reweight", []):
        if (not isinstance(entry, (list, tuple)) or len(entry) != 2
                or not isinstance(entry[1], dict)):
            raise ValueError(
                f"reweight entries are [step, {{name: weight}}]: {entry!r}")
    return spec


def canonical(spec: Optional[dict]) -> Optional[str]:
    """The comparison form: key-sorted JSON (a reordered but identical
    spec is the SAME mixture)."""
    return None if spec is None else json.dumps(spec, sort_keys=True)


def resolve_stream_spec(flag_value: str,
                        manifest: Optional[dict]) -> Optional[dict]:
    """Merge ``--stream_spec`` with the checkpoint manifest's spec.

    Manifest has a spec: it WINS — an explicitly passed spec that differs
    raises (a resumed run cannot silently change its mixture), a matching
    or absent flag follows it. No manifest spec: the flag's spec (or None:
    the launcher keeps its non-stream data path). Raises ValueError —
    launchers convert to their UsageError.
    """
    flag_spec = parse_stream_spec(flag_value) if flag_value else None
    saved = (manifest or {}).get(MANIFEST_KEY)
    if saved is None:
        return flag_spec
    if flag_spec is not None and canonical(flag_spec) != canonical(saved):
        raise ValueError(
            "--stream_spec contradicts the mixture this checkpoint was "
            "training on (model_config.json stream_spec); drop the flag "
            "to resume the recorded mixture — changing it mid-run forks "
            "the data sequence")
    if flag_spec is None:
        log.info("resuming with the manifest's stream_spec (sources: %s)",
                 [s["name"] for s in saved["sources"]])
    return saved


def build_stream(spec: dict, *, global_batch: int, seq_len: int,
                 vocab_size: int = 0, seed: int = 0, host_index: int = 0,
                 host_count: int = 1, mode: str = "clm",
                 producer_depth: int = 2, fault_plan=None):
    """Spec → a ready :class:`~dtf_tpu.data.stream.mixture.MixtureStream`
    (sources built, weights/reweights applied, fault verb armed)."""
    from dtf_tpu.data.stream.mixture import MixtureStream
    from dtf_tpu.data.stream.servelog import ServeLogSource
    from dtf_tpu.data.stream.sources import TFRecordSource, TokenBinSource

    host_view = None
    if host_count > 1:
        # HostView lives in the jax-importing mesh module; single-host
        # builds (every no-backend context) must not pull it in
        from dtf_tpu.core.mesh import HostView

        host_view = HostView(host_index, host_count)

    sources, weights = [], {}
    for salt, src in enumerate(spec["sources"]):
        name = src["name"]
        if src.get("kind", "tokens") == "tfrecord":
            sources.append(TFRecordSource(
                src["pattern"], seq_len,
                tokens_key=src.get("tokens_key", "tokens"),
                seed=seed + salt, name=name))
        elif src.get("kind", "tokens") == "servelog":
            mx = src.get("max_version")
            mn = src.get("min_version")
            sources.append(ServeLogSource(
                src["path"], seq_len, seed=seed + salt, name=name,
                status=src.get("status", "done"),
                min_version=None if mn is None else int(mn),
                max_version=None if mx is None else int(mx),
                min_tokens=int(src.get("min_tokens", 0)),
                pad_id=int(src.get("pad_id", 0))))
        else:
            path = src["path"]
            if os.path.isdir(path) or path.endswith(".bin"):
                sources.append(TokenBinSource(
                    path, seq_len, mode=mode, vocab_size=vocab_size,
                    seed=seed, salt=salt, name=name))
            else:
                raise ValueError(
                    f"source {name!r}: {path!r} is neither a .bin file "
                    "nor a directory holding train.bin")
        weights[name] = float(src.get("weight", 1.0))
    stream = MixtureStream(
        sources, weights, global_batch, seed=seed, host_view=host_view,
        producer_depth=producer_depth)
    for step, ws in spec.get("reweight", []):
        stream.reweight(int(step), {n: float(w) for n, w in ws.items()})
    if fault_plan is not None:
        stream.arm_fault(fault_plan)
    return stream
