"""Host-plane file IO choke points — the atomic-write discipline's one
sanctioned constructor (the ``ring_perm`` idiom applied to file writes).

Every host-plane file that another process or thread READS while this one
writes it — publish manifests, heartbeats, TELEMETRY/DEVICE_PROFILE merge
artifacts, controller/postmortem jsonl — must be written through this
module. The host soundness pass (``dtf_tpu/analysis/host.py``) fences the
jax-free control plane for exactly that: a raw ``open(path, "w")`` or bare
``os.rename``/``os.replace`` anywhere else is a ``non-atomic-publish``
finding, because a reader racing a raw write sees a torn file (the class
of bug publish.py's manifest contract and the controller's torn-heartbeat
guard exist to prevent).

Two primitives, matching the two shapes host files take:

- :func:`atomic_replace` — whole-file replace via unique tmp +
  ``os.replace``: readers observe either the complete old bytes or the
  complete new bytes, never a prefix. The tmp name is pid-suffixed so
  concurrent writers (per-host heartbeats under one logdir) never tread
  on each other's staging file.
- :func:`append_line` — single-writer line append (jsonl). One short
  line per call: a sub-``PIPE_BUF`` append from the one owning process
  lands contiguously on POSIX, and readers tolerate a torn TAIL line by
  construction (``fault/controller.read_heartbeat``'s guard; a jsonl
  parser skips the last partial line). Multi-writer jsonl is NOT
  supported — each file has one owning process.

Stdlib-only on purpose: ``_dtf_artifact.py``'s parents must never import
the ``dtf_tpu`` package (a package import pulls jax, which can hang
against a dead axon tunnel), so they load this file directly via
``importlib`` file-location instead of the package path.
"""

from __future__ import annotations

import os
from typing import Union


def atomic_replace(path: str, data: Union[str, bytes]) -> None:
    """Write ``data`` to ``path`` atomically (unique tmp + ``os.replace``).

    A reader opening ``path`` at any moment sees a complete file — the
    previous content or the new content, never a partial write. A crash
    mid-write leaves the target untouched (the stale tmp is garbage a
    later successful replace of the same path simply ignores).
    """
    path = os.fspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    mode = "wb" if isinstance(data, bytes) else "w"
    try:
        with open(tmp, mode) as f:
            f.write(data)
        os.replace(tmp, path)       # THE commit point — atomic
    except BaseException:
        # never leave the staging file behind on a failed commit: an
        # orphan tmp next to a manifest reads as a crashed publish
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def append_line(path: str, line: str) -> None:
    """Append one newline-terminated line to a single-writer jsonl file.

    ``line`` must not itself contain newlines (one record per line is the
    jsonl contract readers rely on to skip a torn tail).
    """
    path = os.fspath(path)
    if "\n" in line:
        raise ValueError("append_line takes ONE record (no embedded "
                         "newlines) — the jsonl torn-tail guard depends "
                         "on one-record-per-line")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a") as f:
        f.write(line + "\n")


__all__ = ["atomic_replace", "append_line"]
