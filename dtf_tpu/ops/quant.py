"""Low-precision matmul tier: symmetric per-channel int8 / fp8-e4m3
quantization with bf16 master weights (ISSUE 17; docs/TUNING.md).

This generalizes the ``_kv_quant`` idiom the serving KV cache shipped in
PR 6 (``models/gpt.py``: amax over the contracted axis → one f32 scale
per channel, epsilon floor so all-zero rows round-trip exactly) into the
one quantization module every consumer shares:

- :func:`quantize_channel` / :func:`dequantize` — the (values, scale)
  pair. int8 stores ``clip(round(a/s), -127, 127)``; fp8 stores
  ``(a/s)`` converted to e4m3 with the scale mapping each channel's amax
  to the e4m3 max (±448), so the format's 3 mantissa bits spend their
  dynamic range where the data lives.
- :func:`quantized_matmul` — the non-ring ``tp_dense`` compute path:
  int8×int8 with int32 accumulation (the MXU-native product; XLA's CPU
  emitter supports the same ``preferred_element_type`` contract, which
  is what makes this tier provable on the 8-device sim), or fp8 values
  upcast to f32 for a bf16-accumulated product. The ``custom_vjp``
  backward computes BOTH gradients against the full-precision operands
  (master-weight training: quantization error perturbs the forward only;
  the round/clip never zeroes a gradient).
- :func:`resolve_precision` — the tuner seam. ``""`` is bf16 (status
  quo, no store read); ``"auto"`` asks ``dtf_tpu.tune`` for the banked
  per-(site, shape) winner (quality bound enforced at selection time —
  ``search.select_precision_winner``); an explicit ``"int8"``/``"fp8"``
  wins but warns once when it overrides a measured winner (the same
  ``note_override`` contract as block shapes and spec_k).

fp8 is feature-gated through ``_jax_compat.fp8_e4m3_dtype()``: on a jax
without the dtype, fp8 demotes to bf16 with one warning rather than
crashing a launcher.

The communicated-operand ring twins live in
``ops/collective_matmul.py`` (``ag_matmul_quant`` / ``matmul_rs_quant``
— dequant-after-ppermute, ~2x fewer ring bytes); ``core/comms.tp_dense``
is the single dispatch point that routes between them. Quality bounds
are pinned by tests/test_quant.py and banked per shape by
``scripts/bench_quant.py`` rows.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from dtf_tpu import _jax_compat

#: the precision vocabulary tp_dense/TpDense accept. "" = bf16 with no
#: tuner consultation (the pre-ISSUE-17 behavior, byte for byte);
#: "auto" = the kernel-tune resolver decides per (site, shape).
PRECISIONS = ("", "auto", "bf16", "int8", "fp8")

#: e4m3 dynamic range (+/-448): per-channel scales map amax here.
FP8_E4M3_MAX = 448.0
#: amax floor — an all-zero channel quantizes to exact zeros and
#: dequantizes back bitwise (the _kv_quant contract).
_SCALE_EPS = 1e-6


def validate_precision(precision: str, *, what: str = "precision") -> str:
    if precision not in PRECISIONS:
        raise ValueError(
            f"{what}={precision!r} must be one of {PRECISIONS} "
            "('' = bf16, 'auto' = kernel-tune winner; docs/TUNING.md)")
    return precision


def fp8_supported() -> bool:
    return _jax_compat.fp8_e4m3_dtype() is not None


def quantize_channel(a: jax.Array, *, axis: int = -1,
                     dtype: str = "int8"):
    """Symmetric per-channel quantization over ``axis``.

    Returns ``(q, scale)`` with ``scale`` keeping ``axis`` as size 1 so
    ``dequantize`` is a plain broadcast multiply. ``dtype``: "int8"
    (round-to-nearest, clip to +/-127) or "fp8" (convert to e4m3 after
    scaling amax to +/-448)."""
    amax = jnp.max(jnp.abs(a.astype(jnp.float32)), axis=axis,
                   keepdims=True)
    if dtype == "int8":
        scale = jnp.maximum(amax, _SCALE_EPS) / 127.0
        q = jnp.clip(jnp.round(a.astype(jnp.float32) / scale),
                     -127, 127).astype(jnp.int8)
        return q, scale
    if dtype == "fp8":
        f8 = _jax_compat.fp8_e4m3_dtype()
        if f8 is None:
            raise ValueError(
                "fp8 requested but this jax has no float8_e4m3fn — "
                "resolve_precision demotes to bf16; an explicit fp8 "
                "caller must gate on quant.fp8_supported()")
        scale = jnp.maximum(amax, _SCALE_EPS) / FP8_E4M3_MAX
        q = (a.astype(jnp.float32) / scale).astype(f8)
        return q, scale
    raise ValueError(f"quantize_channel dtype={dtype!r} must be "
                     "'int8' or 'fp8'")


def dequantize(q: jax.Array, scale: jax.Array, dtype=jnp.float32
               ) -> jax.Array:
    """Broadcast-multiply back to ``dtype`` (the read side of the
    (values, scale) pair)."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def rel_err(got: jax.Array, want: jax.Array) -> jax.Array:
    """Frobenius relative error — the quality metric the sweep rows
    bank and ``search.PRECISION_REL_ERR_CEILING`` bounds."""
    w = jnp.asarray(want, jnp.float32)
    g = jnp.asarray(got, jnp.float32)
    denom = jnp.maximum(jnp.linalg.norm(w.reshape(-1)), _SCALE_EPS)
    return jnp.linalg.norm((g - w).reshape(-1)) / denom


def _qmm_impl(precision: str, x: jax.Array, w: jax.Array) -> jax.Array:
    """``x @ w`` with both operands quantized along the contraction.

    x [..., t, d] scales per token row, w [d, f] per output column, so
    ``y ≈ (qx @ qw) * sx * sw`` is exact per-channel rescaling."""
    out_dtype = jnp.result_type(x.dtype, w.dtype)
    qx, sx = quantize_channel(x, axis=-1, dtype=precision)
    qw, sw = quantize_channel(w, axis=0, dtype=precision)
    if precision == "int8":
        acc = jnp.einsum("...td,df->...tf", qx, qw,
                         preferred_element_type=jnp.int32)
        acc = acc.astype(jnp.float32)
    else:
        # fp8: values are already rounded to e4m3 — upcast and take the
        # wide-accumulation product (XLA fuses convert∘dot into the fp8
        # MXU path on hardware that has one; the sim just upcasts).
        acc = jnp.einsum("...td,df->...tf", qx.astype(jnp.float32),
                         qw.astype(jnp.float32),
                         preferred_element_type=jnp.float32)
    return (acc * sx * sw).astype(out_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _quantized_matmul(precision: str, x: jax.Array, w: jax.Array
                      ) -> jax.Array:
    return _qmm_impl(precision, x, w)


def _qmm_fwd(precision, x, w):
    return _qmm_impl(precision, x, w), (x, w)


def _qmm_bwd(precision, res, dy):
    # master-weight rule: gradients flow against the FULL-precision
    # operands — the quantization perturbs the forward value only, so
    # dx/dw match the plain einsum's gradients bitwise.
    x, w = res
    dx = jnp.einsum("...tf,df->...td", dy, w).astype(x.dtype)
    dw = jnp.einsum("...td,...tf->df", x, dy).astype(w.dtype)
    return dx, dw


_quantized_matmul.defvjp(_qmm_fwd, _qmm_bwd)


def quantized_matmul(x: jax.Array, w: jax.Array, *,
                     precision: str) -> jax.Array:
    """The quantized ``tp_dense`` compute path (non-ring dispatch)."""
    if precision not in ("int8", "fp8"):
        raise ValueError(
            f"quantized_matmul precision={precision!r} must be 'int8' "
            "or 'fp8' (bf16 callers take the plain einsum)")
    return _quantized_matmul(precision, x, w)


@functools.lru_cache(maxsize=64)
def _warn_fp8_demoted() -> None:
    try:
        from absl import logging as absl_logging

        absl_logging.warning(
            "fp8 matmul precision requested but this jax has no "
            "float8_e4m3fn dtype — demoting to bf16 (feature gate: "
            "dtf_tpu._jax_compat.fp8_e4m3_dtype)")
    except Exception:  # pragma: no cover
        pass


def resolve_precision(precision: str, *, parallel: str, d_in: int,
                      d_out: int, dtype: str = "bfloat16",
                      n_devices: int = 1,
                      backend: str | None = None) -> str:
    """Resolve a ``tp_dense`` precision request to a concrete path.

    ``""``/``"bf16"`` short-circuit (no store read on the default
    path); ``"auto"`` returns the banked ``matmul_precision`` winner at
    the nearest (site, shape) — bf16 when nothing is banked; an
    explicit ``"int8"``/``"fp8"`` wins but ``note_override`` warns once
    when it disagrees with a MEASURED winner. fp8 demotes to bf16 with
    one warning where the jax has no e4m3 dtype."""
    validate_precision(precision)
    if precision in ("", "bf16"):
        return "bf16"
    from dtf_tpu.tune import resolver as tune_resolver

    plan = tune_resolver.matmul_precision_plan(
        parallel=parallel, d_in=int(d_in), d_out=int(d_out), dtype=dtype,
        n_devices=int(n_devices), backend=backend)
    if precision == "auto":
        resolved = plan.precision
    else:
        resolved = precision
        tune_resolver.note_override(
            "matmul_precision", f"{parallel}:{d_in}x{d_out}", precision,
            plan.precision, source=plan.source, measured=plan.measured)
    if resolved == "fp8" and not fp8_supported():
        _warn_fp8_demoted()
        return "bf16"
    return resolved
