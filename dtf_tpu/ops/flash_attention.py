"""Pallas TPU flash attention — fused O(T) -memory attention kernels.

The reference has no attention at all (SURVEY.md §5.7: nothing in
`zjj2wry/distributed-tensorflow` scales sequence length; its models are MNIST
softmax / ResNet / fixed-length BERT). This module is where the TPU-native
framework goes past capability parity: a first-party fused kernel for the
hottest op in the transformer stack, built on Pallas/Mosaic so the MXU sees
[block_q, d] x [d, block_k] matmuls and the softmax statistics never leave
VMEM.

Design (flash-attention-2 style, adapted to the TPU grid model):
- forward: grid (batch*heads, num_q_blocks, num_k_blocks); the k axis is the
  innermost ("arbitrary" = sequential) grid dim, with running max / sum /
  accumulator kept in VMEM scratch that persists across k iterations. Output
  and the logsumexp residual are written on the last k iteration.
- backward: the standard two-kernel split — dq loops k-blocks inside a
  q-block program; dk/dv loop q-blocks inside a k-block program — using the
  saved logsumexp plus delta = rowsum(dO * O) so p is recomputed, never
  materialised at [T, T].
- unaligned T is handled by zero-padding in the wrapper and masking inside
  the kernel (keys beyond t_k get -inf scores; padded query rows are forced
  to p = 0 in the backward so they cannot pollute dk/dv). head_dim is passed
  through as-is — Mosaic handles non-128 lane counts, at some layout cost.

Softmax statistics are float32 regardless of input dtype; p is cast back to
the value dtype for the MXU contraction (the usual bf16 flash recipe).

Runs compiled on TPU (Mosaic) and under ``interpret=True`` on CPU for the
test suite (tests/test_flash_attention.py).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 512x1024 blocks, picked by the on-chip block-shape sweep (ATTN_BENCH.json
# block_sweep, v5e, seq 8k causal fwd): 512x1024 ran 1.61 ms vs 512x512's
# 4.44/5.76 ms, 1024x1024's 2.98 ms and 512x2048's 2.74 ms — with the bwd
# also fastest (9.05 vs 13.4 ms). History: 128x128 was grid-overhead-bound
# (~10 TF/s flat, r3); 512x512 fixed that (42-62 TF/s); doubling only the
# k-extent halves the grid's inner trip count again and keeps the f32
# score tile at [512,1024] = 2 MB, k/v residents 2x256 KB — far under the
# ~16 MB VMEM budget. Since the kernel-tune cache landed these are the
# LAST-RESORT fallback only: block args left at 0 resolve through
# dtf_tpu.tune.resolver (the banked per-shape winners in
# KERNEL_TUNE.json, seeded from this very sweep — docs/TUNING.md), and
# callers can still pin per-shape explicitly.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
_NEG_INF = float("-inf")
_STAT_LANES = 128  # scratch stat arrays are [block_q, 128] (TPU lane width)


def _compiler_params(dims: tuple[str, ...]):
    # pre-0.5 jax spells it TPUCompilerParams; same dataclass either way.
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    fields = {f.name for f in dataclasses.fields(cls)}
    if "dimension_semantics" in fields:
        return cls(dimension_semantics=dims)
    return cls()


def _positions(i, j, block_q, block_k):
    q_pos = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return q_pos, k_pos


def _score_mask(s, i, j, *, causal, block_q, block_k, t_k, window=0):
    """-inf out invalid (padded-key / future-key / out-of-window) scores."""
    need_k_mask = (t_k % block_k) != 0
    if not (causal or need_k_mask):
        return s
    q_pos, k_pos = _positions(i, j, block_q, block_k)
    mask = k_pos < t_k
    if causal:
        mask = jnp.logical_and(mask, q_pos >= k_pos)
        if window:
            # sliding window: query t sees keys in (t-window, t]
            mask = jnp.logical_and(mask, q_pos - k_pos < window)
    return jnp.where(mask, s, _NEG_INF)


def _block_live(i, j, *, causal, window, block_q, block_k):
    """Does (q-block i, k-block j) contain ANY unmasked position? The grid
    skip condition: below-diagonal blocks for causal, plus blocks entirely
    older than the window — this is what makes windowed attention O(T·W)
    instead of O(T²/2)."""
    live = (j * block_k <= i * block_q + block_q - 1) if causal else (j >= 0)
    if causal and window:
        # newest key in block j must be inside the oldest query's window:
        # (j+1)*bk - 1 > i*bq - window  ⇔  some (qp, kp) has qp-kp < window
        live = jnp.logical_and(
            live, (j + 1) * block_k - 1 > i * block_q - window)
    return live


def _kv_sticky_map(*, causal, window, block_q, block_k, num_k):
    """k/v BlockSpec index map for grids iterating (b, i, j): on DEAD
    (i, j) tiles — skipped by ``pl.when(_block_live)`` — point the DMA at
    the q-block's DIAGONAL k-block instead of the dead j. Mosaic elides
    refetches when consecutive steps map to the same block, so dead tiles
    stop burning HBM bandwidth on k/v copies nobody reads (the bundled
    jax flash kernel's trick). The diagonal block is always live: it
    contains a diff==0 position, in-window for any window >= 1."""
    if not causal:
        return lambda b, i, j: (b, j, 0)

    def imap(b, i, j):
        diag = jnp.minimum((i * block_q + block_q - 1) // block_k,
                           num_k - 1)
        live = _block_live(i, j, causal=causal, window=window,
                           block_q=block_q, block_k=block_k)
        return b, jax.lax.select(live, j, diag), 0

    return imap


def _q_sticky_map(*, causal, window, block_q, block_k, num_q, rank4=False):
    """q/do/lse/delta index map for the dkv grid (b, j, i): dead tiles
    point at k-block j's diagonal q-block (ceil((j·bk - bq + 1)/bq),
    computed via the floor identity). Same DMA-elision rationale as
    :func:`_kv_sticky_map`."""
    if not causal:
        if rank4:
            return lambda b, j, i: (b, i, 0, 0)
        return lambda b, j, i: (b, i, 0)

    def imap(b, j, i):
        diag = jnp.minimum((j * block_k) // block_q, num_q - 1)
        live = _block_live(i, j, causal=causal, window=window,
                           block_q=block_q, block_k=block_k)
        i_eff = jax.lax.select(live, i, diag)
        if rank4:
            return b, i_eff, 0, 0
        return b, i_eff, 0

    return imap


def _zero_padded_q_rows(p, i, *, block_q, t_q):
    """Zero p on padded query rows (their lse is -inf ⇒ exp overflows)."""
    if (t_q % block_q) == 0:
        return p
    q_pos = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, p.shape[1]), 0)
    return jnp.where(q_pos < t_q, p, 0.0)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, sm_scale, causal, window,
                block_q, block_k, num_k, t_q, t_k, has_mask):
    mb_ref = rest[0] if has_mask else None
    o_ref, lse_ref, m_scr, l_scr, acc_scr = rest[1:] if has_mask else rest
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, m_scr.dtype)
        l_scr[...] = jnp.zeros(l_scr.shape, l_scr.dtype)
        acc_scr[...] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    run = _block_live(i, j, causal=causal, window=window,
                      block_q=block_q, block_k=block_k)

    @pl.when(run)
    def _block():
        q, k = q_ref[0], k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        s = _score_mask(s, i, j, causal=causal, block_q=block_q,
                        block_k=block_k, t_k=t_k, window=window)
        if has_mask:
            # additive key-padding bias row (0 valid / -inf padded): the
            # existing -inf machinery (running max, dead-row guards) then
            # handles masked keys identically to causal-masked ones.
            s = s + mb_ref[0, 0][None, :]
        m_prev = m_scr[:, 0:1]
        l_prev = l_scr[:, 0:1]
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Fully-masked-so-far rows keep m == -inf; subtracting a 0 stand-in
        # keeps exp() finite (p rows come out 0, alpha comes out 0).
        m_safe = jnp.where(m_next == _NEG_INF, 0.0, m_next)
        alpha = jnp.exp(m_prev - m_safe)
        p = jnp.exp(s - m_safe)
        l_next = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_next, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_next, l_scr.shape)

    @pl.when(j == num_k - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        m = m_scr[:, 0:1]
        lse = jnp.where(l == 0.0, _NEG_INF, m + jnp.log(l_safe))
        # lse is [bh, num_q, 1, block_q]: the num_q axis is blocked by i so
        # each q-block program owns its own output window (the q grid dim is
        # "parallel" — a shared window revisited across i would be UB on
        # megacore), and the trailing (1, block_q) block dims are full-size
        # (Mosaic requires trailing block dims (8,128)-divisible or full).
        lse_ref[0, 0, 0, :] = lse[:, 0]


def _fwd_kernel_hfold(q_ref, k_ref, v_ref, *rest, sm_scale, causal, window,
                      block_q, block_k, num_k, t_q, t_k, has_mask):
    """Head-folded forward: the grid's bh dim advances ``block_h`` heads per
    step, so one grid step runs block_h batched [bq,d]x[d,bk] MXU
    contractions back-to-back — amortizing the fixed per-step overhead
    (PERF.md §3 measured ~1 us/step vs sub-us of matmul work at d=128) by
    the fold factor. Separate from :func:`_fwd_kernel` on purpose: the 2-D
    kernel is the on-chip-proven default; this one is opt-in
    (``block_h > 1``) until the block sweep measures it.

    Same math as the 2-D kernel with a leading head axis [h, ...]: the
    positional/causal masks are head-independent and numpy-broadcast
    against [h, bq, bk] scores; softmax stats carry an extra leading dim.
    """
    mb_ref = rest[0] if has_mask else None
    o_ref, lse_ref, m_scr, l_scr, acc_scr = rest[1:] if has_mask else rest
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, m_scr.dtype)
        l_scr[...] = jnp.zeros(l_scr.shape, l_scr.dtype)
        acc_scr[...] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    run = _block_live(i, j, causal=causal, window=window,
                      block_q=block_q, block_k=block_k)

    @pl.when(run)
    def _block():
        q, k = q_ref[...], k_ref[...]            # [h, bq, d], [h, bk, d]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * sm_scale   # [h, bq, bk]
        s = _score_mask(s, i, j, causal=causal, block_q=block_q,
                        block_k=block_k, t_k=t_k, window=window)
        if has_mask:
            # every folded head shares the batch row (block_h | heads is
            # enforced by the wrapper)
            s = s + mb_ref[0, 0][None, None, :]
        m_prev = m_scr[:, :, 0:1]                # [h, bq, 1]
        l_prev = l_scr[:, :, 0:1]
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        m_safe = jnp.where(m_next == _NEG_INF, 0.0, m_next)
        alpha = jnp.exp(m_prev - m_safe)
        p = jnp.exp(s - m_safe)
        l_next = alpha * l_prev + jnp.sum(p, axis=2, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[...], (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)  # [h, bq, d]
        m_scr[...] = jnp.broadcast_to(m_next, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_next, l_scr.shape)

    @pl.when(j == num_k - 1)
    def _finalize():
        l = l_scr[:, :, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        m = m_scr[:, :, 0:1]
        lse = jnp.where(l == 0.0, _NEG_INF, m + jnp.log(l_safe))
        lse_ref[:, 0, 0, :] = lse[:, :, 0]


def _mask_bias(kv_mask, b, t_k, block_k):
    """[b, 1, t_k_padded] f32 additive bias: 0 valid, -inf padded key.

    PER-BATCH, not per-(batch*head): every head reads the same row, so the
    kernels' index maps divide the bh grid index by the head count instead
    of materializing h identical copies (which the custom_vjp residuals
    would otherwise keep alive through the backward). Shaped with a size-1
    middle axis so the (1, 1, block_k) BlockSpec's trailing dims are
    (1, block_k) — the 1 is full-size, keeping the block Mosaic-legal
    (same trick as the lse residual layout)."""
    bias = jnp.where(kv_mask, 0.0, _NEG_INF).astype(jnp.float32)
    return _pad(bias.reshape(b, 1, t_k), block_k, axis=2)


def _fwd(q, k, v, mask_bias, *, sm_scale, causal, window, block_q, block_k,
         interpret, block_h=1):
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    num_q = pl.cdiv(t_q, block_q)
    num_k = pl.cdiv(t_k, block_k)
    qp = _pad(q, block_q, axis=1)
    kp = _pad(k, block_k, axis=1)
    vp = _pad(v, block_k, axis=1)
    has_mask = mask_bias is not None

    kern = functools.partial(
        _fwd_kernel_hfold if block_h > 1 else _fwd_kernel,
        sm_scale=sm_scale, causal=causal, window=window,
        block_q=block_q, block_k=block_k, num_k=num_k, t_q=t_q, t_k=t_k,
        has_mask=has_mask)
    kv_map = _kv_sticky_map(causal=causal, window=window, block_q=block_q,
                            block_k=block_k, num_k=num_k)
    in_specs = [
        pl.BlockSpec((block_h, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((block_h, block_k, d), kv_map),
        pl.BlockSpec((block_h, block_k, d), kv_map),
    ]
    inputs = [qp, kp, vp]
    if has_mask:
        heads = bh // mask_bias.shape[0]  # bias rows are per-batch
        # folded index b covers heads [b*block_h, (b+1)*block_h) — one
        # batch row serves them all (wrapper enforces block_h | heads)
        in_specs.append(
            pl.BlockSpec((1, 1, block_k),
                         lambda b, i, j: (b * block_h // heads, 0,
                                          kv_map(b, i, j)[1])))
        inputs.append(mask_bias)
    out, lse = pl.pallas_call(
        kern,
        grid=(bh // block_h, num_q, num_k),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((block_h, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((block_h, 1, 1, block_q),
                         lambda b, i, j: (b, i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qp.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, num_q, 1, block_q), jnp.float32),
        ],
        scratch_shapes=(
            [pltpu.VMEM((block_h, block_q, _STAT_LANES), jnp.float32),
             pltpu.VMEM((block_h, block_q, _STAT_LANES), jnp.float32),
             pltpu.VMEM((block_h, block_q, d), jnp.float32)]
            if block_h > 1 else
            [pltpu.VMEM((block_q, _STAT_LANES), jnp.float32),
             pltpu.VMEM((block_q, _STAT_LANES), jnp.float32),
             pltpu.VMEM((block_q, d), jnp.float32)]),
        compiler_params=_compiler_params(("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*inputs)
    return out[:, :t_q], lse.reshape(bh, num_q * block_q)[:, :t_q]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
               sm_scale, causal, window, block_q, block_k, num_k, t_q, t_k,
               has_mask):
    mb_ref = rest[0] if has_mask else None
    dq_ref, dq_scr = rest[1:] if has_mask else rest
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, dq_scr.dtype)

    run = _block_live(i, j, causal=causal, window=window,
                      block_q=block_q, block_k=block_k)

    @pl.when(run)
    def _block():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0, 0, :][:, None]
        delta = delta_ref[0, 0, 0, :][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        s = _score_mask(s, i, j, causal=causal, block_q=block_q,
                        block_k=block_k, t_k=t_k, window=window)
        if has_mask:
            s = s + mb_ref[0, 0][None, :]
        # a fully-masked VALID q row has lse == -inf; exp(s - lse) would be
        # exp(-inf + inf) = nan — force p = 0 there (output was 0 too).
        p = jnp.where(jnp.isneginf(lse), 0.0, jnp.exp(s - lse))
        p = _zero_padded_q_rows(p, i, block_q=block_q, t_q=t_q)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == num_k - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                sm_scale, causal, window, block_q, block_k, num_q, t_q, t_k,
                has_mask):
    mb_ref = rest[0] if has_mask else None
    dk_ref, dv_ref, dk_scr, dv_scr = rest[1:] if has_mask else rest
    j, i = pl.program_id(1), pl.program_id(2)  # k-block outer, q-block inner

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, dk_scr.dtype)
        dv_scr[...] = jnp.zeros(dv_scr.shape, dv_scr.dtype)

    # same tile-liveness predicate as fwd/dq (it is symmetric in the tile)
    run = _block_live(i, j, causal=causal, window=window,
                      block_q=block_q, block_k=block_k)

    @pl.when(run)
    def _block():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0, 0, :][:, None]
        delta = delta_ref[0, 0, 0, :][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        s = _score_mask(s, i, j, causal=causal, block_q=block_q,
                        block_k=block_k, t_k=t_k, window=window)
        if has_mask:
            s = s + mb_ref[0, 0][None, :]
        p = jnp.where(jnp.isneginf(lse), 0.0, jnp.exp(s - lse))
        p = _zero_padded_q_rows(p, i, block_q=block_q, t_q=t_q)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd(q, k, v, mask_bias, out, lse, do, *, sm_scale, causal, window,
         block_q, block_k, interpret):
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    num_q = pl.cdiv(t_q, block_q)
    num_k = pl.cdiv(t_k, block_k)
    has_mask = mask_bias is not None
    # delta = rowsum(dO * O): cheap elementwise+reduce, XLA fuses it fine.
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    qp, dop = _pad(q, block_q, 1), _pad(do, block_q, 1)
    kp, vp = _pad(k, block_k, 1), _pad(v, block_k, 1)
    lsep = _pad(lse, block_q, 1).reshape(bh, num_q, 1, block_q)
    deltap = _pad(delta, block_q, 1).reshape(bh, num_q, 1, block_q)
    if has_mask:
        # The residual bias arrived padded to the FORWARD block_k; when
        # the bwd runs its own block_k the k-grid may cover more columns
        # than that pad — slice back to t_k and re-pad for THIS grid, or
        # the last mask block reads out of bounds.
        mask_bias = _pad(mask_bias[:, :, :t_k], block_k, 2)
    mask_in = [mask_bias] if has_mask else []
    heads = bh // mask_bias.shape[0] if has_mask else 1  # bias is per-batch

    def mask_spec(index_map):
        return ([pl.BlockSpec((1, 1, block_k), index_map)]
                if has_mask else [])

    kv_map = _kv_sticky_map(causal=causal, window=window, block_q=block_q,
                            block_k=block_k, num_k=num_k)
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, sm_scale=sm_scale, causal=causal, window=window,
            block_q=block_q, block_k=block_k, num_k=num_k, t_q=t_q, t_k=t_k,
            has_mask=has_mask),
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, 1, block_q), lambda b, i, j: (b, i, 0, 0)),
            pl.BlockSpec((1, 1, 1, block_q), lambda b, i, j: (b, i, 0, 0)),
        ] + mask_spec(lambda b, i, j: (b // heads, 0, kv_map(b, i, j)[1])),
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap, *mask_in)

    q_map = _q_sticky_map(causal=causal, window=window, block_q=block_q,
                          block_k=block_k, num_q=num_q)
    q_map4 = _q_sticky_map(causal=causal, window=window, block_q=block_q,
                           block_k=block_k, num_q=num_q, rank4=True)
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, sm_scale=sm_scale, causal=causal, window=window,
            block_q=block_q, block_k=block_k, num_q=num_q, t_q=t_q, t_k=t_k,
            has_mask=has_mask),
        grid=(bh, num_k, num_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, 1, 1, block_q), q_map4),
            pl.BlockSpec((1, 1, 1, block_q), q_map4),
        ] + mask_spec(lambda b, j, i: (b // heads, 0, j)),
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(kp.shape, k.dtype),
            jax.ShapeDtypeStruct(vp.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap, *mask_in)
    return dq[:, :t_q], dk[:, :t_k], dv[:, :t_k]


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------


def _pad(x, multiple, axis):
    rem = x.shape[axis] % multiple
    if rem == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, multiple - rem)
    return jnp.pad(x, widths)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11, 12))
def _flash(q, k, v, mask_bias, causal, window, sm_scale, block_q, block_k,
           interpret, block_h, block_q_bwd, block_k_bwd):
    out, _ = _fwd(q, k, v, mask_bias, sm_scale=sm_scale, causal=causal,
                  window=window, block_q=block_q, block_k=block_k,
                  interpret=interpret, block_h=block_h)
    return out


def _flash_fwd(q, k, v, mask_bias, causal, window, sm_scale, block_q,
               block_k, interpret, block_h, block_q_bwd, block_k_bwd):
    out, lse = _fwd(q, k, v, mask_bias, sm_scale=sm_scale, causal=causal,
                    window=window, block_q=block_q, block_k=block_k,
                    interpret=interpret, block_h=block_h)
    return out, (q, k, v, mask_bias, out, lse)


def _flash_bwd(causal, window, sm_scale, block_q, block_k, interpret,
               block_h, block_q_bwd, block_k_bwd, res, do):
    del block_h  # fwd-only lever; the backward keeps the proven 2-D grids
    q, k, v, mask_bias, out, lse = res
    # The backward's two grids stream the OPPOSITE extents from the
    # forward (_dq scans k; _dkv scans q), so the fwd-optimal block shape
    # need not be bwd-optimal — 0 inherits the fwd blocks, the sweep
    # (bench_attention --sweep-blocks bwd rows) picks better ones.
    dq, dk, dv = _bwd(q, k, v, mask_bias, out, lse, do, sm_scale=sm_scale,
                      causal=causal, window=window,
                      block_q=block_q_bwd or block_q,
                      block_k=block_k_bwd or block_k, interpret=interpret)
    dmb = None if mask_bias is None else jnp.zeros_like(mask_bias)
    return dq, dk, dv, dmb


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_sharded(q, k, v, mesh, *, causal: bool = False,
                            window: int = 0,
                            kv_mask: Optional[jax.Array] = None,
                            block_h: int = 0,
                            interpret: bool = False) -> jax.Array:
    """Per-shard flash kernel over a (data, model) mesh: batch/head dims are
    partitioned, seq stays whole per shard. Pallas calls can't be
    GSPMD-partitioned from outside, so the shard_map boundary is where the
    parallelism lives. ``mesh=None`` falls through to the plain kernel.
    Shared by the GPT (causal) and BERT (kv_mask) model paths.

    check_vma=False: pallas_call out_shapes carry no varying-manual-axes
    info, so shard_map's vma checker can't type them.
    """
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        return flash_attention(q, k, v, causal=causal, window=window,
                               kv_mask=kv_mask, block_h=block_h,
                               interpret=interpret)
    if mesh.shape.get("seq", 1) > 1:
        # the in_specs below replicate the sequence dim, so forcing flash
        # on a seq-sharded mesh would silently all-gather T and compute the
        # whole attention redundantly on every seq shard (ADVICE r3) —
        # reject explicitly, mirroring the zigzag+window rejection
        raise ValueError(
            "flash attention keeps the sequence whole per shard; on a mesh "
            f"with seq={mesh.shape['seq']} use attn_impl='ring'/'zigzag' "
            "(full causal) or the halo path (windowed) instead")
    spec = P("data", "model", None, None)
    if kv_mask is None:
        fn = functools.partial(flash_attention, causal=causal, window=window,
                               block_h=block_h, interpret=interpret)
        return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec, check_vma=False)(q, k, v)

    def fn(q, k, v, m):
        return flash_attention(q, k, v, causal=causal, window=window,
                               kv_mask=m, block_h=block_h,
                               interpret=interpret)

    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec, P("data", None)),
        out_specs=spec, check_vma=False)(q, k, v, kv_mask)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False,
                    window: int = 0,
                    kv_mask: Optional[jax.Array] = None,
                    sm_scale: Optional[float] = None,
                    block_q: int = 0,
                    block_k: int = 0,
                    block_h: int = 0,
                    block_q_bwd: int = 0,
                    block_k_bwd: int = 0,
                    interpret: bool = False) -> jax.Array:
    """Fused attention. [B, H, T, D] → [B, H, T, D]; differentiable.

    ``sm_scale`` defaults to ``1/sqrt(head_dim)`` (the *original* head_dim,
    before any internal padding). Unaligned T is padded+masked internally.

    ``kv_mask``: [B, T_k] bool, True = valid key (the BERT/encoder padding
    mask). Rides through the kernels as a precomputed additive -inf bias
    row. A query row whose keys are ALL masked produces output 0 and
    gradient 0 (same contract as ``dense_attention``'s dead-row handling).

    ``window > 0`` (requires ``causal``): sliding-window locality — query t
    attends keys in (t-window, t]. Blocks entirely outside the window are
    SKIPPED at the grid level, so compute is O(T·window) not O(T²/2).

    ``block_h > 1`` (opt-in): fold that many heads into each forward grid
    step — batched MXU contractions amortize the fixed per-step overhead
    (see :func:`_fwd_kernel_hfold`). Must divide ``heads``. Forward only;
    the backward keeps its proven 2-D grids.

    ``block_q_bwd`` / ``block_k_bwd`` (0 = auto): separate block shape
    for the two backward kernels. The backward streams the opposite
    extents from the forward (``_dq`` scans k-blocks, ``_dkv`` scans
    q-blocks), so the fwd-optimal shape is not necessarily bwd-optimal;
    ``bench_attention.py --sweep-blocks`` / ``bench_tune.py`` measure
    the bwd rows on chip.

    Block arguments left at 0 resolve through the kernel-tune cache
    (:mod:`dtf_tpu.tune.resolver` — the banked per-shape on-chip
    winners; docs/TUNING.md), falling back to the module defaults.
    Explicit values always win; an explicit value that differs from a
    MEASURED winner warns once. When the forward blocks are pinned
    explicitly, unset backward blocks keep the old inherit-the-fwd
    contract instead of mixing a tuned bwd with a pinned fwd.
    """
    if q.ndim != 4:
        raise ValueError(f"expected [B, H, T, D], got shape {q.shape}")
    if window < 0 or (window and not causal):
        raise ValueError(
            f"window={window} must be >= 0 and requires causal=True")
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    if not (block_q and block_k and block_h):
        from dtf_tpu.tune import resolver as _tune

        plan = _tune.flash_plan(
            seq=t_q, heads=h, head_dim=d, dtype=jnp.dtype(q.dtype).name,
            causal=causal, window=int(window),
            n_devices=jax.device_count(),
            backend=jax.default_backend())
        for what, explicit, won in (("block_q", block_q, plan.block_q),
                                    ("block_k", block_k, plan.block_k)):
            if explicit:
                _tune.note_override("flash_fwd", what, explicit, won,
                                    source=plan.source,
                                    measured=plan.measured)
        if not (block_q or block_k or block_q_bwd or block_k_bwd):
            # fully-auto forward: the banked backward winner applies;
            # a pinned forward keeps bwd on the inherit contract.
            block_q_bwd, block_k_bwd = plan.block_q_bwd, plan.block_k_bwd
        block_q = block_q or plan.block_q
        block_k = block_k or plan.block_k
        block_h = block_h or plan.block_h
    block_h = block_h or 1
    if block_h < 1 or h % block_h:
        raise ValueError(f"block_h={block_h} must be >= 1 and divide "
                         f"heads={h}")
    scale = float(sm_scale) if sm_scale is not None else d ** -0.5
    block_q = min(block_q, max(t_q, 1))
    block_k = min(block_k, max(t_k, 1))
    qr = q.reshape(b * h, t_q, d)
    kr = k.reshape(b * h, t_k, d)
    vr = v.reshape(b * h, t_k, d)
    mask_bias = None
    if kv_mask is not None:
        if kv_mask.shape != (b, t_k):
            raise ValueError(
                f"kv_mask shape {kv_mask.shape} != (batch, t_k)=({b}, {t_k})")
        mask_bias = _mask_bias(kv_mask, b, t_k, block_k)
    out = _flash(qr, kr, vr, mask_bias, causal, int(window), scale,
                 block_q, block_k, interpret, int(block_h),
                 min(block_q_bwd, max(t_q, 1)),
                 min(block_k_bwd, max(t_k, 1)))
    return out.reshape(b, h, t_q, d)
