"""TPU-first ops: attention (dense + ring), sharded losses, Pallas kernels."""
