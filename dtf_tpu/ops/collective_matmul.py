"""Latency-hiding collective matmul for Megatron TP — the "collective
einsum" pattern (pjit/TPUv4 paper, arxiv 2204.06514; MLPerf TPU-v3 pod
work, arxiv 1909.09756).

The GSPMD baseline for a Megatron TP pair is a BLOCKING all-gather of the
sequence-sharded activations before the column-parallel matmul and a
blocking reduce-scatter after the row-parallel one: MXU idles while ICI
moves bytes, ICI idles while the MXU multiplies. These ops decompose each
(collective, matmul) pair into a ``ppermute`` ring — the idiom already
proven by :func:`dtf_tpu.ops.attention.ring_attention` and the pipeline's
stage boundary — so each ring step's neighbor transfer overlaps the
previous chunk's matmul under XLA's async collective scheduling:

- :func:`ag_matmul`  — all-gather ∘ matmul for the COLUMN-parallel
  in-projection (q/k/v, mlp_in): token chunks ride the ring, each chunk is
  multiplied by the local weight shard on arrival while the next chunk is
  already in flight.
- :func:`matmul_rs`  — matmul ∘ reduce-scatter for the ROW-parallel
  out-projection (attn_out, mlp_out): per-chunk partial products are
  computed while the partial-sum accumulator rides the ring.

Each op carries a ``custom_vjp`` whose backward is the MIRRORED pattern
(d(ag_matmul) needs a matmul_rs for dx; d(matmul_rs) needs an ag_matmul
for dy; both need a gather-on-contract ring for dW), so the overlap
survives autodiff — ``jax.grad`` of the naive composition would fall back
to blocking collectives.

Layout contract (the Megatron sequence-parallel convention): between
projections, activations are token-sharded over ``('seq', axis)`` — the
residual stream never materializes replicated over the TP axis. Per-shard
shapes inside shard_map:

    ag_matmul : x [..., t, d]   w [d, f]  → y [..., n*t, f]
    matmul_rs : y [..., n*t, f] w [f, d]  → z [..., t, d]

with ``n`` = TP axis size, ``t`` = local token rows, ``d`` full (model)
features, ``f`` this shard's feature slice. Exact parity with the plain
sharded einsum (fwd and grads) is pinned by tests/test_collective_matmul.py
on integer-valued data (bitwise-exact under any summation order).

The shard_map wrappers use ``check_vma=False`` (custom_vjp outputs carry
no varying-manual-axes info — the flash_attention/fused_ce precedent).
VERSION TRIPWIRE: under check_vma=False the transpose convention
"replicated inputs' cotangents are psum'd by shard_map itself" is an
unspecified internal (see ops/fused_ce.py); the exact-parity grad tests in
tests/test_collective_matmul.py are the mandatory guards and MUST stay in
the ``not slow`` tier.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dtf_tpu.core.comms import ring_perm


def _ring_perm(n: int):
    """Send to the next ring neighbor: device i → i+1 (one ICI hop).

    Delegates to the named builder in ``core/comms.py`` — the one perm
    construction point the collective soundness pass introspects.
    """
    return ring_perm(n)


def _rows(full: jax.Array, src: jax.Array, t: int) -> jax.Array:
    """Row block ``[src*t, src*t + t)`` of the token axis (-2)."""
    return jax.lax.dynamic_slice_in_dim(full, src * t, t, axis=-2)


# ---------------------------------------------------------------------------
# ag_matmul: all-gather overlapped with matmul (column-parallel projection).
# ---------------------------------------------------------------------------

def _ag_matmul_impl(axis_name: str, x: jax.Array, w: jax.Array) -> jax.Array:
    """y = all_gather(x, rows) @ w, as an n-step ppermute ring.

    Step k multiplies the chunk that arrived at step k-1 while ppermute
    already moves it onward — the send does not depend on the matmul, so
    XLA's async scheduler overlaps collective-permute with MXU time. The
    final chunk is folded OUTSIDE the scan (no dead last transfer, same
    shape as ring_attention's local-block-first trick, mirrored).
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    t = x.shape[-2]
    perm = _ring_perm(n)

    blk0 = jnp.einsum("...td,df->...tf", x, w)
    # zeros buffer derived from blk0 so it inherits the varying-manual-axes
    # type (shard_map's vma checker rejects unvarying scan carries).
    y = jnp.concatenate([blk0 * 0.0] * n, axis=-2)
    y = jax.lax.dynamic_update_slice_in_dim(y, blk0, idx * t, axis=-2)
    if n == 1:
        return y

    def body(carry, k):
        xb, y = carry
        nxt = jax.lax.ppermute(xb, axis_name, perm)   # in flight while...
        src = (idx - k) % n
        blk = jnp.einsum("...td,df->...tf", xb, w)    # ...this multiplies
        y = jax.lax.dynamic_update_slice_in_dim(y, blk, src * t, axis=-2)
        return (nxt, y), None

    # the local block was already folded above (k=0); ring steps 1..n-1
    # receive a neighbor chunk each. The LAST chunk is computed without a
    # trailing send.
    xb = jax.lax.ppermute(x, axis_name, perm)
    if n > 2:
        (xb, y), _ = jax.lax.scan(body, (xb, y), jnp.arange(1, n - 1))
    src_last = (idx - (n - 1)) % n
    blk_last = jnp.einsum("...td,df->...tf", xb, w)
    return jax.lax.dynamic_update_slice_in_dim(
        y, blk_last, src_last * t, axis=-2)


def _ring_dw(axis_name: str, chunk: jax.Array, full: jax.Array) -> jax.Array:
    """dW ring: ``Σ_s chunk_sᵀ @ full[rows s]`` with the chunks riding the
    ring — the gather-on-contracting-dim half of both backward passes.

    ``chunk`` [..., t, c] is this shard's row block of a row-sharded
    tensor; ``full`` [..., n*t, f] has all rows locally. Returns [c, f].
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    t = chunk.shape[-2]
    perm = _ring_perm(n)

    acc = jnp.einsum("...tc,...tf->cf", chunk, _rows(full, idx, t))
    if n == 1:
        return acc

    def body(carry, k):
        cb, acc = carry
        nxt = jax.lax.ppermute(cb, axis_name, perm)
        src = (idx - k) % n
        acc = acc + jnp.einsum("...tc,...tf->cf", cb, _rows(full, src, t))
        return (nxt, acc), None

    cb = jax.lax.ppermute(chunk, axis_name, perm)
    if n > 2:
        (cb, acc), _ = jax.lax.scan(body, (cb, acc), jnp.arange(1, n - 1))
    src_last = (idx - (n - 1)) % n
    return acc + jnp.einsum("...tc,...tf->cf", cb,
                            _rows(full, src_last, t))


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def ag_matmul(axis_name: str, x: jax.Array, w: jax.Array) -> jax.Array:
    """Column-parallel collective matmul (call inside shard_map).

    ``x`` [..., t, d]: this shard's token rows (tokens sharded over
    ``axis_name``); ``w`` [d, f]: this shard's COLUMN slice of the weight.
    Returns ``all_gather(x) @ w`` [..., n*t, f] with the gather decomposed
    into a ppermute ring overlapped with the per-chunk matmuls. Backward
    is the mirrored pattern: dx via :func:`matmul_rs`'s ring, dw via a
    gather-on-contract ring — no blocking collective appears under grad.
    """
    return _ag_matmul_impl(axis_name, x, w)


def _ag_matmul_fwd(axis_name, x, w):
    return _ag_matmul_impl(axis_name, x, w), (x, w)


def _ag_matmul_bwd(axis_name, res, dy):
    x, w = res
    # dX_full = dy @ wᵀ summed over shards, scattered back to our rows —
    # exactly the matmul_rs pattern with the transposed weight.
    dx = _matmul_rs_impl(axis_name, dy, w.T)
    # dw = all_gather(x)ᵀ @ dy, chunk by chunk as x rides the ring.
    dw = _ring_dw(axis_name, x, dy)
    return dx, dw


ag_matmul.defvjp(_ag_matmul_fwd, _ag_matmul_bwd)


# ---------------------------------------------------------------------------
# matmul_rs: matmul overlapped with reduce-scatter (row-parallel projection).
# ---------------------------------------------------------------------------

def _matmul_rs_impl(axis_name: str, y: jax.Array, w: jax.Array) -> jax.Array:
    """z = reduce_scatter(y @ w, rows), as an n-step ppermute ring.

    The partial-sum accumulator rides the ring while each step's chunk
    matmul computes: step k on device j contributes to row chunk
    ``(j - k - 1) mod n`` (the schedule whose final step lands each fully
    reduced chunk on its owner with no trailing transfer). The add depends
    on the arriving accumulator but the matmul does not — overlap again.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    if y.shape[-2] % n:
        raise ValueError(
            f"matmul_rs: token rows {y.shape[-2]} not divisible by "
            f"axis {axis_name!r} size {n}")
    t = y.shape[-2] // n
    if n == 1:
        return jnp.einsum("...tf,fd->...td", y, w)
    perm = _ring_perm(n)

    def partial_for(k):
        tgt = (idx - k - 1) % n
        return jnp.einsum("...tf,fd->...td", _rows(y, tgt, t), w)

    def body(acc, k):
        return jax.lax.ppermute(acc, axis_name, perm) + partial_for(k), None

    acc = partial_for(0)
    if n > 2:
        acc, _ = jax.lax.scan(body, acc, jnp.arange(1, n - 1))
    return jax.lax.ppermute(acc, axis_name, perm) + partial_for(n - 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def matmul_rs(axis_name: str, y: jax.Array, w: jax.Array) -> jax.Array:
    """Row-parallel collective matmul (call inside shard_map).

    ``y`` [..., n*t, f]: full token rows, features sharded over
    ``axis_name``; ``w`` [f, d]: this shard's ROW slice of the weight.
    Returns ``reduce_scatter(y @ w)`` [..., t, d] — this shard's token
    rows of the fully reduced product — with the scatter decomposed into
    a ppermute ring overlapped with the per-chunk matmuls. Backward is
    the mirrored pattern (dy via :func:`ag_matmul`'s ring).
    """
    return _matmul_rs_impl(axis_name, y, w)


def _matmul_rs_fwd(axis_name, y, w):
    return _matmul_rs_impl(axis_name, y, w), (y, w)


def _matmul_rs_bwd(axis_name, res, dz):
    y, w = res
    # dY_j = all_gather(dz) @ w_jᵀ — the mirrored ag_matmul ring.
    dy = _ag_matmul_impl(axis_name, dz, w.T)
    # dw = y[rows s]ᵀ @ dz_s summed over s as dz rides the ring; the ring
    # yields dzᵀ-major [d, f] — transpose to w's [f, d].
    dw = _ring_dw(axis_name, dz, y).T
    return dy, dw


matmul_rs.defvjp(_matmul_rs_fwd, _matmul_rs_bwd)


# ---------------------------------------------------------------------------
# Quantized-communicated-operand rings (ISSUE 17): the same two schedules
# with the tensor that RIDES the ring carried as a (q, scale) pair —
# dequant-after-ppermute — so each hop moves ~2x fewer bytes on the same
# perm. The LOCAL block always computes from the original full-precision
# operand (zero quantization cost for the chunk that never travels), and
# both backwards ride the full-precision rings above (master weights:
# quantization perturbs the forward value only; docs/TUNING.md).
# ---------------------------------------------------------------------------

def _quant_ride(a: jax.Array, qdtype: str):
    """Quantize the ring payload per token row (the contraction axis is
    -1 for ag_matmul's x and matmul_rs's accumulator alike)."""
    from dtf_tpu.ops import quant

    return quant.quantize_channel(a, axis=-1, dtype=qdtype)


def _dequant_ride(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    from dtf_tpu.ops import quant

    return quant.dequantize(q, scale, dtype)


def _ppermute_pair(axis_name: str, perm, q: jax.Array, s: jax.Array):
    # two explicit sends (values + scales) so the comms fence prices the
    # scale sideband honestly instead of hiding it in a tuple transfer.
    return (jax.lax.ppermute(q, axis_name, perm),
            jax.lax.ppermute(s, axis_name, perm))


def _ag_matmul_quant_impl(axis_name: str, qdtype: str, x: jax.Array,
                          w: jax.Array) -> jax.Array:
    """:func:`_ag_matmul_impl` with the token chunks riding the ring as
    (int8|fp8, f32-scale) pairs; each chunk dequantizes on arrival."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    t = x.shape[-2]
    perm = _ring_perm(n)

    blk0 = jnp.einsum("...td,df->...tf", x, w)   # local block: exact
    y = jnp.concatenate([blk0 * 0.0] * n, axis=-2)
    y = jax.lax.dynamic_update_slice_in_dim(y, blk0, idx * t, axis=-2)
    if n == 1:
        return y

    qx, sx = _quant_ride(x, qdtype)

    def body(carry, k):
        qb, sb, y = carry
        nq, ns = _ppermute_pair(axis_name, perm, qb, sb)
        src = (idx - k) % n
        blk = jnp.einsum("...td,df->...tf",
                         _dequant_ride(qb, sb, x.dtype), w)
        y = jax.lax.dynamic_update_slice_in_dim(y, blk, src * t, axis=-2)
        return (nq, ns, y), None

    qb, sb = _ppermute_pair(axis_name, perm, qx, sx)
    if n > 2:
        (qb, sb, y), _ = jax.lax.scan(body, (qb, sb, y),
                                      jnp.arange(1, n - 1))
    src_last = (idx - (n - 1)) % n
    blk_last = jnp.einsum("...td,df->...tf",
                          _dequant_ride(qb, sb, x.dtype), w)
    return jax.lax.dynamic_update_slice_in_dim(
        y, blk_last, src_last * t, axis=-2)


def _matmul_rs_quant_impl(axis_name: str, qdtype: str, y: jax.Array,
                          w: jax.Array) -> jax.Array:
    """:func:`_matmul_rs_impl` with the partial-sum accumulator riding
    the ring quantized (re-quantized before each of the n-1 hops — the
    bounded re-rounding the banked rel-err rows price in)."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    if y.shape[-2] % n:
        raise ValueError(
            f"matmul_rs_quant: token rows {y.shape[-2]} not divisible "
            f"by axis {axis_name!r} size {n}")
    t = y.shape[-2] // n
    if n == 1:
        return jnp.einsum("...tf,fd->...td", y, w)
    perm = _ring_perm(n)

    def partial_for(k):
        tgt = (idx - k - 1) % n
        return jnp.einsum("...tf,fd->...td", _rows(y, tgt, t), w)

    def hop(acc, k):
        qa, sa = _quant_ride(acc, qdtype)
        qa, sa = _ppermute_pair(axis_name, perm, qa, sa)
        return _dequant_ride(qa, sa, acc.dtype) + partial_for(k)

    def body(acc, k):
        return hop(acc, k), None

    acc = partial_for(0)
    if n > 2:
        acc, _ = jax.lax.scan(body, acc, jnp.arange(1, n - 1))
    return hop(acc, n - 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def ag_matmul_quant(axis_name: str, qdtype: str, x: jax.Array,
                    w: jax.Array) -> jax.Array:
    """Column-parallel collective matmul with a quantized ring payload
    (call inside shard_map). Same contract as :func:`ag_matmul`; the
    backward IS :func:`ag_matmul`'s (full-precision mirrored rings), so
    gradients are bitwise those of the bf16 overlap path."""
    return _ag_matmul_quant_impl(axis_name, qdtype, x, w)


def _ag_matmul_quant_fwd(axis_name, qdtype, x, w):
    return _ag_matmul_quant_impl(axis_name, qdtype, x, w), (x, w)


def _ag_matmul_quant_bwd(axis_name, qdtype, res, dy):
    return _ag_matmul_bwd(axis_name, res, dy)


ag_matmul_quant.defvjp(_ag_matmul_quant_fwd, _ag_matmul_quant_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def matmul_rs_quant(axis_name: str, qdtype: str, y: jax.Array,
                    w: jax.Array) -> jax.Array:
    """Row-parallel collective matmul with a quantized ring accumulator
    (call inside shard_map). Same contract as :func:`matmul_rs`;
    backward rides the full-precision mirrored rings."""
    return _matmul_rs_quant_impl(axis_name, qdtype, y, w)


def _matmul_rs_quant_fwd(axis_name, qdtype, y, w):
    return _matmul_rs_quant_impl(axis_name, qdtype, y, w), (y, w)


def _matmul_rs_quant_bwd(axis_name, qdtype, res, dz):
    return _matmul_rs_bwd(axis_name, res, dz)


matmul_rs_quant.defvjp(_matmul_rs_quant_fwd, _matmul_rs_quant_bwd)


# ---------------------------------------------------------------------------
# Global-array wrappers (outside shard_map) + the flax drop-in.
# ---------------------------------------------------------------------------

def _token_spec(axis: str) -> P:
    # activations between TP projections are token-sharded over BOTH the
    # context-parallel axis and the TP axis (Megatron-SP layout); size-1
    # axes are free to name, and every mesh carries all five axes.
    return P("data", ("seq", axis), None)


def ag_matmul_sharded(x: jax.Array, w: jax.Array, mesh: Mesh, *,
                      axis: str = "model") -> jax.Array:
    """shard_map boundary for :func:`ag_matmul`.

    ``x`` [B, T, D] token-sharded P('data', ('seq', axis), None);
    ``w`` [D, F] column-sharded P(None, axis). Returns [B, T, F] with F
    sharded over ``axis`` (the activation layout the attention/gelu paths
    already run in).
    """
    return jax.shard_map(
        functools.partial(ag_matmul, axis), mesh=mesh,
        in_specs=(_token_spec(axis), P(None, axis)),
        out_specs=P("data", "seq", axis), check_vma=False)(x, w)


def matmul_rs_sharded(y: jax.Array, w: jax.Array, mesh: Mesh, *,
                      axis: str = "model") -> jax.Array:
    """shard_map boundary for :func:`matmul_rs`.

    ``y`` [B, T, F] with F sharded over ``axis``; ``w`` [F, D]
    row-sharded P(axis, None). Returns [B, T, D] token-sharded
    P('data', ('seq', axis), None) — the residual-stream layout the next
    block's :func:`ag_matmul_sharded` consumes directly, so the only
    remaining gather is the one GSPMD inserts at the LM head.
    """
    return jax.shard_map(
        functools.partial(matmul_rs, axis), mesh=mesh,
        in_specs=(P("data", "seq", axis), P(axis, None)),
        out_specs=_token_spec(axis), check_vma=False)(y, w)


def ag_matmul_quant_sharded(x: jax.Array, w: jax.Array, mesh: Mesh, *,
                            axis: str = "model",
                            precision: str = "int8") -> jax.Array:
    """:func:`ag_matmul_sharded` with the communicated operand quantized
    to ``precision`` ('int8' | 'fp8'); same specs, ~2x fewer ring bytes."""
    return jax.shard_map(
        functools.partial(ag_matmul_quant, axis, precision), mesh=mesh,
        in_specs=(_token_spec(axis), P(None, axis)),
        out_specs=P("data", "seq", axis), check_vma=False)(x, w)


def matmul_rs_quant_sharded(y: jax.Array, w: jax.Array, mesh: Mesh, *,
                            axis: str = "model",
                            precision: str = "int8") -> jax.Array:
    """:func:`matmul_rs_sharded` with the ring accumulator quantized to
    ``precision`` ('int8' | 'fp8'); same specs, ~2x fewer ring bytes."""
    return jax.shard_map(
        functools.partial(matmul_rs_quant, axis, precision), mesh=mesh,
        in_specs=(P("data", "seq", axis), P(axis, None)),
        out_specs=_token_spec(axis), check_vma=False)(y, w)


# ---------------------------------------------------------------------------
# Introspection surface for the collective soundness pass.
# ---------------------------------------------------------------------------

class RingOp(NamedTuple):
    """One custom_vjp ring op as the analyzer sees it: the forward impl,
    the backward impl, and tiny abstract per-shard arguments for each —
    enough to trace both sides at a given axis size and hold the rings to
    the mirrored-ring invariant (``analysis/collective.py``).

    ``fwd`` is called ``fwd(axis_name, *fwd_args(n))``; ``bwd`` is called
    ``bwd(axis_name, *bwd_args(n))`` where the first bwd arg is the saved
    residual tuple and the second the output cotangent.
    """

    name: str
    fwd: object
    bwd: object
    fwd_args: object      # n -> tuple of ShapeDtypeStructs (per-shard)
    bwd_args: object      # n -> (residuals, cotangent) ShapeDtypeStructs


def ring_inventory() -> tuple[RingOp, ...]:
    """Every shipped collective-matmul ring pair, declared for the
    soundness pass. A new ring op MUST register here: the pass verifies
    (a) every perm either side binds is a true ring permutation and (b)
    the backward rides the forward's ring or its inverse — the mirrored-
    ring invariant overlap-under-grad depends on (module docstring).
    Numeric parity stays pinned by tests/test_collective_matmul.py; this
    hook is what lets a *static* pass catch a transposed perm pair or a
    backward that silently fell off the ring."""
    t, d, f = 2, 4, 4
    sds = lambda *s: jax.ShapeDtypeStruct(s, jnp.float32)  # noqa: E731

    ops = [
        RingOp(
            "ag_matmul", _ag_matmul_impl, _ag_matmul_bwd,
            lambda n: (sds(t, d), sds(d, f)),
            lambda n: ((sds(t, d), sds(d, f)), sds(n * t, f))),
        RingOp(
            "matmul_rs", _matmul_rs_impl, _matmul_rs_bwd,
            lambda n: (sds(n * t, f), sds(f, d)),
            lambda n: ((sds(n * t, f), sds(f, d)), sds(t, d))),
    ]
    # the quantized-payload twins ride the SAME perm fwd and the full-
    # precision rings bwd — registering them holds the dequant-after-
    # ppermute paths to the identical mirrored-ring invariant. fp8 rings
    # exist only where the jax has the e4m3 dtype (same feature gate the
    # resolver demotes through), so the inventory never traces a dtype
    # the install can't represent.
    from dtf_tpu.ops import quant

    for qd in ("int8",) + (("fp8",) if quant.fp8_supported() else ()):
        ops.append(RingOp(
            f"ag_matmul_{qd}",
            (lambda axis_name, x, w, _q=qd:
             _ag_matmul_quant_impl(axis_name, _q, x, w)),
            (lambda axis_name, res, dy, _q=qd:
             _ag_matmul_quant_bwd(axis_name, _q, res, dy)),
            lambda n: (sds(t, d), sds(d, f)),
            lambda n: ((sds(t, d), sds(d, f)), sds(n * t, f))))
        ops.append(RingOp(
            f"matmul_rs_{qd}",
            (lambda axis_name, y, w, _q=qd:
             _matmul_rs_quant_impl(axis_name, _q, y, w)),
            (lambda axis_name, res, dz, _q=qd:
             _matmul_rs_quant_bwd(axis_name, _q, res, dz)),
            lambda n: (sds(n * t, f), sds(f, d)),
            lambda n: ((sds(n * t, f), sds(f, d)), sds(t, d))))
    return tuple(ops)
