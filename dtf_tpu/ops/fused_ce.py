"""Pallas fused LM-head + cross-entropy: logits never touch HBM.

The monolithic loss path materializes [N, V] f32 logits AND their
cotangent (~1.6 GB each for GPT-2's 50k vocab at batch 8 x seq 1024);
the jnp chunked variants (``dtf_tpu/ops/losses.py``) bound that memory
but still stream O(N·V) floats through HBM once per direction. This
kernel computes the head matmul and the CE in VMEM tiles — the same
move flash attention makes for the score matrix (SURVEY.md §2b N3:
Pallas where XLA's fusion cannot reach; the reference has no analogue,
its MNIST softmax is three orders of magnitude smaller):

- forward: grid (token-blocks, vocab-blocks), online logsumexp in
  scratch exactly like ``flash_attention._fwd_kernel``'s (m, l) carry,
  plus a target-logit accumulator (iota-compare pick, no one-hot).
  Outputs per-token lse and picked-target — O(N), not O(N·V).
- backward: dlogits = dce · (softmax − onehot) is REBUILT per tile from
  the saved lse (flash's recompute trade: extra MXU flops for zero HBM
  logits traffic). Two kernels, mirroring flash's dq / dkv split —
  ``dx += dlogits @ Wᵀ`` accumulates over vocab-blocks with dx blocked
  by token, ``dW += xᵀ @ dlogits`` accumulates over token-blocks with
  dW blocked by vocab — because a single grid cannot give both outputs
  consecutive revisits (Mosaic's accumulation contract).

Semantics match :func:`dtf_tpu.ops.losses.softmax_cross_entropy`
(ignore_index, clamped-count mean, out-of-range labels pick nothing);
parity-tested in interpret mode against the full path, fwd and grads
(tests/test_fused_ce.py). ``bias`` is not supported — the GPT flagship
head is bias-free; BERT's MLM path should gather masked positions
first (``--mlm_gather``), after which N is small and chunking is moot.

VMEM sizing: one tile holds x [bn, D] + w [D, bv] + logits f32 [bn, bv]
+ f32 accumulators; the 512x1024 default fits comfortably at D <= 1024
(~8 MB). For much wider models shrink ``block_v``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dtf_tpu.ops.flash_attention import _compiler_params, _pad

_NEG_INF = float("-inf")
_STAT_LANES = 128
# Last-resort fallback tile — block args left at 0 resolve through the
# kernel-tune cache first (dtf_tpu.tune.resolver; docs/TUNING.md).
DEFAULT_BLOCK_N = 512
DEFAULT_BLOCK_V = 1024


def _col_ids(j, shape, block_v):
    return j * block_v + jax.lax.broadcasted_iota(jnp.int32, shape, 1)


def _fwd_kernel(x_ref, w_ref, lab_ref, lse_ref, tgt_ref, m_scr, l_scr,
                t_scr, *, v, block_v, num_v):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, m_scr.dtype)
        l_scr[...] = jnp.zeros(l_scr.shape, l_scr.dtype)
        t_scr[...] = jnp.zeros(t_scr.shape, t_scr.dtype)

    logits = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [bn, bv]
    gid = _col_ids(j, logits.shape, block_v)
    lab = lab_ref[0, 0][:, None]                     # [bn, 1]
    live = gid < v
    masked = jnp.where(live, logits, _NEG_INF)       # pad cols dead
    m_prev = m_scr[:, 0:1]
    m_next = jnp.maximum(m_prev, jnp.max(masked, axis=1, keepdims=True))
    m_safe = jnp.where(m_next == _NEG_INF, 0.0, m_next)
    alpha = jnp.exp(m_prev - m_safe)
    l_scr[...] = jnp.broadcast_to(
        alpha * l_scr[:, 0:1]
        + jnp.sum(jnp.exp(masked - m_safe), axis=1, keepdims=True),
        l_scr.shape)
    m_scr[...] = jnp.broadcast_to(m_next, m_scr.shape)
    # target pick: raw logit where the column IS the label (out-of-range
    # labels match no live column -> picked stays 0, the full-path rule)
    t_scr[...] = t_scr[...] + jnp.broadcast_to(
        jnp.sum(jnp.where((gid == lab) & live, logits, 0.0),
                axis=1, keepdims=True), t_scr.shape)

    @pl.when(j == num_v - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        lse_ref[0, 0, :] = (m_scr[:, 0:1] + jnp.log(l_safe))[:, 0]
        tgt_ref[0, 0, :] = t_scr[:, 0]


def _dlogits(x_ref, w_ref, lab_ref, lse_ref, dce_ref, j, *, v, block_v):
    """Rebuild this tile's dlogits = dce · (softmax − onehot) from the
    saved lse — THE shared recompute both backward kernels run (a
    one-sided edit here cannot desynchronize dx from dW)."""
    logits = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    gid = _col_ids(j, logits.shape, block_v)
    lab = lab_ref[0, 0][:, None]
    live = gid < v
    lse = lse_ref[0, 0][:, None]
    dce = dce_ref[0, 0][:, None]
    p = jnp.where(live, jnp.exp(logits - lse), 0.0)
    return dce * (p - jnp.where((gid == lab) & live, 1.0, 0.0))


def _dx_kernel(x_ref, w_ref, lab_ref, lse_ref, dce_ref, dx_ref, acc_scr,
               *, v, block_v, num_v):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_scr[...] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    dl = _dlogits(x_ref, w_ref, lab_ref, lse_ref, dce_ref, j,
                  v=v, block_v=block_v)
    acc_scr[...] = acc_scr[...] + jax.lax.dot_general(
        dl.astype(w_ref.dtype), w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)          # [bn, D]

    @pl.when(j == num_v - 1)
    def _finalize():
        dx_ref[...] = acc_scr[...].astype(dx_ref.dtype)


def _dw_kernel(x_ref, w_ref, lab_ref, lse_ref, dce_ref, dw_ref, acc_scr,
               *, v, block_v, num_n):
    # grid (vocab-blocks, token-blocks): dW blocked by vocab, accumulated
    # across token steps
    j, i = pl.program_id(0), pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        acc_scr[...] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    dl = _dlogits(x_ref, w_ref, lab_ref, lse_ref, dce_ref, j,
                  v=v, block_v=block_v)
    acc_scr[...] = acc_scr[...] + jax.lax.dot_general(
        x_ref[...], dl.astype(x_ref.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [D, bv]

    @pl.when(i == num_n - 1)
    def _finalize():
        dw_ref[...] = acc_scr[...].astype(dw_ref.dtype)


def _prep(x, w, labels, block_n, block_v):
    n, d = x.shape
    v = w.shape[1]
    num_n = pl.cdiv(n, block_n)
    num_v = pl.cdiv(v, block_v)
    xp = _pad(x, block_n, 0)
    wp = _pad(w, block_v, 1)
    labp = _pad(labels.reshape(-1), block_n, 0).reshape(num_n, 1, block_n)
    return n, d, v, num_n, num_v, xp, wp, labp


def _run_fwd(x, w, labels, block_n, block_v, interpret):
    n, d, v, num_n, num_v, xp, wp, labp = _prep(x, w, labels, block_n,
                                                block_v)
    lse, tgt = pl.pallas_call(
        functools.partial(_fwd_kernel, v=v, block_v=block_v, num_v=num_v),
        grid=(num_n, num_v),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((1, 1, block_n), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_n), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, block_n), lambda i, j: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((num_n, 1, block_n), jnp.float32),
            jax.ShapeDtypeStruct((num_n, 1, block_n), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_n, _STAT_LANES), jnp.float32)
                        for _ in range(3)],
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        interpret=interpret,
    )(xp, wp, labp)
    return lse.reshape(-1)[:n], tgt.reshape(-1)[:n]


def _run_bwd(x, w, labels, lse, dce, block_n, block_v, interpret):
    n, d, v, num_n, num_v, xp, wp, labp = _prep(x, w, labels, block_n,
                                                block_v)
    lsep = _pad(lse, block_n, 0).reshape(num_n, 1, block_n)
    dcep = _pad(dce, block_n, 0).reshape(num_n, 1, block_n)
    common = dict(v=v, block_v=block_v)
    dx = pl.pallas_call(
        functools.partial(_dx_kernel, num_v=num_v, **common),
        grid=(num_n, num_v),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((1, 1, block_n), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, block_n), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, block_n), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(xp.shape, x.dtype),
        scratch_shapes=[pltpu.VMEM((block_n, d), jnp.float32)],
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        interpret=interpret,
    )(xp, wp, labp, lsep, dcep)
    dw = pl.pallas_call(
        functools.partial(_dw_kernel, num_n=num_n, **common),
        grid=(num_v, num_n),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda j, i: (i, 0)),
            pl.BlockSpec((d, block_v), lambda j, i: (0, j)),
            pl.BlockSpec((1, 1, block_n), lambda j, i: (i, 0, 0)),
            pl.BlockSpec((1, 1, block_n), lambda j, i: (i, 0, 0)),
            pl.BlockSpec((1, 1, block_n), lambda j, i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((d, block_v), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct(wp.shape, w.dtype),
        scratch_shapes=[pltpu.VMEM((d, block_v), jnp.float32)],
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        interpret=interpret,
    )(xp, wp, labp, lsep, dcep)
    return dx[:n], dw[:, :v]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fused_ce(x, w, labels, ignore_index, block_n, block_v, interpret,
              axis_names):
    out, _ = _fused_ce_fwd(x, w, labels, ignore_index, block_n, block_v,
                           interpret, axis_names)
    return out


def _valid(labels, ignore_index):
    if ignore_index is None:
        return jnp.ones(labels.shape, jnp.float32)
    return (labels != ignore_index).astype(jnp.float32)


def _fused_ce_fwd(x, w, labels, ignore_index, block_n, block_v, interpret,
                  axis_names):
    lse, tgt = _run_fwd(x, w, labels, block_n, block_v, interpret)
    valid = _valid(labels, ignore_index)
    ce_sum = jnp.sum((lse - tgt) * valid)
    cnt = valid.sum()
    if axis_names:
        # inside a shard_map over token-sharding axes: the mean and count
        # are global, so every shard returns identical (replicated) values
        ce_sum = jax.lax.psum(ce_sum, axis_names)
        cnt = jax.lax.psum(cnt, axis_names)
    cnt = jnp.maximum(cnt, 1.0)
    mean = ce_sum / cnt
    return (mean, cnt), (x, w, labels, lse, valid, cnt)


def _fused_ce_bwd(ignore_index, block_n, block_v, interpret, axis_names,
                  res, g):
    x, w, labels, lse, valid, cnt = res
    g_mean, _g_cnt = g                         # cnt is not differentiable
    if axis_names:
        # Measured shard_map transpose behavior (check_vma=False, CPU sim,
        # tests/test_fused_ce.py::test_sharded_matches_unsharded_grads):
        # a replicated (P()) OUTPUT's cotangent arrives divided by the
        # shard count, and the replicated w INPUT's cotangent is psum'd
        # by shard_map itself. So: undo the division here, add no psum.
        #
        # VERSION TRIPWIRE (ADVICE r5 #1): both halves of that convention
        # are UNSPECIFIED shard_map internals under check_vma=False — a
        # JAX upgrade is free to change either, which would silently
        # mis-scale dx and dw by a factor of the shard count. The fast-
        # tier parity tests
        #   tests/test_fused_ce.py::test_sharded_matches_unsharded_grads
        #   tests/test_fused_ce.py::test_gpt_loss_pallas_matches_full
        # are the mandatory guards: they compare these gradients against
        # the unsharded path and MUST stay in the `not slow` tier. If they
        # start failing after a jax bump, re-measure the convention here
        # (or restructure: per-shard sums out of the custom_vjp, explicit
        # psum outside it, under a vma-checked shard_map).
        g_mean = g_mean * jax.lax.psum(1.0, axis_names)
    dce = (g_mean / cnt) * valid               # [N] (cnt is already global)
    dx, dw = _run_bwd(x, w, labels, lse, dce, block_n, block_v, interpret)
    return dx, dw, None


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def pallas_lm_cross_entropy(x: jax.Array, w_head: jax.Array,
                            labels: jax.Array, *,
                            ignore_index: int | None = None,
                            block_n: int = 0,
                            block_v: int = 0,
                            interpret: bool = False,
                            axis_names: tuple = (),
                            ) -> tuple[jax.Array, jax.Array]:
    """(mean_loss, valid_count) — same contract as
    :func:`dtf_tpu.ops.losses.softmax_cross_entropy`, with the [N, V]
    logits living only in VMEM tiles (module docstring).

    ``block_n`` / ``block_v`` left at 0 resolve through the kernel-tune
    cache (:mod:`dtf_tpu.tune.resolver`; docs/TUNING.md), falling back
    to the 512x1024 module defaults; explicit values win, warning once
    when they differ from a measured winner.

    ``axis_names``: set when calling from INSIDE a shard_map whose named
    axes shard the tokens — the loss/count psum across them and dW's
    cotangent is psum'd in the backward. Callers under plain jit use
    :func:`pallas_lm_cross_entropy_sharded` instead, which owns the
    shard_map boundary (a bare pallas_call cannot be GSPMD-partitioned
    from outside: jit would all-gather the tokens and run the kernel
    redundantly per device — the flash_attention_sharded lesson)."""
    xf = x.reshape(-1, x.shape[-1])
    lab = labels.reshape(-1).astype(jnp.int32)
    n = xf.shape[0]
    if not (block_n and block_v):
        from dtf_tpu.tune import resolver as _tune

        plan = _tune.fused_ce_plan(
            vocab=int(w_head.shape[1]), d_model=int(xf.shape[1]),
            dtype=jnp.dtype(x.dtype).name, n_devices=jax.device_count(),
            backend=jax.default_backend())
        for what, explicit, won in (("block_n", block_n, plan.block_n),
                                    ("block_v", block_v, plan.block_v)):
            if explicit:
                _tune.note_override("fused_ce", what, explicit, won,
                                    source=plan.source,
                                    measured=plan.measured)
        block_n = block_n or plan.block_n
        block_v = block_v or plan.block_v
    bn = min(block_n, max(n, 1))
    bv = min(block_v, max(w_head.shape[1], 1))
    return _fused_ce(xf, w_head, lab, ignore_index, bn, bv, interpret,
                     tuple(axis_names))


def pallas_lm_cross_entropy_sharded(x, w_head, labels, mesh, *,
                                    ignore_index: int | None = None,
                                    block_n: int = 0,
                                    block_v: int = 0,
                                    interpret: bool = False):
    """The shard_map boundary for DP/SP meshes: tokens partition over
    (data, seq), ``w_head`` stays replicated, each shard runs the kernel
    on its LOCAL tokens, and the mean/count/dW are psum'd inside. With
    ``mesh=None`` or no token-sharding axes this is the plain call."""
    from jax.sharding import PartitionSpec as P

    if mesh is not None and mesh.shape.get("model", 1) > 1:
        raise ValueError(
            "pallas fused CE keeps the vocab whole per shard; it cannot "
            "combine with a model (TP) mesh axis — use the standard loss")
    axes = tuple(a for a in ("data", "seq")
                 if mesh is not None and mesh.shape.get(a, 1) > 1)
    if not axes:
        return pallas_lm_cross_entropy(
            x, w_head, labels, ignore_index=ignore_index, block_n=block_n,
            block_v=block_v, interpret=interpret)

    def fn(xl, wl, labl):
        return pallas_lm_cross_entropy(
            xl, wl, labl, ignore_index=ignore_index, block_n=block_n,
            block_v=block_v, interpret=interpret, axis_names=axes)

    return jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P("data", "seq", None), P(None, None), P("data", "seq")),
        out_specs=(P(), P()), check_vma=False)(x, w_head, labels)
