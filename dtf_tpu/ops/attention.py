"""Attention ops: dense reference MHA and ring attention (context parallelism).

The reference has nothing here (SURVEY.md §5.7 — nothing in it scales
sequence length), but long-context is first-class in this framework: ring
attention shards the sequence over the ``seq`` mesh axis and streams K/V
blocks around the ring with ``ppermute`` (one ICI hop per step), using an
online-softmax accumulator so memory stays O(seq/shards) per device. The
blockwise math follows the public ring-attention recipe (Liu et al.;
flash-attention-style streaming max/sum rescaling).

Layouts: [batch, heads, seq, head_dim] (B H T D). Softmax statistics
accumulate in float32 regardless of input dtype (bf16-safe).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def dense_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False,
                    bias: Optional[jax.Array] = None,
                    sm_scale: Optional[float] = None) -> jax.Array:
    """Reference O(T²) attention. [B,H,T,D] → [B,H,T,D]; f32 softmax."""
    *_, t_q, d = q.shape
    t_k = k.shape[-2]
    scale = sm_scale if sm_scale is not None else d ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    if causal:
        q_pos = jnp.arange(t_q)[:, None]
        k_pos = jnp.arange(t_k)[None, :]
        scores = jnp.where(q_pos >= k_pos, scores, -jnp.inf)
    # Fully-masked rows (e.g. an all-padding sequence) would softmax over
    # all--inf and yield NaN; force them to 0 output with a grad-safe where
    # (matches the ring path's l=0 handling).
    dead = jnp.all(jnp.isneginf(scores), axis=-1, keepdims=True)
    probs = jax.nn.softmax(jnp.where(dead, 0.0, scores), axis=-1)
    probs = jnp.where(dead, 0.0, probs)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v)


def _ring_block(scores: jax.Array, v_blk: jax.Array, m: jax.Array,
                l: jax.Array, o: jax.Array):
    """Online-softmax update with one incoming score block (f32 stats).

    Fully-masked rows (all scores -inf so far — e.g. a pad query, or a causal
    query before its diagonal block arrives) are handled by ``safe_m``: their
    running max stays -inf, alpha and p collapse to 0, and l/o stay 0.
    """
    m_blk = scores.max(-1)                                   # [B,H,Tq]
    m_new = jnp.maximum(m, m_blk)
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.exp(jnp.minimum(m - safe_m, 0.0))            # -inf → 0
    p = jnp.exp(scores - safe_m[..., None])                  # -inf → 0
    l_new = l * alpha + p.sum(-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
    return m_new, l_new, o_new


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   kv_mask: Optional[jax.Array] = None, *,
                   axis_name: str = "seq", causal: bool = False,
                   sm_scale: Optional[float] = None,
                   skip_masked_blocks: bool = True) -> jax.Array:
    """Ring attention over a sequence-sharded mesh axis (call inside shard_map).

    Per-shard shapes [B,H,Tl,D] where Tl = T/num_shards; shard i holds global
    positions [i*Tl, (i+1)*Tl). The local K/V block is processed in place;
    each of the n-1 ring steps then receives a neighbor's block (ppermute →
    one ICI hop) and folds it into a streaming-softmax accumulator — compute
    and ICI transfer overlap under XLA's async collective scheduling.

    ``kv_mask`` [B,Tl] (True = valid key) travels the ring alongside K/V, so
    padded positions are excluded exactly as in dense attention. Causal
    masking uses global positions; incoming blocks that lie entirely above
    the diagonal (src > idx) are skipped with ``lax.cond`` — their matmuls
    never run, cutting total causal FLOPs roughly in half at large ring
    sizes. (The cond predicate varies per device; that is fine because the
    skipped branch contains no collectives — the ppermutes stay outside.)
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, t_l, d = q.shape
    scale = sm_scale if sm_scale is not None else d ** -0.5
    if kv_mask is None:
        kv_mask = (q[:, 0, :, 0] * 0 + 1).astype(bool)        # [B,Tl], varying
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = idx * t_l + jnp.arange(t_l)                       # global q rows

    def fold(k_blk, v_blk, mask_blk, src, m, l, o):
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk,
                            preferred_element_type=jnp.float32) * scale
        valid = mask_blk[:, None, None, :]                    # [B,1,1,Tk]
        if causal:
            k_pos = src * t_l + jnp.arange(t_l)
            valid = valid & (q_pos[:, None] >= k_pos[None, :])
        scores = jnp.where(valid, scores, -jnp.inf)
        return _ring_block(scores, v_blk, m, l, o)

    # derive carries from q so they inherit its varying-manual-axes type
    # (shard_map's vma checker rejects unvarying init carries).
    zeros_q = q.astype(jnp.float32) * 0.0                     # [B,H,Tl,D]
    m0, l0, o0 = zeros_q[..., 0] - jnp.inf, zeros_q[..., 0], zeros_q

    # local block first, then n-1 ring steps (no dead final transfer).
    m, l, o = fold(k, v, kv_mask, idx, m0, l0, o0)

    def body(carry, step):
        k_blk, v_blk, mask_blk, m, l, o = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        mask_blk = jax.lax.ppermute(mask_blk, axis_name, perm)
        src = (idx - step) % n
        if causal and skip_masked_blocks:
            m, l, o = jax.lax.cond(
                src <= idx, fold,
                lambda _k, _v, _m, _s, m, l, o: (m, l, o),
                k_blk, v_blk, mask_blk, src, m, l, o)
        else:
            m, l, o = fold(k_blk, v_blk, mask_blk, src, m, l, o)
        return (k_blk, v_blk, mask_blk, m, l, o), None

    if n > 1:
        (_, _, _, m, l, o), _ = jax.lax.scan(
            body, (k, v, kv_mask, m, l, o), jnp.arange(1, n))
    # l=0 rows are fully-masked (pad queries): output 0, excluded from loss.
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           mesh: Mesh, kv_mask: Optional[jax.Array] = None,
                           *, causal: bool = False,
                           sm_scale: Optional[float] = None) -> jax.Array:
    """Global-array wrapper: shard_map(ring_attention) over the mesh.

    Expects [B,H,T,D] with B on ``data``, H on ``model``, T on ``seq``;
    ``kv_mask`` [B,T] (True = valid key) sharded like the sequence. Falls
    back to dense attention when the seq axis is trivial (the shard_map
    would just add partitioning noise).
    """
    seq_shards = mesh.shape.get("seq", 1)
    if seq_shards == 1:
        bias = None
        if kv_mask is not None:
            bias = jnp.where(kv_mask[:, None, None, :], 0.0, -jnp.inf)
        return dense_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               bias=bias)
    spec = P("data", "model", "seq", None)
    mask_spec = P("data", "seq")
    fn = functools.partial(ring_attention, causal=causal, sm_scale=sm_scale)
    if kv_mask is None:
        kv_mask = jnp.ones(q.shape[:1] + q.shape[2:3], bool)
    return jax.shard_map(fn, mesh=mesh,
                         in_specs=(spec, spec, spec, mask_spec),
                         out_specs=spec)(q, k, v, kv_mask)
