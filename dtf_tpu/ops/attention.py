"""Attention ops: dense reference MHA and ring attention (context parallelism).

The reference has nothing here (SURVEY.md §5.7 — nothing in it scales
sequence length), but long-context is first-class in this framework: ring
attention shards the sequence over the ``seq`` mesh axis and streams K/V
blocks around the ring with ``ppermute`` (one ICI hop per step), using an
online-softmax accumulator so memory stays O(seq/shards) per device. The
blockwise math follows the public ring-attention recipe (Liu et al.;
flash-attention-style streaming max/sum rescaling).

Layouts: [batch, heads, seq, head_dim] (B H T D). Softmax statistics
accumulate in float32 regardless of input dtype (bf16-safe).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dtf_tpu.core.comms import ring_perm


def dense_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False,
                    window: int = 0,
                    bias: Optional[jax.Array] = None,
                    sm_scale: Optional[float] = None) -> jax.Array:
    """Reference O(T²) attention. [B,H,T,D] → [B,H,T,D]; f32 softmax.

    ``window > 0`` (requires ``causal``): sliding-window locality — query t
    attends keys in (t-window, t]. The parity oracle for the flash
    kernel's O(T·W) path."""
    *_, t_q, d = q.shape
    t_k = k.shape[-2]
    if window < 0 or (window and not causal):
        raise ValueError(
            f"window={window} must be >= 0 and requires causal=True")
    scale = sm_scale if sm_scale is not None else d ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    if causal:
        q_pos = jnp.arange(t_q)[:, None]
        k_pos = jnp.arange(t_k)[None, :]
        keep = q_pos >= k_pos
        if window:
            keep = jnp.logical_and(keep, q_pos - k_pos < window)
        scores = jnp.where(keep, scores, -jnp.inf)
    # Fully-masked rows (e.g. an all-padding sequence) would softmax over
    # all--inf and yield NaN; force them to 0 output with a grad-safe where
    # (matches the ring path's l=0 handling).
    dead = jnp.all(jnp.isneginf(scores), axis=-1, keepdims=True)
    probs = jax.nn.softmax(jnp.where(dead, 0.0, scores), axis=-1)
    probs = jnp.where(dead, 0.0, probs)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v)


def _ring_block(scores: jax.Array, v_blk: jax.Array, m: jax.Array,
                l: jax.Array, o: jax.Array):
    """Online-softmax update with one incoming score block (f32 stats).

    Fully-masked rows (all scores -inf so far — e.g. a pad query, or a causal
    query before its diagonal block arrives) are handled by ``safe_m``: their
    running max stays -inf, alpha and p collapse to 0, and l/o stay 0.
    """
    m_blk = scores.max(-1)                                   # [B,H,Tq]
    m_new = jnp.maximum(m, m_blk)
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.exp(jnp.minimum(m - safe_m, 0.0))            # -inf → 0
    p = jnp.exp(scores - safe_m[..., None])                  # -inf → 0
    l_new = l * alpha + p.sum(-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
    return m_new, l_new, o_new


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   kv_mask: Optional[jax.Array] = None, *,
                   axis_name: str = "seq", causal: bool = False,
                   sm_scale: Optional[float] = None,
                   skip_masked_blocks: bool = True) -> jax.Array:
    """Ring attention over a sequence-sharded mesh axis (call inside shard_map).

    Per-shard shapes [B,H,Tl,D] where Tl = T/num_shards; shard i holds global
    positions [i*Tl, (i+1)*Tl). The local K/V block is processed in place;
    each of the n-1 ring steps then receives a neighbor's block (ppermute →
    one ICI hop) and folds it into a streaming-softmax accumulator — compute
    and ICI transfer overlap under XLA's async collective scheduling.

    ``kv_mask`` [B,Tl] (True = valid key) travels the ring alongside K/V, so
    padded positions are excluded exactly as in dense attention. Causal
    masking uses global positions; incoming blocks that lie entirely above
    the diagonal (src > idx) are skipped with ``lax.cond`` — their matmuls
    never run, cutting total causal FLOPs roughly in half at large ring
    sizes. (The cond predicate varies per device; that is fine because the
    skipped branch contains no collectives — the ppermutes stay outside.)

    GQA: ``q`` may carry ``G × kv_heads`` heads against K/V with
    ``kv_heads`` — query groups are folded into rows internally so the
    UNEXPANDED K/V ride the ring (G× less ICI traffic than repeating
    them before the shard_map).
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, t_l, d = q.shape
    scale = sm_scale if sm_scale is not None else d ** -0.5
    if kv_mask is None:
        kv_mask = (q[:, 0, :, 0] * 0 + 1).astype(bool)        # [B,Tl], varying
    perm = ring_perm(n)

    if h % k.shape[1]:
        # validate before the group-1 shortcut: 3 q heads over 2 kv heads
        # gives group==1 and would die in an opaque einsum shape error
        raise ValueError(
            f"q heads {h} not a multiple of kv heads {k.shape[1]}")
    group = h // k.shape[1]                       # GQA: q heads per kv head
    if group > 1:
        # Fold query groups into rows so the UNEXPANDED K/V ride the ring
        # (group x less ICI traffic than repeating them): head h = kv*g + j
        # maps to kv-head kv, row block j. Scores/stats become
        # [B, KVH, G*Tl(, Tk)] — the streaming-softmax math is shape-
        # generic, only the causal q-position pattern must tile per group.
        q = q.reshape(b, k.shape[1], group * t_l, d)

    q_pos = jnp.tile(idx * t_l + jnp.arange(t_l), group)      # global q rows

    def fold(k_blk, v_blk, mask_blk, src, m, l, o):
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk,
                            preferred_element_type=jnp.float32) * scale
        valid = mask_blk[:, None, None, :]                    # [B,1,1,Tk]
        if causal:
            k_pos = src * t_l + jnp.arange(t_l)
            valid = valid & (q_pos[:, None] >= k_pos[None, :])
        scores = jnp.where(valid, scores, -jnp.inf)
        return _ring_block(scores, v_blk, m, l, o)

    # derive carries from q so they inherit its varying-manual-axes type
    # (shard_map's vma checker rejects unvarying init carries).
    zeros_q = q.astype(jnp.float32) * 0.0                     # [B,H,Tl,D]
    m0, l0, o0 = zeros_q[..., 0] - jnp.inf, zeros_q[..., 0], zeros_q

    # local block first, then n-1 ring steps (no dead final transfer).
    m, l, o = fold(k, v, kv_mask, idx, m0, l0, o0)

    def body(carry, step):
        k_blk, v_blk, mask_blk, m, l, o = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        mask_blk = jax.lax.ppermute(mask_blk, axis_name, perm)
        src = (idx - step) % n
        if causal and skip_masked_blocks:
            m, l, o = jax.lax.cond(
                src <= idx, fold,
                lambda _k, _v, _m, _s, m, l, o: (m, l, o),
                k_blk, v_blk, mask_blk, src, m, l, o)
        else:
            m, l, o = fold(k_blk, v_blk, mask_blk, src, m, l, o)
        return (k_blk, v_blk, mask_blk, m, l, o), None

    if n > 1:
        (_, _, _, m, l, o), _ = jax.lax.scan(
            body, (k, v, kv_mask, m, l, o), jnp.arange(1, n))
    # l=0 rows are fully-masked (pad queries): output 0, excluded from loss.
    out = o / jnp.maximum(l, 1e-30)[..., None]
    if group > 1:
        out = out.reshape(b, h, t_l, out.shape[-1])
    return out.astype(q.dtype)


def zigzag_permutation(t: int, n_shards: int) -> "jax.Array":
    """Natural→zigzag row permutation for load-balanced causal rings.

    The sequence is cut into ``2n`` chunks; shard ``i`` holds chunks
    ``(i, 2n-1-i)`` concatenated. Under causal masking this balances work:
    plain contiguous sharding gives shard 0 almost nothing to do and shard
    n-1 everything (the ring's wall-clock is the slowest shard), while the
    zigzag pairing gives every shard the same number of live blocks each
    ring step — the standard "zigzag"/striped context-parallel layout.

    Returns ``perm`` with ``zigzag[j] = natural[perm[j]]``; ``perm`` is also
    exactly the global position of zigzag row ``j`` (feed it to RoPE).
    Requires ``t % (2 * n_shards) == 0``.
    """
    if t % (2 * n_shards):
        raise ValueError(f"seq len {t} not divisible by 2*{n_shards} chunks")
    c = t // (2 * n_shards)
    chunks = []
    for i in range(n_shards):
        chunks.append(jnp.arange(i * c, (i + 1) * c))
        chunks.append(jnp.arange((2 * n_shards - 1 - i) * c,
                                 (2 * n_shards - i) * c))
    return jnp.concatenate(chunks)


def inverse_permutation(perm: jax.Array) -> jax.Array:
    return jnp.argsort(perm)


def zigzag_ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                          axis_name: str = "seq",
                          sm_scale: Optional[float] = None) -> jax.Array:
    """Load-balanced CAUSAL ring attention (call inside shard_map).

    Inputs are in the zigzag layout (:func:`zigzag_permutation`): each shard
    holds [B,H,2c,D] = chunks (idx, 2n-1-idx) concatenated. Per ring step a
    shard receives one neighbor pair and folds the live quadrants:

    - q_hi × kv_lo: ALWAYS live and always unmasked (every high chunk is
      causally after every low chunk) — the balanced baseline work;
    - q_lo × kv_lo: live iff src <= idx (diagonal step masks in-chunk);
    - q_hi × kv_hi: live iff src >= idx (ditto);
    - q_lo × kv_hi: never live — never computed.

    So every shard folds exactly 2 of 4 quadrants per off-diagonal step
    (~2x FLOP cut vs dense folds AND no straggler shard), with per-quadrant
    online-softmax accumulators in f32. Full sequences only (no kv_mask);
    use :func:`ring_attention` for padded batches.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, t_l, d = q.shape
    if t_l % 2:
        raise ValueError(f"zigzag shard length {t_l} must be even")
    c = t_l // 2
    scale = sm_scale if sm_scale is not None else d ** -0.5
    perm = ring_perm(n)

    lo_pos = idx * c + jnp.arange(c)
    hi_pos = (2 * n - 1 - idx) * c + jnp.arange(c)
    q_lo, q_hi = q[:, :, :c], q[:, :, c:]

    def scores_of(qh, kh, qpos, kpos, masked):
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                       preferred_element_type=jnp.float32) * scale
        if masked:
            s = jnp.where(qpos[:, None] >= kpos[None, :], s, -jnp.inf)
        return s

    def fold_quadrant(qh, kh, vh, qpos, kpos, masked, m, l, o):
        return _ring_block(scores_of(qh, kh, qpos, kpos, masked), vh, m, l, o)

    zero_lo = q_lo.astype(jnp.float32) * 0.0
    zero_hi = q_hi.astype(jnp.float32) * 0.0
    st = dict(
        m_lo=zero_lo[..., 0] - jnp.inf, l_lo=zero_lo[..., 0], o_lo=zero_lo,
        m_hi=zero_hi[..., 0] - jnp.inf, l_hi=zero_hi[..., 0], o_hi=zero_hi)

    def fold_pair(k_blk, v_blk, src, st):
        k_lo, k_hi = k_blk[:, :, :c], k_blk[:, :, c:]
        v_lo, v_hi = v_blk[:, :, :c], v_blk[:, :, c:]
        klo_pos = src * c + jnp.arange(c)
        khi_pos = (2 * n - 1 - src) * c + jnp.arange(c)

        # q_hi × kv_lo: always live, never masked (hi chunks follow all
        # lo chunks). Masking would be a no-op; skip building it.
        m_hi, l_hi, o_hi = fold_quadrant(
            q_hi, k_lo, v_lo, hi_pos, klo_pos, False,
            st["m_hi"], st["l_hi"], st["o_hi"])

        # q_lo × kv_lo: live iff src <= idx; in-chunk mask only matters on
        # the diagonal but the position compare is cheap — always apply.
        m_lo, l_lo, o_lo = jax.lax.cond(
            src <= idx,
            lambda m, l, o: fold_quadrant(q_lo, k_lo, v_lo, lo_pos, klo_pos,
                                          True, m, l, o),
            lambda m, l, o: (m, l, o),
            st["m_lo"], st["l_lo"], st["o_lo"])

        # q_hi × kv_hi: live iff src >= idx.
        m_hi, l_hi, o_hi = jax.lax.cond(
            src >= idx,
            lambda m, l, o: fold_quadrant(q_hi, k_hi, v_hi, hi_pos, khi_pos,
                                          True, m, l, o),
            lambda m, l, o: (m, l, o),
            m_hi, l_hi, o_hi)
        return dict(m_lo=m_lo, l_lo=l_lo, o_lo=o_lo,
                    m_hi=m_hi, l_hi=l_hi, o_hi=o_hi)

    st = fold_pair(k, v, idx, st)

    def body(carry, step):
        k_blk, v_blk, st = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        st = fold_pair(k_blk, v_blk, (idx - step) % n, st)
        return (k_blk, v_blk, st), None

    if n > 1:
        (_, _, st), _ = jax.lax.scan(body, (k, v, st), jnp.arange(1, n))

    out_lo = st["o_lo"] / jnp.maximum(st["l_lo"], 1e-30)[..., None]
    out_hi = st["o_hi"] / jnp.maximum(st["l_hi"], 1e-30)[..., None]
    return jnp.concatenate([out_lo, out_hi], axis=2).astype(q.dtype)


def zigzag_ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                                  mesh: Mesh, *,
                                  sm_scale: Optional[float] = None
                                  ) -> jax.Array:
    """Global-array wrapper: shard_map(zigzag_ring_attention) over ``seq``.

    Expects [B,H,T,D] already PERMUTED into the zigzag layout (rows ordered
    by :func:`zigzag_permutation`(T, seq_shards)), B on ``data``, H on
    ``model``, T on ``seq``. Falls back to dense causal attention when the
    seq axis is trivial (n=1 ⇒ the zigzag layout is the natural order).
    """
    seq_shards = mesh.shape.get("seq", 1)
    if seq_shards == 1:
        return dense_attention(q, k, v, causal=True, sm_scale=sm_scale)
    spec = P("data", "model", "seq", None)
    fn = functools.partial(zigzag_ring_attention, sm_scale=sm_scale)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)


def halo_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   window: int,
                   axis_name: str = "seq",
                   q_chunk: int = 256,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Sliding-window causal attention under sequence sharding (call inside
    shard_map) — the window × context-parallel composition.

    With ``window - 1 <= local shard length``, a query needs at most the
    PREVIOUS shard's (window-1)-token tail, so instead of rotating all K/V
    around the ring (n-1 ppermutes touching every shard), each shard fetches
    one neighbor halo with a single ppermute and attends locally:
    O(t_local · (t_local + window)) work, O(window) communication — the
    locality win survives the sharding.

    Shard 0's halo arrives wrapped from the LAST shard; its computed global
    positions are negative ("before the sequence start"), and the
    ``k_pos >= 0`` mask kills it, so the wrapped values are never read.
    """
    if window < 1:
        # window=0 means "full causal" everywhere else; here it would make
        # halo=-1 and an all-False keep mask → silent all-NaN softmax
        raise ValueError(
            f"window={window} must be >= 1 (use ring_attention for full "
            "causal under seq sharding)")
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, t, d = q.shape                       # local shapes
    halo = window - 1
    if halo > t:
        # validate HERE too (shapes are static): direct shard_map callers
        # would otherwise hit an opaque dynamic-slice error
        raise ValueError(
            f"window={window} needs a {halo}-token halo but the local "
            f"shard holds only {t} tokens")
    scale = sm_scale if sm_scale is not None else d ** -0.5

    if halo > 0:
        perm = ring_perm(n)
        k_halo = jax.lax.ppermute(k[:, :, t - halo:], axis_name, perm)
        v_halo = jax.lax.ppermute(v[:, :, t - halo:], axis_name, perm)
        kk = jnp.concatenate([k_halo, k], axis=2)       # [b,h,halo+t,d]
        vv = jnp.concatenate([v_halo, v], axis=2)
    else:
        kk, vv = k, v

    # Query-chunked local attention: a full [t, t+halo] score matrix would
    # be O(t_local²) memory — quadratic on exactly the long-context path
    # this exists for. Chunk rows p ∈ [i·c, i·c+c) attend kk slice
    # [i·c, i·c+c+halo) (kk index j ↔ global k position idx·t - halo + j),
    # so live memory is O(c·(c+halo)) per (b, h) and chunks run under
    # lax.map. When c doesn't divide t, q and kk/vv are zero-padded to the
    # next multiple and the pad rows sliced off afterwards (ADVICE r3: the
    # old largest-divisor rule degraded to c=1 — one query row per lax.map
    # step — for prime t). Pad rows stay NaN-free: each one's "diagonal"
    # key exists in the padded kk (diff==0 is always kept), and every
    # padded KEY sits at a global position strictly after the shard's real
    # queries, so causality (diff >= 0) masks it for all real rows.
    c = min(q_chunk, t)
    pad = -t % c
    q_p = jnp.pad(q, ((0, 0), (0, 0), (0, pad), (0, 0))) if pad else q
    kk = jnp.pad(kk, ((0, 0), (0, 0), (0, pad), (0, 0))) if pad else kk
    vv = jnp.pad(vv, ((0, 0), (0, 0), (0, pad), (0, 0))) if pad else vv

    def chunk(i):
        qs = jax.lax.dynamic_slice_in_dim(q_p, i * c, c, axis=2)
        ks_ = jax.lax.dynamic_slice_in_dim(kk, i * c, c + halo, axis=2)
        vs_ = jax.lax.dynamic_slice_in_dim(vv, i * c, c + halo, axis=2)
        q_pos = idx * t + i * c + jnp.arange(c)          # global positions
        k_pos = idx * t - halo + i * c + jnp.arange(c + halo)
        diff = q_pos[:, None] - k_pos[None, :]
        # k_pos >= 0 kills shard 0's wrapped halo ("before sequence start")
        keep = (diff >= 0) & (diff < window) & (k_pos[None, :] >= 0)
        s = jnp.einsum("bhqd,bhkd->bhqk", qs, ks_,
                       preferred_element_type=jnp.float32) * scale
        s = jnp.where(keep[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)                   # diag always live
        return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), vs_)

    if c == t + pad:
        return chunk(0)[:, :, :t]
    out = jax.lax.map(chunk, jnp.arange((t + pad) // c))  # [n_c,b,h,c,d]
    return out.transpose(1, 2, 0, 3, 4).reshape(
        b, h, t + pad, d)[:, :, :t]


def halo_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           mesh: Mesh, *, window: int, q_chunk: int = 256,
                           sm_scale: Optional[float] = None) -> jax.Array:
    """Global-array wrapper: shard_map(halo_attention) over ``seq``.

    Expects [B,H,T,D] with B on ``data``, H on ``model``, T on ``seq`` in
    NATURAL order (no zigzag — windowed attention is already load-balanced:
    every shard does the same local work). Falls back to windowed dense
    when the seq axis is trivial.
    """
    if window < 1:
        raise ValueError(f"window={window} must be >= 1")
    seq_shards = mesh.shape.get("seq", 1)
    if seq_shards == 1:
        return dense_attention(q, k, v, causal=True, window=window,
                               sm_scale=sm_scale)
    t_local = q.shape[2] // seq_shards
    if window - 1 > t_local:
        raise ValueError(
            f"window={window} needs a halo of {window - 1} tokens but each "
            f"seq shard holds only {t_local}; use fewer seq shards (or ring "
            "attention without a window)")
    spec = P("data", "model", "seq", None)
    fn = functools.partial(halo_attention, window=window, q_chunk=q_chunk,
                          sm_scale=sm_scale)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           mesh: Mesh, kv_mask: Optional[jax.Array] = None,
                           *, causal: bool = False,
                           sm_scale: Optional[float] = None) -> jax.Array:
    """Global-array wrapper: shard_map(ring_attention) over the mesh.

    Expects [B,H,T,D] with B on ``data``, H on ``model``, T on ``seq``;
    ``kv_mask`` [B,T] (True = valid key) sharded like the sequence. Falls
    back to dense attention when the seq axis is trivial (the shard_map
    would just add partitioning noise) — including ``mesh=None`` (a
    mesh-less caller, e.g. the un-pipelined eval of a PP x SP config with
    an explicit ``attn_impl='ring'``).
    """
    seq_shards = mesh.shape.get("seq", 1) if mesh is not None else 1
    if seq_shards == 1:
        if k.shape[1] != q.shape[1]:          # GQA: expand for the dense
            rep = q.shape[1] // k.shape[1]    # fallback (no ring to save)
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        bias = None
        if kv_mask is not None:
            bias = jnp.where(kv_mask[:, None, None, :], 0.0, -jnp.inf)
        return dense_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               bias=bias)
    model_shards = mesh.shape.get("model", 1)
    if k.shape[1] % model_shards:
        # the GQA ring keeps K/V unexpanded, so the head spec shards the
        # kv_heads dim directly; an indivisible count would otherwise
        # surface as an opaque GSPMD shape error (ADVICE r3)
        raise ValueError(
            f"kv_heads={k.shape[1]} must be divisible by the 'model' mesh "
            f"axis ({model_shards}) to ring unexpanded GQA K/V; adjust "
            "kv_heads or the mesh")
    spec = P("data", "model", "seq", None)
    mask_spec = P("data", "seq")
    fn = functools.partial(ring_attention, causal=causal, sm_scale=sm_scale)
    if kv_mask is None:
        kv_mask = jnp.ones(q.shape[:1] + q.shape[2:3], bool)
    return jax.shard_map(fn, mesh=mesh,
                         in_specs=(spec, spec, spec, mask_spec),
                         out_specs=spec)(q, k, v, kv_mask)
