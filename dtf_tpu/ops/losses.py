"""GSPMD-friendly losses for sharded logits (TP vocab sharding).

``take_along_axis`` on a vocab-sharded class dim is a sharded gather —
ambiguous/expensive under GSPMD. The one-hot contraction form keeps the
whole loss as matmul/reduce ops the partitioner handles natively (the psum
over the vocab shards is inserted automatically), which is how large-vocab
MLM heads stay TP-sharded end-to-end.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          *, ignore_index: int | None = None,
                          ) -> tuple[jax.Array, jax.Array]:
    """Per-example CE for integer labels via one-hot contraction.

    logits [..., V] (V may be mesh-sharded), labels [...] int. Returns
    (mean_loss, valid_count). With ``ignore_index`` (e.g. -100 for unmasked
    MLM positions), ignored positions contribute 0 and the mean is over valid
    positions only (psum-safe: both numerator and denominator are reductions).
    """
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    valid = (labels != ignore_index) if ignore_index is not None else None
    safe_labels = jnp.where(valid, labels, 0) if valid is not None else labels
    one_hot = jax.nn.one_hot(safe_labels, logits.shape[-1],
                             dtype=logits.dtype)
    picked = jnp.sum(one_hot * logits, axis=-1)
    return _masked_mean(lse - picked, labels, ignore_index)


def _masked_mean(ce: jax.Array, labels: jax.Array,
                 ignore_index: int | None) -> tuple[jax.Array, jax.Array]:
    """The shared ignore/mean tail: (mean over valid, valid_count), count
    clamped to 1 so an all-ignored batch yields 0.0 rather than NaN. ONE
    definition — both CE implementations promise identical semantics."""
    if ignore_index is None:
        return ce.mean(), jnp.asarray(ce.size, jnp.float32)
    valid = labels != ignore_index
    ce = jnp.where(valid, ce, 0.0)
    n = jnp.maximum(valid.sum().astype(jnp.float32), 1.0)
    return ce.sum() / n, n


def chunked_lm_cross_entropy(x: jax.Array, w_head: jax.Array,
                             labels: jax.Array, *, chunk: int = 8192,
                             bias: jax.Array | None = None,
                             ignore_index: int | None = None,
                             ) -> tuple[jax.Array, jax.Array]:
    """Next-token CE fused with the LM head, never materializing [N, V].

    The full-logits path costs O(N·V) f32 twice (logits + their cotangent)
    — 1.6 GB each for GPT-2's 50k vocab at batch 8 x seq 1024, which is
    what caps the batch size (the single-chip MFU lever). This scans the
    vocab in ``chunk``-column slices of the head kernel: each step is an
    MXU-shaped [N, D] x [D, chunk] matmul feeding an online logsumexp and
    a pick of the target logit, with the chunk rematerialized in the
    backward (``jax.checkpoint``), so live memory is O(N·chunk).

    ``x`` [..., D] (pre-head activations, post-final-LN), ``w_head``
    [D, V] (the untied lm_head kernel — or a tied embedding transposed),
    ``bias`` optional [V] (BERT's mlm_bias), ``labels`` [...] int.
    Returns (mean_loss, valid_count) with the same ignore/mean semantics
    as :func:`softmax_cross_entropy` — exact same numbers, different
    memory.
    """
    lead = x.shape[:-1]
    d = x.shape[-1]
    v = w_head.shape[1]
    xf = x.reshape(-1, d)
    lab = labels.reshape(-1)
    n = xf.shape[0]
    n_chunks = -(-v // chunk)
    v_pad = n_chunks * chunk
    wp = jnp.pad(w_head, ((0, 0), (0, v_pad - v))) if v_pad != v else w_head
    bp = None
    if bias is not None:
        bp = jnp.pad(bias, (0, v_pad - v)) if v_pad != v else bias

    @jax.checkpoint
    def body(carry, c):
        m, s, tgt = carry                       # [N], [N], [N]
        w_c = jax.lax.dynamic_slice_in_dim(wp, c * chunk, chunk, axis=1)
        logits = jnp.dot(xf, w_c,
                         preferred_element_type=jnp.float32)  # [N, chunk]
        if bp is not None:
            logits = logits + jax.lax.dynamic_slice_in_dim(
                bp, c * chunk, chunk)[None, :].astype(jnp.float32)
        col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        gid = col + c * chunk                   # global vocab ids
        logits = jnp.where(gid < v, logits, -jnp.inf)  # pad cols dead
        m_new = jnp.maximum(m, jnp.max(logits, axis=1))
        # m is -inf until the first live chunk; guard the rescale
        alpha = jnp.exp(jnp.where(jnp.isneginf(m), -jnp.inf, m - m_new))
        s = s * alpha + jnp.sum(jnp.exp(logits - m_new[:, None]), axis=1)
        # Restrict the pick to live columns: a label in [V, V_pad) would
        # otherwise match a padded -inf column and poison tgt, where the
        # full path's out-of-range one_hot is all-zero (picked stays 0).
        tgt = tgt + jnp.sum(
            jnp.where((gid == lab[:, None]) & (gid < v), logits, 0.0), axis=1)
        return (m_new, s, tgt), None

    init = (jnp.full((n,), -jnp.inf, jnp.float32),
            jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32))
    (m, s, tgt), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    ce = (m + jnp.log(s)) - tgt                 # [N]
    return _masked_mean(ce.reshape(lead), labels, ignore_index)


def token_chunked_lm_cross_entropy(x: jax.Array, w_head: jax.Array,
                                   labels: jax.Array, *, chunk: int = 4096,
                                   bias: jax.Array | None = None,
                                   ignore_index: int | None = None,
                                   ) -> tuple[jax.Array, jax.Array]:
    """Fused head+CE chunking TOKENS instead of vocab columns.

    Same memory guarantee as :func:`chunked_lm_cross_entropy` — live
    logits are O(chunk·V) instead of O(N·V) — but each scan step is ONE
    full-vocab matmul ([chunk, D] x [D, V]) followed by a plain CE, with
    no online-logsumexp carry. The round-5 on-chip rows showed the
    vocab-chunked scan costs ~9 GPT MFU points over the monolithic loss
    (BENCH_LM_SWEEP.json; PERF.md §0b): its per-step [N, chunk] max/
    rescale/pick passes are VPU traffic over the whole activation set
    repeated every chunk, and its carries serialize against the matmul.
    Token chunking does the lse/pick arithmetic ONCE per token on an
    MXU-shaped [chunk, V] tile, so it should sit between the monolithic
    and vocab-chunked points at the same bounded memory. Chunk the vocab
    instead when the HEAD matmul itself must stay narrow (e.g. a [D, V]
    too big to tile comfortably — not the case at GPT-2 scale).

    Semantics identical to :func:`softmax_cross_entropy` (same
    ignore/mean tail, same out-of-range-label behavior). ``w_head``
    [D, V]; each chunk's logits are rematerialized in the backward
    (``jax.checkpoint``), so the cotangent is also O(chunk·V).
    """
    d = x.shape[-1]
    v = w_head.shape[1]
    xf = x.reshape(-1, d)
    lab = labels.reshape(-1)
    n = xf.shape[0]
    n_chunks = -(-n // chunk)
    n_pad = n_chunks * chunk
    live = jnp.arange(n_pad) < n                # padded rows contribute 0
    if n_pad != n:
        xf = jnp.pad(xf, ((0, n_pad - n), (0, 0)))
        lab = jnp.pad(lab, (0, n_pad - n))
    bf = None if bias is None else bias.astype(jnp.float32)

    @jax.checkpoint
    def body(carry, inp):
        tot, cnt = carry
        xc, lc, rowc = inp                      # [chunk,D], [chunk], [chunk]
        logits = jnp.dot(xc, w_head,
                         preferred_element_type=jnp.float32)  # [chunk, V]
        if bf is not None:
            logits = logits + bf[None, :]
        lse = jax.nn.logsumexp(logits, axis=-1)
        valid = rowc if ignore_index is None else (
            rowc & (lc != ignore_index))
        safe = jnp.where(valid, lc, 0)
        # iota-compare pick (the vocab-chunked path's pattern): fuses to a
        # masked reduce with no materialized [chunk, V] f32 one_hot. An
        # out-of-range label matches no column -> picked 0, the exact
        # full-path behavior (softmax_cross_entropy above).
        col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        picked = jnp.sum(
            jnp.where(col == safe[:, None], logits, 0.0), axis=-1)
        ce = jnp.where(valid, lse - picked, 0.0)
        return (tot + ce.sum(), cnt + valid.sum(dtype=jnp.float32)), None

    (tot, cnt), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.float32(0.0)),
        (xf.reshape(n_chunks, chunk, d), lab.reshape(n_chunks, chunk),
         live.reshape(n_chunks, chunk)))
    # same clamped-count contract as _masked_mean (all-ignored -> 0.0)
    cnt = jnp.maximum(cnt, 1.0)
    return tot / cnt, cnt
