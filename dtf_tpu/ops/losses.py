"""GSPMD-friendly losses for sharded logits (TP vocab sharding).

``take_along_axis`` on a vocab-sharded class dim is a sharded gather —
ambiguous/expensive under GSPMD. The one-hot contraction form keeps the
whole loss as matmul/reduce ops the partitioner handles natively (the psum
over the vocab shards is inserted automatically), which is how large-vocab
MLM heads stay TP-sharded end-to-end.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          *, ignore_index: int | None = None,
                          ) -> tuple[jax.Array, jax.Array]:
    """Per-example CE for integer labels via one-hot contraction.

    logits [..., V] (V may be mesh-sharded), labels [...] int. Returns
    (mean_loss, valid_count). With ``ignore_index`` (e.g. -100 for unmasked
    MLM positions), ignored positions contribute 0 and the mean is over valid
    positions only (psum-safe: both numerator and denominator are reductions).
    """
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    valid = (labels != ignore_index) if ignore_index is not None else None
    safe_labels = jnp.where(valid, labels, 0) if valid is not None else labels
    one_hot = jax.nn.one_hot(safe_labels, logits.shape[-1],
                             dtype=logits.dtype)
    picked = jnp.sum(one_hot * logits, axis=-1)
    ce = lse - picked
    if valid is None:
        return ce.mean(), jnp.asarray(ce.size, jnp.float32)
    ce = jnp.where(valid, ce, 0.0)
    n = jnp.maximum(valid.sum().astype(jnp.float32), 1.0)
    return ce.sum() / n, n
