"""Pallas TPU embedding-row gather — the recsys hot op (BASELINE config 5).

The reference served embedding lookups from parameter servers: each lookup
was a remote sparse gather over gRPC against PS-hosted tables
(SURVEY.md §2c "Embedding sharding"). The GSPMD successor keeps tables
row-sharded on device (:mod:`dtf_tpu.parallel.embedding`); this module adds
the first-party kernel for the lookup itself — SURVEY.md §7 hard-part #4,
"sparse lookups under GSPMD are the one place a Pallas kernel may actually
be required".

Design: one grid step per lookup row. The ids vector is a *scalar-prefetch*
operand (SMEM, available before the body runs), so each step's BlockSpec
``index_map`` points the input DMA straight at table row ``ids[i]`` — the
gather IS the pipeline's address stream, there is no one-hot matmul and no
[B, R] intermediate anywhere. Rows stream HBM→VMEM→HBM with double
buffering handled by the Pallas pipeline.

Backward is a scatter-add of the output cotangent into a zero table —
expressed as ``zeros.at[ids].add(ct)`` (XLA's sort-based scatter), attached
via ``custom_vjp`` since the kernel itself is not differentiable.

The sharded/masked wrapper lives in :mod:`dtf_tpu.parallel.embedding`
(``masked_lookup_sharded(use_kernel=True)``) — one implementation of the
range-masking + psum math serves both the ``jnp.take`` and kernel paths.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _gather_kernel(ids_ref, table_ref, out_ref):
    del ids_ref  # consumed by the index_map; body just moves the row
    out_ref[...] = table_ref[...]


def _pallas_gather(table: jax.Array, ids: jax.Array,
                   interpret: bool) -> jax.Array:
    b = ids.shape[0]
    _, d = table.shape
    # Mosaic requires the last two dims of a block to be (8, 128)-divisible
    # or equal to the array dims. A [R, D] table with block (1, D) violates
    # the sublane rule (1 vs R), so view the table as [R, 1, D]: the block
    # (1, 1, D) then matches the array's trailing (1, D) exactly, legal for
    # any D, and the leading row dim becomes a pure grid axis addressed by
    # the scalar-prefetched ids.
    table3 = table.reshape(table.shape[0], 1, d)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda i, ids_ref: (ids_ref[i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda i, ids_ref: (i, 0, 0)),
    )
    out = pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, 1, d), table.dtype),
        interpret=interpret,
    )(ids, table3)
    return out.reshape(b, d)


# module-level custom_vjp (not per-call closures) so repeated calls with the
# same shapes hit JAX's compilation cache; interpret/n_rows are static.
@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _gather(table, ids, interpret, n_rows):
    del n_rows
    return _pallas_gather(table, ids, interpret)


def _gather_fwd(table, ids, interpret, n_rows):
    del n_rows
    return _pallas_gather(table, ids, interpret), ids


def _gather_bwd(interpret, n_rows, ids, ct):
    del interpret
    dt = jnp.zeros((n_rows, ct.shape[-1]), jnp.float32).at[ids].add(
        ct.astype(jnp.float32))
    return dt.astype(ct.dtype), None


_gather.defvjp(_gather_fwd, _gather_bwd)


def gather_rows(table: jax.Array, ids: jax.Array, *,
                interpret: bool = False) -> jax.Array:
    """``table[ids]`` as a fused Pallas gather. table [R,D], ids [...] int32
    in ``[0, R)``; returns [..., D]. Differentiable w.r.t. ``table``."""
    if table.ndim != 2:
        raise ValueError(f"expected table [R,D], got {table.shape}")
    flat = ids.reshape(-1)
    out = _gather(table, flat, bool(interpret), table.shape[0])
    return out.reshape(ids.shape + (table.shape[1],))
