"""Session hooks — successor of tf.train.SessionRunHook and the chief's hook set.

Reference capability replaced (SURVEY.md §3.4): ``MonitoredTrainingSession``
installs ``CheckpointSaverHook``, ``SummarySaverHook``, ``StopAtStepHook``,
``LoggingTensorHook`` on the chief. The same lifecycle — begin / before-step /
after-step / end — is kept so reference users find the familiar shape, but
hooks run on host Python around an async dispatched step, so they cost
nothing on the device timeline unless they block on results.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import time
from typing import Any, Mapping

import jax

from dtf_tpu._hostio import atomic_replace
from dtf_tpu.checkpoint import Checkpointer
from dtf_tpu.metrics import MetricWriter

PyTree = Any

log = logging.getLogger("dtf_tpu")


class StopTraining(Exception):
    """Raised by a hook to end the loop (the ``should_stop()`` successor)."""


class Hook:
    #: goodput bucket the trainer attributes this hook's wall time to when
    #: telemetry is on (dtf_tpu/telemetry/accounting.GOODPUT_BUCKETS)
    telemetry_bucket = "hooks"

    def begin(self, state: PyTree) -> None: ...

    def before_step(self, step: int) -> None: ...

    def after_step(self, step: int, state: PyTree,
                   metrics: Mapping[str, jax.Array]) -> None: ...

    def end(self, state: PyTree) -> None: ...


class StopAtStepHook(Hook):
    """``tf.train.StopAtStepHook`` equivalent (last_step semantics)."""

    def __init__(self, last_step: int):
        self.last_step = last_step

    def before_step(self, step):
        # A resumed state may already be at/past last_step; stop before
        # running an extra step (MonitoredSession checks should_stop()
        # before run(), not only after).
        if step >= self.last_step:
            raise StopTraining

    def after_step(self, step, state, metrics):
        if step >= self.last_step:
            raise StopTraining


class LoggingHook(Hook):
    """Step/loss/throughput logging — ``LoggingTensorHook`` + ``print`` path.

    Materializing ``metrics`` blocks on the async step, so this is also the
    loop's backpressure point; every_n trades log freshness for overlap.

    Throughput accounting (docs/OBSERVABILITY.md): when the launcher passes
    ``tokens_per_step`` the log line gains ``tokens_per_sec``, and with
    ``model_flops_per_step`` (the analytic 6N·tokens rule or an AOT
    ``cost_analysis()`` count) it gains ``mfu`` vs. ``peak_flops`` — both
    pure host arithmetic on the steps/sec it already computes. Defaults
    keep the historical scalars exactly. ``telemetry`` (optional) receives
    the materialized scalars so the crash flight recorder can report the
    last known loss without ever blocking on a device value itself.
    """

    telemetry_bucket = "logging"

    def __init__(self, writer: MetricWriter, every_n: int = 10,
                 lr_schedule=None, *, tokens_per_step=None,
                 model_flops_per_step=None, peak_flops=None,
                 throughput_name: str = "tokens_per_sec",
                 telemetry=None):
        #: optional optax schedule (or plain float) to surface the current
        #: learning rate next to the loss — the schedule position equals
        #: the global step (one optimizer update per step; grad-accum
        #: applies the accumulated mean gradient in that single update)
        self.writer = writer
        self.every_n = every_n
        self.lr_schedule = lr_schedule
        self.tokens_per_step = tokens_per_step
        self.model_flops_per_step = model_flops_per_step
        self.throughput_name = throughput_name
        if peak_flops is None:
            # model_flops_per_step covers the whole global batch, so the
            # MFU denominator is the MESH's peak, not one chip's
            if telemetry is not None:
                peak_flops = telemetry.peak_flops * telemetry.n_devices
            else:
                from dtf_tpu.telemetry.accounting import V5E_PEAK_BF16_FLOPS

                peak_flops = V5E_PEAK_BF16_FLOPS * jax.device_count()
        self.peak_flops = peak_flops
        self.telemetry = telemetry
        self._t0 = None
        self._last_logged = None

    def begin(self, state):
        self._t0 = time.perf_counter()
        self._last_logged = int(state.step)

    def after_step(self, step, state, metrics):
        if step % self.every_n:
            return
        now = time.perf_counter()
        steps_done = step - self._last_logged
        sps = steps_done / max(now - self._t0, 1e-9)
        self._t0, self._last_logged = now, step
        scalars = {k: float(v) for k, v in metrics.items()}
        scalars["steps_per_sec"] = sps
        if self.tokens_per_step:
            scalars[self.throughput_name] = sps * self.tokens_per_step
        if self.model_flops_per_step:
            scalars["mfu"] = (sps * self.model_flops_per_step
                              / self.peak_flops)
        if self.lr_schedule is not None:
            lr = self.lr_schedule
            scalars["lr"] = float(lr(step) if callable(lr) else lr)
        if self.telemetry is not None:
            self.telemetry.note_scalars(step, scalars)
        self.writer.write_scalars(step, scalars)

    def end(self, state):
        self.writer.flush()


class CheckpointHook(Hook):
    """``CheckpointSaverHook`` equivalent: periodic async sharded saves,
    final save + barrier at end. Orbax dedupes by save_interval_steps."""

    telemetry_bucket = "checkpoint"

    def __init__(self, ckpt: Checkpointer, every_n: int = 100):
        self.ckpt = ckpt
        self.every_n = every_n

    def after_step(self, step, state, metrics):
        if step % self.every_n == 0:
            self.ckpt.save(step, state)

    def end(self, state):
        self.ckpt.save(int(state.step), state, force=True)
        self.ckpt.wait()


class PublishHook(Hook):
    """Weight publishing for the train→serve hot-swap loop (ISSUE 14):
    every ``every_n`` steps the current params subtree is published as
    the next monotone version into the publish dir
    (:class:`dtf_tpu.publish.ParamPublisher` — atomic manifest, content
    digest; a crash mid-publish leaves the previous version intact).

    Rides next to :class:`CheckpointHook`, not instead of it: a publish
    is weights-only for serving replicas, the checkpoint stays the full
    resume state. ``publisher=None`` is the non-chief fake-host idiom
    (PreemptionHook's ``ckpt=None``): the hook is inert. The final
    params are published at ``end()`` unless the last periodic publish
    already covered that step. A publish failure WARNs and keeps
    training — serving staleness must never take the trainer down."""

    telemetry_bucket = "checkpoint"

    def __init__(self, publisher, every_n: int = 100):
        if every_n < 1:
            raise ValueError(f"every_n={every_n} must be >= 1")
        self.publisher = publisher
        self.every_n = every_n
        self._last_published_step: int | None = None

    @staticmethod
    def _params_of(state):
        params = getattr(state, "params", None)
        if params is None and isinstance(state, dict):
            params = state.get("params")
        if params is None:
            raise ValueError(
                "PublishHook needs a state with a params subtree "
                "(TrainState attribute or dict key)")
        return params

    def _publish(self, step, state) -> None:
        from dtf_tpu.fault.inject import InjectedCrash

        try:
            self.publisher.publish(step, self._params_of(state))
            self._last_published_step = step
        except InjectedCrash:
            # the crash_in_publish chaos verb: the host DIES mid-publish
            # (that is the scenario) — swallowing it here would turn the
            # atomicity proof into a no-op, and end() must not re-publish
            # from fit's finally (a SIGKILL'd host runs no end hooks;
            # this in-process twin has to match it)
            self.publisher = None
            raise
        except Exception as e:  # noqa: BLE001 — a failed publish leaves
            # the previous version serving; training continues
            log.warning(
                "publish at step %d failed (%s: %.200s); the previous "
                "published version keeps serving", step,
                type(e).__name__, e)

    def after_step(self, step, state, metrics):
        if self.publisher is not None and step % self.every_n == 0:
            self._publish(step, state)

    def end(self, state):
        if self.publisher is None:
            return
        step = getattr(state, "step", None)
        if step is None and isinstance(state, dict):
            step = state.get("step")      # dict states publish too —
            #                               _params_of supports them
        step = int(step) if step is not None else None
        if step is not None and step != self._last_published_step:
            self._publish(step, state)


class PreemptionHook(Hook):
    """Graceful-preemption checkpointing: SIGTERM → save → clean stop.

    Cloud TPU / GKE evictions deliver SIGTERM with a grace window before the
    SIGKILL; the reference era's ``_RecoverableSession`` only covered the
    crash side. The handler just sets a flag (async-signal-safe); the loop
    notices at the next step boundary, force-saves the exact current step,
    blocks until the write is durable, and raises :class:`StopTraining` —
    the relaunch then resumes with zero lost steps (vs. up to
    ``checkpoint_every - 1`` lost on a plain kill; that crash path is
    exercised by tests/test_fault_injection.py).

    Multi-host: the save is a COLLECTIVE Orbax write, and the signal lands
    at different instants on different hosts — acting on the local flag
    alone would have hosts calling save() at different steps and
    deadlocking. So under ``jax.process_count() > 1`` the flag is
    OR-allgathered at each step boundary: collectives match in program
    order, so every host evaluates the k-th sync at the same step and they
    all agree to save that step (the cluster manager signals every host of
    an evicted slice, so the OR converges within one step).

    Must be constructed and ``begin()``-run in the main thread (CPython's
    ``signal.signal`` requirement). Restores the previous handlers at
    ``end()`` so short-lived Trainers don't leak handler state.
    """

    # NOT "checkpoint": this hook's steady-state cost is the periodic
    # flag-sync allgather, a backpressure readback absorbing host
    # run-ahead (accounting.BACKPRESSURE_BUCKETS) — charging it as
    # overhead would invert multi-host goodput
    telemetry_bucket = "preempt_sync"

    def __init__(self, ckpt: Checkpointer | None, signals=(signal.SIGTERM,),
                 check_every: int = 8, *, on_preempt=None,
                 save_retries: int = 2, save_backoff_s: float = 0.25):
        #: multi-host flag-sync cadence: the OR-allgather is a device
        #: collective whose result the host blocks on, so syncing every
        #: step would forfeit async-dispatch run-ahead; every ``check_every``
        #: steps bounds the reaction delay (grace windows are ~30 s, steps
        #: are ms–s) while amortizing the barrier. Single-host runs react
        #: at the very next step regardless.
        #:
        #: ``ckpt=None``: stop cleanly on SIGTERM without saving — the
        #: non-chief fake-host processes of a CPU-sim cluster (the chief
        #: owns the shared checkpoint dir; docs/RESILIENCE.md).
        #: ``on_preempt(step)``: controller notification, called AFTER the
        #: save is durable (the last link of the SIGTERM chain: flight
        #: dump → checkpoint → notify); errors are swallowed — a broken
        #: notifier must not undo a clean preemption exit.
        #: ``save_retries``/``save_backoff_s``: Checkpointer.save_durable
        #: knobs — a transient save failure inside the grace window
        #: retries, then falls back to the previous checkpoint cleanly.
        self.ckpt = ckpt
        self.signals = tuple(signals)
        self.check_every = max(1, check_every)
        self.on_preempt = on_preempt
        self.save_retries = save_retries
        self.save_backoff_s = save_backoff_s
        self.preempted = False
        self._prev: dict = {}
        self._multiprocess = False

    def begin(self, state):
        self._multiprocess = jax.process_count() > 1
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._on_signal)

    def _on_signal(self, signum, frame):
        self.preempted = True

    def after_step(self, step, state, metrics):
        flag = self.preempted
        if self._multiprocess:
            if step % self.check_every:
                # between sync points even a locally-set flag must wait:
                # acting alone would desync the collective order
                return
            import numpy as np
            from jax.experimental import multihost_utils

            flag = bool(multihost_utils.process_allgather(
                np.asarray([self.preempted])).any())
        if flag:
            saved = True
            if self.ckpt is not None:
                saved = self.ckpt.save_durable(
                    step, state, retries=self.save_retries,
                    backoff_s=self.save_backoff_s)
            if saved and self.on_preempt is not None:
                # notify ONLY after the save is durable: the marker means
                # "step N is the resume point" — a failed save must not
                # advertise a step that only exists on the older
                # checkpoint (save_durable already logged the failure).
                try:
                    self.on_preempt(step)
                except Exception:  # noqa: BLE001 — see __init__ docstring
                    pass
            raise StopTraining

    def end(self, state):
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()


class EvalHook(Hook):
    """Periodic evaluation — the reference-era validation-while-training
    pattern (an eval pass between ``mon_sess.run`` steps), as a hook.

    ``eval_step(state, batch) -> metrics`` is a compiled step from
    :func:`dtf_tpu.core.train.make_eval_step`; ``batches()`` returns an
    iterable of host batches for one eval sweep (metrics are averaged);
    ``place_batch`` maps them onto the mesh.
    """

    telemetry_bucket = "eval"

    def __init__(self, eval_step, batches, writer: MetricWriter,
                 every_n: int = 100, *, place_batch=None):
        self.eval_step = eval_step
        self.batches = batches
        self.writer = writer
        self.every_n = every_n
        self.place_batch = place_batch or (lambda b: b)
        self._last_eval_step = None

    def _run(self, step, state):
        totals, n = {}, 0
        for batch in self.batches():
            metrics = self.eval_step(state, self.place_batch(batch))
            for k, v in metrics.items():
                totals[k] = totals.get(k, 0.0) + float(v)
            n += 1
        if n:
            self.writer.write_scalars(step,
                                      {k: v / n for k, v in totals.items()})
        self._last_eval_step = step

    def after_step(self, step, state, metrics):
        if step % self.every_n == 0:
            self._run(step, state)

    def end(self, state):
        # after_step may already have evaluated at the final step; a second
        # sweep would write duplicate scalars and double end-of-run cost.
        if self._last_eval_step != int(state.step):
            self._run(int(state.step), state)


class ProfilerHook(Hook):
    """``tf.profiler``/Timeline equivalent: capture an XPlane trace window.

    Two trigger modes, composable in one hook:

    - **scheduled** (the original): a window of ``num_steps`` opening at
      ``start_step``; ``start_step=None`` disables it.
    - **on-demand** (live-run profiling without a restart): send
      ``trigger_signal`` (e.g. ``SIGUSR1``) to the process, or ``touch``
      ``trigger_file`` — checked at step boundaries every ``check_every``
      steps (an ``os.path.exists`` per check, nothing per step) and
      CONSUMED (unlinked) when it fires, so one touch = one window. The
      next window opens at the following step boundary and runs
      ``num_steps``. Repeatable: touch/kill again after a window closes.

    The signal handler only sets a flag (async-signal-safe, the
    PreemptionHook discipline) and chains nothing — profiling is
    process-local. Construct + ``begin()`` in the main thread when using
    ``trigger_signal`` (CPython's ``signal.signal`` rule); previous
    handlers are restored at ``end()``.

    **Device-time attribution** (``analyze=True``, the default): when a
    window closes, the hook hands its trace dir to the XPlane parser
    (:mod:`dtf_tpu.telemetry.profile`) and writes the per-category
    device-time buckets / overlap efficiency / per-collective provenance
    report to ``<logdir>/device_profile.json`` (also kept as
    ``self.last_profile`` and fed to ``telemetry`` for the RunReport).
    ``hlo_text_fn`` — optional ``() -> str | list[str]`` returning the
    profiled program's OPTIMIZED HLO text, called lazily at parse time —
    enables the ``file:line`` provenance join. The stock launchers do
    NOT pass it (lowering a twin step just for provenance costs a full
    compile); their windows bucket without attribution, and the join
    runs where the HLO is already in hand — ``scripts/bench_profile.py``
    (its own compiled program) or ``python -m dtf_tpu.telemetry report
    --hlo=...`` over the same trace dir. The parse runs on the host
    after the window closed: it adds zero work to traced steps and
    degrades to a reason dict when the proto bindings or per-op events
    are absent.
    """

    telemetry_bucket = "profile"

    def __init__(self, logdir: str, start_step: int | None = 10,
                 num_steps: int = 5, *, trigger_file: str | None = None,
                 trigger_signal: int | None = None, check_every: int = 16,
                 analyze: bool = True, hlo_text_fn=None, telemetry=None,
                 flops_per_step=None):
        self.logdir = logdir
        self.start = start_step
        self.num_steps = num_steps
        self.stop = (start_step + num_steps
                     if start_step is not None else None)
        self.trigger_file = trigger_file
        self.trigger_signal = trigger_signal
        self.check_every = max(1, check_every)
        self.analyze = analyze
        self.hlo_text_fn = hlo_text_fn
        self.telemetry = telemetry
        self.flops_per_step = flops_per_step
        self.last_profile: dict | None = None
        self._active = False
        self._signaled = False
        self._sched_done = start_step is None
        self._prev_handler = None

    def begin(self, state):
        if self.trigger_signal is not None:
            try:
                self._prev_handler = signal.signal(
                    self.trigger_signal, self._on_signal)
            except ValueError:
                # not the main thread: file trigger still works, the
                # signal trigger is simply unavailable here
                self._prev_handler = None

    def _on_signal(self, signum, frame):
        self._signaled = True

    def _triggered(self, step) -> bool:
        if self._signaled:
            self._signaled = False
            return True
        if self.trigger_file and step % self.check_every == 0:
            if os.path.exists(self.trigger_file):
                try:
                    os.unlink(self.trigger_file)   # consume: one touch,
                except OSError:                    # one window
                    pass
                return True
        return False

    def before_step(self, step):
        # non-chief processes must not even POLL the triggers: _triggered
        # consumes the (logdir-shared) trigger file, so a non-chief
        # polling first would eat the chief's window
        if jax.process_index() != 0:
            return
        # `>=` + once-flag, not `==`: an on-demand window open ACROSS the
        # scheduled start must not swallow the scheduled window forever
        # (it covers those steps, so the request is satisfied), and a
        # resume past start_step must not wait for a step that never comes
        sched_due = not self._sched_done and step >= self.start
        if self._active:
            if sched_due:
                self._sched_done = True
            return
        if sched_due or self._triggered(step):
            self._sched_done = self._sched_done or sched_due
            jax.profiler.start_trace(self.logdir)
            self._active = True
            self.stop = step + self.num_steps

    def after_step(self, step, state, metrics):
        if self._active and self.stop is not None and step >= self.stop:
            jax.profiler.stop_trace()
            self._active = False
            self._analyze_window()

    def _analyze_window(self) -> None:
        """Parse the just-closed window's XPlane dump (see class docstring).
        Never raises: a parse failure becomes a ``degraded`` reason in the
        report — profiling must not be able to crash the training run."""
        if not self.analyze:
            return
        try:
            from dtf_tpu.telemetry import profile as profile_mod

            site_map = None
            if self.hlo_text_fn is not None:
                from dtf_tpu.analysis.provenance import profile_site_map

                site_map = profile_site_map(self.hlo_text_fn())
            kw = {}
            if self.flops_per_step and self.telemetry is not None:
                kw = {"model_flops_per_step": self.flops_per_step,
                      "peak_flops": self.telemetry.peak_flops,
                      "n_devices": self.telemetry.n_devices}
            report = profile_mod.parse_logdir(
                self.logdir, site_map=site_map, **kw)
            path = os.path.join(self.logdir, "device_profile.json")
            # atomic: bench_profile and the report CLI read this file
            # from other processes while windows keep closing
            atomic_replace(path, json.dumps(report, indent=1))
        except Exception as e:  # noqa: BLE001 — see docstring
            report = {"degraded": f"profile parse failed: "
                                  f"{type(e).__name__}: {e}"}
        self.last_profile = report
        if self.telemetry is not None:
            self.telemetry.note_device_profile(report)

    def end(self, state):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._analyze_window()
        if self._prev_handler is not None:
            signal.signal(self.trigger_signal, self._prev_handler)
            self._prev_handler = None
