"""Session hooks — successor of tf.train.SessionRunHook and the chief's hook set.

Reference capability replaced (SURVEY.md §3.4): ``MonitoredTrainingSession``
installs ``CheckpointSaverHook``, ``SummarySaverHook``, ``StopAtStepHook``,
``LoggingTensorHook`` on the chief. The same lifecycle — begin / before-step /
after-step / end — is kept so reference users find the familiar shape, but
hooks run on host Python around an async dispatched step, so they cost
nothing on the device timeline unless they block on results.
"""

from __future__ import annotations

import signal
import time
from typing import Any, Mapping

import jax

from dtf_tpu.checkpoint import Checkpointer
from dtf_tpu.metrics import MetricWriter

PyTree = Any


class StopTraining(Exception):
    """Raised by a hook to end the loop (the ``should_stop()`` successor)."""


class Hook:
    def begin(self, state: PyTree) -> None: ...

    def before_step(self, step: int) -> None: ...

    def after_step(self, step: int, state: PyTree,
                   metrics: Mapping[str, jax.Array]) -> None: ...

    def end(self, state: PyTree) -> None: ...


class StopAtStepHook(Hook):
    """``tf.train.StopAtStepHook`` equivalent (last_step semantics)."""

    def __init__(self, last_step: int):
        self.last_step = last_step

    def before_step(self, step):
        # A resumed state may already be at/past last_step; stop before
        # running an extra step (MonitoredSession checks should_stop()
        # before run(), not only after).
        if step >= self.last_step:
            raise StopTraining

    def after_step(self, step, state, metrics):
        if step >= self.last_step:
            raise StopTraining


class LoggingHook(Hook):
    """Step/loss/throughput logging — ``LoggingTensorHook`` + ``print`` path.

    Materializing ``metrics`` blocks on the async step, so this is also the
    loop's backpressure point; every_n trades log freshness for overlap.
    """

    def __init__(self, writer: MetricWriter, every_n: int = 10,
                 lr_schedule=None):
        #: optional optax schedule (or plain float) to surface the current
        #: learning rate next to the loss — the schedule position equals
        #: the global step (one optimizer update per step; grad-accum
        #: applies the accumulated mean gradient in that single update)
        self.writer = writer
        self.every_n = every_n
        self.lr_schedule = lr_schedule
        self._t0 = None
        self._last_logged = None

    def begin(self, state):
        self._t0 = time.perf_counter()
        self._last_logged = int(state.step)

    def after_step(self, step, state, metrics):
        if step % self.every_n:
            return
        now = time.perf_counter()
        steps_done = step - self._last_logged
        sps = steps_done / max(now - self._t0, 1e-9)
        self._t0, self._last_logged = now, step
        scalars = {k: float(v) for k, v in metrics.items()}
        scalars["steps_per_sec"] = sps
        if self.lr_schedule is not None:
            lr = self.lr_schedule
            scalars["lr"] = float(lr(step) if callable(lr) else lr)
        self.writer.write_scalars(step, scalars)

    def end(self, state):
        self.writer.flush()


class CheckpointHook(Hook):
    """``CheckpointSaverHook`` equivalent: periodic async sharded saves,
    final save + barrier at end. Orbax dedupes by save_interval_steps."""

    def __init__(self, ckpt: Checkpointer, every_n: int = 100):
        self.ckpt = ckpt
        self.every_n = every_n

    def after_step(self, step, state, metrics):
        if step % self.every_n == 0:
            self.ckpt.save(step, state)

    def end(self, state):
        self.ckpt.save(int(state.step), state, force=True)
        self.ckpt.wait()


class PreemptionHook(Hook):
    """Graceful-preemption checkpointing: SIGTERM → save → clean stop.

    Cloud TPU / GKE evictions deliver SIGTERM with a grace window before the
    SIGKILL; the reference era's ``_RecoverableSession`` only covered the
    crash side. The handler just sets a flag (async-signal-safe); the loop
    notices at the next step boundary, force-saves the exact current step,
    blocks until the write is durable, and raises :class:`StopTraining` —
    the relaunch then resumes with zero lost steps (vs. up to
    ``checkpoint_every - 1`` lost on a plain kill; that crash path is
    exercised by tests/test_fault_injection.py).

    Multi-host: the save is a COLLECTIVE Orbax write, and the signal lands
    at different instants on different hosts — acting on the local flag
    alone would have hosts calling save() at different steps and
    deadlocking. So under ``jax.process_count() > 1`` the flag is
    OR-allgathered at each step boundary: collectives match in program
    order, so every host evaluates the k-th sync at the same step and they
    all agree to save that step (the cluster manager signals every host of
    an evicted slice, so the OR converges within one step).

    Must be constructed and ``begin()``-run in the main thread (CPython's
    ``signal.signal`` requirement). Restores the previous handlers at
    ``end()`` so short-lived Trainers don't leak handler state.
    """

    def __init__(self, ckpt: Checkpointer, signals=(signal.SIGTERM,),
                 check_every: int = 8):
        #: multi-host flag-sync cadence: the OR-allgather is a device
        #: collective whose result the host blocks on, so syncing every
        #: step would forfeit async-dispatch run-ahead; every ``check_every``
        #: steps bounds the reaction delay (grace windows are ~30 s, steps
        #: are ms–s) while amortizing the barrier. Single-host runs react
        #: at the very next step regardless.
        self.ckpt = ckpt
        self.signals = tuple(signals)
        self.check_every = max(1, check_every)
        self.preempted = False
        self._prev: dict = {}
        self._multiprocess = False

    def begin(self, state):
        self._multiprocess = jax.process_count() > 1
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._on_signal)

    def _on_signal(self, signum, frame):
        self.preempted = True

    def after_step(self, step, state, metrics):
        flag = self.preempted
        if self._multiprocess:
            if step % self.check_every:
                # between sync points even a locally-set flag must wait:
                # acting alone would desync the collective order
                return
            import numpy as np
            from jax.experimental import multihost_utils

            flag = bool(multihost_utils.process_allgather(
                np.asarray([self.preempted])).any())
        if flag:
            self.ckpt.save(step, state, force=True)
            self.ckpt.wait()
            raise StopTraining

    def end(self, state):
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()


class EvalHook(Hook):
    """Periodic evaluation — the reference-era validation-while-training
    pattern (an eval pass between ``mon_sess.run`` steps), as a hook.

    ``eval_step(state, batch) -> metrics`` is a compiled step from
    :func:`dtf_tpu.core.train.make_eval_step`; ``batches()`` returns an
    iterable of host batches for one eval sweep (metrics are averaged);
    ``place_batch`` maps them onto the mesh.
    """

    def __init__(self, eval_step, batches, writer: MetricWriter,
                 every_n: int = 100, *, place_batch=None):
        self.eval_step = eval_step
        self.batches = batches
        self.writer = writer
        self.every_n = every_n
        self.place_batch = place_batch or (lambda b: b)
        self._last_eval_step = None

    def _run(self, step, state):
        totals, n = {}, 0
        for batch in self.batches():
            metrics = self.eval_step(state, self.place_batch(batch))
            for k, v in metrics.items():
                totals[k] = totals.get(k, 0.0) + float(v)
            n += 1
        if n:
            self.writer.write_scalars(step,
                                      {k: v / n for k, v in totals.items()})
        self._last_eval_step = step

    def after_step(self, step, state, metrics):
        if step % self.every_n == 0:
            self._run(step, state)

    def end(self, state):
        # after_step may already have evaluated at the final step; a second
        # sweep would write duplicate scalars and double end-of-run cost.
        if self._last_eval_step != int(state.step):
            self._run(int(state.step), state)


class ProfilerHook(Hook):
    """``tf.profiler``/Timeline equivalent: capture an XPlane trace window."""

    def __init__(self, logdir: str, start_step: int = 10, num_steps: int = 5):
        self.logdir = logdir
        self.start = start_step
        self.stop = start_step + num_steps
        self._active = False

    def before_step(self, step):
        if step == self.start and jax.process_index() == 0:
            jax.profiler.start_trace(self.logdir)
            self._active = True

    def after_step(self, step, state, metrics):
        if self._active and step >= self.stop:
            jax.profiler.stop_trace()
            self._active = False

    def end(self, state):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
