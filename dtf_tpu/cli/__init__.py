"""Flag-compatible launch layer (the reference's CLI contract)."""
