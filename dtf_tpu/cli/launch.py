"""Launch glue: flags → cluster collapse → mesh → trainer pieces.

This is where the reference's L5/L6 (flag parse → ClusterSpec → Server →
ps join / worker build) becomes: parse the same flags, collapse roles,
``jax.distributed`` bootstrap when multi-process, build the mesh, hand the
script a ready (mesh, cluster_info) pair. See SURVEY.md §7 "Hard parts" #1.
"""

from __future__ import annotations

import logging
import sys

import jax

from dtf_tpu.core import dist
from dtf_tpu.core.mesh import MeshConfig, make_mesh, mesh_summary

log = logging.getLogger("dtf_tpu")


def setup(FLAGS):
    """Resolve cluster + mesh from parsed absl FLAGS.

    Returns ``(mesh, info)``. For ``--job_name=ps`` this exits the process
    with status 0 — the TPU-native successor of ``server.join()`` (the PS
    role's state lives sharded on the mesh; the process has nothing to do).

    Multi-worker launches are CHIP-GATED (``dist.initialize_or_fake``):
    true ``jax.distributed.initialize`` on the tpu backend, the fake-hosts
    harness on cpu (this jaxlib refuses cross-process CPU collectives —
    docs/RESILIENCE.md). In fake mode ``--devices_per_host`` sizes each
    host's share of the simulated mesh, so an elastic relaunch with fewer
    workers re-forms a smaller mesh and resumes by resharding.
    """
    info = dist.collapse_cluster_flags(
        ps_hosts=[h for h in FLAGS.ps_hosts.split(",") if h],
        worker_hosts=[h for h in FLAGS.worker_hosts.split(",") if h],
        job_name=FLAGS.job_name,
        task_index=FLAGS.task_index,
    )
    if info.should_exit:
        log.warning("ps role has no work on the %s backend; exiting 0",
                    FLAGS.backend)
        sys.exit(0)
    if not FLAGS.issync:
        log.warning(
            "--issync=0 (async PS SGD) is not reproduced on the TPU backend: "
            "hogwild updates are an anti-pattern under SPMD. Proceeding with "
            "synchronous aggregation (same convergence, no stale gradients).")
    if FLAGS.backend == "cpu":
        # Local-sim path: the test/dev equivalent of a multi-worker cluster.
        jax.config.update("jax_platforms", "cpu")
    info = dist.initialize_or_fake(info, FLAGS.backend)
    devices = None
    dph = getattr(FLAGS, "devices_per_host", 0)
    # cpu only (a real chip's devices are what they are): sizes the
    # simulated cluster — including the 1-worker SURVIVOR relaunch after
    # an elastic shrink, whose mesh must span dph x 1 devices, not every
    # local device.
    if dph and FLAGS.backend == "cpu":
        want = dph * info.num_processes
        have = len(jax.devices())
        if want > have:
            raise ValueError(
                f"--devices_per_host={dph} x {info.num_processes} workers "
                f"= {want} mesh devices, but only {have} simulated devices "
                f"exist (raise --xla_force_host_platform_device_count)")
        devices = jax.devices()[:want]
    mesh = make_mesh(MeshConfig(
        data=FLAGS.mesh_data, seq=FLAGS.mesh_seq, model=FLAGS.mesh_model,
        pipe=FLAGS.mesh_pipe, expert=FLAGS.mesh_expert), devices=devices)
    if info.num_processes > 1:
        from dtf_tpu.core.mesh import assert_host_aligned

        assert_host_aligned(mesh, info.num_processes)
    if info.is_chief:
        log.info("%s | %d process(es), chief=%s fake_hosts=%s",
                 mesh_summary(mesh), info.num_processes, info.is_chief,
                 info.fake_hosts)
    return mesh, info


def host_batches(info, mesh, make_loader):
    """The one data-dispatch for every launch shape.

    ``make_loader(host_index=, host_count=)`` builds one host's loader
    (the kwargs every array loader and ``SyntheticData`` already takes).
    Returns ``(batches, place_batch)`` for the Trainer:

    - single process        → one global loader, default placement;
    - real multi-process    → this process's 1/N loader,
      ``comms.host_local_to_global`` placement (each host contributes its
      addressable shards);
    - fake hosts (cpu sim)  → a ``FakeHostStream`` over ALL N per-host
      loaders + ``comms.fake_hosts_to_global`` placement — the same
      disjoint-rows contract, exercised end to end inside one process.
    """
    from dtf_tpu.core.comms import fake_hosts_to_global, host_local_to_global
    from dtf_tpu.core.mesh import host_views
    from dtf_tpu.data.sharded import FakeHostStream, loaders_for_hosts

    if info.num_processes <= 1:
        return iter(make_loader(host_index=0, host_count=1)), None
    if info.fake_hosts:
        loaders = loaders_for_hosts(make_loader,
                                    host_views(info.num_processes))
        return (iter(FakeHostStream(loaders)),
                lambda hb: fake_hosts_to_global(hb, mesh))
    loader = make_loader(host_index=info.process_id,
                         host_count=info.num_processes)
    return iter(loader), lambda b: host_local_to_global(b, mesh)


def lm_eval_hook(FLAGS, info, mesh, shardings, eval_fn, writer, place_batch,
                 *, kind, mode, vocab_size, batch_shardings=None,
                 telemetry=None):
    """EvalHook for the LM launchers — the one copy of the eval policy.

    Held-out source: ``<data_dir>/val.bin`` when present; a synthetic
    stream at seed+1 ONLY when training itself is synthetic. Training on
    real tokens with no val split returns None (skip eval) with a warning —
    scoring a real model on unrelated synthetic data would masquerade as
    held-out perplexity (same policy as the image path's
    ``detect_image_eval_data``). Sweep = 4 batches. ``batch_shardings``
    must be the same override the train step uses when sequence
    parallelism places batches P('data','seq').
    """
    from dtf_tpu.core import train as tr
    from dtf_tpu.data import formats
    from dtf_tpu.data.synthetic import SyntheticData
    from dtf_tpu.hooks import EvalHook

    eval_data = formats.detect_token_data(
        FLAGS.data_dir, FLAGS.batch_size, FLAGS.seq_len, mode=mode,
        vocab_size=vocab_size, seed=FLAGS.seed, split="val",
        host_index=info.process_id, host_count=info.num_processes)
    if eval_data is not None:
        batches_fn = lambda: (eval_data.batch(i) for i in range(4))  # noqa: E731,E501
    else:
        from dtf_tpu.data.formats import TokenBinData

        if FLAGS.data_dir and TokenBinData.available(FLAGS.data_dir):
            log.warning("no val.bin in %s; skipping held-out eval rather "
                        "than scoring on synthetic data", FLAGS.data_dir)
            return None
        held_out = SyntheticData(
            kind, FLAGS.batch_size, seed=FLAGS.seed + 1,
            seq_len=FLAGS.seq_len, vocab_size=vocab_size,
            host_index=info.process_id, host_count=info.num_processes)
        batches_fn = lambda: (held_out.batch(10_000_000 + i)  # noqa: E731
                              for i in range(4))
    step = tr.make_eval_step(eval_fn, mesh, shardings,
                             batch_shardings=batch_shardings,
                             telemetry=telemetry)
    return EvalHook(step, batches_fn, writer,
                    FLAGS.eval_every or FLAGS.train_steps,
                    place_batch=place_batch)


def profiler_hooks(FLAGS, telemetry=None, flops_per_step=None):
    """[ProfilerHook] from the profiler flags, or [].

    ``--profile_steps`` schedules the classic fixed window; independently,
    ``--profile_on_demand`` (default on) arms the live triggers — SIGUSR1
    or ``touch <logdir>/profile.trigger`` — so a misbehaving run can be
    profiled without restarting with a pre-chosen step window. One hook
    serves both modes (dtf_tpu/hooks.py ProfilerHook docstring).

    Every closed window is parsed into ``<logdir>/profile/
    device_profile.json`` (per-category device-time buckets, comm/compute
    overlap) by the hook's analyze path; ``telemetry`` +
    ``flops_per_step`` additionally put the device-MFU cross-check in the
    RunReport (docs/OBSERVABILITY.md, device-time attribution).
    """
    import os
    import signal as _signal

    scheduled = getattr(FLAGS, "profile_steps", 0)
    on_demand = getattr(FLAGS, "profile_on_demand", False)
    if not scheduled and not on_demand:
        return []

    from dtf_tpu.hooks import ProfilerHook

    return [ProfilerHook(
        os.path.join(FLAGS.logdir, "profile"),
        start_step=FLAGS.profile_start if scheduled else None,
        num_steps=scheduled or 5,
        trigger_file=(os.path.join(FLAGS.logdir, "profile.trigger")
                      if on_demand else None),
        trigger_signal=(getattr(_signal, "SIGUSR1", None)
                        if on_demand else None),
        telemetry=telemetry, flops_per_step=flops_per_step)]


def telemetry_from_flags(FLAGS, info):
    """``--telemetry`` → a configured :class:`dtf_tpu.telemetry.Telemetry`
    (or None). Built on every host — each host keeps its own flight
    recorder (postmortems are per-process facts: the host that hangs is
    the one whose last steps matter) — while :func:`emit_run_report`
    prints only on the chief."""
    if not getattr(FLAGS, "telemetry", False):
        return None
    import os

    import jax

    from dtf_tpu.telemetry import Telemetry

    min_stall = getattr(FLAGS, "telemetry_min_stall_s", 60.0)
    out_dir = os.path.join(FLAGS.logdir, "telemetry")
    if info.num_processes > 1:
        out_dir = os.path.join(out_dir, f"p{info.process_id}")
    return Telemetry(
        out_dir=out_dir,
        keep_steps=getattr(FLAGS, "telemetry_keep_steps", 64),
        stall_factor=getattr(FLAGS, "telemetry_stall_factor", 10.0),
        min_stall_s=min_stall or 60.0,
        watchdog=bool(min_stall),
        # global-batch FLOPs vs ALL chips' peak (mfu would otherwise be
        # overstated by exactly the device count on any multi-chip mesh)
        n_devices=jax.device_count())


def emit_run_report(tel, info, extra=None):
    """Finish the run's telemetry and print THE one RunReport JSON line
    (bench.py idiom; chief only). Returns the report dict (all hosts)."""
    if tel is None:
        return None
    import json

    report = tel.finish(extra)
    if info.is_chief:
        print(json.dumps(report))
    return report
