"""The reference's flag surface, kept launch-compatible (SURVEY.md §5.6).

The reference defines ``tf.app.flags`` globals (``ps_hosts``, ``worker_hosts``,
``job_name``, ``task_index``, ``issync``, data/lr/batch/steps) and runs via
``tf.app.run``. The contract here (BASELINE north_star): "the existing run
scripts launch unchanged with ``--backend=tpu``". Same names, same comma
separated host lists; on the TPU backend the ps/worker flags collapse into
mesh + process identity (:func:`dtf_tpu.core.dist.collapse_cluster_flags`).
"""

from __future__ import annotations

from typing import NamedTuple

from absl import flags

FLAGS = flags.FLAGS


def define_cluster_flags():
    flags.DEFINE_string("ps_hosts", "", "comma-separated ps host:port list "
                        "(accepted for compatibility; collapsed on tpu)")
    flags.DEFINE_string("worker_hosts", "", "comma-separated worker host:port "
                        "list; becomes the process world on tpu")
    flags.DEFINE_string("job_name", "worker", "'ps' or 'worker'; ps exits "
                        "immediately on the tpu backend")
    flags.DEFINE_integer("task_index", 0, "index within the job")
    flags.DEFINE_boolean("issync", True, "sync gradient aggregation. The tpu "
                         "backend is always synchronous; issync=False warns "
                         "(async PS is an anti-pattern on TPU) and proceeds "
                         "synchronously")
    flags.DEFINE_string("backend", "tpu", "tpu | cpu (cpu = simulated mesh "
                        "for local testing)")
    flags.DEFINE_integer(
        "devices_per_host", 0,
        "fake-hosts harness (cpu multi-worker launches): each host's share "
        "of the simulated mesh — the cluster mesh spans devices_per_host x "
        "n_workers devices, so a relaunch with fewer workers re-forms a "
        "SMALLER mesh and resumes by resharding (docs/RESILIENCE.md). "
        "0 = all local devices (single-process behavior).")


def define_mesh_flags():
    flags.DEFINE_integer("mesh_data", -1, "data-parallel axis size (-1: all "
                         "remaining devices)")
    flags.DEFINE_integer("mesh_seq", 1, "sequence/context-parallel axis size")
    flags.DEFINE_integer("mesh_model", 1, "tensor-parallel axis size")
    flags.DEFINE_integer("mesh_pipe", 1, "pipeline-parallel axis size")
    flags.DEFINE_integer("mesh_expert", 1, "expert-parallel (MoE) axis size")


def define_train_flags(batch_size=64, learning_rate=0.01, train_steps=1000,
                       lr_schedule="constant"):
    flags.DEFINE_string("data_dir", "", "dataset directory (empty: synthetic)")
    flags.DEFINE_string("logdir", "/tmp/dtf_tpu_logs", "checkpoint/summary dir")
    flags.DEFINE_integer("batch_size", batch_size, "GLOBAL batch size (the "
                         "reference's per-worker batch × num workers)")
    flags.DEFINE_float("learning_rate", learning_rate, "learning rate")
    flags.DEFINE_integer("train_steps", train_steps, "stop at this global step")
    flags.DEFINE_integer("checkpoint_every", 200, "steps between saves")
    flags.DEFINE_integer("log_every", 10, "steps between metric logs")
    flags.DEFINE_integer("grad_accum", 1, "gradient-accumulation microbatches")
    flags.DEFINE_boolean("grad_shard", False, "with --grad_accum>1: ZeRO-1 "
                         "weight-update sharding for the accumulator — "
                         "microbatch gradients reduce-scatter over the data "
                         "axis into 1/N f32 shards, the optimizer update "
                         "runs on the shard, params all-gather once per "
                         "step (docs/ZERO.md). Needs a pure-GSPMD loss "
                         "(dense attention; no pallas/ring/overlap "
                         "kernels); falls back to the replicated "
                         "accumulator with a warning otherwise")
    flags.DEFINE_float("clip_grad_norm", 0.0, "clip gradients to this global "
                       "norm before the optimizer update (0 = off)")
    flags.DEFINE_string("lr_schedule", lr_schedule, "constant | linear | "
                        "cosine: LR decay after warmup, over the remaining "
                        "train_steps (see make_lr_schedule)")
    flags.DEFINE_integer("warmup_steps", -1, "linear LR warmup 0 -> "
                         "learning_rate over this many steps; -1 = auto "
                         "(min(1000, train_steps/10 + 1) for decaying "
                         "schedules, 0 for constant)")
    flags.DEFINE_float("lr_min_ratio", 0.0, "decay floor as a fraction of "
                       "--learning_rate (cosine alpha / linear end value)")
    flags.DEFINE_string("optimizer", "", "override the script's recipe "
                        "optimizer: sgd | momentum | adam | adamw | lamb | "
                        "adafactor (empty = keep the recipe default). lamb "
                        "is the BERT-at-scale recipe; adafactor is the "
                        "memory-lean TPU option (factored second moments)")
    flags.DEFINE_float("weight_decay", -1.0, "weight decay for "
                       "adamw/lamb overrides (-1 = optimizer default)")
    flags.DEFINE_integer("seed", 0, "PRNG seed")
    flags.DEFINE_integer("prefetch_depth", 2, "device-input prefetch "
                         "depth: batch N+1's host->device transfer "
                         "dispatches while step N computes "
                         "(dtf_tpu/data/prefetch.py double buffer; 1 = "
                         "off). With a mixture stream this also sizes "
                         "the bounded background producer queue "
                         "(docs/DATA.md)")
    flags.DEFINE_integer("profile_steps", 0, "capture an XPlane profiler "
                         "trace spanning this many steps (0 = off); written "
                         "to <logdir>/profile")
    flags.DEFINE_integer("profile_start", 10, "step at which the profiler "
                         "trace window opens")
    flags.DEFINE_boolean("profile_on_demand", True, "accept live-run "
                         "profile requests: SIGUSR1 or `touch "
                         "<logdir>/profile.trigger` opens a "
                         "--profile_steps-wide (default 5) trace window at "
                         "the next step boundary, no restart needed")
    flags.DEFINE_boolean("telemetry", False, "run-wide observability "
                         "(docs/OBSERVABILITY.md): step-phase spans "
                         "(data_wait/h2d/dispatch/hooks p50/p99), MFU + "
                         "goodput accounting, a train-step compile fence, "
                         "and a crash flight recorder dumping the last "
                         "steps to <logdir>/telemetry/postmortem.json on "
                         "crash/stall/SIGTERM. One RunReport JSON line "
                         "prints at exit. Host-side timers only: adds zero "
                         "blocking device readbacks to the training loop")
    flags.DEFINE_integer("telemetry_keep_steps", 64, "flight-recorder ring "
                         "size: step records kept for the postmortem")
    flags.DEFINE_float("telemetry_min_stall_s", 60.0, "stall watchdog "
                       "floor: no step completion within max(this, "
                       "factor x p99 step time) dumps a stall "
                       "postmortem (0 disables the watchdog thread)")
    flags.DEFINE_float("telemetry_stall_factor", 10.0, "stall watchdog "
                       "multiple of the p99 recent step time (set the "
                       "floor above the longest expected hook pause — eval "
                       "sweep / checkpoint wait)")


def make_lr_schedule(FLAGS):
    """--learning_rate/--lr_schedule/--warmup_steps/--lr_min_ratio -> an
    optax schedule (or a plain float when constant with no warmup — the
    zero-overhead path).

    The schedule is what the BERT/GPT pretraining recipes assume (linear
    warmup then decay); it composes with the rest of the optimizer story
    because the step counter lives in the optax state: grad-accum applies
    the update ONCE per global step (the accumulated mean gradient, so the
    count advances per step, not per microbatch), and ZeRO-1 keeps scalar
    state leaves replicated (core/sharding.py zero1 specs), so every shard
    sees the same schedule position. Both are regression-tested.
    """
    import optax

    lr = FLAGS.learning_rate
    kind = getattr(FLAGS, "lr_schedule", "constant")
    warmup = getattr(FLAGS, "warmup_steps", -1)
    ratio = getattr(FLAGS, "lr_min_ratio", 0.0)
    if warmup < 0:
        warmup = (0 if kind == "constant"
                  else min(1000, FLAGS.train_steps // 10 + 1))
    if kind == "constant" and warmup == 0:
        return lr
    decay = max(FLAGS.train_steps - warmup, 1)
    if kind == "constant":
        body = optax.constant_schedule(lr)
    elif kind == "linear":
        body = optax.linear_schedule(lr, lr * ratio, decay)
    elif kind == "cosine":
        body = optax.cosine_decay_schedule(lr, decay, alpha=ratio)
    else:
        raise ValueError(f"unknown --lr_schedule={kind!r} "
                         "(constant | linear | cosine)")
    if warmup == 0:
        return body
    return optax.join_schedules(
        [optax.linear_schedule(0.0, lr, warmup), body], [warmup])


def make_optimizer(FLAGS, recipe, recipe_uses_wd=False):
    """The script's full optimizer story in one call: LR schedule →
    ``--optimizer`` override (or the script's recipe default) →
    :func:`wrap_optimizer` shaping.

    ``recipe``: ``callable(schedule) -> optax.GradientTransformation`` —
    the launcher's era-faithful default (e.g. adamw(wd=0.01) for BERT,
    nesterov SGD for ResNet), used when ``--optimizer`` is empty so
    existing launch commands keep their exact numerics.
    ``recipe_uses_wd=True`` declares that the recipe itself consumes
    ``--weight_decay`` (BERT/GPT pass it into their adamw; ResNet maps
    it to loss-side L2); otherwise an explicitly-set ``--weight_decay``
    that nothing would consume raises instead of silently training
    without it. Every named override composes with ZeRO-1 (param-shaped state shards via
    ``zero1_opt_specs``; adafactor's rank-reduced factored moments fall
    back to a fresh data-axis spec — see ``_zero1_leaf_spec``),
    grad-accum (one update per global step) and the LR schedule (step
    count lives in optax state); regression-tested in
    tests/test_optimizers.py.
    """
    import optax

    sched = make_lr_schedule(FLAGS)
    name = (getattr(FLAGS, "optimizer", "") or "").lower()
    wd = getattr(FLAGS, "weight_decay", -1.0)

    def decay(default):
        return wd if wd >= 0.0 else default

    def reject_wd():
        # A silently-dropped hyperparameter is worse than an error: a
        # --weight_decay sweep over an optimizer that ignores it would
        # train N identical runs.
        if wd >= 0.0:
            raise ValueError(
                f"--weight_decay has no effect with "
                f"--optimizer={name or '<recipe default>'}; use "
                "adamw | lamb | adafactor (or a launcher whose recipe "
                "consumes it)")

    if not name:
        if not recipe_uses_wd:
            reject_wd()
        tx = recipe(sched)
    elif name == "sgd":
        reject_wd()
        tx = optax.sgd(sched)
    elif name == "momentum":
        reject_wd()
        tx = optax.sgd(sched, momentum=0.9, nesterov=True)
    elif name == "adam":
        reject_wd()
        tx = optax.adam(sched)
    elif name == "adamw":
        tx = optax.adamw(sched, weight_decay=decay(1e-4))   # optax default
    elif name == "lamb":
        tx = optax.lamb(sched, weight_decay=decay(0.0))     # optax default
    elif name == "adafactor":
        # adafactor consumes the schedule directly (it scales updates by
        # its own RMS rule); decay rides optax's weight_decay_rate arg
        tx = optax.adafactor(
            learning_rate=sched,
            weight_decay_rate=(wd if wd >= 0.0 else None))
    else:
        raise ValueError(
            f"unknown --optimizer={name!r} "
            "(sgd | momentum | adam | adamw | lamb | adafactor)")
    return wrap_optimizer(tx, FLAGS)


#: optimizer families that apply weight decay themselves (decoupled decay);
#: launchers whose recipes express regularization as loss-side L2 must drop
#: the L2 when one of these is selected — and route the decay here instead.
DECOUPLED_DECAY_OPTIMIZERS = ("adamw", "lamb", "adafactor")


def resolve_loss_l2(FLAGS, recipe_l2: float):
    """Loss-side L2 coefficient for launchers with an L2-based recipe.

    When ``--optimizer`` picks a decoupled-decay family the loss-side L2
    must be dropped (both would fire), so this returns 0.0 — but if
    ``--weight_decay`` was left unset, the optimizer's own default decay
    may be 0.0 (lamb) or None (adafactor), and the run would silently
    train with NO regularization at all (ADVICE r5 #2). In that case the
    recipe's coefficient is promoted into ``--weight_decay`` (consumed by
    :func:`make_optimizer`) with a warning, so the recipe's regularization
    strength survives the optimizer swap.
    """
    name = (getattr(FLAGS, "optimizer", "") or "").lower()
    if name not in DECOUPLED_DECAY_OPTIMIZERS:
        return FLAGS.weight_decay if FLAGS.weight_decay >= 0 else recipe_l2
    if FLAGS.weight_decay < 0:
        from absl import logging as absl_logging

        FLAGS.weight_decay = recipe_l2
        absl_logging.warning(
            "--optimizer=%s drops the recipe's loss-side L2; defaulting "
            "--weight_decay to the recipe's %g (decoupled decay). Pass "
            "--weight_decay explicitly to override.", name, recipe_l2)
    return 0.0


#: decode-config fields the checkpoint manifest is authoritative for: a
#: hand-matched mismatch on any of these silently garbles decode (wrong
#: head count reads the cache at the wrong stride — no shape error).
DECODE_MANIFEST_FIELDS = ("size", "kv_heads", "attn_window",
                          "attn_global_every")


def resolve_decode_config(FLAGS, manifest, *, max_len=None,
                          kv_page_size=None):
    """Merge the checkpoint's ``model_config.json`` manifest into the
    serving flags (``generate_gpt.py`` / ``serve_gpt.py``).

    Manifest present: its architecture fields WIN — an explicitly passed
    flag that contradicts it raises (the mismatch used to garble decode
    silently), a matching or unset flag just follows it. No manifest (old
    checkpoint): flags pass through untouched, exactly the old contract.
    ``kv_cache_dtype`` is a serving-side choice, not an architecture fact,
    so the flag always wins and the manifest only supplies a default —
    but the CHOICE is validated here against the manifest's architecture
    (head dim) and the serving shape (``max_len``/``kv_page_size``), so an
    illegal combination fails at flag resolution with a usable message
    instead of deep inside the engine's AOT build.
    Raises ValueError — launchers convert to their UsageError.
    """
    out = {f: getattr(FLAGS, f) for f in DECODE_MANIFEST_FIELDS}
    out["kv_cache_dtype"] = getattr(FLAGS, "kv_cache_dtype", "")
    if manifest is not None:
        if int(manifest.get("moe_every", 0) or 0):
            raise ValueError(
                f"checkpoint was trained with moe_every="
                f"{manifest['moe_every']}; the decode stack has no MoE "
                "path — serving a Switch-MoE checkpoint would silently "
                "drop the expert weights")
        for f in DECODE_MANIFEST_FIELDS:
            if f not in manifest:
                continue
            if FLAGS[f].present and getattr(FLAGS, f) != manifest[f]:
                raise ValueError(
                    f"--{f}={getattr(FLAGS, f)!r} contradicts the "
                    f"checkpoint manifest ({manifest[f]!r}); drop the "
                    "flag — the manifest written by the training launcher "
                    "is authoritative")
            out[f] = manifest[f]
        if (not FLAGS["kv_cache_dtype"].present
                and "kv_cache_dtype" in manifest):
            out["kv_cache_dtype"] = manifest["kv_cache_dtype"]
    _validate_kv_cache_dtype(out["kv_cache_dtype"], manifest,
                             max_len=max_len, kv_page_size=kv_page_size)
    return out


def _validate_kv_cache_dtype(dtype: str, manifest, *, max_len=None,
                             kv_page_size=None) -> None:
    """The serving-side KV choices, checked where the error is cheap.

    Everything here WOULD otherwise surface as an opaque trace/compile
    error inside ``DecodeEngine``'s AOT build (or, worse, garbled decode):
    an unknown dtype string, an int8 cache on an architecture whose head
    dim breaks the rope-pair/scale layout, or a page size that does not
    divide the per-slot cache length (a page window crossing the cache end
    cannot be copied fixed-shape).
    """
    if dtype not in ("", "int8"):
        raise ValueError(
            f"kv_cache_dtype={dtype!r} must be '' (store at model dtype) "
            "or 'int8'")
    if kv_page_size is not None and kv_page_size:
        if kv_page_size < 1:
            raise ValueError(f"kv_page_size={kv_page_size} must be >= 1")
        if max_len is not None and max_len % kv_page_size:
            raise ValueError(
                f"kv_page_size={kv_page_size} does not divide the per-slot "
                f"cache length max_len={max_len}; pick a page size that "
                "tiles the cache (pages are fixed-shape copies)")
    if dtype == "int8" and manifest is not None:
        d_model = int(manifest.get("d_model", 0) or 0)
        heads = int(manifest.get("heads", 0) or 0)
        if d_model and heads:
            d_head = d_model // heads
            if d_head % 2:
                raise ValueError(
                    f"kv_cache_dtype=int8 needs an even head dim (rope "
                    f"pairs lanes); manifest says d_model={d_model} / "
                    f"heads={heads} -> d_head={d_head}")


def resolve_grad_shard(FLAGS, mesh, *, blockers=()):
    """``--grad_shard`` viability — the safe-fallback gate (docs/ZERO.md).

    The sharded accumulator needs a real data axis, real accumulation, and
    a pure-GSPMD loss: the shard_map'd kernels (ring/zigzag/halo
    attention, flash, the Pallas fused CE, the collective-matmul overlap,
    pipelined stages) pin their own batch-over-data layouts, which the
    per-shard-group vmap cannot nest inside — those would fail at trace
    time deep inside a kernel. Launchers pass the kernel facts they know
    as ``blockers``; this returns the effective setting, WARNING on
    fallback instead of crashing.
    """
    from absl import logging as absl_logging

    if not getattr(FLAGS, "grad_shard", False):
        return False
    reasons = list(blockers)
    if getattr(FLAGS, "grad_accum", 1) <= 1:
        reasons.append("--grad_accum<=1 (no accumulator to shard)")
    if mesh.shape.get("data", 1) <= 1:
        reasons.append("data axis is 1 (nothing to reduce-scatter over)")
    if reasons:
        absl_logging.warning(
            "--grad_shard falls back to the replicated accumulator: %s",
            "; ".join(reasons))
        return False
    return True


#: v5e HBM per chip; the loss-path picker budgets against a fraction of it
#: because params + optimizer state + activations share the pool.
HBM_BYTES_PER_CHIP = 16e9
#: monolithic [B,T,V] f32 logits + their cotangent must fit inside this
#: fraction of HBM to pick the fast path. Calibrated against the on-chip
#: map (PERF.md §0c): GPT-2-small b8 s1024 (3.3 GB) fits and runs 9 MFU
#: points faster unchunked; b16 (6.6 GB) is where throughput falls over.
LOGITS_HBM_FRACTION = 0.25
#: the token-chunk width the sweep banked as the fast bounded-memory shape
#: (one full-vocab MXU matmul per block — PERF.md §0c).
AUTO_LOSS_CHUNK_TOKENS = 4096


class LmLossPath(NamedTuple):
    """The resolved LM loss path (``resolve_lm_loss``). NamedTuple so
    launchers destructure the chunk fields positionally where the old
    2-tuple contract did, with the pallas path and winner provenance
    riding behind."""

    chunk_vocab: int
    chunk_tokens: int
    pallas: bool = False
    source: str = "heuristic"


def resolve_lm_loss(FLAGS, *, batch: int, seq_len: int, vocab_size: int,
                    mesh_shape=None, hbm_bytes: float = HBM_BYTES_PER_CHIP):
    """Pick the LM loss path: HBM estimate + the kernel-tune winners.

    The vocab-chunked loss is a MEMORY lever, not a speed lever: it costs
    ~9 MFU points on GPT and ~5 on BERT versus the monolithic [B,T,V]
    matmul+CE that XLA fuses (PERF.md §0c). So: when no fused-loss flag
    is set and the full logits plus their cotangent fit comfortably per
    device, keep the monolithic path; when they don't, take the banked
    loss-path winner from the kernel-tune cache
    (:func:`dtf_tpu.tune.resolver.lm_loss_winner` — seeded from the
    on-chip BENCH_LM_SWEEP rows, refreshed by ``bench_tune.py``),
    defaulting to the token-chunked fused CE — one full-vocab MXU
    matmul per block, the faster chunking axis — never the vocab scan.

    EXPLICIT flags always win, but warn when they force a
    measured-slower path: any fused flag on a fitting config (paying
    ~9 MFU points for memory it doesn't need), and ``--loss_chunk_vocab``
    on a non-fitting config where the banked winner is a different
    bounded-memory path.

    Returns :class:`LmLossPath`. TP/pipe restrictions stay here: fused
    losses don't compose with a vocab-sharded head or the pipelined
    loss, so under ``mesh_model > 1`` / ``mesh_pipe > 1`` the monolithic
    path is the only legal one (the launchers additionally reject
    explicit fused flags there).
    """
    from absl import logging as absl_logging

    from dtf_tpu.tune import resolver as tune_resolver

    mesh_shape = mesh_shape or {}
    lchunk = getattr(FLAGS, "loss_chunk_vocab", 0)
    tchunk = getattr(FLAGS, "loss_chunk_tokens", 0)
    lpallas = getattr(FLAGS, "loss_pallas", False)
    # per-device token share: logits shard over the data and seq axes
    shards = max(mesh_shape.get("data", 1), 1) * max(
        mesh_shape.get("seq", 1), 1)
    # f32 logits + cotangent live simultaneously through the backward
    est = 2 * (batch * seq_len / shards) * vocab_size * 4
    fits = est <= LOGITS_HBM_FRACTION * hbm_bytes
    n_devices = 1
    for v in mesh_shape.values():
        n_devices *= max(int(v), 1)
    winner = tune_resolver.lm_loss_winner(
        fits=fits, vocab=vocab_size, seq=seq_len, batch=batch,
        n_devices=n_devices, backend=None)
    if lchunk or tchunk or lpallas:
        which = ("--loss_chunk_vocab" if lchunk else
                 "--loss_chunk_tokens" if tchunk else "--loss_pallas")
        if fits:
            absl_logging.warning(
                "%s forces a fused LM loss but the monolithic [B,T,V] "
                "logits fit (est %.2f GB/device of %.0f GB HBM): the "
                "chunked path costs ~9 GPT MFU points (PERF.md 0c) — "
                "drop the flag to let the HBM estimate pick", which,
                est / 1e9, hbm_bytes / 1e9)
        elif lchunk and (winner is None or winner.path != "chunk_vocab"):
            absl_logging.warning(
                "--loss_chunk_vocab forces the measured-slower chunking "
                "axis (the serialized vocab scan costs ~9 GPT MFU "
                "points, PERF.md 0c); the banked winner here is %s (%s) "
                "— drop the flag to follow it",
                winner.path if winner else "the token-chunked fused CE",
                winner.source if winner else "PERF.md 0b chunk-axis "
                "ordering")
        return LmLossPath(lchunk, tchunk, lpallas, source="explicit")
    if (mesh_shape.get("model", 1) > 1 or mesh_shape.get("pipe", 1) > 1):
        # fused losses don't compose with a vocab-sharded head / the
        # pipelined loss; the monolithic path is the only legal one here
        return LmLossPath(0, 0, source="tp/pipe mesh: monolithic only")
    if fits:
        if winner is not None and winner.path != "monolithic":
            # a measured bounded-memory path BEAT monolithic at a
            # fitting shape — honor the data over the heuristic
            return _loss_path_from_winner(winner)
        return LmLossPath(0, 0, source="monolithic logits fit (est "
                          f"{est / 1e9:.2f} GB/device)")
    if winner is not None and winner.path in ("chunk_tokens",
                                              "chunk_vocab", "pallas"):
        # a monolithic winner is NOT honored here: the estimate says the
        # logits don't fit, and a banked mono row from a smaller shape
        # must not talk a bigger one into an OOM.
        absl_logging.warning(
            "monolithic [B,T,V] logits estimated at %.2f GB/device "
            "(> %d%% of %.0f GB HBM): taking the banked loss-path "
            "winner %s (%s); pass an explicit fused-loss flag to "
            "override", est / 1e9, int(LOGITS_HBM_FRACTION * 100),
            hbm_bytes / 1e9, winner.path, winner.source)
        return _loss_path_from_winner(winner)
    absl_logging.warning(
        "monolithic [B,T,V] logits estimated at %.2f GB/device (> %d%% of "
        "%.0f GB HBM): auto-selecting the token-chunked fused loss "
        "(chunk=%d); pass --loss_chunk_tokens/--loss_chunk_vocab to "
        "override", est / 1e9, int(LOGITS_HBM_FRACTION * 100),
        hbm_bytes / 1e9, AUTO_LOSS_CHUNK_TOKENS)
    return LmLossPath(0, AUTO_LOSS_CHUNK_TOKENS,
                      source="HBM heuristic (no banked winner)")


def _loss_path_from_winner(winner) -> "LmLossPath":
    if winner.path == "chunk_vocab":
        return LmLossPath(winner.chunk or 8192, 0, source=winner.source)
    if winner.path == "chunk_tokens":
        return LmLossPath(0, winner.chunk or AUTO_LOSS_CHUNK_TOKENS,
                          source=winner.source)
    if winner.path == "pallas":
        return LmLossPath(0, 0, pallas=True, source=winner.source)
    return LmLossPath(0, 0, source=winner.source)


def wrap_optimizer(tx, FLAGS):
    """Apply the optimizer-shaping train flags to a base optax transform.

    Today that is ``--clip_grad_norm`` (global-norm clipping BEFORE the
    update, the standard transformer-training guard). Clipping composes
    correctly with grad-accum (it sees the accumulated mean gradient) and
    ZeRO-1 (optax transforms are pointwise over the sharded tree; the
    global norm is computed with psum'd full gradients before sharding).
    """
    import optax

    clip = getattr(FLAGS, "clip_grad_norm", 0.0)
    if clip and clip > 0.0:
        return optax.chain(optax.clip_by_global_norm(clip), tx)
    return tx
