"""Parameter-placement rules — the TPU-native successor of
``tf.train.replica_device_setter``.

Reference capability replaced (SURVEY.md §1 L4): the reference pins every
variable to a PS task with ``tf.device(replica_device_setter(...))``, which
round-robins variables across ``/job:ps`` (TF ``device_setter.py``
``_RoundRobinStrategy``). Here placement is declarative: a small rulebook of
``(path regex → PartitionSpec)`` maps each parameter to mesh axes, and GSPMD
materializes the layout. Round-robin across PS hosts becomes row/column
sharding across the mesh.

Also implements ZeRO-1 optimizer-state sharding (BASELINE config 4): the
optimizer state is sharded over the ``data`` axis (per "Automatic
Cross-Replica Sharding of Weight Update", PAPERS.md) — under GSPMD this turns
the weight update into reduce-scatter + sharded-update + all-gather
automatically.
"""

from __future__ import annotations

import re
from typing import Any, NamedTuple, Sequence

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PyTree = Any
#: A rule: (regex matched against "/"-joined param path, PartitionSpec).
Rule = tuple[str, P]

REPLICATED = P()


def path_str(path) -> str:
    """'/'-joined key path for a pytree leaf (flax param dicts → 'layer/kernel')."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def spec_for(path: str, rules: Sequence[Rule], default: P = REPLICATED) -> P:
    """First-match-wins lookup of a PartitionSpec for a param path."""
    for pattern, spec in rules:
        if re.search(pattern, path):
            return spec
    return default


def tree_specs(tree: PyTree, rules: Sequence[Rule],
               default: P = REPLICATED) -> PyTree:
    """PartitionSpec pytree for ``tree`` (params) under ``rules``."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: spec_for(path_str(path), rules, default), tree)


class LeafMatch(NamedTuple):
    """One leaf's resolution against a rulebook (see :func:`rule_matches`)."""

    path: str
    shape: tuple[int, ...]
    dtype: Any
    rule_index: int | None   # winning rule (first match), None = default
    spec: P


def rule_matches(tree: PyTree, rules: Sequence[Rule],
                 default: P = REPLICATED
                 ) -> tuple[list[LeafMatch], list[int], list[int]]:
    """Full first-match-wins resolution trace, for static analysis.

    Returns ``(leaves, raw_hits, wins)`` where ``raw_hits[i]`` counts leaf
    paths rule ``i``'s regex matches at all and ``wins[i]`` counts leaves it
    actually places (i.e. no earlier rule matched).  A rule with
    ``raw_hits == 0`` is dead; one with hits but ``wins == 0`` is shadowed.
    This is the introspection surface ``dtf_tpu.analysis.specs`` builds on —
    the matching semantics stay defined in one place (:func:`spec_for`).
    """
    raw_hits = [0] * len(rules)
    wins = [0] * len(rules)
    leaves: list[LeafMatch] = []

    def visit(path, leaf):
        p = path_str(path)
        winner = None
        for i, (pattern, spec) in enumerate(rules):
            if re.search(pattern, p):
                raw_hits[i] += 1
                if winner is None:
                    winner = (i, spec)
        if winner is not None:
            wins[winner[0]] += 1
        spec = winner[1] if winner is not None else default
        leaves.append(LeafMatch(p, tuple(getattr(leaf, "shape", ())),
                                getattr(leaf, "dtype", None),
                                winner[0] if winner is not None else None,
                                spec))
        return leaf

    jax.tree_util.tree_map_with_path(visit, tree)
    return leaves, raw_hits, wins


def tree_shardings(tree: PyTree, mesh: Mesh, rules: Sequence[Rule] = (),
                   default: P = REPLICATED) -> PyTree:
    return jax.tree.map(lambda s: NamedSharding(mesh, s),
                        tree_specs(tree, rules, default))


def shard_tree(tree: PyTree, mesh: Mesh, rules: Sequence[Rule] = (),
               default: P = REPLICATED) -> PyTree:
    """device_put a pytree according to rules (the replica_device_setter moment)."""
    return jax.tree.map(jax.device_put, tree,
                        tree_shardings(tree, mesh, rules, default))


# ---------------------------------------------------------------------------
# ZeRO-1: optimizer-state sharding over the data axis.
# ---------------------------------------------------------------------------

def _mirrors_param(shape: tuple[int, ...],
                   param_shape: tuple[int, ...] | None) -> bool:
    """Does a state leaf have the param's own layout (adam mu/nu, momentum)?
    adafactor's factored second moments are rank-reduced or placeholder-
    shaped ((d0,) / (1,) for a 2-D param; (1,) for a 1-D one), and the
    param's PartitionSpec must NOT apply to those — a P("model") bias spec
    on a (1,) placeholder is an invalid sharding."""
    return param_shape is None or tuple(shape) == tuple(param_shape)


def _zero1_leaf_spec(param_spec: P, shape: tuple[int, ...], data_size: int,
                     axis: str, param_shape: tuple[int, ...] | None = None
                     ) -> P:
    """Extend a param's spec by sharding its first free divisible dim over
    ``axis``. Scalars / indivisible leaves stay at the param's own spec."""
    if not _mirrors_param(shape, param_shape) or len(param_spec) > len(shape):
        # State leaf does not mirror the param's layout — start fresh and
        # let the data-axis pass below shard the leaf if a dim divides.
        spec = [None] * len(shape)
    else:
        spec = list(param_spec) + [None] * (len(shape) - len(param_spec))
    used = {a for s in spec for a in ((s,) if isinstance(s, str) else (s or ()))}
    if data_size > 1 and axis not in used:
        for i, (s, dim) in enumerate(zip(spec, shape)):
            if s is None and dim % data_size == 0 and dim >= data_size:
                spec[i] = axis
                break
    return P(*spec)


def zero1_param_shard_specs(params: PyTree, param_specs: PyTree, mesh: Mesh,
                            axis: str = "data") -> PyTree:
    """Per-param ZeRO-1 *shard* layouts, paired with :func:`zero1_opt_specs`.

    Each param's own spec extended by an ``axis`` shard on its first free
    divisible dim — exactly the placement :func:`zero1_opt_specs` gives the
    param-shaped optimizer moments, so a gradient accumulated in this layout
    lines up shard-for-shard with the optimizer state it feeds. This is the
    layout ``make_train_step(grad_shard=True)`` reduce-scatters microbatch
    gradients into and runs the optimizer update in (docs/ZERO.md). Leaves
    with no free divisible dim keep the param's own spec — the safe per-leaf
    fallback to the replicated accumulator.
    """
    data_size = mesh.shape.get(axis, 1)
    return jax.tree.map(
        lambda p, spec: _zero1_leaf_spec(
            spec, tuple(p.shape), data_size, axis,
            param_shape=tuple(p.shape)),
        params, param_specs)


def zero1_opt_specs(tx: optax.GradientTransformation, params: PyTree,
                    param_specs: PyTree, mesh: Mesh,
                    axis: str = "data") -> PyTree:
    """PartitionSpec tree for ``tx.init(params)`` with ZeRO-1 sharding.

    Param-shaped leaves (adam mu/nu, momentum, ...) get the param's spec plus
    a ``data``-axis shard on their first free dimension; non-param leaves
    (step counts) are replicated. This is the successor of the reference's
    PS-resident optimizer slots: state lives sharded instead of remote.
    """
    data_size = mesh.shape.get(axis, 1)
    abstract_state = jax.eval_shape(tx.init, params)

    def leaf_spec(state_leaf, spec, param):
        return _zero1_leaf_spec(spec, state_leaf.shape, data_size, axis,
                                param_shape=param.shape)

    return optax.tree_map_params(
        tx, leaf_spec, abstract_state, param_specs,
        jax.eval_shape(lambda p: p, params),
        transform_non_params=lambda _: REPLICATED)


def opt_specs_like_params(tx: optax.GradientTransformation, params: PyTree,
                          param_specs: PyTree) -> PyTree:
    """Optimizer-state specs mirroring the params' specs (no ZeRO).

    Only leaves that actually have the param's shape take its spec;
    rank-reduced / placeholder leaves (adafactor's factored moments) are
    replicated — the param's spec would be an invalid sharding for them.
    """
    abstract_state = jax.eval_shape(tx.init, params)
    return optax.tree_map_params(
        tx,
        lambda leaf, spec, param: (
            spec if _mirrors_param(leaf.shape, param.shape) else REPLICATED),
        abstract_state, param_specs, jax.eval_shape(lambda p: p, params),
        transform_non_params=lambda _: REPLICATED)
