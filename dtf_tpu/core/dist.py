"""Multi-host bootstrap — the TPU-native successor of ClusterSpec/Server/join.

Reference capability replaced (SURVEY.md §1 L5/L6, §2b N1/N5): the reference
launches N processes with ``--ps_hosts/--worker_hosts/--job_name/--task_index``
flags, builds a ``tf.train.ClusterSpec`` and an in-process gRPC
``tf.train.Server`` in each, and PS processes block in ``server.join()``.

Here the same flags are accepted and *collapsed*: there is no PS role (its
state becomes GSPMD-sharded arrays), every former worker becomes one JAX
process, and bootstrap is ``jax.distributed.initialize`` — which stands up the
same TSL coordination service the modern TF stack uses for health/barriers
(SURVEY.md §2b N5: ``coordination_service.h``). Chief ≡ process 0 (the
reference's ``is_chief = (task_index == 0)``).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Sequence

import jax

log = logging.getLogger("dtf_tpu")


@dataclasses.dataclass(frozen=True)
class ClusterInfo:
    """Resolved cluster identity after collapsing ps/worker flags."""

    num_processes: int
    process_id: int
    coordinator_address: str | None
    is_chief: bool
    #: True for --job_name=ps: this process has no role on the TPU backend
    #: (the ``server.join()`` successor is "exit 0 immediately").
    should_exit: bool = False
    #: True when a multi-process launch was collapsed onto the FAKE-hosts
    #: harness: this container's jaxlib refuses multi-process CPU
    #: collectives (docs/RESILIENCE.md), so on the cpu backend every
    #: worker process runs the full deterministic SPMD program over its
    #: own simulated mesh, host identity driving only data sharding
    #: (``core.mesh.HostView``), chief-ness and checkpoint ownership.
    #: The chip path keeps true ``jax.distributed.initialize``.
    fake_hosts: bool = False
    notes: tuple[str, ...] = ()

    @property
    def host_count(self) -> int:
        """Hosts feeding the input pipeline (== num_processes; spelled
        separately so call sites say which concept they mean)."""
        return self.num_processes

    @property
    def participates_in_save(self) -> bool:
        """Whether this process takes part in checkpoint writes.

        Real multi-host: every process (Orbax sharded saves are
        collective — each host writes its addressable shards). Fake
        hosts: the chief only — every worker holds the FULL state, and
        N processes writing one checkpoint dir would race.
        """
        return self.is_chief or not self.fake_hosts

    def local_host_ids(self) -> tuple[int, int]:
        """(host_index, host_count) for loaders that feed THIS process's
        addressable data only — e.g. the eval sweep. Fake hosts hold the
        whole mesh, so they read the full split; real processes read
        their 1/N shard."""
        if self.fake_hosts:
            return 0, 1
        return self.process_id, self.num_processes


def collapse_cluster_flags(
    ps_hosts: Sequence[str] = (),
    worker_hosts: Sequence[str] = (),
    job_name: str = "worker",
    task_index: int = 0,
) -> ClusterInfo:
    """Map the reference's cluster flags onto the SPMD world.

    - workers → JAX processes (world size = len(worker_hosts), min 1)
    - ps hosts → warned and dropped (parameters live sharded on device)
    - job_name=ps → this process has no role; caller should exit 0 (the
      ``server.join()`` successor is "don't start")
    - chief = task_index 0 (identical to the reference)
    """
    notes = []
    worker_hosts = [h for h in worker_hosts if h]
    ps_hosts = [h for h in ps_hosts if h]
    if ps_hosts:
        notes.append(
            f"--ps_hosts={','.join(ps_hosts)} ignored: parameter servers do "
            "not exist on the TPU backend; parameters are GSPMD-sharded "
            "across the device mesh.")
    num = max(len(worker_hosts), 1)
    if job_name == "ps":
        notes.append(
            "--job_name=ps maps to no role on the TPU backend (variables are "
            "mesh-sharded); this process should exit immediately.")
        n_ps = max(len(ps_hosts), 1)
        if not (0 <= task_index < n_ps):
            raise ValueError(
                f"--task_index={task_index} out of range for {n_ps} ps tasks")
        for n in notes:
            log.warning(n)
        return ClusterInfo(
            num_processes=num, process_id=0, coordinator_address=None,
            is_chief=False, should_exit=True, notes=tuple(notes))
    if not (0 <= task_index < num):
        raise ValueError(
            f"--task_index={task_index} out of range for {num} workers")
    # The reference's chief (worker 0) did init/checkpoint; process 0 keeps
    # those duties (Orbax saves, summary writes).
    coordinator = worker_hosts[0] if len(worker_hosts) > 1 else None
    for n in notes:
        log.warning(n)
    return ClusterInfo(
        num_processes=num,
        process_id=task_index,
        coordinator_address=coordinator,
        is_chief=(task_index == 0),
        notes=tuple(notes),
    )


def initialize(info: ClusterInfo) -> None:
    """Start the distributed runtime if this is a multi-process job.

    ``jax.distributed.initialize`` boots the TSL coordination service on the
    chief and connects every process to it — liveness, barrier, and device
    enumeration; afterwards ``jax.devices()`` is cluster-global.
    """
    if info.num_processes <= 1 or info.should_exit:
        return
    # Must not touch jax.devices()/process_count() here: any backend init
    # before jax.distributed.initialize() makes it raise.
    if jax.distributed.is_initialized():
        return
    jax.distributed.initialize(
        coordinator_address=info.coordinator_address,
        num_processes=info.num_processes,
        process_id=info.process_id,
    )


def is_chief() -> bool:
    return jax.process_index() == 0


#: escape hatch for the chip-gated multi-process tests: set to "1" to force
#: true ``jax.distributed.initialize`` on any backend (the slow-tier
#: cross-process tests export it when a platform that CAN run multi-process
#: collectives is attached).
FORCE_REAL_MULTIPROCESS_ENV = "DTF_REAL_MULTIPROCESS"


def multiprocess_collectives_supported(platform: str) -> bool:
    """Whether this launch may use true multi-process collectives.

    The known blocker (PR 8 note, docs/RESILIENCE.md): this container's
    jaxlib refuses cross-process collectives on the CPU backend — the
    first collective hangs in the Gloo rendezvous. TPU backends (and any
    environment that sets ``DTF_REAL_MULTIPROCESS=1`` to vouch for its
    jaxlib) take the real ``jax.distributed.initialize`` path; cpu
    multi-worker launches collapse onto the fake-hosts harness instead.
    """
    if os.environ.get(FORCE_REAL_MULTIPROCESS_ENV) == "1":
        return True
    return platform not in ("cpu",)


def initialize_or_fake(info: ClusterInfo, platform: str) -> ClusterInfo:
    """The launchers' bootstrap: real distributed init on the chip path,
    the fake-hosts collapse where multi-process collectives cannot work.

    Returns the (possibly updated) ClusterInfo; with ``fake_hosts=True``
    the caller must feed data through the per-host harness
    (``cli.launch.host_batches``) and gate checkpoint writes on
    ``info.participates_in_save``. Single-process launches pass through
    untouched either way.
    """
    if info.num_processes <= 1 or info.should_exit:
        return info
    if multiprocess_collectives_supported(platform):
        initialize(info)
        return info
    log.warning(
        "multi-process launch on the %s backend: this jaxlib refuses "
        "cross-process CPU collectives (docs/RESILIENCE.md), so the %d "
        "workers run the fake-hosts harness — each process trains the "
        "full deterministic SPMD program on its own simulated mesh, host "
        "identity drives data sharding only, and the chief (process %d) "
        "owns the checkpoint dir. True multi-process launch engages on "
        "the tpu backend (or %s=1).",
        platform, info.num_processes,
        0, FORCE_REAL_MULTIPROCESS_ENV)
    return dataclasses.replace(info, fake_hosts=True)
