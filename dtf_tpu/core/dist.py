"""Multi-host bootstrap — the TPU-native successor of ClusterSpec/Server/join.

Reference capability replaced (SURVEY.md §1 L5/L6, §2b N1/N5): the reference
launches N processes with ``--ps_hosts/--worker_hosts/--job_name/--task_index``
flags, builds a ``tf.train.ClusterSpec`` and an in-process gRPC
``tf.train.Server`` in each, and PS processes block in ``server.join()``.

Here the same flags are accepted and *collapsed*: there is no PS role (its
state becomes GSPMD-sharded arrays), every former worker becomes one JAX
process, and bootstrap is ``jax.distributed.initialize`` — which stands up the
same TSL coordination service the modern TF stack uses for health/barriers
(SURVEY.md §2b N5: ``coordination_service.h``). Chief ≡ process 0 (the
reference's ``is_chief = (task_index == 0)``).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Sequence

import jax

log = logging.getLogger("dtf_tpu")


@dataclasses.dataclass(frozen=True)
class ClusterInfo:
    """Resolved cluster identity after collapsing ps/worker flags."""

    num_processes: int
    process_id: int
    coordinator_address: str | None
    is_chief: bool
    #: True for --job_name=ps: this process has no role on the TPU backend
    #: (the ``server.join()`` successor is "exit 0 immediately").
    should_exit: bool = False
    notes: tuple[str, ...] = ()


def collapse_cluster_flags(
    ps_hosts: Sequence[str] = (),
    worker_hosts: Sequence[str] = (),
    job_name: str = "worker",
    task_index: int = 0,
) -> ClusterInfo:
    """Map the reference's cluster flags onto the SPMD world.

    - workers → JAX processes (world size = len(worker_hosts), min 1)
    - ps hosts → warned and dropped (parameters live sharded on device)
    - job_name=ps → this process has no role; caller should exit 0 (the
      ``server.join()`` successor is "don't start")
    - chief = task_index 0 (identical to the reference)
    """
    notes = []
    worker_hosts = [h for h in worker_hosts if h]
    ps_hosts = [h for h in ps_hosts if h]
    if ps_hosts:
        notes.append(
            f"--ps_hosts={','.join(ps_hosts)} ignored: parameter servers do "
            "not exist on the TPU backend; parameters are GSPMD-sharded "
            "across the device mesh.")
    num = max(len(worker_hosts), 1)
    if job_name == "ps":
        notes.append(
            "--job_name=ps maps to no role on the TPU backend (variables are "
            "mesh-sharded); this process should exit immediately.")
        n_ps = max(len(ps_hosts), 1)
        if not (0 <= task_index < n_ps):
            raise ValueError(
                f"--task_index={task_index} out of range for {n_ps} ps tasks")
        for n in notes:
            log.warning(n)
        return ClusterInfo(
            num_processes=num, process_id=0, coordinator_address=None,
            is_chief=False, should_exit=True, notes=tuple(notes))
    if not (0 <= task_index < num):
        raise ValueError(
            f"--task_index={task_index} out of range for {num} workers")
    # The reference's chief (worker 0) did init/checkpoint; process 0 keeps
    # those duties (Orbax saves, summary writes).
    coordinator = worker_hosts[0] if len(worker_hosts) > 1 else None
    for n in notes:
        log.warning(n)
    return ClusterInfo(
        num_processes=num,
        process_id=task_index,
        coordinator_address=coordinator,
        is_chief=(task_index == 0),
        notes=tuple(notes),
    )


def initialize(info: ClusterInfo) -> None:
    """Start the distributed runtime if this is a multi-process job.

    ``jax.distributed.initialize`` boots the TSL coordination service on the
    chief and connects every process to it — liveness, barrier, and device
    enumeration; afterwards ``jax.devices()`` is cluster-global.
    """
    if info.num_processes <= 1 or info.should_exit:
        return
    # Must not touch jax.devices()/process_count() here: any backend init
    # before jax.distributed.initialize() makes it raise.
    if jax.distributed.is_initialized():
        return
    jax.distributed.initialize(
        coordinator_address=info.coordinator_address,
        num_processes=info.num_processes,
        process_id=info.process_id,
    )


def is_chief() -> bool:
    return jax.process_index() == 0
