"""Collective communication layer — the TPU-native successor of the reference's
gRPC rendezvous + NCCL backends.

Reference capability replaced (SURVEY.md §2d, §3.1): every PS↔worker variable
read and gradient push in the reference is a remote send/recv through TF's C++
rendezvous (``base_rendezvous_mgr.h``), and its collective strategy rides NCCL
ring all-reduce (``cross_device_ops.py`` ``NcclAllReduce``). Here the only
communication primitives are mesh-axis collectives, lowered by XLA onto ICI
(intra-slice) / DCN (inter-slice). There is deliberately no transport code:
picking the wire, ring schedule, and overlap is the compiler's job.

Two usage contexts:

- inside ``shard_map`` (per-shard code with named axes): the ``p*`` wrappers.
- outside (global arrays under ``jit``): sharding-annotated ops; XLA inserts
  the equivalent collectives automatically. Helpers here build the shardings.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PyTree = Any


# ---------------------------------------------------------------------------
# Named-axis collectives (for use inside shard_map / custom SPMD code).
# ---------------------------------------------------------------------------

def psum(x: PyTree, axis: str | Sequence[str]) -> PyTree:
    """Sum over a mesh axis. Successor of the PS gradient push + NCCL ring."""
    return jax.lax.psum(x, axis)


def pmean(x: PyTree, axis: str | Sequence[str]) -> PyTree:
    """Mean over a mesh axis — the exact ``SyncReplicasOptimizer`` semantics
    (mean of ``replicas_to_aggregate`` gradients; SURVEY.md §3.3)."""
    return jax.lax.pmean(x, axis)


def psum_scatter(x: jax.Array, axis: str, *, scatter_dimension: int = 0,
                 tiled: bool = True) -> jax.Array:
    """Reduce-scatter — the building block of ZeRO-1 weight-update sharding."""
    return jax.lax.psum_scatter(
        x, axis, scatter_dimension=scatter_dimension, tiled=tiled)


def all_gather(x: jax.Array, axis: str, *, gather_dimension: int = 0,
               tiled: bool = True) -> jax.Array:
    return jax.lax.all_gather(
        x, axis, axis=gather_dimension, tiled=tiled)


def axis_index(axis: str) -> jax.Array:
    return jax.lax.axis_index(axis)


def axis_size(axis: str) -> int:
    return jax.lax.axis_size(axis)


def ring_perm(n: int, *, shift: int = 1) -> list[tuple[int, int]]:
    """THE named cyclic ring schedule: device ``i`` sends to ``(i+shift) % n``.

    Every ``ppermute`` ring in the tree (collective matmul, ring/zigzag
    attention, the pipeline's interleaved wraparound) must build its perm
    here or via :func:`shift_perm` — one construction point the collective
    soundness pass (``analysis/collective.py``) can introspect, and the
    srclint fence holds call sites outside ``core/comms.py`` /
    ``ops/collective_matmul.py`` to it (a hand-typed perm with a transposed
    pair compiles fine and trains silently wrong).
    """
    if n < 1:
        raise ValueError(f"ring_perm: axis size {n} must be >= 1")
    if shift % n == 0 and n > 1:
        raise ValueError(f"ring_perm: shift {shift} is a no-op on n={n}")
    return [(i, (i + shift) % n) for i in range(n)]


def shift_perm(n: int, *, shift: int = 1) -> list[tuple[int, int]]:
    """Non-cyclic neighbor shift: ``i → i+shift``, edges fall off (devices
    that receive nothing get zeros — the halo-exchange / pipeline-edge
    contract, deliberately NOT a permutation of the whole axis).

    Same introspection story as :func:`ring_perm` — the named helpers are
    the only sanctioned perm constructions outside the two ring modules.
    """
    if n < 1:
        raise ValueError(f"shift_perm: axis size {n} must be >= 1")
    if not -n < shift < n:
        raise ValueError(f"shift_perm: shift {shift} out of range for n={n}")
    if shift >= 0:
        return [(i, i + shift) for i in range(n - shift)]
    return [(i, i + shift) for i in range(-shift, n)]


def ring_pass(x: PyTree, axis: str, *, shift: int = 1) -> PyTree:
    """Pass each shard to its ring neighbor along ``axis`` (ppermute).

    The primitive under ring attention / ring all-reduce: neighbor exchange
    rides a single ICI hop per step.
    """
    n = jax.lax.axis_size(axis)
    perm = ring_perm(n, shift=shift)
    return jax.tree.map(lambda t: jax.lax.ppermute(t, axis, perm), x)


# ---------------------------------------------------------------------------
# Global-array helpers (outside shard_map).
# ---------------------------------------------------------------------------

def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, *, batch_dim: int = 0,
                   axis: str | tuple[str, ...] = "data",
                   spec: P | None = None) -> NamedSharding:
    """Sharding for a batch: leading dim split over the data axis, or an
    explicit ``spec`` (e.g. ``P('data', 'seq')`` for context parallelism)."""
    if spec is None:
        s = [None] * (batch_dim + 1)
        s[batch_dim] = axis
        spec = P(*s)
    return NamedSharding(mesh, spec)


def shard_batch(batch: PyTree, mesh: Mesh, *, batch_dim: int = 0,
                spec: P | None = None) -> PyTree:
    """Place a host-global batch onto the mesh, split over ``data`` (or an
    explicit spec; extra spec dims are dropped per-leaf for lower-rank leaves,
    so ``P('data','seq')`` works for a batch mixing [B] and [B,T] arrays).

    Single-process path. For multi-host (each process holding its slice of
    the global batch) use :func:`host_local_to_global`.
    """

    def put(x):
        s = spec
        if s is not None and x.ndim < len(s):
            s = P(*list(s)[: x.ndim])
        sh = batch_sharding(mesh, batch_dim=batch_dim, spec=s)
        return jax.device_put(x, sh)

    return jax.tree.map(put, batch)


def batch_shardings_for(example_batch: PyTree, mesh: Mesh,
                        spec: P) -> PyTree:
    """Per-leaf NamedShardings from a spec, truncated to each leaf's rank.

    ``P('data', 'seq')`` → [B,T] leaves shard batch+sequence, [B] leaves
    shard batch only. Pass the result to ``make_train_step(batch_shardings=)``
    and place batches with ``shard_batch(..., spec=...)``.
    """

    def leaf_sharding(x):
        s = list(spec)[: x.ndim]
        return NamedSharding(mesh, P(*s))

    return jax.tree.map(leaf_sharding, example_batch)


def host_local_to_global(local_batch: PyTree, mesh: Mesh,
                         *, batch_dim: int = 0,
                         axis: str | tuple[str, ...] = "data") -> PyTree:
    """Assemble per-process local batches into one global sharded array.

    Successor of the reference's per-worker feed_dict: each worker fed its own
    batch into its own graph replica; here each process contributes its slice
    of a single global array (``jax.make_array_from_process_local_data``).
    """
    sh = batch_sharding(mesh, batch_dim=batch_dim, axis=axis)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sh, np.asarray(x)),
        local_batch)


def global_norm(tree: PyTree) -> jax.Array:
    """L2 norm over a pytree (for grad-norm logging/clipping).

    Works on shard-constrained leaves too: under jit GSPMD lowers each
    ``vdot`` to a local square-sum plus a scalar psum over the sharded
    axes, so the norm of a reduce-scattered gradient tree (``--grad_shard``)
    comes from per-shard partial norms without re-gathering the shards.
    """
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(sum(jnp.vdot(x, x).real for x in leaves))


# ---------------------------------------------------------------------------
# ZeRO-1 weight-update sharding (the --grad_shard choke point).
# ---------------------------------------------------------------------------

def _pin_tree(tree: PyTree, mesh: Mesh, specs: PyTree) -> PyTree:
    return jax.tree.map(
        lambda x, s: jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, s)), tree, specs)


def shard_grads(grads: PyTree, mesh: Mesh, shard_specs: PyTree) -> PyTree:
    """Pin a gradient/accumulator/update pytree to its ZeRO-1 shard layout
    (``sharding.zero1_param_shard_specs``) — every replica holds only its
    1/N slice, and the optimizer math that consumes the tree partitions to
    1/N of the elementwise FLOPs (weight-update sharding, Xu et al.,
    PAPERS.md; docs/ZERO.md)."""
    return _pin_tree(grads, mesh, shard_specs)


def grad_reduce_scatter(stacked: PyTree, mesh: Mesh, param_specs: PyTree,
                        shard_specs: PyTree, *, axis: str = "data") -> PyTree:
    """Reduce-scatter stacked per-shard partial gradients into ZeRO-1 shards
    — THE swap of weight-update sharding (Xu et al., PAPERS.md), and the
    ``--grad_shard`` choke point like ``tp_dense`` is for TP overlap.

    ``stacked``: a gradient tree whose leaves carry a leading
    ``[n_data, ...param dims]`` axis sharded over ``axis`` — slot k holds
    data-shard k's gradient over ITS OWN batch rows only (from the
    per-shard-group vmap in ``make_train_step``), so each replica owns its
    partial and nothing has been reduced yet. Each leaf then rides ONE
    ``psum_scatter`` over ``axis``: the cross-replica sum and the 1/N
    scatter happen in the same collective, moving half the bytes of the
    all-reduce it replaces and returning the full-shaped leaf laid out per
    ``shard_specs`` (its ``zero1_param_shard_specs`` layout). Leaves with
    no data-divisible dim (scalars, tiny biases) fall back per-leaf to an
    explicit ``psum`` — correct, just unscattered.

    GSPMD cannot be left to do this here: the jit partitioner resolves a
    partial sum feeding a sharded consumer as all-reduce + dynamic-slice
    (full bytes, replicated transient), so the collective is issued
    explicitly via a per-leaf ``shard_map``.
    """
    def leaf(g, pspec, sspec):
        ps = tuple(pspec) + (None,) * (g.ndim - 1 - len(pspec))
        ss = tuple(sspec) + (None,) * (g.ndim - 1 - len(sspec))
        d = next((i for i, (a, b) in enumerate(zip(ps, ss)) if a != b), None)
        g = jax.lax.with_sharding_constraint(
            g, NamedSharding(mesh, P(axis, *ps)))
        if d is None:
            body = lambda x: jax.lax.psum(x[0], axis)          # noqa: E731
            out = P(*ps)
        else:
            body = lambda x: jax.lax.psum_scatter(             # noqa: E731
                x[0], axis, scatter_dimension=d, tiled=True)
            out = P(*ss)
        return jax.shard_map(body, mesh=mesh, in_specs=P(axis, *ps),
                             out_specs=out)(g)

    return jax.tree.map(leaf, stacked, param_specs, shard_specs)


def unshard_params(params: PyTree, mesh: Mesh, param_specs: PyTree) -> PyTree:
    """Pin updated params back to their serving layout — the one
    per-step ALL-GATHER that closes weight-update sharding: the optimizer
    ran on 1/N-sized shards, and the next forward needs each param back
    in its rulebook placement."""
    return _pin_tree(params, mesh, param_specs)


# ---------------------------------------------------------------------------
# Megatron TP projection dispatch (the --tp_overlap choke point).
# ---------------------------------------------------------------------------

def tp_overlap_viable(x_shape: Sequence[int], features_in: int,
                      features_out: int, mesh: Mesh | None, *,
                      parallel: str, axis: str = "model") -> bool:
    """Can this projection take the collective-matmul ring path?

    The ring needs: a real TP axis; [B, T, D] activations whose batch and
    token dims split evenly over ('data') x ('seq', axis); and the sharded
    feature dim divisible by the axis (columns of W for the column-parallel
    projection, rows of W = activation features for the row-parallel one).
    Anything else — tp=1, decode's t=1/ragged chunks, non-3D inputs — falls
    back to the plain einsum, where GSPMD's blocking collectives are
    correct, just not overlapped.
    """
    if mesh is None:
        return False
    n = mesh.shape.get(axis, 1)
    if n <= 1 or len(x_shape) != 3:
        return False
    token_shards = mesh.shape.get("seq", 1) * n
    if x_shape[0] % mesh.shape.get("data", 1) or x_shape[1] % token_shards:
        return False
    sharded_f = features_out if parallel == "column" else features_in
    return sharded_f % n == 0


def tp_token_sharded(x: jax.Array, mesh: Mesh | None, *,
                     axis: str = "model") -> jax.Array:
    """Pin the Megatron sequence-parallel residual-stream layout: [B, T, D]
    tokens sharded over ('seq', axis), features whole.

    Without this constraint GSPMD is free to resolve the residual add by
    ALL-GATHERING :func:`matmul_rs`'s token-sharded output back to the
    replicated layout — re-inserting exactly the blocking collective the
    overlap path removes. Pinned, the stream stays token-sharded across
    residual adds / layernorms / dropout, and the only remaining gather is
    the one the LM head genuinely needs. No-op when the layout cannot
    apply (trivial axis, non-3D, indivisible dims).
    """
    if (mesh is None or x.ndim != 3
            or mesh.shape.get(axis, 1) <= 1
            or x.shape[0] % mesh.shape.get("data", 1)
            or x.shape[1] % (mesh.shape.get("seq", 1) * mesh.shape[axis])):
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P("data", ("seq", axis), None)))


def tp_activation_gathered(x: jax.Array, mesh: Mesh | None) -> jax.Array:
    """Leave the Megatron-SP layout with ONE activation gather over the TP
    axis: [B, T, D] pinned back to P('data', 'seq', None).

    Pin this at the embed exit and the LM/MLM head entry. Without it GSPMD
    may satisfy a vocab-sharded table consumer by all-gathering the [V, D]
    embedding/head TABLE instead — ruinous at a 50k vocab, invisible at
    tiny test scale. No-op without a mesh.
    """
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P("data", "seq", None)))


def tp_dense(x: jax.Array, kernel: jax.Array, bias: jax.Array | None,
             mesh: Mesh | None, *, parallel: str, overlap: bool = False,
             dtype=None, axis: str = "model",
             precision: str = "") -> jax.Array:
    """Apply one Megatron TP projection — THE dispatch point the models
    route through (srclint fences direct ``jax.lax`` collectives out of
    ``models/``; see docs/OVERLAP.md).

    ``parallel='column'``: kernel [D, F] placed P(None, axis) (q/k/v,
    mlp_in — output features sharded). ``parallel='row'``: kernel [F, D]
    placed P(axis, None) (attn_out, mlp_out — contracting features
    sharded). ``overlap=True`` routes through the latency-hiding ppermute
    rings of :mod:`dtf_tpu.ops.collective_matmul` when
    :func:`tp_overlap_viable`; otherwise this is exactly the einsum
    ``nn.Dense`` performs and GSPMD schedules the (blocking) collectives.

    ``precision`` is the low-precision compute tier (docs/TUNING.md):
    ``""`` = bf16 status quo (no tuner consult), ``"auto"`` = the banked
    kernel-tune winner for this (parallel, shape) site, explicit
    ``"int8"``/``"fp8"`` = quantized compute with bf16 master weights
    (wins over a measured winner with one WARN). On the ring path the
    COMMUNICATED operand is quantized (dequant-after-ppermute, ~2x fewer
    ring bytes); off it, :func:`dtf_tpu.ops.quant.quantized_matmul` runs
    the low-precision dot. Gradients stay full-precision either way.
    """
    if parallel not in ("column", "row"):
        raise ValueError(f"parallel={parallel!r} must be 'column' or 'row'")
    if dtype is not None:
        x = x.astype(dtype)
        kernel = kernel.astype(dtype)
        bias = bias.astype(dtype) if bias is not None else None
    resolved = "bf16"
    if precision:
        from dtf_tpu.ops import quant

        resolved = quant.resolve_precision(
            precision, parallel=parallel, d_in=kernel.shape[0],
            d_out=kernel.shape[1], dtype=str(jnp.dtype(x.dtype)),
            n_devices=(mesh.devices.size if mesh is not None else 1))
    if overlap and tp_overlap_viable(
            x.shape, kernel.shape[0], kernel.shape[1], mesh,
            parallel=parallel, axis=axis):
        from dtf_tpu.ops import collective_matmul as cm

        if parallel == "column":
            if resolved == "bf16":
                y = cm.ag_matmul_sharded(x, kernel, mesh, axis=axis)
            else:
                y = cm.ag_matmul_quant_sharded(x, kernel, mesh, axis=axis,
                                               precision=resolved)
        else:
            if resolved == "bf16":
                y = cm.matmul_rs_sharded(x, kernel, mesh, axis=axis)
            else:
                y = cm.matmul_rs_quant_sharded(x, kernel, mesh, axis=axis,
                                               precision=resolved)
    elif resolved != "bf16":
        from dtf_tpu.ops import quant

        y = quant.quantized_matmul(x, kernel, precision=resolved)
    else:
        y = jnp.einsum("...td,df->...tf", x, kernel)
    return y if bias is None else y + bias


class TpDense(nn.Module):
    """Drop-in ``nn.Dense`` for Megatron TP projections: same param
    names/shapes/init (kernel [in, features] lecun-normal, zeros bias), so
    rulebooks, checkpoints and parity tests see an identical tree — only
    the matmul routes through :func:`tp_dense`, which swaps GSPMD's
    blocking all-gather/reduce-scatter for the collective-matmul ring when
    ``overlap`` is on and the shapes allow it."""

    features: int
    mesh: Mesh | None
    parallel: str                 # 'column' | 'row'
    overlap: bool = True
    use_bias: bool = True
    dtype: Any = None
    param_dtype: Any = jnp.float32
    precision: str = ""           # '' | 'auto' | 'bf16' | 'int8' | 'fp8'

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features), self.param_dtype)
        bias = (self.param("bias", nn.initializers.zeros,
                           (self.features,), self.param_dtype)
                if self.use_bias else None)
        return tp_dense(x, kernel, bias, self.mesh, parallel=self.parallel,
                        overlap=self.overlap, dtype=self.dtype,
                        precision=self.precision)


# ---------------------------------------------------------------------------
# Fake-N-hosts batch assembly (the elastic-harness data seam).
# ---------------------------------------------------------------------------

def fake_hosts_to_global(host_batches: Sequence[PyTree], mesh: Mesh,
                         *, batch_dim: int = 0,
                         spec: P | None = None) -> PyTree:
    """Single-process stand-in for :func:`host_local_to_global`.

    ``host_batches[k]`` is fake host ``k``'s host-local batch (disjoint
    global rows, the loaders' ``host_index/host_count`` contract). Each
    leaf is assembled into ONE global sharded array by placing every
    device's shard from *the owning host's local array only* — the exact
    data motion N real processes perform, minus the coordination service.

    The per-device ownership check is the harness's proof obligation: a
    device whose batch rows straddle two hosts' local arrays would be
    impossible to feed in a real multi-host run (host k cannot place rows
    on host j's devices), so it raises here instead of silently reading
    across the boundary. ``mesh.shape['data'] % len(host_batches) == 0``
    makes it unreachable (``mesh.assert_host_aligned``).

    Shardings match :func:`shard_batch`'s exactly (same
    ``batch_sharding`` spec path), so a train step compiled against
    single-process placement accepts these arrays without a retrace.
    """
    n_hosts = len(host_batches)
    if not n_hosts:
        raise ValueError("need at least one host batch")

    def leaf(*xs):
        xs = [np.asarray(x) for x in xs]
        local_rows = xs[0].shape[batch_dim]
        for k, x in enumerate(xs):
            if x.shape[batch_dim] != local_rows:
                raise ValueError(
                    f"host {k} local batch has {x.shape[batch_dim]} rows, "
                    f"host 0 has {local_rows} — hosts must feed equal "
                    f"shares of the global batch")
        gshape = list(xs[0].shape)
        gshape[batch_dim] = local_rows * n_hosts
        gshape = tuple(gshape)
        s = spec
        if s is not None and xs[0].ndim < len(s):
            s = P(*list(s)[: xs[0].ndim])
        sh = batch_sharding(mesh, batch_dim=batch_dim, spec=s)
        shards = []
        for dev, idx in sh.devices_indices_map(gshape).items():
            rows = idx[batch_dim]
            start = 0 if rows.start is None else rows.start
            stop = gshape[batch_dim] if rows.stop is None else rows.stop
            host, off = divmod(start, local_rows)
            if stop - start > local_rows - off:
                raise ValueError(
                    f"device {dev} batch rows [{start}:{stop}) straddle "
                    f"the host boundary at {(host + 1) * local_rows} — "
                    f"data axis {mesh.shape.get('data', 1)} is not "
                    f"divisible across {n_hosts} hosts")
            local_idx = list(idx)
            local_idx[batch_dim] = slice(off, off + (stop - start))
            shards.append(jax.device_put(xs[host][tuple(local_idx)], dev))
        return jax.make_array_from_single_device_arrays(gshape, sh, shards)

    return jax.tree.map(leaf, *host_batches)
