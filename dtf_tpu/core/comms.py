"""Collective communication layer — the TPU-native successor of the reference's
gRPC rendezvous + NCCL backends.

Reference capability replaced (SURVEY.md §2d, §3.1): every PS↔worker variable
read and gradient push in the reference is a remote send/recv through TF's C++
rendezvous (``base_rendezvous_mgr.h``), and its collective strategy rides NCCL
ring all-reduce (``cross_device_ops.py`` ``NcclAllReduce``). Here the only
communication primitives are mesh-axis collectives, lowered by XLA onto ICI
(intra-slice) / DCN (inter-slice). There is deliberately no transport code:
picking the wire, ring schedule, and overlap is the compiler's job.

Two usage contexts:

- inside ``shard_map`` (per-shard code with named axes): the ``p*`` wrappers.
- outside (global arrays under ``jit``): sharding-annotated ops; XLA inserts
  the equivalent collectives automatically. Helpers here build the shardings.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PyTree = Any


# ---------------------------------------------------------------------------
# Named-axis collectives (for use inside shard_map / custom SPMD code).
# ---------------------------------------------------------------------------

def psum(x: PyTree, axis: str | Sequence[str]) -> PyTree:
    """Sum over a mesh axis. Successor of the PS gradient push + NCCL ring."""
    return jax.lax.psum(x, axis)


def pmean(x: PyTree, axis: str | Sequence[str]) -> PyTree:
    """Mean over a mesh axis — the exact ``SyncReplicasOptimizer`` semantics
    (mean of ``replicas_to_aggregate`` gradients; SURVEY.md §3.3)."""
    return jax.lax.pmean(x, axis)


def psum_scatter(x: jax.Array, axis: str, *, scatter_dimension: int = 0,
                 tiled: bool = True) -> jax.Array:
    """Reduce-scatter — the building block of ZeRO-1 weight-update sharding."""
    return jax.lax.psum_scatter(
        x, axis, scatter_dimension=scatter_dimension, tiled=tiled)


def all_gather(x: jax.Array, axis: str, *, gather_dimension: int = 0,
               tiled: bool = True) -> jax.Array:
    return jax.lax.all_gather(
        x, axis, axis=gather_dimension, tiled=tiled)


def axis_index(axis: str) -> jax.Array:
    return jax.lax.axis_index(axis)


def axis_size(axis: str) -> int:
    return jax.lax.axis_size(axis)


def ring_pass(x: PyTree, axis: str, *, shift: int = 1) -> PyTree:
    """Pass each shard to its ring neighbor along ``axis`` (ppermute).

    The primitive under ring attention / ring all-reduce: neighbor exchange
    rides a single ICI hop per step.
    """
    n = jax.lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.tree.map(lambda t: jax.lax.ppermute(t, axis, perm), x)


# ---------------------------------------------------------------------------
# Global-array helpers (outside shard_map).
# ---------------------------------------------------------------------------

def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, *, batch_dim: int = 0,
                   axis: str | tuple[str, ...] = "data",
                   spec: P | None = None) -> NamedSharding:
    """Sharding for a batch: leading dim split over the data axis, or an
    explicit ``spec`` (e.g. ``P('data', 'seq')`` for context parallelism)."""
    if spec is None:
        s = [None] * (batch_dim + 1)
        s[batch_dim] = axis
        spec = P(*s)
    return NamedSharding(mesh, spec)


def shard_batch(batch: PyTree, mesh: Mesh, *, batch_dim: int = 0,
                spec: P | None = None) -> PyTree:
    """Place a host-global batch onto the mesh, split over ``data`` (or an
    explicit spec; extra spec dims are dropped per-leaf for lower-rank leaves,
    so ``P('data','seq')`` works for a batch mixing [B] and [B,T] arrays).

    Single-process path. For multi-host (each process holding its slice of
    the global batch) use :func:`host_local_to_global`.
    """

    def put(x):
        s = spec
        if s is not None and x.ndim < len(s):
            s = P(*list(s)[: x.ndim])
        sh = batch_sharding(mesh, batch_dim=batch_dim, spec=s)
        return jax.device_put(x, sh)

    return jax.tree.map(put, batch)


def batch_shardings_for(example_batch: PyTree, mesh: Mesh,
                        spec: P) -> PyTree:
    """Per-leaf NamedShardings from a spec, truncated to each leaf's rank.

    ``P('data', 'seq')`` → [B,T] leaves shard batch+sequence, [B] leaves
    shard batch only. Pass the result to ``make_train_step(batch_shardings=)``
    and place batches with ``shard_batch(..., spec=...)``.
    """

    def leaf_sharding(x):
        s = list(spec)[: x.ndim]
        return NamedSharding(mesh, P(*s))

    return jax.tree.map(leaf_sharding, example_batch)


def host_local_to_global(local_batch: PyTree, mesh: Mesh,
                         *, batch_dim: int = 0,
                         axis: str | tuple[str, ...] = "data") -> PyTree:
    """Assemble per-process local batches into one global sharded array.

    Successor of the reference's per-worker feed_dict: each worker fed its own
    batch into its own graph replica; here each process contributes its slice
    of a single global array (``jax.make_array_from_process_local_data``).
    """
    sh = batch_sharding(mesh, batch_dim=batch_dim, axis=axis)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sh, np.asarray(x)),
        local_batch)


def global_norm(tree: PyTree) -> jax.Array:
    """L2 norm over a pytree (for grad-norm logging/clipping)."""
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(sum(jnp.vdot(x, x).real for x in leaves))
