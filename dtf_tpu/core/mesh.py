"""Device-mesh construction — the TPU-native successor of ``tf.train.ClusterSpec``.

Reference capability replaced (SURVEY.md §1 L5, §2b N1/N2): the reference
builds a cluster from ``--ps_hosts``/``--worker_hosts`` flags via
``tf.train.ClusterSpec`` + ``tf.train.Server`` (TF's
``python/training/server_lib.py``), then pins variables to PS tasks. Here the
cluster is a single logical device mesh; "placement" is a ``NamedSharding``
over the mesh axes, and XLA's GSPMD partitioner does what the TF master's
graph partitioner did.

Axis convention (sizes of 1 are allowed and common):

- ``data``  — data parallelism. Batches are sharded over it; gradients are
  mean-reduced over it (the ``SyncReplicasOptimizer`` semantics); ZeRO-1
  shards optimizer state over it.
- ``seq``   — sequence/context parallelism (ring attention over ICI neighbors).
- ``model`` — tensor parallelism (Megatron-style column/row sharding) and
  row-sharded embedding tables (the PS-sharded-embedding successor).
- ``pipe``  — pipeline parallelism (stage-stacked params; GPipe microbatch
  schedule inside shard_map — see ``dtf_tpu.parallel.pipeline``).
- ``expert`` — expert parallelism (MoE expert-sharded FFN weights; token
  dispatch rides XLA all-to-all — see ``dtf_tpu.parallel.moe``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_SEQ = "seq"
AXIS_MODEL = "model"
AXIS_PIPE = "pipe"
AXIS_EXPERT = "expert"
#: Canonical mesh axis order. data is the slowest-varying axis so that the
#: model/seq axes land on adjacent devices (best ICI locality for the
#: high-traffic TP/SP collectives; DP all-reduce is once per step and can
#: span the longer mesh dimension). pipe sits between: stage boundaries are
#: a single ppermute hop per microbatch, lower-traffic than TP but touched
#: every scan iteration.
AXES = (AXIS_DATA, AXIS_PIPE, AXIS_EXPERT, AXIS_SEQ, AXIS_MODEL)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. ``data=-1`` means "all remaining devices"."""

    data: int = -1
    seq: int = 1
    model: int = 1
    pipe: int = 1
    expert: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int, int, int, int]:
        rest = (self.pipe, self.expert, self.seq, self.model)
        if any(s <= 0 for s in rest):
            raise ValueError(
                f"pipe/expert/seq/model axis sizes must be positive, got {self}")
        rest_prod = math.prod(rest)
        data = self.data
        if data == 0 or data < -1:
            raise ValueError(
                f"data axis size must be positive or -1 (infer), got {self}")
        if data == -1:
            if n_devices % rest_prod:
                raise ValueError(
                    f"{n_devices} devices not divisible by "
                    f"pipe*expert*seq*model={rest_prod}")
            data = n_devices // rest_prod
        if data * rest_prod != n_devices:
            raise ValueError(
                f"mesh data={data} x {rest} != {n_devices} devices")
        return (data,) + rest


def make_mesh(
    config: MeshConfig | None = None, devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """Build the global device mesh.

    This is the whole cluster-bootstrap story: where the reference spun up one
    gRPC server per process and partitioned a graph across them, we enumerate
    devices (already cluster-global after ``jax.distributed.initialize``) and
    arrange them into a named mesh.
    """
    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    shape = config.resolve(len(devices))
    # Auto axis types = classic GSPMD: the compiler propagates shardings and
    # inserts collectives (the design stance of SURVEY.md §7 — annotate at
    # the jit boundary, let XLA place the psum/all-gathers). The 0.9 default
    # (Explicit) would demand per-op out_sharding annotations instead.
    return jax.make_mesh(
        shape, AXES, devices=devices,
        axis_types=(jax.sharding.AxisType.Auto,) * len(AXES))


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    """An all-ones (5-axis) mesh for single-chip runs (local dev/bench)."""
    device = device or jax.devices()[0]
    return jax.make_mesh((1,) * len(AXES), AXES, devices=[device],
                         axis_types=(jax.sharding.AxisType.Auto,) * len(AXES))


def mesh_summary(mesh: Mesh) -> str:
    sizes = dict(mesh.shape)
    n = math.prod(mesh.devices.shape)
    plat = mesh.devices.flat[0].platform
    return f"mesh[{plat}x{n}] " + " ".join(f"{k}={v}" for k, v in sizes.items())
