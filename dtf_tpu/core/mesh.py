"""Device-mesh construction — the TPU-native successor of ``tf.train.ClusterSpec``.

Reference capability replaced (SURVEY.md §1 L5, §2b N1/N2): the reference
builds a cluster from ``--ps_hosts``/``--worker_hosts`` flags via
``tf.train.ClusterSpec`` + ``tf.train.Server`` (TF's
``python/training/server_lib.py``), then pins variables to PS tasks. Here the
cluster is a single logical device mesh; "placement" is a ``NamedSharding``
over the mesh axes, and XLA's GSPMD partitioner does what the TF master's
graph partitioner did.

Axis convention (sizes of 1 are allowed and common):

- ``data``  — data parallelism. Batches are sharded over it; gradients are
  mean-reduced over it (the ``SyncReplicasOptimizer`` semantics); ZeRO-1
  shards optimizer state over it.
- ``seq``   — sequence/context parallelism (ring attention over ICI neighbors).
- ``model`` — tensor parallelism (Megatron-style column/row sharding) and
  row-sharded embedding tables (the PS-sharded-embedding successor).
- ``pipe``  — pipeline parallelism (stage-stacked params; GPipe microbatch
  schedule inside shard_map — see ``dtf_tpu.parallel.pipeline``).
- ``expert`` — expert parallelism (MoE expert-sharded FFN weights; token
  dispatch rides XLA all-to-all — see ``dtf_tpu.parallel.moe``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_SEQ = "seq"
AXIS_MODEL = "model"
AXIS_PIPE = "pipe"
AXIS_EXPERT = "expert"
#: Canonical mesh axis order. data is the slowest-varying axis so that the
#: model/seq axes land on adjacent devices (best ICI locality for the
#: high-traffic TP/SP collectives; DP all-reduce is once per step and can
#: span the longer mesh dimension). pipe sits between: stage boundaries are
#: a single ppermute hop per microbatch, lower-traffic than TP but touched
#: every scan iteration.
AXES = (AXIS_DATA, AXIS_PIPE, AXIS_EXPERT, AXIS_SEQ, AXIS_MODEL)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. ``data=-1`` means "all remaining devices"."""

    data: int = -1
    seq: int = 1
    model: int = 1
    pipe: int = 1
    expert: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int, int, int, int]:
        rest = (self.pipe, self.expert, self.seq, self.model)
        if any(s <= 0 for s in rest):
            raise ValueError(
                f"pipe/expert/seq/model axis sizes must be positive, got {self}")
        rest_prod = math.prod(rest)
        data = self.data
        if data == 0 or data < -1:
            raise ValueError(
                f"data axis size must be positive or -1 (infer), got {self}")
        if data == -1:
            if n_devices % rest_prod:
                raise ValueError(
                    f"{n_devices} devices not divisible by "
                    f"pipe*expert*seq*model={rest_prod}")
            data = n_devices // rest_prod
        if data * rest_prod != n_devices:
            raise ValueError(
                f"mesh data={data} x {rest} != {n_devices} devices")
        return (data,) + rest


def make_mesh(
    config: MeshConfig | None = None, devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """Build the global device mesh.

    This is the whole cluster-bootstrap story: where the reference spun up one
    gRPC server per process and partitioned a graph across them, we enumerate
    devices (already cluster-global after ``jax.distributed.initialize``) and
    arrange them into a named mesh.
    """
    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    shape = config.resolve(len(devices))
    # Auto axis types = classic GSPMD: the compiler propagates shardings and
    # inserts collectives (the design stance of SURVEY.md §7 — annotate at
    # the jit boundary, let XLA place the psum/all-gathers). The 0.9 default
    # (Explicit) would demand per-op out_sharding annotations instead.
    return jax.make_mesh(
        shape, AXES, devices=devices,
        axis_types=(jax.sharding.AxisType.Auto,) * len(AXES))


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    """An all-ones (5-axis) mesh for single-chip runs (local dev/bench)."""
    device = device or jax.devices()[0]
    return jax.make_mesh((1,) * len(AXES), AXES, devices=[device],
                         axis_types=(jax.sharding.AxisType.Auto,) * len(AXES))


def mesh_summary(mesh: Mesh) -> str:
    sizes = dict(mesh.shape)
    n = math.prod(mesh.devices.shape)
    plat = mesh.devices.flat[0].platform
    return f"mesh[{plat}x{n}] " + " ".join(f"{k}={v}" for k, v in sizes.items())


# ---------------------------------------------------------------------------
# Host identity over a process-spanning mesh (the elastic/multi-host seam).
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class HostView:
    """Host ``k`` of ``N`` over one global mesh.

    On a real pod this is simply ``(jax.process_index(), jax.process_count())``
    (:func:`host_view_for_process`) and the devices it owns are the
    process-addressable ones. On the CPU sim — where this container's jaxlib
    refuses multi-process collectives (docs/RESILIENCE.md) — a single process
    holds ALL devices and a ``HostView`` makes it *behave* as host ``k`` for
    the two things host identity actually controls: which rows of the global
    batch this host produces (:meth:`batch_rows`, driving the per-host data
    loaders) and which mesh devices count as addressable
    (:meth:`addressable_devices`, driving shard placement in
    ``comms.fake_hosts_to_global``). That makes every elastic code path —
    per-host sharding, shrink-resume, the run controller — testable in tier-1
    with zero cross-process collectives.
    """

    host_index: int
    host_count: int

    def __post_init__(self):
        if self.host_count < 1:
            raise ValueError(f"host_count must be >= 1, got {self.host_count}")
        if not (0 <= self.host_index < self.host_count):
            raise ValueError(
                f"host_index {self.host_index} out of range for "
                f"{self.host_count} hosts")

    def addressable_devices(self, mesh: Mesh) -> list:
        """The contiguous device block host ``k`` owns.

        ``data`` is the slowest-varying mesh axis (AXES), so splitting the
        flattened device array into ``host_count`` equal blocks gives each
        host whole data shards — the TPU reality (a host owns a contiguous
        slice of the pod) and the precondition for per-host batch rows to
        land only on that host's devices.
        """
        flat = list(mesh.devices.flat)
        per = divmod(len(flat), self.host_count)
        if per[1]:
            raise ValueError(
                f"{len(flat)} mesh devices not divisible across "
                f"{self.host_count} hosts")
        n = per[0]
        return flat[self.host_index * n:(self.host_index + 1) * n]

    def batch_rows(self, global_rows: int) -> tuple[int, int]:
        """[start, stop) of the global-batch rows this host produces.

        Matches the loaders' ``local_batch = global // host_count``
        contract AND the mesh placement: with the data axis divisible by
        ``host_count``, these rows shard exactly onto this host's devices.
        """
        if global_rows % self.host_count:
            raise ValueError(
                f"global batch {global_rows} not divisible by "
                f"{self.host_count} hosts")
        n = global_rows // self.host_count
        return self.host_index * n, (self.host_index + 1) * n


def host_views(host_count: int) -> list[HostView]:
    """All N fake-host identities of one simulated cluster."""
    return [HostView(k, host_count) for k in range(host_count)]


def host_view_for_process() -> HostView:
    """This process's REAL host identity (the chip path's HostView)."""
    return HostView(jax.process_index(), jax.process_count())


def assert_host_aligned(mesh: Mesh, host_count: int) -> None:
    """Fail fast when a mesh cannot be split across ``host_count`` hosts.

    Per-host data feeding requires every host to own whole ``data`` shards:
    the data axis AND the flattened device count must both divide by the
    host count (a data shard spanning two hosts would need one host's rows
    placed on another host's devices — exactly what multi-host cannot do).
    """
    n = math.prod(mesh.devices.shape)
    data = mesh.shape.get(AXIS_DATA, 1)
    if n % host_count:
        raise ValueError(
            f"{n} mesh devices not divisible across {host_count} hosts")
    if data % host_count:
        raise ValueError(
            f"data axis {data} not divisible across {host_count} hosts — "
            f"per-host batch rows would straddle a host boundary")
