"""Core runtime: mesh construction, collectives, sharding rules, train step."""
