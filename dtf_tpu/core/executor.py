"""Fenced AOT program executor — the one place compiled programs are born.

Every compiled program in the codebase (train step, eval step, the serve
tier's prefill / decode-verify / draft pair / page load-save) used to
hand-roll the same four-part idiom: a trace-counting wrapper (the
recompile fence), a ``jax.jit`` with pinned ``out_shardings`` (so AOT
executables reject resharded inputs instead of silently re-laying-out),
version-gated donation, and a hand-written analysis "step view" twin so
the comms/memory budget fences cover the exact graph that serves. Ten
copies of that idiom had ten chances to drift.

:func:`program` is now the choke point. It returns a :class:`Program`
that owns all four concerns:

- **fence** — ``counts[name]`` increments once per TRACE (not per call),
  into whatever dict the caller shares (``DecodeEngine.trace_counts``,
  the telemetry ``CompileFence``); any post-steady-state increment is a
  shape-driven retrace and the owning test fails.
- **pins** — ``jit_kw`` carries ``in_shardings``/``out_shardings``
  verbatim; the executor adds nothing and removes nothing, so a
  program's compiled layout contract is exactly what its builder wrote.
- **donation** — ``donate=`` routes through
  :func:`dtf_tpu.core.train.donation_enabled`, the single version gate
  the analyzer's memory pass asserts (BACKFILLED jax must never donate:
  deserialized donated executables drop aliased outputs there).
- **step view** — ``abstract_args`` + ``arg_shardings`` register what
  the analysis registry needs: :meth:`Program.lower` with no arguments
  lowers against the registered abstracts, and
  ``dtf_tpu.analysis.configs.StepView.of`` reads ``arg_shardings`` for
  the resident-state memory model. Analysis step views enumerate a
  builder's program table instead of re-spelling its jit kwargs.

The srclint AOT fence (``raw-aot-compile``) makes this structural: raw
``.lower(``/``.compile(`` idioms outside this module (+ tune/ + tests)
are findings unless pinned with ``# aot-ok: <why>``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, MutableMapping, Optional

import jax

PyTree = Any


def fenced(name: str, body: Callable, counts: Optional[MutableMapping]):
    """Wrap ``body`` so each TRACE bumps ``counts[name]`` (no-op wrapper
    when ``counts`` is None). The wrapped body runs once per trace under
    ``jax.jit``, so a steady-state count above the compile-time value is
    a retrace — the fence every engine/trainer test pins."""
    if counts is None:
        return body
    counts.setdefault(name, 0)

    @functools.wraps(body)
    def wrapped(*args, **kwargs):
        counts[name] += 1
        return body(*args, **kwargs)

    return wrapped


def donation_argnums(donate: bool, argnums: tuple = (0,)) -> tuple:
    """The donation decision for a program: ``argnums`` when the caller
    asked AND :func:`dtf_tpu.core.train.donation_enabled` allows it on
    this jax, else ``()``. The gate itself stays in core/train.py — the
    analyzer's memory pass asserts it there by name."""
    # lazy: core/train.py imports this module at module level.
    from dtf_tpu.core.train import donation_enabled

    return tuple(argnums) if donation_enabled(donate) else ()


class Program:
    """A fenced program: the jitted callable plus its registration.

    Dispatch (``__call__``) and every jit-surface attribute (``trace``,
    ``eval_shape``, ...) delegate to the wrapped jit, so a Program is a
    drop-in for the raw ``jax.jit`` object it replaces. On top of that:

    - ``body`` — the unfenced python body, for analysis views that
      compose two programs into one lowered step;
    - ``abstract_args`` — the registered operand abstracts;
      :meth:`lower`/:meth:`aot` with no arguments use them;
    - ``arg_shardings`` — the declared input layouts the analysis
      memory pass prices (None = the abstract leaves carry their own);
    - ``compiled`` — the AOT executable after :meth:`aot` (None before).
    """

    def __init__(self, name: str, jitted: Callable, body: Callable, *,
                 abstract_args: Optional[tuple] = None,
                 arg_shardings: Any = None):
        self.name = name
        self.jitted = jitted
        self.body = body
        self.abstract_args = abstract_args
        self.arg_shardings = arg_shardings
        self.compiled = None

    def __call__(self, *args, **kwargs):
        return self.jitted(*args, **kwargs)

    def __getattr__(self, attr):
        # only reached for attributes not set in __init__ — the jit API
        # surface (trace, eval_shape, clear_cache, ...)
        return getattr(self.jitted, attr)

    def __repr__(self):
        return f"Program({self.name!r})"

    def lower(self, *args, **kwargs):
        """Lower against explicit operands, or the registered
        ``abstract_args`` when called bare."""
        if not args and not kwargs:
            if self.abstract_args is None:
                raise ValueError(
                    f"program {self.name!r} has no registered "
                    f"abstract_args; pass operands to lower()")
            args = self.abstract_args
        return self.jitted.lower(*args, **kwargs)

    def aot(self, *args, **kwargs):
        """lower→compile (the AOT idiom): returns the executable, which
        rejects resharded/reshaped operands instead of retracing. Also
        stored as ``self.compiled``. Traces the fenced body exactly
        once."""
        self.compiled = self.lower(*args, **kwargs).compile()
        return self.compiled


def program(name: str, body: Callable, *,
            counts: Optional[MutableMapping] = None,
            jit_kw: Optional[dict] = None,
            donate: Optional[bool] = None,
            donate_args: tuple = (0,),
            abstract_args: Optional[tuple] = None,
            arg_shardings: Any = None,
            table: Optional[MutableMapping] = None) -> Program:
    """Build a fenced :class:`Program` — the only sanctioned spelling of
    ``jax.jit(counted(fn), **pins)[.lower().compile()]``.

    ``jit_kw`` is passed to ``jax.jit`` verbatim (in/out sharding pins,
    static argnums). ``donate=None`` means the program has no donation
    decision (serve programs); a bool routes through
    :func:`donation_argnums`. ``table`` registers the program under
    ``name`` in the caller's program table.
    """
    kw = dict(jit_kw or {})
    if donate is not None:
        kw["donate_argnums"] = donation_argnums(donate, donate_args)
    prog = Program(name, jax.jit(fenced(name, body, counts), **kw), body,
                   abstract_args=abstract_args, arg_shardings=arg_shardings)
    if table is not None:
        table[name] = prog
    return prog
