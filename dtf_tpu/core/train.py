"""The pjit'd train step — successor of the reference's entire L2 session layer.

Reference capabilities replaced (SURVEY.md §3.1, §3.3):

- ``SyncReplicasOptimizer`` (TF ``sync_replicas_optimizer.py``): accumulate N
  worker gradients in PS-side ``ConditionalAccumulator``s, chief applies the
  *mean*, token queue releases workers. Here the same numerics — gradient =
  mean over the global batch — fall out of one compiled step: the batch is
  sharded over the ``data`` axis, the loss is a global mean, and XLA inserts
  the ICI all-reduce. Stale gradients cannot exist by construction; effective
  batch = global batch (= replicas × per-replica batch, as in the reference).
- Async-PS mode (``--issync=0``): intentionally racy hogwild updates. Not
  reproduced — synchronous SPMD is the semantic successor (behavioral delta
  documented in README).
- Gradient accumulation + ZeRO-1 (BASELINE config 4): microbatch scan in f32
  with optimizer state sharded over ``data`` (weight-update sharding).

Design: everything here is *one* jitted function over global arrays; the
ps/worker distinction, variable reads, and gradient pushes of the reference
are all inside XLA's partitioned program, riding ICI instead of gRPC.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dtf_tpu import _jax_compat as _compat
from dtf_tpu.core import executor
from dtf_tpu.core import sharding as shd
from dtf_tpu.core.comms import (batch_sharding, global_norm,
                                grad_reduce_scatter, shard_grads,
                                unshard_params)

PyTree = Any
#: loss_fn(params, extra, batch, rng) -> (loss, LossAux)
LossFn = Callable[..., tuple[jax.Array, "LossAux"]]


class LossAux(struct.PyTreeNode):
    """What a loss function returns besides the scalar loss.

    ``extra``: updated mutable collections (e.g. flax ``batch_stats``) — pass
    through unchanged if unused. ``metrics``: scalar diagnostics,
    weight-averaged across microbatches. ``weight``: this batch's
    contribution weight under
    gradient accumulation — losses that normalize by a data-dependent count
    (e.g. MLM valid positions) must return that count here so microbatch
    gradients combine as Σwᵢgᵢ/Σwᵢ (== the full-batch gradient) instead of a
    uniform mean.
    """

    extra: PyTree = struct.field(default_factory=dict)
    metrics: Mapping[str, jax.Array] = struct.field(default_factory=dict)
    weight: jax.Array | float = 1.0


class TrainState(struct.PyTreeNode):
    """Replicated-by-name successor of the reference's PS-resident state.

    The reference kept (variables, optimizer slots, global_step) on parameter
    servers; here they are one pytree, sharded by ``NamedSharding``, donated
    through the step. ``rng`` seeds per-step dropout etc. via fold_in(step).
    """

    step: jax.Array
    params: PyTree
    opt_state: PyTree
    extra: PyTree
    rng: jax.Array


@dataclasses.dataclass(frozen=True)
class StateShardings:
    """NamedSharding pytree matching TrainState, for jit in/out shardings."""

    state: TrainState  # of NamedShardings

    def batch(self, mesh: Mesh) -> NamedSharding:
        return batch_sharding(mesh)


def state_specs(
    init_fn: Callable[[jax.Array], PyTree],
    tx: optax.GradientTransformation,
    rng: jax.Array,
    mesh: Mesh,
    param_rules: Sequence[shd.Rule] = (),
    *,
    zero1: bool = True,
) -> TrainState:
    """PartitionSpec pytree (as a TrainState) for the full training state.

    ``init_fn(rng)`` must return the flax-style variables dict
    (``{"params": ..., [other collections...]}``).
    """
    abstract = jax.eval_shape(init_fn, rng)
    params = abstract["params"]
    extra = {k: v for k, v in abstract.items() if k != "params"}
    param_specs = shd.tree_specs(params, param_rules)
    if zero1:
        opt_specs = shd.zero1_opt_specs(tx, params, param_specs, mesh)
    else:
        opt_specs = shd.opt_specs_like_params(tx, params, param_specs)
    # Mutable collections (batch_stats) are small; replicate them.
    extra_specs = jax.tree.map(lambda _: P(), extra)
    return TrainState(step=P(), params=param_specs, opt_state=opt_specs,
                      extra=extra_specs, rng=P())


def state_shardings_from_specs(specs: TrainState, mesh: Mesh) -> TrainState:
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def _full_init(init_fn: Callable[[jax.Array], PyTree],
               tx: optax.GradientTransformation) -> Callable:
    """rng -> TrainState builder shared by real and abstract construction."""

    def init(rng):
        variables = init_fn(rng)
        params = variables["params"]
        extra = {k: v for k, v in variables.items() if k != "params"}
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            extra=extra,
            rng=rng,
        )

    return init


def create_train_state(
    init_fn: Callable[[jax.Array], PyTree],
    tx: optax.GradientTransformation,
    rng: jax.Array,
    mesh: Mesh,
    param_rules: Sequence[shd.Rule] = (),
    *,
    zero1: bool = True,
) -> tuple[TrainState, TrainState]:
    """Initialize a sharded TrainState directly on the mesh.

    Returns ``(state, shardings)``. Parameters materialize already sharded
    (init is jitted with out_shardings), so no host-side full copy exists —
    the moment the reference handled with chief-init + PS placement.
    """
    specs = state_specs(init_fn, tx, rng, mesh, param_rules, zero1=zero1)
    shardings = state_shardings_from_specs(specs, mesh)
    state = jax.jit(_full_init(init_fn, tx), out_shardings=shardings)(rng)
    return state, shardings


def abstract_train_state(
    init_fn: Callable[[jax.Array], PyTree],
    tx: optax.GradientTransformation,
    rng: jax.Array,
    mesh: Mesh,
    param_rules: Sequence[shd.Rule] = (),
    *,
    zero1: bool = True,
) -> tuple[TrainState, TrainState]:
    """:func:`create_train_state` without touching a device.

    Returns ``(abstract_state, shardings)`` where the state's leaves are
    ``jax.ShapeDtypeStruct``s — exactly what AOT lowering
    (``step.lower(abstract_state, abstract_batch)``) and the static
    analyzer (:mod:`dtf_tpu.analysis`) need: the compiled collective mix
    can be inspected with zero device memory or compute for the state.
    """
    specs = state_specs(init_fn, tx, rng, mesh, param_rules, zero1=zero1)
    shardings = state_shardings_from_specs(specs, mesh)
    abstract = jax.eval_shape(_full_init(init_fn, tx), rng)
    return abstract, shardings


def donation_enabled(donate: bool = True) -> bool:
    """The ONE donation gate: whether train steps may donate their state.

    On backfilled (pre-0.5) jax a DONATED executable deserialized from
    the persistent compile cache drops aliased outputs (warm-run BN
    stats freeze — see tests/conftest.py), so donation is version-gated
    off there.  Exposed as a hook so the static analyzer's memory pass
    can ASSERT the gate (``donation-on-backfilled-jax``: a registry
    program donating anything on backfilled jax means this gate was
    bypassed) instead of assuming a comment still matches the code.
    """
    return donate and not _compat.BACKFILLED


def make_train_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    shardings: TrainState,
    *,
    grad_accum: int = 1,
    grad_shard: bool = False,
    compute_dtype: jnp.dtype | None = None,
    log_grad_norm: bool = True,
    donate: bool = True,
    batch_shardings: PyTree | None = None,
    telemetry=None,
):
    """Build the compiled train step.

    ``loss_fn(params, extra, batch, rng) -> (loss, LossAux)`` computes the
    *mean* loss over its (global) batch — with the batch sharded over ``data``
    the resulting gradient is the mean over all replicas, which is exactly
    ``SyncReplicasOptimizer``'s aggregation semantics (SURVEY.md §3.3).

    ``grad_accum > 1``: the leading batch dim is split into ``grad_accum``
    microbatches scanned with ``lax.scan``, gradients accumulated in f32
    (BASELINE BERT config) as Σwᵢgᵢ/Σwᵢ with wᵢ = ``LossAux.weight`` (1.0 by
    default, giving the plain mean; count-normalized losses return their
    valid count so the result equals the full-batch gradient exactly).
    Loss and metrics combine with the same weights.

    ``grad_shard`` (with ``grad_accum > 1`` and a data axis > 1): ZeRO-1
    weight-update sharding for the accumulator (docs/ZERO.md). Each
    microbatch is split into its per-data-shard row groups (a vmapped
    loss call whose per-group gradients contract only over local rows, so
    nothing is reduced prematurely), the weighted per-group gradients are
    reduce-scattered over ``data`` into a 1/N-sized f32 shard accumulator
    inside the scan (the ``comms.grad_reduce_scatter`` choke point — half
    the bytes of the full all-reduce the replicated path issues per
    microbatch, overlapping the next microbatch's compute), the optimizer
    update runs on the gradient/param shard against the already-sharded
    ZeRO-1 optimizer state, and updated params are all-gathered back to
    their rulebook layout once per step (``comms.unshard_params``).
    Numerics are exact: the Σwᵢgᵢ/Σwᵢ weighting composes over the finer
    shard×microbatch grid (per-group count weights combine to the same
    full-batch gradient — bitwise on integer data); only the per-group
    dropout rng assignment differs (``fold_in(mb_rng, group)`` instead of
    one global mask per microbatch). Falls back to the replicated
    accumulator when ``data == 1``, when mutable collections are in play
    (``extra`` leaves cannot thread through shard-stacked loss calls),
    and per-leaf for params with no data-divisible dim.
    """

    def grads_of(params, extra, micro, rng):
        if compute_dtype is not None:
            micro = jax.tree.map(
                lambda x: x.astype(compute_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, micro)
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, extra, micro, rng)
        return loss, aux, grads

    param_specs = jax.tree.map(lambda s: s.spec, shardings.params)

    def step_fn(state: TrainState, batch: PyTree) -> tuple[TrainState, dict]:
        rng = jax.random.fold_in(state.rng, state.step)
        # set on the sharded-accumulator path; gates the shard-domain
        # optimizer update + the closing param all-gather below.
        shard_specs = None

        if grad_accum == 1:
            loss, aux, grads = grads_of(state.params, state.extra, batch, rng)
            metrics = dict(aux.metrics)
            extra = aux.extra
        else:
            data_size = mesh.shape.get("data", 1)
            # sharded-accumulation viability: a real data axis, and no
            # mutable collections — the per-shard-group loss calls each
            # produce their own `extra`, which cannot be threaded back
            # into one carry. The replicated path below stays bit-exact
            # with today's behavior whenever this is False.
            if (grad_shard and data_size > 1
                    and not jax.tree.leaves(state.extra)):
                shard_specs = shd.zero1_param_shard_specs(
                    state.params, param_specs, mesh)

            def to_micro(x, sh=None):
                if x.shape[0] % grad_accum or (
                        x.shape[0] // grad_accum) % data_size:
                    raise ValueError(
                        f"global batch {x.shape[0]} with grad_accum="
                        f"{grad_accum} gives microbatch "
                        f"{x.shape[0] // grad_accum}, which must be divisible "
                        f"by the data axis ({data_size} shards)")
                # scan (microbatch) axis replicated; the remaining dims keep
                # the leaf's batch sharding (e.g. P('data','seq') token ids
                # stay seq-sharded — hardcoding None here would all-gather
                # the sequence and defeat context parallelism).
                spec = tuple(sh.spec) if sh is not None else ("data",)
                spec = spec + (None,) * (x.ndim - len(spec))
                m = x.shape[0] // grad_accum
                if shard_specs is not None:
                    # split each microbatch into its per-data-shard row
                    # groups: [accum, n_data, rows/shard, ...], group axis
                    # on `data` so slot k IS shard k's local rows.
                    y = x.reshape(
                        (grad_accum, data_size, m // data_size) + x.shape[1:])
                    full = P(None, "data", None, *spec[1:])
                else:
                    y = x.reshape((grad_accum, m) + x.shape[1:])
                    full = P(None, *spec)
                return jax.lax.with_sharding_constraint(
                    y, NamedSharding(mesh, full))

            if batch_shardings is None:
                micro = jax.tree.map(to_micro, batch)
            else:
                micro = jax.tree.map(to_micro, batch, batch_shardings)

            def body(carry, mb):
                acc, w_sum, extra, i = carry
                mb_rng = jax.random.fold_in(rng, i)
                if shard_specs is not None:
                    # per-shard-group gradients: each vmap slot contracts
                    # only over its own (local) rows, so slot k holds
                    # shard k's UNREDUCED partial — the value the explicit
                    # reduce-scatter below sums and scatters in one
                    # collective. Σwᵢgᵢ/Σwᵢ runs over the finer
                    # group×microbatch grid, which combines to exactly the
                    # full-batch gradient (weights are per-group counts).
                    loss, aux, grads = jax.vmap(
                        lambda mb_k, k: grads_of(
                            state.params, extra, mb_k,
                            jax.random.fold_in(mb_rng, k)))(
                        mb, jnp.arange(data_size))
                    w = jnp.broadcast_to(
                        jnp.asarray(aux.weight, jnp.float32), (data_size,))
                    # a group whose weight is 0 (e.g. no masked MLM
                    # positions among its rows) may carry a 0/0 loss and
                    # NaN gradients from the loss's own count
                    # normalization; its Σwᵢgᵢ/Σwᵢ contribution is exactly
                    # zero either way, so select — don't multiply — it out
                    # (0·NaN would poison the accumulator).
                    def wmul(v):
                        wb = w[(...,) + (None,) * (v.ndim - 1)]
                        return jnp.where(wb > 0, v.astype(jnp.float32) * wb,
                                         0.0)

                    acc = jax.tree.map(
                        lambda a, r: a + r,
                        acc, grad_reduce_scatter(
                            jax.tree.map(wmul, grads), mesh, param_specs,
                            shard_specs))
                    # emit PRE-weighted per-microbatch sums; the post-scan
                    # combine divides the stacked sums by w_sum directly.
                    return ((acc, w_sum + w.sum(), extra, i + 1),
                            (wmul(loss).sum(), w.sum(),
                             jax.tree.map(lambda m: wmul(m).sum(),
                                          aux.metrics)))
                loss, aux, grads = grads_of(state.params, extra, mb, mb_rng)
                w = jnp.asarray(aux.weight, jnp.float32)
                acc = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32) * w, acc, grads)
                return ((acc, w_sum + w, aux.extra, i + 1),
                        (loss * w, w, aux.metrics))

            acc0 = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
            if shard_specs is not None:
                acc0 = shard_grads(acc0, mesh, shard_specs)
            (grads, w_sum, extra, _), (losses, ws, metric_seq) = jax.lax.scan(
                body,
                (acc0, jnp.zeros((), jnp.float32), state.extra,
                 jnp.zeros((), jnp.int32)),
                micro)
            grads = jax.tree.map(
                lambda g, p: (g / w_sum).astype(p.dtype), grads, state.params)
            if shard_specs is not None:
                grads = shard_grads(grads, mesh, shard_specs)
            loss = losses.sum() / w_sum
            # sharded path stacks PRE-weighted metric sums (see body);
            # replicated path stacks raw per-microbatch means.
            metrics = jax.tree.map(
                lambda m: (m if shard_specs is not None
                           else m * ws).sum() / w_sum, dict(metric_seq))

        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        if shard_specs is not None:
            # keep the update math in the shard domain (1/N of the
            # elementwise optimizer FLOPs per replica, against the
            # already-sharded ZeRO-1 moments) ...
            updates = shard_grads(updates, mesh, shard_specs)
        new_params = optax.apply_updates(state.params, updates)
        if shard_specs is not None:
            # ... and close with the ONE param all-gather per step.
            new_params = unshard_params(new_params, mesh, param_specs)
        metrics["loss"] = loss
        if log_grad_norm:
            metrics["grad_norm"] = global_norm(grads)
        new_state = state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt,
            extra=extra)
        return new_state, metrics

    # batch_shardings: a full pytree (from comms.batch_shardings_for) when
    # leaves need rank-dependent specs (e.g. P('data','seq') for [B,T] token
    # ids but P('data') for [B] labels); default is the P('data') prefix.
    batch_sh = (batch_shardings if batch_shardings is not None
                else batch_sharding(mesh))
    if telemetry is not None:
        # the wrapped body runs once per TRACE (not per call): the compile
        # fence pins Trainer.trace_counts["train_step"] at 1 in steady
        # state, the DecodeEngine.trace_counts contract for training.
        step_fn = telemetry.count_traces("train_step", step_fn)
    # donation is version-gated through donation_enabled() — the
    # analyzer's memory pass asserts the gate; the executor routes
    # donate= through it (executor.donation_argnums).
    return executor.program(
        "train_step", step_fn, donate=donate,
        jit_kw=dict(in_shardings=(shardings, batch_sh),
                    out_shardings=(shardings, NamedSharding(mesh, P()))),
        arg_shardings=(shardings, batch_sh),
    )


def make_train_step_from_grads(
    grads_fn: Callable[..., tuple[jax.Array, "LossAux", PyTree]],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    shardings: TrainState,
    *,
    log_grad_norm: bool = True,
    donate: bool = True,
    batch_shardings: PyTree | None = None,
    telemetry=None,
):
    """Train step for losses that produce their own gradients.

    ``grads_fn(params, extra, batch, rng) -> (loss, LossAux, grads)`` with
    ``grads`` matching the params tree — for paths where ``jax.grad`` over
    the loss would destroy the schedule the gradients must be computed
    under, e.g. the fused-1F1B pipeline
    (:func:`dtf_tpu.parallel.pipeline.pipeline_1f1b_grads`), whose O(S)
    activation stash only exists because forward and backward interleave in
    one scan. Microbatching lives inside such a ``grads_fn``, so there is
    no ``grad_accum`` here; optimizer update and metrics handling are
    identical to :func:`make_train_step`.
    """

    def step_fn(state: TrainState, batch: PyTree) -> tuple[TrainState, dict]:
        rng = jax.random.fold_in(state.rng, state.step)
        loss, aux, grads = grads_fn(state.params, state.extra, batch, rng)
        metrics = dict(aux.metrics)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics["loss"] = loss
        if log_grad_norm:
            metrics["grad_norm"] = global_norm(grads)
        new_state = state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt,
            extra=aux.extra)
        return new_state, metrics

    batch_sh = (batch_shardings if batch_shardings is not None
                else batch_sharding(mesh))
    if telemetry is not None:
        # same retrace fence as make_train_step (one program name: the
        # trainer runs exactly one step program either way)
        step_fn = telemetry.count_traces("train_step", step_fn)
    # same executor routing (and donation gate) as make_train_step.
    return executor.program(
        "train_step", step_fn, donate=donate,
        jit_kw=dict(in_shardings=(shardings, batch_sh),
                    out_shardings=(shardings, NamedSharding(mesh, P()))),
        arg_shardings=(shardings, batch_sh),
    )


def make_eval_step(eval_fn: Callable, mesh: Mesh, shardings: TrainState, *,
                   batch_shardings: PyTree | None = None, telemetry=None):
    """Compiled eval step: ``eval_fn(params, extra, batch) -> metrics dict``.

    ``batch_shardings``: override the default data-axis batch placement —
    REQUIRED under sequence parallelism (P('data','seq') batches), exactly
    like ``make_train_step``'s parameter of the same name; a committed
    input whose sharding disagrees with in_shardings makes jit raise.
    """

    def step_fn(state: TrainState, batch: PyTree):
        return eval_fn(state.params, state.extra, batch)

    if telemetry is not None:
        step_fn = telemetry.count_traces("eval_step", step_fn)
    # `is not None`, not truthiness: a falsy-but-valid shardings pytree
    # must not silently degrade to the default placement (same rule as
    # make_train_step's parameter of this name).
    batch_sh = (batch_shardings if batch_shardings is not None
                else batch_sharding(mesh))
    return executor.program(
        "eval_step", step_fn,
        jit_kw=dict(in_shardings=(shardings, batch_sh),
                    out_shardings=NamedSharding(mesh, P())),
        arg_shardings=(shardings, batch_sh),
    )
