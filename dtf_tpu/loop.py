"""The monitored training loop — successor of MonitoredTrainingSession.

Reference capability replaced (SURVEY.md §3.4, §5.3): the reference wraps
``tf.Session`` in ``MonitoredSession`` (hook dispatch) and
``_RecoverableSession`` (on worker failure: rebuild the session, restore the
last checkpoint, continue). Here the loop is plain host Python around one
compiled step; recovery keeps the same semantics via checkpoint-restart —
``Trainer.fit`` restores the latest checkpoint if one exists before training
(crash → relaunch → resume), which is exactly the reference's story minus the
in-process session rebuild (a dead process is relaunched by the cluster
manager either way).

Observability (``telemetry=``, docs/OBSERVABILITY.md): with a
:class:`dtf_tpu.telemetry.Telemetry` attached, each iteration is split into
host-side phase spans — ``data_wait`` (batch production), ``h2d`` (the
``place_batch`` dispatch), ``dispatch`` (the async train-step call),
``hooks`` — wrapped in a ``jax.profiler.StepTraceAnnotation`` so XPlane
traces correlate with the host spans, and fed to the crash flight recorder.
Every measurement is ``time.perf_counter`` arithmetic: telemetry adds ZERO
blocking device readbacks to the hot path (the PR 3 sync-free invariant,
regression-tested with the counter-instrumented idiom), and the srclint
hot-path fence keeps it that way statically.
"""

from __future__ import annotations

import contextlib
import itertools
import logging
import time
from typing import Any, Callable, Iterable, Sequence

from dtf_tpu.checkpoint import Checkpointer
from dtf_tpu.core.comms import shard_batch
from dtf_tpu.data.prefetch import prefetch_to_device
from dtf_tpu.hooks import Hook, StopTraining

PyTree = Any
log = logging.getLogger("dtf_tpu")


class Trainer:
    """Hook-driven loop over a compiled train step.

    ``train_step(state, batch) -> (state, metrics)`` is the jitted function
    from :func:`dtf_tpu.core.train.make_train_step`. ``place_batch`` maps a
    host batch onto the mesh (defaults to data-axis sharding; multi-host
    pipelines pass ``comms.host_local_to_global``-based placement).
    ``telemetry`` (optional) is the run's :class:`~dtf_tpu.telemetry.Telemetry`
    — pass the SAME object to :func:`~dtf_tpu.core.train.make_train_step` so
    :attr:`trace_counts` pins the step program's retraces.
    """

    def __init__(
        self,
        train_step: Callable[[PyTree, PyTree], tuple[PyTree, dict]],
        mesh,
        hooks: Sequence[Hook] = (),
        *,
        checkpointer: Checkpointer | None = None,
        place_batch: Callable | None = None,
        prefetch: int = 2,
        telemetry=None,
    ):
        self.train_step = train_step
        self.mesh = mesh
        self.hooks = list(hooks)
        self.checkpointer = checkpointer
        self.place_batch = place_batch or (
            lambda batch: shard_batch(batch, self.mesh))
        # device-side double buffering: batch N+1's H2D transfer dispatches
        # while step N computes (dtf_tpu/data/prefetch.py). 1 = off.
        self.prefetch = prefetch
        self.telemetry = telemetry

    @property
    def trace_counts(self) -> dict:
        """Traces per program — the ``DecodeEngine.trace_counts`` twin.
        Steady state must stay pinned at 1 per program; needs the same
        telemetry object threaded through ``make_train_step``."""
        return self.telemetry.trace_counts if self.telemetry else {}

    def _run_hooks(self, method: str, *args) -> float:
        """Dispatch one lifecycle method to every hook; with telemetry,
        time each hook into its goodput bucket (``telemetry_bucket`` class
        attribute — checkpoint/eval/logging/...). Returns elapsed."""
        tel = self.telemetry
        if tel is None:
            for h in self.hooks:
                getattr(h, method)(*args)
            return 0.0
        t_all = time.perf_counter()
        for h in self.hooks:
            t0 = time.perf_counter()
            try:
                getattr(h, method)(*args)
            finally:
                tel.account(getattr(h, "telemetry_bucket", "hooks"),
                            time.perf_counter() - t0)
        return time.perf_counter() - t_all

    def fit(self, state: PyTree, batches: Iterable[PyTree],
            *, max_steps: int | None = None) -> PyTree:
        """Run until the iterator ends, a hook stops training, or max_steps.

        Restore-if-exists first (``ChiefSessionCreator`` semantics): if the
        checkpointer has a saved step, training resumes from it — the
        relaunch path after a failure needs no special casing.
        """
        tel = self.telemetry
        _pc = time.perf_counter
        if tel is not None:
            # wall window opens BEFORE restore/begin: the seconds those
            # account into goodput buckets must fall inside the window
            tel.open_wall()
        if self.checkpointer is not None:
            t0 = _pc()
            state, restored = self.checkpointer.restore_if_exists(state)
            if tel is not None:
                # the relaunch-overhead goodput bucket: restore cost only
                # exists because something died (docs/OBSERVABILITY.md)
                tel.account("restore", _pc() - t0)
            if restored is not None:
                log.info("resumed from checkpoint at step %d", restored)

        self._run_hooks("begin", state)
        # telemetry starts AFTER hook begin: its SIGTERM postmortem hook
        # must chain OUTSIDE PreemptionHook's handler (ours dumps, then
        # theirs checkpoints), and signal restore order is LIFO below.
        if tel is not None:
            tel.start()
        # ONE device sync, at the resume point: `state.step` is a device
        # array whose int() blocks on the previous step's completion, so
        # reading it every iteration (as this loop once did) serializes
        # dispatch against compute and defeats the prefetch double-buffer.
        # After this read the counter lives on the host — train_step
        # advances the device counter by exactly 1 per call (the
        # make_train_step contract), so the two never diverge; hooks that
        # want device values (metrics, checkpoints) still block only when
        # THEY materialize them, at their own every_n cadence.
        step = int(state.step)
        # Bound the source to exactly the steps this call can run, so the
        # prefetch lookahead can never pull batches past max_steps out of a
        # (possibly shared) iterator — including the already-done resume
        # case, which stays a strict no-op. Hook-driven early stops
        # (StopTraining) can still discard up to depth-1 staged batches;
        # that lookahead is inherent to prefetching.
        src = batches
        if max_steps is not None:
            src = itertools.islice(batches, max(max_steps - step, 0))
        place = self.place_batch
        if tel is not None:
            base_place = place

            def place(b, _base=base_place):
                t0 = _pc()
                try:
                    return _base(b)
                finally:
                    dt = _pc() - t0
                    tel.spans.add("h2d", dt)
                    tel.account("h2d", dt)

        staged = prefetch_to_device(src, place, max(self.prefetch, 1))
        try:
            while True:
                if max_steps is not None and step >= max_steps:
                    break
                t_iter = _pc()
                h2d_before = tel.spans.total("h2d") if tel is not None else 0.0
                try:
                    batch = next(staged)
                except StopIteration:
                    break
                if tel is not None:
                    # batch-production time net of the H2D dispatches that
                    # ran inside this next() — the two phases stay disjoint.
                    # The span itself is added by note_step below (once).
                    dw = max((_pc() - t_iter)
                             - (tel.spans.total("h2d") - h2d_before), 0.0)
                    tel.account("data_wait", dw)
                ann = (tel.step_annotation(step) if tel is not None
                       else contextlib.nullcontext())
                with ann:
                    self._run_hooks("before_step", step)
                    t_d = _pc()
                    state, metrics = self.train_step(state, batch)
                    t_hooks = _pc()
                    step += 1
                    try:
                        self._run_hooks("after_step", step, state, metrics)
                    finally:
                        # record even when a hook ends the run (StopTraining
                        # at the last step) or crashes — the postmortem must
                        # include the step that was in flight
                        if tel is not None:
                            t_end = _pc()
                            tel.note_step(step, {
                                "step_s": t_end - t_iter,
                                "data_wait_s": dw,
                                "dispatch_s": t_hooks - t_d,
                                "hooks_s": t_end - t_hooks,
                            })
        except StopTraining:
            pass
        except BaseException as e:
            # the flight recorder's reason-to-exist: the last N step
            # records hit disk before the stack unwinds (stalls and
            # SIGTERM have their own dump paths in telemetry/flight.py)
            if tel is not None:
                tel.dump_postmortem("crash", {
                    "step": step, "error": repr(e)[:500]})
            raise
        finally:
            # LIFO signal teardown: telemetry restores PreemptionHook's
            # SIGTERM handler, then the hook's end() restores the original.
            if tel is not None:
                tel.stop()
            self._run_hooks("end", state)
            if tel is not None:
                # end hooks (final save + barrier) accounted above still
                # belong inside the goodput wall window
                tel.close_wall()
        return state
