"""The monitored training loop — successor of MonitoredTrainingSession.

Reference capability replaced (SURVEY.md §3.4, §5.3): the reference wraps
``tf.Session`` in ``MonitoredSession`` (hook dispatch) and
``_RecoverableSession`` (on worker failure: rebuild the session, restore the
last checkpoint, continue). Here the loop is plain host Python around one
compiled step; recovery keeps the same semantics via checkpoint-restart —
``Trainer.fit`` restores the latest checkpoint if one exists before training
(crash → relaunch → resume), which is exactly the reference's story minus the
in-process session rebuild (a dead process is relaunched by the cluster
manager either way).
"""

from __future__ import annotations

import itertools
import logging
from typing import Any, Callable, Iterable, Sequence

from dtf_tpu.checkpoint import Checkpointer
from dtf_tpu.core.comms import shard_batch
from dtf_tpu.data.prefetch import prefetch_to_device
from dtf_tpu.hooks import Hook, StopTraining

PyTree = Any
log = logging.getLogger("dtf_tpu")


class Trainer:
    """Hook-driven loop over a compiled train step.

    ``train_step(state, batch) -> (state, metrics)`` is the jitted function
    from :func:`dtf_tpu.core.train.make_train_step`. ``place_batch`` maps a
    host batch onto the mesh (defaults to data-axis sharding; multi-host
    pipelines pass ``comms.host_local_to_global``-based placement).
    """

    def __init__(
        self,
        train_step: Callable[[PyTree, PyTree], tuple[PyTree, dict]],
        mesh,
        hooks: Sequence[Hook] = (),
        *,
        checkpointer: Checkpointer | None = None,
        place_batch: Callable | None = None,
        prefetch: int = 2,
    ):
        self.train_step = train_step
        self.mesh = mesh
        self.hooks = list(hooks)
        self.checkpointer = checkpointer
        self.place_batch = place_batch or (
            lambda batch: shard_batch(batch, self.mesh))
        # device-side double buffering: batch N+1's H2D transfer dispatches
        # while step N computes (dtf_tpu/data/prefetch.py). 1 = off.
        self.prefetch = prefetch

    def fit(self, state: PyTree, batches: Iterable[PyTree],
            *, max_steps: int | None = None) -> PyTree:
        """Run until the iterator ends, a hook stops training, or max_steps.

        Restore-if-exists first (``ChiefSessionCreator`` semantics): if the
        checkpointer has a saved step, training resumes from it — the
        relaunch path after a failure needs no special casing.
        """
        if self.checkpointer is not None:
            state, restored = self.checkpointer.restore_if_exists(state)
            if restored is not None:
                log.info("resumed from checkpoint at step %d", restored)

        for h in self.hooks:
            h.begin(state)
        # ONE device sync, at the resume point: `state.step` is a device
        # array whose int() blocks on the previous step's completion, so
        # reading it every iteration (as this loop once did) serializes
        # dispatch against compute and defeats the prefetch double-buffer.
        # After this read the counter lives on the host — train_step
        # advances the device counter by exactly 1 per call (the
        # make_train_step contract), so the two never diverge; hooks that
        # want device values (metrics, checkpoints) still block only when
        # THEY materialize them, at their own every_n cadence.
        step = int(state.step)
        # Bound the source to exactly the steps this call can run, so the
        # prefetch lookahead can never pull batches past max_steps out of a
        # (possibly shared) iterator — including the already-done resume
        # case, which stays a strict no-op. Hook-driven early stops
        # (StopTraining) can still discard up to depth-1 staged batches;
        # that lookahead is inherent to prefetching.
        src = batches
        if max_steps is not None:
            src = itertools.islice(batches, max(max_steps - step, 0))
        staged = prefetch_to_device(src, self.place_batch,
                                    max(self.prefetch, 1))
        try:
            for batch in staged:
                if max_steps is not None and step >= max_steps:
                    break
                for h in self.hooks:
                    h.before_step(step)
                state, metrics = self.train_step(state, batch)
                step += 1
                for h in self.hooks:
                    h.after_step(step, state, metrics)
        except StopTraining:
            pass
        finally:
            for h in self.hooks:
                h.end(state)
        return state
