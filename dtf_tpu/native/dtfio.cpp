// dtfio — native data-loading runtime for dtf_tpu.
//
// TPU-native successor of the reference's C++ input machinery (SURVEY.md §2b
// N7): TF's FIFOQueue kernels + queue runners fed the session from C++
// threads; here a small C library does the host-side heavy lifting — mmap'd
// IDX parsing, per-epoch Fisher-Yates shuffling, u8→f32 normalization, batch
// gather — on a background prefetch thread with a double buffer, so Python
// only ever memcpy's a ready batch while the TPU computes.
//
// C ABI only (consumed via ctypes from dtf_tpu/data/native.py). No JAX/TF
// headers; the contract is plain arrays.
//
// Build: make -C dtf_tpu/native   (g++ -O3 -shared -fPIC -pthread)

#include <array>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// IDX container parsing (big-endian header, u8 payload), mmap'd read-only.
// ---------------------------------------------------------------------------

struct IdxFile {
  int fd = -1;
  const uint8_t* map = nullptr;
  size_t map_len = 0;
  const uint8_t* data = nullptr;  // payload start
  std::vector<uint32_t> dims;
  size_t items = 0;      // dims[0]
  size_t item_size = 0;  // product of dims[1:]

  bool open(const char* path) {
    fd = ::open(path, O_RDONLY);
    if (fd < 0) return false;
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size < 4) return false;
    map_len = static_cast<size_t>(st.st_size);
    map = static_cast<const uint8_t*>(
        mmap(nullptr, map_len, PROT_READ, MAP_PRIVATE, fd, 0));
    if (map == MAP_FAILED) { map = nullptr; return false; }
    // magic: 0x00 0x00 dtype ndim ; only u8 (0x08) supported.
    if (map[0] != 0 || map[1] != 0 || map[2] != 0x08) return false;
    const unsigned ndim = map[3];
    if (map_len < 4 + 4ul * ndim) return false;
    dims.resize(ndim);
    size_t total = 1;
    for (unsigned i = 0; i < ndim; ++i) {
      const uint8_t* p = map + 4 + 4 * i;
      dims[i] = (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
                (uint32_t(p[2]) << 8) | uint32_t(p[3]);
      total *= dims[i];
    }
    if (map_len < 4 + 4ul * ndim + total) return false;
    data = map + 4 + 4 * ndim;
    items = ndim ? dims[0] : 0;
    item_size = items ? total / items : 0;
    return true;
  }

  void close() {
    if (map) munmap(const_cast<uint8_t*>(map), map_len);
    if (fd >= 0) ::close(fd);
    map = nullptr; fd = -1;
  }
};

// splitmix64 — deterministic, seedable, platform-independent shuffling.
static inline uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9e3779b97f4a7c15ull);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

struct Loader {
  IdxFile images, labels;
  size_t batch = 0;
  uint64_t seed = 0;
  size_t host_index = 0, host_count = 1;

  // epoch state (owned by the prefetch thread)
  std::vector<uint32_t> order;   // this host's shard of the epoch permutation
  size_t cursor = 0;
  uint64_t epoch = 0;

  // double buffer
  std::vector<float> buf_images[2];
  std::vector<int32_t> buf_labels[2];
  int ready_slot = -1;           // filled slot index, -1 = none
  bool stop = false;
  std::mutex mu;
  std::condition_variable cv_ready, cv_taken;
  std::thread worker;

  void reshuffle() {
    const size_t n = images.items;
    std::vector<uint32_t> perm(n);
    for (size_t i = 0; i < n; ++i) perm[i] = uint32_t(i);
    uint64_t s = seed * 0x9e3779b97f4a7c15ull + epoch + 1;
    for (size_t i = n - 1; i > 0; --i) {
      size_t j = splitmix64(s) % (i + 1);
      std::swap(perm[i], perm[j]);
    }
    order.clear();
    for (size_t i = host_index; i < n; i += host_count)
      order.push_back(perm[i]);
    cursor = 0;
  }

  void fill(int slot) {
    const size_t isz = images.item_size;
    float* out = buf_images[slot].data();
    int32_t* lab = buf_labels[slot].data();
    for (size_t b = 0; b < batch; ++b) {
      if (cursor >= order.size()) {  // epoch boundary: batches may span it
        ++epoch;
        reshuffle();
      }
      const uint32_t idx = order[cursor++];
      const uint8_t* src = images.data + size_t(idx) * isz;
      float* dst = out + b * isz;
      constexpr float kScale = 1.0f / 255.0f;
      for (size_t i = 0; i < isz; ++i) dst[i] = src[i] * kScale;
      lab[b] = labels.data[idx];
    }
  }

  void run() {
    int slot = 0;
    while (true) {
      fill(slot);  // compute outside the lock
      {
        std::unique_lock<std::mutex> l(mu);
        cv_taken.wait(l, [&] { return ready_slot == -1 || stop; });
        if (stop) return;
        ready_slot = slot;
      }
      cv_ready.notify_one();
      slot ^= 1;
    }
  }
};

// ---------------------------------------------------------------------------
// TFRecord framing (the reference ecosystem's on-disk format): each record is
//   u64le payload_length | u32le masked_crc32c(length bytes)
//   payload              | u32le masked_crc32c(payload)
// This indexer mmaps the file, walks the framing once (verifying CRCs), and
// hands Python an offset/length table; payload bytes are then sliced straight
// out of the mapping (np.memmap) with no copies. Software CRC32C — no SSE4.2
// dependency, and indexing is one pass at open time.
// ---------------------------------------------------------------------------

static const uint32_t* crc32c_table() {
  // magic static: thread-safe one-time init (ctypes calls drop the GIL, so
  // concurrent first-opens from two Python threads are real).
  static const auto table = [] {
    std::array<uint32_t, 256> t{};
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? (0x82f63b78u ^ (c >> 1)) : (c >> 1);
      t[i] = c;
    }
    return t;
  }();
  return table.data();
}

static uint32_t crc32c(const uint8_t* p, size_t n) {
  const uint32_t* t = crc32c_table();
  uint32_t c = 0xffffffffu;
  for (size_t i = 0; i < n; ++i) c = t[(c ^ p[i]) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

static inline uint32_t masked_crc32c(const uint8_t* p, size_t n) {
  uint32_t c = crc32c(p, n);
  return ((c >> 15) | (c << 17)) + 0xa282ead8u;
}

static inline uint32_t load_u32le(const uint8_t* p) {
  return uint32_t(p[0]) | (uint32_t(p[1]) << 8) | (uint32_t(p[2]) << 16) |
         (uint32_t(p[3]) << 24);
}

static inline uint64_t load_u64le(const uint8_t* p) {
  return uint64_t(load_u32le(p)) | (uint64_t(load_u32le(p + 4)) << 32);
}

namespace {

struct TfrIndex {
  int fd = -1;
  const uint8_t* map = nullptr;
  size_t map_len = 0;
  std::vector<uint64_t> off, len;  // payload spans

  bool open(const char* path, bool verify_payload_crc) {
    fd = ::open(path, O_RDONLY);
    if (fd < 0) return false;
    struct stat st;
    if (fstat(fd, &st) != 0) return false;
    map_len = static_cast<size_t>(st.st_size);
    if (map_len == 0) return true;  // empty file = zero records, valid
    map = static_cast<const uint8_t*>(
        mmap(nullptr, map_len, PROT_READ, MAP_PRIVATE, fd, 0));
    if (map == MAP_FAILED) { map = nullptr; return false; }
    size_t pos = 0;
    while (pos < map_len) {
      if (map_len - pos < 12) return false;          // truncated header
      const uint64_t n = load_u64le(map + pos);
      // The length CRC is always checked: it is 12 bytes of work and the
      // only guard against walking garbage after a corrupt/truncated write.
      if (load_u32le(map + pos + 8) != masked_crc32c(map + pos, 8))
        return false;
      // overflow-safe truncation check: `n + 4` could wrap for a crafted
      // header whose (CRC-valid-by-chance) length is near 2^64.
      if (map_len - pos - 12 < 4 || n > map_len - pos - 16)
        return false;  // truncated payload
      if (verify_payload_crc &&
          load_u32le(map + pos + 12 + n) != masked_crc32c(map + pos + 12, n))
        return false;
      off.push_back(pos + 12);
      len.push_back(n);
      pos += 12 + n + 4;
    }
    return true;
  }

  void close() {
    if (map) munmap(const_cast<uint8_t*>(map), map_len);
    if (fd >= 0) ::close(fd);
    map = nullptr; fd = -1;
  }
};

}  // namespace

}  // namespace

extern "C" {

// TFRecord index: returns an opaque handle, or nullptr on bad framing / CRC
// mismatch / IO error. verify_payload_crc=0 skips the O(file) payload CRC
// pass (length CRCs are always checked).
void* dtfio_tfrecord_open(const char* path, int verify_payload_crc) {
  auto* T = new TfrIndex();
  if (!T->open(path, verify_payload_crc != 0)) {
    T->close(); delete T;
    return nullptr;
  }
  return T;
}

long long dtfio_tfrecord_count(void* handle) {
  return static_cast<long long>(static_cast<TfrIndex*>(handle)->off.size());
}

// Fills caller-allocated arrays of dtfio_tfrecord_count() u64 entries with
// each record's payload byte offset and length within the file.
void dtfio_tfrecord_spans(void* handle, unsigned long long* off_out,
                          unsigned long long* len_out) {
  auto* T = static_cast<TfrIndex*>(handle);
  for (size_t i = 0; i < T->off.size(); ++i) {
    off_out[i] = T->off[i];
    len_out[i] = T->len[i];
  }
}

void dtfio_tfrecord_close(void* handle) {
  auto* T = static_cast<TfrIndex*>(handle);
  T->close();
  delete T;
}

// Returns an opaque handle or nullptr. Batch is the HOST-LOCAL batch size.
void* dtfio_loader_create(const char* images_path, const char* labels_path,
                          size_t batch, uint64_t seed, size_t host_index,
                          size_t host_count) {
  auto* L = new Loader();
  if (!L->images.open(images_path) || !L->labels.open(labels_path) ||
      L->images.items == 0 || L->images.items != L->labels.items ||
      L->labels.item_size != 1 ||  // labels must be a 1-D idx1 file
      batch == 0 || host_count == 0 || host_index >= host_count ||
      L->images.items / host_count < batch) {
    L->images.close(); L->labels.close(); delete L;
    return nullptr;
  }
  L->batch = batch; L->seed = seed;
  L->host_index = host_index; L->host_count = host_count;
  for (int s = 0; s < 2; ++s) {
    L->buf_images[s].resize(batch * L->images.item_size);
    L->buf_labels[s].resize(batch);
  }
  L->reshuffle();
  L->worker = std::thread([L] { L->run(); });
  return L;
}

size_t dtfio_item_size(void* handle) {
  return static_cast<Loader*>(handle)->images.item_size;
}

size_t dtfio_num_items(void* handle) {
  return static_cast<Loader*>(handle)->images.items;
}

// Blocks until the prefetched batch is ready, copies it out, and wakes the
// prefetch thread to fill the next one. images_out: batch*item_size floats;
// labels_out: batch int32.
void dtfio_loader_next(void* handle, float* images_out, int32_t* labels_out) {
  auto* L = static_cast<Loader*>(handle);
  int slot;
  {
    std::unique_lock<std::mutex> l(L->mu);
    L->cv_ready.wait(l, [&] { return L->ready_slot != -1; });
    slot = L->ready_slot;
    std::memcpy(images_out, L->buf_images[slot].data(),
                L->buf_images[slot].size() * sizeof(float));
    std::memcpy(labels_out, L->buf_labels[slot].data(),
                L->buf_labels[slot].size() * sizeof(int32_t));
    L->ready_slot = -1;
  }
  L->cv_taken.notify_one();
}

void dtfio_loader_destroy(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> l(L->mu);
    L->stop = true;
  }
  L->cv_taken.notify_all();
  if (L->worker.joinable()) L->worker.join();
  L->images.close();
  L->labels.close();
  delete L;
}

}  // extern "C"
