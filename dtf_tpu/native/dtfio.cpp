// dtfio — native data-loading runtime for dtf_tpu.
//
// TPU-native successor of the reference's C++ input machinery (SURVEY.md §2b
// N7): TF's FIFOQueue kernels + queue runners fed the session from C++
// threads; here a small C library does the host-side heavy lifting — mmap'd
// IDX parsing, per-epoch Fisher-Yates shuffling, u8→f32 normalization, batch
// gather — on a background prefetch thread with a double buffer, so Python
// only ever memcpy's a ready batch while the TPU computes.
//
// C ABI only (consumed via ctypes from dtf_tpu/data/native.py). No JAX/TF
// headers; the contract is plain arrays.
//
// Build: make -C dtf_tpu/native   (g++ -O3 -shared -fPIC -pthread)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// IDX container parsing (big-endian header, u8 payload), mmap'd read-only.
// ---------------------------------------------------------------------------

struct IdxFile {
  int fd = -1;
  const uint8_t* map = nullptr;
  size_t map_len = 0;
  const uint8_t* data = nullptr;  // payload start
  std::vector<uint32_t> dims;
  size_t items = 0;      // dims[0]
  size_t item_size = 0;  // product of dims[1:]

  bool open(const char* path) {
    fd = ::open(path, O_RDONLY);
    if (fd < 0) return false;
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size < 4) return false;
    map_len = static_cast<size_t>(st.st_size);
    map = static_cast<const uint8_t*>(
        mmap(nullptr, map_len, PROT_READ, MAP_PRIVATE, fd, 0));
    if (map == MAP_FAILED) { map = nullptr; return false; }
    // magic: 0x00 0x00 dtype ndim ; only u8 (0x08) supported.
    if (map[0] != 0 || map[1] != 0 || map[2] != 0x08) return false;
    const unsigned ndim = map[3];
    if (map_len < 4 + 4ul * ndim) return false;
    dims.resize(ndim);
    size_t total = 1;
    for (unsigned i = 0; i < ndim; ++i) {
      const uint8_t* p = map + 4 + 4 * i;
      dims[i] = (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
                (uint32_t(p[2]) << 8) | uint32_t(p[3]);
      total *= dims[i];
    }
    if (map_len < 4 + 4ul * ndim + total) return false;
    data = map + 4 + 4 * ndim;
    items = ndim ? dims[0] : 0;
    item_size = items ? total / items : 0;
    return true;
  }

  void close() {
    if (map) munmap(const_cast<uint8_t*>(map), map_len);
    if (fd >= 0) ::close(fd);
    map = nullptr; fd = -1;
  }
};

// splitmix64 — deterministic, seedable, platform-independent shuffling.
static inline uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9e3779b97f4a7c15ull);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

struct Loader {
  IdxFile images, labels;
  size_t batch = 0;
  uint64_t seed = 0;
  size_t host_index = 0, host_count = 1;

  // epoch state (owned by the prefetch thread)
  std::vector<uint32_t> order;   // this host's shard of the epoch permutation
  size_t cursor = 0;
  uint64_t epoch = 0;

  // double buffer
  std::vector<float> buf_images[2];
  std::vector<int32_t> buf_labels[2];
  int ready_slot = -1;           // filled slot index, -1 = none
  bool stop = false;
  std::mutex mu;
  std::condition_variable cv_ready, cv_taken;
  std::thread worker;

  void reshuffle() {
    const size_t n = images.items;
    std::vector<uint32_t> perm(n);
    for (size_t i = 0; i < n; ++i) perm[i] = uint32_t(i);
    uint64_t s = seed * 0x9e3779b97f4a7c15ull + epoch + 1;
    for (size_t i = n - 1; i > 0; --i) {
      size_t j = splitmix64(s) % (i + 1);
      std::swap(perm[i], perm[j]);
    }
    order.clear();
    for (size_t i = host_index; i < n; i += host_count)
      order.push_back(perm[i]);
    cursor = 0;
  }

  void fill(int slot) {
    const size_t isz = images.item_size;
    float* out = buf_images[slot].data();
    int32_t* lab = buf_labels[slot].data();
    for (size_t b = 0; b < batch; ++b) {
      if (cursor >= order.size()) {  // epoch boundary: batches may span it
        ++epoch;
        reshuffle();
      }
      const uint32_t idx = order[cursor++];
      const uint8_t* src = images.data + size_t(idx) * isz;
      float* dst = out + b * isz;
      constexpr float kScale = 1.0f / 255.0f;
      for (size_t i = 0; i < isz; ++i) dst[i] = src[i] * kScale;
      lab[b] = labels.data[idx];
    }
  }

  void run() {
    int slot = 0;
    while (true) {
      fill(slot);  // compute outside the lock
      {
        std::unique_lock<std::mutex> l(mu);
        cv_taken.wait(l, [&] { return ready_slot == -1 || stop; });
        if (stop) return;
        ready_slot = slot;
      }
      cv_ready.notify_one();
      slot ^= 1;
    }
  }
};

}  // namespace

extern "C" {

// Returns an opaque handle or nullptr. Batch is the HOST-LOCAL batch size.
void* dtfio_loader_create(const char* images_path, const char* labels_path,
                          size_t batch, uint64_t seed, size_t host_index,
                          size_t host_count) {
  auto* L = new Loader();
  if (!L->images.open(images_path) || !L->labels.open(labels_path) ||
      L->images.items == 0 || L->images.items != L->labels.items ||
      L->labels.item_size != 1 ||  // labels must be a 1-D idx1 file
      batch == 0 || host_count == 0 || host_index >= host_count ||
      L->images.items / host_count < batch) {
    L->images.close(); L->labels.close(); delete L;
    return nullptr;
  }
  L->batch = batch; L->seed = seed;
  L->host_index = host_index; L->host_count = host_count;
  for (int s = 0; s < 2; ++s) {
    L->buf_images[s].resize(batch * L->images.item_size);
    L->buf_labels[s].resize(batch);
  }
  L->reshuffle();
  L->worker = std::thread([L] { L->run(); });
  return L;
}

size_t dtfio_item_size(void* handle) {
  return static_cast<Loader*>(handle)->images.item_size;
}

size_t dtfio_num_items(void* handle) {
  return static_cast<Loader*>(handle)->images.items;
}

// Blocks until the prefetched batch is ready, copies it out, and wakes the
// prefetch thread to fill the next one. images_out: batch*item_size floats;
// labels_out: batch int32.
void dtfio_loader_next(void* handle, float* images_out, int32_t* labels_out) {
  auto* L = static_cast<Loader*>(handle);
  int slot;
  {
    std::unique_lock<std::mutex> l(L->mu);
    L->cv_ready.wait(l, [&] { return L->ready_slot != -1; });
    slot = L->ready_slot;
    std::memcpy(images_out, L->buf_images[slot].data(),
                L->buf_images[slot].size() * sizeof(float));
    std::memcpy(labels_out, L->buf_labels[slot].data(),
                L->buf_labels[slot].size() * sizeof(int32_t));
    L->ready_slot = -1;
  }
  L->cv_taken.notify_one();
}

void dtfio_loader_destroy(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> l(L->mu);
    L->stop = true;
  }
  L->cv_taken.notify_all();
  if (L->worker.joinable()) L->worker.join();
  L->images.close();
  L->labels.close();
  delete L;
}

}  // extern "C"
