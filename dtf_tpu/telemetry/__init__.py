"""Run-wide observability: step-phase spans, MFU/goodput accounting, the
training compile fence, and the crash flight recorder (docs/OBSERVABILITY.md).

Enable with ``--telemetry`` on any train launcher; programmatic use:

    tel = Telemetry(out_dir=...)
    step = make_train_step(..., telemetry=tel)
    Trainer(step, mesh, hooks=..., telemetry=tel).fit(state, batches)
    print(json.dumps(tel.finish()))      # the one RunReport JSON line
"""

from dtf_tpu.telemetry.accounting import (GoodputTracker,          # noqa: F401
                                          RESNET50_TRAIN_FLOPS_PER_IMG,
                                          V5E_PEAK_BF16_FLOPS,
                                          analytic_lm_flops_per_step,
                                          cost_analysis_flops,
                                          param_count)
from dtf_tpu.telemetry.events import EventLog, read_events         # noqa: F401
from dtf_tpu.telemetry.fence import CompileFence                   # noqa: F401
from dtf_tpu.telemetry.flight import (FlightRecorder,              # noqa: F401
                                      StallWatchdog)
from dtf_tpu.telemetry.run import Telemetry, merge_artifact        # noqa: F401
from dtf_tpu.telemetry.spans import SpanRecorder, step_annotation  # noqa: F401
from dtf_tpu.telemetry.trace import TraceCollector                 # noqa: F401

# NOTE: dtf_tpu.telemetry.xplane / .profile are imported lazily by their
# consumers (ProfilerHook, the report CLI, bench_profile.py) — they must
# stay importable without jax OR tensorflow (srclint lazy-import fence).
