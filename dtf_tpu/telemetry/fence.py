"""The training compile fence — retraces and backend compiles, counted.

Two complementary counters, the same pair ``DecodeEngine`` pins
(dtf_tpu/serve/engine.py):

- **trace counts**: :meth:`CompileFence.count_traces` wraps the PYTHON
  step function before ``jax.jit`` sees it, so the wrapper body runs once
  per TRACE (not per call). ``make_train_step(..., telemetry=)`` threads
  this through, and ``Trainer.trace_counts`` surfaces it exactly like
  ``DecodeEngine.trace_counts`` — steady state must stay pinned at 1 per
  program; any increment mid-run is a shape/dtype-driven retrace silently
  recompiling the hot path.
- **backend compile events**: a ``jax.monitoring`` listener counting
  compile-related events and summing the ``/jax/core/compile/*_duration``
  durations — this is what feeds the goodput ``compile`` bucket, and it
  catches compiles the trace counter cannot see (helper jits inside hooks,
  donation fallbacks, cache misses).

jax.monitoring offers no unregister API on this jax, so ONE module-level
listener is installed lazily and dispatches to the currently-active fences
— constructing fences per run (tests build many) never stacks listeners.
"""

from __future__ import annotations

import threading

_LOCK = threading.Lock()
_ACTIVE: list["CompileFence"] = []
_INSTALLED = False


def _on_event(name: str, **kw) -> None:
    for f in list(_ACTIVE):
        f._event(name)


def _on_duration(name: str, duration: float, **kw) -> None:
    for f in list(_ACTIVE):
        f._duration(name, duration)


def _install_listeners() -> bool:
    """Register the global dispatchers once. Returns whether monitoring is
    observable on this jax (callers report honestly when it is not)."""
    global _INSTALLED
    with _LOCK:
        if _INSTALLED:
            return True
        import jax

        mon = getattr(jax, "monitoring", None)
        if mon is None or not hasattr(mon, "register_event_listener"):
            return False
        mon.register_event_listener(_on_event)
        if hasattr(mon, "register_event_duration_secs_listener"):
            mon.register_event_duration_secs_listener(_on_duration)
        _INSTALLED = True
        return True


class CompileFence:
    """Per-run trace + compile counters (see module docstring)."""

    def __init__(self):
        #: traces per program name — the ``DecodeEngine.trace_counts`` twin
        self.trace_counts: dict[str, int] = {}
        self.compile_events = 0
        self.compile_s = 0.0
        #: False when jax.monitoring cannot be observed on this jax —
        #: compile_events==0 then means "unobservable", not "no compiles"
        self.monitoring_available = False

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self.monitoring_available = _install_listeners()
        with _LOCK:
            if self not in _ACTIVE:
                _ACTIVE.append(self)

    def stop(self) -> None:
        with _LOCK:
            if self in _ACTIVE:
                _ACTIVE.remove(self)

    # -------------------------------------------------------- trace counter

    def count_traces(self, name: str, fn):
        """Wrap a to-be-jitted python function so each TRACE increments
        ``trace_counts[name]`` (the DecodeEngine ``counted`` idiom)."""
        self.trace_counts.setdefault(name, 0)

        def wrapped(*args, **kwargs):
            self.trace_counts[name] += 1
            return fn(*args, **kwargs)

        return wrapped

    # ---------------------------------------------------- event ingestion

    def _event(self, name: str) -> None:
        if "compil" in name:
            self.compile_events += 1

    def _duration(self, name: str, duration: float) -> None:
        if "/compile/" in name:
            self.compile_s += duration

    def snapshot(self) -> tuple[dict, int]:
        """(trace_counts copy, compile event count) — the steady-state
        fence idiom: snapshot after the warm lap, assert flat later."""
        return dict(self.trace_counts), self.compile_events
