"""Per-request trace events — the serving tier's request-lifecycle record.

A :class:`TraceCollector` accumulates chrome-trace events on the host:
the router/scheduler record one lifecycle slice per request (submit →
done) plus every engine-call slice (prefill chunk, page load/save,
decode) tagged with the request trace ids it served. Exported through
:func:`dtf_tpu.telemetry.profile.export_chrome_trace` next to the device
slices of a profiler window, a request renders end-to-end in Perfetto:
queue wait → admission → prefill chunks → its decode steps → the device
ops under them.

Same hot-path discipline as :mod:`~dtf_tpu.telemetry.spans`: every entry
point is ``time.perf_counter`` arithmetic and bounded memory (a ring —
a long-running server must not grow host state per request); recording
NEVER touches a device value (counter-instrumented regression test, the
PR 3/5 idiom).
"""

from __future__ import annotations

import collections
import time
from contextlib import contextmanager
from typing import Iterator, Mapping, Optional

#: default event retention — enough for a bench window or a postmortem
#: tail without per-request memory growth.
DEFAULT_KEEP = 65536


class TraceCollector:
    """Bounded chrome-trace event ring with a fixed time zero.

    Timestamps are microseconds since construction (``t0``), the chrome
    ``ts`` convention; ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, keep: int = DEFAULT_KEEP, *,
                 clock=time.perf_counter):
        self.clock = clock
        self._t0 = clock()
        self._events: collections.deque = collections.deque(maxlen=keep)
        self.dropped = 0

    def now_us(self) -> float:
        return (self.clock() - self._t0) * 1e6

    def _push(self, ev: dict) -> None:
        if len(self._events) == self._events.maxlen:
            self.dropped += 1
        self._events.append(ev)

    def complete(self, name: str, *, cat: str, tid, t0_us: float,
                 t1_us: float, pid: str = "serve",
                 args: Optional[Mapping] = None) -> None:
        ev = {"name": name, "ph": "X", "cat": cat, "pid": pid, "tid": tid,
              "ts": round(t0_us, 3), "dur": round(max(t1_us - t0_us, 0.0),
                                                  3)}
        if args:
            ev["args"] = dict(args)
        self._push(ev)

    def instant(self, name: str, *, cat: str, tid, pid: str = "serve",
                args: Optional[Mapping] = None) -> None:
        ev = {"name": name, "ph": "i", "s": "t", "cat": cat, "pid": pid,
              "tid": tid, "ts": round(self.now_us(), 3)}
        if args:
            ev["args"] = dict(args)
        self._push(ev)

    @contextmanager
    def span(self, name: str, *, cat: str, tid, pid: str = "serve",
             args: Optional[Mapping] = None) -> Iterator[None]:
        t0 = self.now_us()
        try:
            yield
        finally:
            self.complete(name, cat=cat, tid=tid, pid=pid,
                          t0_us=t0, t1_us=self.now_us(), args=args)

    @property
    def events(self) -> list[dict]:
        return list(self._events)

    def __len__(self) -> int:
        return len(self._events)
