"""One causally-ordered run timeline + derived SLO report (ISSUE 20).

``python -m dtf_tpu.telemetry timeline --logdir=...`` merges every
host-side trail a run leaves behind into ONE ordered entry list:

- the fleet EVENT PLANE (``events-*.jsonl`` + ``EVENTS_MANIFEST.json``,
  :mod:`dtf_tpu.telemetry.events`) — train hooks, checkpoint saves and
  degraded restores, publish versions, serve health transitions, requeue
  drains, swap lifecycle, stream reweights/faults, sink rotations, SLO
  excursions, controller verdicts mirrored with their own wall stamps;
- ``controller.jsonl`` — the fault controller's full per-transition
  record (including the bulky per-host observation dumps the mirrored
  events drop);
- flight-recorder liveness files (``telemetry/heartbeat.json`` and the
  multi-host ``telemetry/p*/heartbeat.json``) — each is a LAST-snapshot
  (atomic replace), so it contributes one entry: the run's final
  liveness observation per host;
- postmortem dumps (``telemetry/postmortem.json`` + per-host variants) —
  the reason/step/pid of every crash-context dump (the step-record ring
  stays in the file; the timeline carries the verdict).

Ordering is ``(t, seq)`` with a stable sort — ``seq`` is the event
plane's per-writer emit counter, the causal tiebreak when wall stamps
collide. Event records that carry a second clock domain (the health
tracker's injectable ``at``, the Router's ``tick``) keep it as a field:
DURATIONS in the derived report are deltas in the emitter's own clock
domain (the injectable-clock ground truth), while ``t`` only orders the
merged stream.

Everything here is pure host-side file parsing — no backend, no jax
import, deterministic: the same logdir bytes produce a byte-identical
report and chrome trace (sorted keys, no generation timestamps).
docs/OBSERVABILITY.md §9 documents the schema and the workflow.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional, Tuple

from dtf_tpu._hostio import atomic_replace
from dtf_tpu.telemetry.events import (EVENTS_MANIFEST_BASENAME,
                                      _on_disk_shards, read_events)

#: postmortem fields dropped from timeline entries — the step-record
#: ring and scalar panel stay in the dump file; the timeline is a spine.
_POSTMORTEM_BULK = ("records", "last_scalars", "context")


def _percentile(xs: List[float], q: float) -> float:
    """Nearest-rank percentile over a small host-side sample (the
    SpanRecorder rollup convention — no numpy dependency here)."""
    s = sorted(xs)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


def _read_jsonl(path: str) -> List[dict]:
    """Every parseable JSON line of ``path`` (order preserved); a torn
    tail line or a missing file reads as fewer records, never an error."""
    try:
        with open(path) as f:
            raw = f.read()
    except OSError:
        return []
    out = []
    for line in raw.split("\n"):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def resolve_events_dir(logdir: str) -> Optional[str]:
    """Find the run's event-plane directory: the logdir itself, or the
    conventional ``<logdir>/events`` child (what the launchers default
    ``--event_log_dir`` to). None = the run kept no event plane."""
    for cand in (logdir, os.path.join(logdir, "events")):
        if (os.path.exists(os.path.join(cand, EVENTS_MANIFEST_BASENAME))
                or _on_disk_shards(cand)):
            return cand
    return None


def collect_entries(logdir: str, *,
                    events_dir: Optional[str] = None) -> List[dict]:
    """The merged, causally-ordered entry list (module docstring). Each
    entry is ``{"t", "source", "kind", **fields}``; sources are
    ``events`` / ``controller`` / ``heartbeat`` / ``postmortem``."""
    entries: List[dict] = []
    ev_dir = events_dir or resolve_events_dir(logdir)
    if ev_dir is not None:
        for rec in read_events(ev_dir):
            e = {"t": float(rec.get("t", 0.0)), "source": "events",
                 "kind": str(rec.get("event", "unknown"))}
            e.update({k: v for k, v in rec.items() if k not in ("event",)})
            entries.append(e)
    for rec in _read_jsonl(os.path.join(logdir, "controller.jsonl")):
        kind = rec.get("state", rec.get("controller", "event"))
        e = {"t": float(rec.get("t", 0.0)), "source": "controller",
             "kind": f"controller_{kind}"}
        e.update({k: v for k, v in rec.items()
                  if k not in ("controller", "t", "state")})
        entries.append(e)
    tel = os.path.join(logdir, "telemetry")
    hb_paths = sorted(glob.glob(os.path.join(tel, "heartbeat.json"))
                      + glob.glob(os.path.join(tel, "p*", "heartbeat.json")))
    for path in hb_paths:
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        host = os.path.basename(os.path.dirname(path))
        e = {"t": float(rec.get("t", 0.0)), "source": "heartbeat",
             "kind": "heartbeat",
             "host": host if host.startswith("p") else "p0"}
        e.update({k: v for k, v in rec.items() if k != "t"})
        entries.append(e)
    pm_paths = sorted(glob.glob(os.path.join(tel, "postmortem.json"))
                      + glob.glob(os.path.join(tel, "p*", "postmortem.json")))
    for path in pm_paths:
        host = os.path.basename(os.path.dirname(path))
        for rec in _read_jsonl(path):
            e = {"t": float(rec.get("t", 0.0)), "source": "postmortem",
                 "kind": f"postmortem_{rec.get('reason', 'unknown')}",
                 "host": host if host.startswith("p") else "p0"}
            e.update({k: v for k, v in rec.items()
                      if k not in ("telemetry", "t", "reason")
                      and k not in _POSTMORTEM_BULK})
            entries.append(e)
    # stable sort: collection order above is itself deterministic
    # (manifest order, then sorted shard/file names), so ties beyond
    # (t, seq) keep a reproducible order — byte-identical reports.
    entries.sort(key=lambda e: (e["t"], e.get("seq", -1)))
    return entries


# --------------------------------------------------------------- episodes

def _swap_episodes(ev: List[dict]) -> Tuple[List[dict], int]:
    """Pair ``swap_start`` with its ``swap_commit``/``swap_rollback``
    (per version; the Router runs one swap at a time). Durations are
    wall deltas AND tick deltas — ticks are the fake-clock-proof unit."""
    open_by_version: Dict[int, dict] = {}
    episodes, opened = [], 0
    for e in ev:
        v = e.get("version")
        if e["kind"] == "swap_start":
            opened += 1
            open_by_version[v] = e
        elif e["kind"] in ("swap_commit", "swap_rollback"):
            start = open_by_version.pop(v, None)
            if start is None:
                continue
            ep = {"kind": "swap", "version": v,
                  "outcome": e["kind"].split("_", 1)[1],
                  "t0": start["t"], "t1": e["t"],
                  "duration_s": round(e["t"] - start["t"], 6)}
            if "tick" in e and "tick" in start:
                ep["ticks"] = int(e["tick"]) - int(start["tick"])
            if e["kind"] == "swap_rollback":
                ep["cause"] = e.get("cause", "")
            episodes.append(ep)
    return episodes, len(open_by_version)


def _quarantine_episodes(ev: List[dict]) -> Tuple[List[dict], int]:
    """Per-replica ``health_transition`` pairing: entering QUARANTINED
    opens an episode; returning to HEALTHY closes it (probation rides
    inside). Durations are deltas of the tracker's own ``at`` clock."""
    open_by_replica: Dict[int, dict] = {}
    episodes = []
    for e in ev:
        if e["kind"] != "health_transition":
            continue
        r = e.get("replica")
        if e.get("state_to") == "quarantined" and r not in open_by_replica:
            open_by_replica[r] = e
        elif e.get("state_to") == "healthy" and r in open_by_replica:
            start = open_by_replica.pop(r)
            at0 = start.get("at", start["t"])
            at1 = e.get("at", e["t"])
            episodes.append({"kind": "quarantine", "replica": r,
                             "cause": start.get("cause", ""),
                             "t0": start["t"], "t1": e["t"],
                             "duration_s": round(at1 - at0, 6)})
    return episodes, len(open_by_replica)


def _excursion_episodes(ev: List[dict]) -> Tuple[List[dict], int]:
    """Paired ``slo_excursion`` enter/exit edges per key (the Heartbeat's
    per-episode dedup); durations are pump-tick deltas."""
    open_by_key: Dict[str, dict] = {}
    episodes = []
    for e in ev:
        if e["kind"] != "slo_excursion":
            continue
        key = e.get("key", "fleet")
        if e.get("edge") == "enter":
            open_by_key[key] = e
        elif e.get("edge") == "exit" and key in open_by_key:
            start = open_by_key.pop(key)
            episodes.append({"kind": "slo_excursion", "key": key,
                             "t0": start["t"], "t1": e["t"],
                             "ticks": int(e.get("tick", 0))
                             - int(start.get("tick", 0)),
                             "worst_ok_frac": start.get("ok_frac")})
    return episodes, len(open_by_key)


def _duration_stats(episodes: List[dict], field: str = "duration_s") -> dict:
    xs = [float(ep[field]) for ep in episodes if field in ep]
    if not xs:
        return {}
    return {f"{field.rsplit('_', 1)[0]}_p50_s": round(_percentile(xs, 0.50), 6),
            f"{field.rsplit('_', 1)[0]}_p99_s": round(_percentile(xs, 0.99), 6),
            f"{field.rsplit('_', 1)[0]}_total_s": round(sum(xs), 6)}


def derive_slo_report(entries: List[dict]) -> dict:
    """The run's SLO story, derived purely from the merged entries: MTTR
    per recovery episode, swap duration percentiles + canary breaches,
    quarantine episode count/durations, SLO-excursion episodes, requeue
    totals, and acceptance-by-version (draft staleness) when the serve
    summary landed on the plane."""
    ev = [e for e in entries if e["source"] == "events"]
    report: dict = {}

    # --- recovery: the controller's own verdicts. The event plane and
    # controller.jsonl both carry them when both exist — count ONE
    # source (the plane first), never the union, or MTTR doubles.
    mttr = [float(e["mttr_s"]) for e in ev
            if e["kind"] == "controller_recovered" and "mttr_s" in e]
    if not mttr:
        mttr = [float(e["mttr_s"]) for e in entries
                if e["source"] == "controller"
                and e["kind"] == "controller_recovered" and "mttr_s" in e]
    run_end = [e for e in ev if e["kind"] == "run_end"]
    if run_end:
        last = run_end[-1]
        report["run_final"] = last.get("final", "unknown")
        report["restarts"] = int(last.get("restarts", 0))
        report["causes"] = list(last.get("causes", []))
        if not mttr:
            mttr = [float(x) for x in last.get("mttr_s", [])]
    if mttr:
        report["mttr_s"] = [round(x, 6) for x in mttr]
        report["mttr_mean_s"] = round(sum(mttr) / len(mttr), 6)

    swaps, swaps_open = _swap_episodes(ev)
    if swaps or swaps_open:
        sw = {"commits": sum(1 for s in swaps if s["outcome"] == "commit"),
              "rollbacks": sum(1 for s in swaps
                               if s["outcome"] == "rollback"),
              "canary_breaches": sum(
                  1 for s in swaps if s["outcome"] == "rollback"
                  and str(s.get("cause", "")).startswith("canary")),
              "open": swaps_open}
        sw.update(_duration_stats(swaps))
        report["swap"] = sw
    draft_swaps = [e for e in ev if e["kind"] == "swap_commit"
                   and e.get("draft")]
    if draft_swaps:
        report.setdefault("swap", {})["draft_commits"] = len(draft_swaps)

    quarantines, q_open = _quarantine_episodes(ev)
    if quarantines or q_open:
        q = {"episodes": len(quarantines), "open": q_open}
        q.update(_duration_stats(quarantines))
        report["quarantine"] = q

    excursions, x_open = _excursion_episodes(ev)
    if excursions or x_open:
        ticks = [ep["ticks"] for ep in excursions]
        x = {"episodes": len(excursions), "open": x_open}
        if ticks:
            x["ticks_p50"] = _percentile(ticks, 0.50)
            x["ticks_p99"] = _percentile(ticks, 0.99)
        report["slo_excursions"] = x

    drains = [e for e in ev if e["kind"] == "requeue_drain"]
    if drains:
        report["requeue"] = {
            "drains": len(drains),
            "requeued": sum(int(d.get("requeued", 0)) for d in drains),
            "shed": sum(int(d.get("shed", 0)) for d in drains)}

    summaries = [e for e in ev if e["kind"] == "serve_summary"]
    if summaries and summaries[-1].get("accept_by_version"):
        report["accept_by_version"] = summaries[-1]["accept_by_version"]

    ckpt_falls = sum(1 for e in ev if e["kind"] in ("ckpt_fallback",
                                                    "ckpt_resume_degraded"))
    if ckpt_falls:
        report["ckpt_degraded_events"] = ckpt_falls
    return report


# ------------------------------------------------------------ chrome trace

_SOURCE_PIDS = {"events": 1, "controller": 2, "heartbeat": 3,
                "postmortem": 4, "episodes": 5}


def write_chrome_trace(path: str, entries: List[dict]) -> int:
    """A Perfetto-loadable chrome-trace JSON: every entry as an instant
    event (pid = source, tid = replica when the entry names one) plus
    complete ("X") slices for the derived swap/quarantine/excursion
    episodes. Timestamps are microseconds from the earliest entry —
    byte-identical for the same entries (sorted keys, no wall stamps of
    its own). Returns the number of trace events written."""
    t0 = min((e["t"] for e in entries), default=0.0)
    trace: List[dict] = []
    for source, pid in sorted(_SOURCE_PIDS.items()):
        trace.append({"args": {"name": source}, "name": "process_name",
                      "ph": "M", "pid": pid})
    for e in entries:
        args = {k: v for k, v in e.items()
                if k not in ("t", "source", "kind")}
        trace.append({"args": args, "name": e["kind"], "ph": "i",
                      "pid": _SOURCE_PIDS.get(e["source"], 9), "s": "g",
                      "tid": int(e.get("replica", 0))
                      if isinstance(e.get("replica"), (int, float)) else 0,
                      "ts": round((e["t"] - t0) * 1e6, 1)})
    ev = [e for e in entries if e["source"] == "events"]
    for episodes in (_swap_episodes(ev)[0], _quarantine_episodes(ev)[0],
                     _excursion_episodes(ev)[0]):
        for ep in episodes:
            args = {k: v for k, v in ep.items()
                    if k not in ("t0", "t1", "kind")}
            trace.append({"args": args, "dur": round(
                              (ep["t1"] - ep["t0"]) * 1e6, 1),
                          "name": ep["kind"], "ph": "X",
                          "pid": _SOURCE_PIDS["episodes"],
                          "tid": int(ep.get("replica", 0)),
                          "ts": round((ep["t0"] - t0) * 1e6, 1)})
    atomic_replace(path, json.dumps({"traceEvents": trace},
                                    sort_keys=True))
    return len(trace)


def build_timeline(logdir: str, *, events_dir: Optional[str] = None,
                   chrome: str = "") -> dict:
    """The timeline CLI's one JSON line: source counts, per-kind counts,
    and the derived SLO report. Degraded inputs (no event plane, no
    controller log) shrink the report, they never fail it."""
    entries = collect_entries(logdir, events_dir=events_dir)
    sources: Dict[str, int] = {}
    kinds: Dict[str, int] = {}
    for e in entries:
        sources[e["source"]] = sources.get(e["source"], 0) + 1
        kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
    report = {"telemetry": "timeline", "logdir": logdir,
              "entries": len(entries),
              "sources": dict(sorted(sources.items())),
              "kinds": dict(sorted(kinds.items())),
              "slo": derive_slo_report(entries)}
    if not entries:
        report["note"] = ("no timeline sources under the logdir — expected "
                          "an event plane (EVENTS_MANIFEST.json / "
                          "events-*.jsonl), controller.jsonl, or "
                          "telemetry/ liveness files")
    if chrome:
        report["chrome_trace"] = chrome
        report["chrome_trace_events"] = write_chrome_trace(chrome, entries)
    return report


__all__ = ["build_timeline", "collect_entries", "derive_slo_report",
           "resolve_events_dir", "write_chrome_trace"]
