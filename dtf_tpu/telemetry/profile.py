"""Device-time attribution over a parsed XPlane trace — where the other
(1 − MFU) goes.

PR 5's telemetry decomposes the HOST side of a step; this module is the
device half (the per-op breakdowns the pjit-TPUv4 and MLPerf-pod scaling
papers use to find their losses, PAPERS.md 2204.06514 / 1909.09756):

- **buckets** — per-step device time split into MXU matmuls, flash/Pallas
  custom calls, fusions, and collectives by kind;
- **provenance join** — every collective's device seconds attributed to
  the Python ``file:line`` that issued it, by joining the event's
  instruction name (``all-reduce.2``) against the optimized-HLO source
  metadata (:func:`dtf_tpu.analysis.provenance.instruction_sites`);
- **overlap efficiency** — the fraction of collective device time hidden
  behind concurrent compute (the PR 2 ppermute rings claim latency
  hiding; this measures it): ``hidden = 1 − exposed/total`` where
  ``exposed`` is collective time with no compute running on the same
  plane. TPU planes are per-device so the semantics are exact; the CPU
  sim folds 8 virtual devices into one host plane, making sim overlap an
  approximate logic check (documented in docs/OBSERVABILITY.md);
- **device MFU** — flops/step against the measured device-side step
  window, cross-checking the analytic steps/sec MFU.

Everything here is pure arithmetic over :class:`~dtf_tpu.telemetry.xplane.
TraceData` — no jax, no tensorflow at module level (the srclint
lazy-import fence), so reports can be generated on a machine with no
backend from a trace captured on a chip.
"""

from __future__ import annotations

import json
from typing import Mapping, Optional, Sequence

from dtf_tpu._hostio import atomic_replace
from dtf_tpu.telemetry.xplane import OpEvent, StepWindow, TraceData

#: collective opcode prefixes (async -start/-done forms ride the prefix);
#: mirrors analysis/hlo.py COLLECTIVE_OPS without importing it (that
#: module is jax-adjacent via the analysis package's siblings).
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

#: bucket names, in report order.
BUCKETS = ("matmul", "pallas", "fusion") + COLLECTIVE_KINDS + (
    "data", "other")

_MATMUL_PREFIXES = ("dot", "convolution", "cublas", "custom-call-matmul")
_DATA_PREFIXES = ("copy", "transpose", "bitcast", "reshape", "infeed",
                  "outfeed", "dynamic-slice", "dynamic-update-slice",
                  "slice", "concatenate", "broadcast", "iota", "constant",
                  "tuple", "get-tuple-element", "parameter")
_PALLAS_MARKERS = ("pallas", "flash", "tpu_custom_call", "mosaic")


def base_op_name(name: str) -> str:
    """Instruction name → opcode-ish base: strip the ``.N`` instance
    suffix and async ``-start``/``-done`` markers (one transfer)."""
    base = name.split(".")[0]
    for suf in ("-start", "-done"):
        if base.endswith(suf):
            base = base[: -len(suf)]
    return base


def categorize(name: str, category_stat: str = "") -> str:
    """Map one op event into a report bucket.

    The backend's ``hlo_category`` stat wins when it names something we
    bucket (TPU planes carry it); otherwise the instruction name decides —
    collectives first (a fusion can't absorb one), then Pallas markers
    (custom-call names keep the kernel name), matmuls, fusions, data
    movement, ``other``.
    """
    low = name.lower()
    cat = category_stat.lower()
    base = base_op_name(low)
    for kind in COLLECTIVE_KINDS:
        if base.startswith(kind) or kind in cat:
            return kind
    if any(m in low or m in cat for m in _PALLAS_MARKERS):
        return "pallas"
    if base.startswith(_MATMUL_PREFIXES) or "convolution" in cat:
        return "matmul"
    if "fusion" in low or "fusion" in cat:
        # fusions whose name records a dot root are MXU work
        return "matmul" if "dot" in low else "fusion"
    if base.startswith(_DATA_PREFIXES):
        return "data"
    return "other"


# ---------------------------------------------------------------- intervals

def _union(intervals: Sequence[tuple]) -> list[tuple]:
    """Merged, sorted (start, end) union of possibly-overlapping spans."""
    out: list[list] = []
    for s, e in sorted(intervals):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _covered(span: tuple, union: Sequence[tuple]) -> int:
    """Length of ``span`` covered by the (merged, sorted) ``union``."""
    s, e = span
    cov = 0
    for us, ue in union:
        if ue <= s:
            continue
        if us >= e:
            break
        cov += min(e, ue) - max(s, us)
    return cov


def _total(union: Sequence[tuple]) -> int:
    return sum(e - s for s, e in union)


# ----------------------------------------------------------------- analyze

def _in_windows(events: Sequence[OpEvent],
                windows: Sequence[StepWindow]) -> list[OpEvent]:
    """Events whose midpoint falls inside any step window (none → all
    events pass: a trace without step annotations still buckets)."""
    if not windows:
        return list(events)
    return [ev for ev in events
            if any(w.contains(ev.start_ps + ev.dur_ps // 2)
                   for w in windows)]


def analyze(trace: TraceData, *, site_map: Optional[Mapping] = None,
            model_flops_per_step: Optional[float] = None,
            peak_flops: Optional[float] = None,
            n_devices: int = 1) -> dict:
    """The device-profile report dict (see module docstring).

    ``site_map`` is :func:`dtf_tpu.analysis.provenance.profile_site_map`
    output — ``{instruction_name: {"op", "loc", "bytes"}}`` — absent, the
    collective rows still carry device time, just no ``file:line``.
    Degrades with a reason instead of raising on an empty trace.
    """
    windows = trace.step_windows
    events = _in_windows(trace.op_events, windows)
    out: dict = {
        "n_op_events": len(events),
        # raw count next to the windowed one: a device/host clock-domain
        # mismatch (events all falling outside the step windows) reads as
        # total >> windowed here instead of a silently empty report
        "n_op_events_total": len(trace.op_events),
        "n_steps": len(windows),
        "device_planes": len(trace.device_planes),
        "per_op_events": bool(trace.op_events),
    }
    if trace.op_events and windows and not events:
        out["degraded"] = ("all per-op events fall outside the step "
                           "windows (clock-domain mismatch between "
                           "device planes and host annotations?)")
    if not trace.op_events:
        out["degraded"] = ("no per-op device events in trace (CPU backend "
                           "without --xla_cpu_enable_xprof_traceme, or an "
                           "empty window)")

    # ---- per-category buckets -------------------------------------------
    bucket_ps = {b: 0 for b in BUCKETS}
    bucket_n = {b: 0 for b in BUCKETS}
    for ev in events:
        b = categorize(ev.name, ev.category)
        bucket_ps[b] += ev.dur_ps
        bucket_n[b] += 1
    total_ps = sum(bucket_ps.values())
    out["buckets"] = {
        b: {"time_ms": round(bucket_ps[b] / 1e9, 4), "count": bucket_n[b],
            "frac": round(bucket_ps[b] / total_ps, 4) if total_ps else 0.0}
        for b in BUCKETS if bucket_n[b]}
    out["device_time_ms"] = round(total_ps / 1e9, 4)

    # ---- per-collective provenance rows ---------------------------------
    rows: dict[tuple, dict] = {}
    for ev in events:
        kind = categorize(ev.name, ev.category)
        if kind not in COLLECTIVE_KINDS:
            continue
        site = (site_map or {}).get(ev.name) \
            or (site_map or {}).get(base_op_name(ev.name))
        loc = site["loc"] if site else "<unattributed>"
        row = rows.setdefault((kind, loc), {
            "kind": kind, "loc": loc, "time_ms": 0.0, "count": 0,
            "hlo_ops": set()})
        row["time_ms"] += ev.dur_ps / 1e9
        row["count"] += 1
        row["hlo_ops"].add(ev.name)
    out["collectives"] = [
        {**r, "time_ms": round(r["time_ms"], 4),
         "hlo_ops": sorted(r["hlo_ops"])}
        for r in sorted(rows.values(),
                        key=lambda r: -r["time_ms"])]

    # ---- overlap efficiency ---------------------------------------------
    out["overlap"] = overlap_efficiency(events)

    # ---- step timing + device MFU ---------------------------------------
    if windows:
        wall_ps = [w.end_ps - w.start_ps for w in windows]
        mean_wall = sum(wall_ps) / len(wall_ps)
        busy = []
        for w in windows:
            per_plane = {}
            for ev in events:
                mid = ev.start_ps + ev.dur_ps // 2
                if w.contains(mid):
                    per_plane.setdefault(ev.plane, []).append(
                        (ev.start_ps, ev.end_ps))
            if per_plane:
                busy.append(sum(_total(_union(iv))
                                for iv in per_plane.values())
                            / len(per_plane))
        mean_busy = sum(busy) / len(busy) if busy else 0.0
        out["steps"] = {
            "n": len(windows),
            "step_wall_ms_mean": round(mean_wall / 1e9, 4),
            "device_busy_ms_mean": round(mean_busy / 1e9, 4),
            "device_busy_frac": round(mean_busy / mean_wall, 4)
            if mean_wall else 0.0,
            "device_idle_ms_mean": round(
                max(mean_wall - mean_busy, 0.0) / 1e9, 4),
        }
        if model_flops_per_step and peak_flops and mean_wall > 0:
            # device-side cross-check of the analytic steps/sec MFU: the
            # same flops over the measured in-trace step window (host
            # inter-step gaps excluded — if this is far ABOVE the run
            # MFU, the host loop, not the device, is the bottleneck)
            out["mfu_device"] = round(
                model_flops_per_step
                / (mean_wall / 1e12 * peak_flops * max(n_devices, 1)), 8)
    return out


def overlap_efficiency(events: Sequence[OpEvent]) -> dict:
    """Per-collective-kind hidden-time fractions.

    For each plane, compute intervals = union of every NON-collective op
    slice; a collective slice's ``exposed`` time is whatever that union
    does not cover. ``hidden_frac`` is the latency-hiding score the
    ppermute rings (``collective-permute`` rows) are built for: 1.0 means
    fully overlapped with compute, 0.0 means the step stalls for the
    whole transfer. Kinds absent from the trace are omitted.
    """
    compute_by_plane: dict[str, list] = {}
    coll_by_plane: dict[str, list] = {}
    for ev in events:
        kind = categorize(ev.name, ev.category)
        if kind in COLLECTIVE_KINDS:
            coll_by_plane.setdefault(ev.plane, []).append((kind, ev))
        else:
            compute_by_plane.setdefault(ev.plane, []).append(
                (ev.start_ps, ev.end_ps))
    totals: dict[str, list] = {}
    for plane, colls in coll_by_plane.items():
        comp = _union(compute_by_plane.get(plane, []))
        for kind, ev in colls:
            span = (ev.start_ps, ev.end_ps)
            hidden = _covered(span, comp)
            t = totals.setdefault(kind, [0, 0])
            t[0] += ev.dur_ps
            t[1] += hidden
    out = {}
    for kind, (total, hidden) in sorted(totals.items()):
        out[kind] = {
            "time_ms": round(total / 1e9, 4),
            "hidden_ms": round(hidden / 1e9, 4),
            "exposed_ms": round((total - hidden) / 1e9, 4),
            "hidden_frac": round(hidden / total, 4) if total else 0.0,
        }
    return out


def parse_logdir(logdir: str, *, site_map: Optional[Mapping] = None,
                 step_name: str = "train", **analyze_kw) -> dict:
    """Load the newest trace session under ``logdir`` and analyze it.
    Tolerant end to end: every failure mode returns a dict with a
    ``degraded`` reason rather than raising (the report CLI and
    ProfilerHook both call this on arbitrary run state)."""
    from dtf_tpu.telemetry.xplane import load_trace

    trace, reason = load_trace(logdir, step_name=step_name)
    if trace is None:
        return {"n_op_events": 0, "n_steps": 0, "degraded": reason}
    report = analyze(trace, site_map=site_map, **analyze_kw)
    report["trace_dir"] = trace.path
    return report


# ------------------------------------------------------------ chrome trace

def chrome_trace_events(trace: TraceData) -> list[dict]:
    """Device/op slices as chrome-trace complete events (``ph: "X"``,
    microsecond timestamps) — one ``pid`` per plane, ``tid`` per line, so
    Perfetto renders each device as its own track group."""
    events = []
    for w in trace.step_windows:
        events.append({"name": f"{w.name} {w.step}", "ph": "X",
                       "cat": "step", "pid": "steps", "tid": w.name,
                       "ts": w.start_ps / 1e6,
                       "dur": (w.end_ps - w.start_ps) / 1e6,
                       "args": {"step": w.step}})
    for ev in trace.op_events:
        events.append({"name": ev.name, "ph": "X",
                       "cat": categorize(ev.name, ev.category),
                       "pid": ev.plane, "tid": ev.line,
                       "ts": ev.start_ps / 1e6, "dur": ev.dur_ps / 1e6})
    return events


def export_chrome_trace(path: str, *, trace: Optional[TraceData] = None,
                        request_events: Optional[Sequence[Mapping]] = None,
                        meta: Optional[Mapping] = None) -> dict:
    """One Perfetto-loadable chrome-trace JSON: request lifecycles (the
    serve :class:`~dtf_tpu.telemetry.trace.TraceCollector` output) next
    to device slices. The two clock domains share only a best-effort
    zero (each is relative to its own capture start); within a domain
    ordering and durations are exact — docs/OBSERVABILITY.md walks the
    Perfetto workflow."""
    doc: dict = {"traceEvents": [], "displayTimeUnit": "ms"}
    if meta:
        doc["metadata"] = dict(meta)
    if trace is not None:
        doc["traceEvents"] += chrome_trace_events(trace)
    if request_events:
        doc["traceEvents"] += [dict(e) for e in request_events]
    atomic_replace(path, json.dumps(doc))
    return doc
