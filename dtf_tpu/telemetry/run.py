"""Run-wide telemetry façade — one object wired through trainer, hooks,
launchers and the serve scheduler (docs/OBSERVABILITY.md).

Composes the four pillars:

- :class:`~dtf_tpu.telemetry.spans.SpanRecorder` — step-phase spans,
- :class:`~dtf_tpu.telemetry.accounting.GoodputTracker` + the MFU helpers,
- :class:`~dtf_tpu.telemetry.fence.CompileFence` — the compile fence,
- :class:`~dtf_tpu.telemetry.flight.FlightRecorder` +
  :class:`~dtf_tpu.telemetry.flight.StallWatchdog` — the flight recorder,

and emits ONE RunReport dict at the end (the bench.py one-JSON-line
idiom): per-phase p50/p99, tokens/sec, MFU, goodput buckets, trace/compile
counts. ``merge_artifact`` folds reports into the committed TELEMETRY.json
(the STATIC_ANALYSIS.json/BENCH_LM.json pattern: sections survive re-runs).

Lifecycle: the Trainer calls ``start()``/``stop()`` around ``fit`` (signal
hook + watchdog live only inside that window); the launcher calls
``report()`` once after training and prints it. All hot-path entry points
(``note_step``, ``account``) are pure host arithmetic — the no-added-
readbacks contract is regression-tested.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Mapping, Optional

from dtf_tpu._hostio import atomic_replace
from dtf_tpu.telemetry.accounting import (GoodputTracker,
                                          V5E_PEAK_BF16_FLOPS)
from dtf_tpu.telemetry.fence import CompileFence
from dtf_tpu.telemetry.flight import FlightRecorder, StallWatchdog
from dtf_tpu.telemetry.spans import SpanRecorder, step_annotation


class Telemetry:
    """Per-run telemetry state (see module docstring).

    ``out_dir`` is where the flight recorder writes ``postmortem.json``
    (None = in-memory only). ``watchdog=False`` disables the stall thread
    (unit tests drive ``StallWatchdog.check`` directly).
    """

    def __init__(self, out_dir: Optional[str] = None, *,
                 keep_steps: int = 64, stall_factor: float = 10.0,
                 min_stall_s: float = 60.0, watchdog: bool = True,
                 peak_flops: float = V5E_PEAK_BF16_FLOPS,
                 n_devices: int = 1, clock=time.monotonic, wall=time.time):
        self.out_dir = out_dir
        self.spans = SpanRecorder()
        self.fence = CompileFence()
        self.goodput = GoodputTracker()
        self.flight = FlightRecorder(
            os.path.join(out_dir, "postmortem.json") if out_dir else None,
            keep=keep_steps,
            # liveness for the elastic run controller (dtf_tpu/fault):
            # written by the watchdog thread, so it exists exactly when
            # the stall detector runs — the two signals the host-lost vs
            # run-wedged verdict needs come from one place
            heartbeat_path=(os.path.join(out_dir, "heartbeat.json")
                            if out_dir else None),
            clock=clock, wall=wall)
        self.watchdog = StallWatchdog(
            self.flight, factor=stall_factor, min_stall_s=min_stall_s) \
            if watchdog else None
        #: per-CHIP peak × the mesh's device count is the MFU denominator:
        #: model_flops_per_step covers the whole global batch, so quoting
        #: it against one chip's peak would overstate MFU by n_devices
        self.peak_flops = peak_flops
        self.n_devices = max(int(n_devices), 1)
        self.tokens_per_step: Optional[float] = None
        self.model_flops_per_step: Optional[float] = None
        self.throughput_name = "tokens_per_sec"
        self.clock = clock
        #: per-request trace events (serving tier) — None unless a caller
        #: attaches a TraceCollector; recording stays host-clock-only
        self.tracer = None
        #: last ProfilerHook device-profile report (note_device_profile)
        self.device_profile: Optional[dict] = None
        self._t_start: Optional[float] = None
        self._t_stop: Optional[float] = None
        self._steps = 0
        self._last_step: Optional[int] = None
        self._prev_sigterm = None
        self._active = False
        self._compile_accounted = 0.0   # compile_s already in the bucket

    # -------------------------------------------------------- configuration

    def set_throughput_model(self, *, tokens_per_step: Optional[float] = None,
                             model_flops_per_step: Optional[float] = None,
                             throughput_name: Optional[str] = None) -> None:
        """Declare per-step work so the report (and LoggingHook) can turn
        steps/sec into tokens/sec and MFU. Optional: absent, the report
        simply omits those fields. ``throughput_name`` relabels the rate
        key for non-token launchers (``examples_per_sec`` for ResNet/
        WideDeep) so TELEMETRY.json rows stay comparable."""
        if tokens_per_step is not None:
            self.tokens_per_step = float(tokens_per_step)
        if model_flops_per_step is not None:
            self.model_flops_per_step = float(model_flops_per_step)
        if throughput_name is not None:
            self.throughput_name = throughput_name

    # ------------------------------------------------------- compile fence

    def count_traces(self, name: str, fn):
        return self.fence.count_traces(name, fn)

    @property
    def trace_counts(self) -> dict:
        return dict(self.fence.trace_counts)

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Open the run window: fence listeners, watchdog thread, SIGTERM
        postmortem hook (chained AFTER any already-installed handler, e.g.
        PreemptionHook's — ours dumps, then theirs checkpoints)."""
        if self._active:
            return
        self._active = True
        if self._t_start is None:
            self._t_start = self.clock()
        self._t_stop = None
        self.fence.start()
        if self.watchdog is not None:
            self.watchdog.start()
        if threading.current_thread() is threading.main_thread():
            try:
                self._prev_sigterm = signal.signal(
                    signal.SIGTERM, self._on_sigterm)
            except (ValueError, OSError):    # non-main ctx despite check
                self._prev_sigterm = None

    def _on_sigterm(self, signum, frame):
        self.flight.dump("sigterm")
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
            return
        # non-callable previous disposition (SIG_DFL/SIG_IGN): restore it
        # and re-deliver — a telemetry hook must never make the process
        # immune to SIGTERM (SIG_DFL then terminates as it should have;
        # SIG_IGN keeps ignoring, the operator's prior choice).
        try:
            signal.signal(signum,
                          prev if prev is not None else signal.SIG_DFL)
            self._prev_sigterm = None
            os.kill(os.getpid(), signum)
        except (ValueError, OSError):
            pass

    def open_wall(self) -> None:
        """Pin the run's wall-clock start NOW (idempotent). The Trainer
        calls this at ``fit`` entry, BEFORE restore and hook ``begin`` —
        seconds accounted into goodput buckets there must fall inside the
        wall window or ``report()`` would subtract out-of-window overhead
        from in-window wall and understate goodput."""
        if self._t_start is None:
            self._t_start = self.clock()

    def close_wall(self) -> None:
        """Extend the wall-clock end to NOW — the Trainer's ``finally``
        calls this after the end hooks (final checkpoint save + barrier),
        which run after ``stop()`` for the LIFO signal-handler teardown
        yet still account into the checkpoint bucket."""
        self._t_stop = self.clock()

    def stop(self) -> None:
        if not self._active:
            return
        self._active = False
        self._t_stop = self.clock()
        if self.watchdog is not None:
            self.watchdog.stop()
        self.fence.stop()
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except (ValueError, OSError):
                pass
            self._prev_sigterm = None

    # ------------------------------------------------------------ hot path

    def note_step(self, step: int, durations: Mapping[str, float]) -> None:
        """One completed loop iteration: host floats only (the zero-added-
        readbacks contract). Feeds the phase spans AND the flight ring."""
        for name, v in durations.items():
            self.spans.add(name.removesuffix("_s"), v)
        self.flight.record_step(step, durations)
        self._steps += 1
        self._last_step = step

    def account(self, bucket: str, seconds: float) -> None:
        self.goodput.account(bucket, seconds)

    def note_scalars(self, step: int, scalars: Mapping[str, float]) -> None:
        self.flight.note_scalars(step, scalars)

    def dump_postmortem(self, reason: str,
                        extra: Optional[Mapping] = None) -> dict:
        return self.flight.dump(reason, extra)

    def add_postmortem_provider(self, name: str, fn) -> None:
        """Register a flight-recorder context provider (host facts only —
        see :meth:`FlightRecorder.add_provider`); the serve tier hangs its
        in-flight request ids + slot ages here."""
        self.flight.add_provider(name, fn)

    def note_device_profile(self, report: Mapping) -> None:
        """Record a ProfilerHook window's parsed device profile; a compact
        summary rides the RunReport (full detail stays in the hook's
        ``device_profile.json``)."""
        self.device_profile = dict(report)

    # -------------------------------------------------------------- report

    def wall_s(self) -> float:
        if self._t_start is None:
            return 0.0
        end = self._t_stop if self._t_stop is not None else self.clock()
        return end - self._t_start

    def report(self, extra: Optional[Mapping] = None) -> dict:
        """The RunReport dict — emit with ``json.dumps`` as one line.
        Safe to call more than once (a mid-run progress line + finish)."""
        wall = self.wall_s()
        # compile seconds observed by jax.monitoring feed the goodput
        # bucket here (not incrementally: the listener thread must stay
        # allocation-free). Account only the DELTA since the last report —
        # a repeat call must neither double-count nor freeze the bucket
        # at its first-report value.
        compile_s = self.fence.compile_s
        delta = compile_s - self._compile_accounted
        if delta > 0:
            self.goodput.account("compile", delta)
            self._compile_accounted += delta
        out = {
            "telemetry": "run_report",
            "steps": self._steps,
            "last_step": self._last_step,
            "wall_s": round(wall, 3),
            "phases": self.spans.rollup(),
            "trace_counts": self.trace_counts,
            "compile_events": self.fence.compile_events,
            "compile_s": round(compile_s, 3),
            "monitoring_available": self.fence.monitoring_available,
            "goodput_buckets": self.goodput.report(wall),
            "flight": {"records": len(self.flight.records),
                       "dumps": self.flight.dumps},
        }
        if wall > 0 and self._steps:
            sps = self._steps / wall
            out["steps_per_sec"] = round(sps, 4)
            if self.tokens_per_step:
                out[self.throughput_name] = round(
                    sps * self.tokens_per_step, 1)
            if self.model_flops_per_step:
                out["model_flops_per_step"] = self.model_flops_per_step
                out["n_devices"] = self.n_devices
                # 8 digits: tiny CPU-sim runs land at 1e-8..1e-6-scale MFU
                # and must not round to a flat 0.0 in the committed artifact
                out["mfu"] = round(
                    sps * self.model_flops_per_step
                    / (self.peak_flops * self.n_devices), 8)
        if self.flight.last_scalars:
            out["last_scalars"] = dict(self.flight.last_scalars)
        if self.device_profile is not None:
            dp = self.device_profile
            out["device_profile"] = {
                k: dp[k] for k in ("buckets", "overlap", "steps",
                                   "mfu_device", "device_time_ms",
                                   "degraded") if k in dp}
        if extra:
            out.update(extra)
        return out

    def finish(self, extra: Optional[Mapping] = None) -> dict:
        """stop() + report() — the launcher's one call after fit."""
        self.stop()
        return self.report(extra)

    # ------------------------------------------------------------- helpers

    @staticmethod
    def step_annotation(step: int):
        return step_annotation(step)


def merge_artifact(path: str, report: Mapping, *, keep_runs: int = 20,
                   meta: Optional[Mapping] = None) -> dict:
    """Fold one RunReport into the committed TELEMETRY.json artifact.

    ``{"runs": [...]}`` with the newest LAST, bounded at ``keep_runs``
    (round timestamps ride in ``meta``); a malformed existing file is
    replaced, never crashed on — the artifact writer must not be able to
    fail the run it is reporting on.
    """
    data: dict = {"runs": []}
    try:
        with open(path) as f:
            prev = json.load(f)
        if isinstance(prev, dict) and isinstance(prev.get("runs"), list):
            data = prev
    except (OSError, ValueError):
        pass
    entry = dict(report)
    if meta:
        entry.update(meta)
    data["runs"] = (data["runs"] + [entry])[-keep_runs:]
    # atomic: sibling tooling (bench fences, the sentinel's pathspec
    # commits) reads the artifact while runs append to it
    atomic_replace(path, json.dumps(data, indent=1))
    return data
