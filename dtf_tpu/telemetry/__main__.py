"""``python -m dtf_tpu.telemetry report|timeline`` — run analytics, ONE
JSON line (bench.py idiom: stdout's last line is always one JSON object).

    python -m dtf_tpu.telemetry report --logdir=/tmp/run/profile
    python -m dtf_tpu.telemetry report --logdir=... --hlo=step.hlo.txt \
        --flops=1.2e12 --peak=1.97e14 --n-devices=8 --chrome=trace.json
    python -m dtf_tpu.telemetry timeline --logdir=/tmp/run \
        [--events-dir=...] [--chrome=timeline.trace.json]

``timeline`` merges the fleet event plane with controller.jsonl,
heartbeat liveness files and postmortem dumps into one causally-ordered
run story + a derived SLO report (MTTR, swap/quarantine/excursion
episodes) — see :mod:`dtf_tpu.telemetry.timeline`. Deterministic: the
same logdir bytes yield a byte-identical report and chrome trace.

Parses the newest XPlane session under ``--logdir`` into per-category
device-time buckets, per-collective ``file:line`` provenance rows (when
``--hlo`` supplies the optimized HLO text of the profiled program(s)),
comm/compute overlap efficiency, and — with ``--flops``/``--peak`` — the
device-derived MFU cross-check. ``--chrome`` additionally writes a
Perfetto-loadable chrome-trace JSON of the device slices.

Parsing needs no backend, but importing the ``dtf_tpu`` package pulls
jax, and a jax import can hang when the axon tunnel env is set and dead
(CLAUDE.md) — so like ``python -m dtf_tpu.analysis`` this re-execs into a
scrubbed CPU env first. Exit 0 even on a degraded parse (the reason rides
inside the JSON); exit 2 only when the reporter itself crashed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _reexec_if_needed(argv: list[str]) -> None:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.path.insert(0, root)
    from _dtf_env import cpu_sim_env, is_cpu_sim

    if is_cpu_sim(os.environ, 1):
        return
    if os.environ.get("_DTF_TPU_TELEMETRY_REEXEC") == "1":
        return
    import subprocess

    env = cpu_sim_env(1, os.environ)
    env["_DTF_TPU_TELEMETRY_REEXEC"] = "1"
    env.setdefault("PYTHONPATH", root)
    proc = subprocess.run(
        [sys.executable, "-m", "dtf_tpu.telemetry"] + argv,
        env=env, cwd=root, timeout=600)
    sys.exit(proc.returncode)


def _run_report(args) -> dict:
    from dtf_tpu.telemetry import profile as profile_mod

    site_map = None
    if args.hlo:
        from dtf_tpu.analysis.provenance import profile_site_map

        texts = []
        for p in args.hlo:
            with open(p) as f:
                texts.append(f.read())
        site_map = profile_site_map(texts)
    report = profile_mod.parse_logdir(
        args.logdir, site_map=site_map, step_name=args.step_name,
        model_flops_per_step=args.flops, peak_flops=args.peak,
        n_devices=args.n_devices)
    report["telemetry"] = "device_profile"
    if args.chrome:
        from dtf_tpu.telemetry.xplane import load_trace

        trace, reason = load_trace(args.logdir, step_name=args.step_name)
        if trace is not None:
            profile_mod.export_chrome_trace(args.chrome, trace=trace)
            report["chrome_trace"] = args.chrome
        else:
            report["chrome_trace_error"] = reason
    return report


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        _reexec_if_needed(argv)
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001 — the JSON-last-line contract
        print(json.dumps({"telemetry": "device_profile",
                          "error": f"reexec failed: {e}"}))
        return 2
    p = argparse.ArgumentParser(prog="python -m dtf_tpu.telemetry")
    sub = p.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser("report", help="parse an XPlane trace dir")
    rep.add_argument("--logdir", required=True,
                     help="profiler logdir (the ProfilerHook dir or a "
                          "plugins/profile/<ts> session)")
    rep.add_argument("--hlo", action="append", default=[],
                     help="optimized-HLO text file(s) of the profiled "
                          "program(s) for the file:line provenance join; "
                          "repeatable")
    rep.add_argument("--chrome", default="",
                     help="also write a Perfetto chrome-trace JSON here")
    rep.add_argument("--step-name", default="train",
                     help="StepTraceAnnotation name bounding each step")
    rep.add_argument("--flops", type=float, default=None,
                     help="model FLOPs per step (device-MFU cross-check)")
    rep.add_argument("--peak", type=float, default=None,
                     help="per-chip peak FLOP/s (default: v5e bf16)")
    rep.add_argument("--n-devices", type=int, default=1)
    tl = sub.add_parser("timeline", help="merge a run's host-side trails "
                        "into one ordered timeline + SLO report")
    tl.add_argument("--logdir", required=True,
                    help="the run's logdir (holds controller.jsonl, "
                         "telemetry/, and/or the event plane)")
    tl.add_argument("--events-dir", default="",
                    help="event-plane directory when it is not the logdir "
                         "or <logdir>/events")
    tl.add_argument("--chrome", default="",
                    help="also write a Perfetto chrome-trace JSON here")
    args = p.parse_args(argv)
    if args.cmd == "timeline":
        from dtf_tpu.telemetry.timeline import build_timeline

        try:
            report = build_timeline(args.logdir,
                                    events_dir=args.events_dir or None,
                                    chrome=args.chrome)
        except Exception as e:  # noqa: BLE001 — one JSON line no matter what
            print(json.dumps({"telemetry": "timeline",
                              "error": f"{type(e).__name__}: {e}"}))
            return 2
        print(json.dumps(report, sort_keys=True))
        return 0
    if args.peak is None and args.flops is not None:
        from dtf_tpu.telemetry.accounting import V5E_PEAK_BF16_FLOPS

        args.peak = V5E_PEAK_BF16_FLOPS
    try:
        report = _run_report(args)
    except Exception as e:  # noqa: BLE001 — one JSON line no matter what
        print(json.dumps({"telemetry": "device_profile",
                          "error": f"{type(e).__name__}: {e}"}))
        return 2
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
