"""Host-side step-phase spans — "where did the step time go?".

The reference era answered step-time questions with stdout timestamps; the
TPU-pod literature answers them with a per-phase step breakdown (the
MLPerf-on-pods decomposition of arxiv 1909.09756). This module is the
host half of that story: :class:`SpanRecorder` accumulates named wall-time
samples (``data_wait``, ``h2d``, ``dispatch``, ``hooks`` from the trainer
loop; ``serve_prefill_chunk``/``serve_decode`` from the serve scheduler)
and rolls them up into p50/p99 at report time.

Everything here is ``time.perf_counter`` arithmetic on the host — a span
NEVER touches a device value, so recording cannot introduce a blocking
readback into the sync-free loop (the PR 3 invariant; regression-tested by
tests/test_telemetry.py's counter-instrumented fit).

The device half is :func:`step_annotation`:
``jax.profiler.StepTraceAnnotation`` around each loop iteration stamps the
step number into the XPlane timeline, so a ProfilerHook trace window lines
up 1:1 with the host spans recorded for the same steps.
"""

from __future__ import annotations

import collections
import time
from contextlib import contextmanager
from typing import Iterator, Mapping

from dtf_tpu.metrics import quantile

#: per-phase sample retention: enough for tight quantiles over a long run
#: without per-step memory growth (a ring, like the flight recorder).
DEFAULT_KEEP = 4096


class SpanRecorder:
    """Named wall-time samples with bounded memory and p50/p99 rollups.

    ``add(name, seconds)`` is the whole write API (the :meth:`span` context
    manager is sugar over it). Totals/counts are exact over the run; the
    quantiles are computed over the last ``keep`` samples per phase.
    """

    def __init__(self, keep: int = DEFAULT_KEEP, *,
                 clock=time.perf_counter):
        self._keep = keep
        #: injectable monotonic clock (tests assert exact span totals
        #: without real sleeps; analysis host pass: clock-escape)
        self._clock = clock
        self._samples: dict[str, collections.deque] = {}
        self._totals: dict[str, float] = {}
        self._counts: dict[str, int] = {}

    def add(self, name: str, seconds: float) -> None:
        dq = self._samples.get(name)
        if dq is None:
            dq = self._samples[name] = collections.deque(maxlen=self._keep)
            self._totals[name] = 0.0
            self._counts[name] = 0
        dq.append(seconds)
        self._totals[name] += seconds
        self._counts[name] += 1

    @contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = self._clock()
        try:
            yield
        finally:
            self.add(name, self._clock() - t0)

    def total(self, name: str) -> float:
        return self._totals.get(name, 0.0)

    def count(self, name: str) -> int:
        return self._counts.get(name, 0)

    def rollup(self) -> Mapping[str, Mapping[str, float]]:
        """``{phase: {count, total_s, mean_s, p50_s, p99_s}}``."""
        out = {}
        for name, dq in self._samples.items():
            xs = list(dq)
            n = self._counts[name]
            out[name] = {
                "count": n,
                "total_s": round(self._totals[name], 6),
                "mean_s": round(self._totals[name] / max(n, 1), 6),
                "p50_s": round(quantile(xs, 0.5), 6),
                "p99_s": round(quantile(xs, 0.99), 6),
            }
        return out


def step_annotation(step: int, name: str = "train"):
    """``jax.profiler.StepTraceAnnotation`` for one loop iteration.

    Imported lazily so :mod:`dtf_tpu.loop` stays jax-free (its
    counter-instrumented tests run the Trainer against fake states with no
    backend at all). The annotation is a host-side TraceMe — nanoseconds
    when no trace is active, and the XPlane step-correlation marker when a
    ProfilerHook window is open.
    """
    import jax

    return jax.profiler.StepTraceAnnotation(name, step_num=step)
