"""XPlane proto access — the raw half of device-time attribution.

``jax.profiler.start_trace`` (ProfilerHook's window) writes one
``*.xplane.pb`` per host under ``<logdir>/plugins/profile/<ts>/``. This
module loads those protos via the installed
``tensorflow.tsl.profiler.protobuf.xplane_pb2`` and normalizes them into
plain-python facts the analytics layer (:mod:`dtf_tpu.telemetry.profile`)
consumes:

- :class:`OpEvent` — one per-op execution slice: the instruction name XLA
  stamped into the event's ``hlo_op`` stat (``all-reduce.2``, ``dot.3``,
  ``fusion.7``) plus start/duration in picoseconds. On TPU these live on
  the ``/device:TPU:N`` planes; on the CPU sim they appear on the host
  plane when the backend runs with ``--xla_cpu_enable_xprof_traceme=true``
  (:data:`CPU_OP_TRACE_FLAG` — the capture scripts and tests add it).
- :class:`StepWindow` — one per ``jax.profiler.StepTraceAnnotation``
  (the trainer wraps every iteration; ``step_num`` rides as a stat), the
  time fence that assigns op slices to steps.

Deliberate constraints: NO module-level ``jax``/``tensorflow`` import —
parsing must work in a process with no backend at all (the srclint
lazy-import fence covers this file), and every loader degrades to an
explanatory value instead of raising when TF or the trace files are
absent (``python -m dtf_tpu.telemetry report`` must print its one JSON
line whatever the environment looks like).
"""

from __future__ import annotations

import dataclasses
import glob
import os
from typing import Iterator, Optional

#: XLA:CPU flag that makes the CPU backend emit per-op TraceMe events
#: (instruction-named, ``hlo_op``-stat-carrying) — without it a CPU trace
#: has host/python lines only and the parser degrades to step windows.
CPU_OP_TRACE_FLAG = "--xla_cpu_enable_xprof_traceme=true"

#: stat keys resolved off each event (refs resolved to their string names).
_OP_STAT = "hlo_op"
_CATEGORY_STAT = "hlo_category"
_MODULE_STAT = "hlo_module"
_STEP_STAT = "step_num"


@dataclasses.dataclass(frozen=True)
class OpEvent:
    """One executed-op slice on a device (or host-sim) timeline."""

    name: str           # instruction name: the HLO-text join key
    plane: str
    line: str
    start_ps: int
    dur_ps: int
    category: str = ""  # backend's hlo_category stat when present (TPU)
    module: str = ""

    @property
    def end_ps(self) -> int:
        return self.start_ps + self.dur_ps


@dataclasses.dataclass(frozen=True)
class StepWindow:
    """One StepTraceAnnotation span (host TraceMe with a step_num stat)."""

    step: int
    name: str
    start_ps: int
    end_ps: int

    def contains(self, t_ps: int) -> bool:
        return self.start_ps <= t_ps < self.end_ps


@dataclasses.dataclass
class TraceData:
    """Normalized content of one XSpace (plus where it came from)."""

    path: str = ""
    op_events: list = dataclasses.field(default_factory=list)
    step_windows: list = dataclasses.field(default_factory=list)
    device_planes: list = dataclasses.field(default_factory=list)
    host_planes: list = dataclasses.field(default_factory=list)


def xplane_available() -> tuple[bool, str]:
    """(importable?, reason-when-not) for the xplane proto bindings."""
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: F401

        return True, ""
    except Exception as e:  # noqa: BLE001 — any import failure = degrade
        return False, f"xplane_pb2 unavailable: {type(e).__name__}: {e}"


def find_trace_dir(logdir: str) -> Optional[str]:
    """Newest ``plugins/profile/<ts>`` session under ``logdir`` (or the
    logdir itself when it already IS a session dir), None when absent."""
    if not logdir or not os.path.isdir(logdir):
        return None
    if glob.glob(os.path.join(logdir, "*.xplane.pb")):
        return logdir
    sessions = sorted(glob.glob(
        os.path.join(logdir, "plugins", "profile", "*")))
    return sessions[-1] if sessions else None


def find_xplane_files(logdir: str) -> list[str]:
    d = find_trace_dir(logdir)
    return sorted(glob.glob(os.path.join(d, "*.xplane.pb"))) if d else []


def load_xspace(path: str):
    """Parse one serialized XSpace; None when the bindings are missing."""
    ok, _ = xplane_available()
    if not ok:
        return None
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    space = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        space.ParseFromString(f.read())
    return space


def _resolved_stats(plane, event) -> dict:
    """Event stats with ref_values resolved to their metadata names."""
    out = {}
    for s in event.stats:
        meta = plane.stat_metadata.get(s.metadata_id)
        if meta is None:
            continue
        which = s.WhichOneof("value")
        if which is None:
            continue
        v = getattr(s, which)
        if which == "ref_value":
            ref = plane.stat_metadata.get(v)
            v = ref.name if ref is not None else str(v)
        out[meta.name] = v
    return out


def _iter_events(space) -> Iterator[tuple]:
    """(plane, line, event, metadata, line_base_ps) for every event."""
    for plane in space.planes:
        for line in plane.lines:
            base_ps = int(line.timestamp_ns) * 1000
            for ev in line.events:
                md = plane.event_metadata.get(ev.metadata_id)
                if md is None:
                    continue
                yield plane, line, ev, md, base_ps


def extract(space, *, path: str = "", step_name: str = "train") -> TraceData:
    """Normalize one XSpace into :class:`TraceData`.

    Op events are recognized by their ``hlo_op`` stat (present on TPU
    device planes and on CPU xprof-traceme events alike); step windows by
    a ``step_num`` stat on an event whose metadata name equals
    ``step_name`` (the :func:`dtf_tpu.telemetry.spans.step_annotation`
    default). Planes are split device/host by the ``/device:`` name
    prefix so the analytics layer can pick per-device semantics when the
    backend offers them.
    """
    data = TraceData(path=path)
    seen_planes: dict[str, bool] = {}
    for plane, line, ev, md, base_ps in _iter_events(space):
        if plane.name not in seen_planes:
            seen_planes[plane.name] = plane.name.startswith("/device:")
        stats = _resolved_stats(plane, ev)
        start = base_ps + int(ev.offset_ps)
        if _OP_STAT in stats:
            data.op_events.append(OpEvent(
                name=str(stats[_OP_STAT]), plane=plane.name,
                line=line.name, start_ps=start, dur_ps=int(ev.duration_ps),
                category=str(stats.get(_CATEGORY_STAT, "")),
                module=str(stats.get(_MODULE_STAT, ""))))
        elif md.name == step_name and _STEP_STAT in stats:
            data.step_windows.append(StepWindow(
                step=int(stats[_STEP_STAT]), name=md.name,
                start_ps=start, end_ps=start + int(ev.duration_ps)))
    data.device_planes = sorted(p for p, d in seen_planes.items() if d)
    data.host_planes = sorted(p for p, d in seen_planes.items() if not d)
    data.step_windows.sort(key=lambda w: w.start_ps)
    data.op_events.sort(key=lambda e: e.start_ps)
    return data


def load_trace(logdir: str, *, step_name: str = "train"
               ) -> tuple[Optional[TraceData], str]:
    """Load + merge every host's XSpace of the newest session under
    ``logdir``. Returns ``(TraceData, "")`` or ``(None, reason)`` — the
    tolerant no-TF / no-trace degradation path."""
    ok, reason = xplane_available()
    if not ok:
        return None, reason
    files = find_xplane_files(logdir)
    if not files:
        return None, f"no *.xplane.pb under {logdir!r}"
    merged = TraceData(path=find_trace_dir(logdir) or logdir)
    for f in files:
        try:
            space = load_xspace(f)
        except Exception as e:  # noqa: BLE001 — a truncated pb must not
            return None, f"unparseable {f!r}: {e}"   # crash the report
        if space is None:
            return None, "xplane bindings vanished mid-load"
        part = extract(space, path=f, step_name=step_name)
        merged.op_events += part.op_events
        merged.step_windows += part.step_windows
        merged.device_planes = sorted(
            set(merged.device_planes) | set(part.device_planes))
        merged.host_planes = sorted(
            set(merged.host_planes) | set(part.host_planes))
    merged.step_windows.sort(key=lambda w: w.start_ps)
    merged.op_events.sort(key=lambda e: e.start_ps)
    return merged, ""
